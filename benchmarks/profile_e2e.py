"""Phase breakdown of the end-to-end device bench (VERDICT r2 #7).

Splits one timed `DeviceProcessor.deduplicate` batch into its phases so
the end-to-end vs raw-scorer gap is attributable:

  ingest_extract   feature extraction + corpus host-mirror append
  device_update    incremental device mirror update (tree updater call)
  dispatch         scorer enqueue (async) until resolve starts
  device_wait      resolve_block: device execution + result fetch
  finalize         host survivor loop (exact compare + listener events)

Usage: python benchmarks/profile_e2e.py [--corpus 20000] [--queries 1024]
Prints ONE JSON line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--corpus", type=int, default=20000)
    ap.add_argument("--queries", type=int, default=1024)
    args = ap.parse_args()

    from bench import bench_schema, stresstest_records
    from sesam_duke_microservice_tpu.engine.device_matcher import (
        DeviceIndex,
        DeviceProcessor,
        resolve_block,
    )
    from sesam_duke_microservice_tpu.utils.jit_cache import (
        enable_persistent_cache,
    )

    enable_persistent_cache()
    schema = bench_schema()
    corpus = stresstest_records(args.corpus, seed=1234)
    queries = stresstest_records(args.queries, seed=5678, dataset="ds2")

    index = DeviceIndex(schema)
    proc = DeviceProcessor(schema, index)
    for r in corpus:
        index.index(r)
    index.commit()
    # warm both the scorer and the incremental updater shapes
    for seed, ds in ((999, "warm"), (998, "warm2")):
        warm = stresstest_records(args.queries, seed=seed, dataset=ds)
        proc.deduplicate(warm)
        for r in warm:
            index.delete(r)

    out = {"corpus": args.corpus, "queries": args.queries}
    t0 = time.perf_counter()
    for r in queries:
        index.index(r)
    index.commit()
    t1 = time.perf_counter()
    # force the device mirror update now (deduplicate would fold it into
    # dispatch otherwise)
    index.corpus.device_arrays()
    t2 = time.perf_counter()
    pending = proc._scorers.dispatch_block(queries, group_filtering=False)
    t3 = time.perf_counter()
    result = resolve_block(pending)
    t4 = time.perf_counter()
    survivors = 0
    prob_sum = 0.0   # reported: proves the finalize phase did real work
    for qi, record in enumerate(queries):
        for row, _ in result.survivors(qi):
            rid = index.corpus.row_ids[row]
            candidate = index.records.get(rid)
            if candidate is None or rid == record.record_id:
                continue
            survivors += 1
            prob_sum += proc.compare(record, candidate)
    t5 = time.perf_counter()

    live = int(index.corpus.row_valid.sum()
               - index.corpus.row_deleted[index.corpus.row_valid].sum())
    pairs = args.queries * live
    out.update(
        ingest_extract_s=round(t1 - t0, 4),
        device_update_s=round(t2 - t1, 4),
        dispatch_s=round(t3 - t2, 4),
        device_wait_s=round(t4 - t3, 4),
        finalize_s=round(t5 - t4, 4),
        survivors=survivors,
        survivor_prob_sum=round(prob_sum, 3),
        total_s=round(t5 - t0, 4),
        pairs=pairs,
        pairs_per_sec=round(pairs / (t5 - t0)),
        scoring_only_pairs_per_sec=round(pairs / (t4 - t3)),
    )
    print(json.dumps(out))


if __name__ == "__main__":
    main()
