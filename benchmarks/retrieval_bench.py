"""ANN retrieval-scan roofline benchmark: exact vs approx top-C merge.

Isolates the candidate-retrieval stage (``ops.encoder.retrieval_scan``) on
synthetic embeddings and reports achieved MFU against the v5e bf16 matmul
roofline plus HBM-bandwidth bound.  This is the stage the r4 verdict
measured at ~0.4% MFU with the per-step full-sort ``lax.top_k`` merge —
the TPU analogue of the reference's candidate-search limit, "the single
biggest influence on search performance"
(IncrementalLuceneDatabase.java:349-358).

Usage::

    python benchmarks/retrieval_bench.py [--rows 10027008] [--queries 1024]
        [--top-c 64] [--chunks 16384,65536,131072] [--exact-too]

Prints one JSON line per (mode, chunk) configuration.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

# v5e-1 peak: ~197 TFLOP/s bf16, ~819 GB/s HBM
V5E_BF16_FLOPS = 197e12
V5E_HBM_BPS = 819e9


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=10_027_008)
    ap.add_argument("--queries", type=int, default=1024)
    ap.add_argument("--dim", type=int, default=256)
    ap.add_argument("--top-c", type=int, default=64)
    ap.add_argument("--chunks", type=str, default="16384,65536,131072")
    ap.add_argument("--segs", type=str, default="64",
                    help="DEVICE_ANN_SEG values for fused mode")
    ap.add_argument("--exact-too", action="store_true")
    ap.add_argument("--repeats", type=int, default=3)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from sesam_duke_microservice_tpu.ops import encoder as E

    rows, q, dim, c = args.rows, args.queries, args.dim, args.top_c
    rng = np.random.default_rng(0)
    # generate in f32 then store bf16 (the corpus-resident dtype)
    corpus = rng.standard_normal((rows, dim), dtype=np.float32)
    corpus /= np.linalg.norm(corpus, axis=1, keepdims=True)
    corpus_emb = jax.device_put(corpus.astype(E.STORAGE_DTYPE))
    del corpus
    queries = rng.standard_normal((q, dim), dtype=np.float32)
    queries /= np.linalg.norm(queries, axis=1, keepdims=True)
    q_emb = jax.device_put(queries.astype(np.float32))

    cvalid = jax.device_put(np.ones(rows, dtype=bool))
    cdel = jax.device_put(np.zeros(rows, dtype=bool))
    cgroup = jax.device_put(np.zeros(rows, dtype=np.int32))
    qgroup = jax.device_put(np.zeros(q, dtype=np.int32))
    qrow = jax.device_put(np.full(q, -1, dtype=np.int32))

    flops = 2.0 * q * rows * dim
    hbm_bytes = rows * dim * 2.0  # bf16 corpus read dominates

    # mode -> (DEVICE_ANN_EXACT_TOPK, DEVICE_ANN_FUSED)
    modes = [("fused", ("0", "1")), ("approx", ("0", "0"))]
    if args.exact_too:
        modes.append(("exact", ("1", "0")))

    def scan_fn(chunk):
        # arrays ride as jit ARGUMENTS — a zero-arg closure would inline
        # the multi-GB corpus as an XLA constant and stall compilation
        @jax.jit
        def fn(q_emb, corpus_emb, cvalid, cdel, cgroup, qgroup, qrow):
            return E.retrieval_scan(
                q_emb, corpus_emb, cvalid, cdel, cgroup, qgroup, qrow,
                chunk=chunk, top_c=c, group_filtering=False,
            )

        return fn

    # exact reference for recall measurement
    os.environ["DEVICE_ANN_EXACT_TOPK"] = "1"
    ref_sim, ref_idx = jax.block_until_ready(scan_fn(16384)(
        q_emb, corpus_emb, cvalid, cdel, cgroup, qgroup, qrow
    ))
    ref_sets = [set(np.asarray(r).tolist()) - {-1} for r in np.asarray(ref_idx)]

    def run_one(mode, chunk, seg):
        if rows % chunk:
            return
        os.environ["DEVICE_ANN_RETRIEVAL_CHUNK"] = str(chunk)
        fn = scan_fn(chunk)
        sim, idx = jax.block_until_ready(fn(
            q_emb, corpus_emb, cvalid, cdel, cgroup, qgroup, qrow
        ))  # compile
        times = []
        for _ in range(args.repeats):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(
                q_emb, corpus_emb, cvalid, cdel, cgroup, qgroup, qrow
            ))
            times.append(time.perf_counter() - t0)
        t = float(np.median(times))
        got = np.asarray(idx)
        recall = float(np.mean([
            len(ref_sets[i] & (set(got[i].tolist()) - {-1}))
            / max(1, len(ref_sets[i]))
            for i in range(q)
        ]))
        print(json.dumps({
            "mode": mode, "chunk": chunk, "seg": seg, "rows": rows,
            "queries": q, "top_c": c, "seconds": round(t, 4),
            "mfu": round(flops / t / V5E_BF16_FLOPS, 4),
            "hbm_frac": round(hbm_bytes / t / V5E_HBM_BPS, 4),
            "recall_vs_exact": round(recall, 4),
            "pairs_per_sec": round(q * rows / t, 1),
        }), flush=True)

    chunks = [int(x) for x in args.chunks.split(",")]
    for mode, (exact_flag, fused_flag) in modes:
        os.environ["DEVICE_ANN_EXACT_TOPK"] = exact_flag
        os.environ["DEVICE_ANN_FUSED"] = fused_flag
        if mode == "fused":
            # the fused kernel tiles internally; chunk is moot — sweep
            # the recall knob (segment width) instead
            for seg in (int(s) for s in args.segs.split(",")):
                os.environ["DEVICE_ANN_SEG"] = str(seg)
                run_one(mode, chunks[0], seg)
        else:
            for chunk in chunks:
                run_one(mode, chunk, None)


if __name__ == "__main__":
    main()
