"""Matching-quality (F1) stresstest harness: seeded corpus, known truth.

The reference validates matching quality only through the external Sesam
stresstest pipes (sesam_node_deduplication_stresstest_config.conf.json and
its recordlinkage twin — 10,000 fake entities per source, seed 1234, value
pools sized so duplicates occur at a known rate, SURVEY.md section 4).
This harness is the in-process equivalent with a *measurable* ground
truth: every record derives from a true underlying identity, field values
are perturbed with seeded noise (typos, digit swaps), and two records are
true duplicates/links iff they share the identity.  That turns the
BASELINE.json metric ("dedup F1 @ fixed wall-clock") into a number.

Workloads: ``--workload dedup`` (one group, duplicates within) or
``--workload linkage`` (two groups over a shared identity pool, group
filtering on; ``--one-to-one`` additionally attaches the real ONE_TO_ONE
service listener and scores its surviving links).

Usage::

    python benchmarks/f1_stresstest.py
        [--backend host|device|ann|sharded|sharded-brute]
        [--workload dedup|linkage] [--one-to-one]
        [--entities 2000] [--dup-rate 0.3] [--batch 500] [--seed 1234]

Prints one JSON line: {"backend", "workload", "f1", "precision",
"recall", "wall_s", "records_per_sec", "true_pairs", "emitted_pairs",
(+ ProfileStats fields when the backend exposes them)}.
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import random
import sys
import time
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

FIRST = ["ole", "kari", "per", "anne", "nils", "ingrid", "lars", "berit",
         "jan", "liv", "arne", "astrid", "knut", "solveig", "odd", "randi",
         "gunnar", "turid", "leif", "marit"]
LAST = ["hansen", "johansen", "olsen", "larsen", "andersen", "pedersen",
        "nilsen", "kristiansen", "jensen", "karlsen", "johnsen", "pettersen",
        "eriksen", "berg", "haugen", "hagen"]
CITIES = ["oslo", "bergen", "trondheim", "stavanger", "tromso", "drammen",
          "fredrikstad", "kristiansand", "sandnes", "sarpsborg"]


def _typo(rng: random.Random, s: str) -> str:
    if len(s) < 2:
        return s
    op = rng.randrange(3)
    pos = rng.randrange(len(s))
    if op == 0:    # substitute
        return s[:pos] + rng.choice("abcdefghijklmnop") + s[pos + 1:]
    if op == 1:    # delete
        return s[:pos] + s[pos + 1:]
    return s[:pos] + rng.choice("abcdefghijklmnop") + s[pos:]  # insert


_SYL = ["ba", "be", "bo", "da", "de", "di", "ga", "go", "ha", "he", "jo",
        "ka", "ke", "ko", "la", "le", "li", "ma", "me", "mo", "na", "ne",
        "no", "ra", "re", "ro", "sa", "se", "so", "ta", "te", "to", "va",
        "ve", "vi"]


def _surname(rng: random.Random, min_syllables: int = 2,
             max_syllables: int = 4) -> str:
    # syllable-generated surnames: enough entropy that coincidental
    # full-name collisions between DIFFERENT identities stay rare at 10k+
    # scale (the fixed LAST pool saturates and poisons precision with
    # generator artifacts rather than matcher errors).  At 10^6 scale the
    # default 2-4 syllable space ITSELF saturates — ~1/3 of surnames draw
    # from only ~7k forms, so hundreds of thousands of distinct identities
    # genuinely collide within 0-2 edits and every engine (reference
    # included) scores them above threshold; pass min_syllables=3,
    # max_syllables=5 (--name-syllables 3-5) so precision at 1M measures
    # the matcher, not the name pool.
    n = rng.randint(min_syllables, max_syllables)
    return "".join(rng.choice(_SYL) for _ in range(n)) + \
        rng.choice(["sen", "berg", "vik", "dal", "nes", "stad"])


def generate(n_entities: int, dup_rate: float, seed: int = 1234,
             name_syllables=(2, 4)):
    """Seeded corpus: ``n_entities`` records over ~n*(1-dup_rate) identities.

    Returns (records_as_dicts, truth) where truth maps record _id -> true
    identity.  Mirrors the reference stresstest's seeded-pool construction
    but with derived (not independent) fields so duplicate pairs are
    *near*-duplicates the comparators must actually work for.
    """
    rng = random.Random(seed)
    n_identities = max(1, int(n_entities * (1.0 - dup_rate)))
    lo, hi = name_syllables
    identities = {}
    for ident in range(n_identities):
        identities[ident] = {
            "name": f"{rng.choice(FIRST)} {_surname(rng, lo, hi)}",
            "city": rng.choice(CITIES),
            "ssn": str(rng.randint(10_000_000, 99_999_999)),
        }
    rows, truth = [], {}
    for i in range(n_entities):
        # first n_identities records cover every identity once; the rest
        # are duplicates of a random identity with perturbed fields
        ident = i if i < n_identities else rng.randrange(n_identities)
        base = identities[ident]
        name, city, ssn = base["name"], base["city"], base["ssn"]
        if i >= n_identities:
            if rng.random() < 0.5:
                name = _typo(rng, name)
            if rng.random() < 0.2:
                name = _typo(rng, name)
            if rng.random() < 0.15:   # one digit wrong
                pos = rng.randrange(len(ssn))
                ssn = ssn[:pos] + str(rng.randrange(10)) + ssn[pos + 1:]
        rid = f"e{i}"
        rows.append({"_id": rid, "name": name, "city": city, "ssn": ssn})
        truth[rid] = ident
    return rows, truth


def truth_pairs(truth):
    by_ident = defaultdict(list)
    for rid, ident in truth.items():
        by_ident[ident].append(rid)
    pairs = set()
    for members in by_ident.values():
        for a, b in itertools.combinations(sorted(members), 2):
            pairs.add((a, b))
    return pairs


def stresstest_schema(ssn_exact: bool = False):
    """The measured matching schema.

    ``ssn_exact`` swaps the ssn comparator from QGram(high=0.9) to Exact:
    q-grams over 8-digit strings draw from only 100 possible bigrams, so
    at 10^6-entity (~10^12 candidate-pair) density two UNRELATED ssns
    routinely share enough grams to score 0.7+, and (with a city match)
    the Bayes product crosses the threshold — FPs every engine emits identically (host-exact verified),
    i.e. a schema artifact, not a matcher one.  Large-corpus quality runs
    use --ssn-exact so precision measures the matcher.  The default stays
    QGram for continuity with the 10k-scale numbers in BASELINE.md.
    """
    from sesam_duke_microservice_tpu.core import comparators as C
    from sesam_duke_microservice_tpu.core.config import DukeSchema
    from sesam_duke_microservice_tpu.core.records import (
        ID_PROPERTY_NAME,
        Property,
    )

    return DukeSchema(
        threshold=0.8,
        maybe_threshold=None,
        properties=[
            Property(ID_PROPERTY_NAME, id_property=True),
            Property("name", C.Levenshtein(), 0.25, 0.85),
            Property("city", C.Exact(), 0.45, 0.65),
            Property("ssn", C.Exact() if ssn_exact else C.QGram(),
                     0.2, 0.9),
        ],
        data_sources=[],
    )


class PairCollector:
    def __init__(self):
        self.pairs = {}

    def batch_ready(self, n):
        pass

    def batch_done(self):
        pass

    def matches(self, r1, r2, confidence):
        a, b = sorted((r1.record_id, r2.record_id))
        self.pairs[(a, b)] = confidence

    def matches_perhaps(self, r1, r2, confidence):
        pass

    def no_match_for(self, record):
        pass


def build_processor(schema, backend: str, group_filtering: bool = False):
    from sesam_duke_microservice_tpu.core.config import MatchTunables

    if backend != "host":
        from sesam_duke_microservice_tpu.utils.jit_cache import (
            enable_persistent_cache,
        )

        enable_persistent_cache()
    if backend == "sharded":
        from sesam_duke_microservice_tpu.engine.sharded_matcher import (
            ShardedAnnIndex,
            ShardedAnnProcessor,
        )

        index = ShardedAnnIndex(schema, tunables=MatchTunables())
        return ShardedAnnProcessor(schema, index,
                                   group_filtering=group_filtering)
    if backend == "sharded-brute":
        from sesam_duke_microservice_tpu.engine.sharded_matcher import (
            ShardedDeviceIndex,
            ShardedDeviceProcessor,
        )

        index = ShardedDeviceIndex(schema, tunables=MatchTunables())
        return ShardedDeviceProcessor(schema, index,
                                      group_filtering=group_filtering)
    if backend == "device":
        from sesam_duke_microservice_tpu.engine.device_matcher import (
            DeviceIndex,
            DeviceProcessor,
        )

        index = DeviceIndex(schema, tunables=MatchTunables())
        return DeviceProcessor(schema, index,
                               group_filtering=group_filtering)
    if backend == "ann":
        from sesam_duke_microservice_tpu.engine.ann_matcher import (
            AnnIndex,
            AnnProcessor,
        )

        index = AnnIndex(schema, tunables=MatchTunables())
        return AnnProcessor(schema, index, group_filtering=group_filtering)
    from sesam_duke_microservice_tpu.engine.processor import Processor
    from sesam_duke_microservice_tpu.index.inverted import InvertedIndex

    index = InvertedIndex(schema, MatchTunables())
    return Processor(schema, index, group_filtering=group_filtering)


def to_records(rows):
    from sesam_duke_microservice_tpu.core.records import (
        DATASET_ID_PROPERTY_NAME,
        ID_PROPERTY_NAME,
        ORIGINAL_ENTITY_ID_PROPERTY_NAME,
        Record,
    )

    records = []
    for row in rows:
        r = Record()
        r.add_value(ID_PROPERTY_NAME, f"ds__{row['_id']}")
        r.add_value(ORIGINAL_ENTITY_ID_PROPERTY_NAME, row["_id"])
        r.add_value(DATASET_ID_PROPERTY_NAME, "ds")
        for k in ("name", "city", "ssn"):
            r.add_value(k, row[k])
        records.append(r)
    return records


def generate_linkage(n_per_group: int, overlap: float, seed: int = 1234):
    """Two-group corpus (reference recordlinkage stresstest shape): both
    groups drawn from a shared identity pool; a cross-group pair is a true
    link iff the identities match.

    Exactly 2*n_per_group rows are generated and round-robin split — no
    over-generation/truncation, so the duplicate rate stays ``overlap``
    (truncating would keep only overlap^2 of the duplicate rows, since
    generate() emits all duplicates after the canonical block)."""
    rows, truth = generate(n_per_group * 2, overlap, seed)
    g1, g2 = [], []
    for i, row in enumerate(rows):
        (g1 if i % 2 == 0 else g2).append(row)
    t1 = {row["_id"]: truth[row["_id"]] for row in g1}
    t2 = {row["_id"]: truth[row["_id"]] for row in g2}
    return g1, g2, t1, t2


def truth_links(t1, t2):
    by_ident = defaultdict(list)
    for rid, ident in t2.items():
        by_ident[ident].append(rid)
    links = set()
    for rid, ident in t1.items():
        for other in by_ident.get(ident, ()):
            links.add(tuple(sorted((rid, other))))
    return links


def run(backend: str, n_entities: int, dup_rate: float, batch: int,
        seed: int = 1234, workload: str = "dedup",
        one_to_one: bool = False, name_syllables=(2, 4),
        ssn_exact: bool = False, dump_pairs: str = None):
    from sesam_duke_microservice_tpu.core.records import (
        GROUP_NO_PROPERTY_NAME,
    )

    if workload == "linkage":
        g1, g2, t1, t2 = generate_linkage(n_entities // 2, dup_rate, seed)
        del name_syllables  # linkage harness keeps the default pool
        r1, r2 = to_records(g1), to_records(g2)
        for r in r1:
            r.add_value(GROUP_NO_PROPERTY_NAME, "1")
        for r in r2:
            r.add_value(GROUP_NO_PROPERTY_NAME, "2")
        records = r1 + r2
        expected_links = truth_links(t1, t2)
    else:
        rows, truth = generate(n_entities, dup_rate, seed,
                               name_syllables=name_syllables)
        records = to_records(rows)
        expected_links = None

    schema = stresstest_schema(ssn_exact=ssn_exact)
    proc = build_processor(schema, backend,
                           group_filtering=(workload == "linkage"))
    if one_to_one:
        # the REAL service policy (per-batch greedy resolution with
        # cross-batch retraction), not a post-hoc approximation: attach the
        # actual listener over an in-memory link DB and read its live links
        from sesam_duke_microservice_tpu.engine.listeners import (
            ServiceMatchListener,
        )
        from sesam_duke_microservice_tpu.links.base import LinkStatus
        from sesam_duke_microservice_tpu.links.memory import (
            InMemoryLinkDatabase,
        )

        linkdb = InMemoryLinkDatabase()
        listener = ServiceMatchListener(
            "bench", linkdb,
            kind="recordlinkage" if workload == "linkage" else "deduplication",
            one_to_one=True,
            # displacement replay fails closed without a resolver; wire the
            # index lookup exactly as build_workload does
            record_resolver=proc.database.find_record_by_id,
        )
        proc.add_match_listener(listener)
    else:
        collector = PairCollector()
        proc.add_match_listener(collector)

    escalations_start = 0
    if backend != "host":
        from sesam_duke_microservice_tpu.engine import device_matcher as DM

        escalations_start = DM.ESCALATIONS
    t0 = time.perf_counter()
    for start in range(0, len(records), batch):
        proc.deduplicate(records[start:start + batch])
    wall = time.perf_counter() - t0

    if one_to_one:
        pair_items = {
            (link.id1, link.id2): link.confidence
            for link in linkdb.get_changes_since(0)
            if link.status != LinkStatus.RETRACTED
        }
    else:
        pair_items = collector.pairs

    def one_to_one_ceiling():
        """Structural F1 bound of 1:1 mode against all-truth-pairs ground
        truth: an identity with a copies in group 1 and b in group 2
        contributes a*b truth pairs but at most min(a, b) one-to-one links
        (dedup: k copies -> C(k,2) pairs, floor(k/2) links), so recall —
        hence F1 — is capped below 1.0 by the corpus itself, not by the
        matcher.  Returned so the 1:1 score can be read against the number
        it can actually reach."""
        if workload == "linkage":
            c1, c2 = defaultdict(int), defaultdict(int)
            for ident in t1.values():
                c1[ident] += 1
            for ident in t2.values():
                c2[ident] += 1
            max_links = sum(
                min(c1[i], c2[i]) for i in set(c1) & set(c2)
            )
            total = len(expected_links)
        else:
            counts = defaultdict(int)
            for ident in truth.values():
                counts[ident] += 1
            max_links = sum(k // 2 for k in counts.values())
            total = sum(k * (k - 1) // 2 for k in counts.values())
        r = max_links / total if total else 1.0
        return max_links, (2 * r / (1 + r) if r else 0.0)

    stats = getattr(proc, "stats", None)

    emitted = {
        tuple(sorted((a.split("__", 1)[1], b.split("__", 1)[1])))
        for a, b in pair_items
    }
    expected = (expected_links if expected_links is not None
                else truth_pairs(truth))
    tp = len(emitted & expected)
    precision = tp / len(emitted) if emitted else 0.0
    recall = tp / len(expected) if expected else 1.0
    f1 = (2 * precision * recall / (precision + recall)
          if precision + recall else 0.0)
    if dump_pairs:
        # emitted pair set + host-exact confidences, for cross-backend
        # link-set agreement diffs (VERDICT r3 #4)
        with open(dump_pairs, "w") as f:
            for (a, b), conf in sorted(pair_items.items()):
                f.write(f"{a}\t{b}\t{conf:.12f}\n")
    out = {
        "backend": backend,
        "workload": workload,
        "f1": round(f1, 4),
        "precision": round(precision, 4),
        "recall": round(recall, 4),
        "wall_s": round(wall, 2),
        "records_per_sec": round(len(records) / wall, 1),
        "true_pairs": len(expected),
        "emitted_pairs": len(emitted),
    }
    if one_to_one:
        max_links, f1_ceiling = one_to_one_ceiling()
        out["one_to_one_max_links"] = max_links
        out["f1_ceiling"] = round(f1_ceiling, 4)
        out["f1_vs_ceiling"] = round(f1 / f1_ceiling, 4) if f1_ceiling else 0.0
    if stats is not None:
        out["retrieval_s"] = round(stats.retrieval_seconds, 2)
        out["compare_s"] = round(stats.compare_seconds, 2)
        out["pairs_compared"] = stats.pairs_compared
    if backend != "host":
        from sesam_duke_microservice_tpu.engine import device_matcher as DM

        # delta vs run start so repeated in-process runs don't attribute
        # earlier configurations' escalations to this one
        out["escalations"] = DM.ESCALATIONS - escalations_start
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--backend", default="host",
                    choices=["host", "device", "ann", "sharded",
                             "sharded-brute"])
    ap.add_argument("--entities", type=int, default=2000)
    ap.add_argument("--dup-rate", type=float, default=0.3)
    ap.add_argument("--batch", type=int, default=500)
    ap.add_argument("--seed", type=int, default=1234)
    ap.add_argument("--workload", default="dedup",
                    choices=["dedup", "linkage"])
    ap.add_argument("--one-to-one", action="store_true",
                    help="greedy best-match assignment (ONE_TO_ONE policy)")
    ap.add_argument("--ssn-exact", action="store_true",
                    help="scale-appropriate schema: Exact ssn comparator "
                         "(see stresstest_schema)")
    ap.add_argument("--dump-pairs", default=None,
                    help="write the emitted pair set (id1\\tid2\\tconf) "
                         "to this path for cross-backend agreement diffs")
    ap.add_argument("--name-syllables", default="2-4",
                    help="surname syllable range lo-hi (use 3-5 at 10^6 "
                         "scale so the name pool doesn't saturate)")
    args = ap.parse_args()
    lo, hi = (int(x) for x in args.name_syllables.split("-"))
    print(json.dumps(
        run(args.backend, args.entities, args.dup_rate, args.batch,
            args.seed, workload=args.workload, one_to_one=args.one_to_one,
            name_syllables=(lo, hi), ssn_exact=args.ssn_exact,
            dump_pairs=args.dump_pairs)
    ))


if __name__ == "__main__":
    main()
