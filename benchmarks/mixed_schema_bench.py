"""Mixed-schema throughput: one long-text property must not drag the
whole schema off the fast path (VERDICT r3 #5).

Three configurations over the same corpus size, end-to-end through the
DeviceProcessor (the bench.py methodology — scoring rate over an indexed
corpus, warm shapes, ingest excluded from the timed region):

  * ``short``: three short properties (name Levenshtein / area Numeric /
    ssn Exact) — the headline configuration.
  * ``mixed``: the same three PLUS a ~1000-char Levenshtein property.
    With char-width auto-sizing the long property demotes to the host
    path past DEVICE_DEMOTE_CHARS (default 256), so the device keeps
    pruning on the short properties; survivors pay host finalization of
    the long field.
  * ``mixed-256``: the long values truncated to fit the 256-char N-word
    Myers kernel (DEVICE_DEMOTE_CHARS=0 semantics via data length) — the
    all-on-device alternative, for the gap measurement.

Usage: python benchmarks/mixed_schema_bench.py [--corpus 20000]
       [--queries 4096]
Prints one JSON line per configuration.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

WORDS = ("alpha bravo charlie delta echo foxtrot golf hotel india juliett "
         "kilo lima mike november oscar papa quebec romeo sierra tango "
         "uniform victor whiskey xray yankee zulu").split()


def records_for(n, seed, dataset, *, long_chars=0):
    from sesam_duke_microservice_tpu.core.records import (
        DATASET_ID_PROPERTY_NAME,
        ID_PROPERTY_NAME,
        ORIGINAL_ENTITY_ID_PROPERTY_NAME,
        Record,
    )

    rng = random.Random(seed)
    out = []
    for i in range(n):
        r = Record()
        eid = f"{rng.randint(1, 1_000_000)}_{i}"
        r.add_value(ID_PROPERTY_NAME, f"{dataset}__{eid}")
        r.add_value(ORIGINAL_ENTITY_ID_PROPERTY_NAME, eid)
        r.add_value(DATASET_ID_PROPERTY_NAME, dataset)
        r.add_value("name", f"{rng.choice(WORDS)} {rng.choice(WORDS)}")
        r.add_value("area", str(rng.randint(1, 10)))
        r.add_value("ssn", str(rng.randint(1, 1_000_000)))
        if long_chars:
            body = " ".join(
                rng.choice(WORDS) for _ in range(long_chars // 6)
            )
            r.add_value("desc", body[:long_chars])
        out.append(r)
    return out


def schema_for(with_long):
    from sesam_duke_microservice_tpu.core import comparators as C
    from sesam_duke_microservice_tpu.core.config import DukeSchema
    from sesam_duke_microservice_tpu.core.records import (
        ID_PROPERTY_NAME,
        Property,
    )

    numeric = C.Numeric()
    numeric.min_ratio = 0.7
    props = [
        Property(ID_PROPERTY_NAME, id_property=True),
        Property("name", C.Levenshtein(), 0.3, 0.88),
        Property("area", numeric, 0.45, 0.65),
        Property("ssn", C.Exact(), 0.3, 0.95),
    ]
    if with_long:
        props.append(Property("desc", C.Levenshtein(), 0.45, 0.6))
    return DukeSchema(threshold=0.9, maybe_threshold=None,
                      properties=props, data_sources=[])


def run(label, corpus_n, queries_n, long_chars):
    from sesam_duke_microservice_tpu.engine.device_matcher import (
        DeviceIndex,
        DeviceProcessor,
    )
    from sesam_duke_microservice_tpu.utils.jit_cache import (
        enable_persistent_cache,
    )

    enable_persistent_cache()
    schema = schema_for(long_chars > 0)
    index = DeviceIndex(schema)
    proc = DeviceProcessor(schema, index)
    for r in records_for(corpus_n, 1234, "ds1", long_chars=long_chars):
        index.index(r)
    index.commit()
    # warm: two batches at the timed size (corpus upload + compiles + the
    # incremental-updater shape), then tombstone the warm rows
    for seed, ds in ((999, "warm"), (998, "warm2")):
        warm = records_for(queries_n, seed, ds, long_chars=long_chars)
        proc.deduplicate(warm)
        for r in warm:
            index.delete(r)
    queries = records_for(queries_n, 5678, "ds2", long_chars=long_chars)
    stats0 = proc.stats.pairs_compared
    t0 = time.perf_counter()
    proc.deduplicate(queries)
    dt = time.perf_counter() - t0
    scored = proc.stats.pairs_compared - stats0
    device_names = sorted(s.name for s in index.plan.device_props)
    host_names = sorted(p.name for p in index.plan.host_props)
    print(json.dumps({
        "config": label,
        "pairs_per_sec": round(scored / dt, 1),
        "batch_seconds": round(dt, 3),
        "device_props": device_names,
        "host_props": host_names,
        "char_widths": {s.name: s.chars for s in index.plan.device_props},
    }), flush=True)
    return scored / dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--corpus", type=int, default=20000)
    ap.add_argument("--queries", type=int, default=4096)
    args = ap.parse_args()
    short = run("short", args.corpus, args.queries, 0)
    mixed = run("mixed", args.corpus, args.queries, 1000)
    print(json.dumps({
        "config": "summary",
        "mixed_vs_short": round(short / mixed, 2),
        "within_2x": bool(short / mixed <= 2.0),
    }))


if __name__ == "__main__":
    main()
