"""Large-corpus benchmark: ANN matching throughput at 10^5-10^7 rows.

The workload BASELINE.json configs[4] points at ("10M-record synthetic
dedup, mesh-sharded allgather on v5e-8"): index N synthetic records into
the embedding-ANN backend and measure steady-state incremental matching
throughput — the service's hot loop once a big corpus is resident.

Two modes:

  * single chip (default): the AnnProcessor path on the real device.
  * ``--sharded``: the mesh-sharded ANN program
    (``parallel.ann_sharded.build_sharded_ann_scorer``) over an
    ``--devices``-way mesh.  On a host without that many chips the bench
    re-execs itself on a virtual CPU mesh (the tests/conftest recipe), so
    the full shard_map program — per-shard retrieval + rescoring,
    all_gather merge over the mesh axis — executes for real at 10^5-row
    scale, and the printed HBM budget extrapolates the measured bytes/row
    to the 10M-row v5e-8 target.

Usage::

    python benchmarks/large_scale.py [--rows 1000000] [--batch 1024]
        [--measure-batches 5] [--sharded] [--devices 8]

Prints one JSON line: {"rows", "ingest_rows_per_sec", "query_rows_per_sec",
"effective_pairs_per_sec", "hbm_bytes_per_row"} (+ sharded budget fields).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# v5e HBM per chip (16 GiB)
V5E_HBM_BYTES = 16 * (1 << 30)


def _reexec_on_virtual_mesh(n_devices: int) -> None:
    from sesam_duke_microservice_tpu.utils.virtual_mesh import (
        virtual_mesh_env,
    )

    env = virtual_mesh_env(n_devices, "_LS_SHARDED_INNER")
    proc = subprocess.run([sys.executable] + sys.argv, env=env)
    sys.exit(proc.returncode)


def run_sharded(args) -> None:
    import jax

    if os.environ.get("_LS_SHARDED_INNER") == "1":
        from sesam_duke_microservice_tpu.utils.virtual_mesh import (
            force_cpu_platform,
        )

        force_cpu_platform()
    if (len(jax.devices()) < args.devices
            and os.environ.get("_LS_SHARDED_INNER") != "1"):
        _reexec_on_virtual_mesh(args.devices)
        return

    import jax.numpy as jnp
    import numpy as np

    from f1_stresstest import generate, stresstest_schema, to_records
    from sesam_duke_microservice_tpu.ops import encoder as E
    from sesam_duke_microservice_tpu.ops import features as F
    from sesam_duke_microservice_tpu.ops import scoring as S
    from sesam_duke_microservice_tpu.parallel import (
        ShardedCorpus,
        build_sharded_ann_scorer,
        corpus_mesh,
    )

    schema = stresstest_schema()
    plan = F.SchemaFeatures.plan(schema)
    dim = int(os.environ.get("DEVICE_ANN_DIM", "256"))
    enc = E.RecordEncoder(schema, dim)

    devices = jax.devices()[: args.devices]
    assert len(devices) == args.devices, (
        f"need {args.devices} devices for the sharded bench, have "
        f"{len(devices)}"
    )
    mesh = corpus_mesh(devices)
    chunk = int(os.environ.get("SHARDED_CHUNK", "1024"))
    top_c = 64

    # slab-extract the corpus feature tensors + embeddings on host
    t0 = time.perf_counter()
    slabs, slab_rows = [], 50_000
    remaining, seed = args.rows, 1000
    while remaining > 0:
        n = min(slab_rows, remaining)
        rows, _ = generate(n, args.dup_rate, seed)
        records = to_records(rows)
        for r in records:
            r.set_values("ID", [f"s{seed}__{r.record_id}"])
        feats = F.extract_batch(plan, records)
        # the production corpus storage dtype (E.STORAGE_DTYPE)
        feats[E.ANN_PROP] = {E.ANN_TENSOR: enc.encode_corpus(records)}
        slabs.append(feats)
        remaining -= n
        seed += 1
    feats = {
        prop: {
            name: np.concatenate([s[prop][name] for s in slabs])
            for name in slabs[0][prop]
        }
        for prop in slabs[0]
    }
    n_rows = args.rows
    ingest_s = time.perf_counter() - t0

    per_row = sum(
        arr.dtype.itemsize * int(arr.size // max(1, arr.shape[0]))
        for tensors in feats.values() for arr in tensors.values()
    ) + 6  # masks: valid (bool) + deleted (bool) + group (int32)

    # place record-axis sharded over the mesh
    placer = ShardedCorpus(mesh, chunk=chunk)
    valid = np.ones((n_rows,), dtype=bool)
    deleted = np.zeros((n_rows,), dtype=bool)
    group = np.full((n_rows,), -1, dtype=np.int32)
    sfeats, svalid, sdeleted, sgroup = placer.place(
        feats, valid, deleted, group
    )
    local_rows = placer.padded_capacity(n_rows) // mesh.size

    scorer = build_sharded_ann_scorer(plan, mesh, chunk=chunk, top_c=top_c)

    def query_batch(seed):
        rows, _ = generate(args.batch, args.dup_rate, seed)
        records = to_records(rows)
        for r in records:
            r.set_values("ID", [f"q{seed}__{r.record_id}"])
        qf = {
            p: {k: jnp.asarray(a) for k, a in t.items()}
            for p, t in F.extract_batch(plan, records).items()
        }
        q_emb = jnp.asarray(enc.encode_batch(records))
        return q_emb, qf

    min_logit = jnp.float32(
        S.probability_to_logit(schema.threshold)
        - S.host_bound_logit(plan.host_props) - 1e-3
    )
    qrow = jnp.full((args.batch,), -1, jnp.int32)
    qgroup = jnp.full((args.batch,), -2, jnp.int32)

    # warm (compile), then steady-state
    q_emb, qf = query_batch(7777)
    scorer(q_emb, qf, sfeats, svalid, sdeleted, sgroup, qgroup, qrow,
           min_logit)[0].block_until_ready()
    times = []
    for i in range(args.measure_batches):
        q_emb, qf = query_batch(8000 + i)
        t0 = time.perf_counter()
        out = scorer(q_emb, qf, sfeats, svalid, sdeleted, sgroup, qgroup,
                     qrow, min_logit)
        out[0].block_until_ready()
        times.append(time.perf_counter() - t0)
    best = min(times)

    # sanity: merged rows are real global rows
    ti = np.asarray(out[1])
    assert ti.max() < placer.padded_capacity(n_rows) and (ti >= -1).all()

    target_rows = 10_000_000
    budget = {
        "hbm_bytes_per_row": per_row,
        "target_rows": target_rows,
        "target_total_gib": round(target_rows * per_row / (1 << 30), 2),
        "target_per_shard_gib": round(
            target_rows * per_row / args.devices / (1 << 30), 3
        ),
        "v5e_hbm_per_chip_gib": 16,
        # the named v5e-8 verdict is always about 8 chips, regardless of
        # the mesh width this validation run used
        "fits_v5e_8": target_rows * per_row / 8 < 0.8 * V5E_HBM_BYTES,
    }
    print(json.dumps({
        "mode": "sharded",
        "devices": mesh.size,
        "backend": jax.default_backend(),
        "rows": n_rows,
        "rows_per_shard": local_rows,
        "ingest_rows_per_sec": round(n_rows / ingest_s, 1),
        "query_rows_per_sec": round(args.batch / best, 1),
        "effective_pairs_per_sec": round(args.batch * n_rows / best, 1),
        "batch_seconds": round(best, 3),
        **budget,
    }))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_000)
    ap.add_argument("--batch", type=int, default=1024)
    ap.add_argument("--measure-batches", type=int, default=5)
    ap.add_argument("--dup-rate", type=float, default=0.3)
    ap.add_argument("--sharded", action="store_true",
                    help="run the mesh-sharded ANN program (virtual CPU "
                         "mesh when the host lacks the chips)")
    ap.add_argument("--devices", type=int, default=8)
    args = ap.parse_args()
    if args.measure_batches < 1:
        ap.error("--measure-batches must be >= 1")

    if args.sharded:
        run_sharded(args)
        return

    from f1_stresstest import (
        build_processor,
        generate,
        stresstest_schema,
        to_records,
    )

    schema = stresstest_schema()
    proc = build_processor(schema, "ann")
    index = proc.database

    # ingest in slabs to bound host memory.  The clock covers only the
    # framework's work (index + commit: extraction, embedding, corpus
    # append, digests) — synthetic data generation is harness cost and is
    # reported separately (r4 methodology fix; the r3 number folded
    # generate()+to_records() into the ingest rate).
    ingest_s = 0.0
    gen_s = 0.0
    slab = 100_000
    remaining = args.rows
    seed = 1000
    while remaining > 0:
        n = min(slab, remaining)
        t_gen = time.perf_counter()
        rows, _ = generate(n, args.dup_rate, seed)
        records = to_records(rows)
        # distinct ids per slab
        for r in records:
            r.set_values("ID", [f"s{seed}__{r.record_id}"])
        t0 = time.perf_counter()
        gen_s += t0 - t_gen
        for r in records:
            index.index(r)
        index.commit()
        ingest_s += time.perf_counter() - t0
        remaining -= n
        seed += 1
    ingest_rate = args.rows / ingest_s

    # warm the scorer (compile + K/C settling)
    qrows, _ = generate(args.batch, args.dup_rate, 7777)
    warm = to_records(qrows)
    for r in warm:
        r.set_values("ID", [f"warm__{r.record_id}"])
    proc.deduplicate(warm)

    # steady-state incremental batches; per-phase split from the
    # processor's own stats so regressions name their phase (r5)
    times, splits = [], []
    for i in range(args.measure_batches):
        qrows, _ = generate(args.batch, args.dup_rate, 8000 + i)
        batch = to_records(qrows)
        for r in batch:
            r.set_values("ID", [f"q{i}__{r.record_id}"])
        r0 = proc.stats.retrieval_seconds
        c0 = proc.stats.compare_seconds
        t0 = time.perf_counter()
        proc.deduplicate(batch)
        times.append(time.perf_counter() - t0)
        splits.append((proc.stats.retrieval_seconds - r0,
                       proc.stats.compare_seconds - c0))
    best = min(times)
    score_s, finalize_s = splits[times.index(best)]
    corpus_rows = index.corpus.size

    # device bytes per corpus row (features + embedding + masks)
    per_row = 0
    for tensors in index.corpus.feats.values():
        for arr in tensors.values():
            per_row += arr.dtype.itemsize * int(
                arr.size // max(1, arr.shape[0])
            )

    print(json.dumps({
        "rows": corpus_rows,
        "ingest_rows_per_sec": round(ingest_rate, 1),
        "harness_gen_rows_per_sec": round(args.rows / gen_s, 1),
        "query_rows_per_sec": round(args.batch / best, 1),
        "effective_pairs_per_sec": round(args.batch * corpus_rows / best, 1),
        "hbm_bytes_per_row": per_row,
        "batch_seconds": round(best, 3),
        # device scoring wait (dispatch->resolve) vs host finalization;
        # the remainder of batch_seconds is ingest-side (extract, commit,
        # incremental device update)
        "score_seconds": round(score_s, 3),
        "finalize_seconds": round(finalize_s, 3),
        "ingest_side_seconds": round(best - score_s - finalize_s, 3),
    }))


if __name__ == "__main__":
    main()
