"""Large-corpus benchmark: ANN matching throughput at 10^5-10^7 rows.

The workload BASELINE.json configs[4] points at ("10M-record synthetic
dedup"): index N synthetic records into the embedding-ANN backend on one
chip and measure steady-state incremental matching throughput — the
service's hot loop once a big corpus is resident.  For corpora beyond one
chip's HBM the same program shards over a mesh (parallel/ann_sharded.py;
validated on the virtual CPU mesh by tests, dry-run by the driver).

Usage::

    python benchmarks/large_scale.py [--rows 1000000] [--batch 1024]
        [--measure-batches 5]

Prints one JSON line: {"rows", "ingest_rows_per_sec", "query_rows_per_sec",
"effective_pairs_per_sec", "hbm_bytes_per_row"}.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_000)
    ap.add_argument("--batch", type=int, default=1024)
    ap.add_argument("--measure-batches", type=int, default=5)
    ap.add_argument("--dup-rate", type=float, default=0.3)
    args = ap.parse_args()

    from f1_stresstest import (
        build_processor,
        generate,
        stresstest_schema,
        to_records,
    )

    schema = stresstest_schema()
    proc = build_processor(schema, "ann")
    index = proc.database

    # ingest in slabs to bound host memory
    t0 = time.perf_counter()
    slab = 100_000
    remaining = args.rows
    seed = 1000
    while remaining > 0:
        n = min(slab, remaining)
        rows, _ = generate(n, args.dup_rate, seed)
        records = to_records(rows)
        # distinct ids per slab
        for r in records:
            r._values["ID"] = [f"s{seed}__{r.record_id}"]
        for r in records:
            index.index(r)
        index.commit()
        remaining -= n
        seed += 1
    ingest_s = time.perf_counter() - t0
    ingest_rate = args.rows / ingest_s

    # warm the scorer (compile + K/C settling)
    qrows, _ = generate(args.batch, args.dup_rate, 7777)
    warm = to_records(qrows)
    for r in warm:
        r._values["ID"] = [f"warm__{r.record_id}"]
    proc.deduplicate(warm)

    # steady-state incremental batches
    times = []
    for i in range(args.measure_batches):
        qrows, _ = generate(args.batch, args.dup_rate, 8000 + i)
        batch = to_records(qrows)
        for r in batch:
            r._values["ID"] = [f"q{i}__{r.record_id}"]
        t0 = time.perf_counter()
        proc.deduplicate(batch)
        times.append(time.perf_counter() - t0)
    best = min(times)
    corpus_rows = index.corpus.size

    # device bytes per corpus row (features + embedding + masks)
    per_row = 0
    for tensors in index.corpus.feats.values():
        for arr in tensors.values():
            per_row += arr.dtype.itemsize * int(
                arr.size // max(1, arr.shape[0])
            )

    print(json.dumps({
        "rows": corpus_rows,
        "ingest_rows_per_sec": round(ingest_rate, 1),
        "query_rows_per_sec": round(args.batch / best, 1),
        "effective_pairs_per_sec": round(args.batch * corpus_rows / best, 1),
        "hbm_bytes_per_row": per_row,
        "batch_seconds": round(best, 3),
    }))


if __name__ == "__main__":
    main()
