"""Multi-host bootstrap memory benchmark (VERDICT r4 #3 "done" proof).

Measures the frontend's transient memory and the wire-frame sizes while
streaming a large corpus bootstrap to a real follower over a real TCP
socket (two OS processes, the production ``Dispatcher.broadcast`` /
``_FollowerSession`` code), then a hot-reload re-stream.  The r4 protocol
pickled snapshot-bytes + every Record into ONE message — O(corpus) frame
+ O(corpus) transient RAM on both sides; the streamed protocol must hold
the largest frame at ~DUKE_DISPATCH_SNAP_CHUNK and the frontend RSS delta
at O(chunk), independent of --rows.

The frontend topology mirrors the flagship restart: records live in a
SQLite store behind a LazyRecordMap (no eager mirror), features in the
corpus host arrays.  Scoring is deliberately not run — this isolates the
bootstrap path; serving equivalence is tests/test_multihost_serving.py.

Usage::

    python benchmarks/bootstrap_bench.py [--rows 1000000] [--batch 8192]

Prints one JSON line with rss/frames stats for BASELINE.md.
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import socket
import struct
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("DEVICE_PREWARM", "0")

SCHEMA_XML = """
<DukeMicroService>
  <Deduplication name="people" link-database-type="in-memory">
    <duke>
      <schema>
        <threshold>0.8</threshold>
        <property><name>NAME</name><comparator>levenshtein</comparator><low>0.3</low><high>0.9</high></property>
        <property><name>CITY</name><comparator>exact</comparator><low>0.4</low><high>0.85</high></property>
      </schema>
      <data-source class="io.sesam.dukemicroservice.IncrementalDeduplicationDataSource">
        <param name="dataset-id" value="crm"/>
        <column name="name" property="NAME"/>
        <column name="city" property="CITY"/>
      </data-source>
    </duke>
  </Deduplication>
</DukeMicroService>
"""


def _maxrss_mb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def _make_records(start: int, n: int):
    from sesam_duke_microservice_tpu.core.records import (
        ID_PROPERTY_NAME, Record,
    )

    out = []
    for i in range(start, start + n):
        r = Record()
        r.add_value(ID_PROPERTY_NAME, f"crm__crm__r{i}")
        r.add_value("NAME", f"person {i % 97} no {i}")
        r.add_value("CITY", f"city-{i % 1024}")
        out.append(r)
    return out


def follower_child(port: int) -> None:
    """Child: accept the op stream, run _FollowerSession, report rss."""
    from sesam_duke_microservice_tpu.parallel import dispatch

    sock = socket.create_connection(("127.0.0.1", port), timeout=60)
    session = dispatch._FollowerSession(sock.sendall)
    n_ops = 0
    try:
        while True:
            try:
                op = dispatch._recv_msg(sock)
            except EOFError:
                break
            n_ops += 1
            if not session.handle(op):
                break
        key = ("deduplication", "people")
        replica = session.replicas.get(key)
        print(json.dumps({
            "follower_rss_mb": round(_maxrss_mb(), 1),
            "follower_rows": replica.index.corpus.size if replica else 0,
            "follower_ops": n_ops,
        }), flush=True)
    finally:
        session.close()
        sock.close()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_000)
    ap.add_argument("--batch", type=int, default=8192)
    ap.add_argument("--_child-port", type=int, default=0)
    args = ap.parse_args()
    if args._child_port:
        follower_child(args._child_port)
        return

    from sesam_duke_microservice_tpu.core.config import parse_config
    from sesam_duke_microservice_tpu.engine.ann_matcher import AnnIndex
    from sesam_duke_microservice_tpu.parallel import dispatch
    from sesam_duke_microservice_tpu.store.records import (
        LazyRecordMap, SqliteRecordStore,
    )

    sc = parse_config(SCHEMA_XML, env={})
    schema = sc.deduplications["people"].duke

    tmp = tempfile.mkdtemp(prefix="bootstrap-bench-")
    store = SqliteRecordStore(os.path.join(tmp, "records.db"))
    index = AnnIndex(schema, tunables=sc.tunables)

    t0 = time.perf_counter()
    for start in range(0, args.rows, args.batch):
        batch = _make_records(start, min(args.batch, args.rows - start))
        store.put_many(batch)
        for r in batch:
            index.index(r)
        index.commit()
    ingest_s = time.perf_counter() - t0
    # flagship restart topology: store-backed lazy mirror, no eager dict
    index.records = LazyRecordMap(store)
    rss_after_build = _maxrss_mb()

    server = socket.create_server(("127.0.0.1", 0))
    port = server.getsockname()[1]
    child = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__),
         "--_child-port", str(port)],
        stdout=subprocess.PIPE, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    conn, _ = server.accept()

    d = dispatch.Dispatcher(app=None)
    d._conns = [conn]
    frames = {"n": 0, "max": 0, "total": 0}
    orig_broadcast = dispatch.Dispatcher.broadcast

    def counting_broadcast(self, op):
        import pickle

        sz = len(pickle.dumps(op, protocol=pickle.HIGHEST_PROTOCOL))
        frames["n"] += 1
        frames["max"] = max(frames["max"], sz)
        frames["total"] += sz
        orig_broadcast(self, op)

    d.broadcast = counting_broadcast.__get__(d)

    t1 = time.perf_counter()
    d.broadcast((
        "bootstrap_begin", "sharded", SCHEMA_XML, dispatch._env_fingerprint()
    ))
    d._stream_state(("deduplication", "people"), index)
    d.broadcast(("bootstrap_end",))
    stream1_s = time.perf_counter() - t1
    # hot reload path: the same states stream again
    t2 = time.perf_counter()
    d.broadcast(("reload_begin", "sharded", SCHEMA_XML))
    d._stream_state(("deduplication", "people"), index)
    d.broadcast(("bootstrap_end",))
    reload_s = time.perf_counter() - t2
    d.broadcast(("shutdown",))
    conn.close()
    server.close()

    child_out, _ = child.communicate(timeout=600)
    rss_after_stream = _maxrss_mb()
    follower = json.loads(child_out.strip().splitlines()[-1])

    print(json.dumps({
        "rows": args.rows,
        "ingest_s": round(ingest_s, 1),
        "stream_s": round(stream1_s, 1),
        "reload_stream_s": round(reload_s, 1),
        "frontend_rss_after_build_mb": round(rss_after_build, 1),
        "frontend_rss_after_stream_mb": round(rss_after_stream, 1),
        "frontend_stream_rss_delta_mb": round(
            rss_after_stream - rss_after_build, 1
        ),
        "frames": frames["n"],
        "max_frame_mb": round(frames["max"] / 1e6, 2),
        "total_streamed_mb": round(frames["total"] / 1e6, 1),
        **follower,
    }), flush=True)


if __name__ == "__main__":
    main()
