"""Ring bulk re-match at scale on the (virtual) mesh — VERDICT r2 #8.

Scores a corpus against itself (the bulk re-match shape, N x N) through
``parallel/ring.py`` — both query and corpus axes sharded, blocks rotating
over ppermute — and, with ``--verify``, re-scores the same queries through
the replicated ``parallel/sharded.py`` layout and asserts the surviving
(pair, logit) sets are identical.

On hosts without enough chips it self-provisions the virtual CPU mesh
(same recipe as the driver's dryrun).  The absolute throughput on the CPU
mesh is an artifact; the result that matters is the layout equality at
>= 100k x 100k and that per-device query memory is N/D.

Usage::

    python benchmarks/ring_rematch_bench.py [--rows 100000] [--devices 8]
        [--verify] [--block 8192]

Prints ONE JSON line.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _reexec(argv, n_devices):
    from sesam_duke_microservice_tpu.utils.virtual_mesh import (
        virtual_mesh_env,
    )

    env = virtual_mesh_env(n_devices, "_RING_BENCH_INNER")
    code = (
        "from sesam_duke_microservice_tpu.utils.virtual_mesh import "
        "force_cpu_platform; force_cpu_platform(); "
        "import runpy, sys; sys.argv = %r; "
        "runpy.run_path(%r, run_name='__main__')"
        % ([sys.argv[0]] + argv, os.path.abspath(__file__))
    )
    proc = subprocess.run([sys.executable, "-c", code], env=env)
    sys.exit(proc.returncode)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=100_000)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--block", type=int, default=8192,
                    help="query rows per ring call (multiple of devices)")
    ap.add_argument("--chunk", type=int, default=512)
    ap.add_argument("--top-k", type=int, default=64)
    ap.add_argument("--verify", action="store_true",
                    help="also run the replicated layout and compare")
    ap.add_argument("--schema", choices=["lev", "exact"], default="lev",
                    help="'exact' swaps the Levenshtein comparator for a "
                         "hash-equality one: the layout-equality property "
                         "under test is schema-independent, and exact "
                         "pairs run ~100x faster on the 1-core virtual "
                         "CPU mesh, making 100k x 100k tractable there")
    args = ap.parse_args()

    import jax

    if (len(jax.devices()) < args.devices
            and os.environ.get("_RING_BENCH_INNER") != "1"):
        _reexec(sys.argv[1:], args.devices)

    import jax.numpy as jnp
    import numpy as np

    from sesam_duke_microservice_tpu.core import comparators as C
    from sesam_duke_microservice_tpu.core.config import DukeSchema
    from sesam_duke_microservice_tpu.core.records import (
        ID_PROPERTY_NAME,
        Property,
        Record,
    )
    from sesam_duke_microservice_tpu.ops import features as F
    from sesam_duke_microservice_tpu.ops import scoring as S
    from sesam_duke_microservice_tpu.parallel import (
        RingQueryPlacer,
        ShardedCorpus,
        build_ring_scorer,
        build_sharded_scorer,
        corpus_mesh,
    )

    n = args.rows
    mesh = corpus_mesh(jax.devices()[: args.devices])

    comparator = C.Levenshtein() if args.schema == "lev" else C.Exact()
    schema = DukeSchema(
        threshold=0.8, maybe_threshold=None,
        properties=[
            Property(ID_PROPERTY_NAME, id_property=True),
            Property("NAME", comparator, 0.1, 0.95),
        ],
        data_sources=[],
    )
    plan = F.SchemaFeatures.plan(schema)

    rng = np.random.default_rng(1234)
    letters = np.array(list("abcdefghijklmnopqrstuvwxyz"))
    records = []
    prev = None
    for i in range(n):
        r = Record()
        r.add_value(ID_PROPERTY_NAME, f"d__{i}")
        # random 16-char names (distinct rows are far apart in edit
        # distance); every third row duplicates its predecessor -> the
        # survivor set is exactly the seeded duplicate pairs
        if i % 3 == 2 and prev is not None:
            name = prev
        else:
            name = "".join(letters[rng.integers(0, 26, size=16)])
        prev = name
        r.add_value("NAME", name)
        records.append(r)
    feats = F.extract_batch(plan, records)
    valid = np.ones((n,), bool)
    deleted = np.zeros((n,), bool)
    group = np.full((n,), -1, np.int32)

    placer = ShardedCorpus(mesh, chunk=args.chunk)
    sfeats, svalid, sdeleted, sgroup = placer.place(feats, valid, deleted, group)
    qplacer = RingQueryPlacer(mesh)
    ring = build_ring_scorer(plan, mesh, chunk=args.chunk, top_k=args.top_k)
    min_logit = jnp.float32(S.probability_to_logit(0.8) - 1e-3)

    def survivors(tl, ti, rows):
        out = set()
        for qi in range(rows.size):
            keep = tl[qi] > float(min_logit)
            for logit, crow in zip(tl[qi][keep], ti[qi][keep]):
                if int(crow) >= 0:
                    out.add((int(rows[qi]), int(crow), round(float(logit), 4)))
        return out

    ring_pairs = set()
    t0 = time.perf_counter()
    for start in range(0, n, args.block):
        rows = np.arange(start, min(start + args.block, n))
        qf = {p: {k: a[rows] for k, a in t.items()} for p, t in feats.items()}
        rqf, rqg, rqr = qplacer.place(
            qf, group[rows], rows.astype(np.int32)
        )
        tl, ti, cnt = ring(rqf, sfeats, svalid, sdeleted, sgroup, rqg, rqr,
                           min_logit)
        tl = np.asarray(tl)[: rows.size]
        ti = np.asarray(ti)[: rows.size]
        assert int(np.asarray(cnt)[: rows.size].max(initial=0)) <= args.top_k
        ring_pairs |= survivors(tl, ti, rows)
    ring_s = time.perf_counter() - t0

    out = {
        "mode": "ring", "devices": int(mesh.size), "rows": n,
        "pairs_ranked": n * n, "ring_seconds": round(ring_s, 2),
        "pairs_per_sec": round(n * n / ring_s),
        "survivor_pairs": len(ring_pairs),
        "per_device_query_rows": args.block // mesh.size,
    }

    if args.verify:
        sharded = build_sharded_scorer(
            plan, mesh, chunk=args.chunk, top_k=args.top_k
        )
        repl_pairs = set()
        t1 = time.perf_counter()
        for start in range(0, n, args.block):
            rows = np.arange(start, min(start + args.block, n))
            qf = {
                p: {k: jnp.asarray(a[rows]) for k, a in t.items()}
                for p, t in feats.items()
            }
            tl, ti, cnt = sharded(
                qf, sfeats, svalid, sdeleted, sgroup,
                jnp.asarray(group[rows]), jnp.asarray(rows.astype(np.int32)),
                min_logit,
            )
            repl_pairs |= survivors(
                np.asarray(tl)[: rows.size], np.asarray(ti)[: rows.size],
                rows,
            )
        out["replicated_seconds"] = round(time.perf_counter() - t1, 2)
        out["verified_equal"] = ring_pairs == repl_pairs
        assert out["verified_equal"], (
            f"ring != replicated: {len(ring_pairs)} vs {len(repl_pairs)} "
            f"pairs; diff sample: "
            f"{list(ring_pairs ^ repl_pairs)[:5]}"
        )

    print(json.dumps(out))


if __name__ == "__main__":
    main()
