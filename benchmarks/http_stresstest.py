"""HTTP-level stresstest: the Sesam-node pipe flow against a real server.

The reference is system-tested by an external Sesam node pumping seeded
fake entities through the REST surface and polling links back
(sesam_node_deduplication_stresstest_config.conf.json:19-36,86-106 — two
sources of 10,000 entities, seed 1234, area in [1,10], ids in [1,1e6]).
The in-process F1 harness (f1_stresstest.py) measures matching quality at
the engine layer; THIS driver is the reference's actual test shape: an
in-process Sesam stand-in that POSTs JSON batches over real HTTP (so the
service layer — lock discipline, ingest microbatching, datasource
conversion, link feed — is inside the measurement) and polls ``?since=``
incrementally like a ``supports_since`` source pipe.

Usage::

    python benchmarks/http_stresstest.py [--backend host|device|ann|sharded|sharded-brute]
        [--entities 10000] [--batch 500] [--concurrency 4]
        [--workload dedup|linkage]

Prints one JSON line: {"backend", "workload", "entities", "wall_s",
"post_rows_per_sec", "links", "poll_batches", "f1" (vs seeded truth)}.
"""

from __future__ import annotations

import argparse
import concurrent.futures
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import urllib.request

from f1_stresstest import generate, generate_linkage, truth_links, truth_pairs

CONFIG_TEMPLATE = """
<DukeMicroService>
  <Deduplication name="stress" link-database-type="in-memory">
    <duke>
      <schema>
        <threshold>0.8</threshold>
        <property><name>NAME</name><comparator>levenshtein</comparator><low>0.25</low><high>0.85</high></property>
        <property><name>CITY</name><comparator>exact</comparator><low>0.45</low><high>0.65</high></property>
        <property><name>SSN</name><comparator>qgram</comparator><low>0.2</low><high>0.9</high></property>
      </schema>
      <data-source class="io.sesam.dukemicroservice.IncrementalDeduplicationDataSource">
        <param name="dataset-id" value="src"/>
        <column name="name" property="NAME"/>
        <column name="city" property="CITY"/>
        <column name="ssn" property="SSN"/>
      </data-source>
    </duke>
  </Deduplication>
  <RecordLinkage name="stress" link-mode="{link_mode}" link-database-type="in-memory">
    <duke>
      <schema>
        <threshold>0.8</threshold>
        <property><name>NAME</name><comparator>levenshtein</comparator><low>0.25</low><high>0.85</high></property>
        <property><name>CITY</name><comparator>exact</comparator><low>0.45</low><high>0.65</high></property>
        <property><name>SSN</name><comparator>qgram</comparator><low>0.2</low><high>0.9</high></property>
      </schema>
      <group>
        <data-source class="io.sesam.dukemicroservice.IncrementalRecordLinkageDataSource">
          <param name="dataset-id" value="g1"/>
          <column name="name" property="NAME"/>
          <column name="city" property="CITY"/>
          <column name="ssn" property="SSN"/>
        </data-source>
      </group>
      <group>
        <data-source class="io.sesam.dukemicroservice.IncrementalRecordLinkageDataSource">
          <param name="dataset-id" value="g2"/>
          <column name="name" property="NAME"/>
          <column name="city" property="CITY"/>
          <column name="ssn" property="SSN"/>
        </data-source>
      </group>
    </duke>
  </RecordLinkage>
</DukeMicroService>
"""


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=600) as resp:
        body = resp.read()
        assert resp.status == 200, (resp.status, body[:200])
        return json.loads(body)


def _get(url):
    with urllib.request.urlopen(url, timeout=600) as resp:
        return json.loads(resp.read())


def run(backend: str, entities: int, batch: int, concurrency: int,
        workload: str, one_to_one: bool = False):
    from sesam_duke_microservice_tpu.core.config import parse_config
    from sesam_duke_microservice_tpu.service.app import DukeApp, serve
    from sesam_duke_microservice_tpu.utils.jit_cache import (
        enable_persistent_cache,
    )

    if backend in ("device", "ann", "sharded", "sharded-brute"):
        enable_persistent_cache()
    # config env flags apply only to this run's config parse — mutate and
    # restore so in-process callers (the smoke test) don't leak mode
    # changes into the rest of their process
    saved = {k: os.environ.get(k) for k in ("MIN_RELEVANCE", "ONE_TO_ONE")}
    os.environ.setdefault("MIN_RELEVANCE", "0.05")
    # the mode rides the per-workload XML attribute (round 3: link-mode is
    # honored per <RecordLinkage> element); clear any ambient ONE_TO_ONE so
    # the env override cannot silently flip the CLI flag's intent
    os.environ.pop("ONE_TO_ONE", None)
    config = CONFIG_TEMPLATE.format(
        link_mode="one-to-one" if one_to_one else "many-to-many"
    )
    try:
        app = DukeApp(parse_config(config), backend=backend,
                      persistent=False)
    finally:
        for key, value in saved.items():
            if value is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = value
    server = serve(app, port=0, host="127.0.0.1")
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    base = f"http://127.0.0.1:{server.server_address[1]}"

    if workload == "linkage":
        g1, g2, t1, t2 = generate_linkage(entities // 2, 0.3, 1234)
        posts = (
            [(f"{base}/recordlinkage/stress/g1", g1[s:s + batch])
             for s in range(0, len(g1), batch)]
            + [(f"{base}/recordlinkage/stress/g2", g2[s:s + batch])
               for s in range(0, len(g2), batch)]
        )
        expected = truth_links(t1, t2)
        feed = f"{base}/recordlinkage/stress"
    else:
        rows, truth = generate(entities, 0.3, 1234)
        posts = [
            (f"{base}/deduplication/stress/src", rows[s:s + batch])
            for s in range(0, len(rows), batch)
        ]
        expected = truth_pairs(truth)
        feed = f"{base}/deduplication/stress"

    t0 = time.perf_counter()
    # the Sesam node runs several pipes concurrently — concurrency > 1
    # exercises the service's ingest microbatching
    with concurrent.futures.ThreadPoolExecutor(concurrency) as pool:
        list(pool.map(lambda p: _post(*p), posts))
    wall = time.perf_counter() - t0

    # incremental polling, supports_since-style: advance the cursor batch
    # by batch until the feed drains
    since = 0
    links = {}
    poll_batches = 0
    while True:
        rows_ = _get(f"{feed}?since={since}")
        if not rows_:
            break
        poll_batches += 1
        for row in rows_:
            key = tuple(sorted((row["entity1"], row["entity2"])))
            if row["_deleted"]:
                links.pop(key, None)
            else:
                links[key] = row["confidence"]
            since = max(since, row["_updated"])

    emitted = set(links)
    tp = len(emitted & expected)
    precision = tp / len(emitted) if emitted else 0.0
    recall = tp / len(expected) if expected else 1.0
    f1 = (2 * precision * recall / (precision + recall)
          if precision + recall else 0.0)

    server.shutdown()
    return {
        "backend": backend,
        "workload": workload,
        "entities": entities,
        "wall_s": round(wall, 2),
        "post_rows_per_sec": round(entities / wall, 1),
        "links": len(links),
        "poll_batches": poll_batches,
        "f1": round(f1, 4),
        "precision": round(precision, 4),
        "recall": round(recall, 4),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--backend", default="host",
                    choices=["host", "device", "ann", "sharded",
                             "sharded-brute"])
    ap.add_argument("--entities", type=int, default=10000)
    ap.add_argument("--batch", type=int, default=500)
    ap.add_argument("--concurrency", type=int, default=4)
    ap.add_argument("--workload", default="dedup",
                    choices=["dedup", "linkage"])
    ap.add_argument("--one-to-one", action="store_true",
                    help="activate the real ONE_TO_ONE listener policy")
    args = ap.parse_args()
    print(json.dumps(run(args.backend, args.entities, args.batch,
                         args.concurrency, args.workload,
                         one_to_one=args.one_to_one)))


if __name__ == "__main__":
    main()
