"""Restart-to-serving wall clock at scale (VERDICT r2 #5).

Builds a persistent ANN workload of N seeded records (store puts + feature
extraction into the host corpus mirror — no scoring; the restart path
doesn't need it), saves the corpus snapshot, then measures a cold
"container restart": ``build_workload`` over the same data folder, which
loads the record store and restores the corpus tensors from the snapshot
(O(1) content-hash staleness check against the store's incremental digest
— ``store.records.SqliteRecordStore.content_hash``).

Usage::

    python benchmarks/restart_bench.py [--rows 10000000] [--dim 256]

Prints ONE JSON line with the phase timings.  Scale notes:

  * 10M rows needs DEVICE_INITIAL_CAPACITY pre-sizing (set automatically)
    and ~25 GB free disk (sqlite store + uncompressed snapshot; the bench
    sets SNAPSHOT_COMPRESS=0 — zlib over ~9 GB costs minutes).
  * the restart figure is store-load + snapshot-load + wiring; the first
    scoring batch additionally pays the device upload of the restored
    host mirror and any uncached XLA compiles.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("SNAPSHOT_COMPRESS", "0")


CONFIG_TEMPLATE = """
<DukeMicroService dataFolder="{folder}">
  <Deduplication name="restart" link-database-type="h2">
    <duke>
      <schema>
        <threshold>0.8</threshold>
        <property><name>NAME</name><comparator>levenshtein</comparator><low>0.25</low><high>0.85</high></property>
        <property><name>CITY</name><comparator>exact</comparator><low>0.45</low><high>0.65</high></property>
        <property><name>SSN</name><comparator>qgram</comparator><low>0.2</low><high>0.9</high></property>
      </schema>
      <data-source class="io.sesam.dukemicroservice.IncrementalDeduplicationDataSource">
        <param name="dataset-id" value="src"/>
        <column name="name" property="NAME"/>
        <column name="city" property="CITY"/>
        <column name="ssn" property="SSN"/>
      </data-source>
    </duke>
  </Deduplication>
</DukeMicroService>
"""


def seeded_entities(n, seed=1234):
    import random

    rng = random.Random(seed)
    first = ["ole", "kari", "per", "anne", "nils", "ingrid", "lars", "berit"]
    last = ["hansen", "johansen", "olsen", "larsen", "andersen", "pedersen"]
    cities = ["oslo", "bergen", "trondheim", "stavanger", "tromso"]
    for i in range(n):
        yield {
            "_id": str(i),
            "name": f"{rng.choice(first)} {rng.choice(last)} {i % 977}",
            "city": rng.choice(cities),
            "ssn": f"{rng.randrange(10**10):010d}",
        }


def run(rows: int, folder: str, batch: int = 50_000):
    from sesam_duke_microservice_tpu.core.config import parse_config
    from sesam_duke_microservice_tpu.engine.workload import build_workload

    os.environ.setdefault("MIN_RELEVANCE", "0.05")
    os.environ.setdefault("DEVICE_INITIAL_CAPACITY", str(rows + 4096))
    os.environ.setdefault("DEVICE_PREWARM", "0")
    sc = parse_config(CONFIG_TEMPLATE.format(folder=folder))
    wc = sc.deduplications["restart"]

    out = {"rows": rows}

    # -- build phase: store puts + index/commit (feature extraction) --------
    # (skipped when the folder already holds a built corpus — lets the
    # restart phase re-run without the ~15-minute 10M build)
    prebuilt = os.path.exists(
        os.path.join(wc.data_folder, "corpus_snapshot.npz"))
    if prebuilt:
        out["build_skipped"] = True
        snap = os.path.join(wc.data_folder, "corpus_snapshot.npz")
        out["snapshot_bytes"] = os.path.getsize(snap)
        return _restart_phase(rows, wc, sc, out)
    # a half-built folder (no snapshot) would restore + re-index on top of
    # itself and double the corpus; refuse instead
    if os.path.exists(os.path.join(wc.data_folder, "records.sqlite")):
        raise SystemExit(
            "data folder has a record store but no snapshot; delete it "
            "or point --folder elsewhere"
        )
    wl = build_workload(wc, sc, backend="ann", persistent=True)
    ds = wl.datasources["src"]
    t0 = time.perf_counter()
    t_store = t_index = 0.0
    done = 0
    for start in range(0, rows, batch):
        n = min(batch, rows - start)
        entities = list(seeded_entities(n, seed=start + 1))
        for e in entities:
            e["_id"] = str(start + int(e["_id"]))
        records = ds.records_for_batch(entities)
        t1 = time.perf_counter()
        wl.record_store.put_many(records)
        t2 = time.perf_counter()
        for r in records:
            wl.index.index(r)
        wl.index.commit()
        t3 = time.perf_counter()
        t_store += t2 - t1
        t_index += t3 - t2
        done += n
        if done % 1_000_000 < batch:
            print(f"  built {done}/{rows} rows "
                  f"({done / (time.perf_counter() - t0):.0f} rows/s)",
                  file=sys.stderr)
    out["build_total_s"] = round(time.perf_counter() - t0, 2)
    out["store_put_s"] = round(t_store, 2)
    out["extract_index_s"] = round(t_index, 2)

    t4 = time.perf_counter()
    wl.close()  # snapshot save + store/link close
    out["close_with_snapshot_save_s"] = round(time.perf_counter() - t4, 2)
    snap = os.path.join(wc.data_folder, "corpus_snapshot.npz")
    out["snapshot_bytes"] = os.path.getsize(snap)
    out["store_bytes"] = os.path.getsize(
        os.path.join(wc.data_folder, "records.sqlite")
    )

    return _restart_phase(rows, wc, sc, out)


def _restart_phase(rows, wc, sc, out):
    from sesam_duke_microservice_tpu.engine.workload import build_workload

    # prewarm during the restart leg measured HARMFUL on the
    # tunnel-attached bench host (remote compiles contend with the
    # snapshot load: 10M restart 257s -> 1871s); default off, opt in
    # with RESTART_PREWARM=1 on hosts with local TPU compile
    os.environ["DEVICE_PREWARM"] = os.environ.get(
        "RESTART_PREWARM", "0")
    t5 = time.perf_counter()
    wl2 = build_workload(wc, sc, backend="ann", persistent=True)
    out["restart_to_serving_s"] = round(time.perf_counter() - t5, 2)
    if out.get("build_skipped"):
        # prebuilt folder: the corpus defines the row count (a --rows
        # mismatch would otherwise size capacity wrong and abort the
        # measurement at the very end)
        out["rows"] = wl2.index.corpus.size
    else:
        assert wl2.index.corpus.size == rows, wl2.index.corpus.size
    out["snapshot_used"] = True

    # serving proof: one tiny transform probe end-to-end (also surfaces
    # the first-batch device upload + compile cost separately)
    t6 = time.perf_counter()
    with wl2.lock:
        wl2.process_batch(
            "src", [next(iter(seeded_entities(1, seed=7)))],
            http_transform=True,
        )
    out["first_probe_s"] = round(time.perf_counter() - t6, 2)
    wl2.close()
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=10_000_000)
    ap.add_argument("--folder", default=None,
                    help="data folder (default: fresh temp dir, deleted)")
    args = ap.parse_args()
    folder = args.folder or tempfile.mkdtemp(prefix="restart_bench_")
    try:
        print(json.dumps(run(args.rows, folder)))
    finally:
        if args.folder is None:
            shutil.rmtree(folder, ignore_errors=True)


if __name__ == "__main__":
    main()
