"""Sharded scorer vs single-device scorer on the virtual 8-device CPU mesh.

The contract: for any corpus placement, the mesh-sharded scorer returns the
same top-K logits, (global) row indices, and above-bound counts as the
single-device scorer over the concatenated corpus.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sesam_duke_microservice_tpu.ops import features as F
from sesam_duke_microservice_tpu.ops import scoring as S
from sesam_duke_microservice_tpu.parallel import (
    ShardedCorpus,
    build_sharded_scorer,
    corpus_mesh,
)

from test_device_matcher import dedup_schema, random_records

CHUNK = 16
TOP_K = 8


@pytest.fixture(scope="module")
def mesh():
    assert jax.device_count() == 8, "conftest must force 8 virtual CPU devices"
    return corpus_mesh()


def build_inputs(n_corpus, n_queries, seed=17):
    schema = dedup_schema()
    plan = F.SchemaFeatures.plan(schema)
    records = random_records(n_corpus, seed=seed)
    queries = records[:n_queries]
    feats = F.extract_batch(plan, records)
    valid = np.ones((n_corpus,), dtype=bool)
    valid[n_corpus // 3] = False          # one tombstone
    deleted = np.zeros((n_corpus,), dtype=bool)
    deleted[n_corpus // 2] = True         # one dukeDeleted row
    group = np.full((n_corpus,), -1, dtype=np.int32)
    qfeats = F.extract_batch(plan, queries)
    query_row = np.arange(n_queries, dtype=np.int32)
    query_group = np.full((n_queries,), -2, dtype=np.int32)
    return plan, feats, valid, deleted, group, qfeats, query_row, query_group


class TestShardedScorer:
    def test_matches_single_device(self, mesh):
        n = 8 * CHUNK * 2  # 2 chunks per shard
        (plan, feats, valid, deleted, group,
         qfeats, query_row, query_group) = build_inputs(n, 16)

        placer = ShardedCorpus(mesh, chunk=CHUNK)
        sfeats, svalid, sdeleted, sgroup = placer.place(
            feats, valid, deleted, group
        )
        sharded = build_sharded_scorer(plan, mesh, chunk=CHUNK, top_k=TOP_K)
        qf = {p: {k: jnp.asarray(a) for k, a in t.items()}
              for p, t in qfeats.items()}
        min_logit = jnp.float32(-5.0)
        s_logit, s_index, s_count = sharded(
            qf, sfeats, svalid, sdeleted, sgroup,
            jnp.asarray(query_group), jnp.asarray(query_row), min_logit,
        )

        # single-device reference over the same (padded) corpus
        cap = placer.padded_capacity(n)
        def pad(a, fill=0):
            out = np.full((cap,) + a.shape[1:], fill, dtype=a.dtype)
            out[:n] = a
            return out
        single = S.build_corpus_scorer(plan, chunk=CHUNK, top_k=TOP_K)
        d_logit, d_index, d_count = single(
            qf,
            {p: {k: jnp.asarray(pad(a)) for k, a in t.items()}
             for p, t in feats.items()},
            jnp.asarray(pad(valid, False)), jnp.asarray(pad(deleted, False)),
            jnp.asarray(pad(group, -1)),
            jnp.asarray(query_group), jnp.asarray(query_row), min_logit,
        )

        np.testing.assert_allclose(
            np.asarray(s_logit), np.asarray(d_logit), rtol=1e-5, atol=1e-5
        )
        np.testing.assert_array_equal(np.asarray(s_count), np.asarray(d_count))
        # ties may order differently across shards, so raw index equality is
        # too strict; instead every selected global row must score (on the
        # single-device scorer's full logit matrix) exactly what the sharded
        # scorer reported for it — catches any row-offset miscomputation
        s_idx = np.asarray(s_index)
        s_log = np.asarray(s_logit)
        d_idx = np.asarray(d_index)
        d_log = np.asarray(d_logit)
        for qi in range(s_idx.shape[0]):
            # rows scoring strictly above the K-th score are unambiguous
            # (no tie with the cut) and must be selected by both scorers —
            # catches any row-offset miscomputation in the sharded merge
            kth = d_log[qi, -1]
            strict_d = {int(r) for r, v in zip(d_idx[qi], d_log[qi])
                        if v > kth + 1e-4}
            strict_s = {int(r) for r, v in zip(s_idx[qi], s_log[qi])
                        if v > kth + 1e-4}
            assert strict_d == strict_s

    def test_group_filtering_sharded(self, mesh):
        n = 8 * CHUNK
        (plan, feats, valid, deleted, group,
         qfeats, query_row, query_group) = build_inputs(n, 8)
        group = np.asarray([1 + (i % 2) for i in range(n)], dtype=np.int32)
        query_group = np.asarray([1 + (i % 2) for i in range(8)], dtype=np.int32)

        placer = ShardedCorpus(mesh, chunk=CHUNK)
        sfeats, svalid, sdeleted, sgroup = placer.place(
            feats, valid, deleted, group
        )
        sharded = build_sharded_scorer(
            plan, mesh, chunk=CHUNK, top_k=TOP_K, group_filtering=True
        )
        qf = {p: {k: jnp.asarray(a) for k, a in t.items()}
              for p, t in qfeats.items()}
        s_logit, s_index, _ = sharded(
            qf, sfeats, svalid, sdeleted, sgroup,
            jnp.asarray(query_group), jnp.asarray(query_row),
            jnp.float32(-5.0),
        )
        s_index = np.asarray(s_index)
        s_logit = np.asarray(s_logit)
        # every returned candidate must be from the other group
        for qi in range(8):
            for k in range(TOP_K):
                row = s_index[qi, k]
                if row >= 0 and s_logit[qi, k] > S.NEG_INF / 2:
                    assert group[row] != query_group[qi]

    def test_self_exclusion_global_rows(self, mesh):
        # query i IS corpus row i; the sharded scorer must never return the
        # query's own global row even though shards renumber locally
        n = 8 * CHUNK
        (plan, feats, valid, deleted, group,
         qfeats, query_row, query_group) = build_inputs(n, 16)
        placer = ShardedCorpus(mesh, chunk=CHUNK)
        sfeats, svalid, sdeleted, sgroup = placer.place(
            feats, valid, deleted, group
        )
        sharded = build_sharded_scorer(plan, mesh, chunk=CHUNK, top_k=TOP_K)
        qf = {p: {k: jnp.asarray(a) for k, a in t.items()}
              for p, t in qfeats.items()}
        s_logit, s_index, _ = sharded(
            qf, sfeats, svalid, sdeleted, sgroup,
            jnp.asarray(query_group), jnp.asarray(query_row),
            jnp.float32(-5.0),
        )
        s_index = np.asarray(s_index)
        s_logit = np.asarray(s_logit)
        for qi in range(16):
            returned = s_index[qi][s_logit[qi] > S.NEG_INF / 2]
            assert qi not in returned


class TestMultihost:
    def test_initialize_noop_without_coordinator(self, monkeypatch):
        from sesam_duke_microservice_tpu.parallel import multihost

        monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
        assert multihost.initialize() is False

    def test_global_corpus_mesh_spans_all_devices(self):
        import jax

        from sesam_duke_microservice_tpu.parallel import global_corpus_mesh

        mesh = global_corpus_mesh()
        assert mesh.size == jax.device_count()
