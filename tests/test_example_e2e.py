"""End-to-end replication of the reference's example-config flow.

The reference's only functional system test is the Sesam pipe config
``sesam_node_example_config.conf.json``: it pulls the Duke example country
CSVs, pushes them through BOTH workloads' sink endpoints
(``/deduplication/...`` and ``/recordlinkage/...`` for each dataset,
lines 2-93), polls results back with ``supports_since`` (lines 94-119),
and exercises all four http-transform endpoints (lines 120-186).  This
test is that flow in-process: CSV fixtures -> HTTP POST per dataset ->
since-feed -> http-transforms, against the bundled default config
(the port of testdukeconfig.xml) — asserting against *longhand-computed*
expected links (textbook comparator math + Duke's published probability
map and Bayes combination), so the assertion chain never passes through
the engine's own oracle.

Note the reference config's ``capical`` column-name typo for the dbpedia
dataset is part of the schema and preserved here.
"""

import csv
import json
import os
import threading
import urllib.request

import pytest

from sesam_duke_microservice_tpu.core.config import load_default_config
from sesam_duke_microservice_tpu.service.app import DukeApp, serve

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


def _read_csv(name):
    with open(os.path.join(FIXTURES, name), newline="") as f:
        return list(csv.DictReader(f))


def _entities(rows):
    out = []
    for row in rows:
        entity = dict(row)
        entity["_id"] = entity.pop("id")
        out.append(entity)
    return out


# -- longhand Duke math (independent of the library; see test_goldens) ------

def _lev_distance(a, b):
    m, n = len(a), len(b)
    d = [[0] * (n + 1) for _ in range(m + 1)]
    for i in range(m + 1):
        d[i][0] = i
    for j in range(n + 1):
        d[0][j] = j
    for i in range(1, m + 1):
        for j in range(1, n + 1):
            d[i][j] = min(d[i - 1][j] + 1, d[i][j - 1] + 1,
                          d[i - 1][j - 1] + (a[i - 1] != b[j - 1]))
    return d[m][n]


def _lev_sim(a, b):
    if a == b:
        return 1.0
    s, l = min(len(a), len(b)), max(len(a), len(b))
    if s == 0 or (l - s) * 2 > s:
        return 0.0
    return 1.0 - min(_lev_distance(a, b), s) / s


def _numeric_sim(a, b, min_ratio=0.7):
    d1, d2 = float(a), float(b)
    if d1 == d2:
        return 1.0
    ratio = min(abs(d1), abs(d2)) / max(abs(d1), abs(d2))
    return ratio if ratio >= min_ratio else 0.0


def _pmap(sim, low, high):
    return (high - 0.5) * sim * sim + 0.5 if sim >= 0.5 else low


def _bayes(ps):
    num = den = 1.0
    for p in ps:
        num *= p
        den *= 1.0 - p
    return num / (num + den)


def expected_confidence(db_row, mo_row):
    """Longhand pair probability under the demo schema: NAME .09/.93
    Levenshtein, AREA .04/.73 Numeric(0.7), CAPITAL .12/.61 Levenshtein;
    values lower-cased by the cleaners."""
    name = _pmap(_lev_sim(db_row["country"].lower(),
                          mo_row["country"].lower()), 0.09, 0.93)
    area = _pmap(_numeric_sim(db_row["area"], mo_row["area"]), 0.04, 0.73)
    cap = _pmap(_lev_sim(db_row["capical"].lower(),
                         mo_row["capital"].lower()), 0.12, 0.61)
    return _bayes([name, area, cap])


def expected_links(threshold):
    """Cross-dataset country pairs whose longhand probability clears the
    threshold (same-name rows were built to match, Germany/Georgia to not)."""
    out = {}
    for db_row in _read_csv("countries_dbpedia.csv"):
        for mo_row in _read_csv("countries_mondial.csv"):
            conf = expected_confidence(db_row, mo_row)
            if conf > threshold:
                out[(db_row["id"], mo_row["id"])] = conf
    return out


@pytest.fixture(scope="module", params=["host", "device", "sharded"])
def example_server(request):
    os.environ["MIN_RELEVANCE"] = "0.05"  # tiny corpus: don't prune on tf-idf
    try:
        app = DukeApp(load_default_config(), backend=request.param,
                      persistent=False)
        server = serve(app, port=0, host="127.0.0.1")
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        yield f"http://127.0.0.1:{server.server_address[1]}"
        server.shutdown()
    finally:
        os.environ.pop("MIN_RELEVANCE", None)


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req) as resp:
        return resp.status, json.loads(resp.read())


def _get(url):
    with urllib.request.urlopen(url) as resp:
        return json.loads(resp.read())


def test_example_config_flow(example_server):
    base = example_server
    dbpedia = _entities(_read_csv("countries_dbpedia.csv"))
    mondial = _entities(_read_csv("countries_mondial.csv"))

    # 1. sink pushes: each dataset into BOTH workloads (example config
    #    pipes countries-*-to-duke / countries-*-to-duke-deduplication)
    for kind in ("recordlinkage", "deduplication"):
        s, body = _post(
            f"{base}/{kind}/countries-dbpedia-mondial/countries-dbpedia",
            dbpedia)
        assert (s, body) == (200, {"success": True})
        s, body = _post(
            f"{base}/{kind}/countries-dbpedia-mondial/countries-mondial",
            mondial)
        assert (s, body) == (200, {"success": True})

    # 2. the since-feed (supports_since source pipes): linkage at
    #    threshold 0.7 must contain exactly the longhand-expected pairs
    #    with longhand-exact confidences
    rows = _get(f"{base}/recordlinkage/countries-dbpedia-mondial?since=0")
    got = {
        (r["entity1"], r["entity2"]): r for r in rows if not r["_deleted"]
    }
    want = expected_links(0.7)
    assert set(got) == set(want)
    for pair, conf in want.items():
        assert got[pair]["confidence"] == pytest.approx(conf, abs=1e-9)
        assert got[pair]["dataset1"] == "countries-dbpedia"
        assert got[pair]["dataset2"] == "countries-mondial"
    # wire format: link _id is id1_id2 with ':' mapped to '_'
    # (App.java:758-767); the France row's entity id carries a ':'
    fr = next(r for r in rows if r["entity1"] == "fr:7")
    assert ":" not in fr["_id"]
    assert "fr_7" in fr["_id"]
    assert set(fr) == {"_id", "_updated", "_deleted", "entity1", "entity2",
                       "dataset1", "dataset2", "confidence"}

    # 3. dedup workload: same corpora in one group-free workload at
    #    threshold 0.9 — cross-dataset duplicates only for the pairs whose
    #    longhand probability clears 0.9
    rows = _get(f"{base}/deduplication/countries-dbpedia-mondial?since=0")
    got_dedup = {
        frozenset((r["entity1"], r["entity2"]))
        for r in rows if not r["_deleted"]
    }
    want_dedup = {frozenset(p) for p, c in expected_links(0.9).items()}
    assert got_dedup == want_dedup

    # 4. incremental since: polling from the max timestamp returns nothing
    last = max(r["_updated"] for r in rows)
    assert _get(
        f"{base}/deduplication/countries-dbpedia-mondial?since={last}") == []

    # 5. all four http-transform endpoints (…-http-transform pipes):
    #    entities echoed with duke_links; no link-db side effects
    before = _get(f"{base}/recordlinkage/countries-dbpedia-mondial?since=0")
    probe = [{"_id": "probe1", "country": "Norway", "area": "385000",
              "capical": "Oslo"}]
    s, body = _post(
        f"{base}/recordlinkage/countries-dbpedia-mondial/countries-dbpedia"
        "/httptransform", probe)
    assert s == 200
    assert body[0]["_id"] == "probe1"
    linked = {d["entityId"] for d in body[0]["duke_links"]}
    assert "m1" in linked          # mondial Norway
    assert "1" not in linked       # same-group dbpedia row excluded
    probe_mo = [{"_id": "probe2", "country": "Sweden", "capital": "Stockholm",
                 "area": "449000"}]
    s, body = _post(
        f"{base}/recordlinkage/countries-dbpedia-mondial/countries-mondial"
        "/httptransform", probe_mo)
    assert s == 200
    assert {d["entityId"] for d in body[0]["duke_links"]} >= {"2"}
    for dataset, payload in (("countries-dbpedia", probe),
                             ("countries-mondial", probe_mo)):
        s, body = _post(
            f"{base}/deduplication/countries-dbpedia-mondial/{dataset}"
            "/httptransform", payload)
        assert s == 200
        assert body[0]["duke_links"], (dataset, body)
    after = _get(f"{base}/recordlinkage/countries-dbpedia-mondial?since=0")
    assert after == before         # transforms never wrote links
    # transform probes were never indexed either: feeds still resolve only
    # fixture entity ids
    ids = {r["entity1"] for r in after} | {r["entity2"] for r in after}
    assert "probe1" not in ids and "probe2" not in ids
