"""Tests for link databases (memory + sqlite): idempotent assert, since feed,
retraction."""

import pytest

from sesam_duke_microservice_tpu.links import (
    InMemoryLinkDatabase,
    Link,
    LinkKind,
    LinkStatus,
    SqliteLinkDatabase,
    create_link_database,
)


@pytest.fixture(params=["memory", "sqlite"])
def linkdb(request, tmp_path):
    if request.param == "memory":
        return InMemoryLinkDatabase()
    return SqliteLinkDatabase(str(tmp_path / "links.sqlite"))


def L(id1, id2, conf=0.95, status=LinkStatus.INFERRED, kind=LinkKind.DUPLICATE, ts=None):
    return Link(id1, id2, status, kind, conf, ts)


def test_id_normalization():
    link = L("b", "a")
    assert (link.id1, link.id2) == ("a", "b")


def test_assert_and_get(linkdb):
    linkdb.assert_link(L("a", "b", ts=100))
    linkdb.assert_link(L("a", "c", ts=200))
    assert len(linkdb.get_all_links()) == 2
    assert {l.key() for l in linkdb.get_all_links_for("a")} == {("a", "b"), ("a", "c")}
    assert [l.key() for l in linkdb.get_all_links_for("c")] == [("a", "c")]
    assert linkdb.get_all_links_for("zzz") == []


def test_idempotent_assert_preserves_timestamp(linkdb):
    """Re-asserting an identical link must not bump the timestamp
    (SinceAwareInMemoryLinkDatabase.java:12-31)."""
    linkdb.assert_link(L("a", "b", conf=0.9, ts=100))
    linkdb.assert_link(L("a", "b", conf=0.9 + 1e-9, ts=999))
    (link,) = linkdb.get_all_links()
    assert link.timestamp == 100
    # changed confidence beyond epsilon -> replaced
    linkdb.assert_link(L("a", "b", conf=0.8, ts=999))
    (link,) = linkdb.get_all_links()
    assert link.timestamp == 999 and link.confidence == 0.8
    # changed status -> replaced
    linkdb.assert_link(L("a", "b", conf=0.8, status=LinkStatus.RETRACTED, ts=1500))
    (link,) = linkdb.get_all_links()
    assert link.status == LinkStatus.RETRACTED


def test_changes_since_strictly_greater(linkdb):
    linkdb.assert_link(L("a", "b", ts=100))
    linkdb.assert_link(L("c", "d", ts=200))
    linkdb.assert_link(L("e", "f", ts=300))
    assert len(linkdb.get_changes_since(0)) == 3
    assert [l.key() for l in linkdb.get_changes_since(100)] == [("c", "d"), ("e", "f")]
    assert linkdb.get_changes_since(300) == []


def test_retraction_flow(linkdb):
    linkdb.assert_link(L("a", "b", ts=100))
    for link in linkdb.get_all_links_for("a"):
        link.retract()
        linkdb.assert_link(link)
    (link,) = linkdb.get_all_links()
    assert link.status == LinkStatus.RETRACTED
    assert link.timestamp > 100  # retract touches the timestamp
    assert len(linkdb.get_changes_since(100)) == 1


def test_sqlite_persistence(tmp_path):
    path = str(tmp_path / "links.sqlite")
    db = SqliteLinkDatabase(path)
    db.assert_link(L("a", "b", ts=42))
    db.close()
    db2 = SqliteLinkDatabase(path)
    (link,) = db2.get_all_links()
    assert link.key() == ("a", "b") and link.timestamp == 42
    db2.close()


def test_factory(tmp_path):
    from sesam_duke_microservice_tpu.links import WriteBehindLinkDatabase

    # the durable backend is wrapped in the write-behind flusher (unless
    # DUKE_WRITE_BEHIND=0); the in-memory backend has nothing to overlap
    # and stays bare (links.write_behind)
    assert isinstance(create_link_database("in-memory"), InMemoryLinkDatabase)
    db = create_link_database("h2", str(tmp_path / "wl"), is_record_linkage=True)
    assert isinstance(db, WriteBehindLinkDatabase)
    assert isinstance(db.inner, SqliteLinkDatabase)
    assert db.inner.path.endswith("recordlinkdatabase.sqlite")
    with pytest.raises(ValueError):
        create_link_database("bogus")


def test_factory_write_behind_opt_out(tmp_path, monkeypatch):
    monkeypatch.setenv("DUKE_WRITE_BEHIND", "0")
    db = create_link_database("h2", str(tmp_path / "wl"))
    assert isinstance(db, SqliteLinkDatabase)
