"""Tests for the inverted index, processor, datasource and workload flows."""

import pytest

from sesam_duke_microservice_tpu.core.config import parse_config
from sesam_duke_microservice_tpu.engine.workload import build_workload
from sesam_duke_microservice_tpu.index.inverted import InvertedIndex, analyze
from sesam_duke_microservice_tpu.links.base import LinkStatus
from sesam_duke_microservice_tpu.service.datasource import (
    IncrementalDataSource,
    IngestError,
)

DEDUP_XML = """
<DukeMicroService>
  <Deduplication name="people" link-database-type="in-memory">
    <duke>
      <schema>
        <threshold>0.8</threshold>
        <property><name>NAME</name>
          <comparator>levenshtein</comparator><low>0.1</low><high>0.95</high>
        </property>
        <property><name>EMAIL</name>
          <comparator>exact</comparator><low>0.2</low><high>0.95</high>
        </property>
      </schema>
      <data-source class="io.sesam.dukemicroservice.IncrementalDeduplicationDataSource">
        <param name="dataset-id" value="crm"/>
        <column name="name" property="NAME"
                cleaner="no.priv.garshol.duke.cleaners.LowerCaseNormalizeCleaner"/>
        <column name="email" property="EMAIL"/>
      </data-source>
      <data-source class="io.sesam.dukemicroservice.IncrementalDeduplicationDataSource">
        <param name="dataset-id" value="web"/>
        <column name="name" property="NAME"
                cleaner="no.priv.garshol.duke.cleaners.LowerCaseNormalizeCleaner"/>
        <column name="email" property="EMAIL"/>
      </data-source>
    </duke>
  </Deduplication>
</DukeMicroService>
"""

LINKAGE_XML = """
<DukeMicroService>
  <RecordLinkage name="people" link-mode="many-to-many" link-database-type="in-memory">
    <duke>
      <schema>
        <threshold>0.7</threshold>
        <property><name>NAME</name>
          <comparator>levenshtein</comparator><low>0.1</low><high>0.95</high>
        </property>
      </schema>
      <group>
        <data-source class="io.sesam.dukemicroservice.IncrementalRecordLinkageDataSource">
          <param name="dataset-id" value="left"/>
          <column name="name" property="NAME"/>
        </data-source>
      </group>
      <group>
        <data-source class="io.sesam.dukemicroservice.IncrementalRecordLinkageDataSource">
          <param name="dataset-id" value="right"/>
          <column name="name" property="NAME"/>
        </data-source>
      </group>
    </duke>
  </RecordLinkage>
</DukeMicroService>
"""


@pytest.fixture
def dedup_workload():
    sc = parse_config(DEDUP_XML, env={"MIN_RELEVANCE": "0.05"})
    return build_workload(sc.deduplications["people"], sc, persistent=False)


@pytest.fixture
def linkage_workload():
    sc = parse_config(LINKAGE_XML, env={"MIN_RELEVANCE": "0.05"})
    return build_workload(sc.record_linkages["people"], sc, persistent=False)


def test_analyze():
    assert analyze("The Quick Brown-Fox!") == ["quick", "brown", "fox"]
    assert analyze("Åse 42") == ["åse", "42"]


def test_datasource_record_synthesis():
    sc = parse_config(DEDUP_XML, env={})
    ds = IncrementalDataSource(sc.deduplications["people"].duke.data_sources[0])
    r = ds.record_for_entity(
        {"_id": "e1", "name": "John SMITH", "email": "j@x.com", "extra": "ignored"}
    )
    assert r.record_id == "crm__e1"
    assert r.get_value("NAME") == "john smith"
    assert r.get_value("EMAIL") == "j@x.com"
    assert r.get_value("dukeOriginalEntityId") == "e1"
    assert r.get_value("dukeDatasetId") == "crm"
    assert not r.is_deleted()

    assert ds.record_for_entity({"_id": "e2", "_deleted": True, "name": "x"}).is_deleted()
    # array values become multi-valued properties (quirk Q1 fixed)
    multi = ds.record_for_entity({"_id": "e3", "name": ["Ann", "Anna"]})
    assert multi.get_values("NAME") == ["ann", "anna"]
    # numeric _id coerced to string
    assert ds.record_for_entity({"_id": 7, "name": "n"}).record_id == "crm__7"
    with pytest.raises(IngestError):
        ds.record_for_entity({"name": "no id"})


def test_linkage_datasource_group_prefix():
    sc = parse_config(LINKAGE_XML, env={})
    ds1 = IncrementalDataSource(sc.record_linkages["people"].duke.groups[0][0])
    r = ds1.record_for_entity({"_id": "e1", "name": "x"})
    assert r.record_id == "1__left__e1"
    assert r.get_value("dukeGroupNo") == "1"


def test_dedup_end_to_end(dedup_workload):
    wl = dedup_workload
    with wl.lock:
        wl.process_batch("crm", [
            {"_id": "1", "name": "John Smith", "email": "john@x.com"},
            {"_id": "2", "name": "Mary Jones", "email": "mary@x.com"},
        ])
        wl.process_batch("web", [
            {"_id": "9", "name": "Jon Smith", "email": "john@x.com"},
        ])
        rows = wl.links_since(0)
    assert len(rows) == 1
    row = rows[0]
    assert {row["entity1"], row["entity2"]} == {"1", "9"}
    assert {row["dataset1"], row["dataset2"]} == {"crm", "web"}
    assert row["_deleted"] is False
    assert row["confidence"] > 0.8
    assert row["_id"] == "crm__1_web__9"

    # incremental: polling after the fact returns nothing new
    ts = row["_updated"]
    with wl.lock:
        assert wl.links_since(ts) == []

    # re-posting the same batch must not create feed churn (idempotent assert)
    with wl.lock:
        wl.process_batch("web", [{"_id": "9", "name": "Jon Smith", "email": "john@x.com"}])
        assert wl.links_since(ts) == []


def test_dedup_delete_retracts_links(dedup_workload):
    wl = dedup_workload
    with wl.lock:
        wl.process_batch("crm", [{"_id": "1", "name": "John Smith", "email": "j@x.com"}])
        wl.process_batch("web", [{"_id": "9", "name": "John Smith", "email": "j@x.com"}])
        assert len(wl.links_since(0)) == 1
        ts = wl.links_since(0)[0]["_updated"]

        wl.process_batch("web", [{"_id": "9", "_deleted": True, "name": "John Smith"}])
        rows = wl.links_since(ts)
    assert len(rows) == 1
    assert rows[0]["_deleted"] is True
    # the tombstoned record must no longer be matchable
    with wl.lock:
        wl.process_batch("crm", [{"_id": "2", "name": "John Smith", "email": "j@x.com"}])
        new_rows = [r for r in wl.links_since(0) if "crm__2" in r["_id"]]
    assert all("web__9" not in r["_id"] for r in new_rows)


def test_http_transform_is_side_effect_free(dedup_workload):
    wl = dedup_workload
    with wl.lock:
        wl.process_batch("crm", [{"_id": "1", "name": "John Smith", "email": "j@x.com"}])
        rows = wl.process_batch(
            "web",
            [{"_id": "9", "name": "John Smith", "email": "j@x.com"},
             {"_id": "10", "name": "Zzz Yyy", "email": "z@y.com"}],
            http_transform=True,
        )
        assert len(rows) == 2
        assert rows[0]["_id"] == "9"
        assert rows[0]["duke_links"] == [
            {"datasetId": "crm", "entityId": "1", "confidence": pytest.approx(rows[0]["duke_links"][0]["confidence"])}
        ]
        assert rows[0]["duke_links"][0]["confidence"] > 0.8
        assert rows[1]["duke_links"] == []
        # no link persisted, nothing indexed
        assert wl.links_since(0) == []
        assert wl.index.find_record_by_id("web__9") is None


def test_recordlinkage_group_exclusion(linkage_workload):
    wl = linkage_workload
    with wl.lock:
        # two identical names in the SAME group: must not match each other
        wl.process_batch("left", [
            {"_id": "a", "name": "Turing"},
            {"_id": "b", "name": "Turing"},
        ])
        assert wl.links_since(0) == []
        # same name in the other group: matches both
        wl.process_batch("right", [{"_id": "c", "name": "Turing"}])
        rows = wl.links_since(0)
    keys = {r["_id"] for r in rows}
    assert keys == {"1__left__a_2__right__c", "1__left__b_2__right__c"}


def test_inverted_index_visibility_and_lookup(dedup_workload):
    sc = parse_config(DEDUP_XML, env={})
    idx = InvertedIndex(sc.deduplications["people"].duke)
    ds = IncrementalDataSource(sc.deduplications["people"].duke.data_sources[0])
    r = ds.record_for_entity({"_id": "1", "name": "Grace Hopper", "email": "g@h.com"})
    idx.index(r)
    # not visible before commit (Lucene searcher semantics)
    assert idx.find_record_by_id("crm__1") is None
    idx.commit()
    assert idx.find_record_by_id("crm__1").get_value("NAME") == "grace hopper"
    # reindex replaces previous copy
    r2 = ds.record_for_entity({"_id": "1", "name": "Grace B Hopper", "email": "g@h.com"})
    idx.index(r2)
    idx.commit()
    assert len(idx) == 1
    assert idx.find_record_by_id("crm__1").get_value("NAME") == "grace b hopper"


def test_max_search_hits_caps_search(dedup_workload):
    sc = parse_config(DEDUP_XML, env={"MAX_SEARCH_HITS": "3", "MIN_RELEVANCE": "0.0"})
    wl = build_workload(sc.deduplications["people"], sc, persistent=False)
    with wl.lock:
        batch = [
            {"_id": str(i), "name": "John Smith", "email": f"{i}@x.com"}
            for i in range(8)
        ]
        wl.process_batch("crm", batch)
    # search cap limits candidates per record, so matching still works but
    # each record saw at most 3 candidates
    assert wl.processor.stats.candidates_retrieved <= 3 * 8


def test_trace_batch_noop_and_budget(tmp_path, monkeypatch):
    from sesam_duke_microservice_tpu.utils import profiling

    # disabled: plain passthrough
    monkeypatch.delenv("PROFILE_TRACE_DIR", raising=False)
    with profiling.trace_batch("x"):
        pass

    # enabled: captures up to the budget, then passes through
    monkeypatch.setenv("PROFILE_TRACE_DIR", str(tmp_path))
    monkeypatch.setenv("PROFILE_TRACE_BATCHES", "1")
    monkeypatch.setattr(profiling, "_traced_batches", 0)
    import jax.numpy as jnp

    with profiling.trace_batch("batch-one"):
        jnp.zeros((4,)).block_until_ready()
    with profiling.trace_batch("batch-two"):   # over budget: no-op
        pass
    assert profiling._traced_batches == 1
    assert any(tmp_path.iterdir()), "trace directory should be populated"


def test_trace_batch_propagates_body_exceptions(tmp_path, monkeypatch):
    from sesam_duke_microservice_tpu.utils import profiling

    monkeypatch.setenv("PROFILE_TRACE_DIR", str(tmp_path))
    monkeypatch.setenv("PROFILE_TRACE_BATCHES", "5")
    monkeypatch.setattr(profiling, "_traced_batches", 0)
    with pytest.raises(ValueError, match="real scoring error"):
        with profiling.trace_batch("failing"):
            raise ValueError("real scoring error")


def test_one_to_one_listener_keeps_best_assignment():
    from sesam_duke_microservice_tpu.core.records import (
        ID_PROPERTY_NAME,
        ORIGINAL_ENTITY_ID_PROPERTY_NAME,
        Record,
    )
    from sesam_duke_microservice_tpu.engine.listeners import (
        ServiceMatchListener,
    )
    from sesam_duke_microservice_tpu.links.memory import InMemoryLinkDatabase

    def rec(rid):
        r = Record()
        r.add_value(ID_PROPERTY_NAME, rid)
        r.add_value(ORIGINAL_ENTITY_ID_PROPERTY_NAME, rid)
        return r

    a1, a2, b1, b2 = rec("a1"), rec("a2"), rec("b1"), rec("b2")
    linkdb = InMemoryLinkDatabase()
    lis = ServiceMatchListener("t", linkdb, kind="recordlinkage",
                               one_to_one=True)
    lis.batch_ready(2)
    # a1 matches both b1 (0.9) and b2 (0.95); a2 matches b2 (0.8)
    lis.matches(a1, b1, 0.9)
    lis.matches(a1, b2, 0.95)
    lis.matches(a2, b2, 0.8)
    lis.batch_done()
    links = {(l.id1, l.id2) for l in linkdb.get_changes_since(0)}
    # greedy by confidence: a1-b2 (0.95) wins; a2-b2 blocked (b2 taken);
    # a1-b1 blocked (a1 taken) -> exactly one definite link
    assert links == {("a1", "b2")}

    # without the flag all three links assert (reference quirk Q5 behavior)
    linkdb2 = InMemoryLinkDatabase()
    lis2 = ServiceMatchListener("t", linkdb2, kind="recordlinkage")
    lis2.batch_ready(2)
    lis2.matches(a1, b1, 0.9)
    lis2.matches(a1, b2, 0.95)
    lis2.matches(a2, b2, 0.8)
    lis2.batch_done()
    assert len(linkdb2.get_changes_since(0)) == 3


def test_one_to_one_cross_batch_retracts_weaker_link():
    from sesam_duke_microservice_tpu.core.records import (
        ID_PROPERTY_NAME,
        ORIGINAL_ENTITY_ID_PROPERTY_NAME,
        Record,
    )
    from sesam_duke_microservice_tpu.engine.listeners import (
        ServiceMatchListener,
    )
    from sesam_duke_microservice_tpu.links.base import LinkStatus
    from sesam_duke_microservice_tpu.links.memory import InMemoryLinkDatabase

    def rec(rid):
        r = Record()
        r.add_value(ID_PROPERTY_NAME, rid)
        r.add_value(ORIGINAL_ENTITY_ID_PROPERTY_NAME, rid)
        return r

    a1, a2, b1 = rec("a1"), rec("a2"), rec("b1")
    linkdb = InMemoryLinkDatabase()
    lis = ServiceMatchListener("t", linkdb, kind="recordlinkage",
                               one_to_one=True)
    # batch 1: a1-b1 at 0.9
    lis.batch_ready(1)
    lis.matches(a1, b1, 0.9)
    lis.batch_done()
    # batch 2: a2-b1 at 0.95 -> stronger, must retract a1-b1
    lis.batch_ready(1)
    lis.matches(a2, b1, 0.95)
    lis.batch_done()
    live = {(l.id1, l.id2) for l in linkdb.get_changes_since(0)
            if l.status != LinkStatus.RETRACTED}
    assert live == {("a2", "b1")}
    # batch 3: a1-b1 again at 0.9 -> weaker than existing 0.95, suppressed
    lis.batch_ready(1)
    lis.matches(a1, b1, 0.9)
    lis.batch_done()
    live = {(l.id1, l.id2) for l in linkdb.get_changes_since(0)
            if l.status != LinkStatus.RETRACTED}
    assert live == {("a2", "b1")}


def test_one_to_one_displacement_reassigns_runner_up():
    from sesam_duke_microservice_tpu.core.records import (
        ID_PROPERTY_NAME,
        ORIGINAL_ENTITY_ID_PROPERTY_NAME,
        Record,
    )
    from sesam_duke_microservice_tpu.engine.listeners import (
        ServiceMatchListener,
    )
    from sesam_duke_microservice_tpu.links.base import LinkStatus
    from sesam_duke_microservice_tpu.links.memory import InMemoryLinkDatabase

    def rec(rid):
        r = Record()
        r.add_value(ID_PROPERTY_NAME, rid)
        r.add_value(ORIGINAL_ENTITY_ID_PROPERTY_NAME, rid)
        return r

    a1, a2, b1, b2 = rec("a1"), rec("a2"), rec("b1"), rec("b2")
    linkdb = InMemoryLinkDatabase()
    # replay requires a resolver (the listener fails closed without one);
    # here every record stays live with its original content
    live_records = {r.record_id: r for r in (a1, a2, b1, b2)}
    lis = ServiceMatchListener("t", linkdb, kind="recordlinkage",
                               one_to_one=True,
                               record_resolver=live_records.get)
    # batch 1: a1-b1 wins at 0.9; a1's runner-up a1-b2 (0.85) is remembered
    lis.batch_ready(1)
    lis.matches(a1, b1, 0.9)
    lis.matches(a1, b2, 0.85)
    lis.batch_done()
    # batch 2: a2-b1 at 0.95 displaces a1 from b1 -> a1 falls back to its
    # remembered runner-up b2 instead of being stranded
    lis.batch_ready(1)
    lis.matches(a2, b1, 0.95)
    lis.batch_done()
    live = {(l.id1, l.id2) for l in linkdb.get_changes_since(0)
            if l.status != LinkStatus.RETRACTED}
    assert live == {("a2", "b1"), ("a1", "b2")}


def test_one_to_one_transform_pairs_never_become_links():
    from sesam_duke_microservice_tpu.core.records import (
        ID_PROPERTY_NAME,
        ORIGINAL_ENTITY_ID_PROPERTY_NAME,
        Record,
    )
    from sesam_duke_microservice_tpu.engine.listeners import (
        ServiceMatchListener,
    )
    from sesam_duke_microservice_tpu.links.base import LinkStatus
    from sesam_duke_microservice_tpu.links.memory import InMemoryLinkDatabase

    def rec(rid):
        r = Record()
        r.add_value(ID_PROPERTY_NAME, rid)
        r.add_value(ORIGINAL_ENTITY_ID_PROPERTY_NAME, rid)
        return r

    a1, a2, q, b1 = rec("a1"), rec("a2"), rec("transient-q"), rec("b1")
    linkdb = InMemoryLinkDatabase()
    lis = ServiceMatchListener("t", linkdb, kind="recordlinkage",
                               one_to_one=True)
    # indexed batch: a1-b1 asserted
    lis.batch_ready(1)
    lis.matches(a1, b1, 0.9)
    lis.batch_done()
    # http-transform probe: q also matches b1 but loses to nothing —
    # suppressed because b1 is claimed; its pair must NOT be remembered
    lis.set_link_database_updates_disabled(True)
    lis.batch_ready(1)
    lis.matches(q, b1, 0.85)
    lis.batch_done()
    lis.set_link_database_updates_disabled(False)
    # displacement: a2-b1 at 0.95 retracts a1-b1; the transform probe's
    # (q, b1) pair must not resurface as an assertable link
    lis.batch_ready(1)
    lis.matches(a2, b1, 0.95)
    lis.batch_done()
    live = {(l.id1, l.id2) for l in linkdb.get_changes_since(0)
            if l.status != LinkStatus.RETRACTED}
    assert live == {("a2", "b1")}
    assert all("transient-q" not in pair for pair in live)


def test_one_to_one_suppressed_record_gets_no_match_event():
    from sesam_duke_microservice_tpu.core.records import (
        ID_PROPERTY_NAME,
        ORIGINAL_ENTITY_ID_PROPERTY_NAME,
        Record,
    )
    from sesam_duke_microservice_tpu.engine.listeners import (
        ServiceMatchListener,
    )
    from sesam_duke_microservice_tpu.links.memory import InMemoryLinkDatabase

    def rec(rid):
        r = Record()
        r.add_value(ID_PROPERTY_NAME, rid)
        r.add_value(ORIGINAL_ENTITY_ID_PROPERTY_NAME, rid)
        return r

    a1, a2, b1 = rec("a1"), rec("a2"), rec("b1")
    linkdb = InMemoryLinkDatabase()
    lis = ServiceMatchListener("t", linkdb, kind="recordlinkage",
                               one_to_one=True)
    seen = []
    lis._wrapped.no_match_for = lambda r: seen.append(r.record_id)
    lis.batch_ready(2)
    lis.matches(a1, b1, 0.9)
    lis.matches(a2, b1, 0.8)   # loses b1 to a1, no other candidate
    lis.batch_done()
    # a2's only definite match was suppressed at flush -> the listener
    # protocol still emits a terminal event for it
    assert seen == ["a2"]


def test_fuzzy_search_expands_tokens():
    from sesam_duke_microservice_tpu.core import comparators as C
    from sesam_duke_microservice_tpu.core.config import (
        DukeSchema,
        MatchTunables,
    )
    from sesam_duke_microservice_tpu.core.records import (
        ID_PROPERTY_NAME,
        Property,
        Record,
    )

    schema = DukeSchema(
        threshold=0.8, maybe_threshold=None,
        properties=[
            Property(ID_PROPERTY_NAME, id_property=True),
            Property("NAME", C.Levenshtein(), 0.1, 0.9),
        ],
        data_sources=[],
    )

    def rec(rid, name):
        r = Record()
        r.add_value(ID_PROPERTY_NAME, rid)
        r.add_value("NAME", name)
        return r

    def build(fuzzy):
        t = MatchTunables()
        t.min_relevance = 0.0
        t.fuzzy_search = fuzzy
        idx = InvertedIndex(schema, t)
        idx.index(rec("a", "kristiansen"))
        idx.commit()
        return idx

    probe = rec("q", "kristianson")  # 2 edits from the indexed token
    assert build(False).find_candidate_matches(probe) == []
    fuzzy_hits = build(True).find_candidate_matches(probe)
    assert [r.record_id for r in fuzzy_hits] == ["a"]
    # beyond maxEdits=2 stays out even with fuzzy on
    far = rec("q2", "kristol")
    assert build(True).find_candidate_matches(far) == []


def test_osa_distance_counts_transpositions():
    from sesam_duke_microservice_tpu.index.inverted import _osa_distance

    assert _osa_distance("ab", "ba", 2) == 1          # one transposition
    assert _osa_distance("abcdef", "abcdef", 2) == 0
    assert _osa_distance("kristiansen", "kristianson", 2) == 1
    assert _osa_distance("kristiansen", "kristiansonx", 2) == 2
    assert _osa_distance("abcdef", "ghijkl", 2) == 3  # clipped past limit


def test_fuzzy_does_not_dilute_exact_match_scores():
    from sesam_duke_microservice_tpu.core import comparators as C
    from sesam_duke_microservice_tpu.core.config import (
        DukeSchema,
        MatchTunables,
    )
    from sesam_duke_microservice_tpu.core.records import (
        ID_PROPERTY_NAME,
        Property,
        Record,
    )

    schema = DukeSchema(
        threshold=0.8, maybe_threshold=None,
        properties=[
            Property(ID_PROPERTY_NAME, id_property=True),
            Property("NAME", C.Levenshtein(), 0.1, 0.9),
        ],
        data_sources=[],
    )

    def rec(rid, name):
        r = Record()
        r.add_value(ID_PROPERTY_NAME, rid)
        r.add_value("NAME", name)
        return r

    def hits(fuzzy, min_relevance):
        t = MatchTunables()
        t.min_relevance = min_relevance
        t.fuzzy_search = fuzzy
        idx = InvertedIndex(schema, t)
        idx.index(rec("exact", "kristiansen"))
        idx.index(rec("near", "kristianses"))
        idx.commit()
        return {r.record_id
                for r in idx.find_candidate_matches(rec("q", "kristiansen"))}

    # pick a cut that passes the exact match with fuzzy off
    base = hits(False, 0.1)
    assert "exact" in base
    # fuzzy ON may only ADD candidates at the same cut, never remove
    with_fuzzy = hits(True, 0.1)
    assert base <= with_fuzzy
    assert "near" in with_fuzzy
