"""Tests for the two-level XML config parser (core.config)."""

import pytest

from sesam_duke_microservice_tpu.core import config as cfg
from sesam_duke_microservice_tpu.core.comparators import Levenshtein, Numeric
from sesam_duke_microservice_tpu.core.records import Lookup


def demo_config_string():
    with open(cfg.DEFAULT_CONFIG_RESOURCE, "r", encoding="utf-8") as f:
        return f.read()


def test_parse_bundled_demo_config():
    sc = cfg.parse_config(demo_config_string(), env={})
    assert set(sc.deduplications) == {"countries-dbpedia-mondial"}
    assert set(sc.record_linkages) == {"countries-dbpedia-mondial"}

    dedup = sc.deduplications["countries-dbpedia-mondial"]
    assert dedup.duke.threshold == 0.9
    comparison = dedup.duke.comparison_properties()
    assert [p.name for p in comparison] == ["NAME", "AREA", "CAPITAL"]
    name_prop = dedup.duke.property_by_name("NAME")
    assert isinstance(name_prop.comparator, Levenshtein)
    assert name_prop.low == 0.09 and name_prop.high == 0.93

    # hidden properties injected
    all_names = [p.name for p in dedup.duke.properties]
    assert "ID" in all_names
    assert "dukeDatasetId" in all_names
    assert "dukeOriginalEntityId" in all_names
    assert "dukeDeleted" in all_names
    assert "dukeGroupNo" not in all_names  # dedup has no groups

    # Duke resolves <comparator> by object *name*; the demo config references
    # the class name, so AreaComparator's min-ratio is NOT applied (parity)
    area_prop = dedup.duke.property_by_name("AREA")
    assert isinstance(area_prop.comparator, Numeric)
    assert area_prop.comparator.min_ratio == 0.0

    # referencing the named object by name DOES apply its params
    named_ref = demo_config_string().replace(
        "<comparator>no.priv.garshol.duke.comparators.NumericComparator</comparator>",
        "<comparator>AreaComparator</comparator>",
    )
    sc_named = cfg.parse_config(named_ref, env={})
    area_named = sc_named.deduplications["countries-dbpedia-mondial"].duke.property_by_name("AREA")
    assert area_named.comparator.min_ratio == pytest.approx(0.7)

    # two datasources with cleaners wired
    assert [ds.dataset_id for ds in dedup.duke.data_sources] == [
        "countries-dbpedia",
        "countries-mondial",
    ]
    col = dedup.duke.data_sources[0].columns[0]
    assert col.name == "country" and col.property == "NAME"
    assert col.cleaner("USA") == "united states"


def test_parse_linkage_groups():
    sc = cfg.parse_config(demo_config_string(), env={})
    rl = sc.record_linkages["countries-dbpedia-mondial"]
    assert rl.link_mode == "one-to-one"
    assert rl.link_database_type == "h2"
    assert rl.duke.threshold == 0.7
    assert len(rl.duke.groups) == 2
    assert rl.duke.groups[0][0].group_no == 1
    assert rl.duke.groups[1][0].group_no == 2
    assert "dukeGroupNo" in [p.name for p in rl.duke.properties]


def test_env_flags():
    env = {
        "THREADS": "4",
        "PROFILE": "1",
        "MIN_RELEVANCE": "0.5",
        "FUZZY_SEARCH": "TRUE",
        "MAX_SEARCH_HITS": "25",
    }
    sc = cfg.parse_config(demo_config_string(), env=env)
    assert sc.threads == 4
    assert sc.profile is True
    assert sc.tunables.min_relevance == 0.5
    assert sc.tunables.fuzzy_search is True
    assert sc.tunables.max_search_hits == 25
    # non-numeric THREADS ignored (reference regex gate, App.java:233)
    sc2 = cfg.parse_config(demo_config_string(), env={"THREADS": "x4"})
    assert sc2.threads == 1


MINIMAL_DEDUP = """
<DukeMicroService>
  <Deduplication name="d">
    <duke>
      <schema>
        <threshold>0.8</threshold>
        <property><name>N</name>
          <comparator>levenshtein</comparator>
          <low>0.1</low><high>0.9</high>
        </property>
      </schema>
      <data-source class="io.sesam.dukemicroservice.IncrementalDeduplicationDataSource">
        <param name="dataset-id" value="ds1"/>
        <column name="n" property="N"/>
      </data-source>
    </duke>
  </Deduplication>
</DukeMicroService>
"""


def test_minimal_config_and_aliases():
    sc = cfg.parse_config(MINIMAL_DEDUP, env={})
    d = sc.deduplications["d"]
    assert isinstance(d.duke.property_by_name("N").comparator, Levenshtein)
    assert d.link_database_type == "h2"


def _expect_error(xml, message_part):
    with pytest.raises(cfg.ConfigError) as ei:
        cfg.parse_config(xml, env={})
    assert message_part in str(ei.value)


def test_validation_errors():
    _expect_error("<NotDuke/>", "didn't contain a 'DukeMicroService'")
    _expect_error(
        "<root><DukeMicroService/><DukeMicroService/></root>", "more than one"
    )
    _expect_error(
        "<DukeMicroService><Bogus/></DukeMicroService>", "Unknown element 'Bogus'"
    )
    # user-defined id property rejected (App.java:303-307)
    _expect_error(
        MINIMAL_DEDUP.replace(
            "<property><name>N</name>",
            '<property type="id"><name>MYID</name></property><property><name>N</name>',
        ),
        "id'-property",
    )
    # '_id' column rejected (App.java:378-384)
    _expect_error(
        MINIMAL_DEDUP.replace('name="n"', 'name="_id"'), "'_id' column"
    )
    # wrong datasource class
    _expect_error(
        MINIMAL_DEDUP.replace("IncrementalDeduplicationDataSource", "SomethingElse"),
        "unsupported type",
    )
    # missing dataset-id
    _expect_error(
        MINIMAL_DEDUP.replace('name="dataset-id" value="ds1"', 'name="x" value="y"'),
        "no datasetId",
    )


def test_linkage_validation():
    linkage = """
    <DukeMicroService>
      <RecordLinkage name="rl" link-mode="one-to-one">
        <duke>
          <schema><threshold>0.7</threshold>
            <property><name>N</name><comparator>exact</comparator>
              <low>0.1</low><high>0.9</high></property>
          </schema>
          <group>
            <data-source class="io.sesam.dukemicroservice.IncrementalRecordLinkageDataSource">
              <param name="dataset-id" value="a"/><column name="n" property="N"/>
            </data-source>
          </group>
          <group>
            <data-source class="io.sesam.dukemicroservice.IncrementalRecordLinkageDataSource">
              <param name="dataset-id" value="b"/><column name="n" property="N"/>
            </data-source>
          </group>
        </duke>
      </RecordLinkage>
    </DukeMicroService>
    """
    sc = cfg.parse_config(linkage, env={})
    assert sc.record_linkages["rl"].duke.groups[1][0].dataset_id == "b"

    _expect_error(linkage.replace('link-mode="one-to-one"', ''), "link-mode")
    _expect_error(
        linkage.replace('link-mode="one-to-one"', 'link-mode="many"'),
        "Invalid link-mode",
    )
    # only one group
    one_group = linkage.replace(
        """<group>
            <data-source class="io.sesam.dukemicroservice.IncrementalRecordLinkageDataSource">
              <param name="dataset-id" value="b"/><column name="n" property="N"/>
            </data-source>
          </group>""",
        "",
    )
    _expect_error(one_group, "exactly two <group>")


def test_lookup_attribute():
    xml = MINIMAL_DEDUP.replace(
        "<property><name>N</name>",
        '<property lookup="false"><name>M</name><comparator>exact</comparator>'
        "<low>0.2</low><high>0.8</high></property><property><name>N</name>",
    )
    sc = cfg.parse_config(xml, env={})
    duke = sc.deduplications["d"].duke
    assert duke.property_by_name("M").lookup == Lookup.FALSE
    lookups = [p.name for p in duke.lookup_properties()]
    assert "M" not in lookups and "N" in lookups


def test_invalid_lookup_value_is_config_error():
    _expect_error(
        MINIMAL_DEDUP.replace("<property>", '<property lookup="bogus">'),
        "Invalid lookup value 'bogus'",
    )


def test_sqlite_alias_and_bad_linkdb():
    sc = cfg.parse_config(
        MINIMAL_DEDUP.replace('name="d"', 'name="d" link-database-type="sqlite"'),
        env={},
    )
    assert sc.deduplications["d"].link_database_type == "h2"
    _expect_error(
        MINIMAL_DEDUP.replace('name="d"', 'name="d" link-database-type="bogus"'),
        "unknown 'link-database-type'",
    )


def test_malformed_xml_raises_config_error():
    from sesam_duke_microservice_tpu.core.config import ConfigError, parse_config

    with pytest.raises(ConfigError):
        parse_config("<DukeMicroService><Dedup")  # truncated document


def test_unknown_comparator_name_rejected():
    from sesam_duke_microservice_tpu.core.config import parse_config

    bad = MINIMAL_DEDUP.replace(
        "<comparator>levenshtein</comparator>",
        "<comparator>no.such.ComparatorAtAll</comparator>",
    )
    with pytest.raises(Exception) as err:
        parse_config(bad)
    assert "omparator" in str(err.value)


def test_empty_dataset_id_rejected():
    from sesam_duke_microservice_tpu.core.config import parse_config

    bad = MINIMAL_DEDUP.replace('value="ds1"', 'value=""')
    with pytest.raises(Exception) as err:
        parse_config(bad)
    assert "dataset" in str(err.value).lower()


def test_link_mode_controls_one_to_one_per_workload():
    """Round 3: link-mode on the <RecordLinkage> element is honored per
    workload (the reference parses but never reads it — quirk Q5); the
    ONE_TO_ONE env flag is a global override in either direction."""
    two_modes = """
    <DukeMicroService>
      <RecordLinkage name="strict" link-mode="one-to-one">
        <duke>
          <schema><threshold>0.7</threshold>
            <property><name>N</name><comparator>exact</comparator>
              <low>0.1</low><high>0.9</high></property>
          </schema>
          <group>
            <data-source class="io.sesam.dukemicroservice.IncrementalRecordLinkageDataSource">
              <param name="dataset-id" value="a"/><column name="n" property="N"/>
            </data-source>
          </group>
          <group>
            <data-source class="io.sesam.dukemicroservice.IncrementalRecordLinkageDataSource">
              <param name="dataset-id" value="b"/><column name="n" property="N"/>
            </data-source>
          </group>
        </duke>
      </RecordLinkage>
      <RecordLinkage name="loose" link-mode="many-to-many">
        <duke>
          <schema><threshold>0.7</threshold>
            <property><name>N</name><comparator>exact</comparator>
              <low>0.1</low><high>0.9</high></property>
          </schema>
          <group>
            <data-source class="io.sesam.dukemicroservice.IncrementalRecordLinkageDataSource">
              <param name="dataset-id" value="c"/><column name="n" property="N"/>
            </data-source>
          </group>
          <group>
            <data-source class="io.sesam.dukemicroservice.IncrementalRecordLinkageDataSource">
              <param name="dataset-id" value="d"/><column name="n" property="N"/>
            </data-source>
          </group>
        </duke>
      </RecordLinkage>
    </DukeMicroService>
    """
    sc = cfg.parse_config(two_modes, env={})
    assert sc.one_to_one is None
    assert sc.record_linkages["strict"].enforce_one_to_one
    assert not sc.record_linkages["loose"].enforce_one_to_one

    # env override wins in both directions
    assert cfg.parse_config(two_modes, env={"ONE_TO_ONE": "1"}).one_to_one is True
    assert cfg.parse_config(two_modes, env={"ONE_TO_ONE": "0"}).one_to_one is False

    # the two workloads behave independently end-to-end: same ambiguous
    # batch (one 'b'/'d' record matching two 'a'/'c' records exactly)
    from sesam_duke_microservice_tpu.engine.workload import build_workload

    sc = cfg.parse_config(two_modes, env={"MIN_RELEVANCE": "0.05"})
    strict = build_workload(sc.record_linkages["strict"], sc, persistent=False)
    loose = build_workload(sc.record_linkages["loose"], sc, persistent=False)
    try:
        with strict.lock:
            strict.process_batch("a", [{"_id": "a1", "n": "X"},
                                       {"_id": "a2", "n": "X"}])
            strict.process_batch("b", [{"_id": "b1", "n": "X"}])
            n_strict = len([r for r in strict.links_since(0)
                            if not r["_deleted"]])
        with loose.lock:
            loose.process_batch("c", [{"_id": "c1", "n": "X"},
                                      {"_id": "c2", "n": "X"}])
            loose.process_batch("d", [{"_id": "d1", "n": "X"}])
            n_loose = len([r for r in loose.links_since(0)
                           if not r["_deleted"]])
    finally:
        strict.close()
        loose.close()
    assert n_strict == 1   # one-to-one: b1 claims exactly one of a1/a2
    assert n_loose == 2    # many-to-many: both above-threshold pairs link
