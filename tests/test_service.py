"""HTTP surface tests: drive a real server on an ephemeral port."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from sesam_duke_microservice_tpu.core.config import parse_config
from sesam_duke_microservice_tpu.service.app import DukeApp, serve

CONFIG_XML = """
<DukeMicroService>
  <Deduplication name="people" link-database-type="in-memory">
    <duke>
      <schema>
        <threshold>0.8</threshold>
        <property><name>NAME</name>
          <comparator>levenshtein</comparator><low>0.1</low><high>0.95</high>
        </property>
        <property><name>EMAIL</name>
          <comparator>exact</comparator><low>0.2</low><high>0.95</high>
        </property>
      </schema>
      <data-source class="io.sesam.dukemicroservice.IncrementalDeduplicationDataSource">
        <param name="dataset-id" value="crm"/>
        <column name="name" property="NAME"
                cleaner="no.priv.garshol.duke.cleaners.LowerCaseNormalizeCleaner"/>
        <column name="email" property="EMAIL"/>
      </data-source>
      <data-source class="io.sesam.dukemicroservice.IncrementalDeduplicationDataSource">
        <param name="dataset-id" value="web"/>
        <column name="name" property="NAME"
                cleaner="no.priv.garshol.duke.cleaners.LowerCaseNormalizeCleaner"/>
        <column name="email" property="EMAIL"/>
      </data-source>
    </duke>
  </Deduplication>
  <RecordLinkage name="pairing" link-mode="one-to-one" link-database-type="in-memory">
    <duke>
      <schema>
        <threshold>0.7</threshold>
        <property><name>NAME</name>
          <comparator>levenshtein</comparator><low>0.1</low><high>0.95</high>
        </property>
      </schema>
      <group>
        <data-source class="io.sesam.dukemicroservice.IncrementalRecordLinkageDataSource">
          <param name="dataset-id" value="left"/>
          <column name="name" property="NAME"/>
        </data-source>
      </group>
      <group>
        <data-source class="io.sesam.dukemicroservice.IncrementalRecordLinkageDataSource">
          <param name="dataset-id" value="right"/>
          <column name="name" property="NAME"/>
        </data-source>
      </group>
    </duke>
  </RecordLinkage>
</DukeMicroService>
"""


@pytest.fixture(scope="module")
def server_url():
    # low MIN_RELEVANCE via the real env so config hot-reloads (which re-read
    # os.environ, like the reference's configureDatabase) keep the setting;
    # tiny test corpora legitimately score below the 0.9 default cut
    import os

    os.environ["MIN_RELEVANCE"] = "0.05"
    sc = parse_config(CONFIG_XML)
    app = DukeApp(sc, persistent=False)
    server = serve(app, port=0, host="127.0.0.1")
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    url = f"http://127.0.0.1:{server.server_address[1]}"
    yield url
    server.shutdown()
    del os.environ["MIN_RELEVANCE"]


class _NoRedirect(urllib.request.HTTPRedirectHandler):
    def redirect_request(self, *args, **kwargs):
        return None


_opener = urllib.request.build_opener(_NoRedirect)


def request(url, method="GET", body=None, headers=None, timeout=None):
    req = urllib.request.Request(url, data=body, method=method, headers=headers or {})
    try:
        with _opener.open(req, timeout=timeout) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def post_json(url, payload):
    return request(url, "POST", json.dumps(payload).encode(),
                   {"Content-Type": "application/json"})


def test_homepage_lists_endpoints(server_url):
    status, headers, body = request(server_url + "/")
    assert status == 200 and "text/html" in headers["Content-Type"]
    text = body.decode()
    assert "/deduplication/people/crm" in text
    assert "/recordlinkage/pairing/left" in text
    assert "configfile" in text


def test_get_config_verbatim(server_url):
    status, headers, body = request(server_url + "/config")
    assert status == 200
    assert headers["Content-Type"].startswith("application/xml")
    assert body.decode() == CONFIG_XML


def test_post_batch_and_feed(server_url):
    status, _, body = post_json(server_url + "/deduplication/people/crm", [
        {"_id": "1", "name": "Alan Turing", "email": "alan@blechley.uk"},
        {"_id": "2", "name": "Ada Lovelace", "email": "ada@analytical.uk"},
    ])
    assert status == 200
    assert body == b'{"success": true}'

    status, _, body = post_json(server_url + "/deduplication/people/web",
                                {"_id": "9", "name": "Alan Turing", "email": "alan@blechley.uk"})
    assert status == 200 and body == b'{"success": true}'

    status, headers, body = request(server_url + "/deduplication/people?since=0")
    assert status == 200
    rows = json.loads(body)
    assert len(rows) == 1
    assert {rows[0]["entity1"], rows[0]["entity2"]} == {"1", "9"}
    assert rows[0]["confidence"] > 0.8

    # incremental poll: nothing new after the returned timestamp
    ts = rows[0]["_updated"]
    status, _, body = request(server_url + f"/deduplication/people?since={ts}")
    assert json.loads(body) == []


def test_http_transform_single_and_array(server_url):
    post_json(server_url + "/deduplication/people/crm",
              [{"_id": "t1", "name": "Grace Hopper", "email": "g@navy.mil"}])
    # single entity in -> single object out (App.java:1196-1198)
    status, _, body = post_json(
        server_url + "/deduplication/people/web/httptransform",
        {"_id": "t9", "name": "Grace Hopper", "email": "g@navy.mil"},
    )
    assert status == 200
    obj = json.loads(body)
    assert isinstance(obj, dict)
    assert obj["_id"] == "t9"
    assert obj["duke_links"][0]["entityId"] == "t1"
    assert obj["duke_links"][0]["datasetId"] == "crm"

    # array in -> array out
    status, _, body = post_json(
        server_url + "/deduplication/people/web/httptransform",
        [{"_id": "t9", "name": "Grace Hopper", "email": "g@navy.mil"}],
    )
    assert isinstance(json.loads(body), list)

    # transform left no trace: the transformed entity is not in the feed
    status, _, body = request(server_url + "/deduplication/people?since=0")
    assert all("t9" not in json.dumps(r) for r in json.loads(body))


def test_recordlinkage_endpoints(server_url):
    post_json(server_url + "/recordlinkage/pairing/left",
              [{"_id": "L1", "name": "Katherine Johnson"}])
    post_json(server_url + "/recordlinkage/pairing/right",
              [{"_id": "R1", "name": "Katherine Johnson"}])
    status, _, body = request(server_url + "/recordlinkage/pairing")
    rows = json.loads(body)
    assert len(rows) == 1
    assert rows[0]["dataset1"] == "left" and rows[0]["dataset2"] == "right"


def test_validation_status_codes(server_url):
    # unknown workload on entity endpoint -> 404
    status, _, body = post_json(server_url + "/deduplication/nope/crm", [])
    assert status == 404 and b"Unknown deduplication 'nope'" in body
    # unknown dataset -> 404
    status, _, body = post_json(server_url + "/deduplication/people/nope", [])
    assert status == 404 and b"Unknown dataset-id 'nope'" in body
    # GET on POST-only endpoint with valid path -> 405
    status, _, body = request(server_url + "/deduplication/people/crm")
    assert status == 405 and b"only supports POST" in body
    status, _, _ = request(server_url + "/deduplication/people/crm/httptransform")
    assert status == 405
    # GET on POST-only endpoint with bogus name -> 404 (validation first)
    status, _, _ = request(server_url + "/deduplication/nope/crm")
    assert status == 404
    # unknown feed name -> 400
    status, _, _ = request(server_url + "/deduplication/nope")
    assert status == 400
    status, _, _ = request(server_url + "/recordlinkage/nope")
    assert status == 400
    # malformed JSON -> 400
    status, _, _ = request(server_url + "/deduplication/people/crm", "POST",
                           b"{not json", {"Content-Type": "application/json"})
    assert status == 400
    # bad since -> 400
    status, _, _ = request(server_url + "/deduplication/people?since=abc")
    assert status == 400
    # entity without _id -> 500 (reference: RuntimeException out of the handler)
    status, _, _ = post_json(server_url + "/deduplication/people/crm", [{"name": "x"}])
    assert status == 500


def test_feed_503_when_write_locked(server_url):
    import sesam_duke_microservice_tpu.service.app as app_module

    # grab the workload lock as a writer would, then poll the feed
    # find the app via a request for config? Instead reach through the server fixture:
    # the fixture's app object is bound to the handler class of this server.
    # Simpler: create a fresh app+server for this test.
    sc = parse_config(CONFIG_XML, env={})
    app = app_module.DukeApp(sc, persistent=False)
    server = app_module.serve(app, port=0, host="127.0.0.1")
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    url = f"http://127.0.0.1:{server.server_address[1]}"
    wl = app.deduplications["people"]
    old_timeout = app_module.READ_LOCK_TIMEOUT_SECONDS
    app_module.READ_LOCK_TIMEOUT_SECONDS = 0.05
    try:
        with wl.lock:
            status, _, body = request(url + "/deduplication/people")
            assert status == 503
            assert b"being written to" in body
    finally:
        app_module.READ_LOCK_TIMEOUT_SECONDS = old_timeout
        server.shutdown()


def test_config_upload_multipart_and_rollback(server_url):
    new_config = CONFIG_XML.replace('name="people"', 'name="people2"')
    boundary = "----testboundary42"
    part = (
        f"--{boundary}\r\n"
        'Content-Disposition: form-data; name="configfile"; filename="c.xml"\r\n'
        "Content-Type: application/xml\r\n\r\n"
        f"{new_config}\r\n"
        f"--{boundary}--\r\n"
    ).encode()
    status, headers, _ = request(
        server_url + "/config", "POST", part,
        {"Content-Type": f"multipart/form-data; boundary={boundary}"},
    )
    assert status == 302 and headers["Location"] == "/"

    # new workload active, old gone
    status, _, _ = post_json(server_url + "/deduplication/people2/crm", [])
    assert status == 200
    status, _, _ = post_json(server_url + "/deduplication/people/crm", [])
    assert status == 404
    # /config serves the new string verbatim
    _, _, body = request(server_url + "/config")
    assert body.decode() == new_config

    # invalid upload -> 400, old config stays active
    status, _, _ = request(server_url + "/config", "POST", b"<Bogus/>",
                           {"Content-Type": "application/xml"})
    assert status == 400
    status, _, _ = post_json(server_url + "/deduplication/people2/crm", [])
    assert status == 200

    # restore for other tests (raw-body convenience upload)
    status, _, _ = request(server_url + "/config", "POST", CONFIG_XML.encode(),
                           {"Content-Type": "application/xml"})
    assert status == 302


def test_deleted_entity_retraction_over_http(server_url):
    post_json(server_url + "/deduplication/people/crm",
              [{"_id": "d1", "name": "Edsger Dijkstra", "email": "e@tue.nl"}])
    post_json(server_url + "/deduplication/people/web",
              [{"_id": "d9", "name": "Edsger Dijkstra", "email": "e@tue.nl"}])
    _, _, body = request(server_url + "/deduplication/people?since=0")
    link_rows = [r for r in json.loads(body) if "d1" in r["_id"]]
    assert link_rows and link_rows[0]["_deleted"] is False

    post_json(server_url + "/deduplication/people/web",
              [{"_id": "d9", "_deleted": True, "name": "Edsger Dijkstra"}])
    _, _, body = request(server_url + "/deduplication/people?since=0")
    link_rows = [r for r in json.loads(body) if "d1" in r["_id"]]
    assert link_rows[0]["_deleted"] is True


def test_health_endpoint(server_url):
    status, _, body = request(f"{server_url}/health")
    assert status == 200
    assert json.loads(body) == {"status": "ok"}


def test_stats_endpoint(server_url):
    # ingest one batch so the counters move
    post_json(f"{server_url}/deduplication/people/crm",
              [{"_id": "st1", "name": "Stats Person", "email": "s@x.no"}])
    status, _, body = request(f"{server_url}/stats")
    assert status == 200
    payload = json.loads(body)
    assert payload["backend"] in ("host", "device", "ann")
    names = {(w["kind"], w["name"]) for w in payload["workloads"]}
    assert ("deduplication", "people") in names
    assert ("recordlinkage", "pairing") in names
    people = next(w for w in payload["workloads"]
                  if w["name"] == "people")
    assert people["records_indexed"] >= 1
    assert people["batches"] >= 1
    assert people["records_processed"] >= 1


def test_concurrent_posts_microbatch_and_all_succeed(server_url):
    """Concurrent small POSTs merge into workload microbatches; every
    request still gets its own success/error and all links land."""
    results = []
    lock = threading.Lock()

    def poster(i):
        status, _, body = post_json(
            f"{server_url}/deduplication/people/crm",
            [{"_id": f"mb{i}", "name": f"micro batch {i}",
              "email": f"mb{i}@x"},
             {"_id": f"mb{i}-dup", "name": f"micro batch {i}",
              "email": f"mb{i}@x"}],
        )
        with lock:
            results.append((status, body))

    threads = [threading.Thread(target=poster, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(s == 200 for s, _ in results), results
    assert all(json.loads(b)["success"] for _, b in results)
    _, _, feed = request(f"{server_url}/deduplication/people?since=0")
    ids = {row["_id"] for row in json.loads(feed)}
    for i in range(8):
        assert any(f"mb{i}-dup" in rid and f"mb{i}" in rid for rid in ids), \
            (i, ids)

    # a bad request merged with good ones fails alone
    statuses = []

    def post_one(payload):
        status, _, _ = post_json(
            f"{server_url}/deduplication/people/crm", payload)
        with lock:
            statuses.append(status)

    threads = [
        threading.Thread(target=post_one,
                         args=([{"_id": f"ok{i}", "name": f"fine {i}",
                                 "email": f"ok{i}@x"}],))
        for i in range(3)
    ] + [threading.Thread(target=post_one,
                          args=([{"name": "missing id"}],))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sorted(statuses) == [200, 200, 200, 500]


def test_stats_lock_free_under_concurrent_ingest(server_url):
    """/stats must neither stall behind nor crash against concurrent
    ingest (it reads O(1) live counters lock-free; the old implementation
    iterated the record map and could hit a mid-resize RuntimeError or
    block for the duration of a batch)."""
    stop = threading.Event()
    errors = []

    def poster():
        i = 0
        while not stop.is_set():
            # any transport failure must be recorded, not silently kill
            # the thread (a dead poster would leave /stats unexercised
            # under load and the test vacuously green)
            try:
                status, _, _ = post_json(
                    f"{server_url}/deduplication/people/web",
                    [{"_id": f"st{i}-{j}", "name": f"stats load {i} {j}",
                      "email": f"s{i}{j}@x"} for j in range(20)],
                )
            except Exception as e:
                errors.append(("post-error", repr(e)))
                break
            if status != 200:
                errors.append(("post", status))
            i += 1

    def poller():
        while not stop.is_set():
            # the timeout is the stall detector: a /stats that blocks
            # behind an ingest batch (the old behavior) fails here
            try:
                status, _, body = request(f"{server_url}/stats", timeout=10)
            except Exception as e:
                errors.append(("stats-stall", repr(e)))
                continue
            if status != 200:
                errors.append(("stats", status))
                continue
            payload = json.loads(body)
            for row in payload["workloads"]:
                if not isinstance(row["records_indexed"], int):
                    errors.append(("null-count", row))

    threads = [threading.Thread(target=poster) for _ in range(2)] + [
        threading.Thread(target=poller) for _ in range(2)
    ]
    for t in threads:
        t.start()
    time.sleep(2.0)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    assert not any(t.is_alive() for t in threads), "worker thread hung"
    assert not errors, errors[:5]


def test_device_reload_uses_corpus_snapshot(tmp_path, monkeypatch):
    """Hot reload must restore the new workloads' corpora from the
    snapshot saved under the quiesce locks, not re-extract features."""
    from sesam_duke_microservice_tpu.engine.device_matcher import DeviceIndex
    from sesam_duke_microservice_tpu.service.app import DukeApp

    xml = CONFIG_XML.replace(
        "<DukeMicroService>", f'<DukeMicroService dataFolder="{tmp_path}">'
    )
    monkeypatch.setenv("MIN_RELEVANCE", "0.05")
    app = DukeApp(parse_config(xml), backend="device", persistent=True)
    wl = app.deduplications["people"]
    with wl.lock:
        wl.process_batch("crm", [
            {"_id": f"r{i}", "name": f"acme {i}", "email": f"a{i}@x.no"}
            for i in range(10)
        ])
    assert wl.index.corpus.size == 10

    def boom(self, records):
        raise AssertionError("extraction ran during reload despite snapshot")

    monkeypatch.setattr(DeviceIndex, "_extract", boom)
    app.reload_from_string(xml)   # hot reload, same config
    wl2 = app.deduplications["people"]
    assert wl2 is not wl
    assert wl2.index.corpus.size == 10
    app.close()


def test_merged_conflicting_delete_upsert_is_serializable():
    """Round-2 advisor finding: req A (delete X, add Y) merged with req B
    (delete Y, add X) must end in a state matching a serial execution of
    the merged requests.  The merge splits at the delete/upsert conflict,
    so the outcome equals queue order A;B: X re-added live, Y deleted."""
    import os

    from sesam_duke_microservice_tpu.engine.workload import (
        _BatchRequest,
        build_workload,
    )

    saved = os.environ.get("MIN_RELEVANCE")
    os.environ["MIN_RELEVANCE"] = "0.05"
    try:
        sc = parse_config(CONFIG_XML)
    finally:
        if saved is None:
            os.environ.pop("MIN_RELEVANCE", None)
        else:
            os.environ["MIN_RELEVANCE"] = saved
    wl = build_workload(sc.deduplications["people"], sc, backend="host",
                        persistent=False)
    try:
        with wl.lock:
            wl.process_batch("crm", [
                {"_id": "x", "name": "xavier", "email": "x@a.no"},
                {"_id": "y", "name": "yvonne", "email": "y@a.no"},
            ])
        req_a = _BatchRequest("crm", [
            {"_id": "x", "_deleted": True},
            {"_id": "y", "name": "yvonne2", "email": "y@a.no"},
        ])
        req_b = _BatchRequest("crm", [
            {"_id": "y", "_deleted": True},
            {"_id": "x", "name": "xavier2", "email": "x@a.no"},
        ])
        with wl.lock:
            wl._run_merged([req_a, req_b])
        assert req_a.error is None and req_b.error is None
        assert req_a.event.is_set() and req_b.event.is_set()
        rx = wl.index.find_record_by_id("crm__x")
        ry = wl.index.find_record_by_id("crm__y")
        assert rx is not None and not rx.is_deleted()
        assert ry is not None and ry.is_deleted()
    finally:
        wl.close()


def test_merged_flush_skips_sync_stamp_after_partial_store_write(tmp_path):
    """Round-3 advisor finding: if one merged request's store put_many
    commits but its tombstone indexing then raises, the flush must NOT
    stamp the store content_hash as synced just because another request in
    the group succeeded — the stamp would claim the index applied rows it
    never saw, and the restart staleness guard would skip the replay that
    re-indexes the lost tombstone."""
    import os

    from sesam_duke_microservice_tpu.engine.workload import (
        _BatchRequest,
        build_workload,
    )

    saved = os.environ.get("MIN_RELEVANCE")
    os.environ["MIN_RELEVANCE"] = "0.05"
    try:
        sc = parse_config(CONFIG_XML.replace(
            "<DukeMicroService>", f'<DukeMicroService dataFolder="{tmp_path}">'
        ))
    finally:
        if saved is None:
            os.environ.pop("MIN_RELEVANCE", None)
        else:
            os.environ["MIN_RELEVANCE"] = saved
    wl = build_workload(sc.deduplications["people"], sc, backend="host",
                        persistent=True)
    try:
        with wl.lock:
            wl.process_batch("crm", [
                {"_id": "x", "name": "xavier", "email": "x@a.no"},
            ])
        # observe the actual stamp written to the index (the divergence
        # latch lives inside _mark_synced, so wrap below it)
        stamps = []
        wl.index.mark_store_synced = lambda h: stamps.append(h)

        # req_a: tombstone for x — put_many commits, then indexing raises
        real_index = wl.index.index

        def failing_index(record):
            if record.is_deleted():
                raise RuntimeError("tombstone indexing failed")
            return real_index(record)

        wl.index.index = failing_index
        req_a = _BatchRequest("crm", [{"_id": "x", "_deleted": True}])
        req_b = _BatchRequest("crm", [
            {"_id": "z", "name": "zelda", "email": "z@a.no"},
        ])
        with wl.lock:
            wl._run_merged([req_a, req_b])
        assert isinstance(req_a.error, RuntimeError)
        assert req_b.error is None and req_b.event.is_set()
        # the load-bearing assertion: no sync stamp for this flush, so a
        # restart replays the store and re-indexes the tombstone
        assert stamps == []
        # STICKY: a later clean flush must not stamp either — the store
        # hash now includes x's un-applied tombstone, so any later stamp
        # would mask the divergence and the restart would skip the replay
        wl.index.index = real_index
        req_c = _BatchRequest("crm", [
            {"_id": "w", "name": "willa", "email": "w@a.no"},
        ])
        with wl.lock:
            wl._run_merged([req_c])
        assert req_c.error is None
        assert stamps == []
        # same latch via the process_batch path on a fresh workload
        # (own data folder so the two stores don't interleave)
        sc2 = parse_config(CONFIG_XML.replace(
            "<DukeMicroService>",
            f'<DukeMicroService dataFolder="{tmp_path / "wl2"}">',
        ))
        wl2 = build_workload(sc2.deduplications["people"], sc2, backend="host",
                             persistent=True)
        try:
            stamps2 = []
            wl2.index.mark_store_synced = lambda h: stamps2.append(h)
            wl2.index.index = failing_index
            with wl2.lock:
                try:
                    wl2.process_batch("crm", [{"_id": "q", "_deleted": True}])
                except RuntimeError:
                    pass
            wl2.index.index = wl2.index.__class__.index.__get__(wl2.index)
            with wl2.lock:
                wl2.process_batch("crm", [
                    {"_id": "p", "name": "pat", "email": "p@a.no"},
                ])
            assert stamps2 == []
            assert wl2._store_dirty
        finally:
            wl2.close()
    finally:
        wl.close()


def test_oversized_post_answers_413(server_url, monkeypatch):
    """Bodies over MAX_REQUEST_BYTES are refused before being read into
    memory (the reference rides Jetty's request limits — App.java:649; the
    stdlib server needs an explicit cap)."""
    monkeypatch.setenv("MAX_REQUEST_BYTES", "1024")
    big = json.dumps([{"_id": "big", "name": "x" * 4096}]).encode()
    status, _, body = request(server_url + "/deduplication/people/crm", "POST",
                              big, {"Content-Type": "application/json"})
    assert status == 413 and b"MAX_REQUEST_BYTES" in body
    # under the limit still works
    ok = json.dumps([{"_id": "ok", "name": "fits"}]).encode()
    status, _, _ = request(server_url + "/deduplication/people/crm", "POST",
                           ok, {"Content-Type": "application/json"})
    assert status == 200


def test_feed_streams_in_pages_with_bounded_lock_hold():
    """VERDICT r2 #2: a ?since=0 poll over a million-link backlog must
    stream in pages, never holding the workload lock longer than ~100 ms
    and never materializing every row at once."""
    import os

    from sesam_duke_microservice_tpu.engine.workload import build_workload
    from sesam_duke_microservice_tpu.links.base import (
        Link,
        LinkKind,
        LinkStatus,
    )

    saved = os.environ.get("MIN_RELEVANCE")
    os.environ["MIN_RELEVANCE"] = "0.05"
    try:
        sc = parse_config(CONFIG_XML)
    finally:
        if saved is None:
            os.environ.pop("MIN_RELEVANCE", None)
        else:
            os.environ["MIN_RELEVANCE"] = saved
    app = DukeApp(sc, persistent=False)
    wl = app.deduplications["people"]
    # seed 1M links straight into the link DB (the feed path under test
    # is link fetch + row resolution, not matching)
    n_links = 1_000_000
    linkdb = wl.link_database
    base_ts = 1_700_000_000_000
    for i in range(n_links):
        linkdb.assert_link(Link(f"crm__a{i}", f"web__b{i}",
                                LinkStatus.INFERRED, LinkKind.DUPLICATE,
                                0.9, timestamp=base_ts + i))

    # instrument the workload lock to record hold durations
    real_lock = wl.lock
    holds = []

    class TimedLock:
        def acquire(self, timeout=None):
            ok = (real_lock.acquire(timeout=timeout)
                  if timeout is not None else real_lock.acquire())
            if ok:
                self._t0 = time.monotonic()
            return ok

        def release(self):
            holds.append(time.monotonic() - self._t0)
            real_lock.release()

        def __enter__(self):
            self.acquire()
            return self

        def __exit__(self, *exc):
            self.release()

    wl.lock = TimedLock()
    server = serve(app, port=0, host="127.0.0.1")
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    url = f"http://127.0.0.1:{server.server_address[1]}"
    import statistics

    def stream_once():
        """One full streamed poll; returns (rows, last_bytes)."""
        rows = 0
        last = b""
        tail = b""   # marker can straddle a read boundary
        marker = b'"_id"'
        with urllib.request.urlopen(url + "/deduplication/people?since=0",
                                    timeout=600) as resp:
            assert resp.headers.get("Content-Length") is None  # chunked
            while True:
                chunk = resp.read(1 << 20)
                if not chunk:
                    break
                window = tail + chunk
                rows += window.count(marker) - tail.count(marker)
                tail = window[-(len(marker) - 1):]
                last = chunk[-2:] if len(chunk) >= 2 else last + chunk
        return rows, last

    try:
        rows, last = stream_once()
        assert rows == n_links
        assert last.endswith(b"]")
        assert len(holds) >= n_links // 5000  # actually paged
        # the VERDICT target: pages hold the lock <100ms.  The timing is a
        # property of the code, not the host — on a loaded CI machine a
        # run can be entirely preemption noise, so retry the stream a
        # couple of times before declaring the bound violated (a true
        # full-materialization regression holds the lock for seconds on
        # EVERY attempt and still fails all three)
        for attempt in range(3):
            if max(holds) < 2.0 and statistics.median(holds) < 0.1:
                break
            holds.clear()
            rows, _ = stream_once()
            assert rows == n_links
        assert max(holds) < 2.0, f"lock held {max(holds):.3f}s"
        assert statistics.median(holds) < 0.1, (
            f"median page lock hold {statistics.median(holds):.3f}s"
        )
    finally:
        server.shutdown()
        app.close()


def test_feed_pages_do_not_skip_or_duplicate_ties(server_url):
    """Paging cursor is strictly-greater-than on timestamp; rows created
    with colliding timestamps (imported data) must neither drop nor
    duplicate across a page boundary."""
    import os

    from sesam_duke_microservice_tpu.links.base import (
        Link,
        LinkKind,
        LinkStatus,
    )
    from sesam_duke_microservice_tpu.links.memory import InMemoryLinkDatabase
    from sesam_duke_microservice_tpu.links.sqlite import SqliteLinkDatabase
    import tempfile

    ts = 1_600_000_000_000
    mem = InMemoryLinkDatabase()
    with tempfile.TemporaryDirectory() as tmp:
        dbs = [mem, SqliteLinkDatabase(os.path.join(tmp, "l.sqlite"))]
        for db in dbs:
            # 7 links share one timestamp; page size 3 forces tie extension
            for i in range(7):
                db.assert_link(Link(f"x{i}", f"y{i}", LinkStatus.INFERRED,
                                    LinkKind.DUPLICATE, 0.9, timestamp=ts))
            db.assert_link(Link("x9", "y9", LinkStatus.INFERRED,
                                LinkKind.DUPLICATE, 0.9, timestamp=ts + 5))
            seen = []
            cursor = 0
            while True:
                page = db.get_changes_page(cursor, 3)
                if not page:
                    break
                seen.extend((l.id1, l.id2) for l in page)
                cursor = page[-1].timestamp
            assert len(seen) == len(set(seen)) == 8


def test_feed_stream_aborts_on_mid_stream_workload_removal(monkeypatch):
    """A config reload that removes the workload mid-stream must truncate
    the chunked framing (protocol error at the client), never close the
    array cleanly — a clean ']' would make the partial feed look complete."""
    import http.client
    import os

    import sesam_duke_microservice_tpu.service.app as app_module
    from sesam_duke_microservice_tpu.links.base import (
        Link,
        LinkKind,
        LinkStatus,
    )

    monkeypatch.setenv("MIN_RELEVANCE", "0.05")
    monkeypatch.setenv("FEED_PAGE_SIZE", "10")
    sc = parse_config(CONFIG_XML)
    app = DukeApp(sc, persistent=False)
    wl = app.deduplications["people"]
    base_ts = 1_700_000_000_000
    for i in range(200):
        wl.link_database.assert_link(
            Link(f"crm__a{i}", f"web__b{i}", LinkStatus.INFERRED,
                 LinkKind.DUPLICATE, 0.9, timestamp=base_ts + i)
        )

    # remove the workload from the registry after the third page
    real_page = wl.links_page
    pages = []

    def hooked(since, limit):
        pages.append(since)
        if len(pages) == 3:
            app.deduplications = {}
        return real_page(since, limit)

    wl.links_page = hooked
    server = serve(app, port=0, host="127.0.0.1")
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        conn = http.client.HTTPConnection(
            "127.0.0.1", server.server_address[1], timeout=60
        )
        conn.request("GET", "/deduplication/people?since=0")
        resp = conn.getresponse()
        assert resp.status == 200
        with pytest.raises(
                (http.client.IncompleteRead, http.client.HTTPException,
                 ConnectionError)):
            body = resp.read()
            # some stacks surface truncation as a short read instead of
            # raising — a clean read must at least NOT be a complete array
            raise http.client.IncompleteRead(body) if not body.endswith(
                b"]") else AssertionError(f"clean close: ...{body[-20:]!r}")
    finally:
        server.shutdown()
        app.close()
