"""Device-resident certified finalization tests (ISSUE 12).

Three layers hold the dd pipeline sound:

  * the two-float arithmetic core (ops.dd) against the Python-f64 oracle,
    JITTED — the error-free transforms must survive XLA's algebraic
    simplifier (the barriers in ops.dd are what this pins);
  * the certified margin: for EVERY dd-certifiable comparator kind, the
    device dd logit of randomized near-threshold pairs must sit within
    ``certified_dd_margin`` of the host f64 oracle logit — the margin
    validity property the finalize verdict split rests on;
  * the engine split: with ``DUKE_DEVICE_FINALIZE`` on, event streams
    and link rows must be bit-identical to the off control and to the
    host-engine oracle, while certified rejects measurably skip host
    compares; the declared ambiguous residue must be a superset of any
    actual dd-vs-f64 disagreement (held by exact event equality plus the
    margin property above).
"""

import math
import random
import zlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sesam_duke_microservice_tpu.core import comparators as C
from sesam_duke_microservice_tpu.core.bayes import probability_logit
from sesam_duke_microservice_tpu.core.config import DukeSchema, MatchTunables
from sesam_duke_microservice_tpu.core.records import (
    ID_PROPERTY_NAME,
    Property,
    Record,
)
from sesam_duke_microservice_tpu.engine.device_matcher import (
    DeviceIndex,
    DeviceProcessor,
)
from sesam_duke_microservice_tpu.engine.finalize import (
    FinalizeExecutor,
    fallback_pair_logit,
)
from sesam_duke_microservice_tpu.engine.processor import Processor
from sesam_duke_microservice_tpu.ops import dd as D
from sesam_duke_microservice_tpu.ops import features as F
from sesam_duke_microservice_tpu.ops import scoring as S

from test_finalize import (
    BruteForceIndex,
    OrderedLog,
    dedup_schema,
    link_rows,
    make_record,
    random_records,
    run_device,
)


@pytest.fixture(autouse=True)
def _pin_device_finalize(monkeypatch):
    """This module asserts certified-path behavior, so it pins the knob
    ON (the CI DUKE_DEVICE_FINALIZE=0 leg runs the rest of the suite on
    the legacy path; the on/off differential here sets the env per arm
    explicitly, overriding this pin)."""
    monkeypatch.setenv("DUKE_DEVICE_FINALIZE", "1")


def _dd_from_f64(values):
    a = np.asarray(values, dtype=np.float64)
    hi = np.float32(a)
    lo = np.float32(a - hi.astype(np.float64))
    return jnp.asarray(hi), jnp.asarray(lo)


# -- the arithmetic core ------------------------------------------------------


class TestDdCore:
    def test_add_mul_div_match_f64_jitted(self):
        rng = random.Random(11)
        a = np.array([rng.uniform(-1e4, 1e4) for _ in range(512)])
        b = np.array([rng.uniform(0.1, 1e4) * rng.choice([-1, 1])
                      for _ in range(512)])
        ad, bd = _dd_from_f64(a), _dd_from_f64(b)
        # the represented inputs (dd carries ~49 bits of a/b)
        ra = D.to_f64(ad)
        rb = D.to_f64(bd)
        for op, want in (
            (D.add, ra + rb), (D.sub, ra - rb),
            (D.mul, ra * rb), (D.div, ra / rb),
        ):
            got = D.to_f64(jax.jit(op)(ad, bd))
            rel = np.abs(got - want) / np.maximum(np.abs(want), 1e-300)
            # DD_EPS is the budget the margin charges; the true per-op
            # error must sit far inside it
            assert rel.max() < D.DD_EPS / 4, op

    def test_jit_matches_eager(self):
        # the optimization barriers must keep the jitted error terms
        # alive — a simplified-away low word shows up as a jit/eager gap
        a = _dd_from_f64([1.0])
        b = _dd_from_f64([1.0 / 10.0])
        eager = D.to_f64(D.sub(a, b))
        jitted = D.to_f64(jax.jit(D.sub)(a, b))
        assert eager[0] == jitted[0]
        assert abs(jitted[0] - 0.9) < 1e-13  # a bare f32 would be ~2e-8 off

    def test_log_error_bound(self):
        rng = random.Random(7)
        xs = np.array([10.0 ** rng.uniform(-10, 10) for _ in range(2048)])
        xd = _dd_from_f64(xs)
        got = D.to_f64(jax.jit(D.log)(xd))
        want = np.log(D.to_f64(xd))
        err = np.abs(got - want)
        bound = D.LOG_ERR_ABS + D.DD_EPS * np.abs(want)
        assert (err < bound / 4).all()

    def test_from_int_exact(self):
        i = jnp.arange(0, 4096, dtype=jnp.int32)
        hi, lo = D.from_int(i)
        assert (np.asarray(hi) == np.arange(4096, dtype=np.float32)).all()
        assert (np.asarray(lo) == 0.0).all()

    def test_const_pair_reproduces_f64(self):
        for x in (0.9, 0.7, 1e-10, math.log(2.0), 0.3333333333333333):
            hi, lo = D.const_pair(x)
            assert abs((float(hi) + float(lo)) - x) <= abs(x) * 2.0 ** -47


# -- the certified margin -----------------------------------------------------


def _plan(schema, v=1):
    return F.SchemaFeatures.plan(schema, values_per_record=v)


class TestCertifiedDdMargin:
    def test_orders_of_magnitude_inside_f32(self):
        plan = _plan(dedup_schema())
        dd_m = S.certified_dd_margin(plan)
        f32_m = S.certified_f32_margin(plan)
        assert 0.0 < dd_m < f32_m / 1e5

    def test_finite_for_geo_schema(self):
        # geo makes the WHOLE-schema f32 margin infinite; the dd margin
        # covers only the certifiable properties (geo falls back to the
        # host per property), so it stays finite and usable
        geo = C.Geoposition()
        geo.max_distance = 1000.0
        schema = DukeSchema(
            threshold=0.8, maybe_threshold=None,
            properties=[
                Property(ID_PROPERTY_NAME, id_property=True),
                Property("name", C.Levenshtein(), 0.3, 0.9),
                Property("pos", geo, 0.4, 0.8),
            ],
            data_sources=[],
        )
        plan = _plan(schema)
        # geo's inf sim budget is capped at the clamp range per property,
        # but the whole-schema f32 band is hopeless either way...
        assert S.certified_f32_margin(plan) > 40.0
        # ...while the dd margin covers only the certifiable properties
        assert S.certified_dd_margin(plan) < 1e-6
        assert [p.name for p in S.dd_fallback_props(schema, plan)] == ["pos"]

    def test_sharp_high_widens_margin(self):
        mild = _plan(DukeSchema(
            threshold=0.8, maybe_threshold=None,
            properties=[Property(ID_PROPERTY_NAME, id_property=True),
                        Property("n", C.Levenshtein(), 0.3, 0.9)],
            data_sources=[]))
        sharp = _plan(DukeSchema(
            threshold=0.8, maybe_threshold=None,
            properties=[Property(ID_PROPERTY_NAME, id_property=True),
                        Property("n", C.Levenshtein(), 0.3, 0.9999999)],
            data_sources=[]))
        assert S.certified_dd_margin(sharp) > S.certified_dd_margin(mild)

    def test_bounds_bracket_threshold(self):
        schema = dedup_schema(threshold=0.8, maybe=0.6)
        plan = _plan(schema)
        t = probability_logit(0.6)
        assert S.dd_reject_bound(schema, plan) < t
        assert S.dd_event_bound(schema, plan) > t
        # the band is the margin, not the f32 insurance gap
        band = (S.dd_event_bound(schema, plan)
                - S.dd_reject_bound(schema, plan))
        assert band < 1e-6
        assert S.dd_gate_bound(schema, plan) >= S.dd_reject_bound(
            schema, plan)

    def test_jw_width_cap_gates_certifiability(self):
        spec = F.PropertyFeatureSpec(
            name="n", kind=F.CHARS, low=0.3, high=0.9,
            comparator=C.JaroWinkler(), max_chars=32)
        assert S.dd_certifiable_spec(spec)
        spec.max_chars = 512
        assert not S.dd_certifiable_spec(spec)

    def test_uncertifiable_kinds_fall_back_per_property(self):
        schema = dedup_schema()  # name lev, city exact, amount numeric
        plan = _plan(schema)
        assert {s.name for s in S.dd_plan_specs(plan)} == {"name", "city"}
        assert [p.name for p in S.dd_fallback_props(schema, plan)] == [
            "amount"]


# -- margin validity: dd vs the f64 oracle, every kind ------------------------


NOISE = "abcdefgh "


def _noisy(rng, base):
    if base and rng.random() < 0.7:
        pos = rng.randrange(len(base))
        base = base[:pos] + rng.choice(NOISE) + base[pos + 1:]
    return base


WORDS = ["acme corp", "acme corporation", "globex", "globex inc",
         "initech", "umbrella", "umbrela", "stark industries",
         "stark ind", "wayne enterprises"]
PHON = ["smith", "smyth", "johnson", "jonson", "garshol", "garshoel"]


def _qgram(formula):
    qg = C.QGram()
    qg.formula = formula
    return qg


KIND_CASES = [
    ("levenshtein", C.Levenshtein(), WORDS),
    ("jaro_winkler", C.JaroWinkler(), WORDS),
    ("qgram_overlap", _qgram("overlap"), WORDS),
    ("qgram_jaccard", _qgram("jaccard"), WORDS),
    ("qgram_dice", _qgram("dice"), WORDS),
    ("jaccard_tokens", C.JaccardIndex(), WORDS),
    ("dice_tokens", C.DiceCoefficient(), WORDS),
    ("exact", C.Exact(), WORDS),
    ("different", C.Different(), WORDS),
    ("soundex", C.Soundex(), PHON),
    ("metaphone", C.Metaphone(), PHON),
]


class TestDdOracleDifferential:
    @pytest.mark.parametrize("name,cmp,pool",
                             [(n, c, p) for n, c, p in KIND_CASES],
                             ids=[n for n, _, _ in KIND_CASES])
    def test_dd_logit_within_margin_of_oracle(self, name, cmp, pool):
        # near-threshold pairs: mutated copies of a small identity pool,
        # two value slots so the combo fold is exercised
        schema = DukeSchema(
            threshold=0.8, maybe_threshold=0.6,
            properties=[Property(ID_PROPERTY_NAME, id_property=True),
                        Property("p", cmp, 0.32, 0.91)],
            data_sources=[])
        plan = _plan(schema, v=2)
        (spec,) = plan.device_props
        assert S.dd_certifiable_spec(spec)
        # stable per-kind seed (str hash is salted per process — a salted
        # seed made this differential non-reproducible across runs)
        rng = random.Random(zlib.crc32(name.encode()))
        recs = []
        for i in range(24):
            r = Record()
            r.add_value(ID_PROPERTY_NAME, f"r{i}")
            r.add_value("p", _noisy(rng, rng.choice(pool)))
            if rng.random() < 0.5:
                r.add_value("p", _noisy(rng, rng.choice(pool)))
            recs.append(r)
        feats = F.extract_batch(plan, recs)
        n = len(recs)
        k = 6
        top = np.array([[rng.randrange(n) for _ in range(k)]
                        for _ in range(n)], np.int32)
        fn = S.build_dd_rescorer(plan, queries_from_rows=True,
                                 value_slots_cap=8)
        cfeats = {spec.name: {kk: jnp.asarray(v)
                              for kk, v in feats[spec.name].items()}}
        hi, lo, unsafe = fn({}, cfeats, jnp.arange(n, dtype=jnp.int32),
                            jnp.asarray(top))
        ddlog = (np.asarray(hi).astype(np.float64)
                 + np.asarray(lo).astype(np.float64))
        unsafe = np.asarray(unsafe)
        prop = schema.comparison_properties()[0]
        margin = S.certified_dd_margin(plan)
        checked = 0
        for qi in range(n):
            for kk in range(k):
                if unsafe[qi, kk]:
                    continue
                ci = int(top[qi, kk])
                vs1 = recs[qi].get_values("p")
                vs2 = recs[ci].get_values("p")
                best = 0.0
                for v1 in vs1:
                    for v2 in vs2:
                        p = prop.compare_probability(v1, v2)
                        if p > best:
                            best = p
                want = probability_logit(best)
                assert abs(ddlog[qi, kk] - want) <= margin, (
                    name, recs[qi].get_values("p"),
                    recs[ci].get_values("p"))
                checked += 1
        assert checked > n  # unsafe flags must not eat the fixture

    def test_jw_exact_boundary_pair_is_flagged_unsafe(self):
        """Regression: JW("abme corp", "gl bex") has j == 0.5 EXACTLY in
        exact arithmetic ((1/3 + 1/2 + 2/3)/3) — the host f64 chain
        rounds it to 0.5 (high map branch) while the dd chain rounded a
        hair below (low branch), a 1.17-logit verdict flip.  Such pairs
        must carry the branch-guard unsafe flag into the host residue,
        never a certified verdict."""
        cmp = C.JaroWinkler()
        assert cmp.compare("abme corp", "gl bex") == 0.5
        schema = DukeSchema(
            threshold=0.8, maybe_threshold=0.6,
            properties=[Property(ID_PROPERTY_NAME, id_property=True),
                        Property("p", cmp, 0.32, 0.91)],
            data_sources=[])
        plan = _plan(schema, v=2)
        r1 = make_record("a", p="abme corp")
        r2 = Record()
        r2.add_value(ID_PROPERTY_NAME, "b")
        r2.add_value("p", "starkfind")
        r2.add_value("p", "gl bex")
        feats = F.extract_batch(plan, [r1, r2])
        cf = {"p": {k: jnp.asarray(v) for k, v in feats["p"].items()}}
        fn = S.build_dd_rescorer(plan, queries_from_rows=True,
                                 value_slots_cap=8)
        hi, lo, unsafe = fn({}, cf, jnp.asarray([0], jnp.int32),
                            jnp.asarray([[1]], jnp.int32))
        assert bool(np.asarray(unsafe)[0, 0])


class TestPallasGatheredBranch:
    def test_dd_levenshtein_rides_gathered_myers_kernel(self, monkeypatch):
        """The dominant rescoring shape (single value slot, chars<=32,
        Levenshtein) must produce the SAME dd logits through the
        gathered Myers Pallas kernel (interpret mode on CPU) as through
        the flat XLA kernels — only the integer distance comes from the
        tile kernel, the dd ratio/map/logit run outside it."""
        from sesam_duke_microservice_tpu.ops import pallas_kernels as pk

        schema = DukeSchema(
            threshold=0.8, maybe_threshold=0.6,
            properties=[Property(ID_PROPERTY_NAME, id_property=True),
                        Property("name", C.Levenshtein(), 0.3, 0.9)],
            data_sources=[])
        plan = _plan(schema)
        assert plan.device_props[0].chars <= 32
        rng = random.Random(4)
        recs = []
        for i in range(12):
            r = Record()
            r.add_value(ID_PROPERTY_NAME, f"r{i}")
            r.add_value("name", _noisy(rng, rng.choice(WORDS)))
            recs.append(r)
        feats = F.extract_batch(plan, recs)
        cfeats = {"name": {k: jnp.asarray(v)
                           for k, v in feats["name"].items()}}
        n = len(recs)
        top = np.array([[rng.randrange(n) for _ in range(4)]
                        for _ in range(n)], np.int32)

        def run():
            fn = S.build_dd_rescorer(plan, queries_from_rows=True,
                                     value_slots_cap=8)
            hi, lo, uns = fn({}, cfeats, jnp.arange(n, dtype=jnp.int32),
                             jnp.asarray(top))
            return (np.asarray(hi).astype(np.float64)
                    + np.asarray(lo).astype(np.float64))

        flat = run()
        monkeypatch.setenv("DUKE_TPU_PALLAS", "1")  # interpret on CPU
        assert pk.pallas_enabled()
        tiled = run()
        # identical integer distances -> identical dd arithmetic
        np.testing.assert_array_equal(flat, tiled)


# -- truncation-safety mask ---------------------------------------------------


class TestTruncationResidue:
    def _one_pair(self, plan, r1, r2, value_slots_cap=8):
        feats = F.extract_batch(plan, [r1, r2])
        (spec,) = plan.device_props
        fn = S.build_dd_rescorer(plan, queries_from_rows=True,
                                 value_slots_cap=value_slots_cap)
        cfeats = {spec.name: {k: jnp.asarray(v)
                              for k, v in feats[spec.name].items()}}
        hi, lo, unsafe = fn({}, cfeats, jnp.asarray([0], jnp.int32),
                            jnp.asarray([[1]], jnp.int32))
        return bool(np.asarray(unsafe)[0, 0])

    def test_value_slot_saturation_flags_pair(self):
        schema = DukeSchema(
            threshold=0.8, maybe_threshold=None,
            properties=[Property(ID_PROPERTY_NAME, id_property=True),
                        Property("p", C.Exact(), 0.3, 0.9)],
            data_sources=[])
        plan = _plan(schema, v=2)
        a = make_record("a", p="x")
        b = make_record("b", p="y")
        assert not self._one_pair(plan, a, b, value_slots_cap=2)
        full = make_record("c")
        full.add_value("p", "x")
        full.add_value("p", "y")  # every slot valid at the cap
        assert self._one_pair(plan, a, full, value_slots_cap=2)
        # a higher cap means the auto-grown axis covered the data
        assert not self._one_pair(plan, a, full, value_slots_cap=8)

    def test_char_width_saturation_flags_pair(self):
        schema = DukeSchema(
            threshold=0.8, maybe_threshold=None,
            properties=[Property(ID_PROPERTY_NAME, id_property=True),
                        Property("p", C.Levenshtein(), 0.3, 0.9)],
            data_sources=[])
        plan = _plan(schema)
        width = plan.device_props[0].chars
        a = make_record("a", p="x" * (width - 1))
        b = make_record("b", p="y" * 4)
        assert not self._one_pair(plan, a, b)
        long = make_record("c", p="z" * (width + 10))  # truncated
        assert self._one_pair(plan, a, long)

    def test_gram_capacity_saturation_flags_pair(self):
        schema = DukeSchema(
            threshold=0.8, maybe_threshold=None,
            properties=[Property(ID_PROPERTY_NAME, id_property=True),
                        Property("p", _qgram("jaccard"), 0.3, 0.9)],
            data_sources=[])
        plan = _plan(schema)
        a = make_record("a", p="abcd")
        b = make_record("b", p="abce")
        assert not self._one_pair(plan, a, b)
        # > MAX_GRAMS distinct bigrams -> gram_count saturates
        import string
        long = make_record(
            "c", p="".join(rng_c + "x" for rng_c in string.ascii_letters))
        assert self._one_pair(plan, a, long)


# -- the engine split ---------------------------------------------------------


def hostprop_schema(threshold=0.8, maybe=0.6):
    """A schema with a host-only comparator (PersonName has no device
    kernel): the survivor filter widens by the optimistic host bound, so
    plenty of non-emitting survivors exist for dd to certify away."""
    return DukeSchema(
        threshold=threshold, maybe_threshold=maybe,
        properties=[
            Property(ID_PROPERTY_NAME, id_property=True),
            Property("name", C.Levenshtein(), 0.3, 0.9),
            Property("person", C.PersonName(), 0.4, 0.8),
        ],
        data_sources=[])


def _host_oracle_events(schema, records):
    index = BruteForceIndex()
    proc = Processor(schema, index)
    log = OrderedLog()
    proc.add_match_listener(log)
    proc.deduplicate(records)
    return log.events


def _records_with_person(n, seed):
    rng = random.Random(seed)
    names = ["ole olsen", "ola olsen", "kari nordmann", "k nordmann",
             "per hansen", "pär hansen"]
    out = []
    for i, r in enumerate(random_records(n, seed)):
        r.add_value("person", _noisy(rng, rng.choice(names)))
        out.append(r)
    return out


class TestDeviceFinalizeSplit:
    def test_on_off_events_and_links_bit_identical(self, tmp_path,
                                                   monkeypatch):
        from sesam_duke_microservice_tpu.links import SqliteLinkDatabase

        monkeypatch.delenv("DUKE_FINALIZE_THREADS", raising=False)
        schema = hostprop_schema()
        records = _records_with_person(40, seed=13)
        results = {}
        for flag in ("1", "0"):
            monkeypatch.setenv("DUKE_DEVICE_FINALIZE", flag)
            db = SqliteLinkDatabase(str(tmp_path / f"links{flag}.sqlite"))
            log, proc = run_device(schema, [records], linkdb=db)
            assert proc.finalizer.device is (flag == "1")
            results[flag] = (log.events, link_rows(db), proc.stats)
            db.close()
        on_events, on_links, on_stats = results["1"]
        off_events, off_links, off_stats = results["0"]
        assert on_events == off_events
        assert on_links == off_links
        assert on_events, "fixture produced no events"
        # the on arm certifiably rejected survivors on device...
        assert on_stats.pairs_device_certified > 0
        # ...and the off arm pinned the legacy path exactly
        assert off_stats.pairs_device_certified == 0
        assert (on_stats.pairs_rescored + on_stats.pairs_device_certified
                == off_stats.pairs_rescored)

    @pytest.mark.parametrize("schema_fn,records_fn", [
        (lambda: dedup_schema(threshold=0.92, maybe=0.6),
         lambda: random_records(40, seed=7)),
        (hostprop_schema,
         lambda: _records_with_person(40, seed=3)),
        # sharp high: the f32 certified margin exceeds the 1e-3 filter
        # insurance (empty decisive band) — dd must stay exact
        (lambda: DukeSchema(
            threshold=0.92, maybe_threshold=0.6,
            properties=[Property(ID_PROPERTY_NAME, id_property=True),
                        Property("name", C.Levenshtein(), 0.01, 0.99),
                        Property("city", C.Exact(), 0.3, 0.995)],
            data_sources=[]),
         lambda: random_records(40, seed=5)),
        # degenerate low=0/high=1: the f32 margin explodes entirely
        (lambda: DukeSchema(
            threshold=0.8, maybe_threshold=None,
            properties=[Property(ID_PROPERTY_NAME, id_property=True),
                        Property("name", C.Levenshtein(), 0.0, 1.0),
                        Property("city", C.Exact(), 0.4, 0.8)],
            data_sources=[]),
         lambda: random_records(35, seed=9)),
    ], ids=["mixed-numeric", "host-prop", "sharp", "degenerate"])
    def test_events_equal_host_oracle(self, schema_fn, records_fn):
        schema = schema_fn()
        records = records_fn()
        host_events = _host_oracle_events(schema, records)
        dev_log, proc = run_device(schema, [records])
        assert proc.finalizer.device
        assert set(dev_log.events) == set(host_events)

    def test_residue_superset_of_disagreements(self):
        """Certified skips must be provably below every threshold: the
        oracle probability of every dd-certified reject must classify
        reject — i.e. any pair the oracle WOULD emit is in the rescored
        (residue/event) set, never certified away."""
        schema = hostprop_schema()
        records = _records_with_person(30, seed=21)
        emitted_by_oracle = {
            (e[1], e[2]) for e in _host_oracle_events(schema, records)
            if e[0] != "none"}
        dev_log, proc = run_device(schema, [records])
        assert proc.stats.pairs_device_certified > 0
        emitted_by_device = {
            (e[1], e[2]) for e in dev_log.events if e[0] != "none"}
        assert emitted_by_oracle == emitted_by_device

    def test_certified_rejects_skip_the_host_compare(self, monkeypatch):
        """Certified rejects must never reach ``Processor.compare`` —
        the host cost of the certified path is the per-property fallback
        fold plus the event tail, not O(survivors) full compares."""
        monkeypatch.setenv("DUKE_DECISION_RECORD", "0")
        # the DUKE_NUMCHECK=1 CI leg shadow-compares certified rejects
        # BY DESIGN — this test pins the production (sanitizer-off)
        # compare-skipping contract
        monkeypatch.setenv("DUKE_NUMCHECK", "0")
        schema = hostprop_schema()
        records = _records_with_person(30, seed=17)
        index = DeviceIndex(schema, tunables=MatchTunables())
        proc = DeviceProcessor(schema, index)
        proc.add_match_listener(OrderedLog())
        compares = []
        orig = proc.compare
        proc.compare = lambda r1, r2: (
            compares.append(r2.record_id) or orig(r1, r2))
        proc.deduplicate(records)
        assert proc.stats.pairs_device_certified > 0
        # every compare belongs to a rescored pair (memo hits may make
        # compares fewer, never more); certified rejects never compare
        assert 0 < len(compares) <= proc.stats.pairs_rescored

    def test_kind_residue_counted_for_uncertifiable_schema(self):
        numeric = C.Numeric()
        schema = DukeSchema(
            threshold=0.8, maybe_threshold=None,
            properties=[Property(ID_PROPERTY_NAME, id_property=True),
                        Property("amount", numeric, 0.3, 0.9)],
            data_sources=[])
        records = [make_record(f"r{i}", amount=str(100 + i % 7))
                   for i in range(20)]
        log, proc = run_device(schema, [records])
        # no dd-certifiable property: every rescored survivor is kind
        # residue
        assert proc.stats.pairs_device_certified == 0
        assert proc.stats.dd_residue_kind == proc.stats.pairs_rescored
        assert proc.stats.dd_residue_kind > 0

    def test_confidence_memo_is_bit_exact_and_hits(self):
        schema = dedup_schema()
        index = DeviceIndex(schema, tunables=MatchTunables())
        proc = DeviceProcessor(schema, index)
        log = OrderedLog()
        proc.add_match_listener(log)
        # identical duplicate groups: every group pair shares one digest
        # pair, so compare runs once per (identity, identity)
        records = []
        for i in range(24):
            records.append(make_record(
                f"r{i}", name=f"acme corp {i % 4}", city="oslo",
                amount="100"))
        compares = []
        orig_compare = proc.compare

        def counting_compare(r1, r2):
            compares.append((r1.record_id, r2.record_id))
            return orig_compare(r1, r2)

        proc.compare = counting_compare
        proc.deduplicate(records)
        match_events = [e for e in log.events if e[0] == "match"]
        assert match_events
        # far fewer compares than emitted matches: the memo served the
        # repeats, and every served confidence is the bit-identical f64
        # (held by the on/off differential above)
        assert len(compares) < len(match_events)
        assert len(proc.finalizer._conf_cache) > 0

    def test_use_env_false_pins_legacy(self, monkeypatch):
        monkeypatch.setenv("DUKE_DEVICE_FINALIZE", "1")
        assert FinalizeExecutor(1, use_env=False).device is False
        assert FinalizeExecutor(1).device is True
        monkeypatch.setenv("DUKE_DEVICE_FINALIZE", "0")
        assert FinalizeExecutor(1).device is False
        assert FinalizeExecutor(1, device=True, use_env=False).device


# -- fallback property fold ---------------------------------------------------


def test_fallback_pair_logit_matches_compare_restriction():
    schema = dedup_schema()
    plan = _plan(schema)
    fallback = S.dd_fallback_props(schema, plan)
    assert [p.name for p in fallback] == ["amount"]
    r1 = make_record("a", name="acme", city="oslo", amount="120")
    r2 = make_record("b", name="acme", city="oslo", amount="100")
    got = fallback_pair_logit(fallback, r1, r2)
    prop = next(p for p in schema.comparison_properties()
                if p.name == "amount")
    want = probability_logit(prop.compare_probability("120", "100"))
    assert got == want
    # missing values contribute nothing, exactly like Processor.compare
    r3 = make_record("c", name="x", city="y")
    assert fallback_pair_logit(fallback, r1, r3) == 0.0


# -- explain provenance -------------------------------------------------------


class TestExplainDdProvenance:
    def _index(self, schema, records):
        index = DeviceIndex(schema, tunables=MatchTunables())
        for r in records:
            index.index(r)
        index.commit()
        return index

    def test_decided_path_and_dd_fields(self):
        from sesam_duke_microservice_tpu.engine import explain as X

        schema = dedup_schema()
        a = make_record("a", name="acme corp", city="oslo", amount="100")
        b = make_record("b", name="acme corp", city="oslo", amount="100")
        z = make_record("z", name="zzzzz", city="bergen", amount="7")
        index = self._index(schema, [a, b, z])
        out = X.device_breakdown(index, a, b)
        assert out["device_finalize_enabled"] is True
        assert out["decided_path"] in (
            "device_certified", "host_rescore", "band_skip")
        assert set(out["dd_certifiable"]) == {"name", "city"}
        assert out["dd_fallback_properties"] == ["amount"]
        if out["decided_path"] != "band_skip":
            assert "dd_logit" in out
            assert out["certified_dd_margin"] > 0
            # identical records: far above every bound -> certified event
            assert out["decided_path"] == "device_certified"
        far = X.device_breakdown(index, a, z)
        assert far["decided_path"] == "band_skip"

    def test_disabled_device_finalize_reports_host_path(self):
        from sesam_duke_microservice_tpu.engine import explain as X

        schema = dedup_schema()
        a = make_record("a", name="acme corp", city="oslo", amount="100")
        b = make_record("b", name="acme corp", city="oslo", amount="100")
        index = self._index(schema, [a, b])
        out = X.device_breakdown(index, a, b, device=False)
        assert out["device_finalize_enabled"] is False
        assert out["decided_path"] in ("host_rescore", "band_skip")
        assert "dd_logit" not in out
