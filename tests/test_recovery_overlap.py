"""Recovery-overlapped serving (ISSUE 15).

Invariants held here:

  * feed reads during an in-flight journal replay return a MONOTONIC
    PREFIX of the recovered feed — whole batches only, each page
    extending the last, no duplicate;
  * writes (and the ingest-path reads that feed them) fence until the
    replay completes; ``/readyz`` flips ``write_ready`` only then, while
    the HTTP layer serves reads at 200 ``recovering`` behind the
    ``X-Recovering`` staleness header;
  * the ``crash_at`` chaos differential converges bit-identical with
    overlap explicitly enabled AND explicitly disabled;
  * a replay failure latches the wrapper (writes refused, never
    silently served over a store missing acked batches).
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from sesam_duke_microservice_tpu.links import journal as journal_mod
from sesam_duke_microservice_tpu.links.base import Link, LinkKind, LinkStatus
from sesam_duke_microservice_tpu.links.journal import (
    LinkJournal,
    recovery_in_progress,
)
from sesam_duke_microservice_tpu.links.replica import encode_link
from sesam_duke_microservice_tpu.links.sqlite import SqliteLinkDatabase
from sesam_duke_microservice_tpu.links.write_behind import (
    WriteBehindLinkDatabase,
)
from sesam_duke_microservice_tpu.service.app import serve
from sesam_duke_microservice_tpu.utils import faults

from test_crash_recovery import (
    CHILD,
    N_BATCHES,
    _durable_app,
    _ingest,
)


@pytest.fixture(autouse=True)
def _no_env_faults():
    faults.configure("")
    yield
    faults.configure(None)


def L(id1, id2, conf=0.9, ts=None):
    return Link(id1, id2, LinkStatus.INFERRED, LinkKind.DUPLICATE, conf, ts)


def _backlog_journal(path, n, t0=1_000_000):
    """A journal holding ``n`` acked-but-unapplied single-link batches
    (sequential timestamps so the recovered feed order is known)."""
    j = LinkJournal(str(path), sync="none")
    for i in range(n):
        j.append_batch([encode_link(L(f"a{i}", f"b{i}", 0.9, t0 + i))])
    j.close()
    return str(path)


class GatedSqlite(SqliteLinkDatabase):
    """Inner store whose REPLAY writes step through a semaphore while
    gating is on — the test releases one permit per replay chunk,
    making the overlap window deterministic.  Only the recovery thread
    gates: once the fence lifts, a post-replay write's background flush
    lands here too and must not steal a replay chunk's permit."""

    def __init__(self, path):
        super().__init__(path)
        self.gate = threading.Semaphore(0)
        self.gating = True

    def assert_links(self, links):
        if self.gating and threading.current_thread().name == "link-recovery":
            assert self.gate.acquire(timeout=60)
        super().assert_links(links)


class TestLinksLayerOverlap:
    def test_monotonic_prefix_reads_and_write_fence(self, tmp_path):
        n = 600  # 3 replay chunks of 256
        jpath = _backlog_journal(tmp_path / "links.journal", n)
        inner = GatedSqlite(str(tmp_path / "links.sqlite"))
        journal = LinkJournal(jpath)
        assert journal.pending_batches == n
        db = WriteBehindLinkDatabase(inner, journal=journal)
        try:
            db.recover_async(scope="overlap-test")
            assert db.recovering is True
            assert journal_mod.recovery_active("overlap-test") is True

            # writes fence: a committer blocks until replay completes
            wrote = threading.Event()

            def writer():
                db.assert_link(L("new1", "new2", 0.5))
                db.commit()
                wrote.set()

            wt = threading.Thread(target=writer, daemon=True)
            wt.start()
            time.sleep(0.1)
            assert not wrote.is_set()  # fenced

            # reads serve the growing committed prefix, whole chunks only
            expected = [(f"a{i}", f"b{i}") for i in range(n)]
            seen = []
            released = 0
            while released < 3:
                inner.gate.release()
                released += 1
                want = min(released * 256, n)
                deadline = time.monotonic() + 30
                while time.monotonic() < deadline:
                    rows = db.get_changes_page(0, n + 10)
                    pairs = [(lk.id1, lk.id2) for lk in rows
                             if lk.id1.startswith("a")]
                    if len(pairs) >= want:
                        break
                    # never a torn chunk: only whole-chunk sizes appear
                    assert len(pairs) in (0, 256, 512), pairs
                    time.sleep(0.01)
                # monotonic prefix of the recovered feed, no dup/reorder
                assert pairs == expected[:len(pairs)]
                assert pairs[:len(seen)] == seen
                seen = pairs

            inner.gating = False
            assert wrote.wait(timeout=30)  # fence lifted with the replay
            assert db.recovering is False
            assert journal_mod.recovery_active("overlap-test") is False
            db.drain()
            rows = db.get_all_links()
            pairs = {(lk.id1, lk.id2) for lk in rows}
            assert pairs == set(expected) | {("new1", "new2")}
            # the post-fence write journaled AFTER the replayed head
            assert journal.applied_watermark() >= n
        finally:
            db.close()

    def test_publisher_wrapper_sees_through_recovering(self, tmp_path):
        """The HA leader's PublishingLinkDatabase must expose the
        wrapped write-behind DB's recovering flag — the HTTP write
        fence probes the OUTERMOST wrapper, and a False there would
        turn the fast 503 back into a handler thread blocked for the
        whole replay window."""
        from sesam_duke_microservice_tpu.links.replica import (
            PublishingLinkDatabase,
        )

        n = 300
        jpath = _backlog_journal(tmp_path / "links.journal", n)
        inner = GatedSqlite(str(tmp_path / "links.sqlite"))
        journal = LinkJournal(jpath)
        db = WriteBehindLinkDatabase(inner, journal=journal)
        pub = PublishingLinkDatabase(db, lambda seq, rows: None)
        try:
            assert pub.recovering is False
            db.recover_async(scope="pub-fence")
            assert pub.recovering is True  # sees through to the wrapper
            inner.gating = False
            for _ in range(2):
                inner.gate.release()
            deadline = time.monotonic() + 30
            while db.recovering and time.monotonic() < deadline:
                time.sleep(0.01)
            assert pub.recovering is False
        finally:
            db.close()

    def test_no_backlog_recovers_inline(self, tmp_path):
        inner = SqliteLinkDatabase(str(tmp_path / "links.sqlite"))
        journal = LinkJournal(str(tmp_path / "links.journal"))
        db = WriteBehindLinkDatabase(inner, journal=journal)
        try:
            db.recover_async(scope="inline")
            assert db.recovering is False
            assert db._recovery_thread is None
            db.assert_link(L("x", "y"))
            db.commit()
            db.drain()
        finally:
            db.close()

    def test_replay_failure_latches_writes(self, tmp_path):
        jpath = _backlog_journal(tmp_path / "links.journal", 3)

        class Broken(SqliteLinkDatabase):
            def assert_links(self, links):
                raise OSError("disk gone")

        inner = Broken(str(tmp_path / "links.sqlite"))
        journal = LinkJournal(jpath)
        db = WriteBehindLinkDatabase(inner, journal=journal)
        try:
            db.recover_async(scope="latch")
            deadline = time.monotonic() + 30
            while db.recovering and time.monotonic() < deadline:
                time.sleep(0.01)
            assert db.recovering is False
            with pytest.raises(RuntimeError, match="flush failed"):
                db.assert_link(L("x", "y"))
        finally:
            db.close()

    def test_ingest_path_reads_fence(self, tmp_path):
        """get_links_for_ids feeds retraction decisions: a prefix read
        there could miss a link replay was about to restore, so it
        fences exactly like a write."""
        n = 300
        jpath = _backlog_journal(tmp_path / "links.journal", n)
        inner = GatedSqlite(str(tmp_path / "links.sqlite"))
        journal = LinkJournal(jpath)
        db = WriteBehindLinkDatabase(inner, journal=journal)
        try:
            db.recover_async(scope="fence-reads")
            got = []
            done = threading.Event()

            def reader():
                got.extend(db.get_links_for_ids(["a0"]))
                done.set()

            t = threading.Thread(target=reader, daemon=True)
            t.start()
            time.sleep(0.1)
            assert not done.is_set()  # fenced during replay
            inner.gating = False
            for _ in range(3):
                inner.gate.release()
            assert done.wait(timeout=30)
            assert [(lk.id1, lk.id2) for lk in got] == [("a0", "b0")]
        finally:
            db.close()


class TestHttpSurface:
    def _gated_app(self, tmp_path, monkeypatch):
        """A durable app whose startup replay is gated: ingest + close
        seeds store/link rows, then synthetic re-assert batches are
        journaled (confidence bumped, fresh timestamps) so the restart
        has a real backlog of feed-visible work."""
        # pin overlap mode: under the CI DUKE_RECOVERY_OVERLAP=0 leg the
        # gated recover would otherwise block the whole app build
        monkeypatch.setenv("DUKE_RECOVERY_OVERLAP", "1")
        app1 = _durable_app(tmp_path)
        _ingest(app1)
        wl = app1.deduplications["people"]
        links = wl.link_database.get_all_links()
        assert links
        app1.close()

        folder = tmp_path / "deduplication" / "people"
        j = LinkJournal(str(folder / "linkdatabase.journal"), sync="none")
        now = int(time.time() * 1000)
        for i, lk in enumerate(links):
            bumped = Link(lk.id1, lk.id2, lk.status, lk.kind,
                          0.4242, now + i)
            j.append_batch([encode_link(bumped)])
        j.close()

        gate = threading.Event()
        orig = WriteBehindLinkDatabase.recover

        def gated(self):
            assert gate.wait(timeout=120)
            return orig(self)

        monkeypatch.setattr(WriteBehindLinkDatabase, "recover", gated)
        app2 = _durable_app(tmp_path)
        monkeypatch.setattr(WriteBehindLinkDatabase, "recover", orig)
        return app2, gate, links

    def test_readyz_write_split_and_staleness_header(
            self, tmp_path, monkeypatch):
        app, gate, links = self._gated_app(tmp_path, monkeypatch)
        server = serve(app, port=0, host="127.0.0.1")
        threading.Thread(target=server.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            assert app.recovering() is True

            # /readyz: 200 "recovering" — reads are routable; writes not
            with urllib.request.urlopen(base + "/readyz", timeout=30) as r:
                assert r.status == 200
                assert r.headers.get("X-Recovering") == "1"
                body = json.loads(r.read())
            assert body["status"] == "recovering"
            assert body["checks"]["write_ready"] is False
            assert body["checks"]["recovery_complete"] is False

            # writes: fast 503 with Retry-After, not a hung handler
            req = urllib.request.Request(
                base + "/deduplication/people/crm", method="POST",
                data=json.dumps(
                    [{"_id": "z9", "name": "zeta person"}]).encode(),
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(req, timeout=30)
            assert exc.value.code == 503
            assert exc.value.headers.get("Retry-After") == "1"
            assert exc.value.headers.get("X-Recovering") == "1"
            assert "replaying its link journal" in exc.value.read().decode()

            # reads: feed serves the pre-replay prefix behind the header
            with urllib.request.urlopen(
                    base + "/deduplication/people?since=0", timeout=30) as r:
                assert r.status == 200
                assert r.headers.get("X-Recovering") == "1"
                feed_before = json.loads(r.read())
            assert {row["_id"] for row in feed_before}  # old links serve
            assert all(row["confidence"] != 0.4242 for row in feed_before)

            # /stats and /metrics carry the staleness header too
            for path in ("/stats", "/metrics"):
                with urllib.request.urlopen(base + path, timeout=30) as r:
                    assert r.status == 200
                    assert r.headers.get("X-Recovering") == "1"

            gate.set()
            deadline = time.monotonic() + 60
            while app.recovering() and time.monotonic() < deadline:
                time.sleep(0.02)
            assert app.recovering() is False

            with urllib.request.urlopen(base + "/readyz", timeout=30) as r:
                assert r.status == 200
                assert r.headers.get("X-Recovering") is None
                body = json.loads(r.read())
            assert body["status"] == "ready"
            assert body["checks"]["write_ready"] is True

            # the replayed batches are now feed-visible (bumped conf)...
            with urllib.request.urlopen(
                    base + "/deduplication/people?since=0", timeout=30) as r:
                feed_after = json.loads(r.read())
            assert any(row["confidence"] == 0.4242 for row in feed_after)
            # ...and writes 200
            with urllib.request.urlopen(req, timeout=60) as r:
                assert r.status == 200
        finally:
            server.shutdown()
            app.close()

    def test_serial_mode_keeps_whole_app_503(self, tmp_path, monkeypatch):
        """DUKE_RECOVERY_OVERLAP=0 pins the legacy contract: /readyz is
        503 for the entire recovery window."""
        monkeypatch.setenv("DUKE_RECOVERY_OVERLAP", "0")
        app = _durable_app(tmp_path)
        server = serve(app, port=0, host="127.0.0.1")
        threading.Thread(target=server.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            with recovery_in_progress():
                with pytest.raises(urllib.error.HTTPError) as exc:
                    urllib.request.urlopen(base + "/readyz", timeout=30)
                assert exc.value.code == 503
                assert json.loads(exc.value.read())["status"] == "recovering"
        finally:
            server.shutdown()
            app.close()


# -- chaos differential, overlap on AND off ----------------------------------


def _run_child_env(data, *, overlap, fault="", start=0, dump=False,
                   close=False):
    env = dict(os.environ)
    env["DUKE_FAULTS"] = fault
    env["DUKE_JOURNAL"] = "1"
    env["DUKE_RECOVERY_OVERLAP"] = overlap
    env.pop("DUKE_FLUSH_RETRIES", None)
    cmd = [sys.executable, CHILD, "--data", str(data),
           "--backend", "host", "--start", str(start),
           "--batches", str(N_BATCHES), "--linger", "0.0"]
    if dump:
        cmd.append("--dump")
    if close:
        cmd.append("--close")
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=180,
                          env=env)
    acks = [int(line.split()[1]) for line in proc.stdout.splitlines()
            if line.startswith("ACK ")]
    dumps = [json.loads(line[5:]) for line in proc.stdout.splitlines()
             if line.startswith("DUMP ")]
    return proc, acks, (dumps[0] if dumps else None)


@pytest.mark.parametrize("overlap", ["1", "0"])
def test_crash_differential_converges_with_overlap(tmp_path, overlap):
    """The ISSUE 10 kill differential at the journaled-but-unapplied
    site, with DUKE_RECOVERY_OVERLAP explicitly pinned on/off: the
    restarted child resends the unacked suffix (its writes fence behind
    the in-flight replay in the overlap arm) and must converge to link
    rows + feed identical to an uncrashed control."""
    ctrl, _, control = _run_child_env(tmp_path / "ctrl", overlap=overlap,
                                      dump=True, close=True)
    assert ctrl.returncode == 0, ctrl.stderr
    data = tmp_path / "w"
    proc, acks, _ = _run_child_env(data, overlap=overlap,
                                   fault="crash_at=pre_flush:4")
    assert proc.returncode == -signal.SIGKILL
    resume = (max(acks) + 1) if acks else 0
    proc2, _, dump = _run_child_env(data, overlap=overlap, start=resume,
                                    dump=True, close=True)
    assert proc2.returncode == 0, proc2.stderr
    assert dump["links"] == control["links"]
    assert dump["feed"] == control["feed"]
    assert dump["journal_pending"] == 0
    assert dump["replayed"] >= 1
