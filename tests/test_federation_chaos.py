"""Kill-at-every-migration-site chaos differential (ISSUE 14 acceptance).

For each ``crash_at`` site inside the live range migration, a child
federation process is SIGKILLed mid-migration and restarted; the
restarted process resumes the migration (the ``Federation`` constructor
finishes an interrupted one before serving) and must converge to
federated link rows and a merged ``?since=`` feed bit-identical
(timestamps normalized) to an UNMIGRATED control — zero lost, zero
duplicated links — with the moved range owned by the target and thawed.

Mirrors the PR 10 kill-differential methodology (a real process, a real
SIGKILL, a real restart); runs inside every tier-1 leg and verbosely in
the dedicated ``federation-chaos`` CI job.
"""

import json
import os
import signal
import subprocess
import sys

import pytest

from sesam_duke_microservice_tpu.utils import faults

CHILD = os.path.join(os.path.dirname(__file__), "federation_chaos_child.py")
N_BATCHES = 6


@pytest.fixture(autouse=True)
def _no_env_faults():
    # mask any CI-leg DUKE_FAULTS spec; children get an explicit spec
    faults.configure("")
    yield
    faults.configure(None)


def _run_child(data, *, fault="", migrate=False, dump=False, start=0):
    env = dict(os.environ)
    env["DUKE_FAULTS"] = fault
    env["DUKE_JOURNAL"] = "1"
    env.pop("DUKE_FLUSH_RETRIES", None)
    cmd = [sys.executable, CHILD, "--data", str(data),
           "--batches", str(N_BATCHES), "--start", str(start)]
    if migrate:
        cmd.append("--migrate")
    if dump:
        cmd.append("--dump")
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300,
                          env=env)
    acks = [int(line.split()[1]) for line in proc.stdout.splitlines()
            if line.startswith("ACK ")]
    dumps = [json.loads(line[5:]) for line in proc.stdout.splitlines()
             if line.startswith("DUMP ")]
    return proc, acks, (dumps[0] if dumps else None)


@pytest.fixture(scope="module")
def control_dump(tmp_path_factory):
    """The unmigrated control: same ingest, no migration ever."""
    proc, acks, dump = _run_child(tmp_path_factory.mktemp("ctrl") / "f",
                                  dump=True)
    assert proc.returncode == 0, proc.stderr
    assert acks == list(range(N_BATCHES)) and dump["links"], proc.stdout
    assert dump["owner"] == 0
    return dump


def _assert_differential(dump, control):
    assert dump["links"] == control["links"]
    assert dump["feed"] == control["feed"]
    assert dump["owner"] == 1  # the resumed migration really completed
    assert dump["frozen"] is False
    assert dump["migrations"]["resumed"] >= 1


def _assert_timeline(dump, *, resumed, full=True):
    """Migration timeline completeness (ISSUE 16): the newest retained
    /debug/migrations entry shows the run's own driver pass — every
    phase in order with non-negative durations.  A crash that landed
    after the persisted cutover leaves only the drain to redo."""
    tl = dump["timelines"][0]
    assert tl["resumed"] is resumed
    assert tl["outcome"] == "completed"
    phases = [p["phase"] for p in tl["phases"]]
    if full:
        assert phases == ["freeze", "snapshot", "replay", "cutover",
                          "drain"]
        snap = tl["phases"][1]
        assert snap["records"] >= 1 and snap["record_bytes"] > 0
        assert tl["phases"][0]["epoch"] < tl["phases"][3]["epoch"]
    else:
        assert phases == ["drain"]
    for p in tl["phases"]:
        assert p["duration_ms"] >= 0 and p["start_unix"] > 0


MIGRATION_SITES = ["pre_freeze", "post_snapshot", "mid_replay",
                   "pre_cutover", "post_cutover"]


@pytest.mark.parametrize("site", MIGRATION_SITES)
def test_migration_kill_differential(site, control_dump, tmp_path):
    """SIGKILL at the site mid-migration; the restarted federation
    resumes and converges to the unmigrated control's rows and feed."""
    data = tmp_path / "f"
    proc, acks, _ = _run_child(data, fault=f"crash_at={site}:1",
                               migrate=True)
    assert proc.returncode == -signal.SIGKILL, (
        f"child survived the {site} kill site: rc={proc.returncode}\n"
        f"{proc.stdout}\n{proc.stderr}")
    assert acks == list(range(N_BATCHES))  # died migrating, post-ingest

    # restart: the constructor resumes the interrupted migration before
    # serving; every batch was acked so the client resends nothing, and
    # the explicit --migrate reports already_owned
    proc2, _, dump = _run_child(data, migrate=True, dump=True,
                                start=N_BATCHES)
    assert proc2.returncode == 0, proc2.stderr
    _assert_differential(dump, control_dump)
    # the restarted process's ring holds exactly the constructor's
    # resume pass (the explicit re-migrate reports already_owned and
    # never enters the driver); post_cutover resumes are drain-only
    assert len(dump["timelines"]) == 1
    _assert_timeline(dump, resumed=True, full=(site != "post_cutover"))


def test_clean_migration_matches_control(control_dump, tmp_path):
    """No kill: one uninterrupted live migration, same differential."""
    data = tmp_path / "f"
    proc, acks, dump = _run_child(data, migrate=True, dump=True)
    assert proc.returncode == 0, proc.stderr
    assert acks == list(range(N_BATCHES))
    assert dump["links"] == control_dump["links"]
    assert dump["feed"] == control_dump["feed"]
    assert dump["owner"] == 1 and dump["frozen"] is False
    assert dump["migrations"]["completed"] == 1
    assert dump["migrations"]["resumed"] == 0
    assert len(dump["timelines"]) == 1
    _assert_timeline(dump, resumed=False)


def test_double_kill_still_converges(control_dump, tmp_path):
    """Two successive kills (one mid-copy, one mid-cutover-resume) —
    resume is idempotent under repeated interruption."""
    data = tmp_path / "f"
    proc, _, _ = _run_child(data, fault="crash_at=post_snapshot:1",
                            migrate=True)
    assert proc.returncode == -signal.SIGKILL
    # the RESUME itself is killed at its cutover boundary this time
    proc2, _, _ = _run_child(data, fault="crash_at=pre_cutover:1",
                             migrate=True, start=N_BATCHES)
    assert proc2.returncode == -signal.SIGKILL, proc2.stdout + proc2.stderr
    proc3, _, dump = _run_child(data, migrate=True, dump=True,
                                start=N_BATCHES)
    assert proc3.returncode == 0, proc3.stderr
    assert dump["links"] == control_dump["links"]
    assert dump["feed"] == control_dump["feed"]
    assert dump["owner"] == 1 and dump["frozen"] is False
    assert dump["migrations"]["resumed"] >= 1
