"""Child process for the two-process DCN smoke test (test_multihost.py).

Each process: join the jax.distributed job over the localhost coordinator
(DCN path), build the global corpus mesh spanning BOTH processes' devices,
and run (a) a psum/all_gather collective and (b) the real sharded corpus
scorer (parallel.sharded.build_sharded_scorer) over a corpus whose record
axis shards across the two processes — the cross-host layout
parallel/multihost.py documents.

Usage: dcn_smoke_child.py <process_id> <coordinator_host:port>
"""

import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=2"
).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    process_id = int(sys.argv[1])
    coordinator = sys.argv[2]

    import numpy as np

    from sesam_duke_microservice_tpu.parallel import multihost

    assert multihost.initialize(
        coordinator_address=coordinator, num_processes=2,
        process_id=process_id,
    ), "initialize() must report distributed"
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 4, jax.device_count()

    mesh = multihost.global_corpus_mesh()
    assert mesh.size == 4

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from sesam_duke_microservice_tpu.parallel.sharded import SHARD_AXIS

    # (a) collective smoke: psum + all_gather over the global mesh — the
    # same collectives the corpus merge uses, here crossing the process
    # boundary (DCN)
    sharding = NamedSharding(mesh, P(SHARD_AXIS))

    def local_block(index):
        # global array (4, 8): row d holds value d
        start = index[0].start or 0
        rows = np.arange(start, start + 1, dtype=np.float32)
        return np.broadcast_to(rows[:, None], (1, 8)).copy()

    arr = jax.make_array_from_callback((4, 8), sharding, local_block)

    repl = NamedSharding(mesh, P())

    @jax.jit
    def collect(x):
        # constraint-driven collectives (the corpus merge recipe): a
        # replicated shard-axis reduction lowers to the psum, a
        # replicated constraint on the sharded array to the all_gather —
        # here both cross the process boundary (DCN)
        total = jax.lax.with_sharding_constraint(x.sum(axis=0), repl)
        gathered = jax.lax.with_sharding_constraint(x, repl)
        return total, gathered

    total, gathered = collect(arr)
    # replicated outputs are addressable on every process
    local_total = np.asarray(total)
    assert float(local_total[0]) == 0.0 + 1.0 + 2.0 + 3.0, local_total
    assert np.asarray(gathered).shape == (4, 8)

    # (b) the real sharded scorer over a cross-process record axis
    from sesam_duke_microservice_tpu.core import comparators as C
    from sesam_duke_microservice_tpu.core.config import DukeSchema
    from sesam_duke_microservice_tpu.core.records import (
        ID_PROPERTY_NAME,
        Property,
        Record,
    )
    from sesam_duke_microservice_tpu.ops import features as F
    from sesam_duke_microservice_tpu.parallel.sharded import (
        build_sharded_scorer,
    )

    schema = DukeSchema(
        threshold=0.8, maybe_threshold=None,
        properties=[
            Property(ID_PROPERTY_NAME, id_property=True),
            Property("name", C.Levenshtein(), 0.2, 0.9),
        ],
        data_sources=[],
    )
    plan = F.SchemaFeatures.plan(schema)

    chunk, top_k, n_queries = 4, 4, 4
    n_corpus = mesh.size * chunk  # one chunk per shard
    records = []
    for i in range(n_corpus):
        r = Record()
        r.add_value(ID_PROPERTY_NAME, f"ds__{i}")
        r.add_value("name", f"name{i % 5}")
        records.append(r)
    feats = F.extract_batch(plan, records)
    qfeats = F.extract_batch(plan, records[:n_queries])

    def place(arr, fill=0):
        spec = P(SHARD_AXIS, *([None] * (arr.ndim - 1)))
        sh = NamedSharding(mesh, spec)
        local = n_corpus // mesh.size

        def cb(index):
            start = index[0].start or 0
            return arr[start:start + local]

        return jax.make_array_from_callback(arr.shape, sh, cb)

    sfeats = {
        prop: {name: place(a) for name, a in tensors.items()}
        for prop, tensors in feats.items()
    }
    svalid = place(np.ones((n_corpus,), dtype=bool))
    sdeleted = place(np.zeros((n_corpus,), dtype=bool))
    sgroup = place(np.full((n_corpus,), -1, dtype=np.int32))

    scorer = build_sharded_scorer(plan, mesh, chunk=chunk, top_k=top_k)
    qf = {p: {k: jnp.asarray(a) for k, a in t.items()}
          for p, t in qfeats.items()}
    top_logit, top_index, count = scorer(
        qf, sfeats, svalid, sdeleted, sgroup,
        jnp.full((n_queries,), -2, jnp.int32),
        jnp.arange(n_queries, dtype=jnp.int32),
        jnp.float32(-100.0),
    )
    ti = np.asarray(top_index)  # replicated output: gatherable everywhere
    assert ti.shape == (n_queries, top_k)
    # every query's exact-duplicate rows live i%5 apart — the top-K must
    # surface rows from BOTH processes' shards (global row ids >= 8 live
    # on process 1)
    assert (ti >= 8).any(), ti
    for qi in range(n_queries):
        assert qi not in ti[qi], "self-pair leaked"

    print(f"DCN_OK process={jax.process_index()} devices={jax.device_count()}")


if __name__ == "__main__":
    main()
