"""Unit tests for the host comparator oracles (core.comparators)."""

import math

import pytest

from sesam_duke_microservice_tpu.core import comparators as C


def test_levenshtein_distance_basic():
    assert C.levenshtein_distance("kitten", "sitting") == 3
    assert C.levenshtein_distance("", "abc") == 3
    assert C.levenshtein_distance("abc", "abc") == 0
    assert C.levenshtein_distance("abc", "axc") == 1


def test_levenshtein_compare_semantics():
    lev = C.Levenshtein()
    assert lev.compare("oslo", "oslo") == 1.0
    # one edit over min length 4 -> 0.75
    assert lev.compare("oslo", "osla") == pytest.approx(0.75)
    # length ratio early-exit: sim could never reach 0.5
    assert lev.compare("ab", "abcdefgh") == 0.0
    assert lev.compare("", "abc") == 0.0
    # capped at min length: never negative
    assert 0.0 <= lev.compare("abcd", "wxyz") <= 1.0


def test_jaro_winkler_known_values():
    jw = C.JaroWinkler()
    assert jw.compare("martha", "marhta") == pytest.approx(0.9611, abs=1e-3)
    assert jw.compare("dixon", "dicksonx") == pytest.approx(0.8133, abs=1e-3)
    assert jw.compare("same", "same") == 1.0
    assert jw.compare("abc", "xyz") == 0.0


def test_jaro_winkler_prefix_boost():
    jw = C.JaroWinkler()
    # shared prefix should score above plain jaro
    j = C._jaro("prefixes", "prefixed")
    assert jw.compare("prefixes", "prefixed") > j


def test_qgram_formulas():
    q = C.QGram()
    assert q.compare("abcd", "abcd") == 1.0
    # qgrams(abcd)={ab,bc,cd}, qgrams(abcx)={ab,bc,cx}: common=2, overlap=2/3
    assert q.compare("abcd", "abcx") == pytest.approx(2 / 3)
    q.formula = "jaccard"
    assert q.compare("abcd", "abcx") == pytest.approx(2 / 4)
    q.formula = "dice"
    assert q.compare("abcd", "abcx") == pytest.approx(4 / 6)


def test_numeric_comparator():
    num = C.Numeric()
    num.set_param("min-ratio", "0.7")
    assert num.compare("100", "100") == 1.0
    assert num.compare("80", "100") == pytest.approx(0.8)
    assert num.compare("60", "100") == 0.0  # below min-ratio
    assert num.compare("abc", "100") == 0.5  # non-numeric is neutral
    assert num.compare("-5", "5") == 0.0


def test_exact_and_different():
    assert C.Exact().compare("a", "a") == 1.0
    assert C.Exact().compare("a", "b") == 0.0
    assert C.Different().compare("a", "a") == 0.0
    assert C.Different().compare("a", "b") == 1.0


def test_token_set_comparators():
    assert C.JaccardIndex().compare("a b c", "a b d") == pytest.approx(2 / 4)
    assert C.DiceCoefficient().compare("a b c", "a b d") == pytest.approx(4 / 6)
    assert C.JaccardIndex().compare("x", "") == 0.0


def test_person_name():
    pn = C.PersonName()
    assert pn.compare("john smith", "john smith") == 1.0
    assert pn.compare("john smith", "smith john") == pytest.approx(0.95)
    assert pn.compare("j smith", "john smith") > 0.7
    assert pn.compare("john smith", "jane doe") < 0.5


def test_soundex():
    assert C.soundex("Robert") == "R163"
    assert C.soundex("Rupert") == "R163"
    assert C.soundex("Ashcraft") == "A261"
    s = C.Soundex()
    assert s.compare("Robert", "Rupert") == 0.9
    assert s.compare("Robert", "Robert") == 1.0


def test_metaphone_and_norphone():
    assert C.metaphone("Smith") == C.metaphone("Smyth")
    m = C.Metaphone()
    assert m.compare("Smith", "Smyth") == 0.9
    n = C.Norphone()
    assert n.compare("Kristian", "Christian") == 0.9


def test_geoposition():
    geo = C.Geoposition()
    geo.set_param("max-distance", "1000")
    assert geo.compare("59.91,10.75", "59.91,10.75") == 1.0
    # ~111m per 0.001 deg latitude
    sim = geo.compare("59.910,10.75", "59.911,10.75")
    assert 0.85 < sim < 0.95
    assert geo.compare("59.91,10.75", "60.91,10.75") == 0.0
    assert geo.compare("garbage", "59.91,10.75") == 0.5


def test_longest_common_substring():
    lcs = C.LongestCommonSubstring()
    assert lcs.compare("abcdef", "abcdef") == 1.0
    assert lcs.compare("abcdef", "abcxyz") == pytest.approx(0.5)
    assert lcs.compare("abc", "xyz") == 0.0


def test_weighted_levenshtein():
    wl = C.WeightedLevenshtein()
    # digit edits cost more than letter edits
    letters = wl.compare("abcdef", "abcdeg")
    digits = wl.compare("123456", "123457")
    assert digits < letters


def test_registry_java_names():
    for name in (
        "no.priv.garshol.duke.comparators.Levenshtein",
        "no.priv.garshol.duke.comparators.JaroWinkler",
        "no.priv.garshol.duke.comparators.QGramComparator",
        "no.priv.garshol.duke.comparators.NumericComparator",
        "no.priv.garshol.duke.comparators.ExactComparator",
    ):
        comp = C.make_comparator(name)
        assert 0.0 <= comp.compare("abc", "abd") <= 1.0
    with pytest.raises(KeyError):
        C.make_comparator("no.such.Comparator")


def test_set_param_unknown_raises():
    with pytest.raises(KeyError):
        C.Numeric().set_param("no-such-param", "1")


def test_comparators_long_unicode_values():
    """Probe: 200-char unicode values through every registered comparator
    class must return a finite [0, 1] similarity without raising."""
    import math

    from sesam_duke_microservice_tpu.core.comparators import (
        _REGISTRY,
        Comparator,
    )

    v1 = ("åßñ漢字œø" * 40)[:200]
    v2 = ("åßñ漢字œzx" * 40)[:200] + "!"
    seen = set()
    for cls in _REGISTRY.values():
        if cls in seen or not issubclass(cls, Comparator):
            continue
        seen.add(cls)
        cmp = cls()
        sim = cmp.compare(v1, v2)
        assert isinstance(sim, float) and math.isfinite(sim), cls.__name__
        assert -1e-9 <= sim <= 1.0 + 1e-9, (cls.__name__, sim)
        if not cls.__name__.startswith("Different"):
            # string comparators: identity -> 1.0; numeric/geo on
            # unparseable text -> neutral 0.5 (Duke semantics)
            assert cmp.compare(v1, v1) >= 0.5 - 1e-9, cls.__name__
