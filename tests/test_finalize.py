"""Finalization-subsystem tests (parallel host finalization, decisive-band
pruning, write-behind link persist — ISSUE 3).

Determinism contract: the listener event SEQUENCE (not just the set) and
the link-database contents must be identical across any
``DUKE_FINALIZE_THREADS`` — workers only compute, the coordinator emits in
strict query order.  Decisive-band pruning must be invisible in the event
stream: a differential run against the host oracle holds it to the same
events the serial exact path produces.
"""

import random
import threading

import numpy as np
import pytest

from sesam_duke_microservice_tpu.core import comparators as C
from sesam_duke_microservice_tpu.core.config import DukeSchema, MatchTunables
from sesam_duke_microservice_tpu.core.records import (
    DELETED_PROPERTY_NAME,
    GROUP_NO_PROPERTY_NAME,
    ID_PROPERTY_NAME,
    Property,
    Record,
)
from sesam_duke_microservice_tpu.engine.device_matcher import (
    DeviceIndex,
    DeviceProcessor,
    _BlockResult,
)
from sesam_duke_microservice_tpu.engine.finalize import FinalizeExecutor
from sesam_duke_microservice_tpu.engine.listeners import (
    LinkMatchListener,
    MatchListener,
)
from sesam_duke_microservice_tpu.engine.processor import Processor
from sesam_duke_microservice_tpu.index.base import CandidateIndex
from sesam_duke_microservice_tpu.links import (
    InMemoryLinkDatabase,
    Link,
    LinkKind,
    LinkStatus,
    SqliteLinkDatabase,
    WriteBehindLinkDatabase,
)


def dedup_schema(threshold=0.8, maybe=0.6):
    numeric = C.Numeric()
    numeric.min_ratio = 0.5
    return DukeSchema(
        threshold=threshold,
        maybe_threshold=maybe,
        properties=[
            Property(ID_PROPERTY_NAME, id_property=True),
            Property("name", C.Levenshtein(), 0.3, 0.9),
            Property("city", C.Exact(), 0.4, 0.8),
            Property("amount", numeric, 0.4, 0.7),
        ],
        data_sources=[],
    )


def make_record(rid, **props):
    r = Record()
    r.add_value(ID_PROPERTY_NAME, rid)
    for k, v in props.items():
        r.add_value(k, v)
    return r


NAMES = [
    "acme corp", "acme corporation", "globex", "globex inc", "initech",
    "initech llc", "umbrella", "umbrela", "stark industries", "stark ind",
]
CITIES = ["oslo", "bergen", "trondheim"]


def random_records(n, seed, prefix="r"):
    rng = random.Random(seed)
    records = []
    for i in range(n):
        base = rng.choice(NAMES)
        if rng.random() < 0.4:
            pos = rng.randrange(len(base))
            base = base[:pos] + rng.choice("abcdefgh") + base[pos + 1:]
        records.append(make_record(
            f"{prefix}{i}",
            name=base,
            city=rng.choice(CITIES),
            amount=str(rng.choice([100, 200, 200, 300, 1000])),
        ))
    return records


class OrderedLog(MatchListener):
    """Full ordered event tape — sequence equality is the contract."""

    def __init__(self):
        self.events = []

    def matches(self, r1, r2, confidence):
        self.events.append(
            ("match", r1.record_id, r2.record_id, round(confidence, 9)))

    def matches_perhaps(self, r1, r2, confidence):
        self.events.append(
            ("maybe", r1.record_id, r2.record_id, round(confidence, 9)))

    def no_match_for(self, record):
        self.events.append(("none", record.record_id))


class BruteForceIndex(CandidateIndex):
    """Total-recall host oracle index (as in test_device_matcher)."""

    def __init__(self):
        self.records = {}
        self.indexing_disabled = False

    def index(self, record):
        if not self.indexing_disabled:
            self.records[record.record_id] = record

    def commit(self):
        pass

    def find_record_by_id(self, record_id):
        return self.records.get(record_id)

    def find_candidate_matches(self, record, group_filtering=False):
        group = record.get_value(GROUP_NO_PROPERTY_NAME)
        out = []
        for r in self.records.values():
            if r.get_value(DELETED_PROPERTY_NAME) == "true":
                continue
            if group_filtering and r.get_value(GROUP_NO_PROPERTY_NAME) == group:
                continue
            out.append(r)
        return out

    def delete(self, record):
        self.records.pop(record.record_id, None)

    def set_indexing_disabled(self, disabled):
        self.indexing_disabled = disabled


def run_device(schema, batches, *, threads=1, linkdb=None):
    index = DeviceIndex(schema, tunables=MatchTunables())
    proc = DeviceProcessor(schema, index, threads=threads)
    log = OrderedLog()
    proc.add_match_listener(log)
    if linkdb is not None:
        proc.add_match_listener(LinkMatchListener(linkdb))
    for batch in batches:
        proc.deduplicate(batch)
    return log, proc


def link_rows(db):
    return sorted(
        (l.id1, l.id2, l.status.value, l.kind.value, round(l.confidence, 9))
        for l in db.get_all_links()
    )


class TestThreadDeterminism:
    def test_event_sequence_and_links_identical_across_thread_counts(
            self, tmp_path, monkeypatch):
        # CI runs the whole suite under DUKE_FINALIZE_THREADS=4; this test
        # sweeps explicit counts, so the env override must not apply
        monkeypatch.delenv("DUKE_FINALIZE_THREADS", raising=False)
        schema = dedup_schema()
        b1 = random_records(30, seed=11)
        b2 = random_records(20, seed=12, prefix="s")
        results = {}
        for threads in (1, 4, 8):
            db = SqliteLinkDatabase(str(tmp_path / f"links{threads}.sqlite"))
            log, proc = run_device(schema, [b1, b2], threads=threads,
                                   linkdb=db)
            assert proc.finalizer.threads == threads
            results[threads] = (log.events, link_rows(db))
            db.close()
        base_events, base_links = results[1]
        assert base_events, "fixture produced no events"
        for threads in (4, 8):
            events, links = results[threads]
            assert events == base_events, f"threads={threads} event drift"
            assert links == base_links, f"threads={threads} link drift"

    def test_env_knob_overrides_ctor(self, monkeypatch):
        monkeypatch.setenv("DUKE_FINALIZE_THREADS", "6")
        assert FinalizeExecutor(1).threads == 6
        monkeypatch.delenv("DUKE_FINALIZE_THREADS")
        assert FinalizeExecutor(3).threads == 3
        # benchmark baselines pin against the env
        monkeypatch.setenv("DUKE_FINALIZE_THREADS", "6")
        assert FinalizeExecutor(1, use_env=False).threads == 1


class TestDecisiveBand:
    def test_differential_vs_host_oracle(self):
        # decisive-band pruning (on by default) must emit exactly the
        # host engine's events on the fixture corpora
        schema = dedup_schema(threshold=0.92, maybe=0.6)
        records = random_records(40, seed=7)
        host_index = BruteForceIndex()
        host = Processor(schema, host_index)
        host_log = OrderedLog()
        host.add_match_listener(host_log)
        host.deduplicate(records)

        dev_log, proc = run_device(schema, [records])
        assert proc.finalizer.decisive is True
        assert set(dev_log.events) == set(host_log.events)

    def test_flag_off_same_events(self, monkeypatch):
        schema = dedup_schema()
        records = random_records(35, seed=3)
        on_log, on_proc = run_device(schema, [records])
        monkeypatch.setenv("DUKE_DECISIVE_BAND", "0")
        off_log, off_proc = run_device(schema, [records])
        assert off_proc.finalizer.decisive is False
        assert on_log.events == off_log.events
        # with the band off every survivor is rescored
        assert off_proc.stats.pairs_skipped == 0
        assert (off_proc.stats.pairs_rescored
                >= on_proc.stats.pairs_rescored)

    def test_prune_bound_inside_device_filter(self):
        # the device-side survivor filter must retain everything the
        # certified prune bound would emit: prune_logit >= min_logit
        from sesam_duke_microservice_tpu.ops import scoring as S

        schema = dedup_schema()
        index = DeviceIndex(schema, tunables=MatchTunables())
        prune = S.decisive_prune_logit(schema, index.plan)
        min_logit = index.scorer_cache._min_logit()
        assert prune >= min_logit
        assert S.certified_f32_margin(index.plan) < 1e-3

    def test_degenerate_schema_disables_band_not_filter(self):
        # low=0.0 / high=1.0 blows the certified margin up; the device
        # filter must keep its fixed 1e-3 margin (still filtering) while
        # the decisive band collapses to empty (prune below the filter)
        from sesam_duke_microservice_tpu.ops import scoring as S

        schema = DukeSchema(
            threshold=0.8, maybe_threshold=None,
            properties=[
                Property(ID_PROPERTY_NAME, id_property=True),
                Property("name", C.Levenshtein(), 0.0, 1.0),
                Property("city", C.Exact(), 0.4, 0.8),
            ],
            data_sources=[],
        )
        index = DeviceIndex(schema, tunables=MatchTunables())
        min_logit = index.scorer_cache._min_logit()
        expected = S.emit_bound_logit(schema, index.plan, 1e-3)
        assert min_logit == pytest.approx(expected)
        assert min_logit > -10  # the filter still filters
        prune = S.decisive_prune_logit(schema, index.plan)
        assert prune < min_logit  # empty band: nothing ever skipped

    def test_band_skips_without_compare(self):
        # a survivor at or below the certified bound must be dropped
        # WITHOUT a host compare call; one above it must be rescored
        from sesam_duke_microservice_tpu.ops import scoring as S

        schema = dedup_schema()
        index = DeviceIndex(schema, tunables=MatchTunables())
        a = make_record("a", name="acme corp", city="oslo", amount="100")
        b = make_record("b", name="acme corp", city="oslo", amount="100")
        index.index(a)
        index.index(b)
        index.commit()

        prune = S.decisive_prune_logit(schema, index.plan)
        row_b = index.id_to_row["b"]
        compared = []

        class Proc:
            database = index
            compare = staticmethod(
                lambda r1, r2: compared.append((r1.record_id, r2.record_id))
                or 0.99
            )

        Proc.schema = schema
        ex = FinalizeExecutor(1)
        assert ex.decisive

        def result_at(logit):
            return _BlockResult(
                np.array([[logit]], np.float32),
                np.array([[row_b]], np.int32),
                prune - 100.0,  # survivors() filter far below the band
            )

        (out,) = ex.finalize_block(Proc, [a], result_at(prune - 1e-6))
        assert (out.skipped, out.rescored) == (1, 0)
        assert compared == []

        (out,) = ex.finalize_block(Proc, [a], result_at(prune + 1e-3))
        assert (out.skipped, out.rescored) == (0, 1)
        assert compared == [("a", "b")]
        assert out.events and out.events[0][0] == "matches"


class TestWriteBehind:
    def L(self, id1, id2, conf=0.9, status=LinkStatus.INFERRED, ts=None):
        return Link(id1, id2, status, LinkKind.DUPLICATE, conf, ts)

    def test_reads_drain_pending_writes(self):
        db = WriteBehindLinkDatabase(InMemoryLinkDatabase())
        db.assert_link(self.L("a", "b", ts=100))
        db.commit()  # enqueued, possibly not yet applied
        assert [l.key() for l in db.get_all_links()] == [("a", "b")]
        # an UNcommitted buffered write must also be visible to readers
        db.assert_link(self.L("c", "d", ts=200))
        assert len(db.get_changes_since(0)) == 2
        assert db.count() == 2
        db.close()

    def test_batch_is_one_inner_transaction(self):
        calls = []

        class Spy(InMemoryLinkDatabase):
            def assert_links(self, links):
                calls.append(len(links))
                super().assert_links(links)

        db = WriteBehindLinkDatabase(Spy())
        for i in range(5):
            db.assert_link(self.L(f"a{i}", f"b{i}"))
        db.commit()
        db.drain()
        assert calls == [5]
        db.close()

    def test_flush_failure_latches(self):
        class Broken(InMemoryLinkDatabase):
            def assert_links(self, links):
                raise OSError("disk gone")

        db = WriteBehindLinkDatabase(Broken())
        db.assert_link(self.L("a", "b"))
        db.commit()
        with pytest.raises(RuntimeError, match="write-behind"):
            db.drain()
        with pytest.raises(RuntimeError, match="write-behind"):
            db.assert_link(self.L("c", "d"))
        db.close()

    def test_close_drains(self, tmp_path):
        inner = SqliteLinkDatabase(str(tmp_path / "links.sqlite"))
        db = WriteBehindLinkDatabase(inner)
        db.assert_link(self.L("a", "b", ts=42))
        db.close()
        reopened = SqliteLinkDatabase(str(tmp_path / "links.sqlite"))
        assert [l.key() for l in reopened.get_all_links()] == [("a", "b")]
        reopened.close()

    def test_backpressure_bounds_queue(self):
        release = threading.Event()
        entered = threading.Event()

        class Slow(InMemoryLinkDatabase):
            def assert_links(self, links):
                entered.set()
                release.wait(10)
                super().assert_links(links)

        db = WriteBehindLinkDatabase(Slow())
        max_pending = db._MAX_PENDING
        db.assert_link(self.L("a0", "b0"))
        db.commit()
        entered.wait(10)  # flusher is now stuck inside batch 0
        # fill the queue to the cap behind it
        for i in range(1, max_pending + 1):
            db.assert_link(self.L(f"a{i}", f"b{i}"))
            db.commit()
        # the next commit must BLOCK until the flusher frees a slot
        db.assert_link(self.L("c", "d"))
        done = threading.Event()
        t = threading.Thread(target=lambda: (db.commit(), done.set()))
        t.start()
        assert not done.wait(0.3), "commit did not apply backpressure"
        assert len(db._queue) <= max_pending
        release.set()
        t.join(10)
        assert done.is_set()
        db.drain()
        assert db.count() == max_pending + 2
        db.close()

    def test_concurrent_reader_sees_complete_batches(self):
        db = WriteBehindLinkDatabase(InMemoryLinkDatabase())
        errors = []

        def reader():
            try:
                for _ in range(50):
                    rows = db.get_all_links()
                    assert len(rows) % 10 == 0, len(rows)
            except BaseException as e:  # surfaced below
                errors.append(e)

        t = threading.Thread(target=reader)
        t.start()
        for batch in range(20):
            for i in range(10):
                db.assert_link(self.L(f"a{batch}", f"b{i}"))
            db.commit()
        t.join()
        assert not errors
        db.close()


class TestSqliteBatchAndCount:
    def L(self, id1, id2, conf=0.9, status=LinkStatus.INFERRED, ts=None):
        return Link(id1, id2, status, LinkKind.DUPLICATE, conf, ts)

    def test_assert_links_matches_sequential_semantics(self, tmp_path):
        batched = SqliteLinkDatabase(str(tmp_path / "a.sqlite"))
        serial = SqliteLinkDatabase(str(tmp_path / "b.sqlite"))
        links = [
            self.L("a", "b", conf=0.9, ts=100),
            self.L("c", "d", conf=0.8, ts=200),
            self.L("a", "b", conf=0.9, ts=300),   # identical: no ts bump
            self.L("a", "b", conf=0.95, ts=400),  # changed: rewrites
            self.L("e", "f", status=LinkStatus.RETRACTED, ts=500),
        ]
        batched.assert_links([l.copy() for l in links])
        for l in links:
            serial.assert_link(l.copy())
        assert link_rows(batched) == link_rows(serial)
        bt = {l.key(): l.timestamp for l in batched.get_all_links()}
        st = {l.key(): l.timestamp for l in serial.get_all_links()}
        assert bt == st
        assert bt[("a", "b")] == 400
        batched.close()
        serial.close()

    def test_identical_reassert_keeps_timestamp(self, tmp_path):
        db = SqliteLinkDatabase(str(tmp_path / "links.sqlite"))
        db.assert_link(self.L("a", "b", conf=0.9, ts=100))
        db.assert_links([self.L("a", "b", conf=0.9 + 1e-9, ts=999)])
        (link,) = db.get_all_links()
        assert link.timestamp == 100  # pollers must not see a change
        assert db.get_changes_since(100) == []
        db.close()

    def test_count_incremental_and_correct(self, tmp_path):
        path = str(tmp_path / "links.sqlite")
        db = SqliteLinkDatabase(path)
        assert db.count() == 0
        db.assert_link(self.L("a", "b"))
        db.assert_links([self.L("c", "d"), self.L("e", "f"),
                         self.L("a", "b", conf=0.5)])  # update, not insert
        assert db.count() == 3 == len(db.get_all_links())
        # retraction is a status update: row count unchanged
        db.assert_link(self.L("a", "b", conf=0.5,
                              status=LinkStatus.RETRACTED))
        assert db.count() == 3
        db.close()
        # a fresh handle re-counts from the table
        db2 = SqliteLinkDatabase(path)
        assert db2.count() == 3
        db2.close()

    def test_count_is_cached_not_rescanned(self, tmp_path):
        db = SqliteLinkDatabase(str(tmp_path / "links.sqlite"))
        db.assert_link(self.L("a", "b"))
        assert db.count() == 1
        real = db._conn

        def boom():
            raise AssertionError("count() hit the database after warm-up")

        db._conn = boom
        try:
            assert db.count() == 1  # served from the incremental counter
        finally:
            db._conn = real
        db.close()


def test_one_to_one_conflict_prefetch_sees_batch_maybes(tmp_path):
    """The one-to-one flush's conflict prefetch must see THIS batch's
    pass-through maybe-link upserts (they downgraded a prior DUPLICATE
    row), exactly as the legacy per-event writes made visible — a stale
    DUPLICATE row must not block the batch's definite match."""
    from sesam_duke_microservice_tpu.engine.listeners import (
        ServiceMatchListener,
    )

    db = SqliteLinkDatabase(str(tmp_path / "links.sqlite"))
    listener = ServiceMatchListener("wl", db, kind="recordlinkage",
                                    one_to_one=True)
    a = make_record("A", name="acme")
    b = make_record("B", name="acme")
    c = make_record("C", name="acme")

    listener.batch_ready(2)
    listener.matches(a, c, 0.9)          # batch 1: definite (A, C)
    listener.batch_done()

    listener.batch_ready(2)
    listener.matches_perhaps(a, c, 0.65)  # downgraded to maybe...
    listener.matches(a, b, 0.85)          # ...so (A, B) must win
    listener.batch_done()

    rows = {(l.id1, l.id2): (l.kind, l.status) for l in db.get_all_links()}
    assert rows[("A", "B")] == (LinkKind.DUPLICATE, LinkStatus.INFERRED)
    assert rows[("A", "C")][0] == LinkKind.MAYBE
    db.close()


def test_dispatch_followers_gauge_zeroed_on_mark_failed():
    from sesam_duke_microservice_tpu import telemetry
    from sesam_duke_microservice_tpu.parallel.dispatch import Dispatcher

    telemetry.DISPATCH_FOLLOWERS.set(3)
    d = Dispatcher.__new__(Dispatcher)
    d._failed = None
    d.mark_failed("test: follower lost")
    assert telemetry.DISPATCH_FOLLOWERS.single().value == 0
    assert telemetry.DISPATCH_DOWN.single().value == 1
    telemetry.DISPATCH_DOWN.set(0)
