"""Smoke test for the HTTP-level stresstest driver (benchmarks/).

The driver is the reference's system-test shape (Sesam-node stand-in:
concurrent POSTs + incremental since-polling); this guards it from rot
with a tiny corpus on the host backend.
"""

import importlib.util
import os
import sys


def _load_driver():
    bench_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "benchmarks",
    )
    spec = importlib.util.spec_from_file_location(
        "_http_stresstest", os.path.join(bench_dir, "http_stresstest.py")
    )
    module = importlib.util.module_from_spec(spec)
    # the driver imports its sibling f1_stresstest; scope the path
    # mutation to the exec instead of leaving benchmarks/ importable (and
    # shadow-capable) for the rest of the session
    sys.path.insert(0, bench_dir)
    try:
        spec.loader.exec_module(module)
    finally:
        sys.path.remove(bench_dir)
    return module


def test_http_stresstest_driver_smoke():
    env_before = dict(os.environ)
    http_stresstest = _load_driver()

    out = http_stresstest.run(
        "host", entities=200, batch=50, concurrency=2, workload="dedup"
    )
    assert out["entities"] == 200
    assert out["links"] > 0
    assert out["f1"] > 0.8, out

    out = http_stresstest.run(
        "host", entities=200, batch=50, concurrency=2, workload="linkage",
        one_to_one=True,
    )
    assert out["links"] > 0
    assert out["precision"] > 0.8, out

    # the driver must not leak config env flags into this process (later
    # tests parse configs against os.environ)
    assert {k: os.environ.get(k) for k in ("ONE_TO_ONE", "MIN_RELEVANCE")} \
        == {k: env_before.get(k) for k in ("ONE_TO_ONE", "MIN_RELEVANCE")}


def test_http_stresstest_driver_sharded_smoke():
    """The same Sesam-node pipe shape through the mesh serving backend
    (concurrent POSTs microbatch onto the sharded scorer)."""
    http_stresstest = _load_driver()
    out = http_stresstest.run(
        "sharded", entities=200, batch=50, concurrency=2, workload="dedup"
    )
    assert out["entities"] == 200
    assert out["links"] > 0
    assert out["f1"] > 0.8, out
