"""Smoke test for the HTTP-level stresstest driver (benchmarks/).

The driver is the reference's system-test shape (Sesam-node stand-in:
concurrent POSTs + incremental since-polling); this guards it from rot
with a tiny corpus on the host backend.
"""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "benchmarks",
))


def test_http_stresstest_driver_smoke():
    import http_stresstest

    out = http_stresstest.run(
        "host", entities=200, batch=50, concurrency=2, workload="dedup"
    )
    assert out["entities"] == 200
    assert out["links"] > 0
    assert out["f1"] > 0.8, out

    out = http_stresstest.run(
        "host", entities=200, batch=50, concurrency=2, workload="linkage",
        one_to_one=True,
    )
    assert out["links"] > 0
    assert out["precision"] > 0.8, out
