"""Mesh-sharded ANN scorer vs the single-device ANN scorer (8-dev CPU mesh).

Contract: the sharded candidate pool is a superset of the single-device
pool (each shard keeps its own local top-C before the merge), so every
above-bound pair the single-device ANN program finds must appear in the
sharded result with an identical exact logit; counts/self-exclusion/group
filtering must carry over.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sesam_duke_microservice_tpu.ops import encoder as E
from sesam_duke_microservice_tpu.ops import features as F
from sesam_duke_microservice_tpu.ops import scoring as S
from sesam_duke_microservice_tpu.parallel import (
    ShardedCorpus,
    build_sharded_ann_scorer,
    corpus_mesh,
)

from test_device_matcher import dedup_schema, random_records

CHUNK = 16
TOP_C = 8
DIM = 128


@pytest.fixture(scope="module")
def mesh():
    assert jax.device_count() == 8, "conftest must force 8 virtual CPU devices"
    return corpus_mesh()


def build_inputs(n_corpus, n_queries, seed=17):
    schema = dedup_schema()
    plan = F.SchemaFeatures.plan(schema)
    enc = E.RecordEncoder(schema, DIM)
    records = random_records(n_corpus, seed=seed)
    queries = records[:n_queries]
    feats = F.extract_batch(plan, records)
    feats[E.ANN_PROP] = {E.ANN_TENSOR: enc.encode_corpus(records)}
    valid = np.ones((n_corpus,), dtype=bool)
    valid[n_corpus // 3] = False          # one tombstone
    deleted = np.zeros((n_corpus,), dtype=bool)
    deleted[n_corpus // 2] = True         # one dukeDeleted row
    group = np.full((n_corpus,), -1, dtype=np.int32)
    qfeats = F.extract_batch(plan, queries)
    q_emb = enc.encode_batch(queries)
    query_row = np.arange(n_queries, dtype=np.int32)
    query_group = np.full((n_queries,), -2, dtype=np.int32)
    return (plan, feats, valid, deleted, group, qfeats, q_emb,
            query_row, query_group)


def to_dev(tree):
    return {p: {k: jnp.asarray(a) for k, a in t.items()}
            for p, t in tree.items()}


class TestShardedAnnScorer:
    def test_superset_of_single_device(self, mesh):
        n = 8 * CHUNK * 2
        (plan, feats, valid, deleted, group, qfeats, q_emb,
         query_row, query_group) = build_inputs(n, 16)

        placer = ShardedCorpus(mesh, chunk=CHUNK)
        sfeats, svalid, sdeleted, sgroup = placer.place(
            feats, valid, deleted, group
        )
        sharded = build_sharded_ann_scorer(
            plan, mesh, chunk=CHUNK, top_c=TOP_C
        )
        min_logit = jnp.float32(0.0)
        qf = to_dev(qfeats)
        s_logit, s_index, s_sat = sharded(
            jnp.asarray(q_emb), qf, sfeats, svalid, sdeleted, sgroup,
            jnp.asarray(query_group), jnp.asarray(query_row), min_logit,
        )

        # single-device ANN over the same padded corpus
        cap = placer.padded_capacity(n)

        def pad(a, fill=0):
            out = np.full((cap,) + a.shape[1:], fill, dtype=a.dtype)
            out[:n] = a
            return out

        single = S.build_ann_scorer(plan, chunk=CHUNK, top_c=TOP_C)
        pfeats = {p: {k: jnp.asarray(pad(a)) for k, a in t.items()}
                  for p, t in feats.items() if p != E.ANN_PROP}
        d_logit, d_index, d_count = single(
            jnp.asarray(q_emb), qf,
            jnp.asarray(pad(feats[E.ANN_PROP][E.ANN_TENSOR])), pfeats,
            jnp.asarray(pad(valid, False)), jnp.asarray(pad(deleted, False)),
            jnp.asarray(pad(group, -1)),
            jnp.asarray(query_group), jnp.asarray(query_row), min_logit,
        )

        s_log, s_idx = np.asarray(s_logit), np.asarray(s_index)
        d_log, d_idx = np.asarray(d_logit), np.asarray(d_index)
        for qi in range(s_idx.shape[0]):
            single_hits = {
                int(r): float(v) for r, v in zip(d_idx[qi], d_log[qi])
                if v > 0.0
            }
            sharded_hits = {
                int(r): float(v) for r, v in zip(s_idx[qi], s_log[qi])
                if v > 0.0
            }
            # the sharded pool is a superset, and the merge keeps the best
            # top_c of it by exact logit — so a single-device hit is either
            # present with the identical logit, or was displaced by
            # strictly-better candidates (its logit falls at or below the
            # sharded result's worst kept logit)
            worst_kept = min(sharded_hits.values(), default=float("inf"))
            for row, logit in single_hits.items():
                if row in sharded_hits:
                    assert abs(sharded_hits[row] - logit) < 1e-4
                else:
                    assert logit <= worst_kept + 1e-4
            # and the sharded hits dominate: as many or more hits, each at
            # least as good as the single-device k-th best
            assert len(sharded_hits) >= len(single_hits) or len(
                sharded_hits) == TOP_C
            # no self-pairs, no masked rows
            assert qi not in sharded_hits
            assert (n // 3) not in sharded_hits
            assert (n // 2) not in sharded_hits

    def test_group_filtering(self, mesh):
        n = 8 * CHUNK
        (plan, feats, valid, deleted, group, qfeats, q_emb,
         query_row, query_group) = build_inputs(n, 8)
        group = np.asarray([1 + (i % 2) for i in range(n)], dtype=np.int32)
        query_group = np.asarray(
            [1 + (i % 2) for i in range(8)], dtype=np.int32
        )

        placer = ShardedCorpus(mesh, chunk=CHUNK)
        sfeats, svalid, sdeleted, sgroup = placer.place(
            feats, valid, deleted, group
        )
        sharded = build_sharded_ann_scorer(
            plan, mesh, chunk=CHUNK, top_c=TOP_C, group_filtering=True
        )
        s_logit, s_index, _ = sharded(
            jnp.asarray(q_emb), to_dev(qfeats), sfeats, svalid, sdeleted,
            sgroup, jnp.asarray(query_group), jnp.asarray(query_row),
            jnp.float32(0.0),
        )
        s_idx = np.asarray(s_index)
        s_log = np.asarray(s_logit)
        for qi in range(8):
            for r, v in zip(s_idx[qi], s_log[qi]):
                if v > S.NEG_INF / 2 and r >= 0:
                    assert group[int(r)] != query_group[qi]

    def test_saturation_signal(self, mesh):
        # every corpus row identical to the queries -> every local top-C
        # candidate clears the bound on every shard AND the merged pool is
        # fully above-bound -> count_sat >= TOP_C (here ndev * TOP_C, the
        # merged pool count: merge-level truncation is visible too)
        from test_device_matcher import make_record

        schema = dedup_schema()
        plan = F.SchemaFeatures.plan(schema)
        enc = E.RecordEncoder(schema, DIM)
        n = 8 * CHUNK
        records = [
            make_record(f"r{i}", name="acme corp", city="oslo", amount="100")
            for i in range(n)
        ]
        feats = F.extract_batch(plan, records)
        feats[E.ANN_PROP] = {E.ANN_TENSOR: enc.encode_corpus(records)}
        valid = np.ones((n,), dtype=bool)
        deleted = np.zeros((n,), dtype=bool)
        group = np.full((n,), -1, dtype=np.int32)

        placer = ShardedCorpus(mesh, chunk=CHUNK)
        sfeats, svalid, sdeleted, sgroup = placer.place(
            feats, valid, deleted, group
        )
        sharded = build_sharded_ann_scorer(
            plan, mesh, chunk=CHUNK, top_c=TOP_C
        )
        queries = records[:4]
        qfeats = F.extract_batch(plan, queries)
        _, _, sat = sharded(
            jnp.asarray(enc.encode_batch(queries)), to_dev(qfeats),
            sfeats, svalid, sdeleted, sgroup,
            jnp.full((4,), -2, np.int32), jnp.arange(4, dtype=jnp.int32),
            jnp.float32(0.0),
        )
        sat_max = int(np.asarray(sat).max())
        assert sat_max >= TOP_C                      # escalation triggers
        assert sat_max == 8 * TOP_C                  # full merged pool seen
