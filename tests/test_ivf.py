"""ISSUE 9 tests: int8-quantized embeddings + IVF clustered retrieval.

Contract under test (mirrors tests/test_ann.py's philosophy — the
candidate SET is approximate, everything else is exact):

  * int8 storage: the certified reconstruction bound holds, retrieval
    through the int8 x int8 -> int32 matmul finds the same matches as
    the exact brute-force device oracle (probabilities bit-identical for
    retrieved pairs — they share the rescoring + finalization path), and
    snapshots round-trip the codes + scale vector.
  * IVF: measured recall vs the brute-force oracle on a near-duplicate
    corpus; retrieved-pair events bit-identical to the flat scan;
    saturation escalates nprobe and terminally falls back to the flat
    scan (truncation can never pass silently); k-means is deterministic
    under a fixed seed; streaming-append cell assignment is identical to
    assigning every row in one pass with the same centroids.
  * plan-fingerprint satellite: a DUKE_EMB_INT8 / DUKE_IVF flip changes
    the feature-cache key, so cached rows can never mix storage layouts.
  * explain satellite: retrieval provenance reports the EFFECTIVE top-C
    after escalation and, under IVF, the probed cells + whether the
    candidate's cell was probed.
"""

import numpy as np
import pytest

from sesam_duke_microservice_tpu.core.config import MatchTunables
from sesam_duke_microservice_tpu.engine.ann_matcher import (
    AnnIndex,
    AnnProcessor,
)
from sesam_duke_microservice_tpu.ops import encoder as E
from sesam_duke_microservice_tpu.ops import feature_cache as FC
from sesam_duke_microservice_tpu.ops import ivf as IVF

from test_device_matcher import (
    EventLog,
    dedup_schema,
    make_record,
    random_records,
    run_device,
)


def run_ann(schema, batches, group_filtering=False, **index_kw):
    index = AnnIndex(schema, tunables=MatchTunables(), **index_kw)
    proc = AnnProcessor(schema, index, group_filtering=group_filtering)
    log = EventLog()
    proc.add_match_listener(log)
    for batch in batches:
        proc.deduplicate(batch)
    return log, index, proc


_FIRST = ["ole", "kari", "per", "anne", "nils", "ingrid", "lars", "berit",
          "jan", "liv", "arne", "astrid", "knut", "solveig", "odd", "randi"]
_LAST = ["hansen", "johansen", "olsen", "larsen", "andersen", "pedersen",
         "nilsen", "kristiansen", "jensen", "karlsen", "johnsen",
         "pettersen"]


def stress_records(identities, seed):
    """The bench stresstest's workload shape at test scale: each identity
    appears twice — an exact row and a one-character-typo'd near
    duplicate — so true matches are near-identical RECORDS (the
    distribution the recall target is stated for), while distinct
    identities stay pairwise far."""
    import random as _random

    rng = _random.Random(seed)
    records = []
    for i in range(identities):
        name = (f"{rng.choice(_FIRST)} {rng.choice(_LAST)} "
                f"x{rng.randint(100, 999)}")
        city = rng.choice(["oslo", "bergen", "tromso", "stavanger"])
        amount = str(rng.choice([100, 200, 300, 1000]))
        records.append(make_record(f"a{i}", name=name, city=city,
                                   amount=amount))
        pos = rng.randrange(len(name))
        typo = name[:pos] + rng.choice("abcdefgh") + name[pos + 1:]
        records.append(make_record(f"b{i}", name=typo, city=city,
                                   amount=amount))
    return records


@pytest.fixture
def ivf_env(monkeypatch):
    """Small-corpus IVF geometry: train immediately, few cells."""
    monkeypatch.setenv("DUKE_IVF", "1")
    monkeypatch.setenv("DUKE_IVF_MIN_ROWS", "16")
    monkeypatch.setenv("DUKE_IVF_CELLS", "8")
    monkeypatch.setenv("DUKE_IVF_NPROBE", "3")
    monkeypatch.setenv("DUKE_IVF_SCAN_SLOTS", "64")
    yield


# -- int8 quantization --------------------------------------------------------


class TestInt8Quantization:
    def test_reconstruction_within_certified_bound(self):
        rng = np.random.default_rng(7)
        rows = rng.normal(size=(64, 256)).astype(np.float32)
        rows /= np.linalg.norm(rows, axis=1, keepdims=True)
        codes, scale = E.quantize_rows(rows)
        assert codes.dtype == np.int8 and scale.dtype == np.float32
        recon = codes.astype(np.float32) * scale[:, None]
        # per-side error <= sqrt(D)/254 (half the two-sided cosine bound)
        err = np.linalg.norm(recon - rows, axis=1).max()
        assert err <= np.sqrt(256.0) / 254.0 + 1e-7
        # cosine between reconstructions within the certified two-sided eps
        eps = E.int8_cosine_eps(256)
        exact = rows @ rows.T
        approx = recon @ recon.T
        assert np.abs(exact - approx).max() <= eps + 1e-6

    def test_zero_row_quantizes_to_zero(self):
        codes, scale = E.quantize_rows(np.zeros((2, 16), np.float32))
        assert not codes.any() and not scale.any()
        assert not E.dequantize_rows(
            {E.ANN_TENSOR: codes, E.ANN_SCALE: scale}
        ).any()

    def test_match_events_equal_brute_force_oracle(self, monkeypatch):
        monkeypatch.setenv("DUKE_EMB_INT8", "1")
        schema = dedup_schema()
        records = random_records(60, seed=7)
        device, _, _ = run_device(schema, [records])
        ann, index, _ = run_ann(schema, [records])
        assert index.emb_storage == "int8"
        assert index.corpus.feats[E.ANN_PROP][E.ANN_TENSOR].dtype == np.int8
        assert E.ANN_SCALE in index.corpus.feats[E.ANN_PROP]
        # match_set entries carry the rounded confidence: equality means
        # the retrieved pairs' probabilities are identical to the exact
        # oracle, not just the same id pairs
        assert ann.match_set() == device.match_set()
        assert ann.none_set() == device.none_set()

    def test_embedding_hbm_halved(self, monkeypatch):
        schema = dedup_schema()
        records = random_records(40, seed=5)
        monkeypatch.setenv("DUKE_EMB_INT8", "0")  # leg-invariant baseline
        _, bf16_index, _ = run_ann(schema, [records])
        monkeypatch.setenv("DUKE_EMB_INT8", "1")
        _, int8_index, _ = run_ann(schema, [records])
        n = bf16_index.corpus.size
        bf16_bytes = bf16_index.corpus.feats[E.ANN_PROP][E.ANN_TENSOR][
            :n].nbytes
        tree = int8_index.corpus.feats[E.ANN_PROP]
        int8_matrix = tree[E.ANN_TENSOR][:n].nbytes
        int8_total = int8_matrix + tree[E.ANN_SCALE][:n].nbytes
        assert bf16_bytes == 2 * int8_matrix
        assert bf16_bytes / int8_total > 1.9

    def test_int8_snapshot_rejected_by_bf16_index(self, tmp_path,
                                                  monkeypatch):
        monkeypatch.setenv("DUKE_EMB_INT8", "1")
        schema = dedup_schema()
        records = random_records(10, seed=4)
        _, index, _ = run_ann(schema, [records])
        path = str(tmp_path / "snap.npz")
        index.snapshot_save(path)
        monkeypatch.setenv("DUKE_EMB_INT8", "0")
        index2 = AnnIndex(schema, tunables=MatchTunables())
        assert index2.emb_storage != "int8"
        assert index2.snapshot_load(
            path, {r.record_id: r for r in records}
        ) is False


# -- IVF retrieval ------------------------------------------------------------


class TestIvfRetrieval:
    def test_disabled_by_default(self, monkeypatch):
        monkeypatch.delenv("DUKE_IVF", raising=False)
        schema = dedup_schema()
        index = AnnIndex(schema, tunables=MatchTunables())
        assert index.ivf is None

    def test_stays_flat_below_min_rows(self, monkeypatch):
        monkeypatch.setenv("DUKE_IVF", "1")
        monkeypatch.setenv("DUKE_IVF_MIN_ROWS", "4096")
        schema = dedup_schema()
        records = random_records(30, seed=3)
        ann, index, _ = run_ann(schema, [records])
        assert index.ivf is not None and not index.ivf.ready
        device, _, _ = run_device(schema, [records])
        assert ann.match_set() == device.match_set()

    @staticmethod
    def _links(log):
        """Unordered matched pairs with confidence — the link-DB view,
        which is what downstream consumers actually read (the link store
        keys on the sorted id pair, so EITHER retrieval direction
        materializes the link)."""
        return {
            (min(e[1], e[2]), max(e[1], e[2]), e[3])
            for e in log.match_set() if e[0] == "match"
        }

    def test_recall_vs_flat_scan_and_brute_force(self, ivf_env,
                                                 monkeypatch):
        """The acceptance framing: measured recall >= 0.99 vs the flat
        scan (what IVF actually costs — the flat top-C scan is itself
        bounded-recall vs exhaustive on match-dense corpora), plus an
        absolute floor vs the exhaustive brute-force oracle, with
        retrieved-pair probabilities identical to the oracle's."""
        schema = dedup_schema()
        records = stress_records(200, seed=11)
        device, _, _ = run_device(schema, [records])
        ann, index, _ = run_ann(schema, [records])
        assert index.ivf is not None and index.ivf.ready
        monkeypatch.setenv("DUKE_IVF", "0")
        flat, flat_index, _ = run_ann(schema, [records])
        assert flat_index.ivf is None
        oracle = device.match_set()
        found = ann.match_set()
        # retrieved pairs rescore through the identical exact path: any
        # pair the IVF path emits must be IN the oracle with the same
        # rounded confidence
        assert found <= oracle
        olinks = self._links(device)
        flinks = self._links(flat)
        ilinks = self._links(ann)
        recall_vs_flat = len(ilinks & flinks) / max(1, len(flinks))
        assert recall_vs_flat >= 0.99, (recall_vs_flat,
                                        len(flinks) - len(ilinks & flinks))
        recall_vs_oracle = len(ilinks & olinks) / max(1, len(olinks))
        assert recall_vs_oracle >= 0.98, recall_vs_oracle

    def test_retrieved_pairs_bit_identical_to_flat_scan(self, ivf_env,
                                                        monkeypatch):
        schema = dedup_schema()
        records = random_records(200, seed=23)
        ann_ivf, index, _ = run_ann(schema, [records])
        assert index.ivf is not None and index.ivf.ready
        monkeypatch.setenv("DUKE_IVF", "0")
        ann_flat, flat_index, _ = run_ann(schema, [records])
        assert flat_index.ivf is None
        # common pairs carry the identical confidence (shared exact
        # rescoring); the IVF candidate set is a subset by construction
        assert ann_ivf.match_set() <= ann_flat.match_set()

    def test_int8_plus_ivf_match_oracle(self, ivf_env, monkeypatch):
        monkeypatch.setenv("DUKE_EMB_INT8", "1")
        # int8 quantization noise costs a little cell-ranking fidelity on
        # top of the probe truncation; half the cells probed (vs 3/8 for
        # the bf16 recall test) isolates the composition's correctness
        # from the aggressiveness of the tiny test geometry
        monkeypatch.setenv("DUKE_IVF_NPROBE", "4")
        schema = dedup_schema()
        records = stress_records(150, seed=31)
        device, _, _ = run_device(schema, [records])
        ann, index, _ = run_ann(schema, [records])
        assert index.emb_storage == "int8"
        assert index.ivf is not None and index.ivf.ready
        oracle = device.match_set()
        found = ann.match_set()
        assert found <= oracle
        olinks = self._links(device)
        ilinks = self._links(ann)
        assert len(ilinks & olinks) / max(1, len(olinks)) >= 0.98

    def test_saturation_escalates_to_flat_fallback(self, monkeypatch):
        """Tiny C + tiny nprobe on an all-identical corpus: every probe
        saturates, the ladder widens nprobe past ncells and terminally
        re-runs the flat scan — all pairs must surface (the 'truncation
        can never pass silently' contract)."""
        monkeypatch.setenv("DUKE_IVF", "1")
        monkeypatch.setenv("DUKE_IVF_MIN_ROWS", "8")
        monkeypatch.setenv("DUKE_IVF_CELLS", "4")
        monkeypatch.setenv("DUKE_IVF_NPROBE", "1")
        from sesam_duke_microservice_tpu.engine import device_matcher as DM

        schema = dedup_schema(threshold=0.5)
        records = [
            make_record(f"d{i}", name="acme corp", city="oslo", amount="100")
            for i in range(24)
        ]
        esc0 = DM.ESCALATIONS
        ann, index, _ = run_ann(schema, [records], initial_top_c=2)
        assert index.ivf is not None and index.ivf.ready
        match_pairs = {(e[1], e[2]) for e in ann.events if e[0] == "match"}
        assert len(match_pairs) == 24 * 23
        assert DM.ESCALATIONS > esc0

    def test_group_filtering_record_linkage(self, monkeypatch):
        """The gathered candidate mask (scoring.candidate_mask_gathered)
        carries the same group-exclusion policy as the scan mask."""
        monkeypatch.setenv("DUKE_IVF", "1")
        monkeypatch.setenv("DUKE_IVF_MIN_ROWS", "16")
        monkeypatch.setenv("DUKE_IVF_CELLS", "4")
        monkeypatch.setenv("DUKE_IVF_NPROBE", "3")
        schema = dedup_schema()
        records = random_records(40, seed=11, with_group=True)
        device, _, _ = run_device(schema, [records], group_filtering=True)
        ann, index, _ = run_ann(schema, [records], group_filtering=True)
        assert index.ivf is not None and index.ivf.ready
        found = ann.match_set()
        oracle = device.match_set()
        # policy: every emitted pair is in the oracle (same confidence),
        # and the group exclusion held — records carry alternating
        # groups, so a same-group link would be a mask bug
        assert found <= oracle
        from sesam_duke_microservice_tpu.core.records import (
            GROUP_NO_PROPERTY_NAME,
        )

        groups = {
            r.record_id: r.get_value(GROUP_NO_PROPERTY_NAME)
            for r in records
        }
        for _, id1, id2, _ in found:
            assert groups[id1] != groups[id2]
        # tiny 2-of-4-cell geometry still finds the bulk of the links
        assert len(found) >= 0.8 * len(oracle)

    def test_kmeans_deterministic_under_seed(self):
        rng = np.random.default_rng(3)
        x = rng.normal(size=(200, 64)).astype(np.float32)
        x /= np.linalg.norm(x, axis=1, keepdims=True)
        c1 = IVF.train_kmeans(x, 16, seed=42, iters=6)
        c2 = IVF.train_kmeans(x, 16, seed=42, iters=6)
        np.testing.assert_array_equal(c1, c2)
        assert c1.shape == (16, 64)
        norms = np.linalg.norm(c1, axis=1)
        np.testing.assert_allclose(norms[norms > 0], 1.0, atol=1e-5)

    def test_streaming_append_assignment_parity(self, ivf_env):
        """Incremental per-slice assignment == assigning every row in one
        pass under the same centroids (the full-retrain oracle for
        membership, holding centroids fixed)."""
        schema = dedup_schema()
        b1 = random_records(40, seed=1)
        b2 = random_records(12, seed=2)
        for i, r in enumerate(b2):
            r.set_values("ID", [f"s{i}"])
        _, index, _ = run_ann(schema, [b1, b2])
        ivf = index.ivf
        assert ivf is not None and ivf.ready
        n = index.corpus.size
        assert ivf.assigned_upto == n
        # no retrain happened between the batches (52 < 2 * 40)
        assert ivf.trained_rows == 40
        emb = E.dequantize_rows({
            name: arr[:n]
            for name, arr in index.corpus.feats[E.ANN_PROP].items()
        })
        oracle = ivf._assign_rows(emb)
        np.testing.assert_array_equal(ivf.cell_of[:n], oracle)
        # membership matrix: each cell's listed rows == argmax assignment
        for k in range(ivf.ncells):
            listed = sorted(
                int(r) for r in ivf.cell_rows[k] if r >= 0
            )
            assert listed == sorted(np.flatnonzero(oracle == k).tolist())

    def test_refresh_on_doubling(self, ivf_env):
        schema = dedup_schema()
        b1 = random_records(24, seed=5)
        b2 = random_records(40, seed=6)
        for i, r in enumerate(b2):
            r.set_values("ID", [f"g{i}"])
        _, index, _ = run_ann(schema, [b1, b2])
        ivf = index.ivf
        assert ivf is not None and ivf.ready
        # the second batch crossed 2x the first training point -> refresh
        assert ivf.trained_rows == index.corpus.size
        assert ivf.assigned_upto == index.corpus.size


# -- satellites ---------------------------------------------------------------


class TestPlanFingerprint:
    def _fp(self, schema):
        index = AnnIndex(schema, tunables=MatchTunables())
        return FC.plan_fingerprint(index.plan, index.encoder)

    def test_int8_flip_changes_fingerprint(self, monkeypatch):
        schema = dedup_schema()
        monkeypatch.setenv("DUKE_EMB_INT8", "0")
        base = self._fp(schema)
        monkeypatch.setenv("DUKE_EMB_INT8", "1")
        assert self._fp(schema) != base

    def test_ivf_flip_changes_fingerprint(self, monkeypatch):
        schema = dedup_schema()
        monkeypatch.setenv("DUKE_IVF", "0")
        base = self._fp(schema)
        monkeypatch.setenv("DUKE_IVF", "1")
        assert self._fp(schema) != base

    def test_threshold_reload_keeps_fingerprint(self):
        # low/high/threshold changes must NOT invalidate (the PR 4
        # contract, re-asserted over the extended key)
        fp1 = self._fp(dedup_schema(threshold=0.8))
        fp2 = self._fp(dedup_schema(threshold=0.95))
        assert fp1 == fp2

    def test_cache_rows_do_not_mix_storage_modes(self, monkeypatch):
        FC.reset()
        schema = dedup_schema()
        records = random_records(10, seed=9)
        monkeypatch.setenv("DUKE_EMB_INT8", "0")  # leg-invariant baseline
        index = AnnIndex(schema, tunables=MatchTunables())
        bf16 = index._extract(records)
        assert E.ANN_SCALE not in bf16[E.ANN_PROP]
        monkeypatch.setenv("DUKE_EMB_INT8", "1")
        index8 = AnnIndex(schema, tunables=MatchTunables())
        int8 = index8._extract(records)
        # same record content, different fingerprint: the int8 extraction
        # must not be served bf16 cached rows (or vice versa)
        assert int8[E.ANN_PROP][E.ANN_TENSOR].dtype == np.int8
        assert E.ANN_SCALE in int8[E.ANN_PROP]


class TestExplainProvenance:
    def test_effective_top_c_and_probed_cells(self, ivf_env):
        schema = dedup_schema()
        records = random_records(64, seed=13)
        _, index, _ = run_ann(schema, [records])
        assert index.ivf is not None and index.ivf.ready
        out = index.explain_retrieval(records[0], records[1])
        assert out["mode"] == "ann"
        assert out["top_c"] == index.initial_top_c
        assert out["effective_top_c"] >= min(
            index.initial_top_c, index.corpus.capacity
        ) or out["effective_top_c"] > 0
        ivf_info = out["ivf"]
        assert ivf_info["cells"] == index.ivf.ncells
        assert len(ivf_info["probed_cells"]) == ivf_info["nprobe"]
        assert 0 <= ivf_info["candidate_cell"] < index.ivf.ncells
        assert isinstance(ivf_info["cell_probed"], bool)
        # a probed + retrieved candidate reports its rank truthfully
        if out.get("retrieved"):
            assert out["rank"] is not None

    def test_flat_explain_reports_effective_c(self):
        schema = dedup_schema()
        records = random_records(30, seed=17)
        _, index, _ = run_ann(schema, [records])
        out = index.explain_retrieval(records[0], records[1])
        assert "ivf" not in out
        assert out["effective_top_c"] == min(
            index.initial_top_c, index.corpus.capacity
        ) or out["effective_top_c"] > index.initial_top_c
