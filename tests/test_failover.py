"""Leader failover: promotion from replica state + epoch fencing
(ISSUE 8 tentpole).

Kills the leader mid-ingest (injected ``crash_leader`` fault at a
deterministic op index), promotes the follower's replicas into a serving
``DukeApp`` (``dispatch.promote_follower``), and pins the promoted link
DB bit-equal (modulo timestamps) to a clean single-process run of the
batches that committed — then keeps ingesting through the promoted
leader and re-binds the full HTTP frontend.  A zombie ex-leader's
post-promotion broadcasts are rejected by the fenced epoch.
"""

import json
import threading
import time
import urllib.request

import pytest

from sesam_duke_microservice_tpu import telemetry
from sesam_duke_microservice_tpu.parallel import dispatch
from sesam_duke_microservice_tpu.service.app import serve
from sesam_duke_microservice_tpu.utils import faults

from test_replica_serving import KEY, HaGroup
from test_sharded_service import DEDUP_XML, _run_dedup, _seeded_batch


@pytest.fixture(autouse=True)
def _no_env_faults(monkeypatch):
    # parse_config inside the follower/promotion paths reads the real
    # env; pin MIN_RELEVANCE so replica + promoted configs match the
    # leader's (built with env={"MIN_RELEVANCE": "0.05"})
    monkeypatch.setenv("MIN_RELEVANCE", "0.05")
    faults.configure("")
    yield
    faults.configure(None)


def _link_facts(rows):
    """Timestamp-free link identity: the promoted DB is compared against
    a clean run whose wall-clock differs."""
    return sorted(
        (r["entity1"], r["entity2"], r["_deleted"],
         round(r["confidence"], 9))
        for r in rows
    )


def test_leader_crash_promotion_matches_clean_run():
    b1 = _seeded_batch(24)
    b2 = _seeded_batch(12, prefix="b")
    b3 = _seeded_batch(9, prefix="d")

    g = HaGroup(DEDUP_XML, backend="device")
    app2 = None
    try:
        g.ingest(b1)
        g.wait_applied()
        pre_crash_rows = g.leader_feed()

        # kill the leader MID-INGEST: the very next broadcast (b2's
        # corpus commit) dies before any bytes hit the wire
        faults.configure(
            f"crash_leader={g.dispatcher._op_index + 1}"
        )
        with pytest.raises(faults.LeaderCrash):
            g.ingest(b2)
        faults.configure("")

        session = g.followers[0].session
        assert session.link_replicas[KEY].applied_seq \
            == g.workload().link_database.seq

        # -- promote: replicas become a serving leader at epoch 2
        app2 = dispatch.promote_follower(session)
        assert session.promoted and session.epoch == 2
        assert telemetry.DISPATCH_EPOCH.single().value == 2
        wl2 = app2.deduplications["people"]

        # the promoted feed IS the deposed leader's at the watermark —
        # same rows, same timestamps (replicated verbatim)
        with wl2.lock:
            assert wl2.links_since(0) == pre_crash_rows

        # and equals a CLEAN single-process run of the committed batches
        oracle = _run_dedup("device", [b1])
        assert sorted(
            (r[0], r[1], r[2]) for r in _link_facts(pre_crash_rows)
            if not r[2]
        ) == sorted((e1, e2, False) for e1, e2, _c in oracle)

        # -- the promoted leader keeps serving writes: ingest continues
        # and the end state equals a clean run of b1 + b3
        with wl2.lock:
            wl2.process_batch("crm", b3)
            rows_after = wl2.links_since(0)
        clean = _run_dedup("device", [b1, b3])
        assert sorted(
            (e1, e2, round(c, 9))
            for e1, e2, d, c in _link_facts(rows_after) if not d
        ) == clean

        # -- zombie fencing: the deposed leader broadcasts at epoch 1;
        # the promoted session rejects without touching replica state
        stale0 = session.stale_rejected
        count0 = session.link_replicas[KEY].applied_seq
        g.dispatcher.broadcast(("score", KEY, []))
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and session.stale_rejected == stale0:
            time.sleep(0.01)
        assert session.stale_rejected == stale0 + 1
        assert session.link_replicas[KEY].applied_seq == count0
    finally:
        if app2 is not None:
            app2.close()
        g.close()


def test_promoted_frontend_rebinds_http():
    """The full REST surface comes back on the promoted follower: feed,
    /healthz, /readyz, /stats — served from the replica-built app."""
    g = HaGroup(DEDUP_XML, backend="device")
    app2 = None
    server = None
    try:
        g.ingest(_seeded_batch(24))
        g.wait_applied()
        expected = g.leader_feed()

        app2 = dispatch.promote_follower(g.followers[0].session)
        server = serve(app2, port=0, host="127.0.0.1")
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        base = f"http://127.0.0.1:{server.server_address[1]}"

        with urllib.request.urlopen(base + "/deduplication/people?since=0",
                                    timeout=30) as r:
            assert r.status == 200
            assert json.loads(r.read()) == expected
        with urllib.request.urlopen(base + "/readyz", timeout=30) as r:
            assert r.status == 200
        with urllib.request.urlopen(base + "/stats", timeout=30) as r:
            stats = json.loads(r.read())
            assert stats["workloads"][0]["records_indexed"] == 24
        # a post-promotion POST ingests through the promoted engine
        req = urllib.request.Request(
            base + "/deduplication/people/crm",
            json.dumps(_seeded_batch(6, prefix="x")).encode(),
            {"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=120) as r:
            assert r.status == 200
    finally:
        if server is not None:
            server.shutdown()
        if app2 is not None:
            app2.close()
        g.close()


def test_promote_without_replicas_refuses():
    session = dispatch._FollowerSession(lambda frame: None)
    with pytest.raises(RuntimeError, match="nothing to promote"):
        dispatch.promote_follower(session)
    session.close()


def test_promoted_leader_refuses_config_reload():
    """A promoted leader's workloads hold the ONLY copy of the replicated
    link state — a reload would swap in empty link DBs behind a 200."""
    g = HaGroup(DEDUP_XML, backend="device")
    app2 = None
    try:
        g.ingest(_seeded_batch(24))
        g.wait_applied()
        app2 = dispatch.promote_follower(g.followers[0].session)
        wl2 = app2.deduplications["people"]
        with wl2.lock:
            rows_before = wl2.links_since(0)
        assert rows_before
        with pytest.raises(RuntimeError, match="promoted leader"):
            app2.reload_from_string(g.sc.config_string)
        # nothing was swapped or closed: the link state survives intact
        assert app2.deduplications["people"] is wl2
        with wl2.lock:
            assert wl2.links_since(0) == rows_before
    finally:
        if app2 is not None:
            app2.close()
        g.close()


def test_publish_failure_keeps_seq_and_batch():
    """A publish that raises must not advance the stream seq or drop the
    batch — the next commit re-publishes it (no ReplicaGap hole)."""
    from sesam_duke_microservice_tpu.links.memory import (
        InMemoryLinkDatabase,
    )
    from sesam_duke_microservice_tpu.links.base import (
        Link,
        LinkKind,
        LinkStatus,
    )
    from sesam_duke_microservice_tpu.links.replica import (
        PublishingLinkDatabase,
        ReplicaLinkDatabase,
    )

    published = []
    fail = {"on": True}

    def publish(seq, rows):
        if fail["on"]:
            raise RuntimeError("broadcast failed")
        published.append((seq, list(rows)))

    db = PublishingLinkDatabase(InMemoryLinkDatabase(), publish)
    db.assert_link(Link("a", "b", LinkStatus.INFERRED, LinkKind.DUPLICATE,
                        0.9, timestamp=1000))
    with pytest.raises(RuntimeError, match="broadcast failed"):
        db.commit()
    assert db.seq == 0 and not published  # nothing advanced, no hole
    fail["on"] = False
    db.assert_link(Link("c", "d", LinkStatus.INFERRED, LinkKind.DUPLICATE,
                        0.8, timestamp=2000))
    db.commit()
    assert [seq for seq, _ in published] == [1]
    assert len(published[0][1]) == 2  # the failed batch rode along
    replica = ReplicaLinkDatabase()
    replica.apply_ops(*published[0])  # and replays with no gap
    assert replica.count() == 2


def test_leader_alive_probe_distinguishes_eviction_from_death():
    """Split-brain guard: stream EOF alone cannot tell 'the leader
    evicted me' from 'the leader died' — the liveness probe can."""
    import socket

    server = socket.create_server(("127.0.0.1", 0))
    host, port = server.getsockname()
    try:
        assert dispatch._leader_alive(host, port, timeout=5.0) is True
    finally:
        server.close()
    assert dispatch._leader_alive(host, port, timeout=2.0) is False


def test_zero_byte_send_failure_retries_then_heals(monkeypatch):
    """A real OSError that wrote no bytes is retry-safe (the stream is
    still frame-aligned): the retry layer heals it without eviction."""
    g = HaGroup(DEDUP_XML, backend="device")
    try:
        real = dispatch.Dispatcher._send_tracked
        fails = {"n": 2}

        def flaky(conn, frame):
            if fails["n"] > 0:
                fails["n"] -= 1
                e = OSError("transient reset")
                e.frame_sent = 0
                raise e
            return real(conn, frame)

        monkeypatch.setattr(dispatch.Dispatcher, "_send_tracked",
                            staticmethod(flaky))
        monkeypatch.setattr(dispatch, "_RETRY_BASE_S", 0.001)
        g.ingest(_seeded_batch(6))
        assert fails["n"] == 0  # the flaky sends actually happened
        assert g.dispatcher._failed is None
        assert len(g.dispatcher.live_followers()) == 1  # NOT evicted
        g.wait_applied()
        assert g.replica_feed() == g.leader_feed()
    finally:
        g.close()
