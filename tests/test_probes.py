"""Black-box canary probe plane (ISSUE 20).

Four contracts pinned here:

  * **Invisibility differential** — with the prober ON (cycles actually
    running) vs OFF, the user-visible ``?since=`` feed rows and link
    rows are bit-identical (wall-clock ``_updated`` normalized), and the
    ``__probe__`` namespace is rejected outright at the HTTP surface.
  * **Fault drill** — a seeded ``probe_flip`` fault is caught within ONE
    cycle: latched mismatch ring entry with trace/decision joins,
    ``duke_probe_verdict_mismatches_total`` >= 1, ``/healthz`` flips to
    degraded with the per-workload detail.
  * **Per-range federation probing** — under ``fed_down=<g>`` exactly
    group *g*'s owned ranges fail their reachability probe, surfaced on
    the plane's ``/healthz`` and in the fleet rollup's
    ``duke_probe_range_checks_total``.
  * **Shared-ladder accounting** — the probe shadow resolves to the user
    workload's shared AOT ladder: a device-backend probe cycle adds ZERO
    ``duke_jit_compiles_total``.
"""

import json
import urllib.error
import urllib.request

import pytest

from sesam_duke_microservice_tpu import telemetry
from sesam_duke_microservice_tpu.core.config import parse_config
from sesam_duke_microservice_tpu.service.app import DukeApp, serve
from sesam_duke_microservice_tpu.telemetry import slo, tracing
from sesam_duke_microservice_tpu.telemetry.probes import (
    PROBE_PREFIX,
    _perturb_heavy,
    _perturb_light,
    _token,
    derive_canaries,
    is_probe_name,
    probe_name,
)
from sesam_duke_microservice_tpu.utils import faults

from test_federation import make_fed
from test_observability import parse_exposition

CONFIG_XML = """
<DukeMicroService>
  <Deduplication name="people" link-database-type="in-memory">
    <duke>
      <schema>
        <threshold>0.8</threshold>
        <maybe-threshold>0.7</maybe-threshold>
        <property><name>NAME</name>
          <comparator>levenshtein</comparator><low>0.1</low><high>0.95</high>
        </property>
        <property><name>EMAIL</name>
          <comparator>exact</comparator><low>0.2</low><high>0.95</high>
        </property>
      </schema>
      <data-source class="io.sesam.dukemicroservice.IncrementalDeduplicationDataSource">
        <param name="dataset-id" value="crm"/>
        <column name="name" property="NAME"/>
        <column name="email" property="EMAIL"/>
      </data-source>
    </duke>
  </Deduplication>
  <RecordLinkage name="pairing" link-mode="one-to-one" link-database-type="in-memory">
    <duke>
      <schema>
        <threshold>0.7</threshold>
        <property><name>NAME</name>
          <comparator>levenshtein</comparator><low>0.1</low><high>0.95</high>
        </property>
      </schema>
      <group>
        <data-source class="io.sesam.dukemicroservice.IncrementalRecordLinkageDataSource">
          <param name="dataset-id" value="left"/>
          <column name="name" property="NAME"/>
        </data-source>
      </group>
      <group>
        <data-source class="io.sesam.dukemicroservice.IncrementalRecordLinkageDataSource">
          <param name="dataset-id" value="right"/>
          <column name="name" property="NAME"/>
        </data-source>
      </group>
    </duke>
  </RecordLinkage>
</DukeMicroService>
"""

DEDUP_ONLY_XML = """
<DukeMicroService>
  <Deduplication name="people" link-database-type="in-memory">
    <duke>
      <schema>
        <threshold>0.8</threshold>
        <property><name>NAME</name>
          <comparator>levenshtein</comparator><low>0.1</low><high>0.95</high>
        </property>
        <property><name>EMAIL</name>
          <comparator>exact</comparator><low>0.2</low><high>0.95</high>
        </property>
      </schema>
      <data-source class="io.sesam.dukemicroservice.IncrementalDeduplicationDataSource">
        <param name="dataset-id" value="crm"/>
        <column name="name" property="NAME"/>
        <column name="email" property="EMAIL"/>
      </data-source>
    </duke>
  </Deduplication>
</DukeMicroService>
"""

USER_BATCH = [
    {"_id": "u1", "name": "alice smith", "email": "alice@example.no"},
    {"_id": "u2", "name": "alice smith", "email": "alice@example.no"},
    {"_id": "u3", "name": "bob jones", "email": "bob@example.no"},
]


@pytest.fixture(autouse=True)
def _clean_slate(monkeypatch):
    # force-enable regardless of the CI leg's DUKE_PROBE pin — the OFF
    # arm of the differential overrides per-app below
    monkeypatch.setenv("DUKE_PROBE", "1")
    monkeypatch.setenv("DUKE_PROBE_INTERVAL_S", "3600")
    monkeypatch.setenv("MIN_RELEVANCE", "0.05")
    faults.configure("")
    slo._reset_for_tests()
    yield
    faults.configure(None)
    slo._reset_for_tests()
    tracing.RECORDER.clear()


def make_app(xml=CONFIG_XML, backend="host"):
    return DukeApp(parse_config(xml), backend=backend, persistent=False)


def user_feed(wl):
    """Full user ``?since=`` walk, wall-clock ``_updated`` dropped."""
    rows, since = [], 0
    while True:
        page, nxt = wl.links_page(since, 500)
        if not page:
            break
        rows.extend(page)
        since = nxt
    out = []
    for r in rows:
        r = dict(r)
        r.pop("_updated", None)
        out.append(json.dumps(r, sort_keys=True))
    return sorted(out)


def user_links(wl):
    return sorted(
        (l.id1, l.id2, l.status.value, l.kind.value, round(l.confidence, 12))
        for l in wl.link_database.get_all_links()
    )


def request(url, method="GET", body=None):
    req = urllib.request.Request(url, data=body, method=method)
    try:
        with urllib.request.urlopen(req, timeout=60) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


# -- corpus derivation ---------------------------------------------------------


class TestCorpus:
    def test_perturbations_preserve_the_blocking_token(self):
        """Perturbed values keep word 1 intact — the canary must stay
        retrievable by exact-token blocking so it certifies scoring,
        not candidate-search recall."""
        v = _token("pair", "NAME", "ab")
        head = v.split(" ")[0]
        assert _perturb_light(v).split(" ")[0] == head
        assert _perturb_heavy(v).split(" ")[0] == head
        assert _perturb_light(v) != v
        assert _perturb_heavy(v) != v
        # deterministic: same inputs, same corpus, across processes
        assert v == _token("pair", "NAME", "ab")

    def test_oracle_verdicts_straddle_the_thresholds(self):
        app = make_app()
        try:
            app.prober.run_cycle()
            entry = app.prober._shadows[("deduplication", "people")]
            by_key = {c.key: c for c in entry.corpus}
            assert by_key["identical"].expected_verdict == "match"
            assert by_key["disjoint"].expected_verdict == "reject"
            # per-property near/far pairs exist for every mapped prop
            assert {"near-NAME", "far-NAME", "near-EMAIL",
                    "far-EMAIL"} <= set(by_key)
            # a light perturbation stays above threshold; the oracle
            # probability is recorded for the mismatch forensics
            assert by_key["near-NAME"].expected_prob > 0.8
        finally:
            app.close()


# -- tentpole: invisibility differential ---------------------------------------


class TestInvisibilityDifferential:
    def test_user_feed_and_links_bit_identical_prober_on_off(self, monkeypatch):
        # ON arm: probe cycles interleaved around the user ingest
        app_on = make_app()
        try:
            assert app_on.prober is not None
            app_on.prober.run_cycle()
            app_on.scheduler.submit(
                "deduplication", "people", "crm", list(USER_BATCH))
            app_on.prober.run_cycle()
            wl = app_on.deduplications["people"]
            feed_on, links_on = user_feed(wl), user_links(wl)
        finally:
            app_on.close()

        # OFF arm: DUKE_PROBE=0 restores today's behavior exactly
        monkeypatch.setenv("DUKE_PROBE", "0")
        app_off = make_app()
        try:
            assert app_off.prober is None
            app_off.scheduler.submit(
                "deduplication", "people", "crm", list(USER_BATCH))
            wl = app_off.deduplications["people"]
            feed_off, links_off = user_feed(wl), user_links(wl)
        finally:
            app_off.close()

        assert feed_on == feed_off
        assert links_on == links_off
        assert feed_on  # the differential is about something
        # nothing probe-namespaced leaks into the user surface
        assert not any(PROBE_PREFIX in row for row in feed_on)
        assert not any(is_probe_name(name) for name in app_on.deduplications)

    def test_probe_workloads_never_reach_the_registries(self):
        app = make_app()
        try:
            app.prober.run_cycle()
            assert len(app.prober._shadows) == 2
            assert not any(is_probe_name(n) for n in app.deduplications)
            assert not any(is_probe_name(n) for n in app.record_linkages)
            # the scheduler resolves probe names only through the prober
            assert app._resolve_workload(
                "deduplication", probe_name("people")) is not None
            assert app._resolve_workload(
                "deduplication", probe_name("nope")) is None
        finally:
            app.close()


# -- HTTP surface --------------------------------------------------------------


class TestHttpSurface:
    @pytest.fixture()
    def served(self):
        import threading

        app = make_app()
        server = serve(app, port=0, host="127.0.0.1")
        threading.Thread(target=server.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{server.server_address[1]}"
        yield app, base
        server.shutdown()
        app.close()

    def test_probe_namespace_rejected(self, served):
        app, base = served
        app.prober.run_cycle()  # shadows exist — and still unreachable
        status, _ = request(
            f"{base}/deduplication/{PROBE_PREFIX}people/{PROBE_PREFIX}crm",
            "POST", json.dumps(USER_BATCH).encode())
        assert status == 404
        status, _ = request(
            f"{base}/deduplication/people/{PROBE_PREFIX}crm",
            "POST", json.dumps(USER_BATCH).encode())
        assert status == 404
        status, body = request(f"{base}/deduplication/{PROBE_PREFIX}people")
        assert status == 400 and b"reserved" in body

    def test_green_cycle_healthz_metrics_debug(self, served):
        app, base = served
        results = app.prober.run_cycle()
        assert results and all(r["ok"] for r in results.values())

        status, body = request(f"{base}/healthz")
        health = json.loads(body)
        assert status == 200 and health["status"] == "ok"
        assert "probe_verdict_mismatches" not in health

        status, body = request(f"{base}/metrics")
        metrics = parse_exposition(body.decode())
        for kind, name in (("deduplication", "people"),
                           ("recordlinkage", "pairing")):
            lbls = (("kind", kind), ("workload", name))
            assert metrics[("duke_probe_verdict_mismatches_total",
                            lbls)] == 0
            assert metrics[("duke_probe_freshness_seconds", lbls)] >= 0
            for stage in ("ingest", "score", "feed"):
                assert metrics[(
                    "duke_probe_e2e_seconds_count",
                    tuple(sorted(lbls + (("stage", stage),))))] == 1

        status, body = request(f"{base}/debug/probes")
        dbg = json.loads(body)
        assert status == 200 and dbg["enabled"]
        assert {w["workload"] for w in dbg["workloads"]} == {
            "people", "pairing"}
        assert all(w["last"]["ok"] for w in dbg["workloads"])
        assert dbg["mismatches"] == []

    def test_probe_flip_caught_within_one_cycle(self, served):
        """The fault drill the acceptance pins: one seeded verdict
        corruption -> latched ring entry + counter + /healthz flip,
        all observable after a single cycle."""
        app, base = served
        faults.configure("probe_flip=1")
        app.prober.run_cycle()

        status, body = request(f"{base}/healthz")
        health = json.loads(body)
        assert health["status"] == "degraded"
        detail = health["probe_verdict_mismatches"]
        assert detail["verdict_mismatches"] >= 1
        assert any(v >= 1 for v in detail["workloads"].values())

        status, body = request(f"{base}/metrics")
        metrics = parse_exposition(body.decode())
        total = sum(v for (fam, _), v in metrics.items()
                    if fam == "duke_probe_verdict_mismatches_total")
        assert total >= 1

        status, body = request(f"{base}/debug/probes")
        dbg = json.loads(body)
        assert len(dbg["mismatches"]) >= 1
        rec = dbg["mismatches"][0]
        assert rec["expected"] != rec["observed"]
        assert rec["trace"].startswith("/debug/traces/")
        # latched: the first mismatch survives any amount of green churn
        assert app.prober.ring.records()

        # a clean follow-up cycle heals the feed but the latch stays
        faults.configure("")
        app.prober.run_cycle()
        status, body = request(f"{base}/healthz")
        assert json.loads(body)["status"] == "degraded"


# -- federation: per-range probing ---------------------------------------------


class TestRangeProber:
    def test_fed_down_flags_only_that_groups_ranges(self, tmp_path):
        from sesam_duke_microservice_tpu.service.prober import RangeProber

        fed = make_fed(tmp_path, n_groups=2)
        try:
            prober = RangeProber(fed)
            out = prober.run_cycle()
            assert out and all(v == "ok" for v in out.values())
            assert prober.failing_ranges() == []

            faults.configure("fed_down=1")
            out = prober.run_cycle()
            down = sorted(r.range_id for r in fed.map.ranges()
                          if r.group == 1)
            up = sorted(r.range_id for r in fed.map.ranges()
                        if r.group == 0)
            assert sorted(r for r, v in out.items() if v == "fail") == down
            assert all(out[r] == "ok" for r in up)
            assert prober.failing_ranges() == down
            snap = prober.snapshot()
            for rid in down:
                assert snap["ranges"][rid]["last_error"] == "GroupUnavailable"
        finally:
            faults.configure("")
            fed.close()

    def test_plane_healthz_and_rollup_surface_range_failures(self, tmp_path):
        from sesam_duke_microservice_tpu.service.federation_plane import (
            serve_federation,
        )

        fed = make_fed(tmp_path, n_groups=2)
        server = serve_federation(fed)
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            prober = server.RequestHandlerClass.range_prober
            assert prober is not None
            prober.run_cycle()
            faults.configure("fed_down=1")
            prober.run_cycle()
            faults.configure("")

            status, body = request(f"{base}/healthz")
            health = json.loads(body)
            down = sorted(r.range_id for r in fed.map.ranges()
                          if r.group == 1)
            assert health["status"] == "degraded"
            assert health["probe_failing_ranges"] == down

            status, body = request(f"{base}/metrics")
            metrics = parse_exposition(body.decode())
            for rng in fed.map.ranges():
                fails = metrics[("duke_probe_range_checks_total",
                                 (("group", str(rng.group)),
                                  ("outcome", "fail"),
                                  ("range", rng.range_id)))]
                assert fails == (1 if rng.group == 1 else 0)

            status, body = request(f"{base}/debug/probes")
            dbg = json.loads(body)
            assert dbg["enabled"] and dbg["cycles"] == 2
        finally:
            server.shutdown()
            fed.close()


# -- shared AOT ladder: zero probe compiles ------------------------------------


class TestSharedLadder:
    def test_probe_cycle_adds_zero_jit_compiles(self, monkeypatch):
        """The probe shadow shares Property objects with the user
        workload, so its plan fingerprint resolves to the SAME shared
        AOT ladder — a full probe cycle on the device backend must not
        add a single XLA compile."""
        monkeypatch.setenv("DEVICE_PREWARM", "1")
        app = make_app(DEDUP_ONLY_XML, backend="device")
        try:
            wl = app.deduplications["people"]
            t = getattr(wl.index.scorer_cache, "_warm_thread", None)
            if t is not None:
                t.join(timeout=600)
            app.scheduler.submit(
                "deduplication", "people", "crm", list(USER_BATCH))
            before = telemetry.JIT_COMPILES.single().value
            results = app.prober.run_cycle()
            assert results[("deduplication", "people")]["ok"]
            assert telemetry.JIT_COMPILES.single().value == before
            state = app.prober._shadows[("deduplication", "people")].state
            assert state.probe_compiles == 0
        finally:
            app.close()
