"""Native (C++) comparator parity against the pure-Python oracles.

The pure-Python bodies in core/comparators.py are the semantic reference
(they in turn pin the reference's Duke 1.2 comparator behavior); the ctypes
library must agree on every pair, including empty strings, unicode, and
lengths crossing the Myers 64-codepoint boundary.
"""

import random

import numpy as np
import pytest

from sesam_duke_microservice_tpu import native
from sesam_duke_microservice_tpu.core import comparators as C

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native comparator library unavailable"
)

ALPHABET = "abcdefgh 0123456789åßñ漢字"


def _rand_string(rng, max_len=100):
    n = rng.randint(0, max_len)
    return "".join(rng.choice(ALPHABET) for _ in range(n))


def _pairs(seed=7, n=300, max_len=100):
    rng = random.Random(seed)
    pairs = [("", ""), ("", "abc"), ("abc", ""), ("same", "same"),
             ("a" * 70, "a" * 69 + "b"), ("x" * 65, "y" * 65)]
    for _ in range(n):
        a = _rand_string(rng, max_len)
        # half the pairs are mutations of a (realistic near-duplicates)
        if rng.random() < 0.5:
            b = list(a)
            for _ in range(rng.randint(0, 4)):
                if not b:
                    break
                op = rng.choice("ids")
                pos = rng.randrange(len(b))
                if op == "i":
                    b.insert(pos, rng.choice(ALPHABET))
                elif op == "d":
                    del b[pos]
                else:
                    b[pos] = rng.choice(ALPHABET)
            b = "".join(b)
        else:
            b = _rand_string(rng, max_len)
        pairs.append((a, b))
    return pairs


@pytest.fixture
def pure(monkeypatch):
    """Force the pure-Python comparator path."""
    monkeypatch.setattr(C, "_NATIVE", None)
    yield
    # monkeypatch restores _NATIVE (back to the resolved module)


def test_levenshtein_parity(pure):
    lev = C.Levenshtein()
    pairs = _pairs()
    expected = [lev.compare(a, b) for a, b in pairs]
    got = native.lev_sim_batch([a for a, _ in pairs], [b for _, b in pairs])
    for (a, b), e, g in zip(pairs, expected, got):
        assert abs(e - g) < 1e-12, (a, b, e, g)


def test_jaro_winkler_parity(pure):
    jw = C.JaroWinkler()
    pairs = _pairs(seed=11)
    expected = [jw.compare(a, b) for a, b in pairs]
    got = native.jaro_winkler_batch([a for a, _ in pairs],
                                    [b for _, b in pairs])
    for (a, b), e, g in zip(pairs, expected, got):
        assert abs(e - g) < 1e-12, (a, b, e, g)


def test_jaro_winkler_custom_params_parity(pure):
    jw = C.JaroWinkler()
    jw.prefix_scale = 0.2
    jw.boost_threshold = 0.5
    jw.max_prefix = 2
    pairs = _pairs(seed=13, n=100, max_len=30)
    for a, b in pairs:
        e = jw.compare(a, b)
        g = float(native.jaro_winkler_batch(
            [a], [b], prefix_scale=0.2, boost_threshold=0.5, max_prefix=2)[0])
        assert abs(e - g) < 1e-12, (a, b, e, g)


def test_weighted_levenshtein_parity_ascii(pure):
    wl = C.WeightedLevenshtein()
    rng = random.Random(17)
    ascii_alphabet = "abc XY12345-#"
    for _ in range(200):
        a = "".join(rng.choice(ascii_alphabet) for _ in range(rng.randint(0, 40)))
        b = "".join(rng.choice(ascii_alphabet) for _ in range(rng.randint(0, 40)))
        e = wl.compare(a, b)
        g = float(native.weighted_lev_batch([a], [b])[0])
        assert abs(e - g) < 1e-12, (a, b, e, g)


def test_native_dispatch_used_by_comparators():
    """With the library available the comparator classes route through it
    and still produce oracle-identical values (spot check)."""
    assert C._native_module() is not None
    lev = C.Levenshtein()
    assert lev.compare("jonathan smithe", "jonathan smith") == pytest.approx(
        1.0 - 1.0 / 14.0, abs=1e-12
    )
    jw = C.JaroWinkler()
    assert jw.compare("martha", "marhta") == pytest.approx(0.9611111111, abs=1e-9)


def test_lev_distance_exact():
    assert native.lev_distance("kitten", "sitting") == 3
    assert native.lev_distance("", "abc") == 3
    assert native.lev_distance("a" * 80, "a" * 79 + "b") == 1


def test_native_handles_lone_surrogates():
    """json.loads accepts lone surrogates ('"\\ud800abc"'); the native path
    must score them identically to pure Python instead of raising
    UnicodeEncodeError (utf-32 surrogatepass encoding)."""
    from sesam_duke_microservice_tpu.core import comparators as C

    s1 = "\ud800abc"
    s2 = "xabc"
    lev = C.Levenshtein()
    jw = C.JaroWinkler()
    saved = C._NATIVE
    C._NATIVE = None
    try:
        want_lev = lev.compare(s1, s2)
        want_jw = jw.compare(s1, s2)
    finally:
        C._NATIVE = saved
    assert lev.compare(s1, s2) == pytest.approx(want_lev)
    assert jw.compare(s1, s2) == pytest.approx(want_jw)

    from sesam_duke_microservice_tpu import native

    if native.available():
        assert native.lev_sim(s1, s2) == pytest.approx(want_lev)


def test_embed_batch_matches_numpy_oracle():
    from sesam_duke_microservice_tpu import native
    from sesam_duke_microservice_tpu.ops import encoder as E

    if not native.available():
        pytest.skip("native library unavailable")

    from test_device_matcher import dedup_schema, random_records

    schema = dedup_schema()
    enc = E.RecordEncoder(schema, 128)
    records = random_records(120, seed=9)
    # unicode + empty-field coverage
    records[0].set_values("name", ["åse blåbærsyltetøy 中文"])
    records[1].set_values("name", [""])

    nat = enc.encode_batch(records)
    saved = E._native_embed
    try:
        E._native_embed = lambda: None
        ref = enc.encode_batch(records)
    finally:
        E._native_embed = saved
    np.testing.assert_allclose(nat, ref, atol=1e-6)
