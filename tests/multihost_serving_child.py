"""Child process for the two-process multi-host SERVING test.

Each child is one process of a 2-process jax.distributed job with 2
virtual CPU devices (global mesh = 4).  Both enter the real service
entrypoint (``service.__main__.main``): process 0 becomes the HTTP
frontend + op dispatcher, process 1 the follower replay loop — exactly
the production multi-host path of parallel/dispatch.py.

Usage: multihost_serving_child.py <process_id> <coordinator> <http_port>
       <backend>

Env contract (set by the parent): CONFIG_STRING, DEVICE_* shape knobs
identical across processes, DUKE_DISPATCH_HOST=127.0.0.1.
"""

import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=2"
).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    process_id = int(sys.argv[1])
    coordinator = sys.argv[2]
    http_port = sys.argv[3]
    backend = sys.argv[4]

    os.environ["JAX_COORDINATOR_ADDRESS"] = coordinator
    os.environ["JAX_NUM_PROCESSES"] = "2"
    os.environ["JAX_PROCESS_ID"] = str(process_id)

    sys.argv = [
        "duke-service", "--port", http_port, "--host", "127.0.0.1",
        "--backend", backend,
    ]
    from sesam_duke_microservice_tpu.service.__main__ import main as svc_main

    svc_main()


if __name__ == "__main__":
    main()
