"""Ring scorer vs single-device scorer on the virtual 8-device CPU mesh.

Contract (parallel/ring.py): with queries AND corpus sharded, D ppermute
hops return each query block to its home device carrying the same global
top-K the single-device scorer computes over the concatenated corpus.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sesam_duke_microservice_tpu.ops import scoring as S
from sesam_duke_microservice_tpu.parallel import (
    RingQueryPlacer,
    ShardedCorpus,
    build_ring_scorer,
    corpus_mesh,
)

from test_parallel import CHUNK, TOP_K, build_inputs


@pytest.fixture(scope="module")
def mesh():
    assert jax.device_count() == 8, "conftest must force 8 virtual CPU devices"
    return corpus_mesh()


def _run_ring(mesh, n_corpus, n_queries, group=None, query_group_np=None,
              group_filtering=False):
    (plan, feats, valid, deleted, grp,
     qfeats, query_row, query_group) = build_inputs(n_corpus, n_queries)
    if group is not None:
        grp = group
    if query_group_np is not None:
        query_group = query_group_np

    placer = ShardedCorpus(mesh, chunk=CHUNK)
    sfeats, svalid, sdeleted, sgroup = placer.place(
        feats, valid, deleted, grp
    )
    qplacer = RingQueryPlacer(mesh)
    rqfeats, rqgroup, rqrow = qplacer.place(qfeats, query_group, query_row)
    ring = build_ring_scorer(
        plan, mesh, chunk=CHUNK, top_k=TOP_K,
        group_filtering=group_filtering,
    )
    r_logit, r_index, r_count = ring(
        rqfeats, sfeats, svalid, sdeleted, sgroup, rqgroup, rqrow,
        jnp.float32(-5.0),
    )
    # single-device reference over the same padded corpus
    cap = placer.padded_capacity(n_corpus)

    def pad(a, fill=0):
        out = np.full((cap,) + a.shape[1:], fill, dtype=a.dtype)
        out[:n_corpus] = a
        return out

    single = S.build_corpus_scorer(
        plan, chunk=CHUNK, top_k=TOP_K, group_filtering=group_filtering
    )
    qf = {p: {k: jnp.asarray(a) for k, a in t.items()}
          for p, t in qfeats.items()}
    d_logit, d_index, d_count = single(
        qf,
        {p: {k: jnp.asarray(pad(a)) for k, a in t.items()}
         for p, t in feats.items()},
        jnp.asarray(pad(valid, False)), jnp.asarray(pad(deleted, False)),
        jnp.asarray(pad(grp, -1)),
        jnp.asarray(query_group), jnp.asarray(query_row),
        jnp.float32(-5.0),
    )
    n = n_queries
    return (np.asarray(r_logit)[:n], np.asarray(r_index)[:n],
            np.asarray(r_count)[:n], np.asarray(d_logit),
            np.asarray(d_index), np.asarray(d_count))


def test_ring_matches_single_device(mesh):
    n = 8 * CHUNK * 2   # 2 chunks per shard
    n_queries = 16      # 2 queries per device
    (r_log, r_idx, r_cnt, d_log, d_idx, d_cnt) = _run_ring(
        mesh, n, n_queries
    )
    np.testing.assert_allclose(r_log, d_log, rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(r_cnt, d_cnt)
    # tie rows can order differently across hop boundaries; rows scoring
    # strictly above the K-th score are unambiguous and must agree
    for qi in range(n_queries):
        kth = d_log[qi, -1]
        strict_d = {int(r) for r, v in zip(d_idx[qi], d_log[qi])
                    if v > kth + 1e-4}
        strict_r = {int(r) for r, v in zip(r_idx[qi], r_log[qi])
                    if v > kth + 1e-4}
        assert strict_d == strict_r


def test_ring_group_filtering_and_self_exclusion(mesh):
    n = 8 * CHUNK
    n_queries = 16
    group = np.asarray([1 + (i % 2) for i in range(n)], dtype=np.int32)
    qgroup = np.asarray([1 + (i % 2) for i in range(n_queries)],
                        dtype=np.int32)
    (r_log, r_idx, _, d_log, _, _) = _run_ring(
        mesh, n, n_queries, group=group, query_group_np=qgroup,
        group_filtering=True,
    )
    np.testing.assert_allclose(r_log, d_log, rtol=1e-5, atol=1e-5)
    for qi in range(n_queries):
        live = r_idx[qi][r_log[qi] > S.NEG_INF / 2]
        assert qi not in live                       # self-pair exclusion
        for row in live:
            assert group[row] != qgroup[qi]         # group exclusion


def test_ring_query_padding(mesh):
    # query counts not divisible by the mesh size pad cleanly
    n = 8 * CHUNK
    n_queries = 11
    (r_log, _, r_cnt, d_log, _, d_cnt) = _run_ring(mesh, n, n_queries)
    np.testing.assert_allclose(r_log, d_log, rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(r_cnt, d_cnt)
