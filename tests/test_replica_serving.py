"""Replicated link-feed serving (ISSUE 8 tentpole).

Drives a REAL ``Dispatcher`` against real ``_FollowerSession`` replay
loops over loopback sockets — framed ops with epoch/seq fencing, the
commit digest handshake, the published link stream, and the follower
HTTP read plane — without a 2-process jax.distributed job (this host's
jax lacks ``shard_map``, so the suites run the HA machinery on the
single-device ``device``/``ann`` backends; the machinery is
backend-agnostic by construction).

The core claim: a follower's replica link DB, fed only by the bootstrap
``link_state`` + the ``links`` op stream, serves ``?since=`` feed rows
BIT-IDENTICAL to the leader's at the same watermark — including
retractions and one-to-one conflict rewrites — while taking no leader
lock.
"""

import json
import socket
import threading
import time
import urllib.request

import pytest

from sesam_duke_microservice_tpu import telemetry
from sesam_duke_microservice_tpu.core.config import parse_config
from sesam_duke_microservice_tpu.engine.workload import build_workload
from sesam_duke_microservice_tpu.links.replica import (
    ReplicaGap,
    ReplicaLinkDatabase,
    links_feed_page,
)
from sesam_duke_microservice_tpu.parallel import dispatch
from sesam_duke_microservice_tpu.utils import faults

from test_sharded_service import DEDUP_XML, LINKAGE_XML, _seeded_batch

KEY = ("deduplication", "people")

ONE_TO_ONE_XML = LINKAGE_XML.replace(
    'link-mode="many-to-many"', 'link-mode="one-to-one"'
)


@pytest.fixture(autouse=True)
def _no_env_faults():
    """Pin every test to an explicit fault plan (none unless it installs
    one), so the CI chaos leg's DUKE_FAULTS env spec cannot distort
    tests that assert exact eviction/retry behavior."""
    faults.configure("")
    yield
    faults.configure(None)


class LoopbackFollower:
    """One follower replay loop over a socketpair: real framed ops, real
    digest handshake responses, the production ``handle_frame`` fencing."""

    def __init__(self, idx: int = 0):
        self.leader_sock, self.sock = socket.socketpair()
        self.session = dispatch._FollowerSession(self._send,
                                                 follower_idx=idx)
        self.error = None
        self.thread = threading.Thread(target=self._loop, daemon=True)
        self.thread.start()

    def _send(self, frame: bytes) -> None:
        self.sock.sendall(frame)

    def _loop(self) -> None:
        try:
            while True:
                op, epoch, seq = dispatch._recv_op(self.sock)
                if not self.session.handle_frame(op, epoch, seq):
                    return
        except (EOFError, OSError):
            return
        except BaseException as e:  # crash: die hard, like the process
            self.error = e
            try:
                self.sock.close()
            except OSError:
                pass

    def close(self) -> None:
        for s in (self.sock, self.leader_sock):
            try:
                s.close()
            except OSError:
                pass
        self.thread.join(timeout=10)
        self.session.close()


class HaGroup:
    """Leader workloads + dispatcher + N loopback followers, bootstrapped
    exactly like ``Dispatcher.start()`` does it (minus the jax.distributed
    rendezvous)."""

    def __init__(self, xml, backend="device", n_followers=1, env=None):
        sc = parse_config(xml, env=env or {"MIN_RELEVANCE": "0.05"})
        self.sc = sc
        dedups = {
            name: build_workload(wc, sc, backend=backend, persistent=False)
            for name, wc in sc.deduplications.items()
        }
        linkages = {
            name: build_workload(wc, sc, backend=backend, persistent=False)
            for name, wc in sc.record_linkages.items()
        }

        class _App:
            pass

        app = _App()
        app.backend = backend
        app.config_string = sc.config_string
        app.deduplications = dedups
        app.record_linkages = linkages
        self.app = app
        self.dispatcher = dispatch.Dispatcher(app)
        self.followers = [LoopbackFollower(i) for i in range(n_followers)]
        self.dispatcher._conns = [f.leader_sock for f in self.followers]
        self._prev_global = dispatch._DISPATCHER
        dispatch._DISPATCHER = self.dispatcher
        try:
            self.dispatcher._tag_workloads(dedups, linkages)
            self.dispatcher._bootstrap_followers()
        except BaseException:
            self.close()
            raise

    def workload(self, name="people", kind="deduplication"):
        registry = (self.app.deduplications if kind == "deduplication"
                    else self.app.record_linkages)
        return registry[name]

    def ingest(self, batch, dataset="crm", name="people",
               kind="deduplication") -> None:
        wl = self.workload(name, kind)
        with wl.lock:
            wl.process_batch(dataset, batch)

    def wait_applied(self, follower=0, key=KEY, timeout=60) -> None:
        """Block until the follower's replica watermark reaches the
        leader publisher's sequence (links ops carry no handshake)."""
        wl = self.workload(key[1], key[0])
        want = wl.link_database.seq
        session = self.followers[follower].session
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.followers[follower].error is not None:
                raise AssertionError(
                    f"follower died: {self.followers[follower].error!r}"
                )
            db = session.link_replicas.get(key)
            if db is not None and db.applied_seq >= want:
                return
            time.sleep(0.01)
        raise AssertionError(
            f"replica never reached watermark {want} "
            f"(at {session.link_replicas.get(key) and session.link_replicas[key].applied_seq})"
        )

    def replica_feed(self, follower=0, key=KEY, since=0):
        session = self.followers[follower].session
        db = session.link_replicas[key]
        index = session.replicas[key].index
        rows, cursor = [], since
        while True:
            page, cursor = links_feed_page(db, index, cursor, 5000)
            rows.extend(page)
            if not page:
                return rows

    def leader_feed(self, name="people", kind="deduplication", since=0):
        wl = self.workload(name, kind)
        with wl.lock:
            return wl.links_since(since)

    def close(self) -> None:
        dispatch._DISPATCHER = self._prev_global
        try:
            self.dispatcher.close()
        finally:
            for f in self.followers:
                f.close()
            for registry in (self.app.deduplications,
                             self.app.record_linkages):
                for wl in registry.values():
                    try:
                        wl.close()
                    except Exception:
                        pass


@pytest.fixture
def group(request):
    g = HaGroup(DEDUP_XML, backend=getattr(request, "param", "device"))
    try:
        yield g
    finally:
        g.close()


# -- feed parity --------------------------------------------------------------


@pytest.mark.parametrize("group", ["device", "ann"], indirect=True)
def test_replica_feed_parity_with_retractions(group):
    """Leader feed vs follower replica feed: bit-identical rows at the
    same watermark, through ingest with duplicates, a second batch, and a
    deletion (link retraction)."""
    group.ingest(_seeded_batch(24))
    group.ingest(_seeded_batch(12, prefix="b"))
    # record "1" is half of the (0,1)-style duplicate structure: deleting
    # it retracts links, which must replicate as first-class rows
    group.ingest([{"_id": "1", "_deleted": True}])
    group.wait_applied()

    leader_rows = group.leader_feed()
    replica_rows = group.replica_feed()
    assert leader_rows == replica_rows  # full dicts: ts, ids, confidences
    assert any(r["_deleted"] for r in leader_rows), "no retraction exercised"
    # and the replica holds the same watermark the leader published
    session = group.followers[0].session
    assert (session.link_replicas[KEY].applied_seq
            == group.workload().link_database.seq)
    assert session.link_replicas[KEY].lag_ops() == 0


def test_replica_feed_parity_one_to_one_rewrites():
    """One-to-one record linkage: conflict resolution retracts weaker
    links and rewrites winners across batches — the rewrite/retract
    churn must replicate bit-identically."""
    g = HaGroup(ONE_TO_ONE_XML, backend="device",
                env={"MIN_RELEVANCE": "0.05"})
    try:
        key = ("recordlinkage", "pairing")
        g.ingest([{"_id": f"L{i}", "name": f"acme systems {i}"}
                  for i in range(6)],
                 dataset="left", name="pairing", kind="recordlinkage")
        # right side: near-duplicates competing for the same left records
        # (forces one-to-one displacement rewrites)
        g.ingest([{"_id": f"R{i}", "name": f"acme systems {i % 3}"}
                  for i in range(6)],
                 dataset="right", name="pairing", kind="recordlinkage")
        g.ingest([{"_id": "R9", "name": "acme systems 0"}],
                 dataset="right", name="pairing", kind="recordlinkage")
        g.wait_applied(key=key)
        leader_rows = g.leader_feed(name="pairing", kind="recordlinkage")
        assert leader_rows  # the fixture must actually produce links
        assert leader_rows == g.replica_feed(key=key)
    finally:
        g.close()


def test_replica_feed_pages_match_leader_at_cursor(group):
    """Paged replica reads honor the same strictly-greater-than cursor
    contract as the leader's."""
    group.ingest(_seeded_batch(24))
    group.wait_applied()
    leader_rows = group.leader_feed()
    assert len(leader_rows) >= 2
    mid_ts = leader_rows[len(leader_rows) // 2 - 1]["_updated"]
    assert (group.replica_feed(since=mid_ts)
            == group.leader_feed(since=mid_ts))


# -- read plane ---------------------------------------------------------------


def _get(url, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, dict(r.headers), r.read()


def test_replica_http_read_plane(group):
    from sesam_duke_microservice_tpu.service.replica_plane import (
        serve_replica_plane,
    )

    group.ingest(_seeded_batch(24))
    group.wait_applied()
    server = serve_replica_plane(group.followers[0].session, port=0,
                                 host="127.0.0.1")
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        status, headers, body = _get(base + "/deduplication/people?since=0")
        assert status == 200
        assert headers.get("X-Replica-Lag") == "0"
        assert json.loads(body) == group.leader_feed()

        status, _, body = _get(base + "/healthz")
        health = json.loads(body)
        assert status == 200 and health["role"] == "replica"
        assert health["replication_lag_ops"] == 0
        assert health["epoch"] == 1

        status, _, body = _get(base + "/readyz")
        assert status == 200

        status, _, body = _get(base + "/stats")
        stats = json.loads(body)
        row = stats["workloads"][0]
        assert row["links_rows"] == len(
            {(r["entity1"], r["entity2"]) for r in group.leader_feed()}
        ) or row["links_rows"] > 0
        assert row["applied_seq"] == group.workload().link_database.seq
        assert row["lag_ops"] == 0

        status, _, body = _get(base + "/metrics")
        text = body.decode()
        assert "duke_replica_lag_ops" in text
        assert 'workload="people"' in text

        status, _, _ = _get(base + "/recordlinkage/nope?since=0")
    except urllib.error.HTTPError as e:
        assert e.code == 400
    finally:
        server.shutdown()


def test_replica_feed_takes_no_leader_lock(group):
    """Acceptance criterion: a replica serves feed pages while the
    leader's workload lock is HELD (a long ingest in flight)."""
    group.ingest(_seeded_batch(24))
    group.wait_applied()
    wl = group.workload()
    expected = group.leader_feed()
    assert wl.lock.acquire(timeout=5)
    try:
        t0 = time.monotonic()
        rows = group.replica_feed()
        elapsed = time.monotonic() - t0
    finally:
        wl.lock.release()
    assert rows == expected
    assert elapsed < 1.0, "replica read waited on something"


# -- stream discipline --------------------------------------------------------


def test_replica_watermark_drops_dups_and_raises_on_gap():
    db = ReplicaLinkDatabase()
    rows1 = [("a", "b", "inferred", "duplicate", 0.9, 1000)]
    rows2 = [("c", "d", "inferred", "duplicate", 0.8, 2000)]
    assert db.apply_ops(1, rows1) is True
    assert db.apply_ops(1, rows1) is False  # duplicate delivery: dropped
    assert db.count() == 1
    db.note_head(3)
    assert db.lag_ops() == 2
    with pytest.raises(ReplicaGap):
        db.apply_ops(3, rows2)  # seq 2 never arrived
    assert db.apply_ops(2, rows2) is True
    assert db.lag_ops() == 1


def test_epoch_fencing_rejects_stale_frames():
    session = dispatch._FollowerSession(lambda frame: None)
    assert session.handle_frame(("bootstrap_end",), 1, 1)
    session.adopt_epoch(2)  # promotion happened elsewhere
    assert session.handle_frame(("bootstrap_end",), 1, 2)  # zombie: dropped
    assert session.stale_rejected == 1
    # dup seq drops silently; gap raises
    assert session.handle_frame(("bootstrap_end",), 2, 2)
    assert session.handle_frame(("bootstrap_end",), 2, 2)  # dup
    with pytest.raises(RuntimeError, match="stream gap"):
        session.handle_frame(("bootstrap_end",), 2, 9)
    session.close()


def test_higher_epoch_adopted_with_fresh_seq_space():
    session = dispatch._FollowerSession(lambda frame: None)
    assert session.handle_frame(("bootstrap_end",), 1, 1)
    # a new leader's stream starts its own seq space
    assert session.handle_frame(("bootstrap_end",), 3, 1)
    assert session.epoch == 3 and session.last_seq == 1
    session.close()


# -- eviction -----------------------------------------------------------------


def test_follower_eviction_degrades_not_latches(group, monkeypatch):
    """Acceptance criterion: one follower's death evicts IT —
    duke_dispatch_down stays 0, duke_follower_evictions_total moves, and
    the survivors keep replicating bit-identically."""
    monkeypatch.setattr(dispatch, "_CONNECT_TIMEOUT_S", 10.0)
    g2 = HaGroup(DEDUP_XML, backend="device", n_followers=2)
    evictions0 = telemetry.FOLLOWER_EVICTIONS.single().value
    try:
        g2.ingest(_seeded_batch(12))
        g2.wait_applied(follower=0)
        g2.wait_applied(follower=1)
        # follower 0 dies (socket torn, replay loop gone)
        g2.followers[0].sock.close()
        g2.ingest(_seeded_batch(6, prefix="b"))
        assert g2.dispatcher._failed is None
        assert telemetry.DISPATCH_DOWN.single().value == 0
        assert telemetry.FOLLOWER_EVICTIONS.single().value == evictions0 + 1
        assert len(g2.dispatcher.live_followers()) == 1
        g2.wait_applied(follower=1)
        assert g2.replica_feed(follower=1) == g2.leader_feed()
        # and the leader keeps accepting writes afterward
        g2.ingest(_seeded_batch(3, prefix="c"))
        g2.wait_applied(follower=1)
        assert g2.replica_feed(follower=1) == g2.leader_feed()
    finally:
        g2.close()
