"""Streaming-encode tests (ISSUE 4): digest-keyed feature cache +
extract/upload-overlap append.

Contracts held here:

  * the feature cache is INVISIBLE in every output: listener event
    sequences and link rows are identical with ``DUKE_FEATURE_CACHE_MB``
    in {0, default} on the device and ANN backends, including a resync
    pass (re-POST of identical record content) that serves from cache;
  * the plan fingerprint self-invalidates on value-slot widening (and
    any extraction-shaping change) — stale rows can never scatter into a
    corpus built under a different plan;
  * the byte budget evicts LRU and is actually respected;
  * slice-streamed append produces a host mirror, row mapping, and event
    stream bit-identical to the whole-batch append;
  * the incremental corpus live-row counter matches the mask formula it
    replaced through appends, re-upserts, and deletes.
"""

import random

import numpy as np
import pytest

from sesam_duke_microservice_tpu.core import comparators as C
from sesam_duke_microservice_tpu.core.config import (
    DukeSchema,
    MatchTunables,
)
from sesam_duke_microservice_tpu.core.records import (
    ID_PROPERTY_NAME,
    Property,
    Record,
)
from sesam_duke_microservice_tpu.engine import device_matcher as DM
from sesam_duke_microservice_tpu.engine.ann_matcher import (
    AnnIndex,
    AnnProcessor,
)
from sesam_duke_microservice_tpu.engine.device_matcher import (
    DeviceIndex,
    DeviceProcessor,
)
from sesam_duke_microservice_tpu.engine.listeners import (
    LinkMatchListener,
    MatchListener,
)
from sesam_duke_microservice_tpu.index.inverted import InvertedIndex
from sesam_duke_microservice_tpu.links import InMemoryLinkDatabase
from sesam_duke_microservice_tpu.ops import feature_cache as FC
from sesam_duke_microservice_tpu.ops import features as F


def dedup_schema():
    numeric = C.Numeric()
    numeric.min_ratio = 0.5
    return DukeSchema(
        threshold=0.8,
        maybe_threshold=0.6,
        properties=[
            Property(ID_PROPERTY_NAME, id_property=True),
            Property("name", C.Levenshtein(), 0.3, 0.9),
            Property("city", C.Exact(), 0.4, 0.8),
            Property("amount", numeric, 0.4, 0.7),
        ],
        data_sources=[],
    )


def make_record(rid, **props):
    r = Record()
    r.add_value(ID_PROPERTY_NAME, rid)
    for k, v in props.items():
        vals = v if isinstance(v, list) else [v]
        for one in vals:
            r.add_value(k, one)
    return r


NAMES = [
    "acme corp", "acme corporation", "globex", "globex inc", "initech",
    "initech llc", "umbrella", "umbrela", "stark industries", "stark ind",
]
CITIES = ["oslo", "bergen", "trondheim"]


def random_records(n, seed, prefix="r"):
    """Deterministic content: regenerating with the same arguments yields
    FRESH Record objects with identical ids/values — the resync shape
    (digests recompute, then hit)."""
    rng = random.Random(seed)
    records = []
    for i in range(n):
        base = rng.choice(NAMES)
        if rng.random() < 0.4:
            pos = rng.randrange(len(base))
            base = base[:pos] + rng.choice("abcdefgh") + base[pos + 1:]
        records.append(make_record(
            f"{prefix}{i}",
            name=base,
            city=rng.choice(CITIES),
            amount=str(rng.choice([100, 200, 200, 300, 1000])),
        ))
    return records


class OrderedLog(MatchListener):
    def __init__(self):
        self.events = []

    def matches(self, r1, r2, confidence):
        self.events.append(
            ("match", r1.record_id, r2.record_id, round(confidence, 12)))

    def matches_perhaps(self, r1, r2, confidence):
        self.events.append(
            ("maybe", r1.record_id, r2.record_id, round(confidence, 12)))

    def no_match_for(self, record):
        self.events.append(("none", record.record_id))


@pytest.fixture
def cache_env(monkeypatch):
    """Cache control: yields a setter that re-points the process cache;
    always resets after the test so suite-wide state stays whatever the
    session env says."""

    def set_mb(mb):
        monkeypatch.setenv("DUKE_FEATURE_CACHE_MB", str(mb))
        FC.reset()
        return FC.active()

    yield set_mb
    FC.reset()


def _backend(kind, schema):
    if kind == "ann":
        index = AnnIndex(schema, dim=32)
        return index, AnnProcessor(schema, index)
    index = DeviceIndex(schema)
    return index, DeviceProcessor(schema, index)


def _pipeline(kind, schema, batches):
    """Run ``batches`` (lists of records) through a fresh backend; returns
    (event tape, link rows, index)."""
    index, proc = _backend(kind, schema)
    log = OrderedLog()
    db = InMemoryLinkDatabase()
    proc.add_match_listener(log)
    proc.add_match_listener(LinkMatchListener(db))
    for batch in batches:
        proc.deduplicate(batch)
    rows = sorted(
        (l.id1, l.id2, l.status.value, l.kind.value, round(l.confidence, 12))
        for l in db.get_all_links()
    )
    return log.events, rows, index


@pytest.mark.parametrize("kind", ["device", "ann"])
def test_cache_on_off_event_and_link_parity(kind, cache_env):
    """Identical event streams + link rows with the cache off vs on —
    including a resync pass that actually serves from the cache."""
    schema = dedup_schema()
    batches = lambda: [  # noqa: E731
        random_records(40, seed=7),
        random_records(12, seed=8, prefix="s"),
        random_records(40, seed=7),  # resync: same ids, same content
    ]

    cache_env(0)
    assert FC.active() is None
    events_off, links_off, _ = _pipeline(kind, schema, batches())

    cache = cache_env(64)
    events_on, links_on, _ = _pipeline(kind, schema, batches())

    assert events_on == events_off
    assert links_on == links_off
    # the resync pass re-encoded 40 unchanged records from the cache
    assert cache.hits >= 40


def test_resync_hits_all_rows(cache_env):
    cache = cache_env(64)
    schema = dedup_schema()
    index, proc = _backend("device", schema)
    proc.deduplicate(random_records(30, seed=3))
    hits0, misses0 = cache.hits, cache.misses
    proc.deduplicate(random_records(30, seed=3))
    assert cache.hits - hits0 == 30
    assert cache.misses == misses0
    # re-upserts tombstone + append: corpus holds both generations
    assert index.corpus.size == 60
    assert index.corpus.live_rows == 30


def test_query_probe_extraction_uses_cache(cache_env):
    """Query-side _extract (http-transform shape) hits when the query plan
    matches the plan rows were cached under."""
    cache = cache_env(64)
    schema = dedup_schema()
    index, proc = _backend("device", schema)
    proc.deduplicate(random_records(20, seed=5))
    hits0 = cache.hits
    probes = random_records(20, seed=5)
    qplan = index._query_plan(probes)
    out = index._extract(probes, plan=qplan)
    # single-valued probes -> query plan == corpus plan -> all hits
    assert cache.hits - hits0 == 20
    direct = F._extract_direct(qplan, probes)
    for prop, tensors in direct.items():
        for name, arr in tensors.items():
            np.testing.assert_array_equal(out[prop][name], arr)


def test_plan_fingerprint_invalidates_on_widening(cache_env):
    """Value-slot widening changes the fingerprint, so pre-widening rows
    can never scatter into post-widening tensors — and the widened
    extraction is correct."""
    cache = cache_env(64)
    schema = dedup_schema()
    index, proc = _backend("device", schema)
    singles = random_records(16, seed=11)
    proc.deduplicate(singles)
    fp_before = FC.plan_fingerprint(index.plan)

    # a two-valued name widens the plan's value axis (auto-sized); the
    # corpus rebuild re-extracts every stored record under the NEW
    # fingerprint — all misses, no pre-widening row is ever reused
    hits0, misses0 = cache.hits, cache.misses
    proc.deduplicate([make_record(
        "wide0", name=["acme corp", "acme corporation"],
        city="oslo", amount="100",
    )])
    fp_after = FC.plan_fingerprint(index.plan)
    assert fp_before != fp_after
    assert index.plan.device_props[0].values_per_record > 1
    assert cache.hits == hits0
    assert cache.misses - misses0 >= 17  # 16 rebuilt + the widening record

    # resync under the widened plan: served from the rebuild-warmed
    # entries, bit-identical to a direct widened extraction
    hits1 = cache.hits
    fresh = random_records(16, seed=11)
    out = F.extract_batch(index.plan, fresh)
    assert cache.hits - hits1 == 16
    direct = F._extract_direct(index.plan, fresh)
    for prop, tensors in direct.items():
        for name, arr in tensors.items():
            np.testing.assert_array_equal(out[prop][name], arr)


def test_threshold_only_change_keeps_fingerprint():
    """low/high retunes (config reload) must NOT invalidate cached rows —
    they shape scoring, not extraction."""
    schema = dedup_schema()
    plan_a = F.SchemaFeatures.plan(schema)
    retuned = dedup_schema()
    for p in retuned.properties:
        if p.name == "name":
            p.low, p.high = 0.25, 0.95
    plan_b = F.SchemaFeatures.plan(retuned)
    assert FC.plan_fingerprint(plan_a) == FC.plan_fingerprint(plan_b)


def _fake_row(nbytes):
    return {"p": {"t": np.zeros((max(1, nbytes // 8),), dtype=np.int64)}}


def test_byte_budget_eviction():
    budget = 10 * 1024
    cache = FC.FeatureCache(budget)
    row_bytes = 1024
    fp = ("fp",)
    for i in range(20):
        cache.put_many(fp, [(b"d%02d" % i, _fake_row(row_bytes))])
    assert cache.bytes <= budget
    assert cache.evicted > 0
    assert len(cache) < 20
    # LRU: the oldest digests are the evicted ones; the newest survive
    assert cache.get_many(fp, [b"d00"]) == {}
    assert 0 in cache.get_many(fp, [b"d19"])
    # a get refreshes recency: touch an old survivor, insert more, and it
    # outlives untouched peers inserted after it
    survivors = [d for d in (b"d%02d" % i for i in range(20))
                 if cache.get_many(("fp",), [d])]
    victim = survivors[0]
    cache.get_many(fp, [victim])
    cache.put_many(fp, [(b"x%02d" % i, _fake_row(row_bytes))
                        for i in range(len(survivors) - 1)])
    assert 0 in cache.get_many(fp, [victim])
    # an over-budget single row is refused, not thrashed
    cache.put_many(fp, [(b"huge", _fake_row(budget * 2))])
    assert cache.get_many(fp, [b"huge"]) == {}


def test_replacing_same_digest_does_not_leak_bytes():
    cache = FC.FeatureCache(1 << 20)
    for _ in range(5):
        cache.put_many(("fp",), [(b"dig", _fake_row(2048))])
    assert len(cache) == 1
    assert cache.bytes < 2 * (2048 + 1024)


def test_stream_append_equivalence(cache_env, monkeypatch):
    """Slice-streamed append == whole-batch append: host mirror, row
    mapping, masks, and the scored event stream are bit-identical."""
    schema = dedup_schema()
    cache_env(0)  # isolate streaming from the cache

    monkeypatch.setenv("DUKE_STREAM_APPEND", "0")
    events_whole, links_whole, idx_whole = _pipeline(
        "device", schema,
        [random_records(40, seed=21), random_records(24, seed=22, prefix="s")],
    )

    monkeypatch.setattr(DM, "_UPDATE_SLICE", 8)
    monkeypatch.setenv("DUKE_STREAM_APPEND", "1")
    assert DM._stream_append_slice(40) == 8
    events_stream, links_stream, idx_stream = _pipeline(
        "device", schema,
        [random_records(40, seed=21), random_records(24, seed=22, prefix="s")],
    )

    assert events_stream == events_whole
    assert links_stream == links_whole
    assert idx_stream.id_to_row == idx_whole.id_to_row
    a, b = idx_whole.corpus, idx_stream.corpus
    assert a.size == b.size
    np.testing.assert_array_equal(a.row_valid[:a.size], b.row_valid[:b.size])
    np.testing.assert_array_equal(
        a.row_deleted[:a.size], b.row_deleted[:b.size])
    assert a.row_ids == b.row_ids
    for prop, tensors in a.feats.items():
        for name, arr in tensors.items():
            np.testing.assert_array_equal(
                arr[:a.size], b.feats[prop][name][:b.size])


def test_stream_append_slice_sizing(monkeypatch):
    monkeypatch.setenv("DUKE_STREAM_APPEND", "0")
    assert DM._stream_append_slice(10_000) is None
    monkeypatch.setenv("DUKE_STREAM_APPEND", "1")
    monkeypatch.setattr(DM, "_UPDATE_SLICE", 512)
    assert DM._stream_append_slice(512) is None  # nothing to overlap
    assert DM._stream_append_slice(513) == 512
    # a slab that qualifies for the process-pool fan-out keeps it: slices
    # grow to the parallel-extract minimum
    monkeypatch.setenv("DEVICE_EXTRACT_WORKERS", "4")
    monkeypatch.setenv("DEVICE_EXTRACT_PARALLEL_MIN", "2048")
    assert DM._stream_append_slice(10_000) == 2048


def test_live_rows_counter_matches_mask_formula(cache_env):
    cache_env(0)
    schema = dedup_schema()
    index, proc = _backend("device", schema)

    def oracle(corpus):
        return int(corpus.row_valid.sum()
                   - corpus.row_deleted[corpus.row_valid].sum())

    proc.deduplicate(random_records(20, seed=31))
    assert index.corpus.live_rows == oracle(index.corpus) == 20
    # re-upsert half (tombstone + append) and delete a few
    proc.deduplicate(random_records(10, seed=31))
    assert index.corpus.live_rows == oracle(index.corpus) == 20
    for r in random_records(5, seed=31):
        index.delete(r)
    assert index.corpus.live_rows == oracle(index.corpus) == 15
    # dukeDeleted records append as non-live rows
    tomb = make_record("t0", name="acme corp", city="oslo", amount="100")
    tomb.add_value("dukeDeleted", "true")
    index.index(tomb)
    index.commit()
    assert index.corpus.live_rows == oracle(index.corpus) == 15


def test_inverted_grow_and_retry_matches_direct_big_limit():
    """heapq top-limit selection: the adaptive grow-and-retry loop returns
    the same candidates, in the same order, as starting at the maximum
    limit (the full-sort oracle)."""
    schema = DukeSchema(
        threshold=0.8,
        maybe_threshold=None,
        properties=[
            Property(ID_PROPERTY_NAME, id_property=True),
            Property("name", C.Levenshtein(), 0.3, 0.9),
        ],
        data_sources=[],
    )
    tunables = MatchTunables()
    tunables.min_relevance = 0.0
    tunables.max_search_hits = 1000

    def build():
        idx = InvertedIndex(schema, tunables=tunables)
        rng = random.Random(99)
        for i in range(120):
            # shared + distinct tokens -> a large candidate set with a
            # spread of tf-idf scores (ties broken by slot)
            name = "shared " + " ".join(
                rng.choice(["alpha", "beta", "gamma", "delta"])
                for _ in range(rng.randint(1, 4))
            )
            idx.index(make_record(f"i{i}", name=name))
        idx.commit()
        return idx

    probe = make_record("q0", name="shared alpha beta")
    small = build()
    small._estimator.limit = 2  # forces the grow-and-retry path
    got_small = [r.record_id for r in small.find_candidate_matches(probe)]
    big = build()
    big._estimator.limit = 1000
    got_big = [r.record_id for r in big.find_candidate_matches(probe)]
    assert len(got_big) > 10
    assert got_small == got_big


def test_cached_extract_mixed_hit_miss_bit_identical(cache_env):
    """A batch that is part hits, part misses assembles tensors identical
    to a direct extraction of the whole batch."""
    cache_env(64)
    schema = dedup_schema()
    plan = F.SchemaFeatures.plan(schema)
    first = random_records(10, seed=41)
    F.extract_batch(plan, first)  # populate
    mixed = random_records(10, seed=41) + random_records(7, seed=42, prefix="m")
    rng = random.Random(4)
    rng.shuffle(mixed)
    out = F.extract_batch(plan, mixed)
    direct = F._extract_direct(plan, mixed)
    assert set(out) == set(direct)
    for prop, tensors in direct.items():
        assert set(out[prop]) == set(tensors)
        for name, arr in tensors.items():
            np.testing.assert_array_equal(out[prop][name], arr)


def test_records_without_ids_bypass_cache(cache_env):
    cache = cache_env(64)
    schema = dedup_schema()
    plan = F.SchemaFeatures.plan(schema)
    r = Record()
    r.add_value("name", "acme corp")
    out = F.extract_batch(plan, [r])
    assert len(cache) == 0
    direct = F._extract_direct(plan, [r])
    for prop, tensors in direct.items():
        for name, arr in tensors.items():
            np.testing.assert_array_equal(out[prop][name], arr)
