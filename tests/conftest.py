"""Test configuration.

Device-touching tests run on a virtual 8-device CPU mesh so the multi-chip
sharding paths execute in CI without TPU hardware (the driver separately
dry-runs the multi-chip path; see __graft_entry__.py).  Setting the XLA flags
must happen before jax initializes, hence the env mutation at import time.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"

# Small device-matcher shapes: the CPU backend executes the brute-force
# scorer orders of magnitude slower than a TPU; production defaults
# (chunk=512, buckets up to 256) are sized for the MXU/VPU.
os.environ.setdefault("DEVICE_CHUNK", "64")
os.environ.setdefault("DEVICE_QUERY_BUCKETS", "8,32")
os.environ.setdefault("DEVICE_TOP_K", "16")
os.environ.setdefault("DEVICE_MAX_CHARS", "24")
os.environ.setdefault("DEVICE_MAX_GRAMS", "24")
# background compile pre-warm off by default in tests (it competes with the
# slow CPU-interpret compiles); test_device_matcher re-enables it explicitly
os.environ.setdefault("DEVICE_PREWARM", "0")
# canary prober (ISSUE 20): keep the background probe cycle from firing
# mid-test — probe suites drive run_cycle() synchronously, and every
# other suite should see an idle prober (no shadow builds, no probe
# traces in the flight recorder)
os.environ.setdefault("DUKE_PROBE_INTERVAL_S", "3600")
# AOT executable store (ISSUE 15): point at a session-scoped temp dir so
# test runs never write the operator's ~/.cache (subprocess-differential
# tests pin their own DUKE_AOT_DIR); removed at interpreter exit so dev
# boxes don't accumulate serialized-executable dirs across runs
import atexit  # noqa: E402
import shutil  # noqa: E402
import tempfile  # noqa: E402

if "DUKE_AOT_DIR" not in os.environ:
    _aot_tmp = tempfile.mkdtemp(prefix="duke-aot-tests-")
    os.environ["DUKE_AOT_DIR"] = _aot_tmp
    atexit.register(shutil.rmtree, _aot_tmp, True)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The axon sitecustomize hook imports jax at interpreter startup (before
# this conftest runs), so the JAX_PLATFORMS env mutation above is too late
# for jax's config read.  The backend itself initializes lazily — forcing
# the platform via config still works as long as no computation has run.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_sessionfinish(session, exitstatus):
    """Sanitizer legs: a recorded lock-order inversion (DUKE_LOCKCHECK=1)
    or certified-numerics violation (DUKE_NUMCHECK=1) fails the whole
    session even if every individual test passed — the sanitizers
    validate committed invariants (the static lock hierarchy, the
    certified margin bounds), not any one test."""
    from sesam_duke_microservice_tpu.utils import lockcheck, numcheck

    # DUKE_NUMCHECK leg: any certified-vs-oracle disagreement or
    # margin-bound violation recorded during the run fails it (checked
    # unconditionally — injection tests reset() their deliberate
    # violations, so anything left here is real)
    numfound = numcheck.violations()
    if numfound:
        print("\nnumcheck: certified-numerics violations recorded:")
        for line in numfound:
            print("  " + line)
        session.exitstatus = 1

    if not lockcheck.enabled():
        return
    found = lockcheck.inversions()
    if found:
        print("\nlockcheck: lock-order inversions recorded:")
        for line in found:
            print("  " + line)
        session.exitstatus = 1
    rep = lockcheck.report()
    if rep["unknown_edges"]:
        # analyzer drift: the runtime saw a nesting the static graph
        # doesn't model — fail the leg so it gets triaged into
        # MANUAL_EDGES (or the analysis fixed), keeping the committed
        # hierarchy the single source of truth
        print("\nlockcheck: %d observed edge(s) missing from the static "
              "graph (triage scripts/dukecheck/config.py):"
              % len(rep["unknown_edges"]))
        for line in rep["unknown_edges"]:
            print("  " + line)
        session.exitstatus = 1
    if rep["unmapped_lock_edges"]:
        # a lock the hierarchy doc could not even name — naming drift in
        # the static analyzer; advisory until someone extends lockorder's
        # definition extraction for that creation pattern
        print("\nlockcheck: %d observed edge(s) involve a lock with no "
              "static identity (analyzer naming drift):"
              % len(rep["unmapped_lock_edges"]))
        for line in rep["unmapped_lock_edges"]:
            print("  " + line)
