"""Test configuration.

Device-touching tests run on a virtual 8-device CPU mesh so the multi-chip
sharding paths execute in CI without TPU hardware (the driver separately
dry-runs the multi-chip path; see __graft_entry__.py).  Setting the XLA flags
must happen before jax initializes, hence the env mutation at import time.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
