"""dukecheck (ISSUE 7): seeded-violation fixtures for every checker,
the repo-self-scan-matches-baseline gate, and the DUKE_LOCKCHECK runtime
sanitizer's inversion detection.

The fixture tests pin each checker's CONTRACT: a snippet containing a
known violation must produce exactly the expected finding code, and the
cleaned twin must not.  The self-scan test is the CI gate run in-process:
the committed baseline plus inline suppressions must cover every finding
in the live tree (and the committed lock-hierarchy doc must be fresh).
"""

import sys
import textwrap
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from scripts.dukecheck import (  # noqa: E402
    BASELINE_RELPATH,
    collect_findings,
)
from scripts.dukecheck import core as dk_core  # noqa: E402
from scripts.dukecheck import envknob, guardedby, jitpurity  # noqa: E402
from scripts.dukecheck import lockorder, metricwrite  # noqa: E402
from sesam_duke_microservice_tpu.utils import lockcheck  # noqa: E402


def _module(tmp_path: Path, source: str,
            rel: str = "sesam_duke_microservice_tpu/engine/fixture.py"):
    path = tmp_path / rel.replace("/", "_")
    path.write_text(textwrap.dedent(source), encoding="utf-8")
    return dk_core.Module(path, rel)


def _codes(findings):
    return sorted(f.code for f in findings)


# -- checker 1: lock order ----------------------------------------------------


def test_lockorder_cycle_detected(tmp_path):
    mod = _module(tmp_path, """
        import threading


        class A:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def forward(self):
                with self._a:
                    with self._b:
                        pass

            def backward(self):
                with self._b:
                    with self._a:
                        pass
        """)
    findings = lockorder.check([mod], tmp_path)
    assert "DK101" in _codes(findings)
    (cycle,) = [f for f in findings if f.code == "DK101"]
    assert "A._a" in cycle.message and "A._b" in cycle.message


def test_lockorder_nested_order_is_clean(tmp_path):
    mod = _module(tmp_path, """
        import threading


        class A:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def forward(self):
                with self._a:
                    with self._b:
                        pass

            def also_forward(self):
                with self._a:
                    with self._b:
                        pass
        """)
    findings = lockorder.check([mod], tmp_path)
    assert "DK101" not in _codes(findings)


def test_lockorder_transitive_cycle_through_calls(tmp_path):
    # A.outer holds _a and calls helper() which takes _b; B.outer holds
    # _b and calls back into a _a-taking function -> cycle via fixpoint
    mod = _module(tmp_path, """
        import threading


        class A:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def take_b(self):
                with self._b:
                    pass

            def take_a(self):
                with self._a:
                    pass

            def forward(self):
                with self._a:
                    self.take_b()

            def backward(self):
                with self._b:
                    self.take_a()
        """)
    findings = lockorder.check([mod], tmp_path)
    assert "DK101" in _codes(findings)


def test_lockorder_negated_conditional_acquire_orders_nested(tmp_path):
    # `if not x.acquire(False): return` — the fall-through is the SUCCESS
    # path, so a lock taken after it nests under x (regression: the edge
    # used to be dropped, surfacing only as runtime-sanitizer drift)
    mod = _module(tmp_path, """
        import threading


        class A:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def guarded(self):
                if not self._a.acquire(False):
                    return
                with self._b:
                    pass
                self._a.release()
        """)
    graph = lockorder.build_graph([mod])
    assert ("A._a", "A._b") in graph.edges


def test_lockorder_stale_doc_flagged(tmp_path):
    mod = _module(tmp_path, """
        import threading

        _L = threading.Lock()
        """)
    findings = lockorder.check([mod], tmp_path)  # tmp root: no doc file
    assert "DK190" in _codes(findings)
    # writing the doc clears it
    graph = lockorder.build_graph([mod])
    doc = tmp_path / lockorder.DOC_RELPATH
    doc.parent.mkdir(parents=True, exist_ok=True)
    doc.write_text(lockorder.render_doc(graph), encoding="utf-8")
    findings = lockorder.check([mod], tmp_path)
    assert "DK190" not in _codes(findings)


# -- checker 2: guarded-by ----------------------------------------------------

_GUARDED_SRC = """
    import threading


    class Q:
        def __init__(self):
            self._cv = threading.Condition()
            self._queue = []  # guarded by: self._cv
            self.depth = 0  # guarded by: self._cv [writes]

        def ok_write(self):
            with self._cv:
                self._queue.append(1)
                self.depth += 1

        def documented_holder(self):
            # dukecheck: holds self._cv
            self._queue.append(2)

        def bad_write(self):
            self._queue.append(3)

        def bad_read(self):
            return len(self._queue)

        def lockfree_read_of_writes_only(self):
            return self.depth
    """


def test_guardedby_flags_unguarded_access(tmp_path):
    mod = _module(tmp_path, _GUARDED_SRC)
    findings = guardedby.check([mod])
    by_code = _codes(findings)
    assert by_code.count("DK201") == 1  # bad_write only
    assert by_code.count("DK202") == 1  # bad_read only
    (w,) = [f for f in findings if f.code == "DK201"]
    assert "bad_write" in w.detail
    (r,) = [f for f in findings if f.code == "DK202"]
    assert "bad_read" in r.detail


def test_guardedby_writes_only_allows_lockfree_reads(tmp_path):
    mod = _module(tmp_path, _GUARDED_SRC)
    findings = guardedby.check([mod])
    assert not any("lockfree_read_of_writes_only" in f.detail
                   for f in findings)


def test_guardedby_mutator_call_is_a_write(tmp_path):
    mod = _module(tmp_path, """
        import threading


        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = {}  # guarded by: self._lock [writes]

            def bad(self):
                self._items.clear()

            def also_bad(self):
                self._items["k"] = 1
        """)
    findings = guardedby.check([mod])
    assert _codes(findings) == ["DK201", "DK201"]


def test_guardedby_closure_does_not_inherit_with_scope(tmp_path):
    # a def's body runs when CALLED (thread target), not where it is
    # defined — defining it inside `with self._cv:` must not exempt its
    # unguarded accesses
    mod = _module(tmp_path, """
        import threading


        class Q:
            def __init__(self):
                self._cv = threading.Condition()
                self._queue = []  # guarded by: self._cv

            def start(self):
                with self._cv:
                    def worker():
                        self._queue.append(1)
                    self._queue.append(0)  # genuinely under the lock
                    threading.Thread(target=worker).start()
        """)
    findings = guardedby.check([mod])
    assert _codes(findings) == ["DK201"]
    (f,) = findings
    assert "worker" in f.detail


def test_guardedby_conflicting_annotations_are_loud(tmp_path):
    # the per-module check matches by NAME: two classes annotating the
    # same attribute with different locks must fail, not last-one-wins
    mod = _module(tmp_path, """
        import threading


        class A:
            def __init__(self):
                self._lock = threading.Lock()
                self.depth = 0  # guarded by: self._lock


        class B:
            def __init__(self):
                self._cv = threading.Condition()
                self.depth = 0  # guarded by: self._cv
        """)
    findings = guardedby.check([mod])
    (c,) = [f for f in findings if f.code == "DK203"]
    assert "depth" in c.detail and "conflict" in c.key


# -- checker 3: env knobs -----------------------------------------------------


def test_envknob_flags_raw_access(tmp_path):
    mod = _module(tmp_path, """
        import os

        TUNE = int(os.environ.get("MY_KNOB", "3"))
        OTHER = os.getenv("OTHER_KNOB")
        """)
    findings = envknob.check([mod])
    assert _codes(findings) == ["DK301", "DK301"]
    assert {f.detail for f in findings} == {"env:MY_KNOB", "env:OTHER_KNOB"}


def test_envknob_inline_suppression(tmp_path):
    mod = _module(tmp_path, """
        import os

        ENV = dict(os.environ)  # dukecheck: ignore[DK301] subprocess env
        """)
    findings = dk_core.filter_suppressed(
        {mod.rel: mod}, envknob.check([mod]))
    assert findings == []


def test_envknob_allows_the_helper_module(tmp_path):
    mod = _module(tmp_path, """
        import os

        def env_int(name, default):
            return int(os.environ.get(name, default))
        """, rel="sesam_duke_microservice_tpu/telemetry/env.py")
    assert envknob.check([mod]) == []


# -- checker 4: jit purity ----------------------------------------------------


def test_jitpurity_flags_impure_jit_function(tmp_path):
    mod = _module(tmp_path, """
        import os
        import time

        import jax


        @jax.jit
        def scorer(x):
            t = time.time()
            knob = os.environ.get("K")
            return x * t if knob else x

        def pure_host_helper():
            return time.time()
        """)
    findings = jitpurity.check([mod])
    assert _codes(findings) == ["DK401", "DK401"]
    assert all(f.detail.startswith("scorer:") for f in findings)


def test_jitpurity_follows_jit_factory_closures(tmp_path):
    mod = _module(tmp_path, """
        import random

        import jax

        def build(plan):
            def kernel(x):
                return x + random.random()
            return kernel

        SCORER = jax.jit(build(None))
        """)
    findings = jitpurity.check([mod])
    assert "DK401" in _codes(findings)


def test_jitpurity_checks_every_same_named_def(tmp_path):
    # two classes defining the same method name: the jit-reachable walk
    # must scan BOTH bodies (regression: first-def-wins used to hide the
    # impure second definition)
    mod = _module(tmp_path, """
        import time

        import jax


        class Clean:
            def kernel(self, x):
                return x

        class Dirty:
            @jax.jit
            def score(self, x):
                return self.kernel(x)

            def kernel(self, x):
                return x * time.time()
        """)
    findings = jitpurity.check([mod])
    assert "DK401" in _codes(findings)
    assert any("time.time" in f.message for f in findings)


def test_jitpurity_flags_id_keyed_cache(tmp_path):
    mod = _module(tmp_path, """
        _SCORER_CACHE = {}

        def lookup(plan):
            return _SCORER_CACHE.get(id(plan))

        def pinned_ok(plan):
            # keying on the object itself pins it — the fixed pattern
            return _SCORER_CACHE.get(plan)
        """)
    findings = jitpurity.check([mod])
    assert _codes(findings) == ["DK402"]


# -- checker 5: single-writer metrics -----------------------------------------

_METRIC_SRC = """
    from .. import telemetry

    HITS = telemetry.GLOBAL.counter("x_hits", "h", ("k",))
    TOTAL = telemetry.GLOBAL.counter("x_total", "t")

    def hot_path(key):
        HITS.labels(k=key).inc()
        TOTAL.inc()
    """


def test_metricwrite_flags_hot_module(tmp_path):
    mod = _module(tmp_path, _METRIC_SRC,
                  rel="sesam_duke_microservice_tpu/engine/fixture.py")
    findings = metricwrite.check([mod])
    assert _codes(findings) == ["DK501", "DK502"]


def test_metricwrite_ignores_cold_modules(tmp_path):
    mod = _module(tmp_path, _METRIC_SRC,
                  rel="sesam_duke_microservice_tpu/service/fixture.py")
    assert metricwrite.check([mod]) == []


# -- baseline semantics -------------------------------------------------------


def test_baseline_only_shrinks(tmp_path):
    f1 = dk_core.Finding("DK301", "pkg/a.py", 10, "m", "env:X")
    f2 = dk_core.Finding("DK301", "pkg/a.py", 20, "m", "env:Y")
    baseline = {f1.key: "grandfathered"}
    new, stale = dk_core.apply_baseline([f1, f2], baseline)
    assert [f.detail for f in new] == ["env:Y"]
    assert stale == []
    # the violation was fixed -> its entry is stale and must be deleted
    new, stale = dk_core.apply_baseline([f2], baseline)
    assert stale == [f1.key]


def test_baseline_keys_are_line_stable():
    a = dk_core.Finding("DK301", "pkg/a.py", 10, "m", "env:X")
    b = dk_core.Finding("DK301", "pkg/a.py", 999, "m", "env:X")
    assert a.key == b.key  # unrelated edits must not churn the baseline


# -- the repo itself ----------------------------------------------------------


def test_repo_self_scan_matches_baseline():
    """The CI gate, in-process: every finding in the live tree is inline-
    suppressed or baselined, no baseline entry is stale, and the
    committed lock-hierarchy doc is fresh.  The hlocheck gate is
    excluded HERE only because it compiles the full program x flag
    matrix (~a minute of XLA work the lint job pays once);
    tests/test_numcheck.py runs its dd-core program live and the CI
    lint job runs the complete gate via ``python -m scripts.dukecheck``."""
    from scripts.dukecheck import CHECKER_NAMES

    static_checkers = tuple(n for n in CHECKER_NAMES if n != "hlocheck")
    findings = collect_findings(REPO_ROOT, only=static_checkers)
    baseline = dk_core.load_baseline(REPO_ROOT / BASELINE_RELPATH)
    new, stale = dk_core.apply_baseline(findings, baseline)
    assert not new, "unbaselined findings:\n" + "\n".join(
        f.render() for f in new)
    assert not stale, "stale baseline entries:\n" + "\n".join(stale)


def test_repo_baseline_is_small_and_justified():
    baseline = dk_core.load_baseline(REPO_ROOT / BASELINE_RELPATH)
    assert len(baseline) <= 5
    for key, why in baseline.items():
        assert why, f"baseline entry without a justification: {key}"


def test_repo_lock_graph_is_acyclic_and_doc_fresh():
    modules = dk_core.load_modules(REPO_ROOT)
    graph = lockorder.build_graph(modules)
    assert graph.cycles() == []
    doc = REPO_ROOT / lockorder.DOC_RELPATH
    assert doc.exists()
    assert doc.read_text(encoding="utf-8") == lockorder.render_doc(graph)


def test_repo_hierarchy_orders_scheduler_workload_writebehind():
    """The documented scheduler -> workload -> write-behind order (ISSUE 7
    satellite): dispatch drops the scheduler condition before taking the
    workload lock, and the write-behind condvar sits strictly below the
    workload lock — the reverse edges must not exist."""
    modules = dk_core.load_modules(REPO_ROOT)
    graph = lockorder.build_graph(modules)
    reach = graph.reachable()
    assert "WriteBehindBuffer._cv" in reach.get("Workload.lock", set())
    # a wait on the scheduler condition can never sit under the workload
    # lock (nor under the write-behind condvar)
    assert "IngestScheduler._cv" not in reach.get("Workload.lock", set())
    assert "Workload.lock" not in reach.get("WriteBehindBuffer._cv", set())
    assert "Workload.lock" not in reach.get("IngestScheduler._cv", set())


# -- runtime sanitizer (utils/lockcheck.py) -----------------------------------


@pytest.fixture
def sanitizer(monkeypatch):
    """Recording-enabled lockcheck with test-file holds treated as
    package-driven (the foreign-hold filter would otherwise discard
    edges created by this test driver)."""
    monkeypatch.setattr(lockcheck, "_PACKAGE_NAME", "tests")
    monkeypatch.setattr(lockcheck, "_ENABLED", True)
    monkeypatch.setattr(lockcheck, "_installed", True)
    lockcheck.reset()
    yield lockcheck
    lockcheck.reset()


def _proxy(name):
    return lockcheck._LockProxy(lockcheck._REAL_LOCK(), name, "fixture")


def test_lockcheck_records_edges_and_reports_clean(sanitizer):
    a, b = _proxy("A.lock"), _proxy("B.lock")
    with a:
        with b:
            pass
    rep = sanitizer.report()
    assert rep["edges_observed"] == 1
    assert rep["dynamic_inversions"] == []
    sanitizer.assert_clean()


def test_lockcheck_detects_dynamic_inversion(sanitizer):
    a, b = _proxy("A.lock"), _proxy("B.lock")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    rep = sanitizer.report()
    assert len(rep["dynamic_inversions"]) == 1
    with pytest.raises(AssertionError, match="both orders"):
        sanitizer.assert_clean()


def test_lockcheck_detects_static_inversion(sanitizer, monkeypatch):
    # the static hierarchy says B orders before A; acquiring B under A
    # closes a cycle even though only ONE runtime order was ever seen
    monkeypatch.setattr(lockcheck, "_static_names", {})
    monkeypatch.setattr(lockcheck, "_static_reach",
                        {"B.lock": {"A.lock"}})
    a, b = _proxy("A.lock"), _proxy("B.lock")
    with a:
        with b:
            pass
    rep = sanitizer.report()
    assert len(rep["static_inversions"]) == 1
    assert "static" in rep["static_inversions"][0]
    with pytest.raises(AssertionError):
        sanitizer.assert_clean()


def test_lockcheck_reentrant_rlock_is_not_an_edge(sanitizer):
    r = lockcheck._LockProxy(lockcheck._REAL_RLOCK(), "R.lock", "fixture")
    with r:
        with r:
            pass
    assert sanitizer.report()["edges_observed"] == 0


def test_lockcheck_condition_wait_releases_the_hold(sanitizer):
    import threading as _t

    cv = lockcheck._ConditionProxy(
        lockcheck._REAL_CONDITION(), "CV.lock", "fixture")
    other = _proxy("Other.lock")
    woke = _t.Event()

    def waiter():
        with cv:
            woke.set()
            cv.wait(timeout=5)
            # the re-acquired condition is held again here
            with other:
                pass

    t = _t.Thread(target=waiter)
    t.start()
    woke.wait(5)
    with cv:
        cv.notify_all()
    t.join(5)
    edges = sanitizer.report()["edges_observed"]
    assert edges == 1  # CV.lock -> Other.lock; never Other under a stale CV


def test_lockcheck_foreign_holds_are_filtered(monkeypatch):
    # WITHOUT the package-name patch, holds taken from this test file are
    # foreign and must not generate edges
    monkeypatch.setattr(lockcheck, "_ENABLED", True)
    monkeypatch.setattr(lockcheck, "_installed", True)
    lockcheck.reset()
    try:
        a, b = _proxy("A.lock"), _proxy("B.lock")
        with a:
            with b:
                pass
        assert lockcheck.report()["edges_observed"] == 0
    finally:
        lockcheck.reset()


def test_lockcheck_doc_parse_roundtrip():
    modules = dk_core.load_modules(REPO_ROOT)
    graph = lockorder.build_graph(modules)
    names, reach = lockcheck._parse_doc(lockorder.render_doc(graph))
    # every statically-defined lock maps back from its definition site
    # (ad-hoc witness-only rows like the manual-edge table are skipped)
    for lockname, d in graph.locks.items():
        assert names[(d.rel, d.line)] == lockname
    # reachability includes the manually-reviewed runtime edges
    assert "WriteBehindBuffer._cv" in reach["Workload.lock"]


def test_lockcheck_disabled_is_inert(monkeypatch):
    monkeypatch.setattr(lockcheck, "_ENABLED", False)
    assert lockcheck.enabled() is False
    lockcheck.note_blocking("x")  # no-op, must not record
    assert lockcheck.report()["held_across_dispatch"] == {}


def test_lockcheck_note_blocking_records_holds(sanitizer):
    a = _proxy("Dispatcher.op_lock")
    with a:
        sanitizer.note_blocking("dispatch.broadcast")
    rep = sanitizer.report()
    assert rep["held_across_dispatch"] == {
        "dispatch.broadcast": ["Dispatcher.op_lock"]}
