"""uint16 UTF-16 code-unit char tensors (VERDICT r4 #5).

Char tensors store UTF-16 code units in uint16 (half the HBM/row,
upload, snapshot, and bootstrap bytes of the old int32 codepoints), and
the unit model is the REFERENCE's own: Duke comparators run on
java.lang.String chars, where a non-BMP character is a surrogate PAIR
(two positions).  The host comparators apply the same expansion
(core.comparators._utf16_expand), so host and device distances stay
bit-identical — including for non-BMP text, where the old
codepoint-based implementation actually diverged from the reference.
"""

import numpy as np

from sesam_duke_microservice_tpu.core import comparators as C
from sesam_duke_microservice_tpu.ops import features as F

from test_device_matcher import (
    dedup_schema,
    make_record,
    run_device,
    run_host,
)


def test_char_tensors_are_uint16_units():
    schema = dedup_schema()
    plan = F.SchemaFeatures.plan(schema)
    spec = next(s for s in plan.device_props if s.kind == F.CHARS)
    out = F.extract_property(spec, [["a\U0001D4B3b"], ["plain"]])
    assert out["chars"].dtype == np.uint16
    # surrogate pair occupies two unit slots
    assert int(out["length"][0, 0]) == 4
    assert int(out["length"][1, 0]) == 5
    hi, lo = 0xD835, 0xDCB3  # U+1D4B3 as UTF-16
    assert out["chars"][0, 0, 1] == hi and out["chars"][0, 0, 2] == lo


def test_host_comparators_use_java_unit_semantics():
    lev = C.Levenshtein()
    # "ax" vs "a<U+1D4B3>": Java units are [a, x] vs [a, D835, DCB3]
    # -> distance 2 over min_len 2 -> sim 0; codepoint semantics would
    # have said distance 1 -> sim 0.5
    assert lev.compare("ax", "a\U0001D4B3") == 0.0
    # equal strings stay 1.0 regardless
    assert lev.compare("a\U0001D4B3", "a\U0001D4B3") == 1.0
    jw = C.JaroWinkler()
    assert jw.compare("\U0001D4B3x", "\U0001D4B3x") == 1.0


def test_device_matches_host_on_non_bmp_text():
    """The differential anchor: emitted match sets (and thus confidences)
    agree between the host engine and the device kernels for records
    containing surrogate pairs and lone surrogates."""
    schema = dedup_schema(threshold=0.7)
    records = [
        make_record("a", name="caf\U0001D4B3 corp", city="oslo",
                    amount="100"),
        make_record("b", name="caf\U0001D4B3 corp", city="oslo",
                    amount="100"),
        make_record("c", name="caf\U0001D4B3 co", city="oslo",
                    amount="100"),
        make_record("d", name="zzz \U0001F600\U0001F600 qq",
                    city="bergen", amount="900"),
        make_record("e", name="zzz \U0001F600\U0001F600 qr",
                    city="bergen", amount="900"),
        # lone surrogate (json.loads accepts these; must not crash)
        make_record("f", name="bad \ud835 tail", city="tromso",
                    amount="5"),
    ]
    host = run_host(schema, [records])
    device, _, _ = run_device(schema, [records])
    assert device.match_set() == host.match_set()
    assert device.none_set() == host.none_set()
