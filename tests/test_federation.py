"""Sharded serving federation (ISSUE 14): partition map, scatter-gather
router, federated feed cursor, live range migration, degraded mode.

The kill-at-every-site chaos differential lives in
tests/test_federation_chaos.py (its own CI job); this file covers the
in-process semantics: digest-range routing, epoch fencing, the opaque
composite ``?since=`` cursor (roundtrip, monotonicity, lagging-group gap
safety, resumption across a cutover), backpressure propagation, the
journal satellites (streaming scan, range slice, scoped recovery), and
the degraded-mode contract the acceptance criteria pin.
"""

import json
import os
import threading

import pytest

from sesam_duke_microservice_tpu.core.config import parse_config
from sesam_duke_microservice_tpu.federation import Federation
from sesam_duke_microservice_tpu.federation.migrate import RangeMigrator  # noqa: F401  (import path)
from sesam_duke_microservice_tpu.federation.ranges import (
    BadCursor,
    PartitionMap,
    StaleRouterEpoch,
    decode_cursor,
    encode_cursor,
    route_key,
)
from sesam_duke_microservice_tpu.federation.router import (
    FederationRouter,
    FrozenRange,
    GroupUnavailable,
    PartialIngestFailure,
    UnknownFederatedWorkload,
)
from sesam_duke_microservice_tpu.links.journal import (
    LinkJournal,
    recovery_active,
    recovery_in_progress,
)
from sesam_duke_microservice_tpu.utils import faults

FED_XML = """
<DukeMicroService dataFolder="{folder}">
  <Deduplication name="people">
    <duke>
      <schema>
        <threshold>0.8</threshold>
        <property><name>NAME</name><comparator>levenshtein</comparator><low>0.1</low><high>0.95</high></property>
        <property><name>EMAIL</name><comparator>exact</comparator><low>0.2</low><high>0.95</high></property>
      </schema>
      <data-source class="io.sesam.dukemicroservice.IncrementalDeduplicationDataSource">
        <param name="dataset-id" value="crm"/>
        <column name="name" property="NAME"/>
        <column name="email" property="EMAIL"/>
      </data-source>
    </duke>
  </Deduplication>
</DukeMicroService>
"""


@pytest.fixture(autouse=True)
def _no_env_faults():
    faults.configure("")
    yield
    faults.configure(None)


def make_fed(tmp_path, n_groups=3, ranges_per_group=2) -> Federation:
    sc = parse_config(FED_XML.format(folder=tmp_path),
                      env={"MIN_RELEVANCE": "0.05"})
    return Federation(sc, n_groups=n_groups,
                      ranges_per_group=ranges_per_group)


def duplicate_batch(n=24, identities=4, start=0):
    return [{"_id": str(start + i),
             "name": f"person number {(start + i) % identities}",
             "email": f"p{(start + i) % identities}@x.no"}
            for i in range(n)]


def feed_all(fed, token=""):
    """Drain the federated feed; returns (rows, final_token)."""
    rows = []
    while True:
        page = fed.router.feed_page("deduplication", "people", token, 5000)
        rows.extend(page["rows"])
        token = page["next_since"]
        if page["drained"]:
            return rows, token


def norm(rows):
    out = []
    for r in rows:
        r = dict(r)
        r.pop("_updated", None)
        out.append(json.dumps(r, sort_keys=True))
    return sorted(out)


def owned_links(fed):
    """Federated link rows: each group's link DB filtered by CURRENT
    range ownership — the same one-place rule the feed merge applies."""
    pmap = fed.map
    out = []
    for g in fed.groups:
        for wl in g.workloads.values():
            for l in wl.link_database.get_all_links():
                if pmap.owner(route_key(l.id1)).group == g.idx:
                    out.append((l.id1, l.id2, l.status.value, l.kind.value,
                                round(l.confidence, 12)))
    return sorted(out)


# -- partition map -------------------------------------------------------------


class TestPartitionMap:
    def test_create_covers_keyspace_round_robin(self):
        pmap = PartitionMap.create(n_groups=3, n_ranges=6)
        ranges = pmap.ranges()
        assert len(ranges) == 6
        assert ranges[0].lo == 0 and ranges[-1].hi == 1 << 64
        for prev, cur in zip(ranges, ranges[1:]):
            assert prev.hi == cur.lo
        assert [r.group for r in ranges] == [0, 1, 2, 0, 1, 2]
        # every key has exactly one owner
        for key in (0, 123456789, (1 << 64) - 1, route_key("crm__7")):
            assert pmap.owner(key) is not None

    def test_persist_load_roundtrip_and_atomicity(self, tmp_path):
        path = str(tmp_path / "map.json")
        pmap = PartitionMap.create(2, 4, path=path)
        rid = pmap.ranges()[0].range_id
        pmap.freeze(rid)
        pmap.assign(rid, 1)
        loaded = PartitionMap.load(path)
        assert loaded.version == pmap.version
        assert loaded.epoch == pmap.epoch
        assert loaded.find(rid).group == 1
        assert not loaded.find(rid).frozen
        # no stray tmp files (atomic replace)
        assert [f for f in os.listdir(tmp_path) if ".tmp." in f] == []

    def test_freeze_and_assign_bump_version_and_epoch(self, tmp_path):
        pmap = PartitionMap.create(2, 2, path=str(tmp_path / "m.json"))
        v0, e0 = pmap.version, pmap.epoch
        rid = pmap.ranges()[0].range_id
        e1 = pmap.freeze(rid)
        assert pmap.find(rid).frozen and e1 == e0 + 1
        assert pmap.freeze(rid) == e1  # idempotent re-freeze: no bump
        e2 = pmap.assign(rid, 1)
        assert e2 == e1 + 1 and pmap.version == v0 + 2
        r = pmap.find(rid)
        assert r.group == 1 and not r.frozen

    def test_validate_rejects_gap(self):
        from sesam_duke_microservice_tpu.federation.ranges import Range

        with pytest.raises(ValueError, match="gap/overlap"):
            PartitionMap._validate([Range(0, 10, 0),
                                    Range(11, 1 << 64, 0)])
        with pytest.raises(ValueError, match="cover"):
            PartitionMap._validate([Range(0, 10, 0)])

    def test_route_key_is_stable_and_spread(self):
        assert route_key("crm__1") == route_key("crm__1")
        keys = {route_key(f"crm__{i}") for i in range(64)}
        assert len(keys) == 64
        pmap = PartitionMap.create(3, 6)
        owners = {pmap.owner(k).group for k in keys}
        assert owners == {0, 1, 2}  # 64 uniform keys hit every group


# -- federated feed cursor (satellite) ----------------------------------------


class TestFeedCursor:
    def test_roundtrip(self):
        positions = {"0000000000000000": 17, "8000000000000000": 123456}
        token = encode_cursor(3, positions)
        assert decode_cursor(token) == positions
        assert decode_cursor("") == {}
        assert decode_cursor(None) == {}

    def test_legacy_integer_cursor(self):
        assert decode_cursor("12345") == {"*": 12345}

    def test_garbage_rejected(self):
        with pytest.raises(BadCursor):
            decode_cursor("@@@not-base64@@@")
        import base64

        with pytest.raises(BadCursor):
            decode_cursor(base64.urlsafe_b64encode(
                b'{"f": 99, "r": {}}').decode())

    def test_monotonic_across_interleaved_group_batches(self, tmp_path):
        """Paging with the returned token walks every group's stream
        forward monotonically and yields each row exactly once, however
        group batches interleave in time."""
        fed = make_fed(tmp_path, n_groups=3)
        try:
            for start in (0, 24, 48):  # three waves, all groups hit
                fed.router.submit("deduplication", "people", "crm",
                                  duplicate_batch(24, start=start))
            full, _ = feed_all(fed)
            # page with a small page size: union equals the full feed,
            # no duplicates, timestamps non-decreasing per range
            rows, token, pages = [], "", 0
            while True:
                page = fed.router.feed_page("deduplication", "people",
                                            token, 7)
                # the MERGED page is bounded by the limit too (not
                # n_groups x limit); in-process timestamps are strictly
                # monotonic so no tie extension can widen it
                assert len(page["rows"]) <= 7
                rows.extend(page["rows"])
                token = page["next_since"]
                pages += 1
                assert pages < 500
                if page["drained"] and not page["rows"]:
                    break
            ids_full = sorted(r["_id"] for r in full)
            ids_paged = sorted(r["_id"] for r in rows)
            assert ids_paged == ids_full  # exactly once each
        finally:
            fed.close()

    def test_gap_detection_on_lagging_group(self, tmp_path):
        """A dead group's ranges do not advance in the cursor: rows it
        holds are NOT silently skipped — they arrive once it returns
        (no gap), while live groups' rows keep flowing."""
        fed = make_fed(tmp_path, n_groups=3)
        try:
            fed.router.submit("deduplication", "people", "crm",
                              duplicate_batch(36))
            full, _ = feed_all(fed)
            faults.configure("fed_down=1")
            page = fed.router.feed_page("deduplication", "people", "", 5000)
            assert page["degraded_ranges"] == [
                r.range_id for r in fed.map.group_ranges(1)]
            assert page["retry_after"] is not None
            assert 0 < len(page["rows"]) < len(full)
            # the lagging ranges' cursors stayed at 0 in the new token
            positions = decode_cursor(page["next_since"])
            for r in fed.map.group_ranges(1):
                assert positions.get(r.range_id, 0) == 0
            # group returns: resuming with the degraded token serves the
            # missed rows — nothing was skipped
            faults.configure("")
            rest, _ = feed_all(fed, token=page["next_since"])
            assert norm(page["rows"] + rest) == norm(full)
        finally:
            fed.close()

    def test_resumption_across_migration_cutover(self, tmp_path):
        """The cursor survives a range changing owners: a token cut
        mid-stream before the migration resumes loss-free and
        duplicate-free after it."""
        fed = make_fed(tmp_path, n_groups=2)
        try:
            fed.router.submit("deduplication", "people", "crm",
                              duplicate_batch(36))
            full, _ = feed_all(fed)
            first = fed.router.feed_page("deduplication", "people", "", 9)
            moved = next(r for r in fed.map.ranges() if r.group == 0)
            fed.migrate_range(moved.range_id, 1)
            rest, _ = feed_all(fed, token=first["next_since"])
            assert norm(first["rows"] + rest) == norm(full)
        finally:
            fed.close()


# -- scatter-gather routing ----------------------------------------------------


class TestRouterIngest:
    def test_records_land_at_their_owner_groups(self, tmp_path):
        fed = make_fed(tmp_path, n_groups=3)
        try:
            batch = duplicate_batch(30)
            fed.router.submit("deduplication", "people", "crm", batch)
            ds = fed.groups[0].workload(
                "deduplication", "people").datasources["crm"]
            for entity in batch:
                rid = ds.record_id_for_entity(entity)
                owner = fed.map.owner(route_key(rid)).group
                for g in fed.groups:
                    wl = g.workload("deduplication", "people")
                    present = wl.record_store.get(rid) is not None
                    assert present == (g.idx == owner), (rid, g.idx)
        finally:
            fed.close()

    def test_unknown_workload_and_dataset(self, tmp_path):
        fed = make_fed(tmp_path, n_groups=2)
        try:
            with pytest.raises(UnknownFederatedWorkload):
                fed.router.submit("deduplication", "nope", "crm", [])
            with pytest.raises(UnknownFederatedWorkload):
                fed.router.submit("deduplication", "people", "nope",
                                  [{"_id": "1"}])
        finally:
            fed.close()

    def test_frozen_range_rejects_whole_batch_with_retry_after(
            self, tmp_path):
        fed = make_fed(tmp_path, n_groups=2)
        try:
            frozen = next(r for r in fed.map.ranges() if r.group == 0)
            fed.map.freeze(frozen.range_id)
            batch = duplicate_batch(40)
            with pytest.raises(FrozenRange) as exc:
                fed.router.submit("deduplication", "people", "crm", batch)
            assert frozen.range_id in exc.value.range_ids
            assert exc.value.retry_after >= 1
            # thaw: the same batch now lands
            fed.map.assign(frozen.range_id, 0)
            for g in fed.groups:
                g.fence(fed.map.epoch)
            fed.router.submit("deduplication", "people", "crm", batch)
        finally:
            fed.close()

    def test_partial_failure_reports_degraded_ranges_and_max_retry_after(
            self, tmp_path):
        """Satellite: backpressure propagates — the federated error
        carries the degraded-range list and the MAX Retry-After across
        contacted groups."""
        fed = make_fed(tmp_path, n_groups=3)
        try:
            faults.configure("fed_down=2")
            batch = duplicate_batch(40)
            with pytest.raises(PartialIngestFailure) as exc:
                fed.router.submit("deduplication", "people", "crm", batch)
            dead_ranges = [r.range_id for r in fed.map.group_ranges(2)]
            assert exc.value.degraded_ranges == sorted(dead_ranges)
            assert exc.value.retry_after >= 1
            assert list(exc.value.errors) == [2]
            # the live groups' sub-batches DID apply
            live_rows = sum(
                g.workload("deduplication", "people").record_store.count()
                for g in fed.groups[:2])
            assert live_rows > 0
            assert fed.router.degraded_range_ids() == sorted(dead_ranges)
        finally:
            fed.close()

    def test_batch_in_live_ranges_succeeds_while_group_down(self, tmp_path):
        fed = make_fed(tmp_path, n_groups=3)
        try:
            faults.configure("fed_down=2")
            ds = fed.groups[0].workload(
                "deduplication", "people").datasources["crm"]
            live = [e for e in duplicate_batch(60)
                    if fed.map.owner(route_key(
                        ds.record_id_for_entity(e))).group != 2]
            result = fed.router.submit("deduplication", "people", "crm",
                                       live)
            assert result["success"] is True
        finally:
            fed.close()

    def test_stale_router_epoch_fenced_at_group(self, tmp_path):
        """A router holding a pre-freeze map cannot write into a range's
        old owner: the group's fence rejects the stale epoch."""
        fed = make_fed(tmp_path, n_groups=2)
        try:
            stale_map = PartitionMap.load(fed.map.path)
            stale_router = FederationRouter(lambda: stale_map, fed.groups)
            moved = next(r for r in fed.map.ranges() if r.group == 0)
            epoch = fed.map.freeze(moved.range_id)
            fed.groups[0].fence(epoch)
            # direct group write with the stale epoch: fenced
            with pytest.raises(StaleRouterEpoch):
                fed.groups[0].ingest("deduplication", "people", "crm",
                                     duplicate_batch(2),
                                     epoch=stale_map.epoch)
            # the stale ROUTER refreshes its map once and re-routes: its
            # provider still serves the frozen map, so the refresh keeps
            # it stale and the write surfaces as a fencing error — never
            # a write to the old owner
            with pytest.raises((StaleRouterEpoch, FrozenRange,
                                PartialIngestFailure)):
                stale_router.submit("deduplication", "people", "crm",
                                    duplicate_batch(40))
            fed.map.assign(moved.range_id, 0)
        finally:
            fed.close()

    def test_stale_epoch_is_not_marked_as_group_failure(self, tmp_path):
        """A fencing refusal is not ill-health: the refusing group's
        ranges must not surface as degraded, and the stale signal
        itself reaches the caller."""
        fed = make_fed(tmp_path, n_groups=2)
        try:
            stale_map = PartitionMap.load(fed.map.path)
            stale_router = FederationRouter(lambda: stale_map, fed.groups)
            for g in fed.groups:
                g.fence(stale_map.epoch + 5)  # topology moved on
            with pytest.raises(StaleRouterEpoch):
                stale_router.submit("deduplication", "people", "crm",
                                    duplicate_batch(8))
            assert stale_router.degraded_range_ids() == []
            assert all(row["up"] for row in stale_router.group_health())
        finally:
            fed.close()

    def test_fence_recheck_after_write_withholds_ack(self, tmp_path,
                                                     monkeypatch):
        """A freeze landing WHILE a batch runs must withhold the ack
        (the post-write fence re-check): an acked write completing
        after the migration's snapshot walk would be invisible
        forever."""
        fed = make_fed(tmp_path, n_groups=2)
        try:
            group = fed.groups[0]
            wl = group.workload("deduplication", "people")
            real = wl.submit_batch

            def racing(*args, **kwargs):
                out = real(*args, **kwargs)
                group.fence(group.fence_epoch + 1)  # freeze mid-write
                return out

            monkeypatch.setattr(wl, "submit_batch", racing)
            with pytest.raises(StaleRouterEpoch):
                group.ingest("deduplication", "people", "crm",
                             duplicate_batch(2), epoch=fed.map.epoch)
        finally:
            fed.close()

    def test_map_mutation_rolls_back_on_persist_failure(self, tmp_path,
                                                        monkeypatch):
        """A failed map persist must leave the LIVE map unchanged — a
        memory-only freeze would 429 the range forever on an intent no
        restart could ever see."""
        fed = make_fed(tmp_path, n_groups=2)
        try:
            from sesam_duke_microservice_tpu.utils import atomicio

            rid = fed.map.ranges()[0].range_id
            v0, e0 = fed.map.version, fed.map.epoch

            def broken(path, doc):
                raise OSError("disk full")

            monkeypatch.setattr(atomicio, "atomic_write_json", broken)
            # the map module imports the helper inside _persist_locked,
            # so the module-level patch is what it resolves
            monkeypatch.setattr(
                "sesam_duke_microservice_tpu.utils.atomicio"
                ".atomic_write_json", broken)
            with pytest.raises(OSError):
                fed.map.freeze(rid)
            r = fed.map.find(rid)
            assert not r.frozen
            assert (fed.map.version, fed.map.epoch) == (v0, e0)
        finally:
            fed.close()

    def test_group_retry_heals_transient_unavailability(self, tmp_path,
                                                        monkeypatch):
        monkeypatch.setenv("DUKE_FED_RETRIES", "3")
        fed = make_fed(tmp_path, n_groups=2)
        try:
            group = fed.groups[1]
            real = group.ingest
            calls = []

            def flaky(*args, **kwargs):
                calls.append(1)
                if len(calls) == 1:
                    raise GroupUnavailable("transient blip")
                return real(*args, **kwargs)

            monkeypatch.setattr(group, "ingest", flaky)
            result = fed.router.submit("deduplication", "people", "crm",
                                      duplicate_batch(40))
            assert result["success"] is True
            assert len(calls) >= 2  # failed once, healed on retry
        finally:
            fed.close()


# -- degraded-mode acceptance --------------------------------------------------


def test_degraded_mode_contract(tmp_path):
    """Acceptance: with one group down mid scatter-gather, live-range
    queries succeed, dead-range queries answer 503 + Retry-After, and
    the merged feed serves every live group's links."""
    fed = make_fed(tmp_path, n_groups=3)
    try:
        fed.router.submit("deduplication", "people", "crm",
                          duplicate_batch(48))
        full, _ = feed_all(fed)
        live_links = [
            json.dumps(dict(r, _updated=None), sort_keys=True)
            for r in full
            if fed.map.owner(route_key(
                f"crm__{r['entity1']}")).group != 1
        ]
        faults.configure("fed_down=1")
        ds = fed.groups[0].workload(
            "deduplication", "people").datasources["crm"]
        live_batch, dead_batch = [], []
        for e in duplicate_batch(60, start=1000):
            owner = fed.map.owner(route_key(
                ds.record_id_for_entity(e))).group
            (dead_batch if owner == 1 else live_batch).append(e)
        # live ranges: success
        assert fed.router.submit("deduplication", "people", "crm",
                                 live_batch)["success"] is True
        # dead ranges: 503-shaped failure with Retry-After + range list
        with pytest.raises(PartialIngestFailure) as exc:
            fed.router.submit("deduplication", "people", "crm", dead_batch)
        assert exc.value.retry_after >= 1
        assert exc.value.degraded_ranges == [
            r.range_id for r in fed.map.group_ranges(1)]
        # merged feed: every LIVE group's links still serve
        page = fed.router.feed_page("deduplication", "people", "", 5000)
        degraded_set = set(page["degraded_ranges"])
        assert degraded_set == {r.range_id
                                for r in fed.map.group_ranges(1)}
        served = {json.dumps(dict(r, _updated=None), sort_keys=True)
                  for r in page["rows"]}
        for row in live_links:
            assert row in served
    finally:
        fed.close()


# -- live migration ------------------------------------------------------------


class TestMigration:
    def test_feed_and_links_bit_identical_across_migration(self, tmp_path):
        fed = make_fed(tmp_path, n_groups=2)
        try:
            fed.router.submit("deduplication", "people", "crm",
                              duplicate_batch(36))
            before_feed, _ = feed_all(fed)
            before_links = owned_links(fed)
            moved = next(r for r in fed.map.ranges() if r.group == 0)
            result = fed.migrate_range(moved.range_id, 1)
            assert result["moved_records"] > 0
            assert fed.map.find(moved.range_id).group == 1
            after_feed, _ = feed_all(fed)
            # timestamps ship VERBATIM: even _updated must match
            assert (sorted(json.dumps(r, sort_keys=True)
                           for r in after_feed)
                    == sorted(json.dumps(r, sort_keys=True)
                              for r in before_feed))
            assert owned_links(fed) == before_links
        finally:
            fed.close()

    def test_post_migration_ingest_links_at_new_owner(self, tmp_path):
        """After cutover, new duplicates of moved records match at the
        TARGET (the source's copies are tombstoned out of retrieval, so
        no link the map would filter can ever be minted there)."""
        fed = make_fed(tmp_path, n_groups=2)
        try:
            fed.router.submit("deduplication", "people", "crm",
                              duplicate_batch(24))
            moved = next(r for r in fed.map.ranges() if r.group == 0)
            fed.migrate_range(moved.range_id, 1)
            before = len(feed_all(fed)[0])
            # find an identity whose records moved, and post a fresh dup
            ds = fed.groups[0].workload(
                "deduplication", "people").datasources["crm"]
            target_ident = None
            for i in range(24):
                rid = ds.record_id_for_entity({"_id": str(i)})
                if fed.map.find(moved.range_id).contains(route_key(rid)):
                    target_ident = i % 4
                    break
            assert target_ident is not None
            fed.router.submit("deduplication", "people", "crm", [{
                "_id": "9000",
                "name": f"person number {target_ident}",
                "email": f"p{target_ident}@x.no",
            }])
            after = feed_all(fed)[0]
            new_rows = [r for r in after
                        if "9000" in (r["entity1"], r["entity2"])]
            assert len(after) > before and new_rows
            # every new link must be owned by a live mapping (emitted by
            # exactly one group) — owned_links saw them too
            assert any("crm__9000" in (l[0], l[1])
                       for l in owned_links(fed))
        finally:
            fed.close()

    def test_migration_replays_journal_slice(self, tmp_path, monkeypatch):
        """Links journaled but NOT yet applied at snapshot time ride the
        range's journal slice to the target — a wedged flusher cannot
        lose rows across a migration."""
        monkeypatch.setenv("DUKE_JOURNAL", "1")  # pin under the =0 CI leg
        fed = make_fed(tmp_path, n_groups=2)
        try:
            fed.router.submit("deduplication", "people", "crm",
                              duplicate_batch(24))
            before = owned_links(fed)
            moved = next(r for r in fed.map.ranges() if r.group == 0)
            src_wl = fed.groups[0].workload("deduplication", "people")
            journal = src_wl.link_database.journal
            assert journal is not None
            # strand a batch in the journal: appended (acked) but the
            # applied watermark never advanced — exactly the crash
            # window PR 10 closes
            lo, hi = moved.lo, moved.hi
            in_range = next(
                l for l in src_wl.link_database.get_all_links()
                if lo <= route_key(l.id1) < hi)
            stranded = [(in_range.id1, in_range.id2, "inferred",
                         "duplicate", 0.4242, 1234567890123)]
            journal.append_batch([list(r) for r in stranded])
            result = fed.migrate_range(moved.range_id, 1)
            assert result["replayed_slices"] >= 1
            after = owned_links(fed)
            # the stranded row's re-assert (different confidence) landed
            # at the TARGET
            tgt_rows = {
                (l.id1, l.id2, round(l.confidence, 6))
                for l in fed.groups[1].workload(
                    "deduplication", "people")
                .link_database.get_all_links()}
            assert (in_range.id1, in_range.id2, 0.4242) in tgt_rows
            assert len(after) == len(before)
        finally:
            fed.close()

    def test_interrupted_migration_resumes_on_restart(self, tmp_path):
        """A migration that stopped after freeze (crash-shaped: state
        file + frozen map on disk) completes when the federation is
        rebuilt — and the result equals a clean migration."""
        fed = make_fed(tmp_path, n_groups=2)
        fed.router.submit("deduplication", "people", "crm",
                          duplicate_batch(24))
        before_feed = norm(feed_all(fed)[0])
        before_links = owned_links(fed)
        moved = next(r for r in fed.map.ranges() if r.group == 0)
        # freeze + state file, then stop — the crash window between
        # pre_freeze and post_snapshot
        fed.migrator._write_state({"range": moved.range_id, "source": 0,
                                   "target": 1})
        fed.map.freeze(moved.range_id)
        fed.close()

        fed2 = make_fed(tmp_path, n_groups=2)  # auto-resumes in __init__
        try:
            assert fed2.map.find(moved.range_id).group == 1
            assert not fed2.map.find(moved.range_id).frozen
            assert fed2.migrator.outcomes["resumed"] == 1
            assert not os.path.exists(fed2.migrator.state_path)
            assert norm(feed_all(fed2)[0]) == before_feed
            assert owned_links(fed2) == before_links
        finally:
            fed2.close()

    def test_migrate_rejects_bad_args_and_concurrency(self, tmp_path):
        fed = make_fed(tmp_path, n_groups=2)
        try:
            with pytest.raises(KeyError):
                fed.migrate_range("ffffffffffffffff", 1)
            rid = fed.map.ranges()[0].range_id
            with pytest.raises(ValueError):
                fed.migrate_range(rid, 99)
            # already-owned: explicit no-op
            own = fed.map.ranges()[0]
            assert fed.migrate_range(own.range_id, own.group).get(
                "already_owned") is True
            # one migration at a time
            with fed._admin_lock:
                fed._migrating = "somerange"
            try:
                with pytest.raises(RuntimeError, match="in progress"):
                    fed.migrate_range(rid, 1)
            finally:
                with fed._admin_lock:
                    fed._migrating = None
        finally:
            fed.close()


# -- journal satellites --------------------------------------------------------


class TestJournalStreaming:
    def test_scan_matches_legacy_semantics_on_large_journal(self, tmp_path):
        """The streaming scan (satellite: O(n), bounded memory) parses a
        multi-chunk journal identically to the old whole-file scan."""
        path = str(tmp_path / "big.journal")
        j = LinkJournal(path, sync="none")
        # ~3 MiB of frames: forces multiple 1 MiB read chunks
        payload_row = ["id_%06d" % 0, "id_%06d" % 1, "inferred",
                       "duplicate", 0.9, 1111]
        for i in range(3000):
            j.append_batch([payload_row] * 16)
        j.mark_applied(2990)
        j.close()
        assert os.path.getsize(path) > 2 * (1 << 20)

        j2 = LinkJournal(path)
        unapplied = j2.unapplied()
        assert [seq for seq, _ in unapplied] == list(range(2991, 3001))
        assert j2.head_seq() == 3000
        assert j2.applied_watermark() == 2990
        j2.close()

    def test_batches_after_streams_slice(self, tmp_path):
        path = str(tmp_path / "s.journal")
        j = LinkJournal(path, sync="none")
        for i in range(10):
            j.append_batch([[f"a{i}", f"b{i}", "inferred", "duplicate",
                             0.5, i]])
        got = [(seq, rows[0][0]) for seq, rows in j.batches_after(7)]
        assert got == [(8, "a7"), (9, "a8"), (10, "a9")]
        assert list(j.batches_after(10)) == []
        j.close()

    def test_batches_after_stops_silently_at_torn_tail(self, tmp_path):
        path = str(tmp_path / "t.journal")
        j = LinkJournal(path, sync="none")
        j.append_batch([["a", "b", "inferred", "duplicate", 0.5, 1]])
        j.append_batch([["c", "d", "inferred", "duplicate", 0.5, 2]])
        with open(path, "ab") as f:
            f.write(b"B\x00\x00\x01")  # torn header
        got = [seq for seq, _ in j.batches_after(0)]
        assert got == [1, 2]  # intact prefix; tear neither raises nor counts
        j.close()

    def test_retained_pins_compaction(self, tmp_path):
        path = str(tmp_path / "p.journal")
        j = LinkJournal(path, sync="none")
        seq = j.append_batch([["a", "b", "inferred", "duplicate", 0.5, 1]])
        with j.retained():
            j.mark_applied(seq)
            j.compact()
            assert os.path.getsize(path) > 0  # pinned: frames survive
            assert list(j.batches_after(0))  # still walkable
        j.compact()
        assert os.path.getsize(path) == 0  # unpinned: compaction resumes
        j.close()


class TestScopedRecovery:
    def test_one_scope_does_not_flip_another(self):
        assert not recovery_active()
        with recovery_in_progress("/data/g0"):
            assert recovery_active()  # any-scope view
            assert recovery_active("/data/g0")
            assert not recovery_active("/data/g1")  # satellite: isolated
        assert not recovery_active("/data/g0")

    def test_anonymous_scope_is_process_wide(self):
        with recovery_in_progress():
            assert recovery_active("/data/anything")
            assert recovery_active()
        assert not recovery_active("/data/anything")

    def test_nested_and_reentrant(self):
        with recovery_in_progress("/a"):
            with recovery_in_progress("/a"):
                assert recovery_active("/a")
            assert recovery_active("/a")
        assert not recovery_active("/a")

    def test_app_readiness_scoped_to_own_workloads(self, tmp_path):
        """The DukeApp /readyz check watches only its own workloads'
        folders: another group's replay in the same process no longer
        makes every app report recovering."""
        from sesam_duke_microservice_tpu.service.app import DukeApp

        sc = parse_config(FED_XML.format(folder=tmp_path),
                          env={"MIN_RELEVANCE": "0.05"})
        app = DukeApp(sc, backend="host", persistent=False)
        try:
            own = sc.deduplications["people"].data_folder
            with recovery_in_progress("/somewhere/else/entirely"):
                ready, checks = app.readiness()
                assert checks["recovery_complete"] is True
            with recovery_in_progress(own):
                ready, checks = app.readiness()
                assert checks["recovery_complete"] is False
            with recovery_in_progress():  # anonymous: process-wide
                ready, checks = app.readiness()
                assert checks["recovery_complete"] is False
        finally:
            app.close()


# -- group recovery inside the federation -------------------------------------


def test_group_journal_recovery_replays_on_federation_restart(
        tmp_path, monkeypatch):
    """A batch stranded in one group's journal replays when the
    federation is rebuilt — per-group crash recovery composes under the
    router unchanged."""
    monkeypatch.setenv("DUKE_JOURNAL", "1")  # pin under the =0 CI leg
    fed = make_fed(tmp_path, n_groups=2)
    fed.router.submit("deduplication", "people", "crm",
                      duplicate_batch(24))
    before = owned_links(fed)
    # strand a re-assert with a bumped confidence in group 0's journal
    wl = fed.groups[0].workload("deduplication", "people")
    sample = next(l for l in wl.link_database.get_all_links()
                  if fed.map.owner(route_key(l.id1)).group == 0)
    journal_path = os.path.join(
        fed.group_folder(0), "deduplication", "people",
        "linkdatabase.journal")
    fed.close()

    j = LinkJournal(journal_path)
    j.append_batch([[sample.id1, sample.id2, "inferred", "duplicate",
                     0.1313, 9999999999999]])
    j.close()

    fed2 = make_fed(tmp_path, n_groups=2)
    try:
        after = {(l[0], l[1], l[4]) for l in owned_links(fed2)}
        assert (sample.id1, sample.id2, 0.1313) in after
        assert len(owned_links(fed2)) == len(before)
    finally:
        fed2.close()


# -- HTTP frontend -------------------------------------------------------------


class TestFederationPlane:
    @pytest.fixture()
    def plane(self, tmp_path):
        from sesam_duke_microservice_tpu.service.federation_plane import (
            serve_federation,
        )

        fed = make_fed(tmp_path, n_groups=2)
        server = serve_federation(fed)
        base = f"http://127.0.0.1:{server.server_address[1]}"
        yield fed, base
        server.shutdown()
        fed.close()

    @staticmethod
    def _post(url, obj):
        import urllib.request

        req = urllib.request.Request(
            url, data=json.dumps(obj).encode("utf-8"), method="POST",
            headers={"Content-Type": "application/json"})
        return urllib.request.urlopen(req, timeout=60)

    @staticmethod
    def _get(url):
        import urllib.request

        return urllib.request.urlopen(url, timeout=60)

    def test_ingest_feed_migrate_end_to_end(self, plane):
        fed, base = plane
        with self._post(base + "/deduplication/people/crm",
                        duplicate_batch(24)) as r:
            assert r.status == 200
            assert json.loads(r.read())["success"] is True
        with self._get(base + "/deduplication/people?since=") as r:
            rows = json.loads(r.read())
            token = r.headers["X-Fed-Next-Since"]
            assert r.headers["X-Fed-Drained"] == "true"
            assert rows
        # resume: consumed token serves nothing new
        with self._get(f"{base}/deduplication/people?since={token}") as r:
            assert json.loads(r.read()) == []
        # migrate over HTTP; the feed is unchanged after
        mp = json.loads(self._get(base + "/federation/map").read())
        moved = next(x for x in mp["ranges"] if x["group"] == 0)
        with self._post(base + "/federation/migrate",
                        {"range": moved["id"], "target": 1}) as r:
            result = json.loads(r.read())
            assert result["moved_records"] > 0
        with self._get(base + "/deduplication/people?since=") as r:
            assert norm(json.loads(r.read())) == norm(rows)
        mp2 = json.loads(self._get(base + "/federation/map").read())
        assert next(x for x in mp2["ranges"]
                    if x["id"] == moved["id"])["group"] == 1

    def test_frozen_range_answers_429_with_retry_after(self, plane):
        import urllib.error

        fed, base = plane
        frozen = next(r for r in fed.map.ranges() if r.group == 0)
        fed.map.freeze(frozen.range_id)
        try:
            with pytest.raises(urllib.error.HTTPError) as exc:
                self._post(base + "/deduplication/people/crm",
                           duplicate_batch(24))
            assert exc.value.code == 429
            assert int(exc.value.headers["Retry-After"]) >= 1
            body = json.loads(exc.value.read())
            assert frozen.range_id in body["frozen_ranges"]
        finally:
            fed.map.assign(frozen.range_id, 0)
            for g in fed.groups:
                g.fence(fed.map.epoch)

    def test_degraded_group_503_with_ranges_in_error_body(self, plane):
        import urllib.error

        fed, base = plane
        with self._post(base + "/deduplication/people/crm",
                        duplicate_batch(24)) as r:
            assert r.status == 200
        faults.configure("fed_down=1")
        with pytest.raises(urllib.error.HTTPError) as exc:
            self._post(base + "/deduplication/people/crm",
                       duplicate_batch(24, start=100))
        assert exc.value.code == 503
        body = json.loads(exc.value.read())
        assert body["degraded_ranges"] == [
            r.range_id for r in fed.map.group_ranges(1)]
        assert int(exc.value.headers["Retry-After"]) >= 1
        # the merged feed still serves the live group's links, flags
        # the dead ranges, and /readyz reports degraded
        with self._get(base + "/deduplication/people?since=") as r:
            assert json.loads(r.read())
            assert r.headers["X-Fed-Degraded-Ranges"]
            assert int(r.headers["Retry-After"]) >= 1
        with pytest.raises(urllib.error.HTTPError) as exc:
            self._get(base + "/readyz")
        assert exc.value.code == 503
        assert json.loads(exc.value.read())["status"] == "degraded"

    def test_readyz_recovering_scoped_to_group_folders(self, plane):
        import urllib.error

        fed, base = plane
        with self._get(base + "/readyz") as r:
            assert json.loads(r.read())["status"] == "ready"
        scope = fed.group_folders()[0]
        with recovery_in_progress(scope):
            with pytest.raises(urllib.error.HTTPError) as exc:
                self._get(base + "/readyz")
            assert exc.value.code == 503
            body = json.loads(exc.value.read())
            assert body["status"] == "recovering"
            assert scope in body["recovering_scopes"]
        # a FOREIGN scope's recovery does not flip this federation
        with recovery_in_progress("/some/other/process/folder"):
            with self._get(base + "/readyz") as r:
                assert json.loads(r.read())["status"] == "ready"

    def test_stats_and_metrics_surfaces(self, plane):
        fed, base = plane
        with self._post(base + "/deduplication/people/crm",
                        duplicate_batch(12)) as r:
            assert r.status == 200
        stats = json.loads(self._get(base + "/stats").read())
        assert stats["role"] == "federation-router"
        assert len(stats["groups"]) == 2
        assert stats["map"]["n_groups"] == 2
        assert stats["migration"]["phase"] == "idle"
        body = self._get(base + "/metrics").read().decode()
        for family in ("duke_fed_groups", "duke_fed_group_up",
                       "duke_fed_group_seconds_since_contact",
                       "duke_fed_degraded_ranges",
                       "duke_fed_migration_phase",
                       "duke_fed_migrations_total",
                       "duke_fed_requests_total"):
            assert family in body, family

    def test_bad_inputs(self, plane):
        import urllib.error

        fed, base = plane
        with pytest.raises(urllib.error.HTTPError) as exc:
            self._get(base + "/deduplication/people?since=@@@bad@@@")
        assert exc.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as exc:
            self._get(base + "/deduplication/nope?since=")
        assert exc.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as exc:
            self._post(base + "/deduplication/nope/crm", [{"_id": "1"}])
        assert exc.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as exc:
            self._post(base + "/federation/migrate",
                       {"range": "ffffffffffffffff", "target": 1})
        assert exc.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as exc:
            self._post(base + "/federation/migrate", {"nope": 1})
        assert exc.value.code == 400


# -- threading sanity ----------------------------------------------------------


def test_concurrent_submit_and_feed(tmp_path):
    """Scatter ingest and merged feeds interleave safely from many
    threads (the router holds no lock across group calls)."""
    fed = make_fed(tmp_path, n_groups=2)
    errors = []

    def ingest(start):
        try:
            fed.router.submit("deduplication", "people", "crm",
                              duplicate_batch(12, start=start))
        except Exception as e:  # pragma: no cover
            errors.append(e)

    def poll():
        try:
            feed_all(fed)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    try:
        threads = ([threading.Thread(target=ingest, args=(i * 12,))
                    for i in range(4)]
                   + [threading.Thread(target=poll) for _ in range(2)])
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors
        rows, _ = feed_all(fed)
        assert rows  # the merged feed serves everything that linked
    finally:
        fed.close()
