"""Pallas tiled kernels: differential tests vs the XLA path and the scalar
oracle, in interpreter mode on the CPU test backend (tests/conftest.py)."""

import numpy as np
import pytest

from sesam_duke_microservice_tpu.core import comparators as C
from sesam_duke_microservice_tpu.ops import pairwise as pw
from sesam_duke_microservice_tpu.ops import pallas_kernels as pk

import jax.numpy as jnp


def _encode(strings, max_chars=16):
    n = len(strings)
    chars = np.zeros((n, max_chars), np.int32)
    lens = np.zeros((n,), np.int32)
    for i, s in enumerate(strings):
        cps = [ord(ch) for ch in s][:max_chars]
        chars[i, : len(cps)] = cps
        lens[i] = len(cps)
    return jnp.asarray(chars), jnp.asarray(lens)


QUERIES = ["kitten", "saturday", "abc", "", "flaw", "ab", "identical",
           "a" * 16, "xyzzy"]
CORPUS = ["sitting", "sunday", "abc", "lawn", "", "b", "identical",
          "a" * 12 + "bbbb", "plugh", "kitten"]


def test_myers_tiles_vs_flat_myers():
    qc, ql = _encode(QUERIES)
    cc, cl = _encode(CORPUS)
    got = np.asarray(
        pk.myers_distance_tiles(qc, ql, cc, cl, interpret=True)
    )
    # flat reference: expand pairs and run the XLA Myers kernel
    nq, nc = len(QUERIES), len(CORPUS)
    c1 = jnp.repeat(qc, nc, axis=0)
    l1 = jnp.repeat(ql, nc)
    c2 = jnp.tile(cc, (nq, 1))
    l2 = jnp.tile(cl, (nq,))
    want = np.asarray(pw.levenshtein_distance_myers(c1, l1, c2, l2)).reshape(
        nq, nc
    )
    np.testing.assert_array_equal(got, want)


def test_myers_tiles_vs_scalar_oracle():
    qc, ql = _encode(QUERIES)
    cc, cl = _encode(CORPUS)
    got = np.asarray(
        pk.myers_distance_tiles(qc, ql, cc, cl, interpret=True)
    )
    for i, s1 in enumerate(QUERIES):
        for j, s2 in enumerate(CORPUS):
            assert got[i, j] == C.levenshtein_distance(s1, s2), (s1, s2)


def test_myers_tiles_padding_sizes():
    # non-multiple-of-tile shapes round-trip through padding
    rng = np.random.default_rng(7)
    strings = [
        "".join(chr(97 + rng.integers(4)) for _ in range(rng.integers(0, 16)))
        for _ in range(13)
    ]
    qc, ql = _encode(strings[:5])
    cc, cl = _encode(strings)
    got = np.asarray(pk.myers_distance_tiles(qc, ql, cc, cl, interpret=True))
    assert got.shape == (5, 13)
    for i in range(5):
        for j in range(13):
            assert got[i, j] == C.levenshtein_distance(strings[i], strings[j])


def test_myers_two_word_tiles_vs_scalar_oracle():
    """32 < L <= 64 routes to the two-word Hyyro kernel; exact vs the
    scalar DP, including lengths straddling the word boundary."""
    rng = np.random.default_rng(11)
    lens = [0, 1, 31, 32, 33, 40, 47, 63, 64, 20, 50]
    strings = [
        "".join(chr(97 + rng.integers(5)) for _ in range(n)) for n in lens
    ]
    qc, ql = _encode(strings, max_chars=64)
    cc, cl = _encode(strings[::-1], max_chars=64)
    got = np.asarray(pk.myers_distance_tiles(qc, ql, cc, cl, interpret=True))
    rev = strings[::-1]
    for i, s1 in enumerate(strings):
        for j, s2 in enumerate(rev):
            assert got[i, j] == C.levenshtein_distance(s1, s2), (
                len(s1), len(s2), got[i, j]
            )


def test_myers_two_word_matches_one_word_on_short_strings():
    """The two-word kernel degenerates exactly to the one-word result when
    every pattern fits a single word (cross-check of the carry plumbing)."""
    qc, ql = _encode(QUERIES, max_chars=40)   # L=40 -> two-word kernel
    cc, cl = _encode(CORPUS, max_chars=40)
    got = np.asarray(pk.myers_distance_tiles(qc, ql, cc, cl, interpret=True))
    qc1, ql1 = _encode(QUERIES, max_chars=32)
    cc1, cl1 = _encode(CORPUS, max_chars=32)
    want = np.asarray(
        pk.myers_distance_tiles(qc1, ql1, cc1, cl1, interpret=True)
    )
    np.testing.assert_array_equal(got, want)


def test_myers_multiword_tiles_vs_scalar_oracle():
    """64 < L <= 256 routes to the N-word Hyyro kernel (VERDICT r2 #3);
    exact vs the scalar DP, including lengths straddling every word
    boundary in the 4-word (128-char) configuration."""
    rng = np.random.default_rng(13)
    lens = [0, 1, 31, 32, 33, 63, 64, 65, 95, 96, 97, 100, 127, 128]
    strings = [
        "".join(chr(97 + rng.integers(5)) for _ in range(n)) for n in lens
    ]
    qc, ql = _encode(strings, max_chars=128)
    cc, cl = _encode(strings[::-1], max_chars=128)
    got = np.asarray(pk.myers_distance_tiles(qc, ql, cc, cl, interpret=True))
    rev = strings[::-1]
    for i, s1 in enumerate(strings):
        for j, s2 in enumerate(rev):
            assert got[i, j] == C.levenshtein_distance(s1, s2), (
                len(s1), len(s2), got[i, j]
            )


def test_myers_eight_word_tiles_vs_scalar_oracle():
    """The MYERS_MAX_CHARS=256 (8-word) configuration stays exact —
    long-text schemas (addresses, titles) ride the Pallas path."""
    rng = np.random.default_rng(17)
    lens = [0, 1, 64, 128, 129, 191, 192, 193, 255, 256, 200]
    strings = [
        "".join(chr(97 + rng.integers(4)) for _ in range(n)) for n in lens
    ]
    qc, ql = _encode(strings, max_chars=256)
    cc, cl = _encode(strings[::-1], max_chars=256)
    got = np.asarray(pk.myers_distance_tiles(qc, ql, cc, cl, interpret=True))
    rev = strings[::-1]
    for i, s1 in enumerate(strings):
        for j, s2 in enumerate(rev):
            assert got[i, j] == C.levenshtein_distance(s1, s2), (
                len(s1), len(s2), got[i, j]
            )


def test_myers_multiword_matches_two_word_on_short_strings():
    """The 4-word kernel degenerates exactly to the 2-word result when
    every pattern fits 64 chars (cross-check of the carry chain)."""
    qc, ql = _encode(QUERIES, max_chars=100)   # L=100 -> 4-word kernel
    cc, cl = _encode(CORPUS, max_chars=100)
    got = np.asarray(pk.myers_distance_tiles(qc, ql, cc, cl, interpret=True))
    qc1, ql1 = _encode(QUERIES, max_chars=40)  # L=40 -> 2-word kernel
    cc1, cl1 = _encode(CORPUS, max_chars=40)
    want = np.asarray(
        pk.myers_distance_tiles(qc1, ql1, cc1, cl1, interpret=True)
    )
    np.testing.assert_array_equal(got, want)


def test_levenshtein_sim_tiles_matches_comparator():
    qc, ql = _encode(QUERIES)
    cc, cl = _encode(CORPUS)
    equal = np.zeros((len(QUERIES), len(CORPUS)), bool)
    for i, s1 in enumerate(QUERIES):
        for j, s2 in enumerate(CORPUS):
            equal[i, j] = s1 == s2
    sim = np.asarray(
        pk.levenshtein_sim_tiles(
            qc, ql, cc, cl, jnp.asarray(equal), interpret=True
        )
    )
    lev = C.Levenshtein()
    for i, s1 in enumerate(QUERIES):
        for j, s2 in enumerate(CORPUS):
            want = lev.compare(s1, s2)
            assert sim[i, j] == pytest.approx(want, abs=1e-6), (s1, s2)


def test_scoring_program_with_pallas_enabled(monkeypatch):
    """End-to-end: the scoring program routed through the pallas path agrees
    with the XLA path on top-K results."""
    monkeypatch.setenv("DUKE_TPU_PALLAS", "0")
    import jax

    from sesam_duke_microservice_tpu.core.config import DukeSchema
    from sesam_duke_microservice_tpu.core.records import (
        ID_PROPERTY_NAME,
        Property,
        Record,
    )
    from sesam_duke_microservice_tpu.ops import features as F
    from sesam_duke_microservice_tpu.ops import scoring as S

    schema = DukeSchema(
        threshold=0.8,
        maybe_threshold=None,
        properties=[
            Property(ID_PROPERTY_NAME, id_property=True),
            Property("NAME", C.Levenshtein(), 0.3, 0.88),
        ],
        data_sources=[],
    )
    plan = F.SchemaFeatures.plan(schema)
    names = ["oslo", "osло", "bergen", "bergn", "trondheim", "stavanger",
             "stavangr", "tromso"]
    records = []
    for i, nm in enumerate(names):
        r = Record()
        r.add_value(ID_PROPERTY_NAME, f"d__{i}")
        r.add_value("NAME", nm)
        records.append(r)
    feats = F.extract_batch(plan, records)
    def to_dev(t):
        return {p: {k: jnp.asarray(a) for k, a in d.items()}
                for p, d in t.items()}
    dev = to_dev(feats)
    n = len(records)
    valid = jnp.ones((n,), bool)
    deleted = jnp.zeros((n,), bool)
    group = jnp.full((n,), -1, jnp.int32)
    qrow = jnp.arange(n, dtype=jnp.int32)
    qgroup = jnp.full((n,), -2, jnp.int32)

    def run():
        pair_logits = S.build_pair_logits(plan)
        return jax.tree_util.tree_map(
            np.asarray,
            S.scan_topk(
                pair_logits, dev, dev, valid, deleted, group, qgroup, qrow,
                jnp.float32(0.0), chunk=4, top_k=4, group_filtering=False,
            ),
        )

    base = run()
    monkeypatch.setenv("DUKE_TPU_PALLAS", "1")
    pal = run()
    np.testing.assert_allclose(pal[0], base[0], rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(pal[1], base[1])
    np.testing.assert_array_equal(pal[2], base[2])


def test_myers_gathered_vs_scalar_oracle():
    """The gathered-candidate (ANN rescoring layout) kernel: candidate c of
    query q is a specific row, exact vs the scalar DP."""
    rng = np.random.default_rng(5)
    q, c, L = 6, 7, 16
    qs = ["kitten", "saturday", "", "abc", "a" * 16, "flaw"]
    cands = [
        ["".join(chr(97 + rng.integers(5))
                 for _ in range(rng.integers(0, L + 1)))
         for _ in range(c)]
        for _ in range(q)
    ]
    qc, ql = _encode(qs, max_chars=L)
    cc = np.zeros((q, c, L), np.int32)
    cl = np.zeros((q, c), np.int32)
    for i in range(q):
        ch, ln = _encode(cands[i], max_chars=L)
        cc[i] = np.asarray(ch)
        cl[i] = np.asarray(ln)
    got = np.asarray(pk.myers_distance_gathered(
        qc, ql, jnp.asarray(cc), jnp.asarray(cl), interpret=True
    ))
    for i in range(q):
        for j in range(c):
            assert got[i, j] == C.levenshtein_distance(qs[i], cands[i][j]), (
                qs[i], cands[i][j]
            )


def test_gathered_pair_logits_pallas_wiring(monkeypatch):
    """build_gathered_pair_logits routes single-value Levenshtein through
    the gathered kernel and agrees with the flat path."""
    import jax

    from sesam_duke_microservice_tpu.core.config import DukeSchema
    from sesam_duke_microservice_tpu.core.records import (
        ID_PROPERTY_NAME,
        Property,
        Record,
    )
    from sesam_duke_microservice_tpu.ops import features as F
    from sesam_duke_microservice_tpu.ops import scoring as S

    schema = DukeSchema(
        threshold=0.8, maybe_threshold=None,
        properties=[
            Property(ID_PROPERTY_NAME, id_property=True),
            Property("NAME", C.Levenshtein(), 0.3, 0.88),
        ],
        data_sources=[],
    )
    plan = F.SchemaFeatures.plan(schema)
    names = ["oslo", "bergen", "bergn", "trondheim", "stavanger", "tromso"]
    records = []
    for i, nm in enumerate(names):
        r = Record()
        r.add_value(ID_PROPERTY_NAME, f"d__{i}")
        r.add_value("NAME", nm)
        records.append(r)
    feats = F.extract_batch(plan, records)
    n = len(records)
    c = 4
    rng = np.random.default_rng(0)
    rows = rng.integers(0, n, size=(n, c))
    qf = {p: {k: jnp.asarray(a) for k, a in t.items()}
          for p, t in feats.items()}
    cf = {p: {k: jnp.asarray(a[rows.reshape(-1)]).reshape(
              (n, c) + a.shape[1:])
              for k, a in t.items()}
          for p, t in feats.items()}

    monkeypatch.setenv("DUKE_TPU_PALLAS", "0")
    base = np.asarray(S.build_gathered_pair_logits(plan)(qf, cf))
    monkeypatch.setenv("DUKE_TPU_PALLAS", "1")
    pal = np.asarray(S.build_gathered_pair_logits(plan)(qf, cf))
    np.testing.assert_allclose(pal, base, rtol=1e-5, atol=1e-5)


def _encode_sets(value_lists, slots=12):
    from sesam_duke_microservice_tpu.ops.features import SET_PAD

    n = len(value_lists)
    grams = np.full((n, slots), SET_PAD, np.int32)
    counts = np.zeros((n,), np.int32)
    rng = np.random.default_rng(42)
    pool = rng.integers(-2**31, 2**31 - 1, size=1000).astype(np.int32)
    for i, ids in enumerate(value_lists):
        distinct = sorted({int(pool[k % 1000]) for k in ids})[:slots]
        grams[i, : len(distinct)] = distinct
        counts[i] = len(distinct)
    return jnp.asarray(grams), jnp.asarray(counts)


SETS_Q = [[1, 2, 3], [4, 5], [], [1, 2, 3, 4, 5, 6, 7], [9], [1, 9, 17]]
SETS_C = [[1, 2], [5], [3, 4, 5], [], [1, 2, 3, 4, 5, 6, 7], [8, 9, 10]]


def test_set_intersection_tiles_vs_flat():
    qg, qn = _encode_sets(SETS_Q)
    cg, cn = _encode_sets(SETS_C)
    got = np.asarray(
        pk.set_intersection_tiles(qg, qn, cg, cn, interpret=True)
    )
    nq, nc = len(SETS_Q), len(SETS_C)
    g1 = jnp.repeat(qg, nc, axis=0)
    n1 = jnp.repeat(qn, nc)
    g2 = jnp.tile(cg, (nq, 1))
    n2 = jnp.tile(cn, (nq,))
    want = np.asarray(pw.set_intersection_count(g1, n1, g2, n2)).reshape(
        nq, nc
    )
    np.testing.assert_array_equal(got, want)


def test_qgram_sim_tiles_vs_flat():
    qg, qn = _encode_sets(SETS_Q)
    cg, cn = _encode_sets(SETS_C)
    nq, nc = len(SETS_Q), len(SETS_C)
    equal = jnp.zeros((nq, nc), bool)
    for formula in ("overlap", "jaccard", "dice"):
        got = np.asarray(pk.set_sim_tiles(
            qg, qn, cg, cn, equal, formula=formula, interpret=True
        ))
        g1 = jnp.repeat(qg, nc, axis=0)
        n1 = jnp.repeat(qn, nc)
        g2 = jnp.tile(cg, (nq, 1))
        n2 = jnp.tile(cn, (nq,))
        want = np.asarray(pw.qgram_sim(
            g1, n1, g2, n2, equal.reshape(-1), formula=formula
        )).reshape(nq, nc)
        np.testing.assert_allclose(got, want, atol=1e-6)


def test_token_set_sim_tiles_vs_flat():
    qg, qn = _encode_sets(SETS_Q)
    cg, cn = _encode_sets(SETS_C)
    nq, nc = len(SETS_Q), len(SETS_C)
    equal = jnp.zeros((nq, nc), bool)
    for dice in (False, True):
        got = np.asarray(pk.set_sim_tiles(
            qg, qn, cg, cn, equal,
            formula="dice" if dice else "jaccard", interpret=True
        ))
        g1 = jnp.repeat(qg, nc, axis=0)
        n1 = jnp.repeat(qn, nc)
        g2 = jnp.tile(cg, (nq, 1))
        n2 = jnp.tile(cn, (nq,))
        want = np.asarray(pw.token_set_sim(
            g1, n1, g2, n2, equal.reshape(-1), dice=dice
        )).reshape(nq, nc)
        np.testing.assert_allclose(got, want, atol=1e-6)


def test_scoring_program_set_kernels_pallas_wiring(monkeypatch):
    """The GRAM_SET/TOKEN_SET pallas branch agrees with the XLA path."""
    monkeypatch.setenv("DUKE_TPU_PALLAS", "0")
    import jax

    from sesam_duke_microservice_tpu.core.config import DukeSchema
    from sesam_duke_microservice_tpu.core.records import (
        ID_PROPERTY_NAME,
        Property,
        Record,
    )
    from sesam_duke_microservice_tpu.ops import features as F
    from sesam_duke_microservice_tpu.ops import scoring as S

    schema = DukeSchema(
        threshold=0.8,
        maybe_threshold=None,
        properties=[
            Property(ID_PROPERTY_NAME, id_property=True),
            Property("SSN", C.QGram(), 0.2, 0.9),
            Property("TAGS", C.JaccardIndex(), 0.3, 0.8),
        ],
        data_sources=[],
    )
    plan = F.SchemaFeatures.plan(schema)
    rows = [("12345678", "red green"), ("12345679", "red green"),
            ("87654321", "blue"), ("12340078", "green yellow"),
            ("11112222", "red"), ("12345678", "purple orange")]
    records = []
    for i, (ssn, tags) in enumerate(rows):
        r = Record()
        r.add_value(ID_PROPERTY_NAME, f"d__{i}")
        r.add_value("SSN", ssn)
        r.add_value("TAGS", tags)
        records.append(r)
    feats = F.extract_batch(plan, records)
    def to_dev(t):
        return {p: {k: jnp.asarray(a) for k, a in d.items()}
                for p, d in t.items()}
    dev = to_dev(feats)
    n = len(records)
    valid = jnp.ones((n,), bool)
    deleted = jnp.zeros((n,), bool)
    group = jnp.full((n,), -1, jnp.int32)
    qrow = jnp.arange(n, dtype=jnp.int32)
    qgroup = jnp.full((n,), -2, jnp.int32)

    def run():
        pair_logits = S.build_pair_logits(plan)
        return jax.tree_util.tree_map(
            np.asarray,
            S.scan_topk(
                pair_logits, dev, dev, valid, deleted, group, qgroup, qrow,
                jnp.float32(0.0), chunk=2, top_k=4, group_filtering=False,
            ),
        )

    base = run()
    monkeypatch.setenv("DUKE_TPU_PALLAS", "1")
    pal = run()
    np.testing.assert_allclose(pal[0], base[0], rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(pal[1], base[1])
    np.testing.assert_array_equal(pal[2], base[2])


JW_QUERIES = ["martha", "dixon", "jellyfish", "", "dwayne", "arnab",
              "aabbcc", "identical", "ab"]
JW_CORPUS = ["marhta", "dicksonx", "smellyfish", "word", "duane", "raabn",
             "ccbbaa", "identical", "", "ba"]


def test_jaro_winkler_tiles_vs_scalar_oracle():
    qc, ql = _encode(JW_QUERIES)
    cc, cl = _encode(JW_CORPUS)
    equal = jnp.zeros((len(JW_QUERIES), len(JW_CORPUS)), bool)
    got = np.asarray(pk.jaro_winkler_sim_tiles(
        qc, ql, cc, cl, equal, interpret=True
    ))
    jw = C.JaroWinkler()
    for i, s1 in enumerate(JW_QUERIES):
        for j, s2 in enumerate(JW_CORPUS):
            if not s1 or not s2:
                want = 0.0
            elif s1 == s2:
                want = 1.0  # kernel computes raw jaro = 1 for identical
            else:
                want = jw.compare(s1, s2)
            assert got[i, j] == pytest.approx(want, abs=1e-5), (s1, s2)


def test_jaro_winkler_tiles_vs_flat():
    qc, ql = _encode(JW_QUERIES)
    cc, cl = _encode(JW_CORPUS)
    nq, nc = len(JW_QUERIES), len(JW_CORPUS)
    equal = jnp.zeros((nq, nc), bool)
    got = np.asarray(pk.jaro_winkler_sim_tiles(
        qc, ql, cc, cl, equal, interpret=True
    ))
    c1 = jnp.repeat(qc, nc, axis=0)
    l1 = jnp.repeat(ql, nc)
    c2 = jnp.tile(cc, (nq, 1))
    l2 = jnp.tile(cl, (nq,))
    want = np.asarray(pw.jaro_winkler_sim(
        c1, l1, c2, l2, equal.reshape(-1),
        prefix_scale=0.1, boost_threshold=0.7, max_prefix=4,
    )).reshape(nq, nc)
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_scoring_program_jw_pallas_wiring(monkeypatch):
    """The JaroWinkler CHARS pallas branch agrees with the XLA path."""
    monkeypatch.setenv("DUKE_TPU_PALLAS", "0")
    import jax

    from sesam_duke_microservice_tpu.core.config import DukeSchema
    from sesam_duke_microservice_tpu.core.records import (
        ID_PROPERTY_NAME,
        Property,
        Record,
    )
    from sesam_duke_microservice_tpu.ops import features as F
    from sesam_duke_microservice_tpu.ops import scoring as S

    schema = DukeSchema(
        threshold=0.8,
        maybe_threshold=None,
        properties=[
            Property(ID_PROPERTY_NAME, id_property=True),
            Property("CAPITAL", C.JaroWinkler(), 0.3, 0.85),
        ],
        data_sources=[],
    )
    plan = F.SchemaFeatures.plan(schema)
    names = ["oslo", "olso", "stockholm", "stokholm", "helsinki",
             "reykjavik", "copenhagen", "kobenhavn"]
    records = []
    for i, nm in enumerate(names):
        r = Record()
        r.add_value(ID_PROPERTY_NAME, f"d__{i}")
        r.add_value("CAPITAL", nm)
        records.append(r)
    feats = F.extract_batch(plan, records)
    def to_dev(t):
        return {p: {k: jnp.asarray(a) for k, a in d.items()}
                for p, d in t.items()}
    dev = to_dev(feats)
    n = len(records)
    valid = jnp.ones((n,), bool)
    deleted = jnp.zeros((n,), bool)
    group = jnp.full((n,), -1, jnp.int32)
    qrow = jnp.arange(n, dtype=jnp.int32)
    qgroup = jnp.full((n,), -2, jnp.int32)

    def run():
        pair_logits = S.build_pair_logits(plan)
        return jax.tree_util.tree_map(
            np.asarray,
            S.scan_topk(
                pair_logits, dev, dev, valid, deleted, group, qgroup, qrow,
                jnp.float32(0.0), chunk=4, top_k=4, group_filtering=False,
            ),
        )

    base = run()
    monkeypatch.setenv("DUKE_TPU_PALLAS", "1")
    pal = run()
    np.testing.assert_allclose(pal[0], base[0], rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(pal[1], base[1])
    np.testing.assert_array_equal(pal[2], base[2])
