"""Ingest-scheduler tests (ISSUE 6): continuous cross-request
microbatching, SLO-aware admission control, DRR fairness, reload/shutdown
drain, and the satellite observability (busy Retry-After, feed-abort
counter, scheduler metrics).

The load-bearing contract: scheduler on vs off produces bit-identical
listener event streams and link rows for the same request sequence —
the scheduler only changes WHEN work runs, never what it computes
(dispatch rides the same conflict-splitting ``Workload._run_merged`` the
lock-winner path uses).
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from sesam_duke_microservice_tpu.core.config import parse_config
from sesam_duke_microservice_tpu.engine.scheduler import (
    DatasetGone,
    IngestScheduler,
    SchedulerClosed,
    WorkloadGone,
)
from sesam_duke_microservice_tpu.engine.workload import build_workload
from sesam_duke_microservice_tpu.service.app import DukeApp, serve

CONFIG_XML = """
<DukeMicroService>
  <Deduplication name="people" link-database-type="in-memory">
    <duke>
      <schema>
        <threshold>0.8</threshold>
        <property><name>NAME</name>
          <comparator>levenshtein</comparator><low>0.1</low><high>0.95</high>
        </property>
        <property><name>EMAIL</name>
          <comparator>exact</comparator><low>0.2</low><high>0.95</high>
        </property>
      </schema>
      <data-source class="io.sesam.dukemicroservice.IncrementalDeduplicationDataSource">
        <param name="dataset-id" value="crm"/>
        <column name="name" property="NAME"
                cleaner="no.priv.garshol.duke.cleaners.LowerCaseNormalizeCleaner"/>
        <column name="email" property="EMAIL"/>
      </data-source>
    </duke>
  </Deduplication>
  <Deduplication name="orgs" link-database-type="in-memory">
    <duke>
      <schema>
        <threshold>0.8</threshold>
        <property><name>NAME</name>
          <comparator>levenshtein</comparator><low>0.1</low><high>0.95</high>
        </property>
      </schema>
      <data-source class="io.sesam.dukemicroservice.IncrementalDeduplicationDataSource">
        <param name="dataset-id" value="reg"/>
        <column name="name" property="NAME"/>
      </data-source>
    </duke>
  </Deduplication>
</DukeMicroService>
"""


@pytest.fixture()
def sc(monkeypatch):
    monkeypatch.setenv("MIN_RELEVANCE", "0.05")
    return parse_config(CONFIG_XML)


class EventLog:
    """Ordered listener event tape (sequence equality is the contract)."""

    def __init__(self):
        self.events = []

    def start_processing(self):
        pass

    def batch_ready(self, size):
        self.events.append(("batch_ready", size))

    def matches(self, r1, r2, confidence):
        self.events.append(
            ("match", r1.record_id, r2.record_id, repr(confidence)))

    def matches_perhaps(self, r1, r2, confidence):
        self.events.append(
            ("maybe", r1.record_id, r2.record_id, repr(confidence)))

    def no_match_for(self, record):
        self.events.append(("none", record.record_id))

    def batch_done(self):
        self.events.append(("batch_done",))

    def end_processing(self):
        pass


def link_rows(wl):
    return [
        (l.id1, l.id2, l.status.value, l.kind.value, repr(l.confidence))
        for l in wl.link_database.get_changes_since(0)
    ]


REQUESTS = [
    ("crm", [{"_id": "a1", "name": "acme corp", "email": "a@x.no"},
             {"_id": "a2", "name": "bolt ltd", "email": "b@x.no"}]),
    ("crm", [{"_id": "a3", "name": "acme corp", "email": "a@x.no"}]),
    ("crm", [{"name": "missing id — conversion error"}]),
    ("crm", [{"_id": "a2", "_deleted": True},
             {"_id": "a4", "name": "bolt ltd", "email": "b@x.no"}]),
    ("crm", [{"_id": "a5", "name": "quux as", "email": "q@x.no"}]),
]


def run_off(wl):
    errors = []
    for dataset, entities in REQUESTS:
        try:
            wl.submit_batch(dataset, entities)
        except Exception as e:
            errors.append(type(e).__name__)
    return errors


def run_on(wl):
    sched = IngestScheduler(lambda kind, name: wl)
    errors = []
    try:
        for dataset, entities in REQUESTS:
            try:
                sched.submit("deduplication", wl.name, dataset, entities)
            except Exception as e:
                errors.append(type(e).__name__)
    finally:
        sched.shutdown()
    return errors


@pytest.mark.parametrize("backend", ["device", "ann"])
def test_scheduler_on_off_bit_identical(sc, backend):
    """Same request sequence through the scheduler vs the direct lock
    path: identical event tape, identical link rows, per-request errors
    stay per-request (device and ann backends)."""
    tapes, rows, errs = [], [], []
    for runner in (run_off, run_on):
        wl = build_workload(sc.deduplications["people"], sc,
                            backend=backend, persistent=False)
        log = EventLog()
        wl.processor.add_match_listener(log)
        try:
            errs.append(runner(wl))
            tapes.append(log.events)
            rows.append(link_rows(wl))
        finally:
            wl.close()
    assert errs[0] == errs[1]
    assert len(errs[0]) == 1, (
        "exactly the conversion-error request must fail in both modes"
    )
    assert tapes[0] == tapes[1]
    assert rows[0] == rows[1]
    assert rows[0], "the duplicate upsert must have produced links"


def test_bucket_helpers_exposed():
    from sesam_duke_microservice_tpu.engine.device_matcher import (
        bucket_for,
        query_buckets,
    )

    ladder = query_buckets()
    assert ladder == tuple(sorted(ladder))
    assert bucket_for(1) == ladder[0]
    assert bucket_for(ladder[-1] + 1) == ladder[-1]
    for b in ladder:
        assert bucket_for(b) == b


def test_concurrent_submits_coalesce_into_one_microbatch(sc):
    """Requests queued before the dispatcher starts ride ONE microbatch."""
    wl = build_workload(sc.deduplications["people"], sc, backend="host",
                        persistent=False)
    sched = IngestScheduler(lambda kind, name: wl, start=False)
    try:
        threads = [
            threading.Thread(target=sched.submit, args=(
                "deduplication", "people", "crm",
                [{"_id": f"c{i}a", "name": f"co {i}", "email": f"{i}@x"},
                 {"_id": f"c{i}b", "name": f"co {i}", "email": f"{i}@x"}],
            ))
            for i in range(3)
        ]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            qs = sched.queues()
            if qs and len(qs[0].pending) == 3:
                break
            time.sleep(0.01)
        sched.start()
        for t in threads:
            t.join(timeout=30)
        assert not any(t.is_alive() for t in threads)
        (q,) = sched.queues()
        assert q.microbatches == 1
        assert q.merged_requests == 3
        assert q.dispatched_records == 6
        assert len(link_rows(wl)) == 3  # one dup pair per request
    finally:
        sched.shutdown()
        wl.close()


def test_submit_after_shutdown_raises_closed(sc):
    wl = build_workload(sc.deduplications["people"], sc, backend="host",
                        persistent=False)
    sched = IngestScheduler(lambda kind, name: wl)
    sched.shutdown()
    with pytest.raises(SchedulerClosed):
        sched.submit("deduplication", "people", "crm", [{"_id": "x"}])
    wl.close()


def test_shutdown_drains_queued_requests(sc):
    """Requests queued at shutdown complete normally — never lost, never
    completed twice."""
    wl = build_workload(sc.deduplications["people"], sc, backend="host",
                        persistent=False)
    sched = IngestScheduler(lambda kind, name: wl, start=False)
    done = []
    lock = threading.Lock()

    def one(i):
        sched.submit("deduplication", "people", "crm",
                     [{"_id": f"d{i}", "name": f"drain {i}",
                       "email": f"d{i}@x"}])
        with lock:
            done.append(i)

    threads = [threading.Thread(target=one, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        qs = sched.queues()
        if qs and len(qs[0].pending) == 4:
            break
        time.sleep(0.01)
    sched.start()
    sched.shutdown()  # stops admission, drains, joins
    for t in threads:
        t.join(timeout=10)
    assert sorted(done) == [0, 1, 2, 3]
    assert wl.index.find_record_by_id("crm__d0") is not None
    assert wl.index.find_record_by_id("crm__d3") is not None
    wl.close()


def test_workload_gone_fails_queued_requests(sc):
    wl = build_workload(sc.deduplications["people"], sc, backend="host",
                        persistent=False)
    live = {"wl": wl}
    sched = IngestScheduler(lambda kind, name: live["wl"], start=False)
    results = []

    def one():
        try:
            sched.submit("deduplication", "people", "crm",
                         [{"_id": "g1", "name": "gone", "email": "g@x"}])
            results.append("ok")
        except WorkloadGone:
            results.append("gone")

    t = threading.Thread(target=one)
    t.start()
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        qs = sched.queues()
        if qs and len(qs[0].pending) == 1:
            break
        time.sleep(0.01)
    live["wl"] = None  # a reload removed the workload
    sched.start()
    t.join(timeout=10)
    assert results == ["gone"]
    sched.shutdown()
    wl.close()


def test_reload_dropping_dataset_fails_request_as_dataset_gone(sc):
    """A queued request whose dataset the replacement workload no longer
    defines fails with DatasetGone (the HTTP 404), not a bare KeyError
    500 out of the merge."""
    # 'orgs' stands in for the replacement: it has no 'crm' datasource
    replacement = build_workload(sc.deduplications["orgs"], sc,
                                 backend="host", persistent=False)
    sched = IngestScheduler(lambda kind, name: replacement)
    try:
        with pytest.raises(DatasetGone) as exc:
            sched.submit("deduplication", "people", "crm",
                         [{"_id": "dg1", "name": "x", "email": "x@x"}])
        assert exc.value.dataset_id == "crm"
    finally:
        sched.shutdown()
        replacement.close()


def test_removed_workload_queue_ages_out(sc):
    """A tenant queue whose workload a reload removed disappears from the
    scheduler (no stale zero-depth series, no dead DRR rotation entry)."""
    people = build_workload(sc.deduplications["people"], sc, backend="host",
                            persistent=False)
    orgs = build_workload(sc.deduplications["orgs"], sc, backend="host",
                          persistent=False)
    registry = {"people": people, "orgs": orgs}
    sched = IngestScheduler(lambda kind, name: registry.get(name))
    try:
        sched.submit("deduplication", "people", "crm",
                     [{"_id": "ao1", "name": "ager", "email": "a@x"}])
        assert [q.name for q in sched.queues()] == ["people"]
        del registry["people"]  # reload removed it
        # traffic to another tenant drives the rounds that age it out
        sched.submit("deduplication", "orgs", "reg",
                     [{"_id": "ao2", "name": "other tenant"}])
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if all(q.name != "people" for q in sched.queues()):
                break
            sched.submit("deduplication", "orgs", "reg",
                         [{"_id": "ao3", "name": "other tenant again"}])
        assert all(q.name != "people" for q in sched.queues())
    finally:
        sched.shutdown()
        people.close()
        orgs.close()


def test_sparse_tenant_window_does_not_stall_full_tenant(sc, monkeypatch):
    """A sparse tenant inside its coalesce window must not hold the
    dispatcher: tenants with dispatchable work are served first and the
    sparse batch rides a later round (or its window expiry)."""
    monkeypatch.setenv("DUKE_SCHED_WINDOW_MS", "500")
    sparse = build_workload(sc.deduplications["orgs"], sc, backend="host",
                            persistent=False)
    full = build_workload(sc.deduplications["people"], sc, backend="host",
                          persistent=False)
    registry = {"orgs": sparse, "people": full}
    sched = IngestScheduler(lambda kind, name: registry[name], start=False)
    times = {}

    def sparse_post():
        sched.submit("deduplication", "orgs", "reg",
                     [{"_id": "sp1", "name": "sparse tenant"}])
        times["sparse"] = time.monotonic()

    def full_post(i):
        sched.submit("deduplication", "people", "crm",
                     [{"_id": f"fl{i}-{j}", "name": f"full {i} {j}",
                       "email": f"f{i}{j}@x"} for j in range(8)])
        times.setdefault("full_first", time.monotonic())

    ts = threading.Thread(target=sparse_post)
    ts.start()
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if sum(len(q.pending) for q in sched.queues()) == 1:
            break
        time.sleep(0.01)
    # 4 x 8 records fills the 32-query bucket (conftest ladder 8,32), so
    # the full tenant is genuinely dispatchable with no window to honor
    tf = [threading.Thread(target=full_post, args=(i,)) for i in range(4)]
    for t in tf:
        t.start()
    while time.monotonic() < deadline:
        if sum(len(q.pending) for q in sched.queues()) == 5:
            break
        time.sleep(0.01)
    t0 = time.monotonic()
    sched.start()
    for t in tf:
        t.join(timeout=30)
    ts.join(timeout=30)
    assert "sparse" in times and "full_first" in times
    # the full tenant's first completion must not have waited behind the
    # sparse tenant's 500 ms window
    assert times["full_first"] - t0 < 0.4, (
        "full tenant stalled behind the sparse tenant's coalesce window"
    )
    sched.shutdown()
    sparse.close()
    full.close()


def test_drr_fairness_hot_tenant_cannot_starve(sc, monkeypatch):
    """A hot tenant's deep queue must not delay another workload's single
    request to the end of the hot backlog: DRR gives every workload a
    quantum per round."""
    monkeypatch.setenv("DUKE_SCHED_QUANTUM", "8")
    monkeypatch.setenv("DUKE_SCHED_WINDOW_MS", "0")
    hot = build_workload(sc.deduplications["people"], sc, backend="host",
                         persistent=False)
    cold = build_workload(sc.deduplications["orgs"], sc, backend="host",
                          persistent=False)
    registry = {"people": hot, "orgs": cold}
    sched = IngestScheduler(lambda kind, name: registry[name], start=False)
    hot_times = []
    cold_times = []
    lock = threading.Lock()

    def hot_post(i):
        sched.submit("deduplication", "people", "crm",
                     [{"_id": f"h{i}-{j}", "name": f"hot {i} {j}",
                       "email": f"h{i}{j}@x"} for j in range(8)])
        with lock:
            hot_times.append(time.monotonic())

    def cold_post():
        sched.submit("deduplication", "orgs", "reg",
                     [{"_id": "cold1", "name": "the cold tenant"}])
        with lock:
            cold_times.append(time.monotonic())

    threads = [threading.Thread(target=hot_post, args=(i,))
               for i in range(10)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        depth = sum(len(q.pending) for q in sched.queues())
        if depth == 10:
            break
        time.sleep(0.01)
    tc = threading.Thread(target=cold_post)
    tc.start()
    while time.monotonic() < deadline:
        if sum(len(q.pending) for q in sched.queues()) == 11:
            break
        time.sleep(0.01)
    sched.start()
    for t in threads:
        t.join(timeout=30)
    tc.join(timeout=30)
    assert cold_times and len(hot_times) == 10
    # the cold request must complete well before the hot backlog drains
    # (DRR: it rides round 1 or 2, not round 10)
    assert cold_times[0] < sorted(hot_times)[4], (
        "cold tenant starved behind the hot queue"
    )
    # the hot tenant was actually split across rounds, not one megabatch
    hot_q = next(q for q in sched.queues() if q.name == "people")
    assert hot_q.microbatches >= 5
    sched.shutdown()
    hot.close()
    cold.close()


# -- HTTP surface ----------------------------------------------------------


class _NoRedirect(urllib.request.HTTPRedirectHandler):
    def redirect_request(self, *args, **kwargs):
        return None


_opener = urllib.request.build_opener(_NoRedirect)


def request(url, method="GET", body=None, headers=None, timeout=30):
    req = urllib.request.Request(url, data=body, method=method,
                                 headers=headers or {})
    try:
        with _opener.open(req, timeout=timeout) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def post_json(url, payload):
    return request(url, "POST", json.dumps(payload).encode(),
                   {"Content-Type": "application/json"})


def _serve(app):
    server = serve(app, port=0, host="127.0.0.1")
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server, f"http://127.0.0.1:{server.server_address[1]}"


def test_backpressure_429_with_retry_after(sc, monkeypatch):
    """Past DUKE_SCHED_QUEUE_MAX pending requests the service answers 429
    with a Retry-After header instead of queueing unboundedly."""
    monkeypatch.setenv("DUKE_SCHEDULER", "1")  # pin against the CI=0 leg
    monkeypatch.setenv("DUKE_SCHED_QUEUE_MAX", "2")
    monkeypatch.setenv("DUKE_SCHED_WINDOW_MS", "0")
    app = DukeApp(sc, persistent=False)
    server, url = _serve(app)
    wl = app.deduplications["people"]
    results = []
    lock = threading.Lock()

    def post_one(i):
        status, headers, _ = post_json(
            url + "/deduplication/people/crm",
            [{"_id": f"bp{i}-{j}", "name": f"press {i} {j}",
              "email": f"bp{i}{j}@x"} for j in range(8)])
        with lock:
            results.append((status, headers.get("Retry-After")))

    try:
        wl.lock.acquire()  # wedge the dispatcher mid-batch
        threads = []
        for i in range(6):
            t = threading.Thread(target=post_one, args=(i,))
            t.start()
            threads.append(t)
            time.sleep(0.1)  # deterministic arrival order
        # give the last submissions time to hit admission
        time.sleep(0.3)
    finally:
        wl.lock.release()
    for t in threads:
        t.join(timeout=30)
    assert not any(t.is_alive() for t in threads)
    statuses = sorted(s for s, _ in results)
    assert set(statuses) <= {200, 429}
    assert statuses.count(429) >= 1, results
    assert statuses.count(200) >= 2, results
    for status, retry_after in results:
        if status == 429:
            assert retry_after is not None and int(retry_after) >= 1
    # rejected requests are visible on the admission counter and /stats
    status, _, body = request(url + "/stats")
    assert status == 200
    sched_block = json.loads(body)["scheduler"]
    people = next(w for w in sched_block["workloads"]
                  if w["name"] == "people")
    assert people["rejected"] >= 1
    assert people["retry_after_hint"] >= 1
    server.shutdown()
    app.close()


def test_reload_retargets_queued_requests(sc, tmp_path, monkeypatch):
    """A hot reload mid-backlog must lose nothing: queued requests land
    on the replacement workload (same name) and every record is applied
    exactly once."""
    monkeypatch.setenv("MIN_RELEVANCE", "0.05")
    monkeypatch.setenv("DUKE_SCHEDULER", "1")  # pin against the CI=0 leg
    xml = CONFIG_XML.replace(
        "<DukeMicroService>", f'<DukeMicroService dataFolder="{tmp_path}">'
    )
    app = DukeApp(parse_config(xml), persistent=True)
    server, url = _serve(app)
    wl = app.deduplications["people"]
    statuses = []
    lock = threading.Lock()

    def post_one(i):
        status, _, _ = post_json(
            url + "/deduplication/people/crm",
            [{"_id": f"rl{i}a", "name": f"reload {i}", "email": f"r{i}@x"},
             {"_id": f"rl{i}b", "name": f"reload b {i}",
              "email": f"rb{i}@x"}])
        with lock:
            statuses.append(status)

    wl.lock.acquire()
    try:
        threads = [threading.Thread(target=post_one, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            depth = sum(len(q.pending)
                        for q in app.scheduler.queues())
            if depth >= 2:  # dispatcher may hold some, blocked on the lock
                break
            time.sleep(0.01)
        reloader = threading.Thread(
            target=app.reload_from_string, args=(xml,))
        reloader.start()
        time.sleep(0.2)
    finally:
        wl.lock.release()
    for t in threads:
        t.join(timeout=60)
    reloader.join(timeout=60)
    assert statuses == [200, 200, 200]
    # every record applied exactly once on the final (replacement) workload
    wl2 = app.deduplications["people"]
    assert wl2 is not wl
    for i in range(3):
        assert wl2.index.find_record_by_id(f"crm__rl{i}a") is not None
        assert wl2.index.find_record_by_id(f"crm__rl{i}b") is not None
    server.shutdown()
    app.close()


def test_scheduler_off_env_restores_direct_path(sc, monkeypatch):
    monkeypatch.setenv("DUKE_SCHEDULER", "0")
    app = DukeApp(sc, persistent=False)
    assert app.scheduler is None
    server, url = _serve(app)
    status, _, body = post_json(
        url + "/deduplication/people/crm",
        [{"_id": "off1", "name": "no scheduler", "email": "o@x"}])
    assert status == 200 and json.loads(body)["success"]
    status, _, body = request(url + "/stats")
    assert "scheduler" not in json.loads(body)
    server.shutdown()
    app.close()


def test_per_request_error_stays_per_request_over_http(sc):
    app = DukeApp(sc, persistent=False)
    server, url = _serve(app)
    status, _, body = post_json(url + "/deduplication/people/crm",
                                [{"name": "no id"}])
    assert status == 500 and b"Batch processing failed" in body
    status, _, _ = post_json(
        url + "/deduplication/people/crm",
        [{"_id": "ok1", "name": "fine", "email": "f@x"}])
    assert status == 200
    server.shutdown()
    app.close()


def test_busy_503_carries_retry_after(sc, monkeypatch):
    """Read-path lock-timeout 503s get a Retry-After derived from recent
    write-hold observations; the reference body is unchanged."""
    import sesam_duke_microservice_tpu.service.app as app_module

    app = DukeApp(sc, persistent=False)
    server, url = _serve(app)
    wl = app.deduplications["people"]
    # two observations -> EWMA 0.7*4 + 0.3*1 = 3.1 -> ceil 4
    wl.note_lock_hold(4.0)
    wl.note_lock_hold(1.0)
    assert wl.busy_retry_after() == 4
    monkeypatch.setattr(app_module, "READ_LOCK_TIMEOUT_SECONDS", 0.05)
    with wl.lock:
        status, headers, body = request(url + "/deduplication/people")
        assert status == 503
        assert b"being written to" in body
        assert headers.get("Retry-After") == "4"
    server.shutdown()
    app.close()


def test_feed_abort_counter_on_midstream_removal(sc, monkeypatch):
    """The mid-stream workload-removal abort increments
    duke_feed_aborts_total and shows in /stats (the lock-starvation abort
    shares the counter; its 120-retry wait is impractical to drive in a
    unit test)."""
    from sesam_duke_microservice_tpu.links.base import (
        Link,
        LinkKind,
        LinkStatus,
    )

    monkeypatch.setenv("FEED_PAGE_SIZE", "10")
    app = DukeApp(sc, persistent=False)
    wl = app.deduplications["people"]
    base_ts = 1_700_000_000_000
    for i in range(50):
        wl.link_database.assert_link(
            Link(f"crm__a{i}", f"crm__b{i}", LinkStatus.INFERRED,
                 LinkKind.DUPLICATE, 0.9, timestamp=base_ts + i))
    real_page = wl.links_page
    pages = []

    def hooked(since, limit):
        pages.append(since)
        if len(pages) == 2:
            app.deduplications = {}
        return real_page(since, limit)

    wl.links_page = hooked
    server, url = _serve(app)
    try:
        request(url + "/deduplication/people?since=0")
    except Exception:
        pass  # truncated chunked framing surfaces as a transport error
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if app.feed_aborts["workload_removed"]:
            break
        time.sleep(0.01)
    assert app.feed_aborts["workload_removed"] == 1
    app.deduplications = {"people": wl}
    status, _, body = request(url + "/stats")
    assert json.loads(body)["feed_aborts"]["workload_removed"] == 1
    status, _, body = request(url + "/metrics")
    text = body.decode()
    assert 'duke_feed_aborts_total{reason="workload_removed"} 1' in text
    assert 'duke_feed_aborts_total{reason="lock_starved"} 0' in text
    server.shutdown()
    app.close()


def test_metrics_and_stats_expose_scheduler(sc, monkeypatch):
    monkeypatch.setenv("DUKE_SCHEDULER", "1")  # pin against the CI=0 leg
    app = DukeApp(sc, persistent=False)
    server, url = _serve(app)
    status, _, _ = post_json(
        url + "/deduplication/people/crm",
        [{"_id": "m1", "name": "metrics person", "email": "m@x"}])
    assert status == 200
    status, _, body = request(url + "/stats")
    block = json.loads(body)["scheduler"]
    assert block["queue_max"] >= 1 and block["window_ms"] >= 0
    people = next(w for w in block["workloads"] if w["name"] == "people")
    assert people["admitted"] == 1 and people["microbatches"] == 1
    assert people["records_dispatched"] == 1
    status, _, body = request(url + "/metrics")
    text = body.decode()
    for family in ("duke_sched_queue_depth", "duke_sched_queue_records",
                   "duke_sched_admission_total",
                   "duke_sched_microbatches_total",
                   "duke_sched_merged_requests_total",
                   "duke_sched_wait_seconds",
                   "duke_sched_microbatch_records"):
        assert family in text, family
    server.shutdown()
    app.close()
