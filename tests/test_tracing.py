"""Distributed tracing + flight recorder (ISSUE 2).

Covers the tentpole's contract points: traceparent round-trips, span
nesting across the worker-thread pool, tail-latch retention of slow
unsampled requests, dispatch op-tuple propagation (leader + follower
spans sharing one trace id through the digest handshake), Chrome
trace-event export validity, and the ``/debug/*`` HTTP surface.
"""

import json
import socket
import struct
import threading
import time
import urllib.error
import urllib.request

import pytest

from sesam_duke_microservice_tpu.parallel import dispatch
from sesam_duke_microservice_tpu.telemetry import tracing
from sesam_duke_microservice_tpu.utils import profiling

from test_dispatch_auth import _tiny_index

KEY = ("deduplication", "t")


# -- traceparent -------------------------------------------------------------

def test_traceparent_round_trip():
    tid = "0af7651916cd43dd8448eb211c80319c"
    sid = "b7ad6b7169203331"
    for sampled in (True, False):
        ctx = tracing.parse_traceparent(
            tracing.format_traceparent(tid, sid, sampled))
        assert ctx.trace_id == tid
        assert ctx.parent_id == sid
        assert ctx.sampled is sampled


@pytest.mark.parametrize("bad", [
    None,
    "",
    "not-a-traceparent",
    "00-0af7651916cd43dd8448eb211c80319c-b7ad6b7169203331",  # missing flags
    "00-" + "0" * 32 + "-b7ad6b7169203331-01",               # zero trace id
    "00-0af7651916cd43dd8448eb211c80319c-" + "0" * 16 + "-01",
    "ff-0af7651916cd43dd8448eb211c80319c-b7ad6b7169203331-01",
    "00-0AF7651916CD43DD8448EB211C80319C-b7ad6b7169203331-01",  # upper hex
])
def test_traceparent_rejects_malformed(bad):
    assert tracing.parse_traceparent(bad) is None


# -- span nesting ------------------------------------------------------------

def test_span_nesting_across_threads():
    recorder = tracing.FlightRecorder(4, 4)
    with tracing.start_trace("root", sampled=True,
                             recorder=recorder) as root:
        with tracing.span("parent") as parent:
            ctx = tracing.current_context()

            def worker():
                with tracing.attach(ctx):
                    with tracing.span("child"):
                        pass

            t = threading.Thread(target=worker)
            t.start()
            t.join()
    record = recorder.get(root.trace_id)
    assert record is not None
    by_name = {s.name: s for s in record.spans}
    assert set(by_name) == {"root", "parent", "child"}
    assert by_name["child"].parent_id == parent.span_id
    assert by_name["parent"].parent_id == root.span_id
    assert by_name["child"].trace_id == root.trace_id


def test_span_is_noop_outside_a_trace():
    assert tracing.current_context() is None
    with tracing.span("orphan") as s:
        assert s is None  # no active trace: nothing recorded, no error


def test_span_cap_bounds_a_pathological_request(monkeypatch):
    monkeypatch.setenv("TRACE_MAX_SPANS", "8")
    recorder = tracing.FlightRecorder(4, 4)
    with tracing.start_trace("root", sampled=True,
                             recorder=recorder) as root:
        for i in range(50):
            with tracing.span(f"s{i}"):
                pass
    record = recorder.get(root.trace_id)
    assert len(record.spans) <= 9  # 8 capped children + the root
    assert record.dropped >= 40
    assert (root.attributes or {}).get("spans_dropped") == record.dropped


# -- tail latch --------------------------------------------------------------

def test_tail_latch_retains_slow_unsampled_trace(monkeypatch):
    monkeypatch.setenv("TRACE_SAMPLE_RATE", "0")
    monkeypatch.setenv("TRACE_SLOW_MS", "1")
    recorder = tracing.FlightRecorder(4, 4)
    with tracing.start_trace("slow", recorder=recorder) as root:
        time.sleep(0.005)
    assert root.trace_id is not None
    record = recorder.get(root.trace_id)
    assert record is not None and record.slow and not record.sampled


def test_fast_unsampled_trace_digested_but_not_retained(monkeypatch):
    monkeypatch.setenv("TRACE_SAMPLE_RATE", "0")
    monkeypatch.setenv("TRACE_SLOW_MS", "60000")
    recorder = tracing.FlightRecorder(4, 4)
    with tracing.start_trace("fast", recorder=recorder) as root:
        pass
    assert recorder.get(root.trace_id) is None
    digests = recorder.digests()
    assert len(digests) == 1
    assert digests[0]["trace_id"] == root.trace_id
    assert digests[0]["retained"] is False


def test_errored_trace_is_retained(monkeypatch):
    monkeypatch.setenv("TRACE_SAMPLE_RATE", "0")
    monkeypatch.setenv("TRACE_SLOW_MS", "60000")
    recorder = tracing.FlightRecorder(4, 4)
    with pytest.raises(RuntimeError):
        with tracing.start_trace("boom", recorder=recorder) as root:
            raise RuntimeError("kaput")
    record = recorder.get(root.trace_id)
    assert record is not None and record.status == "error"


def test_trace_ring_evicts_oldest():
    recorder = tracing.FlightRecorder(2, 16)
    ids = []
    for i in range(4):
        with tracing.start_trace(f"t{i}", sampled=True,
                                 recorder=recorder) as root:
            pass
        ids.append(root.trace_id)
    assert recorder.get(ids[0]) is None and recorder.get(ids[1]) is None
    assert recorder.get(ids[2]) is not None
    assert [s["trace_id"] for s in recorder.summaries()] == [ids[3], ids[2]]


def test_eviction_prefers_unremarkable_over_slow_traces(monkeypatch):
    """A client stamping every request sampled=01 must not flush the
    slow traces the tail latch retained (eviction skips slow/errored
    records while any sampled-only record remains)."""
    monkeypatch.setenv("TRACE_SLOW_MS", "1")
    recorder = tracing.FlightRecorder(2, 16)
    with tracing.start_trace("slow", sampled=True,
                             recorder=recorder) as slow_root:
        time.sleep(0.005)
    monkeypatch.setenv("TRACE_SLOW_MS", "60000")
    fast_ids = []
    for i in range(3):
        with tracing.start_trace(f"fast{i}", sampled=True,
                                 recorder=recorder) as root:
            pass
        fast_ids.append(root.trace_id)
    assert recorder.get(slow_root.trace_id) is not None  # survived
    assert recorder.get(fast_ids[-1]) is not None        # newest kept
    assert len(recorder.summaries()) == 2


def test_repeat_retention_merges_into_one_tree():
    """A follower replaying several ops of one request retains under one
    trace id several times — the trees must merge, not overwrite."""
    recorder = tracing.FlightRecorder(4, 8)
    tc = {"trace_id": "ab" * 16, "parent_id": "cd" * 8, "sampled": True}
    for name in ("follower:commit", "follower:score"):
        with tracing.capture_remote(name, tc, recorder=recorder):
            pass
    record = recorder.get("ab" * 16)
    assert {s.name for s in record.spans} == {
        "follower:commit", "follower:score"}
    assert len(recorder.summaries()) == 1


def test_digest_carries_phase_seconds():
    recorder = tracing.FlightRecorder(4, 4)
    with tracing.start_trace("batch", sampled=True, recorder=recorder):
        base = time.monotonic_ns()
        tracing.add_span("encode", base, base + 2_000_000)
        tracing.add_span("score", base, base + 3_000_000)
    phases = recorder.digests()[0]["phase_seconds"]
    assert phases["encode"] == pytest.approx(0.002)
    assert phases["score"] == pytest.approx(0.003)


# -- dispatch propagation ----------------------------------------------------

def test_with_trace_ctx_appends_only_inside_a_trace():
    op = ("commit", KEY, ["r"])
    assert dispatch.with_trace_ctx(op) == op  # no active trace
    with tracing.start_trace("x", sampled=True,
                             recorder=tracing.FlightRecorder(2, 2)) as root:
        tagged = dispatch.with_trace_ctx(op)
    assert tagged[:3] == op
    assert tagged[3]["trace_id"] == root.trace_id
    assert tagged[3]["sampled"] is True
    assert dispatch._op_trace_ctx(tagged, 3) == tagged[3]
    assert dispatch._op_trace_ctx(op, 3) is None


class _SpanFollower:
    """Loopback follower replaying commits into a real replica index and
    answering the digest handshake with its replay spans (the production
    follower path's frame shape, driven without jax.distributed)."""

    def __init__(self, sock):
        self.sock = sock
        self.index, _, _ = _tiny_index()
        self.thread = threading.Thread(target=self._run, daemon=True)
        self.thread.start()

    def _run(self):
        last_seq = 0
        while True:
            try:
                op, _epoch, seq = dispatch._recv_op(self.sock)
            except (EOFError, OSError):
                return
            if seq <= last_seq:
                continue  # dup frame (CI chaos leg): production fencing drops
            last_seq = seq
            if op[0] != "commit":
                continue
            _, _key, records = op[:3]
            cap = tracing.capture_remote(
                "follower:commit", dispatch._op_trace_ctx(op, 3),
                {"records": len(records), "process": "follower"},
            )
            with cap:
                for r in records:
                    self.index.index(r)
                self.index.commit()
            self.sock.sendall(dispatch._digest_frame(
                True, self.index._mirror_digest, cap.wire()))


def test_leader_and_follower_spans_share_one_trace(monkeypatch):
    """THE acceptance shape: a commit broadcast carries the leader's
    trace context, the follower's replay ships back through the digest
    handshake, and one tree holds both sides under one trace id."""
    a, b = socket.socketpair()
    d = dispatch.Dispatcher(app=None)
    d._conns = [a]
    follower = _SpanFollower(b)
    recorder = tracing.FlightRecorder(4, 4)
    try:
        idx, _, rec = _tiny_index()
        idx._dispatch_key = KEY
        monkeypatch.setattr(dispatch, "_DISPATCHER", d)
        with tracing.start_trace("POST /deduplication/:name/:datasetId",
                                 sampled=True, recorder=recorder) as root:
            idx.index(rec("a", "acme"))
            idx.commit()
        assert d._failed is None
        record = recorder.get(root.trace_id)
        assert record is not None
        remote = [s for s in record.spans if s.name == "follower:commit"]
        assert len(remote) == 1
        assert remote[0].trace_id == root.trace_id
        assert (remote[0].attributes or {}).get("remote") is True
        assert (remote[0].attributes or {}).get("process") == "follower"
        # digests still verified end to end
        assert idx._mirror_digest == follower.index._mirror_digest
    finally:
        a.close()
        b.close()


def test_follower_session_ships_spans_in_digest_frame():
    """Drive the production ``_FollowerSession`` op handler directly and
    decode the frame it answers with."""
    import types

    sent = []
    session = dispatch._FollowerSession(sent.append)

    class _FakeReplica:
        def __init__(self):
            self.index = types.SimpleNamespace(_mirror_digest=b"\x07" * 32)

        def apply_commit(self, records):
            with tracing.span("replica:index"):
                pass

    session.replicas[KEY] = _FakeReplica()
    tc = {"trace_id": "ab" * 16, "parent_id": "cd" * 8, "sampled": True}
    assert session.handle(("commit", KEY, ["r1", "r2"], tc))
    assert len(sent) == 1
    frame = sent[0]
    fixed = dispatch._DIGEST_LEN
    assert frame[:len(dispatch._DIGEST_MAGIC)] == dispatch._DIGEST_MAGIC
    (blob_len,) = struct.unpack(">I", frame[fixed:fixed + 4])
    rows = json.loads(frame[fixed + 4:fixed + 4 + blob_len])
    names = {r["name"] for r in rows}
    assert names == {"follower:commit", "replica:index"}
    assert all(r["trace_id"] == "ab" * 16 for r in rows)


def test_follower_session_without_ctx_sends_empty_blob():
    import types

    sent = []
    session = dispatch._FollowerSession(sent.append)
    replica = types.SimpleNamespace(
        index=types.SimpleNamespace(_mirror_digest=b"\x01" * 32),
        apply_commit=lambda records: None,
    )
    session.replicas[KEY] = replica
    assert session.handle(("commit", KEY, ["r1"]))  # historical op shape
    fixed = dispatch._DIGEST_LEN
    (blob_len,) = struct.unpack(">I", sent[0][fixed:fixed + 4])
    assert blob_len == 0


# -- chrome export -----------------------------------------------------------

def test_chrome_export_schema():
    recorder = tracing.FlightRecorder(4, 4)
    with tracing.start_trace("GET /x", sampled=True,
                             recorder=recorder) as root:
        with tracing.span("encode", {"records": 3}):
            pass
        tracing.graft_remote(json.dumps([{
            "trace_id": root.trace_id, "span_id": "ee" * 8,
            "parent_id": None, "name": "follower:commit",
            "offset_ns": 0, "duration_ns": 1000, "status": "ok",
            "attributes": {},
        }]).encode())
    out = tracing.chrome_trace(recorder.get(root.trace_id))
    json.dumps(out)  # must be valid JSON end to end
    assert out["displayTimeUnit"] == "ms"
    events = out["traceEvents"]
    complete = [e for e in events if e["ph"] == "X"]
    assert {e["name"] for e in complete} == {
        "GET /x", "encode", "follower:commit"}
    for e in complete:
        assert isinstance(e["ts"], float) and isinstance(e["dur"], float)
        assert e["dur"] >= 0 and "pid" in e and "tid" in e
    # remote spans land on the follower tid row
    assert [e["tid"] for e in complete if e["name"] == "follower:commit"] \
        == [1]
    assert any(e["ph"] == "M" for e in events)


# -- HTTP surface ------------------------------------------------------------

@pytest.fixture(scope="module")
def server_url():
    import os

    from sesam_duke_microservice_tpu.core.config import parse_config
    from sesam_duke_microservice_tpu.service.app import DukeApp, serve
    from test_service import CONFIG_XML

    os.environ["MIN_RELEVANCE"] = "0.05"
    app = DukeApp(parse_config(CONFIG_XML), persistent=False)
    server = serve(app, port=0, host="127.0.0.1")
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{server.server_address[1]}"
    server.shutdown()
    del os.environ["MIN_RELEVANCE"]


def _request(url, method="GET", body=None, headers=None):
    req = urllib.request.Request(url, data=body, method=method,
                                 headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def test_sampled_batch_lands_in_flight_recorder(server_url):
    tp = tracing.format_traceparent("12" * 16, "34" * 8, True)
    body = json.dumps([
        {"_id": "t1", "name": "ole hansen", "email": "o@x"},
        {"_id": "t2", "name": "ole hanse", "email": "o@x"},
    ]).encode()
    status, headers, _ = _request(
        server_url + "/deduplication/people/crm", "POST", body,
        {"Content-Type": "application/json", "traceparent": tp})
    assert status == 200
    assert headers["X-Trace-Id"] == "12" * 16  # inbound trace honored

    status, _, out = _request(server_url + "/debug/traces")
    assert status == 200
    rows = json.loads(out)["traces"]
    mine = [r for r in rows if r["trace_id"] == "12" * 16]
    assert mine and mine[0]["name"] == "POST /deduplication/:name/:datasetId"

    status, _, out = _request(server_url + "/debug/traces/" + "12" * 16)
    assert status == 200
    tree = json.loads(out)
    names = {s["name"] for s in tree["spans"]}
    # the acceptance tree: root HTTP span + all four engine phase spans
    assert "POST /deduplication/:name/:datasetId" in names
    assert {"encode", "retrieve", "score", "persist"} <= names

    status, _, out = _request(
        server_url + "/debug/traces/" + "12" * 16 + "?format=chrome")
    assert status == 200
    chrome = json.loads(out)
    assert chrome["traceEvents"] and any(
        e.get("ph") == "X" for e in chrome["traceEvents"])


def test_slow_unsampled_request_retained_over_http(server_url, monkeypatch):
    monkeypatch.setenv("TRACE_SAMPLE_RATE", "0")
    monkeypatch.setenv("TRACE_SLOW_MS", "0.0001")
    status, headers, _ = _request(server_url + "/healthz")
    assert status == 200
    tid = headers["X-Trace-Id"]
    status, _, out = _request(server_url + "/debug/traces/" + tid)
    assert status == 200
    assert json.loads(out)["slow"] is True


def test_debug_requests_ring_always_on(server_url, monkeypatch):
    monkeypatch.setenv("TRACE_SAMPLE_RATE", "0")
    monkeypatch.setenv("TRACE_SLOW_MS", "60000")
    status, headers, _ = _request(server_url + "/stats")
    assert status == 200
    tid = headers["X-Trace-Id"]
    # the digest lands at root-span exit, AFTER the response is on the
    # wire — a fresh connection can race the handler thread's last few
    # instructions, so poll briefly
    mine = []
    for _ in range(50):
        status, _, out = _request(server_url + "/debug/requests")
        rows = json.loads(out)["requests"]
        mine = [r for r in rows if r["trace_id"] == tid]
        if mine:
            break
        time.sleep(0.02)
    assert mine and mine[0]["retained"] is False
    assert mine[0]["name"] == "GET /stats"
    # but the unretained request still answered 404 on the tree endpoint
    status, _, _ = _request(server_url + "/debug/traces/" + tid)
    assert status == 404


def test_debug_trace_endpoint_validation(server_url):
    status, _, _ = _request(server_url + "/debug/traces/" + "ab" * 16)
    assert status == 404
    status, _, _ = _request(
        server_url + "/debug/traces/" + "ab" * 16 + "?format=xml")
    assert status == 400


def test_profile_endpoint_capture_cycle(server_url, monkeypatch):
    calls = []
    monkeypatch.setattr(profiling, "profiler_start",
                        lambda d: calls.append(("start", d)))
    monkeypatch.setattr(profiling, "profiler_stop",
                        lambda: calls.append(("stop",)))
    status, _, out = _request(server_url + "/debug/profile")
    assert status == 200 and json.loads(out)["capturing"] is None
    try:
        status, _, out = _request(
            server_url + "/debug/profile?seconds=30", "POST", b"")
        assert status == 200
        assert json.loads(out)["capturing"]["seconds"] == 30.0
        assert calls and calls[0][0] == "start"
        assert tracing.device_annotations_active()
        # one capture at a time
        status, _, _ = _request(
            server_url + "/debug/profile?seconds=1", "POST", b"")
        assert status == 409
        # ...but its status is visible, deadline included
        status, _, out = _request(server_url + "/debug/profile")
        live = json.loads(out)["capturing"]
        assert live is not None and live["remaining_seconds"] > 0
    finally:
        profiling.stop_capture()
    assert ("stop",) in calls
    assert not tracing.device_annotations_active()
    # validation
    status, _, _ = _request(
        server_url + "/debug/profile?seconds=bogus", "POST", b"")
    assert status == 400
    status, _, _ = _request(
        server_url + "/debug/profile?seconds=-1", "POST", b"")
    assert status == 400


def test_profile_reset_rearms_trace_budget(server_url):
    profiling._traced_batches = 5
    status, _, out = _request(
        server_url + "/debug/profile/reset", "POST", b"")
    assert status == 200
    assert json.loads(out)["trace_budget_reset"] is True
    assert profiling._traced_batches == 0


def test_error_responses_carry_request_and_trace_ids(server_url):
    status, headers, _ = _request(server_url + "/no/such/path")
    assert status == 404
    assert headers.get("X-Request-Id") not in (None, "-")
    assert headers.get("X-Trace-Id") not in (None, "-")
    # stdlib 501 path (no do_PUT): bypasses _reply, still correlatable —
    # send_error mints an id when dispatch never assigned one
    status, headers, _ = _request(server_url + "/healthz", method="PUT")
    assert status == 501
    assert headers.get("X-Request-Id") not in (None, "-")
