"""Golden parity tests — independent of the engine's own oracle.

Every differential test elsewhere pins the device path to the host engine
(``engine.processor``) — an oracle this codebase also wrote, so an oracle
bug would be invisible to them.  The goldens here were derived by hand
from Duke 1.2's *published* algorithm semantics (textbook Levenshtein DP,
the classic Winkler examples, q-gram set overlap, NumericComparator's
ratio cut, PropertyImpl's quadratic [low,high] map, Utils.computeBayes'
odds product; the reference drives these at App.java:1005 with the
testdukeconfig.xml:25-42 weights) and committed as
``tests/goldens/comparator_goldens.json`` with a longhand derivation per
case.  A drifting oracle fails here even while device==oracle still
agrees (SURVEY.md section 7 hard part 4).
"""

import json
import os

import pytest

from sesam_duke_microservice_tpu.core import comparators as C
from sesam_duke_microservice_tpu.core.bayes import combine_probabilities
from sesam_duke_microservice_tpu.core.records import Property

GOLDENS = os.path.join(os.path.dirname(__file__), "goldens",
                       "comparator_goldens.json")


@pytest.fixture(scope="module")
def goldens():
    with open(GOLDENS) as f:
        return json.load(f)


def test_levenshtein_goldens(goldens):
    cmp = C.Levenshtein()
    for case in goldens["levenshtein"]:
        got = cmp.compare(case["v1"], case["v2"])
        assert got == pytest.approx(case["expected"], abs=1e-12), case


def test_levenshtein_goldens_pure_python(goldens, monkeypatch):
    # the native C++ comparator library must agree with the same goldens
    # as the pure-Python path (both run in CI; whichever loaded first)
    monkeypatch.setattr(C, "_NATIVE", None)
    cmp = C.Levenshtein()
    for case in goldens["levenshtein"]:
        got = cmp.compare(case["v1"], case["v2"])
        assert got == pytest.approx(case["expected"], abs=1e-12), case


def test_jaro_winkler_goldens(goldens):
    cmp = C.JaroWinkler()
    for case in goldens["jaro_winkler"]:
        got = cmp.compare(case["v1"], case["v2"])
        assert got == pytest.approx(case["expected"], abs=1e-12), case


def test_qgram_goldens(goldens):
    cmp = C.QGram()
    for case in goldens["qgram_overlap"]:
        got = cmp.compare(case["v1"], case["v2"])
        assert got == pytest.approx(case["expected"], abs=1e-12), case


def test_numeric_goldens(goldens):
    cmp = C.Numeric()
    cmp.min_ratio = 0.7
    for case in goldens["numeric_min_ratio_0_7"]:
        got = cmp.compare(case["v1"], case["v2"])
        assert got == pytest.approx(case["expected"], abs=1e-12), case


def test_dice_and_jaccard_goldens(goldens):
    dice = C.DiceCoefficient()
    jac = C.JaccardIndex()
    for case in goldens["dice_tokens"]:
        assert dice.compare(case["v1"], case["v2"]) == pytest.approx(
            case["expected"], abs=1e-12), case
    for case in goldens["jaccard_tokens"]:
        assert jac.compare(case["v1"], case["v2"]) == pytest.approx(
            case["expected"], abs=1e-12), case


def test_weighted_levenshtein_goldens(goldens):
    cmp = C.WeightedLevenshtein()
    for case in goldens["weighted_levenshtein"]:
        got = cmp.compare(case["v1"], case["v2"])
        assert got == pytest.approx(case["expected"], abs=1e-12), case


def test_jaro_winkler_tokenized_goldens(goldens):
    cmp = C.JaroWinklerTokenized()
    for case in goldens["jaro_winkler_tokenized"]:
        got = cmp.compare(case["v1"], case["v2"])
        assert got == pytest.approx(case["expected"], abs=1e-12), case


def test_soundex_goldens(goldens):
    cmp = C.Soundex()
    for case in goldens["soundex"]:
        got = cmp.compare(case["v1"], case["v2"])
        assert got == pytest.approx(case["expected"], abs=1e-12), case


def test_person_name_goldens(goldens):
    """Pins the registry's documented PersonName semantics (Duke-shaped,
    not a byte-level Duke port): reorder plateau, initial matching,
    sqrt token-count discount."""
    cmp = C.PersonName()
    for case in goldens["person_name"]:
        got = cmp.compare(case["v1"], case["v2"])
        assert got == pytest.approx(case["expected"], abs=1e-12), case


def test_bayes_combination_goldens(goldens):
    """Probability map + naive-Bayes combination under the demo-config
    weights (NAME .09/.93, AREA .04/.73, CAPITAL .12/.61)."""
    weights = {"NAME": (0.09, 0.93), "AREA": (0.04, 0.73),
               "CAPITAL": (0.12, 0.61)}
    for case in goldens["bayes_demo_weights"]:
        probs = []
        for name, sim in case["sims"].items():
            low, high = weights[name]
            prop = Property(name, C.Exact(), low, high)
            # drive the published map through the library's own
            # Property.compare_probability via a fixed-similarity stub
            prop.comparator = _FixedSim(sim)
            probs.append(prop.compare_probability("a", "b"))
        assert probs == pytest.approx(case["probs"], abs=1e-12), case
        got = combine_probabilities(probs)
        assert got == pytest.approx(case["expected"], abs=1e-9), case


class _FixedSim:
    is_tokenized = False

    def __init__(self, sim):
        self.sim = sim

    def compare(self, v1, v2):
        return self.sim
