"""Shared-memory parallel feature extraction (VERDICT r4 #6).

The differential contract: the process-pool + shared-memory path must
produce BIT-IDENTICAL tensors to the serial extractor for every feature
kind, including the ANN embedding, uint16 char units, and auto-width
specs.  Throughput is environment-bound (this CI host exposes ONE core,
where any process pool loses by construction — the r4 finding); the
speedup claim belongs to multi-core deployments and is documented in
BASELINE.md, not asserted here.
"""

import numpy as np
import pytest

from sesam_duke_microservice_tpu.core import comparators as C
from sesam_duke_microservice_tpu.core.config import DukeSchema
from sesam_duke_microservice_tpu.core.records import (
    ID_PROPERTY_NAME,
    Property,
    Record,
)
from sesam_duke_microservice_tpu.ops import encoder as E
from sesam_duke_microservice_tpu.ops import features as F
from sesam_duke_microservice_tpu.ops import parallel_extract as PX


def _schema():
    return DukeSchema(
        threshold=0.8, maybe_threshold=None,
        properties=[
            Property(ID_PROPERTY_NAME, id_property=True),
            Property("name", C.Levenshtein(), 0.3, 0.9),
            Property("city", C.QGram(), 0.3, 0.85),
            Property("amount", C.Numeric(), 0.4, 0.7),
        ],
        data_sources=[],
    )


def _records(n, with_unicode=True):
    import random

    rng = random.Random(11)
    out = []
    for i in range(n):
        r = Record()
        r.add_value(ID_PROPERTY_NAME, f"r{i}")
        name = f"acme {rng.randint(0, 999)} corp {i % 77}"
        if with_unicode and i % 7 == 0:
            name += " \U0001D4B3å"
        r.add_value("name", name)
        if i % 5:  # some records lack the property entirely
            r.add_value("city", rng.choice(["oslo", "bergen", "tromsø"]))
        r.add_value("amount", str(rng.randint(1, 10 ** 6)))
        if i % 11 == 0:  # multi-valued slot
            r.add_value("name", f"alias {i}")
        out.append(r)
    return out


@pytest.fixture(autouse=True)
def _force_two_workers(monkeypatch):
    monkeypatch.setenv("DEVICE_EXTRACT_WORKERS", "2")
    monkeypatch.setenv("DEVICE_EXTRACT_PARALLEL_MIN", "64")
    yield
    PX._shutdown()


def test_parallel_matches_serial_bit_exact():
    schema = _schema()
    plan = F.SchemaFeatures.plan(schema, values_per_record=2)
    enc = E.RecordEncoder(schema, 64)
    records = _records(700)

    par = PX.extract_batch_parallel(plan, records, encoder=enc)
    assert par is not None
    ser = F._extract_serial(plan, records)
    # storage-mode-aware ({emb} bf16, or {emb, scale} under DUKE_EMB_INT8)
    ser[E.ANN_PROP] = enc.corpus_tensors(records)

    assert set(par) == set(ser)
    for prop in ser:
        assert set(par[prop]) == set(ser[prop])
        for name in ser[prop]:
            a, b = ser[prop][name], par[prop][name]
            assert a.dtype == b.dtype, (prop, name)
            np.testing.assert_array_equal(
                np.asarray(a).view(np.uint16)
                if a.dtype == E.STORAGE_DTYPE else a,
                np.asarray(b).view(np.uint16)
                if b.dtype == E.STORAGE_DTYPE else b,
                err_msg=f"{prop}.{name}",
            )


def test_enabled_gating():
    assert not PX.enabled(10)          # below the slab threshold
    assert PX.enabled(100000)
    # a single-core default disables the pool entirely
    import os

    old = os.environ.pop("DEVICE_EXTRACT_WORKERS")
    try:
        if (os.cpu_count() or 1) < 4:
            assert not PX.enabled(100000)
    finally:
        os.environ["DEVICE_EXTRACT_WORKERS"] = old


def test_extract_batch_routes_through_parallel(monkeypatch):
    """extract_batch uses the pool above the threshold and falls back
    serially when the pool path reports failure."""
    schema = _schema()
    plan = F.SchemaFeatures.plan(schema)
    records = _records(200, with_unicode=False)

    calls = {"n": 0}
    real = PX.extract_batch_parallel

    def spy(plan_, records_, *, encoder=None):
        calls["n"] += 1
        return real(plan_, records_, encoder=encoder)

    monkeypatch.setattr(PX, "extract_batch_parallel", spy)
    out = F.extract_batch(plan, records)
    assert calls["n"] == 1 and "name" in out

    monkeypatch.setattr(
        PX, "extract_batch_parallel",
        lambda plan_, records_, encoder=None: None,
    )
    out2 = F.extract_batch(plan, records)  # serial fallback
    np.testing.assert_array_equal(
        out["name"]["chars"], out2["name"]["chars"]
    )
