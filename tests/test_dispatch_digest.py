"""Multi-host mirror-consistency digest handshake (VERDICT r4 #4).

Follower commit replay used to be fire-and-forget: an asymmetric failure
(swallowed replay exception, OOM, a nondeterministic bug) silently
diverged the follower's corpus mirror until a collective hung or wrong
top-K indices finalized into links.  Now every commit is answered with a
chained mirror digest (DeviceIndex._fold_mirror_digest) and the frontend
compares before releasing the op lock.  These tests drive a real
``Dispatcher`` and a real replica index over loopback sockets — the
replay loop body is exercised without a 2-process jax.distributed job
(which tests/test_multihost_serving.py covers, handshake included, on
every commit it makes).
"""

import socket
import threading

from sesam_duke_microservice_tpu.parallel import dispatch

from test_dispatch_auth import _tiny_index


KEY = ("deduplication", "t")


class _LoopbackFollower:
    """Minimal follower: replays commit ops into a real replica index and
    answers the digest handshake — optionally corrupting the replay."""

    def __init__(self, sock, drop_record_at=None, fail_at=None):
        self.sock = sock
        self.index, _, _ = _tiny_index()
        self.drop_record_at = drop_record_at
        self.fail_at = fail_at
        self.commits = 0
        self.thread = threading.Thread(target=self._run, daemon=True)
        self.thread.start()

    def _run(self):
        last_seq = 0
        while True:
            try:
                op, _epoch, seq = dispatch._recv_op(self.sock)
            except (EOFError, OSError):
                return
            if seq <= last_seq:
                continue  # dup frame (CI chaos leg): production fencing drops
            last_seq = seq
            if op[0] != "commit":
                continue
            _, _key, records = op
            self.commits += 1
            if self.fail_at == self.commits:
                # replay raised: the production loop answers ok=False
                self.sock.sendall(dispatch._digest_frame(False, b""))
                continue
            if self.drop_record_at == self.commits:
                records = records[1:]  # the corruption: one record lost
            for r in records:
                self.index.index(r)
            self.index.commit()
            self.sock.sendall(
                dispatch._digest_frame(True, self.index._mirror_digest)
            )


def _wired_dispatcher(**follower_kw):
    a, b = socket.socketpair()
    d = dispatch.Dispatcher(app=None)
    d._conns = [a]
    follower = _LoopbackFollower(b, **follower_kw)
    return d, follower, (a, b)


def _frontend_index(d, monkeypatch):
    idx, _, rec = _tiny_index()
    idx._dispatch_key = KEY
    monkeypatch.setattr(dispatch, "_DISPATCHER", d)
    return idx, rec


def test_matching_mirrors_pass_and_chain(monkeypatch):
    d, follower, socks = _wired_dispatcher()
    try:
        idx, rec = _frontend_index(d, monkeypatch)
        for batch in (["a", "b"], ["c"], ["a"]):  # includes a re-index
            for rid in batch:
                idx.index(rec(rid, f"name-{rid}"))
            idx.commit()
        assert d._failed is None
        follower.thread.join(timeout=0.5)  # still alive = no error exit
        assert idx._mirror_digest == follower.index._mirror_digest
        # the chain moved off the empty sentinel (no XOR self-cancellation)
        from sesam_duke_microservice_tpu.store.records import (
            EMPTY_CONTENT_HASH,
        )

        assert idx._mirror_digest != EMPTY_CONTENT_HASH
    finally:
        for s in socks:
            s.close()


def test_corrupted_follower_mirror_evicts_follower(monkeypatch):
    """THE verdict criterion, updated for the HA serving group (ISSUE 8):
    a corrupted follower mirror is detected at the very commit that
    diverged — but now that FOLLOWER is evicted and the group degrades to
    the survivors, instead of latching the whole slice down."""
    from sesam_duke_microservice_tpu import telemetry

    d, follower, socks = _wired_dispatcher(drop_record_at=2)
    evictions0 = telemetry.FOLLOWER_EVICTIONS.single().value
    try:
        idx, rec = _frontend_index(d, monkeypatch)
        idx.index(rec("a", "acme"))
        idx.commit()  # commit 1: mirrors agree
        assert d._failed is None
        idx.index(rec("b", "globex"))
        idx.index(rec("c", "initech"))
        idx.commit()  # commit 2: follower lost record "b" -> evicted
        assert d._failed is None, "a follower fault must not latch"
        assert d.live_followers() == []
        assert telemetry.FOLLOWER_EVICTIONS.single().value == evictions0 + 1
        assert telemetry.DISPATCH_DOWN.single().value == 0
        # the dispatcher keeps serving (no live followers left to send to)
        d.broadcast(("score", KEY, []))
        idx.index(rec("d", "umbrella"))
        idx.commit()
    finally:
        for s in socks:
            s.close()


def test_follower_replay_failure_evicts_follower(monkeypatch):
    d, follower, socks = _wired_dispatcher(fail_at=1)
    try:
        idx, rec = _frontend_index(d, monkeypatch)
        idx.index(rec("a", "acme"))
        idx.commit()  # follower answered ok=False -> evicted, not latched
        assert d._failed is None
        assert d.live_followers() == []
    finally:
        for s in socks:
            s.close()


def test_dead_follower_evicted_at_handshake(monkeypatch):
    monkeypatch.setattr(dispatch, "_CONNECT_TIMEOUT_S", 5.0)
    a, b = socket.socketpair()
    d = dispatch.Dispatcher(app=None)
    d._conns = [a]
    try:
        idx, rec = _frontend_index(d, monkeypatch)
        idx.index(rec("a", "acme"))
        b.close()  # follower died before answering
        # caught either at the send (broken pipe) or at the digest read
        # (EOF) depending on kernel buffering — both evict the follower
        # and the commit stands on the frontend's authoritative state
        idx.commit()
        assert d._failed is None
        assert d.live_followers() == []
    finally:
        a.close()


def test_verify_disabled_skips_handshake(monkeypatch):
    monkeypatch.setenv("DUKE_DISPATCH_VERIFY", "0")
    a, b = socket.socketpair()
    d = dispatch.Dispatcher(app=None)
    d._conns = [a]
    try:
        idx, rec = _frontend_index(d, monkeypatch)
        idx.index(rec("a", "acme"))
        idx.commit()  # no follower answer needed; must not block
        assert d._failed is None
        # and the flag rides the env fingerprint so both sides agree
        assert dispatch._env_fingerprint()["verify"] is False
    finally:
        a.close()
        b.close()


def test_bootstrap_stream_carries_digest():
    """The streamed state_begin meta must carry the frontend's chained
    digest so followers resume the chain from the captured point."""
    idx, _, rec = _tiny_index()
    idx.index(rec("a", "acme"))
    idx.commit()

    class _Wl:
        index = idx

    sent = []
    d = dispatch.Dispatcher(app=None)
    d.broadcast = sent.append
    d._stream_states({"t": _Wl()}, {})
    begin = next(op for op in sent if op[0] == "state_begin")
    assert begin[2]["mirror_digest"] == idx._mirror_digest
    assert begin[2]["has_snapshot"] is True
    assert sent[-1] == ("state_end", ("deduplication", "t"))
