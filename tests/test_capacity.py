"""Load & capacity attribution (ISSUE 17).

Covers the three tentpole ledgers end to end:

  * device-time cost ledger — busy/compile accumulation, the sliding
    utilization window, and the RECONCILIATION invariant: the
    per-workload × per-phase ``duke_cost_device_seconds_total`` counters
    sum to the process busy ledger within tolerance, proven under the
    scheduler's merged-microbatch path;
  * HBM ledger — weakref registration, per-workload corpus components,
    headroom vs the budget and the overflow forecast;
  * sub-range heat maps — bucket/split math, the skewed-keyspace case
    (80% of traffic in 5% of a range must pull the suggested split into
    the hot band), and the ``/debug/loadmap`` payload.

Satellites riding along: the four ``GET /debug/{costs,memory,loadmap,
slo}`` endpoints on both serving planes, lossless rollup of the two new
families through the federation ``/metrics``, cross-plane profile
ownership (second start answers 409 with the live owner + deadline, not
a misleading 200), and SLO violation exemplar trace links.
"""

import json
import threading
import urllib.error
import urllib.request

import pytest

from sesam_duke_microservice_tpu import telemetry
from sesam_duke_microservice_tpu.core.config import parse_config
from sesam_duke_microservice_tpu.federation.ranges import route_key
from sesam_duke_microservice_tpu.service import debug as debug_api
from sesam_duke_microservice_tpu.service.app import DukeApp, serve
from sesam_duke_microservice_tpu.telemetry import costs, heat, memory, slo
from sesam_duke_microservice_tpu.utils import faults, profiling

from test_federation import FED_XML, duplicate_batch, make_fed  # noqa: F401
from test_observability import parse_exposition  # noqa: F401


@pytest.fixture(autouse=True)
def _clean_slate():
    faults.configure("")
    costs._reset_for_tests()
    memory._reset_for_tests()
    slo._reset_for_tests()
    yield
    faults.configure(None)
    costs._reset_for_tests()
    memory._reset_for_tests()
    slo._reset_for_tests()


# -- tentpole a: the device-time cost ledger ----------------------------------


class TestCostLedger:
    def test_busy_and_compile_accumulate(self):
        now = 1_000_000.0
        costs.note_busy(0.25, now)
        costs.note_busy(0.5, now + 1)
        costs.note_compile(2.0)
        assert costs.busy_seconds_total() == pytest.approx(0.75)
        assert costs.compile_seconds_total() == pytest.approx(2.0)

    def test_disabled_ledger_is_a_noop(self):
        costs.configure(False)
        try:
            costs.note_busy(1.0)
            costs.note_compile(1.0)
            assert costs.busy_seconds_total() == 0.0
            assert costs.compile_seconds_total() == 0.0
            assert costs.snapshot()["enabled"] is False
        finally:
            costs.configure(True)

    def test_utilization_window_ages_out(self):
        """2.5 busy seconds inside the 60 s window → ~4.2% utilization;
        the same credit 2 windows ago → 0 (uptime pinned past the
        window so the clamp does not distort the denominator)."""
        import time as _time

        now = _time.monotonic() + 2 * costs.WINDOW_S
        costs.note_busy(2.5, now - 10.0)
        assert costs.utilization(now) == pytest.approx(
            2.5 / costs.WINDOW_S, rel=1e-6)
        costs._reset_for_tests()
        costs.note_busy(2.5, now - 3 * costs.WINDOW_S)
        assert costs.utilization(now + costs.WINDOW_S) == 0.0

    def test_utilization_clamps_to_one(self):
        import time as _time

        now = _time.monotonic() + 2 * costs.WINDOW_S
        costs.note_busy(10_000.0, now - 1.0)
        assert costs.utilization(now) == 1.0

    def test_ledger_families_render_on_global(self):
        costs.note_busy(0.125)
        costs.note_compile(0.5)
        scraped = parse_exposition(telemetry.render(telemetry.GLOBAL))
        assert scraped[("duke_cost_busy_seconds_total", ())] == \
            pytest.approx(0.125)
        assert scraped[("duke_cost_compile_seconds_total", ())] == \
            pytest.approx(0.5)
        assert ("duke_device_utilization", ()) in scraped


class TestReconciliation:
    """The acceptance invariant: attributed phase seconds == measured
    busy seconds, under the scheduler's merged-microbatch path."""

    def _submit_concurrently(self, app, n_threads=4, batches_each=3):
        errors = []

        def worker(t):
            for b in range(batches_each):
                try:
                    app.scheduler.submit(
                        "deduplication", "people", "crm",
                        duplicate_batch(8, identities=4,
                                        start=1000 * t + 100 * b))
                except Exception as e:  # pragma: no cover
                    errors.append(e)

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors

    def test_ledger_reconciles_under_scheduler(self, tmp_path, monkeypatch):
        monkeypatch.setenv("MIN_RELEVANCE", "0.05")
        sc = parse_config(FED_XML.format(folder=tmp_path))
        app = DukeApp(sc, persistent=False)
        try:
            assert app.scheduler is not None, \
                "scheduler must be on (default) for the merged path"
            self._submit_concurrently(app)
            attributed = 0.0
            for _kind, _name, wl in debug_api._app_workloads(app):
                attributed += sum(
                    wl.processor.phases.phase_seconds().values())
            busy = costs.busy_seconds_total()
            assert busy > 0.0
            assert attributed == pytest.approx(
                busy, abs=max(0.05, 0.01 * busy))
        finally:
            app.close()

    def test_debug_costs_reports_reconciles(self, tmp_path, monkeypatch):
        monkeypatch.setenv("MIN_RELEVANCE", "0.05")
        sc = parse_config(FED_XML.format(folder=tmp_path))
        app = DukeApp(sc, persistent=False)
        try:
            self._submit_concurrently(app, n_threads=2, batches_each=2)
            status, body, _ = debug_api.handle_costs(
                debug_api._app_workloads(app))
            payload = json.loads(body)
            assert status == 200
            assert payload["reconciles"] is True
            assert payload["busy_seconds_total"] > 0.0
            assert payload["attributed_seconds"] == pytest.approx(
                payload["busy_seconds_total"],
                abs=payload["tolerance_seconds"])
            (wl_row,) = payload["workloads"]
            assert wl_row["workload"] == "people"
            assert set(wl_row["phase_seconds"]) == \
                {"encode", "retrieve", "score", "persist"}
        finally:
            app.close()


# -- tentpole b: the HBM ledger -----------------------------------------------


class _FakeOwner:
    closed = False


class TestHbmLedger:
    def test_register_components_and_weakref_reaping(self):
        owner = _FakeOwner()
        memory.register(owner, "deduplication", "x",
                        lambda: {"corpus_tensors": 1024, "empty": 0})
        assert memory.components_for(owner) == {"corpus_tensors": 1024.0}
        owner.closed = True
        assert all(
            o is not owner for _k, _n, o, _f, _l in memory._iter_live())
        owner.closed = False
        del owner
        import gc

        gc.collect()
        assert memory._iter_live() == []

    def test_components_fn_failure_never_fails_a_scrape(self):
        owner = _FakeOwner()

        def boom():
            raise RuntimeError("mid-mutation")

        memory.register(owner, "deduplication", "x", boom)
        assert memory.components_for(owner) == {}
        assert memory.debug_snapshot()["workloads"] == []

    def test_budget_env_override_and_headroom(self, monkeypatch):
        monkeypatch.setenv("DUKE_HBM_BUDGET_MB", "64")
        owner = _FakeOwner()
        memory.register(owner, "deduplication", "x",
                        lambda: {"corpus_tensors": 1 << 20})
        snap = memory.debug_snapshot()
        assert snap["budget_source"] == "env"
        assert snap["budget_bytes"] == 64 << 20
        assert snap["headroom_bytes"] == \
            snap["budget_bytes"] - snap["total_bytes"]
        assert snap["total_bytes"] >= 1 << 20
        assert {"kind": "deduplication", "workload": "x",
                "component": "corpus_tensors",
                "bytes": 1 << 20} in snap["workloads"]

    def test_overflow_forecast(self):
        assert memory.overflow_days(1000.0) == -1.0  # no growth observed
        with memory._REG_LOCK:
            memory._growth.append((1_000.0, 100.0))
            memory._growth.append((1_000.0 + 86_400.0, 200.0))
        assert memory.growth_bytes_per_day() == pytest.approx(100.0)
        assert memory.overflow_days(1000.0) == pytest.approx(10.0)

    def test_headroom_families_render_on_global(self, monkeypatch):
        monkeypatch.setenv("DUKE_HBM_BUDGET_MB", "64")
        scraped = parse_exposition(telemetry.render(telemetry.GLOBAL))
        assert scraped[("duke_device_headroom_bytes", ())] <= 64 << 20
        assert ("duke_device_overflow_days", ()) in scraped

    def test_workload_registers_corpus_components(self, tmp_path,
                                                  monkeypatch):
        monkeypatch.setenv("MIN_RELEVANCE", "0.05")
        sc = parse_config(FED_XML.format(folder=tmp_path))
        # device backend: the host index keeps no device-resident corpus
        # tensors, so only device-backed workloads have HBM components
        app = DukeApp(sc, backend="device", persistent=False)
        try:
            app.scheduler.submit("deduplication", "people", "crm",
                                 duplicate_batch(12))
            (_, _, wl), = list(debug_api._app_workloads(app))
            comps = memory.components_for(wl)
            assert comps.get("corpus_tensors", 0) > 0
        finally:
            app.close()


# -- tentpole c: sub-range heat maps ------------------------------------------


class _Rng:
    def __init__(self, lo, hi, range_id=None):
        self.lo, self.hi = lo, hi
        self.range_id = range_id or f"{lo:016x}"


class TestHeat:
    def test_uniform_load_splits_near_midpoint(self):
        lo, hi = 0, 1 << 32
        counts = [4] * heat.N_BUCKETS
        split = int(heat.suggest_split(lo, hi, counts), 16)
        mid = (lo + hi) // 2
        span = hi - lo
        assert abs(split - mid) <= span // heat.N_BUCKETS

    def test_no_traffic_no_split(self):
        assert heat.suggest_split(0, 1 << 32, [0] * heat.N_BUCKETS) is None
        assert heat.suggest_split(5, 6, [1] * heat.N_BUCKETS) is None

    def test_skewed_keyspace_split_lands_in_hot_band(self):
        """80% of traffic in the first 5% of the span: the naive
        midpoint is exactly wrong; the suggested split must bisect the
        OBSERVED load, i.e. land inside the hot band."""
        lo, hi = 1 << 40, 1 << 41
        span = hi - lo
        hm = heat.HeatMap()
        rng = _Rng(lo, hi)
        hot_hi = lo + span // 20  # first 5% of the keyspan
        for i in range(800):
            hm.note(rng, lo + (i * (hot_hi - lo) // 800))
        for i in range(200):
            hm.note(rng, lo + (i * span // 200))
        (range_id, slo_, shi, counts), = hm.snapshot()
        total = sum(counts)
        assert total == 1000
        split = int(heat.suggest_split(slo_, shi, counts), 16)
        # load-bisecting split sits inside the hot 5% band, nowhere
        # near the naive midpoint
        assert lo < split <= hot_hi + span // heat.N_BUCKETS
        assert abs(split - (lo + hi) // 2) > span // 4

    def test_counts_reset_on_bound_change(self):
        hm = heat.HeatMap()
        hm.note(_Rng(0, 100, "r"), 10)
        hm.note(_Rng(0, 200, "r"), 10)  # re-keyed span: old buckets lie
        (_, _, hi, counts), = hm.snapshot()
        assert hi == 200 and sum(counts) == 1

    def test_loadmap_payload(self):
        hm = heat.HeatMap()
        rng = _Rng(0, 256)
        for key in (0, 0, 0, 200):
            hm.note(rng, key)
        payload = heat.loadmap(hm)
        assert payload["n_buckets"] == heat.N_BUCKETS
        (row,) = payload["ranges"]
        assert row["records_total"] == 4
        assert row["hot_bucket_share"] == pytest.approx(0.75)
        assert row["suggested_split"] is not None
        assert heat.loadmap(None) == {"n_buckets": heat.N_BUCKETS,
                                      "ranges": []}

    def test_collect_family_emits_nonzero_buckets_only(self):
        hm = heat.HeatMap()
        hm.note(_Rng(0, 256, "r0"), 7)
        fam = heat.collect_family(hm)
        assert fam.name == "duke_fed_subrange_records_total"
        assert fam.samples == [
            ("", (("range", "r0"), ("bucket", "7")), 1.0)]


# -- the federation plane: rollup + debug surface -----------------------------


class TestFederationPlaneCapacity:
    @pytest.fixture()
    def plane(self, tmp_path):
        from sesam_duke_microservice_tpu.federation import Federation
        from sesam_duke_microservice_tpu.service.federation_plane import (
            serve_federation,
        )

        # device-backed groups so the HBM ledger has corpus components
        # to roll up (the host index keeps nothing device-resident)
        sc = parse_config(FED_XML.format(folder=tmp_path),
                          env={"MIN_RELEVANCE": "0.05"})
        fed = Federation(sc, n_groups=2, backend="device")
        server = serve_federation(fed)
        base = f"http://127.0.0.1:{server.server_address[1]}"
        yield fed, base
        server.shutdown()
        fed.close()

    @staticmethod
    def _get(url):
        return urllib.request.urlopen(url, timeout=60)

    @staticmethod
    def _post(url, obj=None):
        req = urllib.request.Request(
            url, data=json.dumps(obj or []).encode("utf-8"), method="POST",
            headers={"Content-Type": "application/json"})
        return urllib.request.urlopen(req, timeout=60)

    def _ingest(self, fed, base, n=24):
        with self._post(base + "/deduplication/people/crm",
                        duplicate_batch(n)) as r:
            assert r.status == 200
        for g in fed.groups:
            for wl in g.workloads.values():
                wl.link_database.drain()

    def test_cost_and_hbm_families_roll_up_losslessly(self, plane):
        """The acceptance differential for the two new families: the
        fed scrape's ``duke_cost_device_seconds_total`` equals the
        key-wise SUM of the groups' own collector samples, and every
        per-group ``duke_device_bytes`` gauge appears relabeled under
        its disjoint ``group=`` label set."""
        from sesam_duke_microservice_tpu.service.metrics import (
            make_group_collector,
        )

        fed, base = plane
        self._ingest(fed, base)

        expected_sums = {}
        expected_gauges = {}
        for g in fed.groups:
            for fam in make_group_collector(g)():
                if fam.name not in ("duke_cost_device_seconds_total",
                                    "duke_device_bytes"):
                    continue
                for suffix, labels, value in fam.samples:
                    if fam.mtype == "gauge":
                        key = (fam.name + suffix, tuple(sorted(
                            labels + (("group", str(g.idx)),))))
                        expected_gauges[key] = float(value)
                    else:
                        key = (fam.name + suffix, tuple(sorted(labels)))
                        expected_sums[key] = (
                            expected_sums.get(key, 0.0) + float(value))

        with self._get(base + "/metrics") as r:
            scraped = parse_exposition(r.read().decode("utf-8"))

        assert expected_sums, "no cost counters emitted"
        assert expected_gauges, "no per-workload device-bytes gauges"
        for key, value in expected_sums.items():
            assert key in scraped, key
            assert scraped[key] == pytest.approx(value), key
        for key, value in expected_gauges.items():
            assert key in scraped, key
            assert scraped[key] == pytest.approx(value), key
        # both groups ran all four phases
        phases = {dict(ls).get("phase")
                  for (n, ls) in scraped
                  if n == "duke_cost_device_seconds_total"}
        assert phases == {"encode", "retrieve", "score", "persist"}
        # the process-level ledger + headroom gauges ride the same scrape
        assert scraped[("duke_cost_busy_seconds_total", ())] > 0.0
        assert ("duke_device_headroom_bytes", ()) in scraped

    def test_subrange_heat_reaches_metrics_and_loadmap(self, plane):
        fed, base = plane
        self._ingest(fed, base, n=30)
        with self._get(base + "/metrics") as r:
            scraped = parse_exposition(r.read().decode("utf-8"))
        routed = sum(v for (n, _ls), v in scraped.items()
                     if n == "duke_fed_subrange_records_total")
        assert routed == 30
        with self._get(base + "/debug/loadmap") as r:
            payload = json.loads(r.read())
        assert payload["n_buckets"] == heat.N_BUCKETS
        assert sum(row["records_total"]
                   for row in payload["ranges"]) == 30
        for row in payload["ranges"]:
            assert set(row) >= {"range", "lo", "hi", "records_total",
                                "buckets", "hot_bucket_share",
                                "suggested_split"}

    def test_heat_counts_follow_ownership(self, plane):
        """Bucket placement is not just volume: every routed record's
        key must land in the histogram of the range that OWNS it."""
        fed, base = plane
        batch = duplicate_batch(20)
        self._ingest(fed, base, n=20)
        ds = fed.groups[0].workload(
            "deduplication", "people").datasources["crm"]
        per_range = {}
        for e in batch:
            rng = fed.map.owner(route_key(ds.record_id_for_entity(e)))
            per_range[rng.range_id] = per_range.get(rng.range_id, 0) + 1
        observed = {range_id: sum(counts) for range_id, _lo, _hi, counts
                    in fed.router.heat.snapshot()}
        assert observed == per_range

    def test_debug_costs_memory_slo_on_fed_plane(self, plane):
        fed, base = plane
        self._ingest(fed, base)
        with self._get(base + "/debug/costs") as r:
            payload = json.loads(r.read())
        assert payload["reconciles"] is True
        assert len(payload["workloads"]) == len(fed.groups)
        with self._get(base + "/debug/memory") as r:
            payload = json.loads(r.read())
        assert payload["budget_bytes"] > 0
        assert any(row["component"] == "corpus_tensors"
                   for row in payload["workloads"])
        with self._get(base + "/debug/slo") as r:
            payload = json.loads(r.read())
        assert any(t["signal"] == "ingest" for t in payload["trackers"])

    def test_heat_disabled_by_env(self, tmp_path, monkeypatch):
        monkeypatch.setenv("DUKE_FED_HEAT", "0")
        fed = make_fed(tmp_path, n_groups=2)
        try:
            assert fed.router.heat is None
            fed.router.submit("deduplication", "people", "crm",
                              duplicate_batch(6))
            assert heat.loadmap(fed.router.heat)["ranges"] == []
        finally:
            fed.close()


# -- the main serving plane: the four debug endpoints -------------------------


class TestMainPlaneEndpoints:
    @pytest.fixture()
    def app_base(self, tmp_path, monkeypatch):
        monkeypatch.setenv("MIN_RELEVANCE", "0.05")
        sc = parse_config(FED_XML.format(folder=tmp_path))
        app = DukeApp(sc, persistent=False)
        server = serve(app, port=0, host="127.0.0.1")
        threading.Thread(target=server.serve_forever, daemon=True).start()
        yield app, f"http://127.0.0.1:{server.server_address[1]}"
        server.shutdown()
        app.close()

    def test_capacity_endpoints_live(self, app_base):
        app, base = app_base
        app.scheduler.submit("deduplication", "people", "crm",
                             duplicate_batch(8))
        with urllib.request.urlopen(base + "/debug/costs",
                                    timeout=60) as r:
            payload = json.loads(r.read())
        assert payload["reconciles"] is True
        assert payload["busy_seconds_total"] > 0.0
        with urllib.request.urlopen(base + "/debug/memory",
                                    timeout=60) as r:
            payload = json.loads(r.read())
        assert payload["headroom_bytes"] == \
            payload["budget_bytes"] - payload["total_bytes"]
        with urllib.request.urlopen(base + "/debug/loadmap",
                                    timeout=60) as r:
            payload = json.loads(r.read())
        # a single-process plane routes nothing through a federation
        # router: the loadmap is present but empty
        assert payload == {"n_buckets": heat.N_BUCKETS, "ranges": []}
        with urllib.request.urlopen(base + "/debug/slo",
                                    timeout=60) as r:
            payload = json.loads(r.read())
        assert isinstance(payload["trackers"], list)


# -- cross-plane profile ownership (satellite 1 + 6) --------------------------


class TestProfileOwnership:
    @pytest.fixture(autouse=True)
    def _stub_profiler(self, monkeypatch):
        monkeypatch.setattr(profiling, "profiler_start", lambda d: None)
        monkeypatch.setattr(profiling, "profiler_stop", lambda: None)
        yield
        profiling.stop_capture()

    def test_second_start_is_409_with_owner_and_deadline(self):
        status, body, _ = debug_api.handle_profile_start(
            {"seconds": ["60"]}, owner="federation")
        assert status == 200
        assert json.loads(body)["capturing"]["owner"] == "federation"
        status, body, _ = debug_api.handle_profile_start(
            {"seconds": ["5"]}, owner="replica")
        payload = json.loads(body)
        assert status == 409
        assert payload["owner"] == "federation"
        assert payload["deadline_unix"] > 0
        assert 0 < payload["remaining_seconds"] <= 60
        status, body, _ = debug_api.handle_profile_status()
        assert json.loads(body)["capturing"]["owner"] == "federation"

    def test_fed_plane_profile_endpoints(self, tmp_path):
        from sesam_duke_microservice_tpu.service.federation_plane import (
            serve_federation,
        )

        fed = make_fed(tmp_path, n_groups=2)
        server = serve_federation(fed)
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            req = urllib.request.Request(
                base + "/debug/profile?seconds=30", data=b"",
                method="POST")
            with urllib.request.urlopen(req, timeout=60) as r:
                assert r.status == 200
                assert json.loads(r.read())["capturing"]["owner"] == \
                    "federation"
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(urllib.request.Request(
                    base + "/debug/profile?seconds=5", data=b"",
                    method="POST"), timeout=60)
            assert exc.value.code == 409
            conflict = json.loads(exc.value.read())
            assert conflict["owner"] == "federation"
            assert conflict["deadline_unix"] > 0
            req = urllib.request.Request(
                base + "/debug/profile/reset", data=b"", method="POST")
            with urllib.request.urlopen(req, timeout=60) as r:
                assert json.loads(r.read())["trace_budget_reset"] is True
        finally:
            server.shutdown()
            fed.close()

    def test_replica_plane_profile_endpoints(self):
        from sesam_duke_microservice_tpu.service.replica_plane import (
            serve_replica_plane,
        )
        from test_observability import _StubSession

        server = serve_replica_plane(_StubSession(), port=0,
                                     host="127.0.0.1")
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            req = urllib.request.Request(
                base + "/debug/profile?seconds=30", data=b"",
                method="POST")
            with urllib.request.urlopen(req, timeout=60) as r:
                assert r.status == 200
                assert json.loads(r.read())["capturing"]["owner"] == \
                    "replica"
            # the replica's read-side debug routes ride along
            with urllib.request.urlopen(base + "/debug/costs",
                                        timeout=60) as r:
                assert json.loads(r.read())["reconciles"] is True
            with urllib.request.urlopen(base + "/debug/memory",
                                        timeout=60) as r:
                assert "headroom_bytes" in json.loads(r.read())
        finally:
            server.shutdown()


# -- SLO violation exemplars (satellite 2) ------------------------------------


class TestSloExemplars:
    def test_violation_carries_exemplar_trace_link(self):
        t = slo.tracker("ingest", "deduplication", "people")
        t.record(0.001)                      # within objective: no row
        t.record(30.0, trace_id="cafe1234")  # violation with exemplar
        t.record(30.0)                       # violation, unsampled
        snap = slo.debug_snapshot()
        tracker = next(row for row in snap["trackers"]
                       if row["signal"] == "ingest"
                       and row["workload"] == "people")
        assert tracker["violations_total"] == 2
        recent = tracker["recent_violations"]
        assert len(recent) == 2
        # newest first: the unsampled one, then the exemplar
        assert recent[0]["trace_id"] is None
        assert recent[0]["trace"] is None
        assert recent[1]["trace_id"] == "cafe1234"
        assert recent[1]["trace"] == "/debug/traces/cafe1234"
        assert recent[1]["age_seconds"] >= 0.0

    def test_debug_snapshot_limit(self):
        t = slo.tracker("ingest", "deduplication", "people")
        for i in range(30):
            t.record(30.0, trace_id=f"t{i}")
        snap = slo.debug_snapshot(limit=5)
        tracker = next(row for row in snap["trackers"]
                       if row["workload"] == "people")
        assert [v["trace_id"] for v in tracker["recent_violations"]] == \
            ["t29", "t28", "t27", "t26", "t25"]

    def test_batch_exemplars_align_with_latencies(self):
        t = slo.SloTracker(objective_s=0.1, target=0.99)
        now = 1_000_000.0
        t.record_batch([0.01, 0.5, 0.02, 0.9], now,
                       trace_ids=[None, "aa", None, "bb"])
        rows = t.recent_violations()
        assert [(ts, tid) for ts, tid in rows] == \
            [(now, "bb"), (now, "aa")]
