"""Durable record store + restart/resume.

The reference resumes by restarting the container over the same volume:
Lucene index reopened in APPEND mode (IncrementalLuceneDatabase.java:233-244)
and the H2 link DB reopened (App.java:577-604); clients replay via ?since=
(App.java:742,843).  Here the record store is the durable source of truth
and the blocking index is replayed from it at workload build time.
"""

import pytest

from sesam_duke_microservice_tpu.core.config import parse_config
from sesam_duke_microservice_tpu.core.records import ID_PROPERTY_NAME, Record
from sesam_duke_microservice_tpu.engine.workload import build_workload
from sesam_duke_microservice_tpu.store import (
    InMemoryRecordStore,
    SqliteRecordStore,
)


def _record(rid, **props):
    r = Record()
    r.add_value(ID_PROPERTY_NAME, rid)
    for k, v in props.items():
        r.add_value(k, v)
    return r


@pytest.mark.parametrize("make", [InMemoryRecordStore,
                                  lambda: SqliteRecordStore(":memory:")])
def test_store_basics(make):
    store = make()
    store.put(_record("a__1", NAME="ann"))
    store.put(_record("a__2", NAME="bob"))
    assert store.count() == 2
    assert store.get("a__1").get_value("NAME") == "ann"
    assert store.get("missing") is None
    # replace on same id
    store.put(_record("a__1", NAME="anna"))
    assert store.count() == 2
    assert store.get("a__1").get_value("NAME") == "anna"
    assert [r.record_id for r in store.all_records()] == ["a__2", "a__1"]
    with pytest.raises(ValueError):
        store.put(Record())
    # duplicate ids within one batch: last occurrence wins, no error
    store.put_many([_record("b__1", NAME="v1"), _record("b__1", NAME="v2")])
    assert store.get("b__1").get_value("NAME") == "v2"


def test_sqlite_store_survives_reopen(tmp_path):
    path = str(tmp_path / "records.sqlite")
    store = SqliteRecordStore(path)
    store.put(_record("x__1", NAME="åse", EMAIL="a@x.no"))
    store.close()

    store2 = SqliteRecordStore(path)
    assert store2.count() == 1
    got = store2.get("x__1")
    assert got.get_value("NAME") == "åse"
    assert got.get_value("EMAIL") == "a@x.no"
    store2.close()


def test_connection_pool_prunes_dead_threads(tmp_path):
    import threading

    from sesam_duke_microservice_tpu.utils.sqlite import SqliteConnectionPool

    pool = SqliteConnectionPool(str(tmp_path / "p.sqlite"))
    pool.conn()

    def worker():
        pool.conn()

    for _ in range(8):
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    # a fresh thread's acquisition prunes the 8 dead threads' connections,
    # leaving its own entry plus the main thread's
    t = threading.Thread(target=worker)
    t.start()
    t.join()
    assert len(pool._conns) == 2
    pool.close()
    import pytest as _pytest
    import sqlite3 as _sqlite3
    with _pytest.raises(_sqlite3.ProgrammingError):
        pool.conn()


DEDUP_XML = """
<DukeMicroService dataFolder="{folder}">
  <Deduplication name="people" link-database-type="h2">
    <duke>
      <schema>
        <threshold>0.8</threshold>
        <property><name>NAME</name>
          <comparator>levenshtein</comparator><low>0.1</low><high>0.95</high>
        </property>
      </schema>
      <data-source class="io.sesam.dukemicroservice.IncrementalDeduplicationDataSource">
        <param name="dataset-id" value="crm"/>
        <column name="name" property="NAME"/>
      </data-source>
    </duke>
  </Deduplication>
</DukeMicroService>
"""


def _build(tmp_path):
    xml = DEDUP_XML.format(folder=tmp_path)
    sc = parse_config(xml, env={"MIN_RELEVANCE": "0.05"})
    return build_workload(sc.deduplications["people"], sc)


def test_workload_restart_resumes_state(tmp_path):
    wl = _build(tmp_path)
    wl.process_batch("crm", [{"_id": "1", "name": "jonathan smithe"},
                             {"_id": "2", "name": "jonathan smith"}])
    rows = wl.links_since(0)
    assert len(rows) == 1 and rows[0]["confidence"] > 0.8
    wl.close()

    # "container restart": rebuild the workload over the same data folder
    wl2 = _build(tmp_path)
    # link feed is durable and entity fields resolve via the replayed index
    rows2 = wl2.links_since(0)
    assert len(rows2) == 1
    assert {rows2[0]["entity1"], rows2[0]["entity2"]} == {"1", "2"}
    assert rows2[0]["dataset1"] == "crm"

    # a new batch matches against the REPLAYED corpus (record 3 matches 1+2
    # that arrived before the restart)
    wl2.process_batch("crm", [{"_id": "3", "name": "jonathan smith"}])
    rows3 = wl2.links_since(0)
    matched = {frozenset((r["entity1"], r["entity2"])) for r in rows3}
    assert frozenset(("2", "3")) in matched
    assert wl2.record_store.count() == 3
    wl2.close()


def test_restart_preserves_deletion_tombstones(tmp_path):
    wl = _build(tmp_path)
    wl.process_batch("crm", [{"_id": "1", "name": "maria garcia"},
                             {"_id": "2", "name": "maria garcia"}])
    assert len(wl.links_since(0)) == 1
    wl.process_batch("crm", [{"_id": "2", "name": "maria garcia",
                              "_deleted": True}])
    rows = wl.links_since(0)
    assert rows and all(r["_deleted"] for r in rows)
    wl.close()

    wl2 = _build(tmp_path)
    # tombstone replayed: a fresh duplicate must not match the deleted record
    wl2.process_batch("crm", [{"_id": "4", "name": "maria garcia"}])
    live_pairs = {frozenset((r["entity1"], r["entity2"]))
                  for r in wl2.links_since(0) if not r["_deleted"]}
    assert frozenset(("1", "4")) in live_pairs
    assert frozenset(("2", "4")) not in live_pairs
    wl2.close()


def test_workload_restart_uses_corpus_snapshot(tmp_path, monkeypatch):
    """Device-backend restart restores tensors from the snapshot without
    re-running feature extraction; a missing snapshot replays instead."""
    from sesam_duke_microservice_tpu.core.config import parse_config
    from sesam_duke_microservice_tpu.engine.device_matcher import DeviceIndex
    from sesam_duke_microservice_tpu.engine.workload import build_workload

    xml = f"""
    <DukeMicroService dataFolder="{tmp_path}">
      <Deduplication name="w" link-database-type="in-memory">
        <duke>
          <schema>
            <threshold>0.8</threshold>
            <property><name>NAME</name>
              <comparator>levenshtein</comparator><low>0.1</low><high>0.9</high>
            </property>
          </schema>
          <data-source class="io.sesam.dukemicroservice.IncrementalDeduplicationDataSource">
            <param name="dataset-id" value="d"/>
            <column name="name" property="NAME"/>
          </data-source>
        </duke>
      </Deduplication>
    </DukeMicroService>
    """
    sc = parse_config(xml)
    wc = sc.deduplications["w"]

    wl = build_workload(wc, sc, backend="device", persistent=True)
    with wl.lock:
        wl.process_batch("d", [{"_id": f"r{i}", "name": f"acme {i}"}
                               for i in range(12)])
    assert wl.index.corpus.size == 12
    wl.close()  # saves the snapshot

    # restart: extraction must NOT run (snapshot covers the whole store)
    def boom(self, records):
        raise AssertionError("extraction ran despite snapshot")

    monkeypatch.setattr(DeviceIndex, "_extract", boom)
    wl2 = build_workload(wc, sc, backend="device", persistent=True)
    assert wl2.index.corpus.size == 12
    assert len(wl2.index.records) == 12
    monkeypatch.undo()
    wl2.close()


def test_content_hash_incremental_equals_rebuild(tmp_path):
    """The running XOR hash after arbitrary put/replace sequences equals
    the hash a fresh store computes from the same final rows (the
    migration path folds every row from scratch)."""
    import sqlite3

    from sesam_duke_microservice_tpu.store.records import SqliteRecordStore

    path = str(tmp_path / "r.sqlite")
    store = SqliteRecordStore(path)
    empty = store.content_hash()
    store.put_many([_record(f"id{i}", name=f"n{i}") for i in range(20)])
    store.put_many([_record("id3", name="replaced")])     # replace
    store.put_many([_record("id3", name="replaced")])     # idempotent re-put
    store.put_many([_record("id5", name="a"), _record("id5", name="b")])
    incremental = store.content_hash()
    assert incremental != empty
    store.close()

    # drop the meta row: reopening must rebuild the same hash from rows
    conn = sqlite3.connect(path)
    conn.execute("DELETE FROM meta WHERE key='content_hash'")
    conn.commit()
    conn.close()
    store2 = SqliteRecordStore(path)
    assert store2.content_hash() == incremental
    store2.close()


def test_snapshot_rejected_when_store_mutates_after_save(tmp_path,
                                                         monkeypatch):
    """O(1)-hash staleness guard: a record updated in the store after the
    snapshot was saved forces a full replay (stale features must never
    score)."""
    monkeypatch.setenv("MIN_RELEVANCE", "0.05")
    sc = parse_config(DEDUP_XML.format(folder=tmp_path),
                      env={"MIN_RELEVANCE": "0.05"})
    wc = sc.deduplications["people"]
    wl = build_workload(wc, sc, backend="device", persistent=True)
    with wl.lock:
        wl.process_batch("crm", [
            {"_id": str(i), "name": f"name {i}"} for i in range(8)
        ])
    wl.close()  # snapshot saved with the store's current hash

    # out-of-band store mutation (simulates a crash after a store write
    # but before the next snapshot save)
    from sesam_duke_microservice_tpu.store.records import SqliteRecordStore
    import os

    store = SqliteRecordStore(
        os.path.join(wc.data_folder, "records.sqlite")
    )
    store.put_many([_record("crm__3", NAME="changed behind the snapshot")])
    store.close()

    wl2 = build_workload(wc, sc, backend="device", persistent=True)
    try:
        # replay (not snapshot) must win: the changed value is served
        rec = wl2.index.find_record_by_id("crm__3")
        assert rec.get_value("NAME") == "changed behind the snapshot"
    finally:
        wl2.close()


def test_lazy_restart_updates_keep_snapshot_valid(tmp_path):
    """r3 review regression: after a lazy snapshot restore, updating a
    PRE-EXISTING record must keep the sync stamp coherent — the next
    restart still rides the snapshot and serves the new value; and a
    store write whose scoring pass failed must force a replay."""
    from sesam_duke_microservice_tpu.store.records import LazyRecordMap

    sc = parse_config(DEDUP_XML.format(folder=tmp_path),
                      env={"MIN_RELEVANCE": "0.05"})
    wc = sc.deduplications["people"]
    wl = build_workload(wc, sc, backend="device", persistent=True)
    with wl.lock:
        wl.process_batch("crm", [
            {"_id": str(i), "name": f"name {i}"} for i in range(8)
        ])
    wl.close()

    # restart #1: lazy restore, then update record 3 end-to-end
    wl2 = build_workload(wc, sc, backend="device", persistent=True)
    assert isinstance(wl2.index.records, LazyRecordMap)
    with wl2.lock:
        wl2.process_batch("crm", [{"_id": "3", "name": "updated three"}])
        assert wl2.index.find_record_by_id(
            "crm__3").get_value("NAME") == "updated three"
    wl2.close()

    # restart #2: the snapshot (saved with the post-update stamp) must be
    # ACCEPTED — no silent permanent replay — and serve the updated value
    from sesam_duke_microservice_tpu.engine.device_matcher import DeviceIndex

    real_extract = DeviceIndex._extract
    calls = []

    def counting(self, records, plan=None):
        calls.append(len(records))
        return real_extract(self, records, plan)

    DeviceIndex._extract = counting
    try:
        wl3 = build_workload(wc, sc, backend="device", persistent=True)
    finally:
        DeviceIndex._extract = real_extract
    with wl3.lock:
        assert not calls, "snapshot rejected after a post-restore update"
        assert wl3.index.find_record_by_id(
            "crm__3").get_value("NAME") == "updated three"
        assert wl3.index.live_records == 8

        # divergence: store write whose index pass fails -> next restart
        # must replay (stale features must never score)
        wl3.record_store.put_many(
            wl3.datasources["crm"].records_for_batch(
                [{"_id": "5", "name": "written behind the index"}]
            )
        )
    wl3.close()
    wl4 = build_workload(wc, sc, backend="device", persistent=True)
    with wl4.lock:
        # replay (not snapshot) served the out-of-band value
        assert wl4.index.find_record_by_id(
            "crm__5").get_value("NAME") == "written behind the index"
        assert not isinstance(wl4.index.records, LazyRecordMap)
    wl4.close()


def test_lazy_tombstone_keeps_live_count_exact(tmp_path):
    """Deleting a pre-restore record through the lazy mirror must
    decrement live_records exactly once (liveness from index state, not
    store read-through)."""
    sc = parse_config(DEDUP_XML.format(folder=tmp_path),
                      env={"MIN_RELEVANCE": "0.05"})
    wc = sc.deduplications["people"]
    wl = build_workload(wc, sc, backend="device", persistent=True)
    with wl.lock:
        wl.process_batch("crm", [
            {"_id": str(i), "name": f"name {i}"} for i in range(6)
        ])
    wl.close()

    wl2 = build_workload(wc, sc, backend="device", persistent=True)
    with wl2.lock:
        assert wl2.index.live_records == 6
        wl2.process_batch("crm", [{"_id": "2", "_deleted": True}])
        assert wl2.index.live_records == 5
        # re-delete is idempotent for the count
        wl2.process_batch("crm", [{"_id": "2", "_deleted": True}])
        assert wl2.index.live_records == 5
    wl2.close()


def test_lazy_feed_page_prefetch_batches_lookups(tmp_path):
    """Feed pages over a lazy mirror resolve their link endpoints via one
    batched store query, and the rows come out identical to eager."""
    from sesam_duke_microservice_tpu.store.records import (
        LazyRecordMap,
        SqliteRecordStore,
    )

    sc = parse_config(DEDUP_XML.format(folder=tmp_path),
                      env={"MIN_RELEVANCE": "0.05"})
    wc = sc.deduplications["people"]
    wl = build_workload(wc, sc, backend="device", persistent=True)
    with wl.lock:
        wl.process_batch("crm", [
            {"_id": str(i), "name": f"dupname {i // 2}"} for i in range(40)
        ])
        eager_rows = wl.links_since(0)
    assert eager_rows
    wl.close()

    wl2 = build_workload(wc, sc, backend="device", persistent=True)
    try:
        assert isinstance(wl2.index.records, LazyRecordMap)
        gets = []
        real_get = SqliteRecordStore.get

        def counting_get(self, rid):
            gets.append(rid)
            return real_get(self, rid)

        SqliteRecordStore.get = counting_get
        try:
            with wl2.lock:
                rows, _ = wl2.links_page(0, 1000)
        finally:
            SqliteRecordStore.get = real_get
        assert rows == eager_rows
        # resolution rode the batched prefetch, not per-id point gets
        assert not gets, f"{len(gets)} point lookups during page resolution"
    finally:
        wl2.close()


def test_record_digest_memo_invalidates_on_mutation():
    """record_digest memoizes per record but mutation invalidates; the
    digest stays a pure function of content."""
    from sesam_duke_microservice_tpu.store.records import record_digest

    r = _record("x", NAME="a")
    d1 = record_digest(r)
    assert record_digest(r) == d1
    r.add_value("NAME", "b")
    d2 = record_digest(r)
    assert d2 != d1
    fresh = _record("x", NAME="a")
    fresh.add_value("NAME", "b")
    assert record_digest(fresh) == d2

    # store put seeds the memo with the row digest it folded
    store = SqliteRecordStore(":memory:")
    rec = _record("y", NAME="z")
    store.put(rec)
    assert rec._digest_cache is not None
    assert record_digest(rec) == rec._digest_cache
    store.close()
