"""Mesh-vs-single-device parity with the dd lift engaged (ISSUE 18).

The acceptance differential for the constraint-driven sharded backends:
drive identical batches through a mesh arm and its single-chip
counterpart and require

  * the ORDERED event tapes bit-identical (not just the link sets — the
    finalizer's emission order is part of the replay contract);
  * the link rows (id pair, status, kind, confidence) bit-identical;
  * ``pairs_device_certified > 0`` on the sharded arm — the dd survivor
    gather (engine.sharded_matcher._MeshProgramLift._dd_call) actually
    ran and certified verdicts on device, rather than silently falling
    back to the host rescore the seed used.

Runs on the suite's virtual 8-device CPU mesh (conftest).
"""

import pytest

from sesam_duke_microservice_tpu.core.config import MatchTunables
from sesam_duke_microservice_tpu.engine.ann_matcher import AnnIndex, AnnProcessor
from sesam_duke_microservice_tpu.engine.device_matcher import (
    DeviceIndex,
    DeviceProcessor,
)
from sesam_duke_microservice_tpu.engine.sharded_matcher import (
    ShardedAnnIndex,
    ShardedAnnProcessor,
    ShardedDeviceIndex,
    ShardedDeviceProcessor,
)
from sesam_duke_microservice_tpu.engine.listeners import LinkMatchListener
from sesam_duke_microservice_tpu.links import InMemoryLinkDatabase

from test_dd import _records_with_person, hostprop_schema
from test_finalize import OrderedLog, link_rows


@pytest.fixture(autouse=True)
def _pin_device_finalize(monkeypatch):
    # this module asserts certified-path behavior on the mesh arm, so it
    # pins the knob ON (the CI DUKE_DEVICE_FINALIZE=0 leg runs the rest
    # of the suite on the legacy path)
    monkeypatch.setenv("DUKE_DEVICE_FINALIZE", "1")


ARMS = {
    "device": lambda schema: (
        lambda idx: DeviceProcessor(schema, idx))(
            DeviceIndex(schema, tunables=MatchTunables())),
    "sharded-brute": lambda schema: (
        lambda idx: ShardedDeviceProcessor(schema, idx))(
            ShardedDeviceIndex(schema, tunables=MatchTunables())),
    "ann": lambda schema: (
        lambda idx: AnnProcessor(schema, idx))(
            AnnIndex(schema, tunables=MatchTunables())),
    "sharded": lambda schema: (
        lambda idx: ShardedAnnProcessor(schema, idx))(
            ShardedAnnIndex(schema, tunables=MatchTunables())),
}


def _run_arm(name, schema, batches):
    proc = ARMS[name](schema)
    log = OrderedLog()
    db = InMemoryLinkDatabase()
    proc.add_match_listener(log)
    proc.add_match_listener(LinkMatchListener(db))
    for batch in batches:
        proc.deduplicate(batch)
    return log.events, link_rows(db), proc


@pytest.mark.parametrize("sharded,single", [
    ("sharded-brute", "device"),
    ("sharded", "ann"),
])
def test_mesh_event_tape_and_links_bit_identical(sharded, single):
    # hostprop_schema leaves plenty of non-emitting survivors for dd to
    # certify away (test_dd), so the >0 assertion below has teeth
    schema = hostprop_schema()
    batches = [_records_with_person(40, seed=5)]
    mesh_events, mesh_links, mesh_proc = _run_arm(sharded, schema, batches)
    base_events, base_links, _ = _run_arm(single, schema, batches)
    assert mesh_events, "fixture produced no events"
    assert mesh_links == base_links
    if sharded == "sharded-brute":
        # exact blocking: the merged global top-K IS the single-device
        # top-K, so the whole ordered tape must be bit-identical
        assert mesh_events == base_events
    else:
        # approximate blocking: per-shard top-C + saturation escalation
        # legally reorder the candidate walk across topologies
        # (test_ann_sharded pins the superset property), so the contract
        # is the emitted pair set + confidences, not the walk order
        assert sorted(mesh_events) == sorted(base_events)
    # the dd lift decided real pairs on device — the mesh arm is a
    # first-class certified-finalize backend, not a host fallback
    assert mesh_proc.stats.pairs_device_certified > 0
    cache = mesh_proc.database.scorer_cache
    assert cache.supports_dd is True
    assert cache._dd_gathers > 0
    assert cache._dd_gather_rows > 0


def test_explain_replays_dd_on_sharded_backend():
    """/explain on a fully-addressable sharded backend replays the SAME
    dd program the live path runs: an identical pair reports
    ``decided_path == "device_certified"`` — not the blanket
    ``host_rescore`` + ``dd_residue_reason == "backend"`` the seed's
    supports_dd=False gate forced on every mesh workload."""
    from test_device_matcher import dedup_schema, make_record

    from sesam_duke_microservice_tpu.engine import explain as X

    schema = dedup_schema()
    a = make_record("a", name="acme corp", city="oslo", amount="100")
    b = make_record("b", name="acme corp", city="oslo", amount="100")
    z = make_record("z", name="zzzzz", city="bergen", amount="7")
    index = ShardedDeviceIndex(schema, tunables=MatchTunables())
    for r in (a, b, z):
        index.index(r)
    index.commit()
    assert index.scorer_cache.supports_dd is True
    out = X.device_breakdown(index, a, b)
    assert out["device_finalize_enabled"] is True
    assert out.get("dd_residue_reason") != "backend"
    assert out["decided_path"] == "device_certified"
    assert out["certified_dd_margin"] > 0
    # the far pair still prunes on the decisive band, same as one chip
    far = X.device_breakdown(index, a, z)
    assert far["decided_path"] == "band_skip"


def test_mesh_dd_gate_matches_single_device_stats():
    """The residue attribution (why a pair was NOT certified) must agree
    between the arms — the gather lift may not change which pairs reach
    the host."""
    schema = hostprop_schema()
    batches = [_records_with_person(24, seed=9)]
    _, _, mesh_proc = _run_arm("sharded-brute", schema, batches)
    _, _, base_proc = _run_arm("device", schema, batches)
    assert base_proc.stats.pairs_device_certified > 0
    for field in ("pairs_device_certified", "dd_residue_margin",
                  "dd_residue_kind", "dd_residue_truncation"):
        assert getattr(mesh_proc.stats, field) == \
            getattr(base_proc.stats, field), field
