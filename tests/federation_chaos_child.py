"""Federation migration kill-differential child (ISSUE 14,
tests/test_federation_chaos.py).

One federation process the harness can SIGKILL at an exact migration
site and restart:

  * builds a 2-group federation over ``--data`` (per-group journal
    recovery, store replay and partition-map load run inside the
    ``Federation`` constructor exactly as a real start — including the
    AUTO-RESUME of a migration a crash interrupted);
  * ingests the deterministic duplicate-heavy corpus through the
    scatter router, printing ``ACK <i>`` per batch;
  * with ``--migrate`` moves the first group-0-owned range to group 1
    (``DUKE_FAULTS=crash_at=<site>:<n>`` in the environment SIGKILLs
    mid-migration; on the restarted run the constructor finishes the
    interrupted migration first, and the explicit call then reports
    ``already_owned``);
  * ``--dump`` prints ``DUMP <json>``: the federated link rows (each
    group's link DB filtered by CURRENT range ownership — the same
    one-place rule the feed merge applies), the drained federated
    ``?since=`` feed (timestamps dropped: wall clock differs across
    runs by construction), the moved range's owner, and the migration
    outcome counters.

The differential: for EVERY kill site, restart + resume must converge
to link rows and a federated feed bit-identical to an UNMIGRATED
control — zero lost, zero duplicated links.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_batches(n_batches: int, per_batch: int, identities: int = 4):
    out = []
    for b in range(n_batches):
        rows = []
        for i in range(per_batch):
            ident = (b * per_batch + i) % identities
            name = f"person number {ident}"
            rows.append({
                "_id": f"r{b}_{i}",
                "name": name,
                "email": f"{name.replace(' ', '.')}@x.no",
            })
        out.append(rows)
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", required=True)
    ap.add_argument("--batches", type=int, default=6)
    ap.add_argument("--per-batch", type=int, default=6)
    ap.add_argument("--start", type=int, default=0)
    ap.add_argument("--migrate", action="store_true")
    ap.add_argument("--dump", action="store_true")
    args = ap.parse_args()

    from sesam_duke_microservice_tpu.core.config import parse_config
    from sesam_duke_microservice_tpu.federation import Federation
    from sesam_duke_microservice_tpu.federation.ranges import route_key

    xml = f"""
<DukeMicroService dataFolder="{args.data}">
  <Deduplication name="people">
    <duke>
      <schema>
        <threshold>0.8</threshold>
        <property><name>NAME</name><comparator>levenshtein</comparator><low>0.1</low><high>0.95</high></property>
        <property><name>EMAIL</name><comparator>exact</comparator><low>0.2</low><high>0.95</high></property>
      </schema>
      <data-source class="io.sesam.dukemicroservice.IncrementalDeduplicationDataSource">
        <param name="dataset-id" value="crm"/>
        <column name="name" property="NAME"/>
        <column name="email" property="EMAIL"/>
      </data-source>
    </duke>
  </Deduplication>
</DukeMicroService>
"""
    sc = parse_config(xml, env={"MIN_RELEVANCE": "0.05"})
    # the constructor resumes an interrupted migration BEFORE serving
    fed = Federation(sc, n_groups=2, ranges_per_group=2)

    batches = make_batches(args.batches, args.per_batch)
    for i in range(args.start, args.batches):
        fed.router.submit("deduplication", "people", "crm", batches[i])
        print(f"ACK {i}", flush=True)

    # the moved range: the keyspace's first range, which the pristine
    # round-robin map assigns to group 0 — deterministic across runs.
    # After a resumed/completed migration it is already owned by group 1
    # and migrate() reports already_owned instead of re-moving.
    moved_id = f"{0:016x}"
    if args.migrate:
        result = fed.migrator.migrate(moved_id, 1)
        print(f"MIGRATED {json.dumps(result)}", flush=True)

    if args.dump:
        links = []
        for g in fed.groups:
            for wl in g.workloads.values():
                for l in wl.link_database.get_all_links():
                    if fed.map.owner(route_key(l.id1)).group == g.idx:
                        links.append([l.id1, l.id2, l.status.value,
                                      l.kind.value,
                                      round(l.confidence, 12)])
        links.sort()
        feed, token = [], ""
        while True:
            page = fed.router.feed_page("deduplication", "people", token,
                                        5000)
            feed.extend(page["rows"])
            token = page["next_since"]
            if page["drained"]:
                break
        for row in feed:
            row.pop("_updated", None)
        feed.sort(key=lambda r: r["_id"])
        print("DUMP " + json.dumps({
            "links": links,
            "feed": feed,
            "owner": fed.map.find(moved_id).group,
            "frozen": fed.map.find(moved_id).frozen,
            "migrations": fed.migrator.outcomes,
            # phase-timeline ring (ISSUE 16): newest-first, so [0] is
            # the run's own (possibly resumed) migration
            "timelines": fed.migrator.timelines_snapshot(),
        }), flush=True)

    fed.close()
    print("DONE", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
