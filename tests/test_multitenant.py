"""Multi-tenant density (ISSUE 19): the shared device-memory arena,
the deduplicated AOT ladder, and per-tenant quotas.

Invariants held here:

  * arena admission spills the COLDEST resident tenant (cost-ledger
    device-seconds, admission recency as tiebreak), never the admitting
    one, and a spilled tenant's next query faults back in — with the
    event tape and link rows BIT-IDENTICAL to an arena-off control
    (the arena changes WHERE tensors live, never what scoring computes);
  * a corpus that cannot fit the HBM budget even after spilling every
    eligible resident is refused with a loud 503 + Retry-After at the
    HTTP layer (``ArenaAdmissionError``), not an allocator OOM;
  * N same-schema tenants lease ONE shared AOT ladder (same underlying
    dict — an executable registered through one cache is visible to
    all), refcounted: a plan move rebinds the mover onto a new key
    while others keep theirs, and the last lease release evicts the
    ladder's executables;
  * per-tenant journal recovery is ISOLATED: tenant A replaying a large
    backlog fences only A's writes (503 + Retry-After) while tenant B
    ingests normally the whole time — PR 14's per-folder scoping, now
    proven at the HTTP layer;
  * per-tenant DRR quotas: ``DUKE_TENANT_WEIGHT`` scales the round
    quantum, the ``DUKE_TENANT_MIN_SHARE`` floor keeps a zero-weighted
    tenant draining (starvation-proof), and deficit-throttled rounds
    count into ``duke_tenant_throttled_total``.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from sesam_duke_microservice_tpu import telemetry
from sesam_duke_microservice_tpu.core.config import parse_config
from sesam_duke_microservice_tpu.engine.scheduler import (
    IngestScheduler,
    parse_tenant_weights,
)
from sesam_duke_microservice_tpu.engine.workload import build_workload
from sesam_duke_microservice_tpu.links.base import Link, LinkKind, LinkStatus
from sesam_duke_microservice_tpu.links.journal import LinkJournal
from sesam_duke_microservice_tpu.links.replica import encode_link
from sesam_duke_microservice_tpu.links.sqlite import SqliteLinkDatabase
from sesam_duke_microservice_tpu.ops import arena as arena_mod
from sesam_duke_microservice_tpu.ops.arena import (
    ARENA,
    ArenaAdmissionError,
    DeviceArena,
)
from sesam_duke_microservice_tpu.service.app import DukeApp, serve
from sesam_duke_microservice_tpu.telemetry import memory
from sesam_duke_microservice_tpu.utils import faults
from sesam_duke_microservice_tpu.utils.jit_cache import SHARED_LADDERS

from test_observability import parse_exposition  # noqa: F401
from test_scheduler import CONFIG_XML, EventLog, link_rows


@pytest.fixture(autouse=True)
def _clean_slate(monkeypatch):
    # pin the density features on regardless of the CI leg's env (the
    # arena=0 legacy leg runs this suite too — tests that exercise the
    # opt-outs set DUKE_ARENA/DUKE_SHARED_AOT=0 themselves)
    monkeypatch.setenv("DUKE_ARENA", "1")
    monkeypatch.setenv("DUKE_SHARED_AOT", "1")
    faults.configure("")
    ARENA._reset_for_tests()
    SHARED_LADDERS._reset_for_tests()
    yield
    faults.configure(None)
    ARENA._reset_for_tests()
    SHARED_LADDERS._reset_for_tests()


@pytest.fixture()
def sc(monkeypatch):
    monkeypatch.setenv("MIN_RELEVANCE", "0.05")
    return parse_config(CONFIG_XML)


class _Owner:
    """A fake corpus: admission needs only a spill callable."""

    def __init__(self):
        self.spilled = 0

    def spill(self) -> int:
        self.spilled += 1
        return 0


def _arena(budget):
    a = DeviceArena()
    a._budget_bytes = lambda: float(budget)
    return a


# -- tentpole a: the shared device memory arena (unit) ------------------------


class TestArenaUnit:
    def test_admit_within_budget_keeps_everyone_resident(self):
        a = _arena(1000)
        o1, o2 = _Owner(), _Owner()
        a.admit(o1, 400, spill=o1.spill, label="t1")
        a.admit(o2, 400, spill=o2.spill, label="t2")
        assert a.tier_bytes() == {"device": 800, "host": 0}
        assert (o1.spilled, o2.spilled) == (0, 0)
        assert a.admissions == 2 and a.spills == 0

    def test_eviction_picks_the_coldest_tenant_first(self):
        a = _arena(1000)
        hot, cold, new = _Owner(), _Owner(), _Owner()
        a.admit(cold, 400, spill=cold.spill, label="cold",
                heat=lambda: 0.01)
        a.admit(hot, 400, spill=hot.spill, label="hot",
                heat=lambda: 99.0)
        a.admit(new, 400, spill=new.spill, label="new")
        assert cold.spilled == 1 and hot.spilled == 0
        assert a.tier_bytes() == {"device": 800, "host": 400}
        assert a.spills == 1

    def test_admitting_owner_is_never_its_own_victim(self):
        a = _arena(1000)
        o = _Owner()
        a.admit(o, 900, spill=o.spill)
        # regrow past the budget alone: must reject, not self-spill
        with pytest.raises(ArenaAdmissionError):
            a.admit(o, 1100, spill=o.spill)
        assert o.spilled == 0 and a.rejections == 1

    def test_budget_exhaustion_raises_not_ooms(self):
        a = _arena(500)
        o1, o2 = _Owner(), _Owner()
        a.admit(o1, 300, spill=o1.spill, label="resident")
        with pytest.raises(ArenaAdmissionError) as e:
            a.admit(o2, 600, spill=o2.spill, label="huge")
        assert e.value.need == 600 and e.value.budget == 500
        # a doomed admission must not evict bystanders on the way down
        assert o1.spilled == 0
        assert a.tier_bytes()["device"] == 300

    def test_fault_in_counts_only_after_a_spill(self):
        a = _arena(500)
        o1, o2 = _Owner(), _Owner()
        a.admit(o1, 300, spill=o1.spill)     # cold start: not a fault
        a.admit(o2, 300, spill=o2.spill)     # spills o1
        assert a.faults == 0
        a.admit(o1, 300, spill=o1.spill)     # fault-in (spills o2)
        assert a.faults == 1
        a.admit(o1, 300, spill=o1.spill)     # steady state: no-op
        assert a.faults == 1 and a.admissions == 3

    def test_disabled_arena_is_a_noop(self, monkeypatch):
        monkeypatch.setenv("DUKE_ARENA", "0")
        a = _arena(10)
        o = _Owner()
        a.admit(o, 1 << 30, spill=o.spill)  # way past budget: no reject
        assert a.tier_bytes() == {"device": 0, "host": 0}

    def test_dead_owners_are_pruned(self):
        a = _arena(1000)
        o = _Owner()
        a.admit(o, 400, spill=o.spill)
        del o
        import gc

        gc.collect()
        assert a.tier_bytes() == {"device": 0, "host": 0}

    def test_debug_snapshot_shape(self):
        a = _arena(1000)
        o = _Owner()
        a.admit(o, 400, spill=o.spill, label="dedup/people",
                heat=lambda: 1.25)
        snap = a.debug_snapshot()
        assert snap["enabled"] is True
        (row,) = snap["leases"]
        assert row == {"label": "dedup/people", "bytes": 400,
                       "resident": True, "faults": 0,
                       "heat_device_seconds": 1.25}
        assert snap["tiers"] == {"device": 400, "host": 0}


# -- tentpole a: spill -> fault-in bit-identity (device backend) --------------


REQUESTS = [
    ("crm", [{"_id": "a1", "name": "acme corp", "email": "a@x.no"},
             {"_id": "a2", "name": "acme corp", "email": "a@x.no"}]),
    ("reg", [{"_id": "r1", "name": "bolt ltd"},
             {"_id": "r2", "name": "bolt ltd"}]),
    ("crm", [{"_id": "a3", "name": "quux as", "email": "q@x.no"},
             {"_id": "a4", "name": "quux as", "email": "q@x.no"}]),
    ("reg", [{"_id": "r3", "name": "acme corp"}]),
]


def _run_two_tenants(sc, budget=None):
    """Drive two device workloads through REQUESTS, optionally forcing
    the global arena's budget so the second tenant's admission spills
    the first.  Returns (tapes, rows, faults, spills)."""
    wls = {
        "people": build_workload(sc.deduplications["people"], sc,
                                 backend="device", persistent=False),
        "orgs": build_workload(sc.deduplications["orgs"], sc,
                               backend="device", persistent=False),
    }
    logs = {}
    for name, wl in wls.items():
        logs[name] = EventLog()
        wl.processor.add_match_listener(logs[name])
    old_budget = ARENA._budget_bytes
    try:
        if budget is not None:
            ARENA._budget_bytes = lambda: float(budget)
        for dataset, entities in REQUESTS:
            wl = wls["people"] if dataset == "crm" else wls["orgs"]
            wl.submit_batch(dataset, entities)
        tapes = {n: logs[n].events for n in wls}
        rows = {n: link_rows(wls[n]) for n in wls}
        return tapes, rows, ARENA.faults, ARENA.spills
    finally:
        ARENA._budget_bytes = old_budget
        for wl in wls.values():
            wl.close()


class TestSpillFaultInBitIdentity:
    def test_spill_and_fault_in_tapes_bit_identical(self, sc, monkeypatch):
        # control: arena off, both tenants pinned (the legacy behavior)
        monkeypatch.setenv("DUKE_ARENA", "0")
        control_tapes, control_rows, _, _ = _run_two_tenants(sc)
        ARENA._reset_for_tests()

        # arena on with a budget that fits ONE tenant: each dataset flip
        # in REQUESTS forces a spill of the other tenant and a fault-in
        monkeypatch.setenv("DUKE_ARENA", "1")
        wl = build_workload(sc.deduplications["people"], sc,
                            backend="device", persistent=False)
        try:
            wl.submit_batch("crm", REQUESTS[0][1])
            one = wl.index.corpus._device_nbytes()
            assert one > 0
        finally:
            wl.close()
        ARENA._reset_for_tests()

        tapes, rows, faults, spills = _run_two_tenants(
            sc, budget=int(one * 1.5))
        assert spills >= 2, "the budget must actually force spills"
        assert faults >= 1, "a spilled tenant must fault back in"
        assert tapes == control_tapes
        assert rows == control_rows
        assert rows["people"], "the duplicate upserts must have linked"

    def test_arena_families_render_after_spill(self, sc, monkeypatch):
        monkeypatch.setenv("DUKE_ARENA", "1")
        wl = build_workload(sc.deduplications["people"], sc,
                            backend="device", persistent=False)
        try:
            wl.submit_batch("crm", REQUESTS[0][1])
            one = wl.index.corpus._device_nbytes()
            old = ARENA._budget_bytes
            ARENA._budget_bytes = lambda: float(one * 1.1)
            try:
                wl2 = build_workload(sc.deduplications["orgs"], sc,
                                     backend="device", persistent=False)
                try:
                    wl2.submit_batch("reg", REQUESTS[1][1])
                    wl.submit_batch("crm", REQUESTS[2][1])  # fault-in
                finally:
                    wl2.close()
            finally:
                ARENA._budget_bytes = old
            scraped = parse_exposition(telemetry.render(telemetry.GLOBAL))
            dev = scraped[("duke_arena_bytes", (("tier", "device"),))]
            assert dev > 0
            assert ("duke_arena_bytes", (("tier", "host"),)) in scraped
            assert scraped[("duke_arena_faults_total", ())] >= 1.0
        finally:
            wl.close()

    def test_ledger_attributes_arena_once(self, sc, monkeypatch):
        """Satellite 1: with the arena on, resident slab bytes sit under
        the arena owner while tenants keep LOGICAL views — the budget
        totals count the slabs exactly once."""
        monkeypatch.setenv("DUKE_ARENA", "1")
        memory._reset_for_tests()
        wl = build_workload(sc.deduplications["people"], sc,
                            backend="device", persistent=False)
        try:
            wl.submit_batch("crm", REQUESTS[0][1])
            snap = memory.debug_snapshot()
            arena_rows = [r for r in snap["workloads"]
                          if r["kind"] == "arena"]
            logical_rows = [r for r in snap["workloads"]
                            if r.get("logical")]
            assert arena_rows, "arena must re-enroll after a ledger reset"
            assert logical_rows, "tenants must keep logical views"
            arena_total = sum(r["bytes"] for r in arena_rows)
            logical_corpus = sum(
                r["bytes"] for r in logical_rows
                if r["component"] in memory._ARENA_VIEW_COMPONENTS)
            assert arena_total == pytest.approx(logical_corpus)
            # the budget total counts the slabs once: arena rows plus
            # process-level components (AOT executables, feature cache)
            # — the tenants' logical corpus views add NOTHING on top
            process_total = sum(snap["process"].values())
            assert snap["total_bytes"] == pytest.approx(
                arena_total + process_total)
            assert snap["arena"]["tiers"]["device"] == arena_total
        finally:
            wl.close()
            memory._reset_for_tests()


# -- tentpole a: budget exhaustion is a loud 503 ------------------------------


class _NoRedirect(urllib.request.HTTPRedirectHandler):
    def redirect_request(self, *args, **kwargs):
        return None


_opener = urllib.request.build_opener(_NoRedirect)


def _request(url, method="GET", body=None, headers=None, timeout=30):
    req = urllib.request.Request(url, data=body, method=method,
                                 headers=headers or {})
    try:
        with _opener.open(req, timeout=timeout) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def _post(url, payload):
    return _request(url, "POST", json.dumps(payload).encode(),
                    {"Content-Type": "application/json"})


class TestBudgetCeiling503:
    def test_exhausted_budget_maps_to_503_with_retry_after(
            self, tmp_path, monkeypatch):
        monkeypatch.setenv("MIN_RELEVANCE", "0.05")
        monkeypatch.setenv("DUKE_ARENA", "1")
        sc = parse_config(CONFIG_XML)
        app = DukeApp(sc, backend="device", persistent=False)
        server = serve(app, port=0, host="127.0.0.1")
        threading.Thread(target=server.serve_forever, daemon=True).start()
        url = f"http://127.0.0.1:{server.server_address[1]}"
        old = ARENA._budget_bytes
        try:
            ARENA._budget_bytes = lambda: 16.0  # nothing fits
            status, headers, body = _post(
                url + "/deduplication/people/crm",
                [{"_id": "x1", "name": "acme", "email": "a@x"}])
            assert status == 503
            assert "HBM budget exhausted" in body.decode()
            assert headers.get("Retry-After")
            # raising the ceiling heals the tenant without a restart
            ARENA._budget_bytes = old
            status, _, _ = _post(
                url + "/deduplication/people/crm",
                [{"_id": "x2", "name": "acme", "email": "a@x"}])
            assert status == 200
        finally:
            ARENA._budget_bytes = old
            server.shutdown()
            app.close()


# -- tentpole b: the deduplicated AOT ladder ----------------------------------


class TestSharedLadder:
    def _two_same_schema(self, sc):
        w1 = build_workload(sc.deduplications["people"], sc,
                            backend="device", persistent=False)
        w2 = build_workload(parse_config(CONFIG_XML).deduplications["people"],
                            sc, backend="device", persistent=False)
        return w1, w2

    def test_same_schema_tenants_share_one_ladder(self, sc):
        w1, w2 = self._two_same_schema(sc)
        try:
            w1.submit_batch("crm", REQUESTS[0][1])
            w2.submit_batch("crm", REQUESTS[0][1])
            c1 = w1.index.scorer_cache
            c2 = w2.index.scorer_cache
            assert c1._aot is c2._aot, \
                "same (fingerprint, geometry) must lease ONE ladder"
            stats = SHARED_LADDERS.stats()
            assert stats["ladders"] == 1 and stats["refs"] == 2
            # an executable registered through one tenant serves all:
            # the maps are the same object, so dispatch on tenant 2 hits
            # entries tenant 1 compiled (the N-tenants-one-compile win)
            if c1._aot:
                akey = next(iter(c1._aot))
                assert c2._aot[akey] is c1._aot[akey]
        finally:
            w1.close()
            w2.close()
        # refcounted evict: both leases released on close
        assert SHARED_LADDERS.stats() == {
            "ladders": 0, "refs": 0, "executables": 0}

    def test_last_release_evicts_the_ladder(self, sc):
        w1, w2 = self._two_same_schema(sc)
        w1.submit_batch("crm", REQUESTS[0][1])
        w2.submit_batch("crm", REQUESTS[0][1])
        shared_map = w1.index.scorer_cache._aot
        w1.close()
        stats = SHARED_LADDERS.stats()
        assert stats["ladders"] == 1 and stats["refs"] == 1
        assert w2.index.scorer_cache._aot is shared_map, \
            "the survivor keeps the warm ladder"
        w2.close()
        assert SHARED_LADDERS.stats()["ladders"] == 0

    def test_plan_move_rebinds_without_disturbing_others(self, sc):
        """The refcounted form of the eviction seam: a geometry flip
        (group_filtering here — same facet family as a plan move)
        rebinds the mover to a NEW key; the other tenant keeps its
        ladder and executables."""
        w1, w2 = self._two_same_schema(sc)
        try:
            w1.submit_batch("crm", REQUESTS[0][1])
            w2.submit_batch("crm", REQUESTS[0][1])
            c1 = w1.index.scorer_cache
            c2 = w2.index.scorer_cache
            kept = c2._aot
            c1._rebind_shared_ladder(True)  # key differs from gf=False
            stats = SHARED_LADDERS.stats()
            assert stats["ladders"] == 2 and stats["refs"] == 2
            assert c1._aot is not c2._aot
            assert c2._aot is kept
        finally:
            w1.close()
            w2.close()
        assert SHARED_LADDERS.stats()["ladders"] == 0

    def test_concurrent_plan_mutation_keeps_refcounts_consistent(self, sc):
        """Two tenants flip between ladder keys concurrently (the
        worst-case plan-mutation interleaving): refcounts stay exact,
        no ladder leaks, no double-free."""
        w1, w2 = self._two_same_schema(sc)
        try:
            w1.submit_batch("crm", REQUESTS[0][1])
            w2.submit_batch("crm", REQUESTS[0][1])
            caches = [w1.index.scorer_cache, w2.index.scorer_cache]
            errors = []

            def churn(cache, n):
                try:
                    for i in range(n):
                        cache._rebind_shared_ladder(bool(i % 2))
                except Exception as e:  # pragma: no cover
                    errors.append(e)

            threads = [threading.Thread(target=churn, args=(c, 60))
                       for c in caches for _ in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            assert not errors
            stats = SHARED_LADDERS.stats()
            assert stats["refs"] == 2
            assert 1 <= stats["ladders"] <= 2
            for c in caches:
                assert c._aot is c._shared_holder[0].map
        finally:
            w1.close()
            w2.close()
        assert SHARED_LADDERS.stats() == {
            "ladders": 0, "refs": 0, "executables": 0}

    def test_shared_refs_gauge_renders(self, sc):
        w1, w2 = self._two_same_schema(sc)
        try:
            w1.submit_batch("crm", REQUESTS[0][1])
            w2.submit_batch("crm", REQUESTS[0][1])
            scraped = parse_exposition(telemetry.render(telemetry.GLOBAL))
            assert scraped[("duke_aot_shared_refs", ())] == 2.0
        finally:
            w1.close()
            w2.close()

    def test_shared_ladder_opt_out(self, sc, monkeypatch):
        monkeypatch.setenv("DUKE_SHARED_AOT", "0")
        w1, w2 = self._two_same_schema(sc)
        try:
            w1.submit_batch("crm", REQUESTS[0][1])
            w2.submit_batch("crm", REQUESTS[0][1])
            assert w1.index.scorer_cache._aot is not \
                w2.index.scorer_cache._aot
            assert SHARED_LADDERS.stats()["ladders"] == 0
        finally:
            w1.close()
            w2.close()


# -- tentpole c: per-tenant quotas --------------------------------------------


class TestTenantQuotas:
    def test_weight_spec_parsing(self):
        w = parse_tenant_weights("people=2, deduplication/orgs=0.5")
        assert w == {"people": 2.0, "deduplication/orgs": 0.5}
        # malformed entries are skipped, never fatal; negatives clamp
        w = parse_tenant_weights("a=junk,b,=3,c=-1,d=4")
        assert w == {"c": 0.0, "d": 4.0}
        assert parse_tenant_weights(None) == {}

    def test_weights_scale_the_round_quantum(self, sc, monkeypatch):
        monkeypatch.setenv("DUKE_TENANT_WEIGHT",
                           "deduplication/people=2,orgs=0.5")
        wls = {
            "people": build_workload(sc.deduplications["people"], sc,
                                     backend="host", persistent=False),
            "orgs": build_workload(sc.deduplications["orgs"], sc,
                                   backend="host", persistent=False),
        }
        sched = IngestScheduler(lambda kind, name: wls[name])
        try:
            sched.submit("deduplication", "people", "crm",
                         [{"_id": "p1", "name": "acme", "email": "a@x"}])
            sched.submit("deduplication", "orgs", "reg",
                         [{"_id": "o1", "name": "acme"}])
            by_name = {q.name: q for q in sched.queues()}
            assert by_name["people"].weight == 2.0
            assert by_name["orgs"].weight == 0.5
            assert sched._quantum_for(by_name["people"]) == \
                2 * sched.quantum
            assert sched._quantum_for(by_name["orgs"]) == \
                max(int(sched.quantum * sched.min_share),
                    sched.quantum // 2)
        finally:
            sched.shutdown()
            for wl in wls.values():
                wl.close()

    def test_zero_weight_still_drains_via_min_share_floor(
            self, sc, monkeypatch):
        """Starvation-proof: a zero-weighted tenant's grant is the
        min-share floor — its requests complete, just last."""
        monkeypatch.setenv("DUKE_TENANT_WEIGHT", "people=0")
        wl = build_workload(sc.deduplications["people"], sc,
                            backend="host", persistent=False)
        sched = IngestScheduler(lambda kind, name: wl)
        try:
            sched.submit("deduplication", "people", "crm",
                         [{"_id": f"z{i}", "name": f"zed {i}",
                           "email": f"z{i}@x"} for i in range(8)])
            (q,) = sched.queues()
            assert q.weight == 0.0
            assert sched._quantum_for(q) == max(
                1, int(sched.quantum * sched.min_share))
            assert q.dispatched_records == 8  # it actually drained
        finally:
            sched.shutdown()
            wl.close()

    def test_throttled_rounds_count_and_work_completes(
            self, sc, monkeypatch):
        """A batch wider than the tenant's grant defers to later rounds
        (deficit accumulates) and each deferral counts into the
        ``duke_tenant_throttled_total`` family."""
        monkeypatch.setenv("DUKE_SCHED_QUANTUM", "2")
        wl = build_workload(sc.deduplications["people"], sc,
                            backend="host", persistent=False)
        sched = IngestScheduler(lambda kind, name: wl, start=False)
        try:
            t = threading.Thread(
                target=sched.submit,
                args=("deduplication", "people", "crm",
                      [{"_id": f"t{i}", "name": f"tee {i}",
                        "email": f"t{i}@x"} for i in range(8)]))
            t.start()
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                qs = sched.queues()
                if qs and qs[0].pending:
                    break
                time.sleep(0.01)
            sched.start()
            t.join(timeout=30)
            assert not t.is_alive()
            (q,) = sched.queues()
            assert q.throttled >= 1, \
                "an 8-record batch on a quantum of 2 must defer rounds"
            assert q.dispatched_records == 8
            snap = sched.stats_snapshot()
            assert snap["min_share"] == pytest.approx(0.05)
            (row,) = snap["workloads"]
            assert row["throttled"] == q.throttled
            assert row["weight"] == 1.0
        finally:
            sched.shutdown()
            wl.close()

    def test_down_weighted_retry_after_scales(self, sc, monkeypatch):
        """A down-weighted tenant's 429 Retry-After reflects ITS drain
        rate (est / weight), not the fleet's."""
        monkeypatch.setenv("DUKE_TENANT_WEIGHT", "people=0.25")
        wl = build_workload(sc.deduplications["people"], sc,
                            backend="host", persistent=False)
        sched = IngestScheduler(lambda kind, name: wl, start=False)
        try:
            # seed the queue so the estimator sees backlog + weight
            t = threading.Thread(
                target=sched.submit,
                args=("deduplication", "people", "crm",
                      [{"_id": f"w{i}", "name": "acme", "email": "a@x"}
                       for i in range(4)]))
            t.start()
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                qs = sched.queues()
                if qs and qs[0].pending:
                    break
                time.sleep(0.01)
            (q,) = sched.queues()
            assert q.weight == 0.25
            with sched._cv:
                sched._ewma_sec_per_record = 2.0  # 4 records -> est 8 s
                weighted = sched._retry_after_locked(q)
                q.weight = 1.0
                unweighted = sched._retry_after_locked(q)
                q.weight = 0.25
            assert unweighted == 8
            assert weighted == 32, \
                "0.25-weight drains 4x slower: Retry-After must say so"
            sched.start()
            t.join(timeout=30)
        finally:
            sched.shutdown()
            wl.close()


# -- satellite: per-tenant recovery isolation ---------------------------------


TWO_TENANT_DURABLE_XML = """
<DukeMicroService dataFolder="{folder}">
  <Deduplication name="people">
    <duke>
      <schema>
        <threshold>0.8</threshold>
        <property><name>NAME</name><comparator>levenshtein</comparator><low>0.1</low><high>0.95</high></property>
      </schema>
      <data-source class="io.sesam.dukemicroservice.IncrementalDeduplicationDataSource">
        <param name="dataset-id" value="crm"/>
        <column name="name" property="NAME"/>
      </data-source>
    </duke>
  </Deduplication>
  <Deduplication name="orgs">
    <duke>
      <schema>
        <threshold>0.8</threshold>
        <property><name>NAME</name><comparator>levenshtein</comparator><low>0.1</low><high>0.95</high></property>
      </schema>
      <data-source class="io.sesam.dukemicroservice.IncrementalDeduplicationDataSource">
        <param name="dataset-id" value="reg"/>
        <column name="name" property="NAME"/>
      </data-source>
    </duke>
  </Deduplication>
</DukeMicroService>
"""


def _link(i, t0=1_000_000):
    return Link(f"a{i}", f"b{i}", LinkStatus.INFERRED, LinkKind.DUPLICATE,
                0.9, t0 + i)


class TestRecoveryIsolation:
    def test_tenant_a_replay_fences_only_tenant_a(
            self, tmp_path, monkeypatch):
        """PR 14's per-folder scoping, proven end to end: tenant A boots
        into a journal replay of a large acked backlog; for the whole
        replay window A's writes 503 with Retry-After while B's ingest
        lands 200.  When A's fence lifts, A writes normally and the
        recovered backlog is intact."""
        monkeypatch.setenv("MIN_RELEVANCE", "0.05")
        monkeypatch.setenv("DUKE_JOURNAL", "1")  # pin under the =0 CI leg
        folder = tmp_path / "deduplication" / "people"
        folder.mkdir(parents=True)
        n = 1024
        j = LinkJournal(str(folder / "linkdatabase.journal"), sync="none")
        for i in range(n):
            j.append_batch([encode_link(_link(i))])
        j.close()

        # slow each replay chunk so the overlap window is deterministic:
        # only the link-recovery thread gates (B has no backlog, and
        # post-fence flushes run on the write-behind thread)
        real = SqliteLinkDatabase.assert_links

        def gated(self, links):
            if threading.current_thread().name == "link-recovery":
                time.sleep(0.35)
            return real(self, links)

        monkeypatch.setattr(SqliteLinkDatabase, "assert_links", gated)
        sc = parse_config(TWO_TENANT_DURABLE_XML.format(folder=tmp_path))
        app = DukeApp(sc, backend="host", persistent=True)
        server = serve(app, port=0, host="127.0.0.1")
        threading.Thread(target=server.serve_forever, daemon=True).start()
        url = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            a_db = app.deduplications["people"].link_database
            assert getattr(a_db, "recovering", False), \
                "the 1024-batch backlog must still be replaying"
            # tenant A: fenced for the whole replay
            status, headers, body = _post(
                url + "/deduplication/people/crm",
                [{"_id": "pa", "name": "fenced write"}])
            assert status == 503
            assert headers.get("Retry-After")
            assert "replaying" in body.decode()
            # tenant B: completely unaffected, repeatedly, while A is
            # still mid-replay (asserted before AND after the writes)
            for i in range(3):
                status, headers, _ = _post(
                    url + "/deduplication/orgs/reg",
                    [{"_id": f"ob{i}", "name": f"org {i}"},
                     {"_id": f"ob{i}x", "name": f"org {i}"}])
                assert status == 200, \
                    "tenant B must ingest while A replays"
            # fence lifts: A serves writes again, backlog intact
            deadline = time.monotonic() + 60
            while getattr(a_db, "recovering", False) and \
                    time.monotonic() < deadline:
                time.sleep(0.05)
            assert not getattr(a_db, "recovering", False)
            status, _, _ = _post(
                url + "/deduplication/people/crm",
                [{"_id": "pz", "name": "post-recovery write"}])
            assert status == 200
            recovered = a_db.get_changes_since(0)
            assert len(recovered) >= n
            b_rows = app.deduplications["orgs"] \
                .link_database.get_changes_since(0)
            assert b_rows, "B's overlapped ingest must have linked"
        finally:
            server.shutdown()
            app.close()
