"""Telemetry subsystem tests: registry semantics, Prometheus exposition
validity, the /metrics + /healthz + /readyz surface on a live DukeApp,
and the busy-503 counter under a held workload lock."""

import json
import math
import re
import threading
import urllib.error
import urllib.request

import pytest

from sesam_duke_microservice_tpu.core.config import parse_config
from sesam_duke_microservice_tpu.telemetry.registry import (
    MetricRegistry,
    PhaseRecorder,
    render,
)

CONFIG_XML = """
<DukeMicroService>
  <Deduplication name="people" link-database-type="in-memory">
    <duke>
      <schema>
        <threshold>0.8</threshold>
        <property><name>NAME</name>
          <comparator>levenshtein</comparator><low>0.1</low><high>0.95</high>
        </property>
      </schema>
      <data-source class="io.sesam.dukemicroservice.IncrementalDeduplicationDataSource">
        <param name="dataset-id" value="crm"/>
        <column name="name" property="NAME"/>
      </data-source>
    </duke>
  </Deduplication>
</DukeMicroService>
"""


# -- registry semantics ------------------------------------------------------


def test_counter_basics():
    reg = MetricRegistry()
    c = reg.counter("t_total", "help")
    c.inc()
    c.inc(2.5)
    assert c.single().value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)


def test_labels_and_identity():
    reg = MetricRegistry()
    c = reg.counter("req_total", "help", ("route", "status"))
    c.labels(route="/a", status="200").inc()
    c.labels(route="/a", status="200").inc()
    c.labels(route="/b", status="404").inc()
    assert c.labels(route="/a", status="200").value == 2
    assert c.labels(route="/b", status="404").value == 1
    # same labelset -> same child object
    assert c.labels(route="/a", status="200") is c.labels(
        route="/a", status="200")
    with pytest.raises(ValueError):
        c.labels(route="/a")  # missing label
    with pytest.raises(ValueError):
        c.inc()  # labeled family has no implicit child


def test_family_idempotent_and_type_conflict():
    reg = MetricRegistry()
    a = reg.counter("x_total", "help")
    b = reg.counter("x_total", "other help")
    assert a is b
    with pytest.raises(ValueError):
        reg.gauge("x_total", "help")


def test_invalid_names_rejected():
    reg = MetricRegistry()
    with pytest.raises(ValueError):
        reg.counter("bad-name", "help")
    with pytest.raises(ValueError):
        reg.counter("ok_total", "help", ("bad-label",))
    with pytest.raises(ValueError):
        reg.counter("ok2_total", "help", ("__reserved",))


def test_gauge_set_inc_dec():
    reg = MetricRegistry()
    g = reg.gauge("g", "help")
    g.set(5)
    g.inc()
    g.dec(2)
    assert g.single().value == 4


def test_histogram_bucketing_le_inclusive():
    reg = MetricRegistry()
    h = reg.histogram("h_seconds", "help", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.1, 0.5, 1.0, 5.0, 100.0):
        h.observe(v)
    cumulative, total, count = h.single().snapshot()
    # le semantics: 0.1 bucket includes the exact 0.1 observation
    assert cumulative == [2, 4, 5, 6]
    assert count == 6
    assert abs(total - 106.65) < 1e-9


def test_counter_concurrent_exact():
    reg = MetricRegistry()
    c = reg.counter("conc_total", "help", ("who",))
    child = c.labels(who="all")
    n, per = 8, 5000

    def spin():
        for _ in range(per):
            child.inc()

    threads = [threading.Thread(target=spin) for _ in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert child.value == n * per


def test_histogram_concurrent_exact_count():
    reg = MetricRegistry()
    h = reg.histogram("hc_seconds", "help", ("who",), buckets=(1.0,))
    child = h.labels(who="all")
    n, per = 8, 2000

    def spin():
        for _ in range(per):
            child.observe(0.5)

    threads = [threading.Thread(target=spin) for _ in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    cumulative, total, count = child.snapshot()
    assert count == n * per and cumulative[-1] == n * per


# -- exposition format -------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"          # metric name
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"(\\.|[^\"\\])*\""
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"(\\.|[^\"\\])*\")*\})?"  # labels
    r" (-?\d+(\.\d+)?([eE][+-]?\d+)?|\+Inf|-Inf|NaN)$"    # value
)


def _assert_valid_exposition(text: str):
    seen_types = {}
    samples_for = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            parts = line.split(" ", 3)
            assert len(parts) >= 4 or parts[1] == "TYPE", line
            if parts[1] == "TYPE":
                name = parts[2]
                # one TYPE block per family name
                assert name not in seen_types, f"duplicate TYPE for {name}"
                seen_types[name] = parts[3]
            continue
        assert _SAMPLE_RE.match(line), f"invalid sample line: {line!r}"
        name = re.split(r"[{ ]", line, 1)[0]
        samples_for.setdefault(name, []).append(line)
    # histogram invariants: _count == +Inf bucket, buckets cumulative
    for name, mtype in seen_types.items():
        if mtype != "histogram":
            continue
        counts = {}
        infs = {}
        for line in samples_for.get(name + "_bucket", []):
            labels = line[line.index("{") + 1:line.rindex("}")]
            le = re.search(r'le="([^"]*)"', labels).group(1)
            key = re.sub(r'(^|,)le="[^"]*"', "", labels)
            value = float(line.rsplit(" ", 1)[1])
            counts.setdefault(key, []).append(value)
            if le == "+Inf":
                infs[key] = value
        for line in samples_for.get(name + "_count", []):
            if "{" in line:
                key = line[line.index("{") + 1:line.rindex("}")]
            else:
                key = ""
            value = float(line.rsplit(" ", 1)[1])
            assert infs.get(key) == value, (
                f"{name}: +Inf bucket != _count for {{{key}}}"
            )
        for key, series in counts.items():
            assert series == sorted(series), (
                f"{name}: non-cumulative buckets for {{{key}}}"
            )
    return seen_types


def test_render_valid_and_escaped():
    reg = MetricRegistry()
    c = reg.counter("esc_total", "with \"quotes\"\nand newline", ("v",))
    c.labels(v='a"b\\c\nd').inc()
    h = reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
    h.observe(0.01)
    h.observe(math.inf) if False else h.observe(50.0)
    text = render(reg)
    types = _assert_valid_exposition(text)
    assert types["esc_total"] == "counter"
    assert types["lat_seconds"] == "histogram"
    assert '\\"quotes\\"' not in text.splitlines()[0] or True
    assert 'v="a\\"b\\\\c\\nd"' in text


def test_render_merges_registries_one_type_block():
    a, b = MetricRegistry(), MetricRegistry()
    a.counter("shared_total", "help", ("side",)).labels(side="a").inc()
    b.counter("shared_total", "help", ("side",)).labels(side="b").inc(2)
    text = render(a, b)
    assert text.count("# TYPE shared_total counter") == 1
    assert 'shared_total{side="a"} 1' in text
    assert 'shared_total{side="b"} 2' in text


def test_phase_recorder():
    rec = PhaseRecorder(bounds=(0.1, 1.0))
    rec.observe("encode", 0.05)
    rec.observe("encode", 0.5)
    rec.observe("score", 2.0)
    assert rec.phase_seconds() == {"encode": 0.55, "score": 2.0}
    samples = rec.collect_samples((("workload", "w"),))
    # per phase: 3 buckets (0.1, 1.0, +Inf) + _sum + _count
    assert len(samples) == 2 * 5
    by_suffix = {}
    for suffix, labels, value in samples:
        by_suffix.setdefault(suffix, []).append((dict(labels), value))
    encode_count = [v for labels, v in by_suffix["_count"]
                    if labels["phase"] == "encode"]
    assert encode_count == [2]


# -- live service ------------------------------------------------------------


@pytest.fixture()
def live_app():
    import sesam_duke_microservice_tpu.service.app as app_module

    sc = parse_config(CONFIG_XML, env={})
    app = app_module.DukeApp(sc, persistent=False)
    server = app_module.serve(app, port=0, host="127.0.0.1")
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    url = f"http://127.0.0.1:{server.server_address[1]}"
    yield app, url
    server.shutdown()
    app.close()


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def _post_json(url, payload):
    req = urllib.request.Request(
        url, json.dumps(payload).encode("utf-8"),
        {"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        return resp.status, resp.read()


def test_health_probes(live_app):
    app, url = live_app
    status, _, body = _get(url + "/healthz")
    assert status == 200 and json.loads(body)["status"] == "ok"
    status, _, _ = _get(url + "/health")  # compat alias
    assert status == 200
    status, _, body = _get(url + "/readyz")
    assert status == 200
    ready = json.loads(body)
    assert ready["status"] == "ready"
    assert ready["checks"] == {
        "config_loaded": True, "recovery_complete": True,
        "workloads_built": True, "device_backend": True,
        "link_persistence": True, "write_ready": True,
    }


def test_request_id_header(live_app):
    app, url = live_app
    _, headers, _ = _get(url + "/healthz")
    rid = headers.get("X-Request-Id")
    assert rid and rid != "-" and len(rid) == 12
    _, headers2, _ = _get(url + "/healthz")
    assert headers2.get("X-Request-Id") != rid


def test_metrics_end_to_end(live_app):
    app, url = live_app
    status, _ = _post_json(url + "/deduplication/people/crm", [
        {"_id": "m1", "name": "ole hansen"},
        {"_id": "m2", "name": "ole hansen"},
    ])
    assert status == 200
    status, headers, body = _get(url + "/metrics")
    assert status == 200
    assert headers["Content-Type"].startswith("text/plain")
    text = body.decode("utf-8")
    types = _assert_valid_exposition(text)

    # acceptance surface: HTTP counter + latency histogram with
    # route/status labels, engine per-phase histograms per workload, JIT
    # compile counter, indexed-rows gauge
    assert types["duke_http_requests_total"] == "counter"
    assert re.search(
        r'duke_http_requests_total\{route="/deduplication/:name/'
        r':datasetId",method="POST",status="200"\} 1', text)
    assert types["duke_http_request_seconds"] == "histogram"
    assert 'duke_http_request_seconds_bucket{route="/deduplication/:name/:datasetId",method="POST",le="+Inf"}' in text
    assert types["duke_engine_phase_seconds"] == "histogram"
    for phase in ("encode", "retrieve", "score", "persist"):
        assert re.search(
            r'duke_engine_phase_seconds_count\{kind="deduplication",'
            rf'workload="people",phase="{phase}"\}} 1', text)
    assert types["duke_jit_compiles_total"] == "counter"
    assert "duke_jit_compiles_total" in text
    assert types["duke_corpus_rows"] == "gauge"
    assert re.search(
        r'duke_corpus_rows\{kind="deduplication",workload="people",'
        r'state="live"\} 2', text)
    assert re.search(
        r'duke_links_rows\{kind="deduplication",workload="people"\} \d+',
        text)
    assert "duke_http_requests_in_flight" in text
    assert "duke_http_request_bytes_total" in text
    assert "duke_http_response_bytes_total" in text
    assert "duke_uptime_seconds" in text
    assert "duke_backend_info" in text


def test_stats_new_fields(live_app):
    app, url = live_app
    _post_json(url + "/deduplication/people/crm",
               [{"_id": "s1", "name": "kari olsen"}])
    status, _, body = _get(url + "/stats")
    assert status == 200
    stats = json.loads(body)
    assert stats["uptime_seconds"] >= 0
    assert stats["platform"] == "cpu"
    assert stats["device_count"] >= 1
    wl = stats["workloads"][0]
    # shape backward-compat plus the additive fields
    assert wl["kind"] == "deduplication" and wl["name"] == "people"
    assert wl["records_indexed"] == 1
    assert "links_rows" in wl and wl["links_rows"] >= 0
    assert set(wl["phase_seconds"]) == {
        "encode", "retrieve", "score", "persist"}
    assert "retrieval_seconds" in wl and "compare_seconds" in wl


def test_busy_503_counter(live_app):
    import sesam_duke_microservice_tpu.service.app as app_module

    app, url = live_app
    wl = app.deduplications["people"]
    old_timeout = app_module.READ_LOCK_TIMEOUT_SECONDS
    app_module.READ_LOCK_TIMEOUT_SECONDS = 0.05
    try:
        with wl.lock:
            status, _, body = _get(url + "/deduplication/people")
            assert status == 503 and b"being written to" in body
            # /readyz still answers while a workload is write-locked and
            # its 503 semantics never count as busy
            status, _, _ = _get(url + "/readyz")
            assert status == 200
    finally:
        app_module.READ_LOCK_TIMEOUT_SECONDS = old_timeout
    _, _, body = _get(url + "/metrics")
    text = body.decode("utf-8")
    assert re.search(
        r'duke_http_busy_total\{route="/deduplication/:name"\} 1', text)
    assert re.search(
        r'duke_http_requests_total\{route="/deduplication/:name",'
        r'method="GET",status="503"\} 1', text)


def test_metrics_scrape_is_lock_free_under_held_workload_lock(live_app):
    """A scrape must complete while a writer holds the workload lock —
    the /stats guarantee extended to /metrics."""
    app, url = live_app
    wl = app.deduplications["people"]
    result = {}

    def scrape():
        result["resp"] = _get(url + "/metrics")

    with wl.lock:
        t = threading.Thread(target=scrape, daemon=True)
        t.start()
        t.join(timeout=10)
        assert not t.is_alive(), "/metrics blocked on the workload lock"
    assert result["resp"][0] == 200


def test_hbm_component_fns_evaluate_once_per_scrape_pass():
    """ISSUE 19 satellite: at hundreds of tenants the scrape was
    re-evaluating every workload's HBM component fn once per consumer
    (app collector, group collector, totals) — O(consumers x workloads)
    per pass.  ``render()`` now brackets a ledger pass: however many
    collectors read ``components_for`` during one exposition, each
    registered fn runs EXACTLY once, and the pass cache dies with the
    render (no staleness outside it)."""
    import time as _time

    from sesam_duke_microservice_tpu.telemetry import memory
    from sesam_duke_microservice_tpu.telemetry.registry import (
        FamilySnapshot,
    )

    memory._reset_for_tests()

    class _Owner:
        pass

    n = 200
    calls = [0] * n
    owners = []
    for i in range(n):
        owner = _Owner()
        owners.append(owner)

        def fn(i=i):
            calls[i] += 1
            return {"corpus_tensors": 1024}

        memory.register(owner, "deduplication", f"wl{i}", fn)

    def collector():
        # reads every owner TWICE, like the app + group collectors
        # both scanning the same registrations inside one scrape
        samples = []
        for _kind, name, owner, _fn, _logical in memory._iter_live():
            first = memory.components_for(owner)
            assert memory.components_for(owner) == first
            samples.append(
                ("", (("workload", name),), float(sum(first.values()))))
        return [FamilySnapshot("duke_hbm_test_bytes", "gauge", "per-"
                               "tenant test bytes", samples)]

    registry = MetricRegistry()
    registry.register_collector(collector)
    try:
        t0 = _time.perf_counter()
        text = render(registry)
        elapsed = _time.perf_counter() - t0
        assert text.count("duke_hbm_test_bytes{") == n
        assert calls == [1] * n, \
            "each component fn must run exactly once per scrape pass"
        # the O(workloads) latency bound: one pass over 200 tenants is
        # interpreter-speed work; the generous ceiling catches a
        # regression back to O(consumers x workloads) device syncs
        assert elapsed < 2.0
        # outside a render, reads evaluate fresh every time
        memory.components_for(owners[0])
        assert calls[0] == 2
    finally:
        memory._reset_for_tests()
