"""Child process for the MESH AOT restart differential (tests/test_mesh_aot.py).

The sharded counterpart of aot_restart_child.py: builds a
``ShardedDeviceIndex`` over the virtual 8-device mesh, ingests a
deterministic corpus, waits for the warm thread (every mesh ladder entry
compiled AND serialized), and prints one JSON line with the
compile/load counters plus the full event stream — the parent asserts
the SECOND process deserializes the whole mesh ladder and compiles ZERO
scorers while producing an identical stream.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--records", type=int, default=40)
    args = ap.parse_args()

    from test_device_matcher import EventLog, dedup_schema, random_records

    from sesam_duke_microservice_tpu import telemetry
    from sesam_duke_microservice_tpu.engine.sharded_matcher import (
        ShardedDeviceIndex,
        ShardedDeviceProcessor,
    )

    schema = dedup_schema()
    index = ShardedDeviceIndex(schema)
    processor = ShardedDeviceProcessor(schema, index, group_filtering=False)
    log = EventLog()
    processor.add_match_listener(log)
    records = random_records(args.records, seed=3)
    t0 = time.monotonic()
    processor.deduplicate(records)
    first_batch_s = time.monotonic() - t0
    # the acceptance counter is read BEFORE waiting on the warm thread:
    # "zero scorer compiles before serving its first scoring batch"
    compiles_at_first_batch = telemetry.JIT_COMPILES.single().value
    cache = index.scorer_cache
    t = cache._warm_thread
    if t is not None:
        t.join(timeout=600)
    print("RESULT " + json.dumps({
        "jit_compiles_at_first_batch": compiles_at_first_batch,
        "jit_compiles": telemetry.JIT_COMPILES.single().value,
        "jit_cache_hits": telemetry.JIT_CACHE_HITS.single().value,
        "aot_loaded": cache._aot_loaded,
        "warm_compiled": cache._warm_compiled,
        "warm_seconds": cache._warm_seconds,
        "first_batch_seconds": first_batch_s,
        "mesh_devices": index.mesh.size,
        "supports_dd": bool(cache.supports_dd),
        "dd_gathers": cache._dd_gathers,
        "events": log.events,
    }))


if __name__ == "__main__":
    main()
