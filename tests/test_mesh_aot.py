"""Sharded AOT executable store (ISSUE 18).

The mesh caches keep ``supports_aot`` on: ladder executables are lowered
against mesh-annotated avals (``parallel.sharded.PARTITION_RULES``),
serialized through the same validate-on-save ``AotStore``, and keyed by
the mesh facets — so a restart of a mesh replica deserializes the whole
sharded ladder and compiles ZERO scorers (the cold-start acceptance of
ISSUE 15, extended to the sharded backends), while an executable
partitioned for one topology is unreachable from any other.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from sesam_duke_microservice_tpu.utils.jit_cache import (
    AotStore,
    mesh_fingerprint,
)

CHILD = os.path.join(os.path.dirname(__file__), "mesh_restart_child.py")


def _run_child(aot_dir, xla_dir, *, prewarm="1", aot="1"):
    env = dict(os.environ)
    env.update({
        "DEVICE_CHUNK": "64",
        # one bucket and the from_rows-free mesh ladder keep the cold
        # arm at 2 entries (2 caps x 1 bucket x 1 variant) on the slow
        # CPU backend
        "DEVICE_QUERY_BUCKETS": "8",
        "DEVICE_TOP_K": "16",
        "DEVICE_MAX_CHARS": "24",
        "DEVICE_MAX_GRAMS": "24",
        "DEVICE_PREWARM": prewarm,
        "DUKE_AOT": aot,
        "DUKE_AOT_DIR": str(aot_dir),
        "JAX_COMPILATION_CACHE_DIR": str(xla_dir),
        "DUKE_JIT_CACHE_MIN_SECS": "0",
    })
    proc = subprocess.run(
        [sys.executable, CHILD], capture_output=True, text=True,
        timeout=600, env=env,
    )
    assert proc.returncode == 0, proc.stderr
    line = [ln for ln in proc.stdout.splitlines()
            if ln.startswith("RESULT ")][0]
    return json.loads(line[len("RESULT "):])


def test_mesh_restart_compiles_zero_scorers(tmp_path):
    """THE sharded cold-start differential: process 1 compiles +
    serializes the mesh ladder; process 2 deserializes everything — zero
    compiles through its first scoring batch — with the event stream
    bit-identical."""
    aot_dir, xla_dir = tmp_path / "aot", tmp_path / "xla"
    cold = _run_child(aot_dir, xla_dir)
    assert cold["mesh_devices"] == 8, cold
    assert cold["supports_dd"] is True, cold
    assert cold["warm_compiled"] == 2, cold  # 2 caps x 1 bucket x 1 variant
    assert cold["jit_compiles"] >= 2
    saved = list(aot_dir.glob("*.aotx"))
    assert len(saved) == 2, saved

    warm = _run_child(aot_dir, xla_dir)
    assert warm["jit_compiles_at_first_batch"] == 0, warm
    assert warm["jit_compiles"] == 0, warm  # no miss-fill ran either
    assert warm["aot_loaded"] == 2
    assert warm["warm_compiled"] == 0
    # the scoring outcome is the same mesh program: bit-identical events
    assert warm["events"] == cold["events"]
    assert warm["jit_cache_hits"] >= 1


def test_mesh_aot_off_leg_still_serves(tmp_path):
    """DUKE_AOT=0 pins the legacy jit-only mesh path: nothing saved,
    restart compiles again, events unchanged."""
    aot_dir, xla_dir = tmp_path / "aot", tmp_path / "xla"
    cold = _run_child(aot_dir, xla_dir)
    off = _run_child(aot_dir, xla_dir, aot="0")
    assert off["aot_loaded"] == 0
    assert off["jit_compiles"] > 0
    assert off["events"] == cold["events"]


def _mesh(n):
    import jax

    from sesam_duke_microservice_tpu.parallel.sharded import corpus_mesh

    return corpus_mesh(jax.devices()[:n])


def test_mesh_executable_roundtrip_validate_on_save(tmp_path, monkeypatch):
    """Save/load round-trip of a REAL mesh-partitioned executable: the
    deserialized program executes sharded inputs and reproduces the
    compiled output (including the collective the replicated constraint
    inserts)."""
    import jax
    import jax.numpy as jnp

    from sesam_duke_microservice_tpu.parallel.sharded import rule_sharding

    monkeypatch.setenv("DUKE_AOT_DIR", str(tmp_path / "store"))
    mesh = _mesh(8)
    corpus_sh = rule_sharding(mesh, "corpus", 2)
    repl = rule_sharding(mesh, "queries", 1)

    @jax.jit
    def fn(x):
        return jax.lax.with_sharding_constraint((x * 2.0).sum(axis=1), repl)

    aval = jax.ShapeDtypeStruct((16, 4), jnp.float32, sharding=corpus_sh)
    compiled = fn.lower(aval).compile()

    store = AotStore()
    key = {"builder": "mesh-test", "cap": 16,
           "mesh": mesh_fingerprint(mesh)}
    assert store.save(key, compiled) is True
    loaded = store.load(key)
    assert loaded is not None
    x = jax.device_put(
        np.arange(64, dtype=np.float32).reshape(16, 4), corpus_sh)
    np.testing.assert_array_equal(np.asarray(loaded(x)),
                                  np.asarray(compiled(x)))


def test_mesh_save_reject_path_is_loud(tmp_path, monkeypatch, caplog):
    """Validate-on-save: when the PJRT layer cannot round-trip a mesh
    executable, save() refuses (False), persists NOTHING, and logs — the
    warm thread then counts a prewarm miss instead of planting an entry
    every restart would reject."""
    import logging

    import jax
    import jax.numpy as jnp
    from jax.experimental import serialize_executable as se

    from sesam_duke_microservice_tpu.parallel.sharded import rule_sharding

    monkeypatch.setenv("DUKE_AOT_DIR", str(tmp_path / "store"))
    mesh = _mesh(8)
    corpus_sh = rule_sharding(mesh, "corpus", 1)
    fn = jax.jit(lambda x: x * 2.0)
    compiled = fn.lower(
        jax.ShapeDtypeStruct((16,), jnp.float32, sharding=corpus_sh)
    ).compile()

    def broken(*a, **k):
        raise RuntimeError("Symbols not found: mesh executable thin")

    monkeypatch.setattr(se, "deserialize_and_load", broken)
    store = AotStore()
    key = {"builder": "mesh-test", "mesh": mesh_fingerprint(mesh)}
    with caplog.at_level(logging.WARNING, logger="jit-cache"):
        assert store.save(key, compiled) is False
    assert not os.path.exists(store._path(key))
    assert any("save failed" in r.message for r in caplog.records)


def test_mesh_shape_keys_isolate(tmp_path, monkeypatch):
    """A 4-way entry is unreachable from an 8-way mesh (and vice versa)
    even though the environment fingerprint — same host, same 8 visible
    devices — is identical: the mesh facets live in the store KEY."""
    import jax
    import jax.numpy as jnp

    from sesam_duke_microservice_tpu.parallel.sharded import rule_sharding

    monkeypatch.setenv("DUKE_AOT_DIR", str(tmp_path / "store"))
    mesh8, mesh4 = _mesh(8), _mesh(4)
    fp8, fp4 = mesh_fingerprint(mesh8), mesh_fingerprint(mesh4)
    assert fp8 != fp4
    assert fp8["shape"] == [8] and fp4["shape"] == [4]

    store = AotStore()
    logical = {"builder": "mesh-test", "cap": 16}
    key8 = dict(logical, mesh=fp8)
    key4 = dict(logical, mesh=fp4)
    assert store._path(key8) != store._path(key4)

    fn = jax.jit(lambda x: x + 1.0)
    compiled8 = fn.lower(
        jax.ShapeDtypeStruct((16,), jnp.float32,
                             sharding=rule_sharding(mesh8, "corpus", 1))
    ).compile()
    assert store.save(key8, compiled8) is True
    # the 8-way entry exists; the 4-way key misses instead of loading a
    # wrongly-partitioned executable
    assert store.load(key4) is None
    assert store.load(key8) is not None
