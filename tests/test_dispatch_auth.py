"""Dispatch join-handshake hardening (advisor r4).

The frontend must authenticate a fixed-format raw-bytes frame BEFORE any
pickle touches socket bytes: unpickling attacker-controlled bytes is
arbitrary code execution.  These tests drive ``Dispatcher._accept_followers``
directly over loopback sockets — no jax.distributed job needed.
"""

import pickle
import socket
import struct
import threading

import pytest

from sesam_duke_microservice_tpu.parallel import dispatch


PWNED = {"hit": False}


def _set_pwned():
    PWNED["hit"] = True
    return ()


class _Evil:
    """Pickle payload that executes on load (the pre-fix attack shape)."""

    def __reduce__(self):
        return (_set_pwned, ())


def _accept_in_thread(n, token):
    d = dispatch.Dispatcher(app=None)
    d._server = socket.create_server(("127.0.0.1", 0))
    port = d._server.getsockname()[1]
    t = threading.Thread(
        target=d._accept_followers, args=(n, token), daemon=True
    )
    t.start()
    return d, port, t


def test_crafted_pickle_rejected_without_execution(monkeypatch):
    monkeypatch.setattr(dispatch, "_CONNECT_TIMEOUT_S", 10.0)
    PWNED["hit"] = False
    d, port, t = _accept_in_thread(1, "secret-token")
    try:
        # attacker: the old wire format — length-prefixed pickle hello.
        # With the raw handshake this must neither authenticate nor ever
        # reach pickle.loads.
        evil = pickle.dumps(("hello", _Evil()))
        attacker = socket.create_connection(("127.0.0.1", port), timeout=5)
        attacker.sendall(struct.pack(">Q", len(evil)) + evil)
        # half-close so the server's fixed-length read sees EOF even when
        # the crafted frame is shorter than _HELLO_LEN
        attacker.shutdown(socket.SHUT_WR)
        # server should reject; our read then sees EOF
        attacker.settimeout(5)
        assert attacker.recv(1) == b""
        attacker.close()
        assert not PWNED["hit"], "crafted pickle was executed before auth"
        assert d._conns == []
        # the real follower still gets its slot afterwards
        good = socket.create_connection(("127.0.0.1", port), timeout=5)
        good.sendall(dispatch._hello_frame("secret-token"))
        t.join(timeout=10)
        assert not t.is_alive()
        assert len(d._conns) == 1
        good.close()
    finally:
        d._server.close()
        for c in d._conns:
            c.close()


def test_wrong_token_rejected_right_token_accepted(monkeypatch):
    monkeypatch.setattr(dispatch, "_CONNECT_TIMEOUT_S", 10.0)
    d, port, t = _accept_in_thread(1, "right")
    try:
        bad = socket.create_connection(("127.0.0.1", port), timeout=5)
        bad.sendall(dispatch._hello_frame("wrong"))
        bad.settimeout(5)
        assert bad.recv(1) == b""  # rejected: server closed the socket
        bad.close()
        good = socket.create_connection(("127.0.0.1", port), timeout=5)
        good.sendall(dispatch._hello_frame("right"))
        t.join(timeout=10)
        assert len(d._conns) == 1
        good.close()
    finally:
        d._server.close()
        for c in d._conns:
            c.close()


def test_hello_frame_carries_follower_index(monkeypatch):
    """The authenticated hello's trailing index is the follower's stable
    identity: accept order must not define it (ISSUE 8 — DUKE_FAULTS
    coordinates like `partition=1:...` must mean the same process every
    run)."""
    assert dispatch._hello_frame("x", 3)[-8:] == struct.pack(">Q", 3)
    monkeypatch.setattr(dispatch, "_CONNECT_TIMEOUT_S", 10.0)
    d, port, t = _accept_in_thread(2, "tok")
    conns = []
    try:
        # connect in REVERSE process order: idx must come from the frame
        for idx in (1, 0):
            c = socket.create_connection(("127.0.0.1", port), timeout=5)
            c.sendall(dispatch._hello_frame("tok", idx))
            conns.append(c)
        t.join(timeout=10)
        assert not t.is_alive()
        assert [f.idx for f in d._followers] == [1, 0]
    finally:
        d._server.close()
        for c in conns:
            c.close()
        for c in d._conns:
            c.close()


class _StubDispatcher:
    """Records broadcasts + the failure latch (no sockets)."""

    def __init__(self):
        self.ops = []
        self.failed = None
        self.verified = []

    def broadcast(self, op):
        self.ops.append(op[0])

    def mark_failed(self, reason):
        self.failed = reason

    def verify_mirror_digest(self, key, digest):
        self.verified.append((key, digest))


def _tiny_index():
    from sesam_duke_microservice_tpu.core import comparators as C
    from sesam_duke_microservice_tpu.core.config import DukeSchema
    from sesam_duke_microservice_tpu.core.records import (
        ID_PROPERTY_NAME, Property, Record,
    )
    from sesam_duke_microservice_tpu.engine.device_matcher import DeviceIndex

    schema = DukeSchema(
        threshold=0.8, maybe_threshold=None,
        properties=[
            Property(ID_PROPERTY_NAME, id_property=True),
            Property("name", C.Levenshtein(), 0.3, 0.9),
        ],
        data_sources=[],
    )
    idx = DeviceIndex(schema)

    def rec(rid, name):
        r = Record()
        r.add_value(ID_PROPERTY_NAME, rid)
        r.add_value("name", name)
        return r

    return idx, schema, rec


def test_frontend_commit_failure_latches_dispatcher(monkeypatch):
    """A frontend that fails to apply a commit it already broadcast must
    latch the dispatcher (followers are one op ahead — advisor r4)."""
    idx, _schema, rec = _tiny_index()
    idx._dispatch_key = ("deduplication", "t")
    stub = _StubDispatcher()
    monkeypatch.setattr(dispatch, "_DISPATCHER", stub)
    idx.index(rec("a", "acme"))
    monkeypatch.setattr(
        idx, "_append_records",
        lambda *a, **k: (_ for _ in ()).throw(RuntimeError("boom")),
    )
    with pytest.raises(RuntimeError, match="boom"):
        idx.commit()
    assert stub.ops == ["commit"]
    assert stub.failed is not None and "commit failed" in stub.failed


def test_frontend_scoring_abort_latches_dispatcher(monkeypatch):
    """A frontend scoring pass that aborts after the 'score' broadcast must
    latch (followers entered collective programs it never will)."""
    from sesam_duke_microservice_tpu.engine.device_matcher import (
        DeviceProcessor,
    )

    idx, schema, rec = _tiny_index()
    idx._dispatch_key = ("deduplication", "t")
    stub = _StubDispatcher()
    monkeypatch.setattr(dispatch, "_DISPATCHER", stub)
    proc = DeviceProcessor(schema, idx)
    monkeypatch.setattr(
        proc, "_score_blocks",
        lambda records: (_ for _ in ()).throw(RuntimeError("listener died")),
    )
    with pytest.raises(RuntimeError, match="listener died"):
        proc.deduplicate([rec("a", "acme"), rec("b", "acme")])
    assert stub.ops == ["commit", "score"]
    assert stub.failed is not None and "scoring pass aborted" in stub.failed


def test_preshared_token_env(monkeypatch):
    """DUKE_DISPATCH_TOKEN is honored on both sides (advisor r4 low: the
    DUKE_DISPATCH_ADDR bypass needs a pre-shared secret to ever work)."""
    monkeypatch.setenv("DUKE_DISPATCH_TOKEN", "psk")
    assert dispatch._join_token() == "psk"
    monkeypatch.delenv("DUKE_DISPATCH_TOKEN")
    assert dispatch._join_token() is None
    # hello frames are fixed-length for any secret length
    assert len(dispatch._hello_frame("x")) == dispatch._HELLO_LEN
    assert len(dispatch._hello_frame("x" * 500)) == dispatch._HELLO_LEN
