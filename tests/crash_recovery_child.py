"""Kill-differential child driver (ISSUE 10, tests/test_crash_recovery.py).

One ingest process the harness can crash at an exact site and restart:

  * builds ONE persistent dedup workload over ``--data`` (host backend by
    default; ``--backend ann`` for the snapshot sites) — journal
    recovery, store replay and snapshot load all run inside
    ``build_workload`` exactly as a real service start;
  * ingests the deterministic duplicate-heavy corpus batch by batch,
    printing ``ACK <i>`` after each batch returns (the moment a real
    client would see HTTP 200) — the parent resumes a crashed run from
    the first unacked batch, the at-least-once retry contract every
    Sesam client already implements;
  * with ``DUKE_FAULTS=crash_at=<site>:<n>`` in the environment the
    process SIGKILLs itself mid-flight (utils.faults) — no cleanup, no
    atexit, an honest crash;
  * ``--dump`` prints ``DUMP <json>``: the normalized link-DB rows, the
    ``?since=`` feed (timestamps dropped — wall clock differs across
    runs by construction; everything else must be byte-identical), and
    the recovery counters the differential asserts on.

Timestamps are the ONE normalized field: links carry wall-clock millis
assigned at event time, so a crashed+recovered run can never equal the
control on them.  Row content, pair set, statuses, kinds and confidences
must match exactly.
"""

import argparse
import json
import os
import sys

# the package is imported from the repo checkout (same bootstrap as
# tests/conftest.py — the child has no conftest)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_batches(n_batches: int, per_batch: int, identities: int = 4):
    """Duplicate-heavy deterministic corpus: record (b, i) carries
    identity ``(b*per_batch + i) % identities``, so every batch re-mints
    identities earlier batches already ingested — each batch both links
    internally and against prior batches' records."""
    out = []
    for b in range(n_batches):
        rows = []
        for i in range(per_batch):
            ident = (b * per_batch + i) % identities
            name = f"person number {ident}"
            rows.append({
                "_id": f"r{b}_{i}",
                "name": name,
                "email": f"{name.replace(' ', '.')}@x.no",
            })
        out.append(rows)
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", required=True)
    ap.add_argument("--backend", default="host")
    ap.add_argument("--batches", type=int, default=6)
    ap.add_argument("--per-batch", type=int, default=6)
    ap.add_argument("--start", type=int, default=0)
    ap.add_argument("--dump", action="store_true")
    ap.add_argument("--close", action="store_true")
    # keep the process alive after the last ack so a crash site on the
    # BACKGROUND flusher thread (e.g. the final batch's pre_flush) is
    # reached before process exit would reap the daemon thread
    ap.add_argument("--linger", type=float, default=0.0)
    args = ap.parse_args()

    from sesam_duke_microservice_tpu import telemetry
    from sesam_duke_microservice_tpu.core.config import parse_config
    from sesam_duke_microservice_tpu.engine.workload import build_workload

    xml = f"""
<DukeMicroService dataFolder="{args.data}">
  <Deduplication name="people">
    <duke>
      <schema>
        <threshold>0.8</threshold>
        <property><name>NAME</name><comparator>levenshtein</comparator><low>0.1</low><high>0.95</high></property>
        <property><name>EMAIL</name><comparator>exact</comparator><low>0.2</low><high>0.95</high></property>
      </schema>
      <data-source class="io.sesam.dukemicroservice.IncrementalDeduplicationDataSource">
        <param name="dataset-id" value="crm"/>
        <column name="name" property="NAME"/>
        <column name="email" property="EMAIL"/>
      </data-source>
    </duke>
  </Deduplication>
</DukeMicroService>
"""
    sc = parse_config(xml, env={"MIN_RELEVANCE": "0.05"})
    wl = build_workload(sc.deduplications["people"], sc,
                        backend=args.backend, persistent=True)

    batches = make_batches(args.batches, args.per_batch)
    for i in range(args.start, args.batches):
        with wl.lock:
            wl.process_batch("crm", batches[i])
        print(f"ACK {i}", flush=True)
    if args.linger:
        import time

        time.sleep(args.linger)

    if args.dump:
        links = sorted(
            (l.id1, l.id2, l.status.value, l.kind.value,
             round(l.confidence, 12))
            for l in wl.link_database.get_all_links()
        )
        with wl.lock:
            feed = wl.links_since(0)
        for row in feed:
            row.pop("_updated", None)
        feed.sort(key=lambda r: r["_id"])
        journal = getattr(wl.link_database, "journal", None)
        print("DUMP " + json.dumps({
            "links": links,
            "feed": feed,
            "store_rows": (wl.record_store.count()
                           if wl.record_store is not None else None),
            "journal_pending": (journal.pending_batches
                                if journal is not None else None),
            "torn": telemetry.JOURNAL_TORN_TAILS.single().value,
            "replayed": telemetry.RECOVERY_REPLAYED.single().value,
        }), flush=True)

    if args.close:
        with wl.lock:
            wl.close()
    print("DONE", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
