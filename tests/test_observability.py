"""Federation-wide observability plane (ISSUE 16).

Covers the three tentpole layers end to end:

  * cross-group trace propagation — the router's partition/fan-out/merge
    spans, per-group outcome spans (ok / degraded / stale-epoch), and
    the group-side ``group.ingest`` subtree captured across the
    ``LocalGroup`` seam and re-anchored into ONE causal tree, verified
    both in-process and through the plane's ``/debug/traces``;
  * fleet metrics rollup — the plane's ``/metrics`` proven EQUAL to the
    per-group sums for counters and histogram buckets and label-disjoint
    for relabeled gauges (the differential the acceptance pins);
  * runtime SLO signals — burn-rate window math on the violation ring,
    feed-lag metering, and the always-on families in the exposition.

Satellites riding along: /debug routes on the replica plane, the
migration phase-timeline ring on ``/debug/migrations`` (kill-site
completeness lives in tests/test_federation_chaos.py), and the recovery
replay progress gauges.
"""

import json
import re
import urllib.request

import pytest

from sesam_duke_microservice_tpu import telemetry
from sesam_duke_microservice_tpu.federation.ranges import PartitionMap
from sesam_duke_microservice_tpu.federation.router import (
    FederationRouter,
    PartialIngestFailure,
)
from sesam_duke_microservice_tpu.telemetry import slo, tracing
from sesam_duke_microservice_tpu.telemetry.registry import (
    DEFAULT_LATENCY_BUCKETS,
    FamilySnapshot,
)
from sesam_duke_microservice_tpu.telemetry.rollup import (
    GroupRollup,
    merge_groups,
)
from sesam_duke_microservice_tpu.utils import faults

from test_federation import FED_XML, duplicate_batch, make_fed  # noqa: F401


@pytest.fixture(autouse=True)
def _clean_slate():
    faults.configure("")
    slo._reset_for_tests()
    yield
    faults.configure(None)
    slo._reset_for_tests()
    # the plane tests retain traces (including a fixed upstream trace
    # id) in the process flight recorder; later suites assert on its
    # contents, so leave it as empty as we found it
    tracing.RECORDER.clear()


# -- layer 3: SLO burn-rate math ----------------------------------------------


class TestSloTracker:
    def test_violation_counting_and_histogram(self):
        t = slo.SloTracker(objective_s=0.1, target=0.99)
        now = 1_000_000.0
        t.record_batch([0.05, 0.2, 0.3, 0.01], now)
        (counts, total, count), v_total, windows = t.scrape(now)
        assert count == 4 and v_total == 2
        assert total == pytest.approx(0.56)
        assert sum(counts) == 4
        assert windows["5m"] == (4, 2, pytest.approx((2 / 4) / 0.01))
        assert windows["1h"] == (4, 2, pytest.approx((2 / 4) / 0.01))

    def test_windows_age_out_independently(self):
        """A violation 400 s old burns the 1h window but not the 5m one
        — the multi-window discipline that keeps slow burns visible."""
        t = slo.SloTracker(objective_s=0.1, target=0.99)
        now = 1_000_000.0
        t.record_batch([0.5], now - 400)   # outside 5m, inside 1h
        t.record_batch([0.01], now)        # fresh, within objective
        _, v_total, windows = t.scrape(now)
        assert v_total == 2 - 1            # one violation ever
        assert windows["5m"][1] == 0
        assert windows["1h"][1] == 1
        assert windows["5m"][2] == 0.0
        assert windows["1h"][2] > 0.0

    def test_burn_rate_one_spends_exactly_the_budget(self):
        """100 requests, 1 violation, target 0.99 → burn rate 1.0."""
        t = slo.SloTracker(objective_s=0.1, target=0.99)
        now = 1_000_000.0
        t.record_batch([0.01] * 99 + [0.5], now)
        _, _, windows = t.scrape(now)
        assert windows["5m"][2] == pytest.approx(1.0)

    def test_tracker_registry_and_objective_env(self, monkeypatch):
        monkeypatch.setenv("DUKE_SLO_FEED_MS", "250")
        slo._reset_for_tests()
        t = slo.tracker("feed", "deduplication", "people")
        assert t.objective_s == pytest.approx(0.25)
        assert slo.tracker("feed", "deduplication", "people") is t

    def test_families_always_render_on_global(self):
        slo.tracker("ingest", "deduplication", "people").record(0.001)
        slo.feed_meter("deduplication", "people").note_write(100.0)
        text = telemetry.render(telemetry.GLOBAL)
        for fam in ("duke_slo_ingest_latency_seconds",
                    "duke_slo_feed_latency_seconds",
                    "duke_slo_violations_total", "duke_slo_burn_rate",
                    "duke_slo_objective_seconds", "duke_feed_lag_seconds",
                    "duke_recovery_replay_remaining_batches",
                    "duke_recovery_replay_applied_total"):
            assert fam in text, fam
        assert 'window="5m"' in text and 'window="1h"' in text


class TestFeedLagMeter:
    def test_lag_ages_from_oldest_pending_write(self):
        m = slo.FeedLagMeter()
        assert m.lag_seconds() == 0.0
        m.note_write(100.0)
        m.note_write(150.0)  # oldest pending stays at 100
        assert m.lag_seconds(160.0) == pytest.approx(60.0)

    def test_drain_resets_to_caught_up(self):
        m = slo.FeedLagMeter()
        m.note_write(100.0)
        m.note_drain()
        assert m.lag_seconds(1000.0) == 0.0
        m.note_write(200.0)
        assert m.lag_seconds(205.0) == pytest.approx(5.0)


# -- layer 2: rollup merge semantics ------------------------------------------


class TestMergeGroups:
    def test_counters_sum_gauges_relabel(self):
        labels = (("kind", "deduplication"), ("workload", "people"))
        per_group = [
            ("0", [FamilySnapshot("duke_x_total", "counter", "h",
                                  [("", labels, 3.0)]),
                   FamilySnapshot("duke_g", "gauge", "h",
                                  [("", labels, 7.0)])]),
            ("1", [FamilySnapshot("duke_x_total", "counter", "h",
                                  [("", labels, 5.0)]),
                   FamilySnapshot("duke_g", "gauge", "h",
                                  [("", labels, 9.0)])]),
        ]
        merged = {f.name: f for f in merge_groups(per_group)}
        assert merged["duke_x_total"].samples == [("", labels, 8.0)]
        gauge = sorted(merged["duke_g"].samples)
        assert gauge == [
            ("", labels + (("group", "0"),), 7.0),
            ("", labels + (("group", "1"),), 9.0),
        ]

    def test_histogram_buckets_sum_bucketwise(self):
        def hist(n):
            return FamilySnapshot("duke_h_seconds", "histogram", "h", [
                ("_bucket", (("le", "0.1"),), float(n)),
                ("_bucket", (("le", "+Inf"),), float(n + 1)),
                ("_sum", (), 0.5 * n),
                ("_count", (), float(n + 1)),
            ])
        merged = merge_groups([("0", [hist(2)]), ("1", [hist(4)])])
        samples = dict(((s[0], s[1]), s[2]) for s in merged[0].samples)
        assert samples[("_bucket", (("le", "0.1"),))] == 6.0
        assert samples[("_bucket", (("le", "+Inf"),))] == 8.0
        assert samples[("_sum", ())] == pytest.approx(3.0)
        assert samples[("_count", ())] == 8.0


# -- layer 1: trace propagation across the LocalGroup seam --------------------


def _spans_by_name(record):
    out = {}
    for s in record.spans:
        out.setdefault(s.name, []).append(s)
    return out


class TestFederatedTracePropagation:
    def test_one_causal_tree_for_a_federated_ingest(self, tmp_path):
        fed = make_fed(tmp_path, n_groups=2)
        rec = tracing.FlightRecorder(8, 64)
        try:
            with tracing.start_trace("fed ingest", sampled=True,
                                     recorder=rec) as root:
                tid = root.trace_id
                result = fed.router.submit("deduplication", "people",
                                           "crm", duplicate_batch(24))
            assert result["success"] is True
            record = rec.get(tid)
            assert record is not None
            by_name = _spans_by_name(record)
            for name in ("fed.partition", "fed.fanout", "fed.merge"):
                assert name in by_name, name
            fanout = by_name["fed.fanout"][0]
            group_spans = by_name["fed.group"]
            assert {s.attributes["group"] for s in group_spans} == {0, 1}
            assert all(s.attributes["outcome"] == "ok"
                       for s in group_spans)
            assert all(len(s.attributes["ranges"]) >= 1
                       for s in group_spans)
            # the group-side subtree crossed the seam: re-anchored
            # remote spans, same trace id, parented under the fan-out
            remote = by_name["group.ingest"]
            assert {s.attributes["group"] for s in remote} == {0, 1}
            for s in remote:
                assert s.trace_id == tid
                assert s.attributes["remote"] is True
                assert s.parent_id == fanout.span_id
        finally:
            fed.close()

    def test_degraded_group_span_outcome(self, tmp_path):
        fed = make_fed(tmp_path, n_groups=2)
        rec = tracing.FlightRecorder(8, 64)
        try:
            fed.router.submit("deduplication", "people", "crm",
                              duplicate_batch(12))
            faults.configure("fed_down=1")
            with tracing.start_trace("fed ingest degraded", sampled=True,
                                     recorder=rec) as root:
                tid = root.trace_id
                with pytest.raises(PartialIngestFailure):
                    fed.router.submit("deduplication", "people", "crm",
                                      duplicate_batch(24, start=100))
            by_name = _spans_by_name(rec.get(tid))
            outcomes = {s.attributes["group"]: s.attributes["outcome"]
                        for s in by_name["fed.group"]}
            assert outcomes[1] == "degraded"
            assert outcomes[0] == "ok"
            # only the live group's subtree came back across the seam
            assert {s.attributes["group"]
                    for s in by_name.get("group.ingest", [])} == {0}
        finally:
            faults.configure("")
            fed.close()

    def test_stale_epoch_span_outcome(self, tmp_path):
        from sesam_duke_microservice_tpu.federation.ranges import (
            StaleRouterEpoch,
        )

        fed = make_fed(tmp_path, n_groups=2)
        rec = tracing.FlightRecorder(8, 64)
        try:
            stale_map = PartitionMap.load(fed.map.path)
            stale_router = FederationRouter(lambda: stale_map, fed.groups)
            for g in fed.groups:
                g.fence(stale_map.epoch + 5)  # topology moved on
            with tracing.start_trace("fed ingest stale", sampled=True,
                                     recorder=rec) as root:
                tid = root.trace_id
                with pytest.raises(StaleRouterEpoch):
                    stale_router.submit("deduplication", "people", "crm",
                                        duplicate_batch(8))
            by_name = _spans_by_name(rec.get(tid))
            assert any(s.attributes["outcome"] == "stale-epoch"
                       for s in by_name["fed.group"])
        finally:
            fed.close()

    def test_untraced_hot_path_is_span_free(self, tmp_path):
        """No active trace → no spans recorded anywhere (the sampling
        overhead stance: the unsampled path never builds span objects)."""
        fed = make_fed(tmp_path, n_groups=2)
        rec = tracing.FlightRecorder(8, 64)
        try:
            fed.router.submit("deduplication", "people", "crm",
                              duplicate_batch(12))
            assert rec.summaries() == []
            assert tracing.propagation_context() is None
        finally:
            fed.close()


# -- the plane: /metrics differential + debug surface -------------------------


_SAMPLE_RE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
                        r"(?:\{(.*)\})?\s+(\S+)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"')


def parse_exposition(text):
    """{(name_with_suffix, sorted-label-tuple): value} for every sample
    line in a Prometheus exposition body."""
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        name, labels, value = m.groups()
        lbls = tuple(sorted(_LABEL_RE.findall(labels or "")))
        out[(name, lbls)] = float(value)
    return out


class TestFederationPlaneObservability:
    @pytest.fixture()
    def plane(self, tmp_path):
        from sesam_duke_microservice_tpu.service.federation_plane import (
            serve_federation,
        )

        fed = make_fed(tmp_path, n_groups=2)
        server = serve_federation(fed)
        base = f"http://127.0.0.1:{server.server_address[1]}"
        yield fed, base
        server.shutdown()
        fed.close()

    @staticmethod
    def _get(url):
        return urllib.request.urlopen(url, timeout=60)

    @staticmethod
    def _post(url, obj):
        req = urllib.request.Request(
            url, data=json.dumps(obj).encode("utf-8"), method="POST",
            headers={"Content-Type": "application/json"})
        return urllib.request.urlopen(req, timeout=60)

    def test_fleet_rollup_equals_per_group_sums(self, plane):
        """The acceptance differential: for counters and histogram
        buckets the fleet exposition equals the key-wise SUM of the
        groups' own collector outputs; gauges appear once per group
        under disjoint ``group=`` label sets, never summed."""
        from sesam_duke_microservice_tpu.service.metrics import (
            make_group_collector,
        )

        fed, base = plane
        with self._post(base + "/deduplication/people/crm",
                        duplicate_batch(24)) as r:
            assert r.status == 200
        # settle the write-behind link flushers so the two scrapes (the
        # direct collector call and the HTTP one) see the same state
        for g in fed.groups:
            for wl in g.workloads.values():
                wl.link_database.drain()

        expected_sums = {}
        expected_gauges = {}
        for g in fed.groups:
            for fam in make_group_collector(g)():
                for suffix, labels, value in fam.samples:
                    if fam.mtype == "gauge":
                        key = (fam.name + suffix, tuple(sorted(
                            labels + (("group", str(g.idx)),))))
                        expected_gauges[key] = float(value)
                    else:
                        key = (fam.name + suffix, tuple(sorted(labels)))
                        expected_sums[key] = (
                            expected_sums.get(key, 0.0) + float(value))

        with self._get(base + "/metrics") as r:
            scraped = parse_exposition(r.read().decode("utf-8"))

        assert expected_sums, "group collectors produced no counters"
        for key, value in expected_sums.items():
            assert key in scraped, key
            assert scraped[key] == pytest.approx(value), key
        # the summed ingest counter really covers the whole batch
        total = sum(v for (n, ls), v in expected_sums.items()
                    if n == "duke_engine_records_processed_total")
        assert total == 24
        for key, value in expected_gauges.items():
            assert key in scraped, key
            assert scraped[key] == pytest.approx(value), key
        # relabeled gauges: every per-workload gauge sample carries a
        # group label, and the per-group label sets are disjoint
        depth_keys = [ls for (n, ls) in scraped
                      if n == "duke_ingest_queue_depth"]
        assert depth_keys
        assert all(any(k == "group" for k, _v in ls) for ls in depth_keys)
        assert len(depth_keys) == len(set(depth_keys)) == len(fed.groups)
        # the per-range scatter families joined the fed collector
        assert any(n == "duke_fed_range_requests_total"
                   and ("outcome", "ok") in ls for (n, ls) in scraped)
        assert any(n == "duke_fed_range_latency_seconds_count"
                   for (n, ls) in scraped)

    def test_retained_federated_trace_on_debug_traces(self, plane,
                                                      monkeypatch):
        """Acceptance: one retained trace tree spans plane root → router
        fan-out → group ingest for a real federated POST, read back off
        the plane's own /debug/traces."""
        monkeypatch.setenv("TRACE_SAMPLE_RATE", "1.0")
        fed, base = plane
        with self._post(base + "/deduplication/people/crm",
                        duplicate_batch(24)) as r:
            assert r.status == 200
            tid = r.headers["X-Trace-Id"]
            assert r.headers["X-Request-Id"]
        assert re.fullmatch(r"[0-9a-f]{32}", tid)
        with self._get(base + "/debug/traces") as r:
            summaries = json.loads(r.read())["traces"]
        assert any(s["trace_id"] == tid for s in summaries)
        with self._get(base + f"/debug/traces/{tid}") as r:
            tree = json.loads(r.read())
        assert tree["name"] == "POST /deduplication:name/:datasetId"
        names = [s["name"] for s in tree["spans"]]
        for required in ("fed.partition", "fed.fanout", "fed.group",
                         "fed.merge", "group.ingest"):
            assert required in names, required
        fanout = next(s for s in tree["spans"]
                      if s["name"] == "fed.fanout")
        remote = [s for s in tree["spans"] if s["name"] == "group.ingest"]
        assert {s["attributes"]["group"] for s in remote} == {0, 1}
        for s in remote:
            assert s["attributes"]["remote"] is True
            assert s["parent_id"] == fanout["span_id"]
        with self._get(base + "/debug/requests") as r:
            digests = json.loads(r.read())["requests"]
        assert any(d["trace_id"] == tid and d["retained"]
                   for d in digests)

    def test_traceparent_header_continues_the_callers_trace(self, plane):
        fed, base = plane
        upstream = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
        req = urllib.request.Request(
            base + "/deduplication/people/crm",
            data=json.dumps(duplicate_batch(8)).encode("utf-8"),
            method="POST",
            headers={"Content-Type": "application/json",
                     "traceparent": upstream})
        with urllib.request.urlopen(req, timeout=60) as r:
            assert r.status == 200
            assert r.headers["X-Trace-Id"] == "ab" * 16
        # sampled flag inherited from the header → tree retained, and
        # the remote group spans carry the SAME inherited trace id
        with self._get(base + "/debug/traces/" + "ab" * 16) as r:
            tree = json.loads(r.read())
        assert any(s["name"] == "group.ingest" for s in tree["spans"])

    def test_migration_timeline_ring_and_trace(self, plane):
        fed, base = plane
        with self._post(base + "/deduplication/people/crm",
                        duplicate_batch(24)) as r:
            assert r.status == 200
        mp = json.loads(self._get(base + "/federation/map").read())
        moved = next(x for x in mp["ranges"] if x["group"] == 0)
        with self._post(base + "/federation/migrate",
                        {"range": moved["id"], "target": 1}) as r:
            assert r.status == 200
        with self._get(base + "/debug/migrations") as r:
            timelines = json.loads(r.read())["migrations"]
        assert len(timelines) == 1
        tl = timelines[0]
        assert tl["range"] == moved["id"]
        assert tl["outcome"] == "completed" and tl["resumed"] is False
        assert [p["phase"] for p in tl["phases"]] == [
            "freeze", "snapshot", "replay", "cutover", "drain"]
        snap = tl["phases"][1]
        assert snap["records"] > 0 and snap["record_bytes"] > 0
        # the migrate route forces retention (sampled=True): the phase
        # spans are readable under the timeline's own trace id
        assert tl["trace_id"]
        with self._get(base + f"/debug/traces/{tl['trace_id']}") as r:
            names = [s["name"] for s in json.loads(r.read())["spans"]]
        for phase in ("freeze", "snapshot", "replay", "cutover", "drain"):
            assert f"migrate.{phase}" in names, phase

    def test_feed_slo_and_lag_on_plane_metrics(self, plane):
        fed, base = plane
        with self._post(base + "/deduplication/people/crm",
                        duplicate_batch(24)) as r:
            assert r.status == 200
        with self._get(base + "/deduplication/people?since=") as r:
            assert r.headers["X-Fed-Drained"] == "true"
        with self._get(base + "/metrics") as r:
            scraped = parse_exposition(r.read().decode("utf-8"))
        feed_count = scraped.get((
            "duke_slo_feed_latency_seconds_count",
            (("kind", "deduplication"), ("workload", "people"))))
        assert feed_count is not None and feed_count >= 1
        # group ingest bypasses the service scheduler, so the group
        # boundary records the ingest SLO signal — one observation per
        # routed sub-batch (2 groups hit here)
        ingest_count = scraped.get((
            "duke_slo_ingest_latency_seconds_count",
            (("kind", "deduplication"), ("workload", "people"))))
        assert ingest_count is not None and ingest_count >= 2
        # drained feed → caught up → zero lag
        lag = scraped.get((
            "duke_feed_lag_seconds",
            (("kind", "deduplication"), ("workload", "people"))))
        assert lag == 0.0


# -- replica plane debug routes -----------------------------------------------


class _StubSession:
    replicas = {}
    link_replicas = {}
    epoch = 1
    follower_idx = 0
    stale_rejected = 0


class TestReplicaPlaneDebugRoutes:
    @pytest.fixture()
    def replica_base(self):
        from sesam_duke_microservice_tpu.service.replica_plane import (
            serve_replica_plane,
        )

        server = serve_replica_plane(_StubSession(), port=0,
                                     host="127.0.0.1")
        yield f"http://127.0.0.1:{server.server_address[1]}"
        server.shutdown()

    def test_debug_routes_mounted(self, replica_base):
        with urllib.request.urlopen(replica_base + "/debug/traces",
                                    timeout=60) as r:
            assert r.status == 200
            assert "traces" in json.loads(r.read())
        with urllib.request.urlopen(replica_base + "/debug/requests",
                                    timeout=60) as r:
            assert r.status == 200
            digests = json.loads(r.read())["requests"]
        # the replica root span digests its own requests
        assert any(d["name"] == "GET /debug/traces" for d in digests)

    def test_404_advertises_debug_routes(self, replica_base):
        import urllib.error

        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(replica_base + "/nope", timeout=60)
        assert exc.value.code == 404
        assert b"/debug/traces" in exc.value.read()


# -- rollup shim renders through telemetry.render -----------------------------


def test_group_rollup_is_render_compatible():
    reg0, reg1 = telemetry.MetricRegistry(), telemetry.MetricRegistry()
    reg0.counter("duke_t_total", "h").inc(2)
    reg1.counter("duke_t_total", "h").inc(3)
    reg0.gauge("duke_t_gauge", "h").set(1)
    reg1.gauge("duke_t_gauge", "h").set(4)
    text = telemetry.render(GroupRollup([("0", reg0), ("1", reg1)]))
    scraped = parse_exposition(text)
    assert scraped[("duke_t_total", ())] == 5.0
    assert scraped[("duke_t_gauge", (("group", "0"),))] == 1.0
    assert scraped[("duke_t_gauge", (("group", "1"),))] == 4.0


def test_slo_histogram_ladder_matches_shared_buckets():
    """The SLO histograms ride the shared ladder, so fleet merging of
    their buckets is lossless by construction."""
    t = slo.SloTracker(0.1, 0.99)
    t.record_batch([b * 0.99 for b in DEFAULT_LATENCY_BUCKETS], 0.0)
    (counts, _total, count), _, _ = t.scrape(0.0)
    assert count == len(DEFAULT_LATENCY_BUCKETS)
    assert len(counts) == len(DEFAULT_LATENCY_BUCKETS) + 1
    assert counts[-1] == 0  # nothing past the +Inf boundary's last bound
