"""Match-decision explainability tests (ISSUE 5).

The contracts under test:

  * per-property host contributions sum (from the 0.5 prior) to EXACTLY
    the pair logit ``Processor.compare`` folds (1e-6 acceptance, held to
    1e-9), and ``sigmoid(sum)`` reproduces the emitted probability;
  * the device explain program's per-property f32 logits sum to the
    host-exact logit over the device properties within the certified
    f32 margin — and match the LIVE scorer's device logit for indexed
    pairs, across the brute-force and ANN backends;
  * explain-mode replay is side-effect free: interleaving ``/explain``
    calls with ingest leaves the listener event tape and the link rows
    bit-identical to an untouched run;
  * the decision ring's tail latch retains every disagreement and
    near-threshold band skip at sample rate 0, and the shared
    ``LatchedRing`` honors capacity/byte budgets while preferring
    unremarkable evictions;
  * the audit log writes one JSONL row per confirmed link with the
    explanation digest ``/explain`` reproduces;
  * the HTTP surface: ``POST /explain``, ``GET /debug/decisions[/<id>]``.
"""

import json
import math
import random
import threading
import urllib.error
import urllib.request

import pytest

from sesam_duke_microservice_tpu.core import comparators as C
from sesam_duke_microservice_tpu.core.config import (
    DukeSchema,
    MatchTunables,
    parse_config,
)
from sesam_duke_microservice_tpu.core.records import (
    ID_PROPERTY_NAME,
    Property,
    Record,
)
from sesam_duke_microservice_tpu.engine import explain as X
from sesam_duke_microservice_tpu.engine.ann_matcher import AnnIndex
from sesam_duke_microservice_tpu.engine.device_matcher import (
    DeviceIndex,
    DeviceProcessor,
)
from sesam_duke_microservice_tpu.engine.listeners import MatchListener
from sesam_duke_microservice_tpu.engine.workload import build_workload
from sesam_duke_microservice_tpu.ops import scoring as S
from sesam_duke_microservice_tpu.telemetry.decisions import (
    DecisionRecorder,
    PairDecision,
    audit_log,
)
from sesam_duke_microservice_tpu.telemetry.rings import LatchedRing


def dedup_schema(threshold=0.8, maybe=0.6):
    numeric = C.Numeric()
    numeric.min_ratio = 0.5
    return DukeSchema(
        threshold=threshold,
        maybe_threshold=maybe,
        properties=[
            Property(ID_PROPERTY_NAME, id_property=True),
            Property("name", C.Levenshtein(), 0.3, 0.9),
            Property("city", C.Exact(), 0.4, 0.8),
            Property("amount", numeric, 0.4, 0.7),
        ],
        data_sources=[],
    )


def make_record(rid, **props):
    r = Record()
    r.add_value(ID_PROPERTY_NAME, rid)
    for k, v in props.items():
        r.add_value(k, v)
    return r


NAMES = [
    "acme corp", "acme corporation", "globex", "globex inc", "initech",
    "initech llc", "umbrella", "umbrela", "stark industries", "stark ind",
]
CITIES = ["oslo", "bergen", "trondheim"]


def random_records(n, seed, prefix="r"):
    rng = random.Random(seed)
    records = []
    for i in range(n):
        base = rng.choice(NAMES)
        if rng.random() < 0.4:
            pos = rng.randrange(len(base))
            base = base[:pos] + rng.choice("abcdefgh") + base[pos + 1:]
        records.append(make_record(
            f"{prefix}{i}",
            name=base,
            city=rng.choice(CITIES),
            amount=str(rng.choice([100, 200, 200, 300, 1000])),
        ))
    return records


class OrderedLog(MatchListener):
    def __init__(self):
        self.events = []

    def matches(self, r1, r2, confidence):
        self.events.append(
            ("match", r1.record_id, r2.record_id, round(confidence, 9)))

    def matches_perhaps(self, r1, r2, confidence):
        self.events.append(
            ("maybe", r1.record_id, r2.record_id, round(confidence, 9)))

    def no_match_for(self, record):
        self.events.append(("none", record.record_id))


# -- host breakdown -----------------------------------------------------------


class TestHostBreakdown:
    def test_contributions_sum_to_compare(self):
        from sesam_duke_microservice_tpu.engine.processor import Processor
        from sesam_duke_microservice_tpu.index.inverted import InvertedIndex

        schema = dedup_schema()
        proc = Processor(schema, InvertedIndex(schema))
        records = random_records(24, seed=7)
        for i in range(0, len(records) - 1, 2):
            r1, r2 = records[i], records[i + 1]
            out = X.host_breakdown(schema, r1, r2)
            contrib_sum = sum(p["logit"] for p in out["properties"])
            assert contrib_sum == pytest.approx(out["pair_logit"], abs=1e-9)
            # acceptance: 1e-6 on host — held far tighter
            assert abs(out["probability"] - proc.compare(r1, r2)) < 1e-12

    def test_missing_property_contributes_nothing(self):
        schema = dedup_schema()
        r1 = make_record("a", name="acme corp")  # no city/amount
        r2 = make_record("b", name="acme corp", city="oslo", amount="100")
        out = X.host_breakdown(schema, r1, r2)
        by_name = {p["name"]: p for p in out["properties"]}
        assert by_name["city"]["status"] == "missing"
        assert by_name["city"]["logit"] == 0.0
        assert by_name["name"]["status"] == "compared"
        assert by_name["name"]["best_similarity"] == 1.0


# -- device breakdown ---------------------------------------------------------


def _ingested_index(index_cls, schema, records):
    index = index_cls(schema, tunables=MatchTunables())
    for r in records:
        index.index(r)
    index.commit()
    return index


@pytest.mark.parametrize("index_cls", [DeviceIndex, AnnIndex])
class TestDeviceBreakdown:
    def test_per_property_sum_within_certified_margin(self, index_cls):
        schema = dedup_schema()
        records = random_records(12, seed=3)
        index = _ingested_index(index_cls, schema, records)
        margin = S.certified_f32_margin(index.plan)
        for r1, r2 in zip(records[::2], records[1::2]):
            out = X.device_breakdown(index, r1, r2)
            per_sum = sum(p["logit"] for p in out["per_property"])
            assert per_sum == pytest.approx(out["logit"], abs=1e-6)
            # f32 device logit vs host-exact f64 logit over the device
            # properties: the certified-margin acceptance bound
            host = X.host_breakdown(schema, r1, r2)
            host_by_name = {p["name"]: p["logit"]
                            for p in host["properties"]}
            device_names = {p["name"] for p in out["per_property"]}
            host_device_logit = sum(
                v for k, v in host_by_name.items() if k in device_names
            )
            assert abs(out["logit"] - host_device_logit) <= margin

    def test_matches_live_scorer_logit(self, index_cls):
        schema = dedup_schema()
        records = random_records(10, seed=11)
        index = _ingested_index(index_cls, schema, records)
        margin = S.certified_f32_margin(index.plan)
        query = records[0]
        result = index.scorer_cache.score_block(
            [query], group_filtering=False
        )
        survivors = dict(result.survivors(0))
        checked = 0
        for row, live_logit in survivors.items():
            rid = index.corpus.row_ids[row]
            candidate = index.records[rid]
            out = X.device_breakdown(index, query, candidate)
            # explain re-extracts under the same corpus plan and runs
            # the same kernels: within two margins of the live scorer
            assert abs(out["logit"] - live_logit) <= 2 * margin + 1e-5
            checked += 1
        assert checked > 0


# -- golden explain parity ----------------------------------------------------


class TestExplainParity:
    CONFIG = """
<DukeMicroService>
  <Deduplication name="people" link-database-type="in-memory">
    <duke>
      <schema>
        <threshold>0.8</threshold>
        <maybe-threshold>0.6</maybe-threshold>
        <property><name>NAME</name>
          <comparator>levenshtein</comparator><low>0.3</low><high>0.9</high>
        </property>
        <property><name>CITY</name>
          <comparator>exact</comparator><low>0.4</low><high>0.8</high>
        </property>
      </schema>
      <data-source class="io.sesam.dukemicroservice.IncrementalDeduplicationDataSource">
        <param name="dataset-id" value="crm"/>
        <column name="name" property="NAME"/>
        <column name="city" property="CITY"/>
      </data-source>
    </duke>
  </Deduplication>
</DukeMicroService>
"""

    def _entities(self):
        rng = random.Random(5)
        out = []
        for i in range(40):
            base = rng.choice(NAMES)
            out.append({
                "_id": str(i), "name": base, "city": rng.choice(CITIES),
            })
        return out

    @pytest.mark.parametrize("backend", ["host", "device"])
    def test_replay_leaves_pipeline_bit_identical(self, backend):
        entities = self._entities()
        batches = [entities[:20], entities[20:]]

        def run(with_explain):
            sc = parse_config(self.CONFIG)
            wl = build_workload(
                sc.deduplications["people"], sc, backend=backend,
                persistent=False,
            )
            log = OrderedLog()
            wl.processor.add_match_listener(log)
            try:
                with wl.lock:
                    wl.process_batch("crm", batches[0])
                if with_explain:
                    # replay BETWEEN batches: by ids, by raw records,
                    # and a mixed pair — none of it may perturb batch 2
                    X.explain_request(wl, {
                        "id1": "crm__0", "id2": "crm__1"})
                    X.explain_request(wl, {
                        "record1": {"dataset": "crm",
                                    "entity": entities[2]},
                        "id2": "crm__3"})
                with wl.lock:
                    wl.process_batch("crm", batches[1])
                if with_explain:
                    X.explain_request(wl, {"id1": "crm__4",
                                           "id2": "crm__5"})
                links = sorted(
                    (l.id1, l.id2, l.kind.value, l.status.value,
                     round(l.confidence, 12))
                    for l in wl.link_database.get_all_links()
                )
                return log.events, links
            finally:
                wl.close()

        base_events, base_links = run(with_explain=False)
        explained_events, explained_links = run(with_explain=True)
        assert explained_events == base_events
        assert explained_links == base_links
        assert len(base_links) > 0

    def test_explain_response_consistency(self):
        sc = parse_config(self.CONFIG)
        wl = build_workload(
            sc.deduplications["people"], sc, backend="device",
            persistent=False,
        )
        try:
            with wl.lock:
                wl.process_batch("crm", self._entities()[:10])
            out = X.explain_request(wl, {"id1": "crm__0", "id2": "crm__1"})
            assert out["workload"] == "people"
            contrib = sum(p["logit"] for p in out["properties"])
            assert contrib == pytest.approx(out["pair_logit"], abs=1e-9)
            prob = 1.0 / (1.0 + math.exp(-out["pair_logit"]))
            assert prob == pytest.approx(out["probability"], abs=1e-12)
            assert out["classification"] in ("match", "maybe", "reject")
            device = out["device"]
            per_sum = sum(p["logit"] for p in device["per_property"])
            assert per_sum == pytest.approx(device["logit"], abs=1e-6)
            assert device["band_verdict"] in (
                "filtered", "pruned", "rescored")
            assert len(out["explanation_digest"]) == 16
            with pytest.raises(X.ExplainError):
                X.explain_request(wl, {"id1": "nope", "id2": "crm__1"})
        finally:
            wl.close()


# -- decision recorder / ring -------------------------------------------------


class TestDecisionRecorder:
    def _recorder(self, **kw):
        kw.setdefault("sample_rate", 0.0)
        kw.setdefault("enabled", True)
        return DecisionRecorder(0.8, 0.6, **kw)

    def test_disagreement_latched_at_sample_zero(self):
        rec = self._recorder()
        q = make_record("q", name="acme")
        # f32 verdict says match (logit 3 -> p=0.95) but f64 rescore says
        # reject: a disagreement, latched into the ring
        rec.observe(q, [PairDecision("c1", 3.0, False, 0.5)])
        # agreeing decision: not retained at sample 0
        rec.observe(q, [PairDecision("c2", 3.0, False, 0.97)])
        assert rec.disagreements == 1
        records = rec.records()
        assert len(records) == 1
        assert records[0]["latched"] == "disagreement"
        assert records[0]["candidate"] == "c1"
        assert rec.outcomes["reject"] == 1
        assert rec.outcomes["match"] == 1

    def test_near_band_skip_latched(self):
        rec = self._recorder()
        q = make_record("q", name="acme")
        prune, margin = 1.0, 0.01
        # slack 0.005 <= margin: latched; slack 0.5: plain pruned
        rec.observe(q, [
            PairDecision("near", prune - 0.005, True, None),
            PairDecision("far", prune - 0.5, True, None),
        ], prune=prune, margin=margin)
        assert rec.outcomes["pruned"] == 2
        records = rec.records()
        assert [r["candidate"] for r in records] == ["near"]
        assert records[0]["latched"] == "near-band-skip"
        assert rec.margin_slack_hist.count == 2

    def test_sampling_records_breakdown(self):
        schema = dedup_schema()
        cand = make_record("c", name="acme corp", city="oslo")
        rec = self._recorder(
            sample_rate=1.0,
            breakdown=lambda q, c: X.host_breakdown(schema, q, c),
            resolver={"c": cand}.get,
        )
        q = make_record("q", name="acme corp", city="oslo")
        rec.observe(q, [PairDecision("c", 4.0, False, 0.97)])
        (record,) = rec.records()
        assert record["sampled"] is True
        assert {p["name"] for p in record["properties"]} == {
            "name", "city", "amount"}
        assert rec.similarity_hists["name"].count == 1

    def test_disabled_recorder_is_inert(self):
        rec = DecisionRecorder(0.8, 0.6, enabled=False)
        rec.observe(make_record("q"), [PairDecision("c", 3.0, False, 0.5)])
        assert rec.outcomes["reject"] == 0
        assert len(rec.ring) == 0


class TestLatchedRing:
    def test_capacity_eviction_prefers_unremarkable(self):
        ring = LatchedRing(3)
        ring.put("a", "A", remarkable=True)
        ring.put("b", "B")
        ring.put("c", "C")
        ring.put("d", "D")  # evicts b (oldest unremarkable), not a
        assert ring.get("a") == "A"
        assert ring.get("b") is None
        assert [r for r in ring.records()] == ["D", "C", "A"]

    def test_all_remarkable_falls_back_to_fifo(self):
        ring = LatchedRing(2)
        ring.put("a", "A", remarkable=True)
        ring.put("b", "B", remarkable=True)
        ring.put("c", "C", remarkable=True)
        assert ring.get("a") is None
        assert len(ring) == 2

    def test_byte_budget_is_hard_bound(self):
        ring = LatchedRing(100, byte_budget=100)
        ring.put("a", "A", nbytes=60)
        ring.put("b", "B", remarkable=True, nbytes=60)  # evicts a
        assert ring.get("a") is None
        assert ring.bytes == 60
        # the newest record is never the victim: with only the latched
        # record left, FIFO applies and the ring stays live — a single
        # over-budget record survives alone
        ring.put("c", "C", nbytes=200)
        assert ring.get("c") == "C"
        assert ring.get("b") is None
        assert len(ring) == 1

    def test_latched_survive_sampled_flood_under_byte_budget(self):
        ring = LatchedRing(100, byte_budget=300)
        ring.put("latch", "L", remarkable=True, nbytes=100)
        for i in range(10):
            ring.put(f"s{i}", f"S{i}", nbytes=100)
        # byte pressure evicts the sampled records, never the latched
        # one — and the newest sampled record is always present
        assert ring.get("latch") == "L"
        assert ring.get("s9") == "S9"
        assert len(ring) == 3

    def test_replace_keeps_position_and_bytes(self):
        ring = LatchedRing(10, byte_budget=1000)
        ring.put("a", "A1", nbytes=100)
        ring.put("b", "B", nbytes=50)
        ring.put("a", "A2", nbytes=10)
        assert ring.bytes == 60
        assert ring.records() == ["B", "A2"]  # a kept its (older) slot


class TestEnginePathRecording:
    def test_device_processor_records_decisions(self, monkeypatch):
        monkeypatch.setenv("DUKE_DECISION_SAMPLE", "1.0")
        schema = dedup_schema()
        index = DeviceIndex(schema, tunables=MatchTunables())
        proc = DeviceProcessor(schema, index)
        proc.add_match_listener(OrderedLog())
        records = random_records(16, seed=21)
        proc.deduplicate(records)
        rec = proc.decisions
        total = sum(rec.outcomes.values())
        assert total > 0
        assert total == (proc.stats.pairs_rescored
                         + proc.stats.pairs_skipped
                         + proc.stats.pairs_device_certified)
        assert len(rec.ring) > 0
        one = rec.records()[0]
        assert one["query"].startswith("r")
        assert "device_logit" in one

    def test_host_processor_records_decisions(self, monkeypatch):
        from sesam_duke_microservice_tpu.engine.processor import Processor
        from sesam_duke_microservice_tpu.index.inverted import InvertedIndex

        monkeypatch.setenv("MIN_RELEVANCE", "0.0")
        monkeypatch.setenv("DUKE_DECISION_SAMPLE", "1.0")
        schema = dedup_schema()
        proc = Processor(
            schema, InvertedIndex(schema, MatchTunables(min_relevance=0.0)))
        proc.add_match_listener(OrderedLog())
        proc.deduplicate(random_records(12, seed=2))
        assert sum(proc.decisions.outcomes.values()) > 0
        assert proc.decisions.pair_logit_hist.count > 0


# -- retrieval provenance -----------------------------------------------------


class TestRetrievalProvenance:
    def test_inverted_terms(self):
        from sesam_duke_microservice_tpu.index.inverted import InvertedIndex

        schema = dedup_schema()
        index = InvertedIndex(schema, MatchTunables(min_relevance=0.0))
        a = make_record("a", name="acme corp", city="oslo")
        b = make_record("b", name="acme inc", city="oslo")
        index.index(a)
        index.index(b)
        index.commit()
        out = index.explain_retrieval(a, b)
        assert out["mode"] == "inverted-index"
        assert out["candidate_indexed"] is True
        tokens = {t["token"] for t in out["terms"]}
        assert "acme" in tokens and "oslo" in tokens
        assert out["retrieved"] is True
        assert out["score"] > 0
        # unindexed candidate
        out2 = index.explain_retrieval(a, make_record("z", name="zzz"))
        assert out2["candidate_indexed"] is False

    def test_ann_rank_and_cosine(self):
        schema = dedup_schema()
        records = random_records(10, seed=4)
        index = _ingested_index(AnnIndex, schema, records)
        out = index.explain_retrieval(records[0], records[1])
        assert out["mode"] == "ann"
        assert -1.001 <= out["cosine"] <= 1.001
        assert out["top_c"] == index.initial_top_c
        assert "retrieved" in out
        if out["retrieved"]:
            assert isinstance(out["rank"], int)


# -- audit log ----------------------------------------------------------------


class TestAuditLog:
    def test_confirmed_links_audited_with_digest(self, tmp_path,
                                                 monkeypatch):
        path = tmp_path / "audit.jsonl"
        monkeypatch.setenv("DUKE_AUDIT_LOG", str(path))
        # a 2-doc index scores below the default 0.9 relevance cut
        monkeypatch.setenv("MIN_RELEVANCE", "0.05")
        sc = parse_config(TestExplainParity.CONFIG)
        wl = build_workload(
            sc.deduplications["people"], sc, backend="host",
            persistent=False,
        )
        try:
            with wl.lock:
                wl.process_batch("crm", [
                    {"_id": "1", "name": "acme corp", "city": "oslo"},
                    {"_id": "2", "name": "acme corp", "city": "oslo"},
                ])
            log = audit_log()
            assert log is not None
            log.drain()
            rows = [json.loads(line)
                    for line in path.read_text().splitlines()]
            assert rows, "no audit rows written"
            row = rows[0]
            assert {row["id1"], row["id2"]} == {"crm__1", "crm__2"}
            assert row["workload"] == "people"
            assert row["link_kind"] in ("duplicate", "maybe")
            # the explanation digest joins to a later /explain replay
            out = X.explain_request(
                wl, {"id1": row["id1"], "id2": row["id2"]})
            assert out["explanation_digest"] == row["explanation_digest"]
        finally:
            wl.close()
            monkeypatch.delenv("DUKE_AUDIT_LOG")
            audit_log()  # closes the instance for the removed path


# -- HTTP surface -------------------------------------------------------------


@pytest.fixture()
def server_url(monkeypatch):
    from sesam_duke_microservice_tpu.service.app import DukeApp, serve

    monkeypatch.setenv("MIN_RELEVANCE", "0.05")
    monkeypatch.setenv("DUKE_DECISION_SAMPLE", "1.0")
    sc = parse_config(TestExplainParity.CONFIG)
    app = DukeApp(sc, persistent=False)
    server = serve(app, port=0, host="127.0.0.1")
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        yield f"http://127.0.0.1:{server.server_address[1]}"
    finally:
        server.shutdown()
        app.close()


def _post(url, path, payload):
    req = urllib.request.Request(
        url + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req) as resp:
        return json.loads(resp.read())


def _get(url, path):
    with urllib.request.urlopen(url + path) as resp:
        return json.loads(resp.read())


class TestHttpSurface:
    def test_explain_and_decisions_endpoints(self, server_url):
        _post(server_url, "/deduplication/people/crm", [
            {"_id": "1", "name": "acme corp", "city": "oslo"},
            {"_id": "2", "name": "acme corp", "city": "oslo"},
            {"_id": "3", "name": "globex", "city": "bergen"},
        ])
        out = _post(server_url, "/explain",
                    {"id1": "crm__1", "id2": "crm__2"})
        assert out["classification"] == "match"
        assert out["retrieval"]["mode"] == "inverted-index"
        # raw-record variant
        out2 = _post(server_url, "/explain", {
            "name": "people",
            "record1": {"dataset": "crm",
                        "entity": {"_id": "9", "name": "acme corp",
                                   "city": "oslo"}},
            "id2": "crm__1",
        })
        assert out2["probability"] > 0.8
        listing = _get(server_url, "/debug/decisions")
        assert listing["decisions"], "decision ring empty"
        row = listing["decisions"][0]
        full = _get(server_url, f"/debug/decisions/{row['id']}")
        assert full["outcome"] == row["outcome"]
        assert full["workload"] == "people"
        stats = _get(server_url, "/stats")
        assert "feature_cache" in stats
        wl_row = stats["workloads"][0]
        assert wl_row["decisions"]["outcomes"]["match"] >= 2

    def test_explain_error_statuses(self, server_url):
        for payload, status in (
            ({"id1": "nope", "id2": "also-nope"}, 404),
            ({"name": "zzz", "id1": "a", "id2": "b"}, 404),
            ({}, 400),
        ):
            req = urllib.request.Request(
                server_url + "/explain",
                data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
            )
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(req)
            assert err.value.code == status
        req = urllib.request.Request(
            server_url + "/debug/decisions/d99999999")
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(req)
        assert err.value.code == 404


# -- docs drift ---------------------------------------------------------------


def test_metrics_docs_in_sync():
    import subprocess
    import sys
    from pathlib import Path

    script = (Path(__file__).resolve().parent.parent
              / "scripts" / "check_metrics_docs.py")
    proc = subprocess.run(
        [sys.executable, str(script)], capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
