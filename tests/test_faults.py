"""Deterministic fault injection + the chaos differential (ISSUE 8).

The acceptance bar: under an injected-fault spec (op-stream drop/delay/
dup, partitions, follower crash, write-behind flush failure, slow
locks), the surviving group's link rows and feeds stay BIT-IDENTICAL to
unfaulted serving — transient faults are healed by the retry layer and
the seq-fencing dup-drop, topology faults degrade to the survivors
(``duke_follower_evictions_total`` moves while ``duke_dispatch_down``
stays 0), and persistence faults surface in /readyz instead of hiding
until a read drains.
"""

import json
import threading
import time
import urllib.request

import pytest

from sesam_duke_microservice_tpu import telemetry
from sesam_duke_microservice_tpu.core.config import parse_config
from sesam_duke_microservice_tpu.links.base import Link, LinkKind, LinkStatus
from sesam_duke_microservice_tpu.links.sqlite import SqliteLinkDatabase
from sesam_duke_microservice_tpu.links.write_behind import (
    WriteBehindLinkDatabase,
)
from sesam_duke_microservice_tpu.parallel import dispatch
from sesam_duke_microservice_tpu.service.app import DukeApp, serve
from sesam_duke_microservice_tpu.utils import faults

from test_replica_serving import HaGroup
from test_sharded_service import DEDUP_XML, _seeded_batch


@pytest.fixture(autouse=True)
def _no_env_faults():
    faults.configure("")
    yield
    faults.configure(None)


def _fault_count(kind: str) -> float:
    return telemetry.FAULTS_INJECTED.labels(kind=kind).value


# -- spec parsing -------------------------------------------------------------


def test_fault_spec_parses_every_kind():
    plan = faults.FaultPlan(
        "seed=42; drop=0.5@commit; dup=0.25; delay=0.1:0.05;"
        "partition=1:10:20; crash_follower=0:7; crash_leader=33;"
        "flush_fail=2; slow_lock=0.5:0.01; crash_at=pre_flush:4"
    )
    assert plan.seed == 42
    assert plan._drop == [(0.5, "commit")]
    assert plan._dup == [(0.25, None)]
    assert plan._delay == [(0.1, 0.05, None)]
    assert plan._partitions == {1: (10, 20)}
    assert plan._follower_crash == {0: 7}
    assert plan._leader_crash == 33
    assert plan._flush_fail_at == 2
    assert plan._slow_lock == (0.5, 0.01)
    assert plan._crash_at == {"pre_flush": 4}
    # counting without the kill: only the configured occurrence hits
    assert [plan.crash_hit("pre_flush") for _ in range(5)] == [
        False, False, False, True, False]
    assert plan.crash_hit("unconfigured_site") is False


def test_fault_spec_rejects_garbage():
    with pytest.raises(ValueError, match="bad DUKE_FAULTS token"):
        faults.FaultPlan("drop=notanumber")
    with pytest.raises(ValueError, match="unknown fault kind"):
        faults.FaultPlan("explode=1")


def test_fault_draws_are_deterministic():
    """Same seed + same site coordinates => same injection decision,
    regardless of call order — the property the chaos CI leg rests on."""
    p1 = faults.FaultPlan("seed=7;drop=0.5")
    p2 = faults.FaultPlan("seed=7;drop=0.5")
    decisions1 = []
    for op in range(50):
        try:
            p1.before_send("commit", 0, op, 0)
            decisions1.append(False)
        except faults.InjectedSendFailure:
            decisions1.append(True)
    decisions2 = []
    for op in reversed(range(50)):
        try:
            p2.before_send("commit", 0, op, 0)
            decisions2.append(False)
        except faults.InjectedSendFailure:
            decisions2.append(True)
    assert decisions1 == list(reversed(decisions2))
    assert any(decisions1) and not all(decisions1)


def test_env_spec_activation(monkeypatch):
    faults.configure(None)  # let the env var through
    monkeypatch.setenv("DUKE_FAULTS", "seed=1;drop=0.5")
    plan = faults.active()
    assert plan is not None and plan.seed == 1
    monkeypatch.delenv("DUKE_FAULTS")
    assert faults.active() is None


# -- chaos differential -------------------------------------------------------


def test_chaos_differential_drop_dup_delay_bit_identical():
    """THE chaos claim: under heavy transient op-stream faults (drops
    retried, dups seq-dropped, delays slept), leader AND replica feeds
    are bit-identical to each other — and equal to an unfaulted control
    group run of the same batches."""
    batches = [_seeded_batch(24), _seeded_batch(12, prefix="b"),
               [{"_id": "1", "_deleted": True}]]

    # control: same batches, no faults
    control = HaGroup(DEDUP_XML, backend="device")
    try:
        for b in batches:
            control.ingest(b)
        control.wait_applied()
        control_leader = control.leader_feed()
        control_replica = control.replica_feed()
    finally:
        control.close()
    assert control_leader == control_replica

    faults.configure("seed=3;drop=0.35;dup=0.35;delay=0.15:0.002")
    drops0, dups0 = _fault_count("drop"), _fault_count("dup")
    evictions0 = telemetry.FOLLOWER_EVICTIONS.single().value
    g = HaGroup(DEDUP_XML, backend="device", n_followers=2)
    try:
        for b in batches:
            g.ingest(b)
        g.wait_applied(follower=0)
        g.wait_applied(follower=1)
        leader_rows = g.leader_feed()
        assert g.replica_feed(follower=0) == leader_rows
        assert g.replica_feed(follower=1) == leader_rows
        # the faults actually fired...
        assert _fault_count("drop") > drops0
        assert _fault_count("dup") > dups0
        # ...and were HEALED: no eviction, no latch
        assert telemetry.FOLLOWER_EVICTIONS.single().value == evictions0
        assert telemetry.DISPATCH_DOWN.single().value == 0
        assert g.dispatcher._failed is None
    finally:
        g.close()
        faults.configure("")

    # the faulted group's rows equal the control group's, timestamps
    # aside (different wall-clock runs)
    def facts(rows):
        return sorted((r["entity1"], r["entity2"], r["_deleted"],
                       round(r["confidence"], 9)) for r in rows)

    assert facts(leader_rows) == facts(control_leader)


def test_partition_exhausts_retries_and_evicts(monkeypatch):
    """A partitioned follower (every send attempt fails) is evicted
    after the bounded retries; the group degrades to the survivor and
    stays bit-identical — duke_dispatch_down stays 0 throughout."""
    monkeypatch.setattr(dispatch, "_SEND_RETRIES", 2)
    monkeypatch.setattr(dispatch, "_RETRY_BASE_S", 0.001)
    faults.configure("partition=0:1:100000")
    evictions0 = telemetry.FOLLOWER_EVICTIONS.single().value
    partitions0 = _fault_count("partition")
    g = HaGroup(DEDUP_XML, backend="device", n_followers=2)
    try:
        g.ingest(_seeded_batch(12))
        assert _fault_count("partition") > partitions0
        assert telemetry.FOLLOWER_EVICTIONS.single().value == evictions0 + 1
        assert telemetry.DISPATCH_DOWN.single().value == 0
        assert g.dispatcher._failed is None
        assert [f.idx for f in g.dispatcher.live_followers()] == [1]
        g.wait_applied(follower=1)
        assert g.replica_feed(follower=1) == g.leader_feed()
    finally:
        g.close()


def test_follower_crash_evicted_group_survives(monkeypatch):
    """crash_follower kills the replay loop mid-stream; the dead digest
    handshake evicts it and the leader keeps serving."""
    monkeypatch.setattr(dispatch, "_CONNECT_TIMEOUT_S", 10.0)
    # the bootstrap for one workload is ~4 ops; crash follower 0 shortly
    # after, mid-ingest
    faults.configure("crash_follower=0:6")
    g = HaGroup(DEDUP_XML, backend="device", n_followers=2)
    try:
        g.ingest(_seeded_batch(12))
        g.ingest(_seeded_batch(6, prefix="b"))
        assert g.followers[0].error is not None  # the loop really died
        assert g.dispatcher._failed is None
        assert telemetry.DISPATCH_DOWN.single().value == 0
        assert len(g.dispatcher.live_followers()) == 1
        g.wait_applied(follower=1)
        assert g.replica_feed(follower=1) == g.leader_feed()
    finally:
        g.close()


# -- write-behind flush failure ----------------------------------------------


def test_flush_fail_latches_buffer(tmp_path, monkeypatch):
    # retries off: the injected failure only hits the FIRST flush call
    # (the fault counts attempts), so the default retry ladder would
    # heal it — which is now its own test (test_crash_recovery's
    # flush-retry satellite); this test pins the latch itself
    monkeypatch.setenv("DUKE_FLUSH_RETRIES", "0")
    faults.configure("flush_fail=1")
    db = WriteBehindLinkDatabase(
        SqliteLinkDatabase(str(tmp_path / "links.db"))
    )
    try:
        db.assert_link(Link("a", "b", LinkStatus.INFERRED,
                            LinkKind.DUPLICATE, 0.9))
        db.commit()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and db.flush_error is None:
            time.sleep(0.01)
        assert isinstance(db.flush_error, faults.InjectedFlushFailure)
        assert _fault_count("flush_fail") >= 1
        with pytest.raises(RuntimeError, match="flush failed"):
            db.drain()
    finally:
        db.close()


def test_flush_latch_flips_readyz_and_healthz(tmp_path, monkeypatch):
    """ISSUE 8 satellite: a dead persistence thread goes unready in
    /readyz and is NAMED in /healthz — before any read drains into it."""
    monkeypatch.setenv("DUKE_FLUSH_RETRIES", "0")  # latch on first failure
    xml = DEDUP_XML.replace(
        "<DukeMicroService>",
        f'<DukeMicroService dataFolder="{tmp_path}">',
    ).replace(' link-database-type="in-memory"', "")
    app = DukeApp(parse_config(xml, env={"MIN_RELEVANCE": "0.05"}),
                  backend="host", persistent=True)
    server = serve(app, port=0, host="127.0.0.1")
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        with urllib.request.urlopen(base + "/readyz", timeout=30) as r:
            assert r.status == 200  # healthy before the fault

        faults.configure("flush_fail=1")
        wl = app.deduplications["people"]
        with wl.lock:
            wl.process_batch("crm", _seeded_batch(6))
        deadline = time.monotonic() + 10
        while (time.monotonic() < deadline
               and wl.link_database.flush_error is None):
            time.sleep(0.01)
        assert wl.link_database.flush_error is not None

        ready, checks = app.readiness()
        assert ready is False and checks["link_persistence"] is False
        try:
            urllib.request.urlopen(base + "/readyz", timeout=30)
            raise AssertionError("readyz stayed ready past the latch")
        except urllib.error.HTTPError as e:
            assert e.code == 503
            assert json.loads(e.read())["checks"]["link_persistence"] is False
        # liveness stays 200 but NAMES the latched exception
        with urllib.request.urlopen(base + "/healthz", timeout=30) as r:
            health = json.loads(r.read())
            assert r.status == 200
            assert "deduplication/people" in health["link_flush_errors"]
            assert "InjectedFlushFailure" in \
                health["link_flush_errors"]["deduplication/people"]
    finally:
        faults.configure("")
        server.shutdown()
        app.close()


# -- feed lock deadline -------------------------------------------------------


def test_feed_midstream_deadline_abort(monkeypatch):
    """ISSUE 8 satellite: the mid-stream lock retry loop is bounded by a
    wall-clock deadline (backoff + jitter, not 120 fixed 1 s retries);
    hitting it truncates the stream and counts the 'deadline' reason."""
    monkeypatch.setenv("FEED_PAGE_SIZE", "10")
    monkeypatch.setenv("DUKE_FEED_RETRY_DEADLINE", "2")
    sc = parse_config(DEDUP_XML, env={"MIN_RELEVANCE": "0.05"})
    app = DukeApp(sc, backend="host", persistent=False)
    wl = app.deduplications["people"]
    base_ts = 1_700_000_000_000
    for i in range(50):
        wl.link_database.assert_link(
            Link(f"crm__a{i}", f"crm__b{i}", LinkStatus.INFERRED,
                 LinkKind.DUPLICATE, 0.9, timestamp=base_ts + i))

    # Deterministic contention: after page 1 the wrapped lock DENIES the
    # feed's mid-stream re-acquisitions (simulating a writer holding the
    # lock past the deadline).  A racing "thief" thread was flaky two
    # ways — it could miss the whole stream (all pages fit in one GIL
    # slice before the thread ever contended) and even a pre-parked
    # waiter loses to CPython lock barging (release -> immediate
    # re-acquire by the same thread) — while the denial drives the real
    # retry/backoff/deadline code path every run.
    deny = threading.Event()
    inner_lock = wl.lock

    class DenyingLock:
        def acquire(self, *a, **kw):
            if deny.is_set():
                return False
            return inner_lock.acquire(*a, **kw)

        def release(self):
            return inner_lock.release()

        def __enter__(self):
            self.acquire()
            return self

        def __exit__(self, *exc):
            self.release()
            return False

    real_page = wl.links_page
    pages = []

    def hooked(since, limit):
        pages.append(since)
        if len(pages) == 1:
            deny.set()
        return real_page(since, limit)

    wl.links_page = hooked
    wl.lock = DenyingLock()
    server = serve(app, port=0, host="127.0.0.1")
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        try:
            with urllib.request.urlopen(
                base + "/deduplication/people?since=0", timeout=60
            ) as r:
                r.read()
        except Exception:
            pass  # truncated chunked framing surfaces as a transport error
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if app.feed_aborts["deadline"]:
                break
            time.sleep(0.05)
        assert app.feed_aborts["deadline"] == 1
        deny.clear()
        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            text = r.read().decode()
        assert 'duke_feed_aborts_total{reason="deadline"} 1' in text
    finally:
        deny.clear()
        server.shutdown()
        app.close()


def test_slow_lock_fault_counts_and_stalls():
    faults.configure("slow_lock=1:0.01")
    plan = faults.active()
    before = _fault_count("slow_lock")
    assert plan.lock_delay() == 0.01
    assert _fault_count("slow_lock") == before + 1
    faults.configure("slow_lock=0:0.01")
    assert faults.active().lock_delay() == 0.0
