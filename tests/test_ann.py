"""Embedding-ANN backend tests: encoder properties, retrieval recall vs the
exact brute-force device backend, and event parity for retrieved pairs.

The ANN candidate set is approximate by design (engine.ann_matcher), so the
contract tested here is: (a) every pair the ANN path emits carries the same
exact probability the host oracle computes; (b) on the bundled-stresstest-
style corpus the ANN path finds the same matches as exhaustive scoring
(high recall at these sizes since true duplicates are near in n-gram
space); (c) mutation semantics (tombstones, deletes, groups) carry over
from the shared DeviceCorpus machinery.
"""


import numpy as np
from sesam_duke_microservice_tpu.core.config import MatchTunables
from sesam_duke_microservice_tpu.core.records import ID_PROPERTY_NAME
from sesam_duke_microservice_tpu.engine.ann_matcher import (
    AnnIndex,
    AnnProcessor,
)
from sesam_duke_microservice_tpu.ops import encoder as E

from test_device_matcher import (
    EventLog,
    dedup_schema,
    make_record,
    random_records,
    run_device,
    run_host,
)


def run_ann(schema, batches, group_filtering=False, **index_kw):
    index = AnnIndex(schema, tunables=MatchTunables(), **index_kw)
    proc = AnnProcessor(schema, index, group_filtering=group_filtering)
    log = EventLog()
    proc.add_match_listener(log)
    for batch in batches:
        proc.deduplicate(batch)
    return log, index, proc


class TestEncoder:
    def test_normalized_and_deterministic(self):
        v1 = E.embed_values([("name", "acme corp"), ("city", "oslo")], 128)
        v2 = E.embed_values([("name", "acme corp"), ("city", "oslo")], 128)
        assert np.allclose(v1, v2)
        assert abs(np.linalg.norm(v1) - 1.0) < 1e-5

    def test_similar_strings_closer_than_different(self):
        a = E.embed_values([("name", "acme corporation")], 256)
        b = E.embed_values([("name", "acme corpration")], 256)   # typo
        c = E.embed_values([("name", "globex industries")], 256)
        assert float(a @ b) > float(a @ c)

    def test_field_salting_separates_properties(self):
        # same value in different fields must not look identical
        a = E.embed_values([("name", "oslo")], 256)
        b = E.embed_values([("city", "oslo")], 256)
        assert float(a @ b) < 0.99

    def test_empty_record_is_zero(self):
        v = E.embed_values([], 64)
        assert np.all(v == 0.0)

    def test_encoder_uses_comparison_properties(self):
        schema = dedup_schema()
        enc = E.RecordEncoder(schema, 64)
        assert set(enc.props) == {"name", "city", "amount"}
        r = make_record("x", name="acme", city="oslo", amount="100")
        assert abs(np.linalg.norm(enc.encode(r)) - 1.0) < 1e-5


class TestAnnVsBruteForce:
    def test_match_events_equal_exhaustive(self):
        schema = dedup_schema()
        records = random_records(60, seed=7)
        device, _, _ = run_device(schema, [records])
        ann, _, _ = run_ann(schema, [records])
        assert ann.match_set() == device.match_set()
        assert ann.none_set() == device.none_set()

    def test_probabilities_match_host_oracle(self):
        schema = dedup_schema()
        records = random_records(50, seed=13)
        host = run_host(schema, [records])
        ann, _, _ = run_ann(schema, [records])
        # every ANN-emitted pair must appear in the host oracle with the
        # identical (rounded) confidence — exact rescoring, no drift
        assert ann.match_set() <= host.match_set()

    def test_multi_batch_incremental(self):
        schema = dedup_schema()
        b1 = random_records(30, seed=1)
        b2 = random_records(25, seed=2)
        for i, r in enumerate(b2):
            r.set_values(ID_PROPERTY_NAME, [f"s{i}"])
        device, _, _ = run_device(schema, [b1, b2])
        ann, _, _ = run_ann(schema, [b1, b2])
        assert ann.match_set() == device.match_set()

    def test_group_filtering_record_linkage(self):
        schema = dedup_schema()
        records = random_records(40, seed=11, with_group=True)
        device, _, _ = run_device(schema, [records], group_filtering=True)
        ann, _, _ = run_ann(schema, [records], group_filtering=True)
        assert ann.match_set() == device.match_set()

    def test_maybe_threshold(self):
        schema = dedup_schema(threshold=0.92, maybe=0.6)
        records = random_records(35, seed=3)
        device, _, _ = run_device(schema, [records])
        ann, _, _ = run_ann(schema, [records])
        assert ann.match_set() == device.match_set()

    def test_recall_escalation_triggers(self):
        # tiny C forces saturation: every retrieved candidate clears the
        # bound, so the scorer must escalate instead of truncating
        schema = dedup_schema(threshold=0.5)
        records = [
            make_record(f"d{i}", name="acme corp", city="oslo", amount="100")
            for i in range(24)
        ]
        ann, index, _ = run_ann(schema, [records], initial_top_c=2)
        # all 24 identical records must match each other despite C=2 start
        match_pairs = {(e[1], e[2]) for e in ann.events if e[0] == "match"}
        assert len(match_pairs) == 24 * 23


class TestAnnMutation:
    def test_reindex_tombstones_old_row(self):
        schema = dedup_schema()
        r1 = make_record("a", name="acme corp", city="oslo", amount="100")
        r2 = make_record("b", name="acme corp", city="oslo", amount="100")
        ann, index, proc = run_ann(schema, [[r1, r2]])
        assert ("match", "a", "b") in {e[:3] for e in ann.match_set()}
        # re-index "a" with a different name: old row tombstoned
        r1b = make_record("a", name="zzz qqq ww", city="bergen", amount="900")
        proc.deduplicate([r1b])
        log2 = EventLog()
        proc.listeners[:] = [log2]
        proc.deduplicate(
            [make_record("c", name="acme corp", city="oslo", amount="100")]
        )
        ids = {e[2] for e in log2.match_set()}
        assert "b" in ids and "a" not in ids

    def test_deleted_records_excluded(self):
        schema = dedup_schema()
        r1 = make_record("a", name="acme corp", city="oslo", amount="100")
        ann, index, proc = run_ann(schema, [[r1]])
        index.delete(r1)
        log2 = EventLog()
        proc.listeners[:] = [log2]
        proc.deduplicate(
            [make_record("c", name="acme corp", city="oslo", amount="100")]
        )
        assert log2.match_set() == set()

    def test_find_candidate_matches_interface(self):
        schema = dedup_schema()
        records = [
            make_record("a", name="acme corp", city="oslo", amount="100"),
            make_record("b", name="acme corpo", city="oslo", amount="100"),
            make_record("c", name="globex industries", city="tromso",
                        amount="1000"),
        ]
        _, index, _ = run_ann(schema, [records])
        cands = index.find_candidate_matches(records[0])
        ids = {r.record_id for r in cands}
        assert "b" in ids and "a" not in ids


class TestAnnSnapshot:
    def test_embedding_snapshot_roundtrip(self, tmp_path):
        """np.savez cannot represent bf16 natively; the snapshot stores a
        uint16 bit view and must come back as bf16 — a corrupted dtype
        would crash the first post-restart ingest instead of replaying.
        Under DUKE_EMB_INT8 the embedding tree is int8 codes + a f32
        scale vector (plain savez dtypes) and must round-trip
        bit-identically too."""
        schema = dedup_schema()
        records = random_records(20, seed=3)
        ann, index, proc = run_ann(schema, [records])
        expected = (np.dtype(np.int8) if index.emb_storage == "int8"
                    else np.dtype(E.STORAGE_DTYPE))
        assert index.corpus.feats[E.ANN_PROP][E.ANN_TENSOR].dtype == expected
        path = str(tmp_path / "snap.npz")
        index.snapshot_save(path)

        index2 = AnnIndex(schema, tunables=MatchTunables())
        ok = index2.snapshot_load(
            path, {r.record_id: r for r in records}
        )
        assert ok, "snapshot must load"
        tree = index2.corpus.feats[E.ANN_PROP]
        assert tree[E.ANN_TENSOR].dtype == expected
        n = index2.corpus.size
        for name, arr in index.corpus.feats[E.ANN_PROP].items():
            assert tree[name].dtype == arr.dtype
            assert tree[name][:n].tobytes() == arr[:n].tobytes()
        # and the restored corpus still scores: a near-duplicate probe
        # matches records through the loaded embedding matrix
        proc2 = AnnProcessor(schema, index2)
        log = EventLog()
        proc2.add_match_listener(log)
        probe = make_record("probe", name=records[0].get_value("name"),
                            city=records[0].get_value("city"),
                            amount=records[0].get_value("amount"))
        proc2.deduplicate([probe])
        assert ("match", "probe", "r0") in {e[:3] for e in log.match_set()}

    def test_stale_dtype_snapshot_rejected(self, tmp_path, monkeypatch):
        """A snapshot written under a different embedding storage dtype
        (e.g. a pre-bf16 f32 deployment) must be rejected — accepting it
        would silently pin the corpus to the old dtype."""
        schema = dedup_schema()
        records = random_records(10, seed=4)
        ann, index, proc = run_ann(schema, [records])
        # forge the old deployment: fingerprint computed with f32 storage
        monkeypatch.setattr(index, "emb_storage", "float32")
        path = str(tmp_path / "snap.npz")
        index.snapshot_save(path)

        index2 = AnnIndex(schema, tunables=MatchTunables())
        assert index2.snapshot_load(
            path, {r.record_id: r for r in records}
        ) is False


def test_ann_prewarm_compiles_both_variants(monkeypatch):
    """r3 regression: the prewarm ladder lowers BOTH scorer variants for
    the ANN cache (from_rows=True and the http-transform probe shape) —
    the r3 base-class change added kwargs the ANN override lacked, so the
    warm thread died with TypeError and the ladder silently stopped."""
    from sesam_duke_microservice_tpu.engine.ann_matcher import (
        AnnIndex,
        AnnProcessor,
    )

    monkeypatch.setenv("DEVICE_PREWARM", "1")
    schema = dedup_schema()
    records = random_records(24, seed=11)
    index = AnnIndex(schema, tunables=MatchTunables())
    proc = AnnProcessor(schema, index)
    proc.deduplicate(records)
    cache = index.scorer_cache
    assert cache._warm_thread is not None
    cache._warm_thread.join(timeout=240)
    assert not cache._warm_thread.is_alive()
    # both variants per (capacity, bucket) step -> an odd ladder would
    # mean one variant failed; >= 2 proves at least one full step of both
    assert cache._warm_compiled >= 2, cache._warm_compiled
