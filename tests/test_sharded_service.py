"""Mesh-sharded serving backends on the virtual 8-device mesh.

VERDICT r2 #1: the sharded scorers must be reachable from the REST
service, with results equal to the single-chip backends.  These tests
build real workloads with ``backend="sharded"`` / ``"sharded-brute"``
(engine.sharded_matcher) and drive them through the same paths the HTTP
handlers use — plus one end-to-end HTTP server test over the sharded
backend.
"""

import json
import threading
import urllib.request

import pytest

from sesam_duke_microservice_tpu.core.config import parse_config
from sesam_duke_microservice_tpu.engine.workload import build_workload

DEDUP_XML = """
<DukeMicroService>
  <Deduplication name="people" link-database-type="in-memory">
    <duke>
      <schema>
        <threshold>0.8</threshold>
        <property><name>NAME</name><comparator>levenshtein</comparator><low>0.1</low><high>0.95</high></property>
        <property><name>EMAIL</name><comparator>exact</comparator><low>0.2</low><high>0.95</high></property>
      </schema>
      <data-source class="io.sesam.dukemicroservice.IncrementalDeduplicationDataSource">
        <param name="dataset-id" value="crm"/>
        <column name="name" property="NAME"/>
        <column name="email" property="EMAIL"/>
      </data-source>
    </duke>
  </Deduplication>
</DukeMicroService>
"""

LINKAGE_XML = """
<DukeMicroService>
  <RecordLinkage name="pairing" link-mode="many-to-many" link-database-type="in-memory">
    <duke>
      <schema>
        <threshold>0.7</threshold>
        <property><name>NAME</name><comparator>levenshtein</comparator><low>0.1</low><high>0.95</high></property>
      </schema>
      <group>
        <data-source class="io.sesam.dukemicroservice.IncrementalRecordLinkageDataSource">
          <param name="dataset-id" value="left"/>
          <column name="name" property="NAME"/>
        </data-source>
      </group>
      <group>
        <data-source class="io.sesam.dukemicroservice.IncrementalRecordLinkageDataSource">
          <param name="dataset-id" value="right"/>
          <column name="name" property="NAME"/>
        </data-source>
      </group>
    </duke>
  </RecordLinkage>
</DukeMicroService>
"""


def _seeded_batch(n, prefix=""):
    """Deterministic names with a known duplicate structure: every third
    record repeats the previous name (so i and i-1 match), the rest are
    distinct."""
    rows = []
    for i in range(n):
        if i % 3 == 2:
            name = f"person number {i - 1}"
        else:
            name = f"person number {i}"
        rows.append({
            "_id": f"{prefix}{i}",
            "name": name,
            "email": f"{name.replace(' ', '.')}@x.no",
        })
    return rows


def _live_links(wl):
    return sorted(
        (r["entity1"], r["entity2"], round(r["confidence"], 9))
        for r in wl.links_since(0) if not r["_deleted"]
    )


def _run_dedup(backend, batches, env=None):
    sc = parse_config(DEDUP_XML, env=env or {"MIN_RELEVANCE": "0.05"})
    wl = build_workload(sc.deduplications["people"], sc, backend=backend,
                        persistent=False)
    try:
        with wl.lock:
            for batch in batches:
                wl.process_batch("crm", batch)
            return _live_links(wl)
    finally:
        wl.close()


@pytest.mark.parametrize("sharded,single", [
    ("sharded", "ann"),
    ("sharded-brute", "device"),
])
def test_sharded_matches_single_chip_dedup(sharded, single):
    """Same batches through the mesh backend and its single-chip
    counterpart produce identical links and confidences."""
    batches = [_seeded_batch(24), _seeded_batch(12, prefix="b")]
    assert _run_dedup(sharded, batches) == _run_dedup(single, batches)
    # sanity: the corpus actually produced links
    assert len(_run_dedup(sharded, batches)) >= 10


def test_sharded_linkage_group_exclusion_and_transform():
    sc = parse_config(LINKAGE_XML, env={"MIN_RELEVANCE": "0.05"})
    wl = build_workload(sc.record_linkages["pairing"], sc, backend="sharded",
                        persistent=False)
    try:
        with wl.lock:
            # same name twice in the SAME group: must not link
            wl.process_batch("left", [
                {"_id": "a", "name": "Turing"},
                {"_id": "b", "name": "Turing"},
            ])
            assert wl.links_since(0) == []
            wl.process_batch("right", [{"_id": "c", "name": "Turing"}])
            keys = {r["_id"] for r in wl.links_since(0)}
            assert keys == {"1__left__a_2__right__c",
                            "1__left__b_2__right__c"}
            # http-transform: side-effect-free probe over the sharded corpus
            rows = wl.process_batch(
                "right", [{"_id": "probe", "name": "Turing"}],
                http_transform=True,
            )
            linked = {d["entityId"] for d in rows[0]["duke_links"]}
            assert linked == {"a", "b"}
            assert {r["_id"] for r in wl.links_since(0)} == keys
    finally:
        wl.close()


def test_sharded_delete_retracts_and_tombstones():
    sc = parse_config(DEDUP_XML, env={"MIN_RELEVANCE": "0.05"})
    wl = build_workload(sc.deduplications["people"], sc, backend="sharded",
                        persistent=False)
    try:
        with wl.lock:
            wl.process_batch("crm", [
                {"_id": "1", "name": "Alan Turing", "email": "a@x.no"},
                {"_id": "2", "name": "Alan Turing", "email": "a@x.no"},
            ])
            assert len(_live_links(wl)) == 1
            wl.process_batch("crm", [{"_id": "2", "_deleted": True}])
            assert _live_links(wl) == []
            # the tombstoned record must stay resolvable for the feed but
            # never come back as a candidate
            wl.process_batch("crm", [
                {"_id": "3", "name": "Alan Turing", "email": "a@x.no"},
            ])
            live = _live_links(wl)
            assert {(e1, e2) for e1, e2, _ in live} == {("1", "3")}
    finally:
        wl.close()


def test_sharded_value_slot_growth_rebuilds_on_mesh():
    """Multi-valued records widen the value axis; the rebuilt corpus must
    stay sharded and keep scoring correctly."""
    sc = parse_config(DEDUP_XML, env={"MIN_RELEVANCE": "0.05"})
    wl = build_workload(sc.deduplications["people"], sc, backend="sharded",
                        persistent=False)
    try:
        with wl.lock:
            wl.process_batch("crm", [
                {"_id": "1", "name": "Ada Lovelace", "email": "a@x.no"},
            ])
            # second value is the matching one: invisible without growth
            wl.process_batch("crm", [
                {"_id": "2", "name": ["Zzz Yyy", "Ada Lovelace"],
                 "email": "a@x.no"},
            ])
            live = _live_links(wl)
        assert {(e1, e2) for e1, e2, _ in live} == {("1", "2")}
        from sesam_duke_microservice_tpu.parallel.sharded import SHARD_AXIS

        feats, valid, _, _ = wl.index.corpus.device_arrays()
        assert SHARD_AXIS in str(valid.sharding.spec)
    finally:
        wl.close()


def test_sharded_snapshot_restart(tmp_path):
    """Persistent sharded workload: restart restores the corpus from the
    snapshot onto the mesh and serves identical results."""
    xml = DEDUP_XML.replace(
        "<DukeMicroService>", f'<DukeMicroService dataFolder="{tmp_path}">'
    ).replace('link-database-type="in-memory"', 'link-database-type="h2"')
    sc = parse_config(xml, env={"MIN_RELEVANCE": "0.05"})
    wl = build_workload(sc.deduplications["people"], sc, backend="sharded",
                        persistent=True)
    with wl.lock:
        wl.process_batch("crm", _seeded_batch(18))
        before = _live_links(wl)
    wl.close()  # saves the snapshot

    from sesam_duke_microservice_tpu.engine.sharded_matcher import (
        ShardedAnnIndex,
    )

    real_extract = ShardedAnnIndex._extract
    calls = []

    def counting_extract(self, records, plan=None):
        calls.append(len(records))
        return real_extract(self, records, plan)

    ShardedAnnIndex._extract = counting_extract
    try:
        wl2 = build_workload(sc.deduplications["people"], sc,
                             backend="sharded", persistent=True)
    finally:
        ShardedAnnIndex._extract = real_extract
    try:
        # restart must come from the snapshot, not per-record re-extraction
        assert not calls
        with wl2.lock:
            assert _live_links(wl2) == before
            # and the restored corpus keeps serving new batches
            wl2.process_batch("crm", [
                {"_id": "again0", "name": "person number 0",
                 "email": "person.number.0@x.no"},
            ])
            after = _live_links(wl2)
        assert len(after) > len(before)
    finally:
        wl2.close()


def test_sharded_http_service_end_to_end():
    """The full REST surface over the sharded backend: POST, feed,
    transform, /stats."""
    import os

    from sesam_duke_microservice_tpu.service.app import DukeApp, serve

    saved = os.environ.get("MIN_RELEVANCE")
    os.environ["MIN_RELEVANCE"] = "0.05"
    try:
        app = DukeApp(parse_config(DEDUP_XML), backend="sharded",
                      persistent=False)
    finally:
        if saved is None:
            os.environ.pop("MIN_RELEVANCE", None)
        else:
            os.environ["MIN_RELEVANCE"] = saved
    server = serve(app, port=0, host="127.0.0.1")
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    base = f"http://127.0.0.1:{server.server_address[1]}"

    def post(path, payload):
        req = urllib.request.Request(
            base + path, data=json.dumps(payload).encode(), method="POST",
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=300) as resp:
            return resp.status, json.loads(resp.read())

    try:
        status, body = post("/deduplication/people/crm", [
            {"_id": "1", "name": "Alan Turing", "email": "a@x.no"},
            {"_id": "2", "name": "Alan Turing", "email": "a@x.no"},
        ])
        assert (status, body) == (200, {"success": True})
        with urllib.request.urlopen(
                base + "/deduplication/people?since=0", timeout=300) as resp:
            rows = json.loads(resp.read())
        assert len(rows) == 1
        assert {rows[0]["entity1"], rows[0]["entity2"]} == {"1", "2"}

        status, body = post("/deduplication/people/crm/httptransform",
                            {"_id": "p", "name": "Alan Turing",
                             "email": "a@x.no"})
        assert status == 200
        assert {d["entityId"] for d in body["duke_links"]} == {"1", "2"}

        with urllib.request.urlopen(base + "/stats", timeout=60) as resp:
            stats = json.loads(resp.read())
        assert stats["backend"] == "sharded"
        assert stats["workloads"][0]["records_indexed"] == 2
    finally:
        server.shutdown()
        app.close()


def _escalation_batch(n_dups, n_filler):
    """n_dups records sharing one name (every pair a candidate) plus
    distinct filler rows."""
    rows = [
        {"_id": f"dup{i}", "name": "grace hopper",
         "email": f"g{i}@navy.mil"}
        for i in range(n_dups)
    ]
    rows += [
        {"_id": f"f{i}", "name": f"unrelated person {i:04d}",
         "email": f"u{i}@x.no"}
        for i in range(n_filler)
    ]
    return rows


@pytest.mark.parametrize("sharded,single", [
    ("sharded-brute", "device"),   # K-escalation (top-K overflow)
    ("sharded", "ann"),            # C-escalation (retrieval saturation)
])
def test_sharded_escalation_fires_and_matches_single_chip(sharded, single):
    """VERDICT r3 #7: the claim that 'escalation loops run unchanged' on
    the mesh must be tested, not asserted.  One name cluster larger than
    the initial top-K/top-C forces the widening loop INSIDE shard_map
    (count is psum'd over the mesh, so the decision depends on the
    collective); links + confidences must equal the single-chip backend's
    under escalation, and the escalation counter must actually move on
    both."""
    from sesam_duke_microservice_tpu.engine import device_matcher as DM

    # DEVICE_TOP_K=16 (K path) and initial_top_c=64 (C path): a
    # 72-strong duplicate cluster overflows both widths
    batches = [_escalation_batch(72, 24)]

    def run_counting(backend):
        start = DM.ESCALATIONS
        links = _run_dedup(backend, batches)
        return links, DM.ESCALATIONS - start

    sharded_links, sharded_esc = run_counting(sharded)
    single_links, single_esc = run_counting(single)
    assert sharded_esc > 0, "mesh escalation never fired"
    assert single_esc > 0, "single-chip escalation never fired"
    assert sharded_links == single_links
    # the cluster must actually be fully linked (C(40,2) pairs) — proof
    # the widened pass surfaced candidates beyond the initial width
    dup_pairs = [
        (a, b) for a, b, _ in sharded_links
        if a.startswith("dup") and b.startswith("dup")
    ]
    assert len(dup_pairs) == 72 * 71 // 2
