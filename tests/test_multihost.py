"""Two-process DCN smoke test: jax.distributed over localhost.

``parallel/multihost.py`` is the multi-host entrypoint (one controller
process per host, coordinator over DCN).  This test actually exercises it:
two OS processes join a distributed job through
``multihost.initialize(coordinator_address="localhost:<port>")``, build
the global corpus mesh spanning both processes' devices (2 virtual CPU
devices each -> 4 global), run a psum/all_gather across the process
boundary, and execute the real sharded corpus scorer with the record axis
sharded across processes (see ``dcn_smoke_child.py``).  This is the
closest a single machine gets to the v5e multi-host deployment — same
code path, coordinator handshake, and collectives, with gRPC-over-
localhost standing in for DCN.
"""

import os
import socket
import subprocess
import sys

CHILD = os.path.join(os.path.dirname(__file__), "dcn_smoke_child.py")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_distributed_init_and_sharded_scoring():
    env = dict(os.environ)
    # children force their own platform/device-count; scrub the suite's
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")

    # _free_port closes its probe socket before the coordinator binds it —
    # a TOCTOU window another process can win on a busy host; retry once
    # with a fresh port so such a loss doesn't fail the test spuriously
    last = None
    for _ in range(2):
        coordinator = f"localhost:{_free_port()}"
        procs = [
            subprocess.Popen(
                [sys.executable, CHILD, str(pid), coordinator],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
                env=env, cwd=REPO,
            )
            for pid in (0, 1)
        ]
        outs = []
        try:
            for p in procs:
                out, err = p.communicate(timeout=240)
                outs.append((p.returncode, out, err))
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            raise
        if all(rc == 0 and "DCN_OK" in out for rc, out, _ in outs):
            return
        last = outs
    for rc, out, err in last:
        assert rc == 0, f"child failed (rc={rc}):\n{err[-4000:]}"
        assert "DCN_OK" in out, (out, err[-2000:])
