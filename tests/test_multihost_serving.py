"""Two-process multi-host SERVING test (VERDICT r3 #1).

The full REST surface served from a 2-process jax.distributed job (2
virtual CPU devices per process, global mesh = 4): process 0 is the HTTP
frontend + op dispatcher, process 1 the follower replay loop
(parallel/dispatch.py).  The test drives real HTTP against the frontend —
ingest with duplicates, concurrent POSTs, deletion/retraction, the
``?since=`` feed, http-transform, hot config reload, post-reload ingest —
and pins the emitted link set equal to a single-process run of the same
batches (the collectives cross the process boundary on every scoring
pass, so any lockstep divergence deadlocks or diverges loudly).
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from sesam_duke_microservice_tpu.core.config import parse_config
from sesam_duke_microservice_tpu.engine.workload import build_workload

from test_sharded_service import DEDUP_XML, _seeded_batch

CHILD = os.path.join(os.path.dirname(__file__), "multihost_serving_child.py")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _post(url, payload, timeout=120):
    req = urllib.request.Request(
        url, json.dumps(payload).encode(),
        {"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, r.read()


def _get(url, timeout=120):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read()


def _wait_health(base, procs, deadline_s=180):
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        for p in procs:
            if p.poll() is not None:
                _, err = p.communicate(timeout=10)
                raise AssertionError(
                    f"child died rc={p.returncode}:\n{err[-4000:]}"
                )
        try:
            status, _ = _get(base + "/health", timeout=2)
            if status == 200:
                return
        except (urllib.error.URLError, ConnectionError, OSError):
            pass
        time.sleep(0.5)
    raise AssertionError("frontend /health never came up")


@pytest.mark.parametrize("backend", ["sharded-brute", "sharded"])
def test_two_process_serving_full_rest_surface(backend, tmp_path):
    # durable link DB (drop the in-memory attribute): the flow includes a
    # hot reload, and an in-memory link DB is legitimately emptied by one
    # (reference behavior — a fresh link database per config swap)
    xml = DEDUP_XML.replace(
        "<DukeMicroService>",
        f'<DukeMicroService dataFolder="{tmp_path / backend}">',
    ).replace(' link-database-type="in-memory"', "")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # children force their own device count
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["CONFIG_STRING"] = xml
    env["MIN_RELEVANCE"] = "0.05"
    env["DUKE_DISPATCH_HOST"] = "127.0.0.1"

    coordinator = f"localhost:{_free_port()}"
    http_port = _free_port()
    base = f"http://127.0.0.1:{http_port}"
    procs = [
        subprocess.Popen(
            [sys.executable, CHILD, str(pid), coordinator, str(http_port),
             backend],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=REPO,
        )
        for pid in (0, 1)
    ]
    try:
        _wait_health(base, procs)

        # -- ingest: two sequential batches with known duplicates
        b1 = _seeded_batch(24)
        b2 = _seeded_batch(12, prefix="b")
        for batch in (b1, b2):
            status, body = _post(f"{base}/deduplication/people/crm", batch)
            assert status == 200 and json.loads(body)["success"] is True

        # -- concurrent POSTs (distinct id spaces): exercises the
        # microbatch merge + the global op-lock serialization
        conc = [_seeded_batch(6, prefix=f"c{t}-") for t in range(4)]
        errors = []

        def poster(batch):
            try:
                status, _ = _post(f"{base}/deduplication/people/crm", batch)
                if status != 200:
                    errors.append(status)
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))

        threads = [threading.Thread(target=poster, args=(b,)) for b in conc]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        assert not any(t.is_alive() for t in threads), "poster hung"
        assert not errors, errors

        # -- deletion: record "1" is half of the (1,2) duplicate pair
        status, _ = _post(f"{base}/deduplication/people/crm",
                          [{"_id": "1", "_deleted": True}])
        assert status == 200

        # -- transform: probe matching an indexed record, no side effects
        probe = dict(b1[3])
        probe["_id"] = "probe-x"
        status, body = _post(
            f"{base}/deduplication/people/crm/httptransform", probe
        )
        assert status == 200
        transform_links = {
            (l["entityId"], round(l["confidence"], 9))
            for l in json.loads(body)["duke_links"]
        }

        # -- hot reload (same config), then more ingest: followers must
        # swap replicas in lockstep and keep scoring
        req = urllib.request.Request(
            f"{base}/config", xml.encode(),
            {"Content-Type": "application/xml"}, method="POST",
        )
        with urllib.request.urlopen(req, timeout=300) as r:
            assert r.status in (200, 302)
        b3 = _seeded_batch(9, prefix="d")
        status, _ = _post(f"{base}/deduplication/people/crm", b3)
        assert status == 200

        # -- feed
        status, body = _get(f"{base}/deduplication/people?since=0")
        assert status == 200
        rows = json.loads(body)
        got_live = sorted(
            (r["entity1"], r["entity2"], round(r["confidence"], 9))
            for r in rows if not r["_deleted"]
        )
        got_retracted = sorted(
            (r["entity1"], r["entity2"]) for r in rows if r["_deleted"]
        )

        # -- /stats sanity (no hangs, sane counters)
        status, body = _get(f"{base}/stats")
        assert status == 200
        stats = json.loads(body)["workloads"][0]
        assert stats["records_indexed"] > 0

        # -- ring re-match runs multi-host (r4): the query-sharded ring
        # program executes across both processes, results materialize via
        # process_allgather, and re-matching an intact link DB is
        # idempotent — the feed comparison below must still hold
        status, body = _post(f"{base}/deduplication/people/rematch", [],
                             timeout=300)
        assert status == 200
        rstats = json.loads(body)
        assert rstats["queries"] > 0 and rstats["devices"] == 4
        assert rstats["events"] > 0

        status, body = _get(f"{base}/deduplication/people?since=0")
        assert status == 200
        rows_after = json.loads(body)
        assert sorted(
            (r["entity1"], r["entity2"], round(r["confidence"], 9))
            for r in rows_after if not r["_deleted"]
        ) == got_live
    finally:
        procs[0].send_signal(signal.SIGTERM)
        outs = []
        for p in procs:
            try:
                outs.append(p.communicate(timeout=120))
            except subprocess.TimeoutExpired:
                p.kill()
                outs.append(p.communicate())
        for p, (out, err) in zip(procs, outs):
            assert p.returncode == 0, (
                f"child rc={p.returncode}:\n{err[-4000:]}"
            )

    # -- single-process oracle: identical batches through the equivalent
    # in-process workload (conftest's virtual mesh); links + confidences
    # must match bit-for-bit (host-exact finalization both sides)
    single = "device" if backend == "sharded-brute" else "ann"
    sc = parse_config(DEDUP_XML, env={"MIN_RELEVANCE": "0.05"})
    wl = build_workload(sc.deduplications["people"], sc, backend=single,
                        persistent=False)
    try:
        with wl.lock:
            wl.process_batch("crm", b1)
            wl.process_batch("crm", b2)
            for batch in conc:
                wl.process_batch("crm", batch)
            wl.process_batch("crm", [{"_id": "1", "_deleted": True}])
            expected_transform = {
                (l["entityId"], round(l["confidence"], 9))
                for row in wl.process_batch("crm", [probe],
                                            http_transform=True)
                for l in row["duke_links"]
            }
            wl.process_batch("crm", b3)
            expected_rows = wl.links_since(0)
    finally:
        wl.close()
    expected_live = sorted(
        (r["entity1"], r["entity2"], round(r["confidence"], 9))
        for r in expected_rows if not r["_deleted"]
    )
    expected_retracted = sorted(
        (r["entity1"], r["entity2"]) for r in expected_rows if r["_deleted"]
    )
    assert got_live == expected_live
    assert got_retracted == expected_retracted
    assert transform_links == expected_transform
