"""Crash-consistent ingest (ISSUE 10): durable link journal, exactly-once
recovery replay, and the kill-at-every-site chaos differential.

The acceptance bar: for EVERY injected crash site, a child process killed
mid-ingest and restarted (the unacked suffix re-sent, the at-least-once
contract every Sesam client implements) must converge to a link DB and
``?since=`` feed identical to an uncrashed control — timestamps excluded
(wall clock differs across processes by construction), everything else
byte-for-byte.  Torn journal tails are truncated and counted; replayed
batches are counted; with ``DUKE_JOURNAL=0`` the legacy loss window is
demonstrably back (pinning that the journal is what closed it).
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from sesam_duke_microservice_tpu import telemetry
from sesam_duke_microservice_tpu.core.config import parse_config
from sesam_duke_microservice_tpu.links import create_link_database
from sesam_duke_microservice_tpu.links.base import Link, LinkKind, LinkStatus
from sesam_duke_microservice_tpu.links.journal import (
    LinkJournal,
    recovery_in_progress,
)
from sesam_duke_microservice_tpu.links.memory import InMemoryLinkDatabase
from sesam_duke_microservice_tpu.links.replica import (
    PublishingLinkDatabase,
    ReplicaLinkDatabase,
)
from sesam_duke_microservice_tpu.links.sqlite import SqliteLinkDatabase
from sesam_duke_microservice_tpu.links.write_behind import (
    WriteBehindLinkDatabase,
)
from sesam_duke_microservice_tpu.service.app import (
    DukeApp,
    install_shutdown_handlers,
    serve,
)
from sesam_duke_microservice_tpu.utils import faults

CHILD = os.path.join(os.path.dirname(__file__), "crash_recovery_child.py")
N_BATCHES = 6


@pytest.fixture(autouse=True)
def _no_env_faults():
    # mask any CI-leg DUKE_FAULTS spec for in-process state; child runs
    # get their spec via an explicit env override
    faults.configure("")
    yield
    faults.configure(None)


def L(id1, id2, conf=0.9, status=LinkStatus.INFERRED, ts=None):
    return Link(id1, id2, status, LinkKind.DUPLICATE, conf, ts)


# -- journal format / scan ----------------------------------------------------


class TestJournalFormat:
    def test_roundtrip_and_watermark(self, tmp_path):
        path = str(tmp_path / "links.journal")
        j = LinkJournal(path, sync="none")
        rows1 = [("a", "b", "inferred", "duplicate", 0.9, 111)]
        rows2 = [("c", "d", "inferred", "maybe", 0.5, 222),
                 ("e", "f", "retracted", "duplicate", 0.7, 333)]
        assert j.append_batch(rows1) == 1
        assert j.append_batch(rows2) == 2
        j.mark_applied(1)
        assert j.pending_batches == 1
        j.close()

        j2 = LinkJournal(path)
        assert j2.pending_batches == 1
        unapplied = j2.unapplied()
        assert unapplied == [(2, [list(r) for r in rows2])]
        # seq continues past the scanned head
        assert j2.append_batch(rows1) == 3
        j2.close()

    def test_torn_tail_truncated_counted_never_fatal(self, tmp_path):
        path = str(tmp_path / "links.journal")
        j = LinkJournal(path, sync="none")
        j.append_batch([("a", "b", "inferred", "duplicate", 0.9, 1)])
        j.append_batch([("c", "d", "inferred", "duplicate", 0.8, 2)])
        j.close()
        good = os.path.getsize(path)
        with open(path, "ab") as f:
            f.write(b"B\x07\x00\x00")  # half a frame header: crash mid-append

        torn0 = telemetry.JOURNAL_TORN_TAILS.single().value
        j2 = LinkJournal(path)
        assert telemetry.JOURNAL_TORN_TAILS.single().value == torn0 + 1
        assert os.path.getsize(path) == good  # tail gone, prefix intact
        assert [seq for seq, _ in j2.unapplied()] == [1, 2]
        # the journal keeps working after the truncation
        assert j2.append_batch([("e", "f", "inferred", "duplicate", 0.7, 3)]) == 3
        j2.close()

    def test_corrupt_frame_truncates_from_there(self, tmp_path):
        path = str(tmp_path / "links.journal")
        j = LinkJournal(path, sync="none")
        j.append_batch([("a", "b", "inferred", "duplicate", 0.9, 1)])
        first = os.path.getsize(path)
        j.append_batch([("c", "d", "inferred", "duplicate", 0.8, 2)])
        j.close()
        raw = bytearray(open(path, "rb").read())
        raw[first + 20] ^= 0xFF  # flip a byte inside frame 2's payload
        open(path, "wb").write(bytes(raw))

        torn0 = telemetry.JOURNAL_TORN_TAILS.single().value
        j2 = LinkJournal(path)
        assert telemetry.JOURNAL_TORN_TAILS.single().value == torn0 + 1
        # frame 1 survives; everything from the corrupt frame on is dropped
        assert [seq for seq, _ in j2.unapplied()] == [1]
        assert os.path.getsize(path) == first
        j2.close()

    def test_compacts_to_empty_when_applied(self, tmp_path):
        path = str(tmp_path / "links.journal")
        j = LinkJournal(path, sync="fsync")
        for i in range(3):
            seq = j.append_batch([("a", f"b{i}", "inferred", "duplicate",
                                   0.9, i)])
            j.mark_applied(seq)
        j.close()  # drained close compacts regardless of size threshold
        assert os.path.getsize(path) == 0
        # reopening an empty journal recovers nothing
        j2 = LinkJournal(path)
        assert j2.unapplied() == []
        j2.close()

    def test_sync_policy_fail_to_default(self, monkeypatch, tmp_path):
        from sesam_duke_microservice_tpu.links import journal as jmod

        monkeypatch.setenv("DUKE_JOURNAL_SYNC", "fsync")
        assert jmod.sync_policy() == "fsync"
        monkeypatch.setenv("DUKE_JOURNAL_SYNC", "none")
        assert jmod.sync_policy() == "none"
        monkeypatch.setenv("DUKE_JOURNAL_SYNC", "bogus")
        assert jmod.sync_policy() == jmod.DEFAULT_SYNC_POLICY
        monkeypatch.delenv("DUKE_JOURNAL_SYNC")
        assert jmod.sync_policy() == jmod.DEFAULT_SYNC_POLICY


# -- write-behind + journal integration ---------------------------------------


class TestJournaledWriteBehind:
    def test_commit_journals_before_flush(self, tmp_path):
        """The durability point precedes the background apply: a batch
        sealed by commit() is on disk in the journal even while the
        flusher is still stuck on it."""
        entered = threading.Event()
        release = threading.Event()

        class Slow(InMemoryLinkDatabase):
            def assert_links(self, links):
                entered.set()
                release.wait(10)
                super().assert_links(links)

        j = LinkJournal(str(tmp_path / "l.journal"), sync="none")
        db = WriteBehindLinkDatabase(Slow(), journal=j)
        db.assert_link(L("a", "b", ts=1))
        db.commit()
        entered.wait(10)
        assert j.pending_batches >= 1  # journaled while the flush hangs
        release.set()
        db.drain()
        assert j.pending_batches == 0  # watermark advanced after apply
        db.close()
        assert os.path.getsize(j.path) == 0  # drained close -> empty

    def test_recover_replays_exactly_once(self, tmp_path, monkeypatch):
        """A journaled batch the flusher never applied replays at the
        next open — and a second recovery (or a replay of an already-
        applied batch) changes nothing: the idempotent-assert contract
        is what makes at-least-once redo exactly-once in effect."""
        monkeypatch.setenv("DUKE_FLUSH_RETRIES", "0")

        class Broken(SqliteLinkDatabase):
            def assert_links(self, links):
                raise OSError("disk gone")

        jpath = str(tmp_path / "l.journal")
        spath = str(tmp_path / "l.sqlite")
        db = WriteBehindLinkDatabase(Broken(spath),
                                     journal=LinkJournal(jpath, sync="none"))
        db.assert_link(L("a", "b", conf=0.91, ts=1000))
        db.assert_link(L("c", "d", conf=0.92, ts=1001))
        db.commit()
        deadline = time.monotonic() + 10
        while db.flush_error is None and time.monotonic() < deadline:
            time.sleep(0.01)
        assert db.flush_error is not None  # latched; rows only in journal
        db.close()

        replayed0 = telemetry.RECOVERY_REPLAYED.single().value
        inner = SqliteLinkDatabase(spath)
        db2 = WriteBehindLinkDatabase(inner, journal=LinkJournal(jpath))
        assert db2.recover() == 1
        assert telemetry.RECOVERY_REPLAYED.single().value == replayed0 + 1
        rows = sorted((l.id1, l.id2, l.confidence, l.timestamp)
                      for l in inner.get_all_links())
        assert rows == [("a", "b", 0.91, 1000), ("c", "d", 0.92, 1001)]
        assert os.path.getsize(jpath) == 0  # compacted after replay
        # second recovery: nothing left
        assert db2.recover() == 0
        db2.close()

    def test_flush_retry_heals_transient_error(self, monkeypatch, tmp_path):
        """Satellite: a transient flush failure retries (bounded by
        DUKE_FLUSH_RETRIES) instead of poisoning the wrapper until
        restart; a persistent failure still latches at retries=0."""
        monkeypatch.setenv("DUKE_FLUSH_RETRIES", "3")
        attempts = []

        class Flaky(InMemoryLinkDatabase):
            def assert_links(self, links):
                attempts.append(len(links))
                if len(attempts) == 1:
                    raise OSError("transient EIO")
                super().assert_links(links)

        db = WriteBehindLinkDatabase(Flaky(),
                                     journal=LinkJournal(
                                         str(tmp_path / "a.journal"),
                                         sync="none"))
        db.assert_link(L("a", "b"))
        db.commit()
        db.drain()  # must NOT raise: the retry healed it
        assert db.flush_error is None
        assert len(attempts) == 2  # failed once, succeeded on retry
        assert db.count() == 1
        db.close()

        monkeypatch.setenv("DUKE_FLUSH_RETRIES", "0")

        class Broken(InMemoryLinkDatabase):
            def assert_links(self, links):
                raise OSError("disk gone")

        db2 = WriteBehindLinkDatabase(Broken())
        db2.assert_link(L("c", "d"))
        db2.commit()
        with pytest.raises(RuntimeError, match="flush failed"):
            db2.drain()
        db2.close()

    def test_factory_wires_journal_and_recovers(self, tmp_path, monkeypatch):
        monkeypatch.setenv("DUKE_JOURNAL", "1")  # pin under the =0 CI leg
        monkeypatch.setenv("DUKE_FLUSH_RETRIES", "0")
        d = str(tmp_path / "wl")
        db = create_link_database("h2", d)
        assert isinstance(db, WriteBehindLinkDatabase)
        assert db.journal is not None
        db.assert_link(L("a", "b", ts=5))
        db.commit()
        db.drain()
        db.close()

        # strand a batch: journal-only write, then "crash" (no close)
        j = LinkJournal(os.path.join(d, "linkdatabase.journal"))
        j.append_batch([("x", "y", "inferred", "duplicate", 0.8, 6)])
        j.close()

        db2 = create_link_database("h2", d)  # factory recovery replays
        keys = {l.key() for l in db2.get_all_links()}
        assert keys == {("a", "b"), ("x", "y")}
        assert db2.journal.pending_batches == 0
        db2.close()

    def test_factory_journal_opt_out_keeps_legacy_path(self, tmp_path,
                                                       monkeypatch):
        monkeypatch.setenv("DUKE_JOURNAL", "0")
        db = create_link_database("h2", str(tmp_path / "wl"))
        assert isinstance(db, WriteBehindLinkDatabase)
        assert db.journal is None  # the documented loss window is back
        db.assert_link(L("a", "b"))
        db.commit()
        db.drain()
        assert not os.path.exists(
            str(tmp_path / "wl" / "linkdatabase.journal"))
        db.close()

    def test_opt_out_warns_about_stranded_journal(self, tmp_path,
                                                  monkeypatch, caplog):
        """Flipping journaling off with unapplied batches on disk must
        be loud: the data stays stranded (deliberately — the opt-out
        legs pin the legacy path exactly) until DUKE_JOURNAL=1."""
        d = str(tmp_path / "wl")
        os.makedirs(d)
        j = LinkJournal(os.path.join(d, "linkdatabase.journal"))
        j.append_batch([("x", "y", "inferred", "duplicate", 0.8, 6)])
        j.close()

        import logging

        for knob in ("DUKE_JOURNAL", "DUKE_WRITE_BEHIND"):
            monkeypatch.setenv(knob, "0")
            with caplog.at_level(logging.WARNING, logger="links"):
                caplog.clear()
                db = create_link_database("h2", d)
            assert any("NOT being replayed" in r.getMessage()
                       for r in caplog.records), knob
            db.close()
            monkeypatch.setenv(knob, "1")
        # journal untouched: re-enabling replays it
        monkeypatch.setenv("DUKE_JOURNAL", "1")
        db = create_link_database("h2", d)
        assert {l.key() for l in db.get_all_links()} == {("x", "y")}
        db.close()

    def test_journal_failure_fails_commit_before_ack(self, tmp_path):
        """If the durability point itself fails (journal disk error),
        commit() raises and the batch stays buffered — an unjournaled
        batch must never be acked."""
        j = LinkJournal(str(tmp_path / "l.journal"), sync="none")
        db = WriteBehindLinkDatabase(InMemoryLinkDatabase(), journal=j)
        os.close(j._fd)  # simulate the journal device going away
        j._fd = os.open(os.devnull, os.O_RDONLY)  # writes now fail EBADF-ish
        db.assert_link(L("a", "b"))
        with pytest.raises(OSError):
            db.commit()
        # the batch is still buffered, not lost (the read path surfaces
        # the buffered row once the journal device is repaired)
        os.close(j._fd)
        j._fd = os.open(j.path, os.O_RDWR | os.O_CREAT | os.O_APPEND)
        db.commit()
        db.drain()
        assert db.count() == 1
        db.close()


# -- leader + replica interplay -----------------------------------------------


def test_crash_between_publish_and_flush_converges_leader_and_replica(
        tmp_path, monkeypatch):
    """ISSUE 10 tentpole: a leader crash after
    ``PublishingLinkDatabase.publish`` but before the write-behind flush
    must converge — the replica already folded the batch, and the
    restarted leader's journal replays the same rows, so both serve
    identical link state (timestamps included: rows ride the journal
    verbatim)."""
    monkeypatch.setenv("DUKE_FLUSH_RETRIES", "0")

    class Broken(SqliteLinkDatabase):
        # the flush never lands: the crash window held open
        def assert_links(self, links):
            raise OSError("crashed before flush")

    jpath = str(tmp_path / "l.journal")
    spath = str(tmp_path / "l.sqlite")
    wb = WriteBehindLinkDatabase(Broken(spath),
                                 journal=LinkJournal(jpath, sync="none"))
    replica = ReplicaLinkDatabase()
    pub = PublishingLinkDatabase(wb, lambda seq, rows: replica.apply_ops(
        seq, rows))
    pub.assert_link(L("a", "b", conf=0.93, ts=100))
    pub.assert_link(L("c", "d", conf=0.85, ts=101))
    pub.commit()  # journal append -> (flush will fail) -> publish
    deadline = time.monotonic() + 10
    while wb.flush_error is None and time.monotonic() < deadline:
        time.sleep(0.01)
    assert wb.flush_error is not None
    pub.close()

    # leader restart: journal recovery into a healthy store
    inner = SqliteLinkDatabase(spath)
    wb2 = WriteBehindLinkDatabase(inner, journal=LinkJournal(jpath))
    assert wb2.recover() == 1

    def rows(db):
        return sorted((l.id1, l.id2, l.status.value, l.kind.value,
                       l.confidence, l.timestamp)
                      for l in db.get_all_links())

    assert rows(inner) == rows(replica)  # bit-identical, timestamps too
    wb2.close()


# -- kill differential (subprocess matrix) ------------------------------------


def _run_child(data, *, fault="", start=0, dump=False, close=False,
               backend="host", journal="1", linger=0.0):
    env = dict(os.environ)
    env["DUKE_FAULTS"] = fault  # never inherit a CI chaos spec
    env["DUKE_JOURNAL"] = journal
    env.pop("DUKE_FLUSH_RETRIES", None)
    cmd = [sys.executable, CHILD, "--data", str(data),
           "--backend", backend, "--start", str(start),
           "--batches", str(N_BATCHES), "--linger", str(linger)]
    if dump:
        cmd.append("--dump")
    if close:
        cmd.append("--close")
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=180,
                          env=env)
    acks = [int(line.split()[1]) for line in proc.stdout.splitlines()
            if line.startswith("ACK ")]
    dumps = [json.loads(line[5:]) for line in proc.stdout.splitlines()
             if line.startswith("DUMP ")]
    return proc, acks, (dumps[0] if dumps else None)


def _assert_differential(dump, control):
    assert dump["links"] == control["links"]
    assert dump["feed"] == control["feed"]
    assert dump["store_rows"] == control["store_rows"]
    assert dump["journal_pending"] == 0


@pytest.fixture(scope="module")
def control_dump(tmp_path_factory):
    proc, acks, dump = _run_child(tmp_path_factory.mktemp("ctrl") / "w",
                                  dump=True, close=True)
    assert proc.returncode == 0, proc.stderr
    assert acks == list(range(N_BATCHES)) and dump["links"], proc.stdout
    return dump


# (site, occurrence, deterministic counter minimums in the recovered dump)
CRASH_SITES = [
    ("post_store_put", 4, {}),
    ("post_journal_append", 4, {"replayed": 1}),
    ("pre_flush", 4, {"replayed": 1}),
    ("mid_flush", 4, {"replayed": 1}),
    ("post_flush_pre_truncate", 4, {"replayed": 1}),
    ("mid_journal_write", 4, {"torn": 1}),
]


@pytest.mark.parametrize("site,nth,minimums",
                         CRASH_SITES, ids=[s for s, _, _ in CRASH_SITES])
def test_kill_differential(site, nth, minimums, control_dump, tmp_path):
    """Kill at the site, restart, resend the unacked suffix: the
    recovered link DB and feed equal the uncrashed control."""
    data = tmp_path / "w"
    proc, acks, _ = _run_child(data, fault=f"crash_at={site}:{nth}")
    assert proc.returncode == -signal.SIGKILL, (
        f"child survived the {site} crash site: rc={proc.returncode}\n"
        f"{proc.stdout}\n{proc.stderr}")
    assert len(acks) < N_BATCHES  # it really died mid-corpus

    resume = (max(acks) + 1) if acks else 0
    proc2, _, dump = _run_child(data, start=resume, dump=True, close=True)
    assert proc2.returncode == 0, proc2.stderr
    _assert_differential(dump, control_dump)
    for key, minimum in minimums.items():
        assert dump[key] >= minimum, (key, dump)


def test_kill_differential_journal_off_loses_the_acked_batch(
        control_dump, tmp_path):
    """DUKE_JOURNAL=0 restores the legacy loss window bit-for-bit: a
    crash between ack and flush permanently loses the acked batch's
    links (store rows survive — only the link writes evaporate).  This
    is the documented trade the journal exists to close."""
    data = tmp_path / "w"
    # the LAST batch's flush is the guaranteed-stranded one; the client
    # saw (or is modeled to have seen) every ack, so nothing is resent.
    # linger keeps the process alive for the background flusher to reach
    # the site (the kill lands within milliseconds)
    proc, _, _ = _run_child(
        data, fault=f"crash_at=pre_flush:{N_BATCHES}", journal="0",
        linger=30)
    assert proc.returncode == -signal.SIGKILL
    proc2, _, dump = _run_child(data, start=N_BATCHES, dump=True,
                                close=True, journal="0")
    assert proc2.returncode == 0, proc2.stderr
    assert dump["store_rows"] == control_dump["store_rows"]
    control_links = {tuple(l) for l in control_dump["links"]}
    recovered = {tuple(l) for l in dump["links"]}
    assert recovered < control_links  # strictly lost links: the window
    assert dump["replayed"] == 0 and dump["torn"] == 0


def test_kill_differential_mid_snapshot_save(tmp_path):
    """Crash inside ``snapshot_save``'s tmp-written/not-yet-renamed
    window (graceful shutdown's save): the restart ignores the torn tmp,
    replays the store, and serves the identical link state."""
    ctrl_proc, _, control = _run_child(tmp_path / "c", backend="ann",
                                       dump=True, close=True)
    assert ctrl_proc.returncode == 0, ctrl_proc.stderr

    data = tmp_path / "w"
    proc, acks, _ = _run_child(data, backend="ann",
                               fault="crash_at=mid_snapshot_save:1",
                               close=True)
    assert proc.returncode == -signal.SIGKILL, proc.stdout + proc.stderr
    assert acks == list(range(N_BATCHES))  # died during close, post-ingest
    wl_folder = os.path.join(data, "deduplication", "people")
    leftovers = [f for f in os.listdir(wl_folder) if ".tmp." in f]
    assert leftovers  # the torn tmp is really there

    proc2, _, dump = _run_child(data, backend="ann", start=N_BATCHES,
                                dump=True, close=True)
    assert proc2.returncode == 0, proc2.stderr
    _assert_differential(dump, control)


# -- snapshot integrity -------------------------------------------------------


class TestSnapshotIntegrity:
    def _built_snapshot(self, tmp_path):
        from test_device_matcher import dedup_schema, random_records, run_device

        schema = dedup_schema()
        records = random_records(12, seed=9)
        _, index, _ = run_device(schema, [records])
        path = str(tmp_path / "snap.npz")
        index.snapshot_save(path)
        return schema, index, path

    def _fallbacks(self, reason):
        return telemetry.SNAPSHOT_FALLBACKS.labels(reason=reason).value

    def _fresh(self, schema):
        from sesam_duke_microservice_tpu.core.config import MatchTunables
        from sesam_duke_microservice_tpu.engine.device_matcher import (
            DeviceIndex,
        )

        return DeviceIndex(schema, tunables=MatchTunables())

    def test_truncated_archive_falls_back_with_counter(self, tmp_path):
        schema, index, path = self._built_snapshot(tmp_path)
        raw = open(path, "rb").read()
        open(path, "wb").write(raw[: len(raw) // 2])
        before = self._fallbacks("corrupt")
        assert self._fresh(schema).snapshot_load(
            path, dict(index.records)) is False
        assert self._fallbacks("corrupt") == before + 1

    def test_flipped_byte_falls_back_with_counter(self, tmp_path):
        import zipfile

        schema, index, path = self._built_snapshot(tmp_path)
        # flip one byte inside the LARGEST member's stored data (located
        # through its local header, so the flip is guaranteed to land in
        # payload, not zip padding): the member-CRC layer (corrupt) or
        # the stamped content checksum (checksum) must catch it — never
        # a successful load
        with zipfile.ZipFile(path) as zf:
            info = max(zf.infolist(), key=lambda i: i.compress_size)
        raw = bytearray(open(path, "rb").read())
        nlen = int.from_bytes(
            raw[info.header_offset + 26:info.header_offset + 28], "little")
        elen = int.from_bytes(
            raw[info.header_offset + 28:info.header_offset + 30], "little")
        data_off = info.header_offset + 30 + nlen + elen
        raw[data_off + info.compress_size // 2] ^= 0xFF
        open(path, "wb").write(bytes(raw))
        before = self._fallbacks("corrupt") + self._fallbacks("checksum")
        assert self._fresh(schema).snapshot_load(
            path, dict(index.records)) is False
        assert (self._fallbacks("corrupt")
                + self._fallbacks("checksum")) == before + 1

    def test_checksum_catches_member_substitution(self, tmp_path):
        """A structurally-valid archive whose payload member was swapped
        (every member CRC fine) is exactly what the stamped checksum
        exists for."""
        import zipfile

        import numpy as np

        schema, index, path = self._built_snapshot(tmp_path)
        with zipfile.ZipFile(path) as zf:
            names = zf.namelist()
            arrays = {}
            with np.load(path) as data:
                for key in data.files:
                    arrays[key] = data[key]
        assert "__row_group.npy" in names
        arrays["__row_group"] = arrays["__row_group"] + 1  # swapped member
        np.savez(path, **arrays)
        before = self._fallbacks("checksum")
        assert self._fresh(schema).snapshot_load(
            path, dict(index.records)) is False
        assert self._fallbacks("checksum") == before + 1

    def test_store_drift_counts_content_fallback(self, tmp_path):
        schema, index, path = self._built_snapshot(tmp_path)
        by_id = dict(index.records)
        by_id.pop(next(iter(by_id)))
        before = self._fallbacks("content")
        assert self._fresh(schema).snapshot_load(path, by_id) is False
        assert self._fallbacks("content") == before + 1

    def test_stray_save_tmp_does_not_block_previous_snapshot(self, tmp_path):
        """A crash inside snapshot_save leaves ``<path>.tmp.<pid>[.npz]``
        behind; the previous snapshot at ``path`` must still load."""
        schema, index, path = self._built_snapshot(tmp_path)
        open(path + ".tmp.12345.npz", "wb").write(b"torn garbage")
        fresh = self._fresh(schema)
        assert fresh.snapshot_load(path, dict(index.records)) is True
        assert fresh.corpus.size == index.corpus.size


# -- graceful shutdown + readiness --------------------------------------------


DEDUP_DURABLE_XML = """
<DukeMicroService dataFolder="{folder}">
  <Deduplication name="people">
    <duke>
      <schema>
        <threshold>0.8</threshold>
        <property><name>NAME</name><comparator>levenshtein</comparator><low>0.1</low><high>0.95</high></property>
      </schema>
      <data-source class="io.sesam.dukemicroservice.IncrementalDeduplicationDataSource">
        <param name="dataset-id" value="crm"/>
        <column name="name" property="NAME"/>
      </data-source>
    </duke>
  </Deduplication>
</DukeMicroService>
"""


def _durable_app(tmp_path, backend="host"):
    sc = parse_config(DEDUP_DURABLE_XML.format(folder=tmp_path),
                      env={"MIN_RELEVANCE": "0.05"})
    return DukeApp(sc, backend=backend, persistent=True)


def _ingest(app, n=8):
    wl = app.deduplications["people"]
    batch = [{"_id": str(i), "name": f"person number {i // 2}"}
             for i in range(n)]
    with wl.lock:
        wl.process_batch("crm", batch)
    return wl


def test_graceful_shutdown_leaves_empty_journal_and_warm_snapshot(
        tmp_path, monkeypatch):
    """Satellite: SIGTERM-driven close drains the scheduler and the
    write-behind flush, compacts the journal to empty, and saves the
    corpus snapshot — the next start recovers nothing and loads warm."""
    monkeypatch.setenv("DUKE_JOURNAL", "1")  # pin under the =0 CI leg
    app = _durable_app(tmp_path, backend="ann")
    server = serve(app, port=0, host="127.0.0.1")
    threading.Thread(target=server.serve_forever, daemon=True).start()
    _ingest(app)
    old_term = signal.getsignal(signal.SIGTERM)
    old_int = signal.getsignal(signal.SIGINT)
    try:
        install_shutdown_handlers(app, server)
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.monotonic() + 30
        while not app._closed and time.monotonic() < deadline:
            time.sleep(0.02)
        assert app._closed
        # the close sequence runs on a background thread; wait for its
        # observable outputs rather than the thread handle
        folder = str(tmp_path / "deduplication" / "people")
        journal = os.path.join(folder, "linkdatabase.journal")
        snapshot = os.path.join(folder, "corpus_snapshot.npz")
        while time.monotonic() < deadline:
            if (os.path.exists(snapshot) and os.path.exists(journal)
                    and os.path.getsize(journal) == 0):
                break
            time.sleep(0.05)
        assert os.path.exists(journal) and os.path.getsize(journal) == 0
        assert os.path.exists(snapshot)
    finally:
        signal.signal(signal.SIGTERM, old_term)
        signal.signal(signal.SIGINT, old_int)
        server.shutdown()
        app.close()


def test_close_is_idempotent(tmp_path):
    app = _durable_app(tmp_path)
    _ingest(app)
    app.close()
    app.close()  # second close must be a no-op, not an error


def test_readyz_reports_recovering_during_replay(tmp_path, monkeypatch):
    # pin overlap mode: this asserts the 200-recovering read/write split
    # (the DUKE_RECOVERY_OVERLAP=0 contract is pinned in
    # tests/test_recovery_overlap.py)
    monkeypatch.setenv("DUKE_RECOVERY_OVERLAP", "1")
    app = _durable_app(tmp_path)
    server = serve(app, port=0, host="127.0.0.1")
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        with urllib.request.urlopen(base + "/readyz", timeout=30) as r:
            assert json.loads(r.read())["status"] == "ready"
        with recovery_in_progress():
            ready, checks = app.readiness()
            assert ready is False and checks["recovery_complete"] is False
            # overlapped recovery (ISSUE 15, the default): reads serve
            # the committed prefix, so /readyz answers 200 with the
            # distinct "recovering" status and write_ready down — the
            # 503 window covers only the write path now (the legacy
            # whole-app 503 is pinned under DUKE_RECOVERY_OVERLAP=0 in
            # tests/test_recovery_overlap.py)
            with urllib.request.urlopen(base + "/readyz", timeout=30) as r:
                assert r.headers.get("X-Recovering") == "1"
                body = json.loads(r.read())
            assert body["status"] == "recovering"
            assert body["checks"]["recovery_complete"] is False
            assert body["checks"]["write_ready"] is False
        ready, checks = app.readiness()
        assert ready is True and checks["recovery_complete"] is True
    finally:
        server.shutdown()
        app.close()


def test_journal_metrics_on_scrape(tmp_path, monkeypatch):
    """duke_journal_batches / duke_journal_bytes ride the app collector
    for journaled workloads; the torn/replayed/snapshot counters render
    from the global registry."""
    monkeypatch.setenv("DUKE_JOURNAL", "1")  # pin under the =0 CI leg
    app = _durable_app(tmp_path)
    _ingest(app)
    try:
        wl = app.deduplications["people"]
        wl.link_database.drain()
        body = telemetry.render(app.metrics, telemetry.GLOBAL)
        assert 'duke_journal_batches{kind="deduplication",workload="people"}' in body
        assert 'duke_journal_bytes{kind="deduplication",workload="people"}' in body
        assert "duke_journal_torn_tails_total" in body
        assert "duke_recovery_replayed_total" in body
        assert "duke_snapshot_fallbacks_total" in body
    finally:
        app.close()
