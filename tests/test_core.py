"""Tests for core records, bayes math and cleaners."""

import math

import pytest

from sesam_duke_microservice_tpu.core import bayes
from sesam_duke_microservice_tpu.core import cleaners
from sesam_duke_microservice_tpu.core.comparators import Levenshtein
from sesam_duke_microservice_tpu.core.records import Property, Record


def test_compute_bayes():
    assert bayes.compute_bayes(0.5, 0.5) == pytest.approx(0.5)
    assert bayes.compute_bayes(0.9, 0.9) == pytest.approx(0.81 / (0.81 + 0.01))
    assert bayes.compute_bayes(0.5, 0.9) == pytest.approx(0.9)
    assert bayes.compute_bayes(0.9, 0.1) == pytest.approx(0.5)


def test_combine_probabilities_matches_pairwise_fold():
    probs = [0.93, 0.73, 0.61, 0.12]
    expected = 0.5
    for p in probs:
        expected = bayes.compute_bayes(expected, p)
    assert bayes.combine_probabilities(probs) == pytest.approx(expected, rel=1e-9)


def test_combine_probabilities_extremes_clamped():
    assert bayes.combine_probabilities([1.0]) > 0.999
    assert bayes.combine_probabilities([0.0]) < 0.001
    assert math.isfinite(bayes.probability_logit(1.0))


def test_property_compare_probability():
    prop = Property("NAME", Levenshtein(), low=0.09, high=0.93)
    # identical -> sim 1.0 -> (0.93-0.5)*1 + 0.5 = 0.93
    assert prop.compare_probability("oslo", "oslo") == pytest.approx(0.93)
    # sim 0.75 -> (0.43)*(0.5625) + 0.5
    assert prop.compare_probability("oslo", "osla") == pytest.approx(0.43 * 0.5625 + 0.5)
    # dissimilar -> low
    assert prop.compare_probability("oslo", "reykjavik") == pytest.approx(0.09)
    # no comparator -> neutral
    assert Property("X").compare_probability("a", "b") == 0.5


def test_record_basics():
    r = Record()
    r.add_value("NAME", "norway")
    r.add_value("NAME", "norge")
    r.add_value("EMPTY", "")
    r.add_value("NONE", None)
    assert r.get_values("NAME") == ["norway", "norge"]
    assert r.get_value("NAME") == "norway"
    assert r.get_values("EMPTY") == []
    assert r.get_value("MISSING") is None
    assert not r.is_deleted()
    r.add_value("dukeDeleted", "true")
    assert r.is_deleted()


def test_cleaners():
    assert cleaners.lower_case_normalize("  Ålesund   By ") == "alesund by"
    assert cleaners.trim("  x ") == "x"
    assert cleaners.digits_only("a1b2c3") == "123"
    assert cleaners.family_comma_given("Smith, John") == "john smith"
    assert cleaners.country_name("USA") == "united states"
    assert cleaners.country_name("Norway") == "norway"
    assert cleaners.capital("Mexico City") == "mexico"
    assert cleaners.capital("Oslo (capital)") == "oslo"
    assert cleaners.phone_number("+47 22 33 44 55") == "4722334455"


def test_cleaner_registry():
    c = cleaners.get_cleaner("no.priv.garshol.duke.cleaners.LowerCaseNormalizeCleaner")
    assert c("ABC") == "abc"
    with pytest.raises(KeyError):
        cleaners.get_cleaner("no.such.Cleaner")


def test_regexp_and_chained_cleaners():
    rc = cleaners.RegexpCleaner(r"(\d+)")
    assert rc("abc 123 def") == "123"
    assert rc("no digits") is None
    chain = cleaners.ChainedCleaner(cleaners.trim, cleaners.lower_case_normalize)
    assert chain("  ABC  ") == "abc"
