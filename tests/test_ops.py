"""Differential tests: device kernels (ops.*) vs scalar oracles (core.comparators).

Each batched pairwise kernel must reproduce the host comparator's value for
randomized string pairs (the host implementations are the semantic oracles;
they in turn carry the Duke 1.2 semantics the reference drives — SURVEY.md
section 1 L1).  Strings are kept within ops.features.MAX_CHARS so truncation
(the one documented divergence) doesn't enter.
"""

import random
import string

import numpy as np
import pytest

from sesam_duke_microservice_tpu.core import comparators as C
from sesam_duke_microservice_tpu.core.bayes import combine_probabilities
from sesam_duke_microservice_tpu.ops import features as F
from sesam_duke_microservice_tpu.ops import pairwise as pw
from sesam_duke_microservice_tpu.ops import scoring as S

rng = random.Random(1234)

ALPHABET = string.ascii_lowercase + "0123456789 éøå"


def rand_value(max_len=20, min_len=1):
    n = rng.randint(min_len, max_len)
    return "".join(rng.choice(ALPHABET) for _ in range(n))


def make_pairs(n=300):
    """Mixed pair population: random, near-duplicates, exact, empty."""
    pairs = []
    for _ in range(n):
        a = rand_value()
        roll = rng.random()
        if roll < 0.2:
            b = a  # exact
        elif roll < 0.5 and a:
            # near-duplicate: few random edits
            b = list(a)
            for _ in range(rng.randint(1, 3)):
                op = rng.randint(0, 2)
                pos = rng.randrange(len(b)) if b else 0
                if op == 0 and b:
                    b[pos] = rng.choice(ALPHABET)
                elif op == 1:
                    b.insert(pos, rng.choice(ALPHABET))
                elif b:
                    del b[pos]
            b = "".join(b)
        else:
            b = rand_value()
        if not b:
            b = "x"
        pairs.append((a, b))
    # NOTE: empty values never reach comparators — Record.add_value drops
    # them and the scoring driver masks invalid value slots — so pairs here
    # are always non-empty.
    pairs += [("a", "a"), ("a", "b"), ("ab", "ba"), ("x", "xyzzy")]
    return pairs


def features_for(comparator, values, low=0.3, high=0.9):
    spec = F.PropertyFeatureSpec(
        name="p", kind=F.feature_kind(comparator), low=low, high=high,
        comparator=comparator,
    )
    feats = F.extract_property(spec, [[v] if v else [] for v in values])
    return spec, feats


def _flat(feats, name):
    a = feats[name]
    return np.asarray(a[:, 0]) if a.ndim >= 2 else np.asarray(a)


def _equal_flags(f1, f2):
    return (
        (_flat(f1, "hash_hi") == _flat(f2, "hash_hi"))
        & (_flat(f1, "hash_lo") == _flat(f2, "hash_lo"))
        & _flat(f1, "valid")
        & _flat(f2, "valid")
    )


def run_kernel(comparator, pairs):
    """Score pairs with the device kernel matching the comparator."""
    import jax.numpy as jnp

    v1s = [p[0] for p in pairs]
    v2s = [p[1] for p in pairs]
    spec, f1 = features_for(comparator, v1s)
    _, f2 = features_for(comparator, v2s)
    equal = jnp.asarray(_equal_flags(f1, f2))
    kind = spec.kind
    if kind == F.CHARS:
        if isinstance(comparator, C.JaroWinkler):
            sim = pw.jaro_winkler_sim(
                jnp.asarray(_flat(f1, "chars")), jnp.asarray(_flat(f1, "length")),
                jnp.asarray(_flat(f2, "chars")), jnp.asarray(_flat(f2, "length")),
                equal,
                prefix_scale=comparator.prefix_scale,
                boost_threshold=comparator.boost_threshold,
                max_prefix=comparator.max_prefix,
            )
        else:
            sim = pw.levenshtein_sim(
                jnp.asarray(_flat(f1, "chars")), jnp.asarray(_flat(f1, "length")),
                jnp.asarray(_flat(f2, "chars")), jnp.asarray(_flat(f2, "length")),
                equal,
            )
    elif kind == F.CHARS_WEIGHTED:
        sim = pw.weighted_levenshtein_sim(
            jnp.asarray(_flat(f1, "chars")), jnp.asarray(_flat(f1, "classes")),
            jnp.asarray(_flat(f1, "length")),
            jnp.asarray(_flat(f2, "chars")), jnp.asarray(_flat(f2, "classes")),
            jnp.asarray(_flat(f2, "length")),
            equal,
            digit_weight=comparator.digit_weight,
            letter_weight=comparator.letter_weight,
            other_weight=comparator.other_weight,
        )
    elif kind == F.GRAM_SET:
        sim = pw.qgram_sim(
            jnp.asarray(_flat(f1, "grams")), jnp.asarray(_flat(f1, "gram_count")),
            jnp.asarray(_flat(f2, "grams")), jnp.asarray(_flat(f2, "gram_count")),
            equal, formula=comparator.formula,
        )
    elif kind == F.TOKEN_SET:
        sim = pw.token_set_sim(
            jnp.asarray(_flat(f1, "tokens")), jnp.asarray(_flat(f1, "token_count")),
            jnp.asarray(_flat(f2, "tokens")), jnp.asarray(_flat(f2, "token_count")),
            equal, dice=isinstance(comparator, C.DiceCoefficient),
        )
    elif kind == F.HASH:
        sim = (
            pw.different_sim(equal)
            if isinstance(comparator, C.Different)
            else pw.exact_sim(equal)
        )
    elif kind == F.PHONETIC:
        code_equal = (
            (_flat(f1, "code_hi") == _flat(f2, "code_hi"))
            & (_flat(f1, "code_lo") == _flat(f2, "code_lo"))
        )
        sim = pw.phonetic_sim(
            equal, jnp.asarray(code_equal),
            jnp.asarray(_flat(f1, "code_valid") & _flat(f2, "code_valid")),
        )
    elif kind == F.NUMERIC:
        sim = pw.numeric_sim(
            jnp.asarray(_flat(f1, "number")), jnp.asarray(_flat(f1, "number_valid")),
            jnp.asarray(_flat(f2, "number")), jnp.asarray(_flat(f2, "number_valid")),
            min_ratio=comparator.min_ratio,
        )
    elif kind == F.GEO:
        sim = pw.geoposition_sim(
            jnp.asarray(_flat(f1, "lat")), jnp.asarray(_flat(f1, "lon")),
            jnp.asarray(_flat(f1, "geo_valid")),
            jnp.asarray(_flat(f2, "lat")), jnp.asarray(_flat(f2, "lon")),
            jnp.asarray(_flat(f2, "geo_valid")),
            max_distance=comparator.max_distance,
        )
    else:
        raise AssertionError(kind)
    return np.asarray(sim)


CHAR_COMPARATORS = [
    C.Levenshtein(),
    C.WeightedLevenshtein(),
    C.JaroWinkler(),
]


@pytest.mark.parametrize(
    "comparator",
    CHAR_COMPARATORS + [C.QGram(), C.JaccardIndex(), C.DiceCoefficient(),
                        C.Exact(), C.Different(), C.Soundex(), C.Metaphone(),
                        C.Norphone()],
    ids=lambda c: type(c).__name__,
)
def test_kernel_matches_oracle(comparator):
    pairs = make_pairs()
    got = run_kernel(comparator, pairs)
    want = np.array([comparator.compare(a, b) for a, b in pairs])
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_qgram_formulas():
    pairs = make_pairs(150)
    for formula in ("overlap", "jaccard", "dice"):
        cmp = C.QGram()
        cmp.formula = formula
        got = run_kernel(cmp, pairs)
        want = np.array([cmp.compare(a, b) for a, b in pairs])
        np.testing.assert_allclose(got, want, atol=1e-5)


def test_qgram_q3():
    cmp = C.QGram()
    cmp.q = 3
    pairs = make_pairs(150)
    got = run_kernel(cmp, pairs)
    want = np.array([cmp.compare(a, b) for a, b in pairs])
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_numeric_kernel():
    cmp = C.Numeric()
    cmp.min_ratio = 0.7
    values = ["42", "41", "0", "-5", "5", "abc", "", "1e3", "999.5", "nan", "42"]
    pairs = [(a, b) for a in values for b in values]
    got = run_kernel(cmp, pairs)
    want = np.array([cmp.compare(a, b) for a, b in pairs])
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_geoposition_kernel():
    cmp = C.Geoposition()
    cmp.max_distance = 5000.0
    values = ["59.91,10.75", "59.92,10.76", "40.71,-74.0", "bogus", "", "59.91,10.75"]
    pairs = [(a, b) for a in values for b in values]
    got = run_kernel(cmp, pairs)
    want = np.array([cmp.compare(a, b) for a, b in pairs])
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_levenshtein_distance_exact():
    pairs = make_pairs(200)
    spec, f1 = features_for(C.Levenshtein(), [p[0] for p in pairs])
    _, f2 = features_for(C.Levenshtein(), [p[1] for p in pairs])
    import jax.numpy as jnp

    dist = np.asarray(
        pw.levenshtein_distance(
            jnp.asarray(_flat(f1, "chars")), jnp.asarray(_flat(f1, "length")),
            jnp.asarray(_flat(f2, "chars")), jnp.asarray(_flat(f2, "length")),
        )
    )
    want = np.array([C.levenshtein_distance(a, b) for a, b in pairs])
    np.testing.assert_array_equal(dist, want)


def test_levenshtein_myers_exact():
    # the bit-parallel kernel must agree with the oracle for every pair,
    # including boundary lengths at the full 32-bit word
    import jax.numpy as jnp

    pairs = [(a[:32], b[:32]) for a, b in make_pairs(300)]
    pairs += [
        ("a" * 32, "a" * 31 + "b"),
        ("a" * 32, "a" * 32),
        ("a" * 31, "b" * 32),
        ("a", "b" * 32),
        ("ab" * 16, "ba" * 16),
    ]
    n = len(pairs)
    c1 = np.zeros((n, 32), np.int32)
    c2 = np.zeros((n, 32), np.int32)
    l1 = np.zeros((n,), np.int32)
    l2 = np.zeros((n,), np.int32)
    for i, (a, b) in enumerate(pairs):
        l1[i], l2[i] = len(a), len(b)
        c1[i, : len(a)] = [ord(ch) for ch in a]
        c2[i, : len(b)] = [ord(ch) for ch in b]
    dist = np.asarray(
        pw.levenshtein_distance_myers(
            jnp.asarray(c1), jnp.asarray(l1), jnp.asarray(c2), jnp.asarray(l2)
        )
    )
    want = np.array([C.levenshtein_distance(a, b) for a, b in pairs])
    np.testing.assert_array_equal(dist, want)


# -- the assembled scoring program ------------------------------------------


def test_pair_logits_match_host_bayes():
    """build_pair_logits == host per-pair Bayes over a multi-property schema."""
    import jax.numpy as jnp
    from sesam_duke_microservice_tpu.core.config import DukeSchema
    from sesam_duke_microservice_tpu.core.records import Property

    lev = C.Levenshtein()
    num = C.Numeric()
    num.min_ratio = 0.7
    props = [
        Property("ID", id_property=True),
        Property("name", lev, 0.3, 0.8),
        Property("area", num, 0.1, 0.9),
    ]
    schema = DukeSchema(
        threshold=0.85, maybe_threshold=None, properties=props, data_sources=[]
    )
    plan = F.SchemaFeatures.plan(schema)
    assert not plan.host_props

    n = 40
    recs1 = []
    recs2 = []
    for i in range(n):
        name = rand_value(12)
        recs1.append({"name": [name] if name else [],
                      "area": [str(rng.randint(1, 50))]})
        name2 = name if rng.random() < 0.5 else rand_value(12)
        recs2.append({"name": [name2] if name2 else [],
                      "area": [str(rng.randint(1, 50))]})

    def feats(recs):
        return {
            spec.name: F.extract_property(spec, [r[spec.name] for r in recs])
            for spec in plan.device_props
        }

    f1 = {k: {n2: jnp.asarray(a) for n2, a in d.items()} for k, d in feats(recs1).items()}
    f2 = {k: {n2: jnp.asarray(a) for n2, a in d.items()} for k, d in feats(recs2).items()}

    pair_logits = S.build_pair_logits(plan)
    logits = np.asarray(pair_logits(f1, f2))  # (n, n)
    probs = S.logit_to_probability(logits)

    name_prop = props[1]
    area_prop = props[2]
    for i in range(0, n, 7):
        for j in range(0, n, 7):
            ps = []
            if recs1[i]["name"] and recs2[j]["name"]:
                ps.append(
                    name_prop.compare_probability(
                        recs1[i]["name"][0], recs2[j]["name"][0]
                    )
                )
            ps.append(
                area_prop.compare_probability(
                    recs1[i]["area"][0], recs2[j]["area"][0]
                )
            )
            want = combine_probabilities(ps)
            assert probs[i, j] == pytest.approx(want, abs=1e-4)


def test_multi_value_max_semantics():
    """Multi-valued properties: device takes max prob over value pairs."""
    import jax.numpy as jnp
    from sesam_duke_microservice_tpu.core.records import Property

    lev = C.Levenshtein()
    prop = Property("name", lev, 0.3, 0.8)
    spec = F.PropertyFeatureSpec(
        name="name", kind=F.CHARS, low=0.3, high=0.8, comparator=lev,
        values_per_record=2,
    )
    v1 = [["alpha", "beta"]]
    v2 = [["betta"]]
    f1 = {k: jnp.asarray(v) for k, v in F.extract_property(spec, v1).items()}
    f2 = {k: jnp.asarray(v) for k, v in F.extract_property(spec, v2).items()}
    logit = np.asarray(S._property_logit(spec, f1, f2, 1, 1))[0, 0]
    want = max(
        prop.compare_probability(a, b) for a in v1[0] for b in v2[0]
    )
    got = S.logit_to_probability(logit)
    assert got == pytest.approx(want, abs=1e-5)


def test_host_bound_logit():
    from sesam_duke_microservice_tpu.core.records import Property

    props = [Property("a", C.PersonName(), 0.2, 0.8),
             Property("b", C.PersonName(), 0.4, 0.5)]
    bound = S.host_bound_logit(props)
    assert bound == pytest.approx(S.probability_to_logit(0.8), abs=1e-9)


def test_fnv1a64_batch_matches_scalar():
    """The vectorized ingest hash is bit-identical to the scalar fold
    (device/host equality and snapshot compatibility both ride on it)."""
    import numpy as np

    from sesam_duke_microservice_tpu.ops.features import (
        fnv1a64,
        fnv1a64_batch,
    )

    values = [
        "", "a", "kitten", "a" * 300, "Åse Strøm", "日本語テキスト",
        "\udc80lone-surrogate", "mixed 123 !@#", "\x00nul", "🎉emoji",
        "b" * 4096, "c" * 4097, "d" * 20000,   # bucket edge + scalar fallback
    ]
    got = fnv1a64_batch(values)
    assert got.dtype == np.uint64
    for v, h in zip(values, got):
        assert int(h) == fnv1a64(v), repr(v)


def test_extract_property_batched_hashing_parity():
    """extract_property's vectorized path produces the same tensors as
    direct scalar hashing for every feature kind's hash fields."""
    import numpy as np

    from sesam_duke_microservice_tpu.core import comparators as C
    from sesam_duke_microservice_tpu.ops import features as F

    values = [["kitten", "sitting"], [], ["Åse"], ["a b c d", "x"], [""]]
    values = [[v for v in vs if v] for vs in values]
    for comparator, kind in [
        (C.Levenshtein(), F.CHARS),
        (C.QGram(), F.GRAM_SET),
        (C.JaccardIndex(), F.TOKEN_SET),
        (C.Exact(), F.HASH),
        (C.Soundex(), F.PHONETIC),
    ]:
        spec = F.PropertyFeatureSpec(
            name="p", kind=kind, low=0.3, high=0.9,
            comparator=comparator, values_per_record=2,
        )
        out = F.extract_property(spec, values)
        for i, vs in enumerate(values):
            for k, v in enumerate(vs[:2]):
                hi, lo = F._hash2x32(v)
                assert out["hash_hi"][i, k] == hi, (kind, v)
                assert out["hash_lo"][i, k] == lo, (kind, v)
                assert out["valid"][i, k]
        if kind == F.CHARS:
            assert out["chars"][0, 0, :6].tolist() == [ord(c) for c in "kitten"]
            assert out["length"][2, 0] == 3
