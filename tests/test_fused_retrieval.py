"""Fused Pallas retrieval (matmul + mask + segment-max in VMEM).

Differential tests of ``ops.encoder._fused_retrieval`` /
``ops.pallas_kernels.retrieval_segmax`` against the exact XLA scan on the
CPU interpreter: with SEG=1 the segment reduction is the identity, so the
fused path must reproduce the exact top-C *as a set* (tie order may
differ); with real SEG it must respect every mask (tombstones, groups,
self-exclusion) and hit high recall on random data.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from sesam_duke_microservice_tpu.ops import encoder as E


def _random_problem(n=1024, q=96, d=128, seed=0, groups=False):
    # n must stay a multiple of the scan chunk (512) — the XLA reference
    # path requires it, as production capacities guarantee
    rng = np.random.default_rng(seed)
    corpus = rng.standard_normal((n, d), dtype=np.float32)
    corpus /= np.linalg.norm(corpus, axis=1, keepdims=True)
    queries = rng.standard_normal((q, d), dtype=np.float32)
    queries /= np.linalg.norm(queries, axis=1, keepdims=True)
    cvalid = rng.random(n) > 0.05
    cdel = rng.random(n) < 0.05
    cgroup = (rng.integers(0, 2, n) if groups
              else np.full(n, -1)).astype(np.int32)
    qgroup = (rng.integers(0, 2, q) if groups
              else np.full(q, -1)).astype(np.int32)
    qrow = np.where(rng.random(q) < 0.5,
                    rng.integers(0, n, q), -1).astype(np.int32)
    return (jnp.asarray(queries), jnp.asarray(corpus.astype(E.STORAGE_DTYPE)),
            jnp.asarray(cvalid), jnp.asarray(cdel), jnp.asarray(cgroup),
            jnp.asarray(qgroup), jnp.asarray(qrow))


def _run(monkeypatch, args, *, fused, seg=64, top_c=16, gf=False,
         offset=0):
    monkeypatch.setenv("DUKE_TPU_PALLAS", "1" if fused else "0")
    monkeypatch.setenv("DEVICE_ANN_FUSED", "1" if fused else "0")
    monkeypatch.setenv("DEVICE_ANN_EXACT_TOPK", "0" if fused else "1")
    monkeypatch.setenv("DEVICE_ANN_SEG", str(seg))
    # test corpora are tiny; loosen the bin-count floor (top_c/(1-r)) so
    # the kernel path actually engages (recall is exact on CPU anyway —
    # approx_max_k falls back to a sort).  test_small_corpus_falls_back
    # covers the floor itself.
    monkeypatch.setenv("DEVICE_ANN_RECALL_TARGET", "0.8")
    q, c, cv, cd, cg, qg, qr = args
    if offset:
        qr = jnp.where(qr >= 0, qr + offset, qr)
    sim, idx = E.retrieval_scan(
        q, c, cv, cd, cg, qg, qr, chunk=512, top_c=top_c,
        group_filtering=gf, row_offset=offset,
    )
    return np.asarray(sim), np.asarray(idx)


@pytest.mark.parametrize("gf", [False, True])
def test_seg1_matches_exact_scan(monkeypatch, gf):
    args = _random_problem(groups=gf, seed=3)
    es, ei = _run(monkeypatch, args, fused=False, gf=gf)
    fs, fi = _run(monkeypatch, args, fused=True, seg=1, gf=gf)
    for r in range(ei.shape[0]):
        exact = {(i, round(float(s), 4))
                 for i, s in zip(ei[r], es[r]) if i >= 0}
        fused = {(i, round(float(s), 4))
                 for i, s in zip(fi[r], fs[r]) if i >= 0}
        assert fused == exact


def test_masks_respected_under_segmentation(monkeypatch):
    """No retrieved index may ever be tombstoned/invalid, same-group (when
    filtering), or the query's own row — regardless of SEG binning."""
    args = _random_problem(groups=True, seed=7)
    _, idx = _run(monkeypatch, args, fused=True, seg=8, gf=True)
    _, c, cv, cd, cg, qg, qr = args
    cv, cd, cg = np.asarray(cv), np.asarray(cd), np.asarray(cg)
    for r, row in enumerate(np.asarray(idx)):
        for i in row:
            if i < 0:
                continue
            assert cv[i] and not cd[i]
            assert cg[i] != np.asarray(qg)[r]
            assert i != np.asarray(qr)[r]


def test_row_offset_returns_global_ids(monkeypatch):
    """Sharded use: local kernel rows come back shifted by row_offset and
    self-exclusion works on GLOBAL query rows."""
    args = _random_problem(seed=11)
    off = 4096
    sim, idx = _run(monkeypatch, args, fused=True, seg=4, offset=off)
    live = np.asarray(args[2]) & ~np.asarray(args[3])
    n = live.shape[0]
    qr = np.asarray(args[6])
    for r, row in enumerate(np.asarray(idx)):
        for i in row:
            if i < 0:
                continue
            assert off <= i < off + n
            assert i != (qr[r] + off if qr[r] >= 0 else -1)


def test_recall_high_on_random_data(monkeypatch):
    args = _random_problem(n=2048, q=128, seed=5)
    es, ei = _run(monkeypatch, args, fused=False, top_c=16)
    fs, fi = _run(monkeypatch, args, fused=True, seg=8, top_c=16)
    hits = total = 0
    for r in range(ei.shape[0]):
        exact = {int(i) for i in ei[r] if i >= 0}
        fused = {int(i) for i in fi[r] if i >= 0}
        hits += len(exact & fused)
        total += len(exact)
    assert hits / total > 0.9, hits / total


def test_unsupported_shapes_fall_back(monkeypatch):
    """Shapes outside the kernel's envelope (embedding dim not a lane
    multiple) must quietly use the XLA scan, not crash."""
    args = _random_problem(n=1024, q=8, d=192, seed=2)  # 192 % 128 != 0
    fs, fi = _run(monkeypatch, args, fused=True)
    es, ei = _run(monkeypatch, args, fused=False)
    assert fs.shape == es.shape
    for r in range(ei.shape[0]):
        assert ({int(i) for i in fi[r] if i >= 0}
                == {int(i) for i in ei[r] if i >= 0})


def test_adjacent_duplicate_cluster_not_collapsed(monkeypatch):
    """THE dedup-critical case: duplicates commit together, so they sit in
    ADJACENT corpus rows.  Contiguous binning would collapse the cluster
    into one bin winner (dropping matches and starving the count signal
    the C-escalation loop needs); the strided bins must instead return a
    full top-C of cluster rows, exactly like the exact scan."""
    rng = np.random.default_rng(0)
    n, q, d, top_c = 1024, 96, 128, 16
    corpus = rng.standard_normal((n, d)).astype(np.float32)
    corpus /= np.linalg.norm(corpus, axis=1, keepdims=True)
    base = corpus[100].copy()
    corpus[100:124] = base  # 24 identical ADJACENT rows
    queries = np.tile(base, (q, 1))
    args = (jnp.asarray(queries),
            jnp.asarray(corpus.astype(E.STORAGE_DTYPE)),
            jnp.ones(n, bool), jnp.zeros(n, bool),
            jnp.full(n, -1, np.int32), jnp.full(q, -1, np.int32),
            jnp.full(q, -1, np.int32))
    _, idx = _run(monkeypatch, args, fused=True, seg=8, top_c=top_c)
    cluster = set(range(100, 124))
    for row in np.asarray(idx):
        got = set(int(i) for i in row if i >= 0)
        assert len(got & cluster) == top_c, (
            f"cluster collapsed: only {len(got & cluster)}/{top_c} "
            "retrieved candidates are cluster rows"
        )


def test_small_corpus_falls_back_on_bin_floor(monkeypatch):
    """The bin-count floor (top_c / (1 - recall_target)): a corpus whose
    bin count cannot carry the recall target must use the scan path —
    at 256 bins for C=64 the 10k stresstest silently lost
    0.989-confidence pairs (r5 bringup)."""
    monkeypatch.setenv("DUKE_TPU_PALLAS", "1")
    monkeypatch.setenv("DEVICE_ANN_FUSED", "1")
    monkeypatch.setenv("DEVICE_ANN_SEG", "64")
    monkeypatch.setenv("DEVICE_ANN_RECALL_TARGET", "0.95")
    args = _random_problem(n=16384, q=96, seed=9)
    # nbins = 256 < 64/0.05 = 1280 -> must return None (scan fallback)
    assert E._fused_retrieval(
        *args, top_c=64, group_filtering=False, row_offset=0,
        recall_target=0.95,
    ) is None
    # with a loose target the same shape engages the kernel
    got = E._fused_retrieval(
        *args, top_c=16, group_filtering=False, row_offset=0,
        recall_target=0.8,
    )
    assert got is not None


def test_kernel_path_engages_in_run_config(monkeypatch):
    """Guard against the differential tests silently testing the scan
    fallback: the shared _run() config must reach the Pallas kernel."""
    args = _random_problem(seed=3)
    monkeypatch.setenv("DUKE_TPU_PALLAS", "1")
    monkeypatch.setenv("DEVICE_ANN_SEG", "8")
    monkeypatch.setenv("DEVICE_ANN_RECALL_TARGET", "0.8")
    got = E._fused_retrieval(
        *args, top_c=16, group_filtering=False, row_offset=0,
        recall_target=0.8,
    )
    assert got is not None
