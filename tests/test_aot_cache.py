"""Plan-keyed AOT executable cache (ISSUE 15).

The acceptance contract: a process restart against a populated AOT store
performs ZERO scorer compiles before serving its first scoring batch —
pinned via the ``JIT_COMPILES`` counter in a real two-process
differential — with the event stream bit-identical to the cold run.
Plus the store's key-derivation/invalidation semantics, the call-time
reject fallback, the ``DUKE_JIT_CACHE_MIN_SECS`` knob, and the pre-warm
failure latch.
"""

import json
import os
import pickle
import subprocess
import sys

import numpy as np
import pytest

from sesam_duke_microservice_tpu import telemetry
from sesam_duke_microservice_tpu.utils.jit_cache import (
    AotStore,
    aot_enabled,
    enable_persistent_cache,
    environment_fingerprint,
)

CHILD = os.path.join(os.path.dirname(__file__), "aot_restart_child.py")


def _run_child(aot_dir, xla_dir, *, prewarm="1", aot="1"):
    env = dict(os.environ)
    env.update({
        "DEVICE_CHUNK": "64",
        # one bucket keeps the ladder at 4 entries (2 caps x 2 variants)
        # so the cold arm stays fast on the CPU backend
        "DEVICE_QUERY_BUCKETS": "8",
        "DEVICE_TOP_K": "16",
        "DEVICE_MAX_CHARS": "24",
        "DEVICE_MAX_GRAMS": "24",
        "DEVICE_PREWARM": prewarm,
        "DUKE_AOT": aot,
        "DUKE_AOT_DIR": str(aot_dir),
        "JAX_COMPILATION_CACHE_DIR": str(xla_dir),
        "DUKE_JIT_CACHE_MIN_SECS": "0",
    })
    proc = subprocess.run(
        [sys.executable, CHILD], capture_output=True, text=True,
        timeout=600, env=env,
    )
    assert proc.returncode == 0, proc.stderr
    line = [ln for ln in proc.stdout.splitlines()
            if ln.startswith("RESULT ")][0]
    return json.loads(line[len("RESULT "):])


def test_restart_compiles_zero_scorers(tmp_path):
    """THE acceptance differential: process 1 compiles + serializes the
    ladder; process 2 deserializes everything — zero compiles through
    its first scoring batch (and after the warm-thread join too), same
    events."""
    aot_dir, xla_dir = tmp_path / "aot", tmp_path / "xla"
    cold = _run_child(aot_dir, xla_dir)
    assert cold["warm_compiled"] == 4, cold  # 2 caps x 1 bucket x 2 variants
    assert cold["jit_compiles"] >= 4
    saved = list(aot_dir.glob("*.aotx"))
    assert len(saved) == 4, saved

    warm = _run_child(aot_dir, xla_dir)
    assert warm["jit_compiles_at_first_batch"] == 0, warm
    assert warm["jit_compiles"] == 0, warm  # no miss-fill ran either
    assert warm["aot_loaded"] == 4
    assert warm["warm_compiled"] == 0
    # the scoring outcome is the same program: bit-identical events
    assert warm["events"] == cold["events"]
    # the dispatched blocks were served as program-cache hits
    assert warm["jit_cache_hits"] >= 1


def test_aot_off_leg_still_serves(tmp_path):
    """DUKE_AOT=0 pins the legacy jit path: nothing saved, restart
    compiles again, events unchanged."""
    aot_dir, xla_dir = tmp_path / "aot", tmp_path / "xla"
    cold = _run_child(aot_dir, xla_dir)
    off = _run_child(aot_dir, xla_dir, aot="0")
    assert off["aot_loaded"] == 0
    assert off["jit_compiles"] > 0
    assert off["events"] == cold["events"]


def test_store_roundtrip_and_key_isolation(tmp_path, monkeypatch):
    """Save/load round-trip of a real executable; a different key
    misses; a corrupt entry rejects (counted) and is deleted."""
    import jax
    import jax.numpy as jnp

    monkeypatch.setenv("DUKE_AOT_DIR", str(tmp_path / "store"))
    store = AotStore()
    fn = jax.jit(lambda x: (x * 2.0).sum())
    compiled = fn.lower(
        jax.ShapeDtypeStruct((8,), jnp.float32)).compile()
    key = {"builder": "test", "cap": 8}
    hit0 = telemetry.AOT_LOADS.labels(outcome="hit").value
    miss0 = telemetry.AOT_LOADS.labels(outcome="miss").value
    rej0 = telemetry.AOT_LOADS.labels(outcome="reject").value

    assert store.save(key, compiled) is True
    loaded = store.load(key)
    assert loaded is not None
    out = loaded(np.arange(8, dtype=np.float32))
    assert float(out) == float(compiled(np.arange(8, dtype=np.float32)))
    assert telemetry.AOT_LOADS.labels(outcome="hit").value == hit0 + 1

    # a different key is a different entry: miss
    assert store.load({"builder": "test", "cap": 16}) is None
    assert telemetry.AOT_LOADS.labels(outcome="miss").value == miss0 + 1

    # corrupt the entry: reject, counted, file deleted so a re-save can
    # land
    path = store._path(key)
    with open(path, "wb") as f:
        f.write(b"garbage")
    assert store.load(key) is None
    assert telemetry.AOT_LOADS.labels(outcome="reject").value == rej0 + 1
    assert not os.path.exists(path)

    # a stored-key mismatch under the same filename also rejects
    store.save(key, compiled)
    blob = pickle.loads(open(path, "rb").read())
    doctored = ({"not": "the-key"},) + blob[1:]
    with open(path, "wb") as f:
        f.write(pickle.dumps(doctored))
    assert store.load(key) is None


def test_env_fingerprint_keys_the_path(tmp_path, monkeypatch):
    """Same logical key, different environment fingerprint -> different
    file: a cross-version/cross-backend entry is unreachable, never
    wrong."""
    monkeypatch.setenv("DUKE_AOT_DIR", str(tmp_path))
    a = AotStore()
    b = AotStore()
    b._env = dict(environment_fingerprint())
    b._env["jax"] = "some-other-version"
    key = {"builder": "test", "cap": 8}
    assert a._path(key) != b._path(key)


def test_call_time_reject_falls_back_to_jit(monkeypatch):
    """A registered executable that raises (plan drift after it was
    built) is dropped — counted as a reject — and the jit path serves
    the block; scoring output is unaffected."""
    from test_device_matcher import EventLog, dedup_schema, random_records

    from sesam_duke_microservice_tpu.engine.device_matcher import (
        DeviceIndex,
        DeviceProcessor,
    )

    schema = dedup_schema()
    index = DeviceIndex(schema)
    processor = DeviceProcessor(schema, index, group_filtering=False)
    log = EventLog()
    processor.add_match_listener(log)
    records = random_records(24, seed=7)
    processor.deduplicate(records)
    baseline = list(log.events)

    cache = index.scorer_cache

    def broken(*args):
        raise TypeError("shape drift")

    rej0 = telemetry.AOT_LOADS.labels(outcome="reject").value
    # poison EVERY shape the next batch could dispatch on
    from sesam_duke_microservice_tpu.engine import device_matcher as DM

    cap = index.corpus.capacity
    poisoned = []
    for bucket in DM._QUERY_BUCKETS:
        for from_rows in (True, False):
            akey = (cache._ladder_k(cap), False, from_rows, cap, bucket)
            cache._aot[akey] = broken
            poisoned.append(akey)

    log.events.clear()
    processor.deduplicate(records)  # identical re-ingest: same events
    assert log.events == baseline
    assert telemetry.AOT_LOADS.labels(outcome="reject").value > rej0
    # the dispatched shape's poisoned entry was dropped
    assert any(k not in cache._aot for k in poisoned)


def test_plan_mutation_evicts_registered_executables(monkeypatch):
    """A live plan mutation (value-slot/char growth) re-keys the warm
    fingerprint; registered executables built for the OLD shapes must be
    evicted — a stale entry would otherwise occupy its akey slot, block
    the load pass from refilling it, and die at dispatch as a reject
    with no refill path.  A capacity-only change keeps the map."""
    from test_device_matcher import dedup_schema

    from sesam_duke_microservice_tpu.engine.device_matcher import (
        DeviceIndex,
    )

    monkeypatch.setenv("DEVICE_PREWARM", "0")  # no background compiles
    schema = dedup_schema()
    index = DeviceIndex(schema)
    cache = index.scorer_cache
    cache.prewarm_async(False)
    key0 = cache._warmed
    assert key0 is not None
    sentinel = object()
    cache._aot[(16, False, True, 64, 8)] = sentinel

    # capacity-only change: entries survive (old-cap keys are merely
    # unreachable)
    cache._warmed = (key0[0] * 2,) + key0[1:]
    cache._warmed, moved = key0, cache._warmed
    cache._warmed = moved
    cache.prewarm_async(False)  # back to key0's cap via live corpus
    assert cache._aot.get((16, False, True, 64, 8)) is sentinel

    # plan-shape change: widen one spec's char tensors -> evicted
    index.plan.device_props[0].max_chars = (
        index.plan.device_props[0].chars * 2)
    cache.prewarm_async(False)
    assert cache._warmed != key0
    assert (16, False, True, 64, 8) not in cache._aot


def test_jit_cache_min_secs_knob(tmp_path, monkeypatch):
    """DUKE_JIT_CACHE_MIN_SECS feeds jax's persistence floor (the
    hard-coded 1.0 s meant CPU programs never persisted — untestable in
    CI)."""
    import jax

    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", str(tmp_path))
    monkeypatch.setenv("DUKE_JIT_CACHE_MIN_SECS", "0.25")
    assert enable_persistent_cache() == str(tmp_path)
    assert jax.config.jax_persistent_cache_min_compile_time_secs == 0.25
    monkeypatch.setenv("DUKE_JIT_CACHE_MIN_SECS", "not-a-number")
    enable_persistent_cache()  # malformed -> fail-to-default, no raise
    assert jax.config.jax_persistent_cache_min_compile_time_secs == 1.0


def test_aot_enabled_knob(monkeypatch):
    monkeypatch.delenv("DUKE_AOT", raising=False)
    assert aot_enabled() is True
    monkeypatch.setenv("DUKE_AOT", "0")
    assert aot_enabled() is False


def test_prewarm_failure_counted_and_surfaced(monkeypatch):
    """A warm-thread failure increments duke_prewarm_failures_total and
    latches the error for /healthz detail (a silently-cold replica must
    be diagnosable)."""
    from test_device_matcher import dedup_schema

    from sesam_duke_microservice_tpu.engine.device_matcher import (
        DeviceIndex,
    )

    schema = dedup_schema()
    index = DeviceIndex(schema)
    cache = index.scorer_cache
    fail0 = telemetry.PREWARM_FAILURES.single().value

    monkeypatch.setattr(
        type(cache), "_lower_one",
        lambda self, *a, **k: (_ for _ in ()).throw(
            RuntimeError("boom: no HBM left")),
    )
    # drive the warm body synchronously (thread scheduling out of the
    # assertion path)
    key = (64, tuple(), False)
    cache._warmed = key
    cache._prewarm(False, key, missing=[(64, 8, True)])
    assert telemetry.PREWARM_FAILURES.single().value == fail0 + 1
    assert cache._warm_error is not None
    assert "boom" in cache._warm_error


def test_prewarm_error_in_healthz(tmp_path, monkeypatch):
    """app.prewarm_errors() names the workload and the latched error —
    the /healthz detail surface."""
    from test_crash_recovery import _durable_app

    app = _durable_app(tmp_path, backend="ann")
    try:
        wl = app.deduplications["people"]
        cache = getattr(wl.index, "scorer_cache", None)
        assert cache is not None
        assert app.prewarm_errors() == {}
        cache._warm_error = "RuntimeError('boom')"
        errs = app.prewarm_errors()
        assert errs == {"deduplication/people": "RuntimeError('boom')"}
    finally:
        app.close()


@pytest.mark.skipif(
    os.environ.get("DEVICE_QUERY_BUCKETS") is None,
    reason="needs the conftest small-shape env")
def test_in_process_warm_registers_executables():
    """Within ONE process, warm-thread compiles register for the
    dispatch fast path too (first contact skips the live jit trace)."""
    from test_device_matcher import dedup_schema

    from sesam_duke_microservice_tpu.engine.device_matcher import (
        DeviceIndex,
    )

    schema = dedup_schema()
    index = DeviceIndex(schema)
    cache = index.scorer_cache
    assert cache.supports_aot is True
    # the ladder enumeration covers the speculative next doubling and
    # both variants
    ladder = cache._ladder(64)
    caps = {c for c, _, _ in ladder}
    assert caps == {64, 128}
    assert {fr for _, _, fr in ladder} == {True, False}
