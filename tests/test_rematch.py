"""Ring bulk re-match (engine.rematch) on the virtual 8-device mesh."""

import json
import threading
import urllib.request

import pytest

from sesam_duke_microservice_tpu.core.config import parse_config
from sesam_duke_microservice_tpu.engine.rematch import ring_rematch
from sesam_duke_microservice_tpu.engine.workload import build_workload

XML = """
<DukeMicroService>
  <Deduplication name="people" link-database-type="in-memory">
    <duke>
      <schema>
        <threshold>0.8</threshold>
        <property><name>NAME</name><comparator>levenshtein</comparator><low>0.1</low><high>0.95</high></property>
        <property><name>EMAIL</name><comparator>exact</comparator><low>0.2</low><high>0.95</high></property>
      </schema>
      <data-source class="io.sesam.dukemicroservice.IncrementalDeduplicationDataSource">
        <param name="dataset-id" value="crm"/>
        <column name="name" property="NAME"/>
        <column name="email" property="EMAIL"/>
      </data-source>
    </duke>
  </Deduplication>
</DukeMicroService>
"""


def _batch(n):
    rows = []
    for i in range(n):
        name = f"person number {i - 1 if i % 3 == 2 else i}"
        rows.append({"_id": str(i), "name": name,
                     "email": f"{name.replace(' ', '.')}@x.no"})
    return rows


def _live_links(wl):
    return sorted(
        (r["entity1"], r["entity2"], round(r["confidence"], 9))
        for r in wl.links_since(0) if not r["_deleted"]
    )


def _bulk_import(wl, entities):
    """Index + persist records WITHOUT scoring (the backfill scenario:
    records exist, links don't)."""
    records = wl.datasources["crm"].records_for_batch(entities)
    if wl.record_store is not None:
        wl.record_store.put_many(records)
    for r in records:
        wl.index.index(r)
    wl.index.commit()


@pytest.mark.parametrize("backend", ["device", "sharded-brute"])
def test_ring_rematch_backfills_links_equal_to_scoring(backend):
    env = {"MIN_RELEVANCE": "0.05"}
    sc = parse_config(XML, env=env)
    wc = sc.deduplications["people"]

    # reference: same batch through the normal scoring path
    ref = build_workload(wc, sc, backend="device", persistent=False)
    entities = _batch(30)
    try:
        with ref.lock:
            ref.process_batch("crm", entities)
            want = _live_links(ref)
    finally:
        ref.close()
    assert len(want) >= 8

    # backfill: records imported without scoring, then ring re-match
    wl = build_workload(wc, sc, backend=backend, persistent=False)
    try:
        with wl.lock:
            _bulk_import(wl, entities)
            assert wl.links_since(0) == []
            stats = ring_rematch(wl)
            got = _live_links(wl)
            assert got == want
            assert stats["queries"] == 30
            assert stats["events"] == len(want)
            # idempotence: a second pass asserts nothing new (timestamps
            # unchanged for pollers)
            before = [r["_updated"] for r in wl.links_since(0)]
            ring_rematch(wl)
            assert [r["_updated"] for r in wl.links_since(0)] == before
    finally:
        wl.close()


def test_ring_rematch_respects_tombstones():
    sc = parse_config(XML, env={"MIN_RELEVANCE": "0.05"})
    wl = build_workload(sc.deduplications["people"], sc, backend="device",
                        persistent=False)
    try:
        with wl.lock:
            _bulk_import(wl, [
                {"_id": "1", "name": "Alan Turing", "email": "a@x.no"},
                {"_id": "2", "name": "Alan Turing", "email": "a@x.no"},
                {"_id": "3", "name": "Alan Turing", "email": "a@x.no",
                 "_deleted": True},
            ])
            ring_rematch(wl)
            pairs = {(e1, e2) for e1, e2, _ in _live_links(wl)}
        assert pairs == {("1", "2")}
    finally:
        wl.close()


def test_rematch_http_endpoint():
    import os

    from sesam_duke_microservice_tpu.service.app import DukeApp, serve

    saved = os.environ.get("MIN_RELEVANCE")
    os.environ["MIN_RELEVANCE"] = "0.05"
    try:
        app = DukeApp(parse_config(XML), backend="device", persistent=False)
    finally:
        if saved is None:
            os.environ.pop("MIN_RELEVANCE", None)
        else:
            os.environ["MIN_RELEVANCE"] = saved
    server = serve(app, port=0, host="127.0.0.1")
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        wl = app.deduplications["people"]
        with wl.lock:
            _bulk_import(wl, _batch(12))
        req = urllib.request.Request(
            base + "/deduplication/people/rematch", data=b"", method="POST"
        )
        with urllib.request.urlopen(req, timeout=300) as resp:
            stats = json.loads(resp.read())
        assert stats["queries"] == 12 and stats["events"] >= 4
        with urllib.request.urlopen(
                base + "/deduplication/people?since=0", timeout=60) as resp:
            assert len(json.loads(resp.read())) == stats["events"]
        # unknown workload -> 404
        req = urllib.request.Request(
            base + "/deduplication/nope/rematch", data=b"", method="POST"
        )
        try:
            urllib.request.urlopen(req, timeout=60)
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        server.shutdown()
        app.close()
