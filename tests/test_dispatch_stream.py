"""Streamed multi-host bootstrap (VERDICT r4 #3).

The r4 bootstrap pickled the ENTIRE corpus (snapshot bytes + every
Record) into one TCP message per follower — a ~10+ GB frame at the 10M
flagship scale.  Now the state streams in O(chunk) messages (snapshot
file-chunked, records batched into a follower-local SQLite store behind
a LazyRecordMap), so neither side's transient memory scales with the
corpus.  These tests drive ``Dispatcher._stream_state`` and
``_FollowerSession`` directly in-process on the virtual CPU mesh; the
2-OS-process path (including hot reload over real sockets) is
tests/test_multihost_serving.py.  The 1M-row memory measurement is
benchmarks/bootstrap_bench.py.
"""

import pickle

import pytest

from sesam_duke_microservice_tpu.core.config import parse_config
from sesam_duke_microservice_tpu.engine.workload import build_workload
from sesam_duke_microservice_tpu.parallel import dispatch

from test_sharded_service import DEDUP_XML, _seeded_batch

KEY = ("deduplication", "people")


@pytest.fixture
def frontend_workload():
    sc = parse_config(DEDUP_XML, env={"MIN_RELEVANCE": "0.05"})
    wl = build_workload(sc.deduplications["people"], sc, backend="sharded",
                        persistent=False)
    try:
        with wl.lock:
            wl.process_batch("crm", _seeded_batch(60))
        yield sc, wl
    finally:
        wl.close()


def _stream_frames(wl, *, snap_chunk, rec_batch, monkeypatch):
    monkeypatch.setattr(dispatch, "_SNAP_CHUNK", snap_chunk)
    monkeypatch.setattr(dispatch, "_REC_BATCH", rec_batch)
    d = dispatch.Dispatcher(app=None)
    frames = []
    d.broadcast = frames.append
    d._stream_state(KEY, wl.index)
    return frames


def test_stream_is_chunk_bounded(frontend_workload, monkeypatch):
    """No single message may scale with the corpus: snapshot rides in
    <= snap_chunk pieces, records in <= rec_batch groups."""
    _, wl = frontend_workload
    frames = _stream_frames(wl, snap_chunk=1024, rec_batch=16,
                            monkeypatch=monkeypatch)
    kinds = [op[0] for op in frames]
    assert kinds[0] == "state_begin" and kinds[-1] == "state_end"
    assert kinds.count("snap") >= 2, "snapshot was not actually chunked"
    for op in frames:
        if op[0] == "snap":
            assert len(op[2]) <= 1024
        elif op[0] == "recs":
            assert len(op[2]) <= 16
        # the serialized frame itself stays O(chunk)
        assert len(pickle.dumps(op)) <= 8192 + 65536


def test_follower_assembles_equivalent_replica(frontend_workload,
                                               monkeypatch):
    sc, wl = frontend_workload
    frames = _stream_frames(wl, snap_chunk=8192, rec_batch=16,
                            monkeypatch=monkeypatch)
    sent = []
    sess = dispatch._FollowerSession(sent.append)
    try:
        sess.handle(("bootstrap_begin", "sharded", sc.config_string,
                     dispatch._env_fingerprint()))
        for op in frames:
            sess.handle(op)
        sess.handle(("bootstrap_end",))
        replica = sess.replicas[KEY]
        assert replica.index.corpus.size == wl.index.corpus.size
        assert replica.index.id_to_row == wl.index.id_to_row
        assert replica.index._mirror_digest == wl.index._mirror_digest
        assert set(replica.index.records) == set(wl.index.records)
        # the mirror reads through the follower-local store
        some_id = next(iter(wl.index.records))
        assert (replica.index.records[some_id].get_values("name")
                == wl.index.records[some_id].get_values("name"))

        # post-bootstrap commit replay: same records through both sides
        # keeps the digest chain equal, and the handshake frame says so
        batch = wl.datasources["crm"].records_for_batch(
            _seeded_batch(8, prefix="post")
        )
        sess.handle(("commit", KEY, batch))
        for r in batch:
            wl.index.index(r)
        wl.index.commit()
        assert sent[-1] == dispatch._digest_frame(
            True, wl.index._mirror_digest
        )
        assert replica.index._mirror_digest == wl.index._mirror_digest
    finally:
        sess.close()


def test_reload_rebuilds_replicas(frontend_workload, monkeypatch):
    sc, wl = frontend_workload
    frames = _stream_frames(wl, snap_chunk=8192, rec_batch=16,
                            monkeypatch=monkeypatch)
    sess = dispatch._FollowerSession(lambda frame: None)
    try:
        sess.handle(("bootstrap_begin", "sharded", sc.config_string,
                     dispatch._env_fingerprint()))
        for op in frames:
            sess.handle(op)
        sess.handle(("bootstrap_end",))
        first = sess.replicas[KEY]
        # hot reload: same config streamed again; replicas swap wholesale
        sess.handle(("reload_begin", "sharded", sc.config_string))
        for op in frames:
            sess.handle(op)
        sess.handle(("bootstrap_end",))
        second = sess.replicas[KEY]
        assert second is not first
        assert second.index.corpus.size == wl.index.corpus.size
    finally:
        sess.close()


def test_empty_corpus_streams_no_payload(monkeypatch):
    sc = parse_config(DEDUP_XML, env={})
    wl = build_workload(sc.deduplications["people"], sc, backend="sharded",
                        persistent=False)
    try:
        frames = _stream_frames(wl, snap_chunk=8192, rec_batch=16,
                                monkeypatch=monkeypatch)
        assert [op[0] for op in frames] == ["state_begin", "state_end"]
        sess = dispatch._FollowerSession(lambda frame: None)
        try:
            sess.handle(("bootstrap_begin", "sharded", sc.config_string,
                         dispatch._env_fingerprint()))
            for op in frames:
                sess.handle(op)
            sess.handle(("bootstrap_end",))
            assert sess.replicas[KEY].index.corpus.size == 0
        finally:
            sess.close()
    finally:
        wl.close()
