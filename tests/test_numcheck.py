"""Certified-numerics analysis suite tests (ISSUE 13).

Four layers, mirroring the suite itself:

  * the EFT-discipline linter (DK601..DK604) against seeded-violation
    fixtures AND the live tree (self-scan must be clean);
  * the error-budget ledger (DK611/DK612/DK613/DK690): interval
    evaluator semantics, coverage/headroom/ceiling failures, doc
    staleness, and the repo's own annotations resolving with their
    declared headroom;
  * the compiled-HLO gate: parser/detector units on synthetic HLO text
    (a stripped-commit mutant and an exposed mul->add pair must be
    caught) plus the live dd-core program surviving compilation;
  * the runtime sanitizer (DUKE_NUMCHECK): unit semantics, the live
    engine pipeline running clean under it, and a disagreement
    injection (a deliberately-broken reject bound) being caught.

Plus THE mutation test the acceptance criteria name: deleting any
single ``_f32`` commit from ``ops/dd.py`` must be caught by at least
one static gate.
"""

import re
import sys
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from scripts.dukecheck import CHECKER_NAMES, collect_findings  # noqa: E402
from scripts.dukecheck import budgets, hlocheck, numerics  # noqa: E402
from scripts.dukecheck import core as dk_core  # noqa: E402
from scripts.dukecheck.config import (  # noqa: E402
    DD_BUDGET_MODULE,
    DD_CORE_MODULES,
    DD_KINDS_MODULE,
)

DD_CORE_REL = DD_CORE_MODULES[0]
DD_PROGRAM_REL = "sesam_duke_microservice_tpu/ops/scoring.py"


def mk_module(tmp_path, rel, source):
    path = tmp_path / rel.replace("/", "__")
    path.write_text(source, encoding="utf-8")
    return dk_core.Module(path, rel)


def codes(findings):
    return sorted(f.code for f in findings)


# -- DK601: raw component arithmetic ------------------------------------------


class TestDK601:
    def test_component_arithmetic_flagged(self, tmp_path):
        mod = mk_module(tmp_path, DD_PROGRAM_REL, (
            "import jax.numpy as jnp\n"
            "def _dd_bad(x, y):\n"
            "    return x[0] + y[0]\n"
        ))
        found = numerics.check([mod])
        assert "DK601" in codes(found)

    def test_helper_calls_clean(self, tmp_path):
        mod = mk_module(tmp_path, DD_PROGRAM_REL, (
            "def _dd_good(D, x, y):\n"
            "    s = D.add(x, y)\n"
            "    return D.mul(s, s)\n"
        ))
        assert codes(numerics.check([mod])) == []

    def test_non_dd_functions_unscanned(self, tmp_path):
        # only the configured dd-prefixed functions carry the rule
        mod = mk_module(tmp_path, DD_PROGRAM_REL, (
            "def plain(x, y):\n"
            "    return x[0] + y[0]\n"
        ))
        assert codes(numerics.check([mod])) == []


# -- DK602: commit discipline -------------------------------------------------


_CORE_HEADER = (
    "import jax.numpy as jnp\n"
    "from jax import lax\n"
    "def _f32(x):\n"
    "    return lax.reduce_precision(x, exponent_bits=8, mantissa_bits=23)\n"
)


class TestDK602:
    def test_uncommitted_binop_flagged(self, tmp_path):
        mod = mk_module(tmp_path, DD_CORE_REL, _CORE_HEADER + (
            "def two_sum(a, b):\n"
            "    s = _f32(a + b)\n"
            "    e = b - _f32(s - a)\n"   # outer sub uncommitted
            "    return s, e\n"
        ))
        found = numerics.check([mod])
        assert codes(found) == ["DK602"]
        assert "b - _f32(s - a)" in found[0].message

    def test_committed_chain_clean(self, tmp_path):
        mod = mk_module(tmp_path, DD_CORE_REL, _CORE_HEADER + (
            "def two_sum(a, b):\n"
            "    s = _f32(a + b)\n"
            "    e = _f32(b - _f32(s - a))\n"
            "    return s, e\n"
        ))
        assert codes(numerics.check([mod])) == []

    def test_const_args_and_caps_arithmetic_exempt(self, tmp_path):
        mod = mk_module(tmp_path, DD_CORE_REL, _CORE_HEADER + (
            "TERMS = 11\n"
            "def const(x, like=None):\n"
            "    return jnp.float32(x), jnp.float32(0.0)\n"
            "def log_series(x):\n"
            "    s = const(1.0 / (2 * TERMS + 1))\n"   # host f64, exact
            "    n = TERMS - 1\n"                      # module-constant int
            "    return s, n\n"
        ))
        assert codes(numerics.check([mod])) == []

    def test_host_side_helpers_exempt(self, tmp_path):
        mod = mk_module(tmp_path, DD_CORE_REL, _CORE_HEADER + (
            "import numpy as np\n"
            "def const_pair(x):\n"
            "    hi = np.float32(x)\n"
            "    lo = np.float32(x - float(hi))\n"     # host-side, exact
            "    return hi, lo\n"
        ))
        assert codes(numerics.check([mod])) == []

    def test_inline_ignore_respected(self, tmp_path):
        mod = mk_module(tmp_path, DD_CORE_REL, _CORE_HEADER + (
            "def f(a, b):\n"
            "    return a + b  # dukecheck: ignore[DK602] test fixture\n"
        ))
        by_rel = {mod.rel: mod}
        found = dk_core.filter_suppressed(by_rel, numerics.check([mod]))
        assert codes(found) == []


# -- DK603: inexact float literals --------------------------------------------


class TestDK603:
    def test_inexact_literal_to_lift_flagged(self, tmp_path):
        mod = mk_module(tmp_path, DD_CORE_REL, _CORE_HEADER + (
            "def from_f32(a):\n"
            "    a = jnp.asarray(a, jnp.float32)\n"
            "    return a, jnp.zeros_like(a)\n"
            "def bad(x):\n"
            "    return from_f32(0.1)\n"               # silently rounds
        ))
        found = numerics.check([mod])
        assert "DK603" in codes(found)
        assert "0.1" in [f for f in found if f.code == "DK603"][0].message

    def test_exact_literal_clean(self, tmp_path):
        mod = mk_module(tmp_path, DD_CORE_REL, _CORE_HEADER + (
            "def from_f32(a):\n"
            "    a = jnp.asarray(a, jnp.float32)\n"
            "    return a, jnp.zeros_like(a)\n"
            "def good(x):\n"
            "    return from_f32(0.5)\n"               # f32-exact
        ))
        assert "DK603" not in codes(numerics.check([mod]))

    def test_const_constructor_blessed(self, tmp_path):
        mod = mk_module(tmp_path, DD_PROGRAM_REL, (
            "def _dd_map(D, x):\n"
            "    return D.add(x, D.const(0.1, like=x[0]))\n"
        ))
        assert "DK603" not in codes(numerics.check([mod]))

    def test_inexact_literal_to_dd_op_flagged(self, tmp_path):
        mod = mk_module(tmp_path, DD_PROGRAM_REL, (
            "import jax.numpy as jnp\n"
            "def _dd_map(D, x, h):\n"
            "    return D.add(x, (jnp.full_like(h, 0.3), "
            "jnp.zeros_like(h)))\n"
        ))
        assert "DK603" in codes(numerics.check([mod]))


# -- DK604: budget-table completeness -----------------------------------------


_KINDS_SRC = (
    "CHARS = 'chars'\n"
    "HASH = 'hash'\n"
    "GEO = 'geo'\n"
    "{extra_def}"
    "ALL_KINDS = (CHARS, HASH, GEO{extra_ref})\n"
)
_BUDGET_SRC = (
    "from . import features as F\n"
    "_SIM_ERROR_BOUND = {{F.CHARS: 1e-6, F.HASH: 1e-6, "
    "F.GEO: float('inf'){f32_extra}}}\n"
    "_DD_SIM_OPS = {{F.CHARS: 64.0, F.HASH: 16.0{ops_extra}}}\n"
    "DD_KINDS = (F.CHARS, F.HASH{cert_extra},)\n"
    "DD_FALLBACK_KINDS = (F.GEO{fb_extra},)\n"
)


class TestDK604:
    def _mods(self, tmp_path, *, extra=False, budgeted=False):
        kinds = mk_module(tmp_path, DD_KINDS_MODULE, _KINDS_SRC.format(
            extra_def="FOO = 'foo'\n" if extra else "",
            extra_ref=", FOO" if extra else "",
        ))
        budget = mk_module(tmp_path, DD_BUDGET_MODULE, _BUDGET_SRC.format(
            f32_extra=", F.FOO: 1e-6" if budgeted else "",
            ops_extra=", F.FOO: 32.0" if budgeted else "",
            cert_extra=", F.FOO" if budgeted else "",
            fb_extra="",
        ))
        return [kinds, budget]

    def test_complete_tables_clean(self, tmp_path):
        assert codes(numerics.check(self._mods(tmp_path))) == []

    def test_new_kind_without_entries_flagged(self, tmp_path):
        found = numerics.check(self._mods(tmp_path, extra=True))
        details = {f.detail for f in found}
        assert codes(found).count("DK604") >= 2
        assert "_SIM_ERROR_BOUND:FOO" in details     # no margin entry
        assert "partition:FOO" in details            # no split decision

    def test_new_kind_with_entries_clean(self, tmp_path):
        found = numerics.check(
            self._mods(tmp_path, extra=True, budgeted=True))
        assert codes(found) == []

    def test_certified_kind_missing_ops_budget(self, tmp_path):
        mods = self._mods(tmp_path, extra=True, budgeted=True)
        # drop FOO's _DD_SIM_OPS entry but keep it certified
        src = mods[1].path.read_text().replace(", F.FOO: 32.0", "")
        mods[1] = mk_module(tmp_path, DD_BUDGET_MODULE + "x", src)
        mods[1].rel = DD_BUDGET_MODULE
        found = numerics.check(mods)
        assert "_DD_SIM_OPS:FOO" in {f.detail for f in found}

    def test_unregistered_feature_kind_return_flagged(self, tmp_path):
        """Forgetting the ALL_KINDS registry entry entirely must not
        bypass the gate: any kind ``feature_kind`` can return has to be
        registered, or it ships with margin silently inf."""
        kinds = mk_module(tmp_path, DD_KINDS_MODULE, (
            "CHARS = 'chars'\n"
            "SOUNDEX2 = 'soundex2'\n"
            "ALL_KINDS = (CHARS,)\n"   # SOUNDEX2 forgotten
            "def feature_kind(comparator):\n"
            "    if comparator is None:\n"
            "        return None\n"
            "    if comparator == 's2':\n"
            "        return SOUNDEX2\n"
            "    return CHARS\n"
        ))
        budget = mk_module(tmp_path, DD_BUDGET_MODULE, (
            "from . import features as F\n"
            "_SIM_ERROR_BOUND = {F.CHARS: 1e-6}\n"
            "_DD_SIM_OPS = {F.CHARS: 64.0}\n"
            "DD_KINDS = (F.CHARS,)\n"
            "DD_FALLBACK_KINDS = ()\n"
        ))
        found = numerics.check([kinds, budget])
        assert "ALL_KINDS-unregistered:SOUNDEX2" in {f.detail
                                                     for f in found}

    def test_repo_registry_partition_holds(self):
        """The live tree's tables are complete (the DK604 leg of the
        empty-baseline acceptance criterion)."""
        mods = dk_core.load_modules(REPO_ROOT)
        found = [f for f in numerics.check(mods) if f.code == "DK604"]
        assert found == []


# -- the error-budget ledger --------------------------------------------------


class TestLedger:
    def test_interval_evaluator_outward_rounds(self):
        iv = budgets.eval_interval("1/3", {})
        assert iv.lo < 1 / 3 < iv.hi
        iv = budgets.eval_interval("max(3*u32**2, 12*u32**2)", {})
        assert iv.hi >= 12 * (2.0 ** -24) ** 2

    def test_unknown_symbol_is_dk613(self, tmp_path):
        mod = mk_module(tmp_path, DD_CORE_REL, (
            "# dd-budget: X covers nonsense_symbol\n"
            "X = 1.0\n"
        ))
        _, found = budgets.collect([mod])
        assert codes(found) == ["DK613"]

    def test_uncovered_constant_is_dk611(self, tmp_path):
        mod = mk_module(tmp_path, DD_CORE_REL, (
            "# dd-budget: EPS covers 64 * u32 headroom 2\n"
            "EPS = 2.0 ** -24\n"   # equals 1*u32: covers nothing
        ))
        _, found = budgets.collect([mod])
        assert codes(found) == ["DK611"]

    def test_headroom_policy_enforced(self, tmp_path):
        mod = mk_module(tmp_path, DD_CORE_REL, (
            "# dd-budget: EPS covers 3 * u32 headroom 4\n"
            "EPS = 2.0 ** -22\n"   # 4*u32: covers, but headroom 1.33 < 4
        ))
        _, found = budgets.collect([mod])
        assert codes(found) == ["DK611"]
        assert "headroom" in found[0].message

    def test_ceiling_violation_is_dk612(self, tmp_path):
        mod = mk_module(tmp_path, DD_CORE_REL, (
            "# dd-budget: GUARD covers 2 * u32 below 8 * u32\n"
            "GUARD = 2.0 ** -20\n"   # 16*u32 > the 8*u32 ceiling
        ))
        _, found = budgets.collect([mod])
        assert codes(found) == ["DK612"]

    def test_table_entry_targets_resolve(self, tmp_path):
        # real tables key on F.<KIND> attributes and compose pinned
        # symbols; the fixture mirrors the shape with literals
        mod = mk_module(tmp_path, DD_BUDGET_MODULE, (
            "TBL = {\n"
            "    KEY: 8 * 2.0 ** -23,"
            "  # dd-budget: TBL[KEY] covers 2 * eps32\n"
            "}\n"
        ))
        entries, found = budgets.collect([mod])
        assert found == [] and len(entries) == 1
        assert entries[0].actual == pytest.approx(4.0)

    def test_unknown_code_symbol_is_dk613(self, tmp_path):
        mod = mk_module(tmp_path, DD_BUDGET_MODULE, (
            "TBL = {\n"
            "    KEY: 8 * E,  # dd-budget: TBL[KEY] covers 2 * eps32\n"
            "}\n"
        ))
        _, found = budgets.collect([mod])
        assert codes(found) == ["DK613"]  # `E` is not a pinned symbol

    def test_malformed_headroom_is_dk613_not_a_crash(self, tmp_path):
        mod = mk_module(tmp_path, DD_CORE_REL, (
            "# dd-budget: X covers u32 headroom 1.2e\n"
            "X = 1.0\n"
        ))
        _, found = budgets.collect([mod])
        assert codes(found) == ["DK613"]
        assert "headroom" in found[0].message

    def test_duplicate_target_is_dk613(self, tmp_path):
        mod = mk_module(tmp_path, DD_CORE_REL, (
            "# dd-budget: X covers u32\n"
            "X = 1.0\n"
            "# dd-budget: X covers u64\n"
            "Y = 1.0\n"
        ))
        _, found = budgets.collect([mod])
        assert "DK613" in codes(found)

    def test_repo_ledger_resolves_with_headroom(self):
        mods = dk_core.load_modules(REPO_ROOT)
        entries, found = budgets.collect(mods)
        assert found == [], [f.render() for f in found]
        assert len(entries) >= 14
        by_name = {e.target: e for e in entries}
        assert by_name["DD_EPS"].actual >= 1.25
        assert by_name["_DD_JW_BRANCH_GUARD"].ceiling is not None

    def test_repo_doc_fresh_and_stale_detected(self, tmp_path):
        mods = dk_core.load_modules(REPO_ROOT)
        assert [f.render() for f in budgets.check(mods, REPO_ROOT)] == []
        # a doctored doc must be DK690
        root = tmp_path / "fake_root"
        (root / "docs").mkdir(parents=True)
        doc = REPO_ROOT / budgets.DOC_RELPATH
        (root / budgets.DOC_RELPATH).write_text(
            doc.read_text(encoding="utf-8") + "\ndrift\n", encoding="utf-8")
        found = budgets.check(mods, root)
        assert codes(found) == ["DK690"]


# -- the compiled-HLO gate ----------------------------------------------------


_SYNTH_HLO = """\
HloModule test
%fused_computation {
  %p0 = f32[8]{0} parameter(0)
  %p1 = f32[8]{0} parameter(1)
  %multiply.1 = f32[8]{0} multiply(f32[8]{0} %p0, f32[8]{0} %p1), metadata={source_file="x/ops/dd.py" source_line=128}
  %reduce-precision.1 = f32[8]{0} reduce-precision(f32[8]{0} %multiply.1), exponent_bits=8, mantissa_bits=23
  %add.1 = f32[8]{0} add(f32[8]{0} %reduce-precision.1, f32[8]{0} %p1), metadata={source_file="x/ops/dd.py" source_line=129}
  ROOT %add.2 = f32[8]{0} add(f32[8]{0} %add.1, f32[8]{0} %p0)
}
"""


class TestHloCheck:
    def test_commit_counting(self):
        assert hlocheck.count_commits(_SYNTH_HLO) == 1
        stripped = "\n".join(l for l in _SYNTH_HLO.splitlines()
                             if "reduce-precision" not in l)
        assert hlocheck.count_commits(stripped) == 0

    def test_committed_mul_add_not_exposed(self):
        # the multiply feeds the add THROUGH reduce-precision: clean
        assert hlocheck.exposed_contractions(_SYNTH_HLO) == []

    def test_stripped_commit_mutant_exposes_contraction(self):
        # compiler-strip simulation: rewrite the add to consume the
        # multiply directly (what the optimized HLO shows once a
        # simplifier removes the barrier)
        mutant = _SYNTH_HLO.replace(
            "add(f32[8]{0} %reduce-precision.1", "add(f32[8]{0} %multiply.1")
        exposed = hlocheck.exposed_contractions(mutant)
        assert len(exposed) == 1 and "multiply" in exposed[0]

    def test_non_dd_mul_add_ignored(self):
        # same adjacency WITHOUT dd metadata is outside the discipline
        mutant = _SYNTH_HLO.replace("ops/dd.py", "ops/other.py").replace(
            "add(f32[8]{0} %reduce-precision.1", "add(f32[8]{0} %multiply.1")
        assert hlocheck.exposed_contractions(mutant) == []

    def test_live_dd_core_program_survives_compilation(self):
        """The real ops.dd composite keeps every commit through XLA
        optimization on this backend (the in-suite leg of the gate; the
        CI lint job runs the full program x flag matrix)."""
        fn, args = hlocheck._build_dd_core()
        lowered = fn.lower(*args)
        unopt = hlocheck.count_commits_mlir(lowered.as_text())
        opt_text = lowered.compile().as_text()
        opt = hlocheck.count_commits(opt_text)
        assert unopt > 0
        assert opt >= unopt, (opt, unopt)
        assert hlocheck.exposed_contractions(opt_text) == []


# -- THE mutation test --------------------------------------------------------


def _strip_f32_occurrence(source: str, start: int) -> str:
    """Remove one ``_f32`` commit, keeping its argument (parenthesized,
    so multi-line wrapped expressions stay syntactically valid)."""
    open_paren = source.index("(", start)
    return source[:start] + source[open_paren:]


def test_every_commit_deletion_is_caught(tmp_path):
    """Acceptance criterion: removing any single ``reduce_precision``
    commit from ops/dd.py fails CI via at least one static gate (DK602
    here; the runtime hlocheck DK703/DK701 legs back it up for
    transformations the AST cannot see)."""
    source = (REPO_ROOT / DD_CORE_REL).read_text(encoding="utf-8")
    occurrences = [m.start() for m in re.finditer(r"(?<![\w.])_f32\(",
                                                  source)
                   if not source[:m.start()].endswith("def ")]
    assert len(occurrences) >= 20  # the EFT core is committed throughout
    uncaught = []
    for start in occurrences:
        mutated = _strip_f32_occurrence(source, start)
        mod = mk_module(tmp_path, DD_CORE_REL, mutated)
        found = [f for f in numerics.check([mod])
                 if f.code in ("DK601", "DK602", "DK603")]
        if not found:
            line = source.count("\n", 0, start) + 1
            uncaught.append(f"dd.py:{line}")
    assert not uncaught, (
        "commit deletions no static gate catches: " + ", ".join(uncaught))


# -- the runtime sanitizer ----------------------------------------------------


from sesam_duke_microservice_tpu.utils import numcheck  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_numcheck_state():
    numcheck.reset()
    yield
    # injection tests leave deliberate violations; never leak them into
    # the conftest session gate
    numcheck.reset()


class TestNumcheckUnit:
    def test_agreeing_reject_records_no_violation(self):
        import math
        prob = 1.0 / (1.0 + math.exp(3.0))  # oracle logit == dd total
        numcheck.observe("reject", "a", "b", total=-3.0, prob=prob,
                         threshold=0.8, maybe=0.6, margin=1e-9)
        assert numcheck.violations() == []
        assert numcheck.report()["checked"] == 1

    def test_reject_disagreement_caught(self):
        numcheck.observe("reject", "a", "b", total=-3.0, prob=0.95,
                         threshold=0.8, maybe=0.6, margin=1e-9)
        v = numcheck.violations()
        assert len(v) == 1 and "CERTIFIED-REJECT" in v[0]

    def test_event_disagreement_caught(self):
        numcheck.observe("event", "a", "b", total=4.0, prob=0.30,
                         threshold=0.8, maybe=0.6, margin=1e-9)
        v = numcheck.violations()
        assert len(v) == 1 and "CERTIFIED-EVENT" in v[0]

    def test_margin_bound_violation_caught(self):
        import math
        prob = 1.0 / (1.0 + math.exp(3.0))  # oracle logit = -3
        numcheck.observe("reject", "a", "b", total=-3.5, prob=prob,
                         threshold=0.8, maybe=None, margin=1e-6)
        v = numcheck.violations()
        assert len(v) == 1 and "MARGIN-BOUND" in v[0]

    def test_margin_check_skipped_outside_interior(self):
        # |logit| > 10: reconstruction is ill-conditioned, class-only
        numcheck.observe("reject", "a", "b", total=-40.0, prob=1e-9,
                         threshold=0.8, maybe=None, margin=1e-9)
        assert numcheck.violations() == []

    def test_violations_latch_in_ring(self):
        numcheck.observe("reject", "a", "b", total=-3.0, prob=0.95,
                         threshold=0.8, maybe=None, margin=1e-9)
        for i in range(2000):  # flood: the violation must survive
            numcheck.observe("reject", f"x{i}", "y", total=-5.0,
                             prob=0.01, threshold=0.8, maybe=None,
                             margin=1e-9)
        recent = numcheck.report()["recent"]
        assert any(r["violation"] for r in recent)

    def test_sampling_stride_deterministic(self):
        taken = sum(numcheck.take_sample(0.25) for _ in range(1000))
        assert taken == 250
        assert sum(numcheck.take_sample(0.0) for _ in range(10)) == 0


class TestNumcheckEngine:
    """Live-pipeline legs: the honest engine runs clean under the
    sanitizer; a broken certification bound is caught."""

    def _run(self, monkeypatch):
        # the host-prop schema + person corpus is the proven
        # certified>0 fixture (test_dd's on/off differential)
        from test_dd import _records_with_person, hostprop_schema
        from test_finalize import run_device

        monkeypatch.setenv("DUKE_DEVICE_FINALIZE", "1")
        monkeypatch.setenv("DUKE_NUMCHECK", "1")
        monkeypatch.delenv("DUKE_NUMCHECK_SAMPLE", raising=False)
        schema = hostprop_schema()
        log, proc = run_device(schema, [_records_with_person(40, seed=13)])
        assert proc.stats.pairs_device_certified > 0
        return log

    def test_honest_pipeline_clean_and_observed(self, monkeypatch):
        self._run(monkeypatch)
        rep = numcheck.report()
        assert numcheck.violations() == [], numcheck.violations()
        # certified verdicts existed and were shadow-checked
        assert rep["checked"] > 0

    def test_broken_reject_bound_injection_caught(self, monkeypatch):
        """Disagreement injection: force every survivor to 'certify' as
        a reject — the shadow oracle must catch real events being
        certified away (this is the sanitizer's reason to exist: a
        margin-calculus bug ships silently without it)."""
        from sesam_duke_microservice_tpu.ops import scoring as S

        monkeypatch.setattr(S, "dd_reject_bound",
                            lambda schema, plan: 1e9)
        self._run(monkeypatch)
        v = numcheck.violations()
        assert v and any("CERTIFIED-REJECT" in line for line in v)


# -- DK401 pallas roots (ISSUE 13 satellite) ----------------------------------


def test_pallas_kernel_closures_are_jit_roots(tmp_path):
    """The name-bound ``kernel = functools.partial(_kernel, ...)`` idiom
    every real pl.pallas_call site uses must resolve to the kernel def —
    an impure call inside the kernel body is DK401."""
    from scripts.dukecheck import jitpurity

    mod = mk_module(tmp_path, "sesam_duke_microservice_tpu/ops/pk.py", (
        "import functools, time\n"
        "import jax.experimental.pallas as pl\n"
        "def _tile_kernel(x_ref, o_ref, *, L):\n"
        "    o_ref[...] = x_ref[...] * time.time()\n"
        "def run(x, L):\n"
        "    kernel = functools.partial(_tile_kernel, L=L)\n"
        "    return pl.pallas_call(kernel, out_shape=x)(x)\n"
    ))
    found = jitpurity.check([mod])
    assert any(f.code == "DK401" and "time" in f.message for f in found)


def test_real_pallas_kernels_are_scanned():
    from scripts.dukecheck import jitpurity

    mods = dk_core.load_modules(REPO_ROOT)
    pk = next(m for m in mods if m.rel.endswith("ops/pallas_kernels.py"))
    roots = jitpurity._jit_roots(pk)
    assert "_myers_tile_kernel" in roots  # not just the bare local name


# -- suite-level wiring -------------------------------------------------------


def test_only_filter_scopes_checkers():
    assert "numerics" in CHECKER_NAMES and "hlocheck" in CHECKER_NAMES
    found = collect_findings(REPO_ROOT, only=("numerics",))
    assert [f for f in found if not f.code.startswith("DK6")] == []


def test_repo_numerics_and_budgets_clean():
    """The ISSUE 13 acceptance criterion: the numerics + ledger gates
    pass on the live tree with an EMPTY baseline."""
    found = collect_findings(REPO_ROOT, only=("numerics", "budgets"))
    assert found == [], [f.render() for f in found]


def test_hlocheck_never_baselinable(tmp_path, capsys):
    """A DK7xx baseline entry is rejected outright."""
    import shutil

    from scripts.dukecheck import run as dk_run

    root = tmp_path / "repo"
    (root / "scripts").mkdir(parents=True)
    shutil.copytree(REPO_ROOT / "scripts" / "dukecheck",
                    root / "scripts" / "dukecheck")
    (root / "sesam_duke_microservice_tpu").mkdir()
    (root / "sesam_duke_microservice_tpu" / "__init__.py").write_text("")
    (root / "scripts" / "dukecheck" / "baseline.txt").write_text(
        "DK701 scripts/dukecheck/hlocheck.py :: commit-loss:x:default"
        "  # nope\n")
    rc = dk_run(root, only=("env-knob",))
    out = capsys.readouterr().out
    assert rc == 1
    assert "NEVER" in out and "baselinable" in out
