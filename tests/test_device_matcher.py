"""Differential tests: DeviceProcessor vs the host Processor oracle.

The device path must produce the same matches/matches_perhaps/no_match_for
event stream as the host engine (SURVEY.md section 7 hard part 4 — exact
semantic parity), modulo candidate *retrieval*: the host InvertedIndex can
miss candidates (Lucene-parity recall), the device path is exact brute
force.  So the oracle here is the host Processor run over a brute-force
index that returns everything — same scoring semantics, total recall.
"""

import random

import numpy as np
import pytest

from sesam_duke_microservice_tpu.core import comparators as C
from sesam_duke_microservice_tpu.core.config import DukeSchema, MatchTunables
from sesam_duke_microservice_tpu.core.records import (
    DELETED_PROPERTY_NAME,
    GROUP_NO_PROPERTY_NAME,
    ID_PROPERTY_NAME,
    Property,
    Record,
)
from sesam_duke_microservice_tpu.engine.device_matcher import (
    DeviceIndex,
    DeviceProcessor,
)
from sesam_duke_microservice_tpu.engine.listeners import MatchListener
from sesam_duke_microservice_tpu.engine.processor import Processor
from sesam_duke_microservice_tpu.index.base import CandidateIndex


class BruteForceIndex(CandidateIndex):
    """Total-recall host index: every live record is a candidate."""

    def __init__(self):
        self.records = {}
        self.indexing_disabled = False

    def index(self, record):
        if not self.indexing_disabled:
            self.records[record.record_id] = record

    def commit(self):
        pass

    def find_record_by_id(self, record_id):
        return self.records.get(record_id)

    def find_candidate_matches(self, record, group_filtering=False):
        group = record.get_value(GROUP_NO_PROPERTY_NAME)
        out = []
        for r in self.records.values():
            if r.get_value(DELETED_PROPERTY_NAME) == "true":
                continue
            if group_filtering and r.get_value(GROUP_NO_PROPERTY_NAME) == group:
                continue
            out.append(r)
        return out

    def delete(self, record):
        self.records.pop(record.record_id, None)

    def set_indexing_disabled(self, disabled):
        self.indexing_disabled = disabled


class EventLog(MatchListener):
    def __init__(self):
        self.events = []

    def matches(self, r1, r2, confidence):
        self.events.append(("match", r1.record_id, r2.record_id, round(confidence, 5)))

    def matches_perhaps(self, r1, r2, confidence):
        self.events.append(("maybe", r1.record_id, r2.record_id, round(confidence, 5)))

    def no_match_for(self, record):
        self.events.append(("none", record.record_id))

    def match_set(self):
        return {e for e in self.events if e[0] != "none"}

    def none_set(self):
        return {e for e in self.events if e[0] == "none"}


def make_record(rid, group=None, **props):
    r = Record()
    r.add_value(ID_PROPERTY_NAME, rid)
    if group is not None:
        r.add_value(GROUP_NO_PROPERTY_NAME, str(group))
    for k, v in props.items():
        if isinstance(v, list):
            for item in v:
                r.add_value(k, item)
        else:
            r.add_value(k, v)
    return r


NAMES = [
    "acme corp", "acme corporation", "globex", "globex inc", "initech",
    "initech llc", "umbrella", "umbrela", "stark industries", "stark ind",
    "wayne enterprises", "wayne ent", "hooli", "hooli xyz", "pied piper",
]
CITIES = ["oslo", "bergen", "trondheim", "stavanger", "tromso"]


def random_records(n, seed, with_group=False):
    rng = random.Random(seed)
    records = []
    for i in range(n):
        base = rng.choice(NAMES)
        # perturb to create near-duplicates at a known rate
        if rng.random() < 0.4:
            pos = rng.randrange(len(base))
            base = base[:pos] + rng.choice("abcdefgh") + base[pos + 1:]
        rec = make_record(
            f"r{i}",
            group=(1 + i % 2) if with_group else None,
            name=base,
            city=rng.choice(CITIES),
            amount=str(rng.choice([100, 200, 200, 300, 1000])),
        )
        records.append(rec)
    return records


def dedup_schema(threshold=0.8, maybe=None):
    numeric = C.Numeric()
    numeric.min_ratio = 0.5
    return DukeSchema(
        threshold=threshold,
        maybe_threshold=maybe,
        properties=[
            Property(ID_PROPERTY_NAME, id_property=True),
            Property("name", C.Levenshtein(), 0.3, 0.9),
            Property("city", C.Exact(), 0.4, 0.8),
            Property("amount", numeric, 0.4, 0.7),
        ],
        data_sources=[],
    )


def run_host(schema, batches, group_filtering=False):
    index = BruteForceIndex()
    proc = Processor(schema, index, group_filtering=group_filtering)
    log = EventLog()
    proc.add_match_listener(log)
    for batch in batches:
        proc.deduplicate(batch)
    return log


def run_device(schema, batches, group_filtering=False):
    index = DeviceIndex(schema, tunables=MatchTunables())
    proc = DeviceProcessor(schema, index, group_filtering=group_filtering)
    log = EventLog()
    proc.add_match_listener(log)
    for batch in batches:
        proc.deduplicate(batch)
    return log, index, proc


class TestDeviceVsHostParity:
    def test_small_batch_exact_events(self):
        schema = dedup_schema()
        records = random_records(40, seed=7)
        host = run_host(schema, [records])
        device, _, _ = run_device(schema, [records])
        assert device.match_set() == host.match_set()
        assert device.none_set() == host.none_set()

    def test_multi_batch_incremental(self):
        schema = dedup_schema()
        b1 = random_records(30, seed=1)
        b2 = random_records(25, seed=2)
        # distinct ids for the second batch
        for i, r in enumerate(b2):
            r.set_values(ID_PROPERTY_NAME, [f"s{i}"])
        host = run_host(schema, [b1, b2])
        device, _, _ = run_device(schema, [b1, b2])
        assert device.match_set() == host.match_set()

    def test_maybe_threshold_events(self):
        schema = dedup_schema(threshold=0.92, maybe=0.6)
        records = random_records(35, seed=3)
        host = run_host(schema, [records])
        device, _, _ = run_device(schema, [records])
        assert device.match_set() == host.match_set()

    def test_group_filtering_record_linkage(self):
        schema = dedup_schema()
        records = random_records(40, seed=11, with_group=True)
        host = run_host(schema, [records], group_filtering=True)
        device, _, _ = run_device(schema, [records], group_filtering=True)
        assert device.match_set() == host.match_set()

    def test_missing_group_raises_under_group_filtering(self):
        # host-engine parity: InvertedIndex raises when a record lacks
        # dukeGroupNo in record-linkage mode; the device path must match
        schema = dedup_schema()
        with_group = make_record("a", group=1, name="acme", city="oslo",
                                 amount="1")
        without_group = make_record("b", name="acme", city="oslo", amount="1")
        index = DeviceIndex(schema)
        proc = DeviceProcessor(schema, index, group_filtering=True)
        proc.add_match_listener(EventLog())
        proc.deduplicate([with_group])
        with pytest.raises(ValueError, match="dukeGroupNo"):
            proc.deduplicate([without_group])

    def test_reindex_same_id_replaces(self):
        schema = dedup_schema()
        r1 = make_record("a", name="acme corp", city="oslo", amount="100")
        r2 = make_record("b", name="acme corp", city="oslo", amount="100")
        updated = make_record("a", name="zzzz totally different", city="tromso",
                              amount="9999")
        device, index, proc = run_device(schema, [[r1, r2]])
        assert {(e[1], e[2]) for e in device.match_set()} == {("a", "b"), ("b", "a")}
        # update record a: must stop matching b
        log2 = EventLog()
        proc.listeners = [log2]
        proc.deduplicate([updated])
        assert all(e[0] == "none" for e in log2.events)
        # corpus has a tombstoned row
        assert index.corpus.row_valid.sum() == 2

    def test_deleted_records_excluded(self):
        schema = dedup_schema()
        r1 = make_record("a", name="acme corp", city="oslo", amount="100")
        r2 = make_record("b", name="acme corp", city="oslo", amount="100")
        dead = make_record("a", name="acme corp", city="oslo", amount="100")
        dead.add_value(DELETED_PROPERTY_NAME, "true")
        device, index, proc = run_device(schema, [[r1, r2]])
        assert len(device.match_set()) > 0
        # workload flow (engine.workload.process_batch): deleted records are
        # tombstoned via index+commit, never passed through deduplicate()
        index.index(dead)
        index.commit()
        # the deleted record stays resolvable by id (GET feed point lookups)
        assert index.find_record_by_id("a") is not None
        # and is excluded as a candidate for future queries
        log3 = EventLog()
        proc.listeners = [log3]
        proc.deduplicate([make_record("c", name="acme corp", city="oslo",
                                      amount="100")])
        matched = {e[2] for e in log3.match_set()}
        assert "a" not in matched
        assert "b" in matched

    def test_k_escalation_many_duplicates(self):
        # 100 identical records: every query has 99 candidates above the
        # bound, forcing K-escalation past the initial 64
        schema = dedup_schema()
        records = [
            make_record(f"r{i}", name="acme corp", city="oslo", amount="100")
            for i in range(100)
        ]
        host = run_host(schema, [records])
        device, _, _ = run_device(schema, [records])
        assert device.match_set() == host.match_set()
        assert len(device.match_set()) == 100 * 99

    def test_multi_value_records(self):
        # device plan v=1 truncates value lists; use v=2 to hold both values
        schema = dedup_schema()
        records = [
            make_record("a", name=["acme corp", "acme inc"], city="oslo",
                        amount="100"),
            make_record("b", name="acme inc", city="oslo", amount="100"),
            make_record("c", name="nothing alike", city="bergen", amount="777"),
        ]
        host = run_host(schema, [records])
        index = DeviceIndex(schema, values_per_record=2)
        proc = DeviceProcessor(schema, index)
        log = EventLog()
        proc.add_match_listener(log)
        proc.deduplicate(records)
        assert log.match_set() == host.match_set()

    def test_multi_value_auto_grow(self):
        # VERDICT round-1 item 3: with the default (auto-sized) value axis a
        # record whose *second* value is the matching one must be visible to
        # device pruning — events equal the host engine with no explicit
        # values_per_record.
        schema = dedup_schema()
        records = [
            make_record("a", name=["zzz unrelated", "acme inc"], city="oslo",
                        amount="100"),
            make_record("b", name="acme inc", city="oslo", amount="100"),
            make_record("c", name="nothing alike", city="bergen", amount="777"),
        ]
        host = run_host(schema, [records])
        device, index, _ = run_device(schema, [records])
        assert device.match_set() == host.match_set()
        assert device.none_set() == host.none_set()
        spec = next(s for s in index.plan.device_props if s.name == "name")
        assert spec.v == 2

    def test_multi_value_growth_rebuilds_existing_corpus(self):
        # growth arriving in a LATER batch must widen already-indexed rows:
        # record "a" (indexed single-valued) then "b" whose 2nd value matches
        # "a"; plus the b->a direction only works if a's tensors survived the
        # rebuild.
        schema = dedup_schema()
        b1 = [
            make_record("a", name="acme inc", city="oslo", amount="100"),
            make_record("x", name="completely other", city="tromso",
                        amount="5"),
        ]
        b2 = [
            make_record("b", name=["zzz unrelated", "acme inc"], city="oslo",
                        amount="100"),
        ]
        host = run_host(schema, [b1, b2])
        device, index, _ = run_device(schema, [b1, b2])
        assert device.match_set() == host.match_set()
        assert index.corpus.size == 3  # rebuild dropped no rows
        # three or more values in a later batch grows again (power of two)
        b3 = [make_record("d", name=["q1", "q2", "acme inc"], city="oslo",
                          amount="100")]
        host2 = run_host(schema, [b1, b2, b3])
        device2, _, _ = run_device(schema, [b1, b2, b3])
        assert device2.match_set() == host2.match_set()

    def test_multi_value_transform_query_widens_query_side_only(self):
        # a non-indexed query (http-transform path: from_rows=False) whose
        # 2nd value is the matching one scores via a wider QUERY value axis;
        # the corpus plan must not widen for a transient probe
        schema = dedup_schema()
        corpus = [
            make_record("a", name="acme inc", city="oslo", amount="100"),
            make_record("x", name="other thing", city="tromso", amount="5"),
        ]
        _, index, _ = run_device(schema, [corpus])
        probe = make_record("probe", name=["zzz unrelated", "acme inc"],
                            city="oslo", amount="100")
        cands = index.find_candidate_matches(probe)
        assert "a" in {c.record_id for c in cands}
        assert all(s.v == 1 for s in index.plan.device_props)

    def test_host_only_comparator_hybrid(self):
        # PersonNameComparator has no device kernel -> host-prop hybrid path
        class Weird:
            def compare(self, v1, v2):
                return 1.0 if v1[::-1] == v2 else 0.0

        schema = DukeSchema(
            threshold=0.75,
            maybe_threshold=None,
            properties=[
                Property(ID_PROPERTY_NAME, id_property=True),
                Property("name", C.Levenshtein(), 0.3, 0.9),
                Property("code", Weird(), 0.2, 0.8),
            ],
            data_sources=[],
        )
        records = [
            make_record("a", name="acme corp", code="abc"),
            make_record("b", name="acme corp", code="cba"),
            make_record("c", name="acme corp", code="xyz"),
            make_record("d", name="other thing", code="zyx"),
        ]
        host = run_host(schema, [records])
        device, index, _ = run_device(schema, [records])
        assert len(index.plan.host_props) == 1
        assert device.match_set() == host.match_set()
        assert device.none_set() == host.none_set()

    def test_find_candidate_matches_interface(self):
        schema = dedup_schema()
        records = random_records(20, seed=5)
        _, index, _ = run_device(schema, [records])
        probe = make_record("probe", name=records[0].get_value("name"),
                            city=records[0].get_value("city"),
                            amount=records[0].get_value("amount"))
        cands = index.find_candidate_matches(probe)
        assert records[0].record_id in {c.record_id for c in cands}


class TestDeviceCorpus:
    def test_capacity_doubles_and_preserves(self):
        schema = dedup_schema()
        index = DeviceIndex(schema)
        proc = DeviceProcessor(schema, index)
        proc.add_match_listener(EventLog())
        for start in range(0, 600, 200):
            batch = [
                make_record(f"n{i}", name=f"name {i}", city="oslo", amount="1")
                for i in range(start, start + 200)
            ]
            proc.deduplicate(batch)
        assert index.corpus.size == 600
        assert index.corpus.capacity >= 600
        assert index.corpus.capacity % 512 == 0
        assert index.corpus.row_valid[:600].all()


    def test_prewarm_compiles_ladder_and_scoring_unchanged(self, monkeypatch):
        """Background pre-warm (enabled explicitly; conftest disables it for
        suite speed) compiles without error and scoring results match an
        un-warmed index."""
        monkeypatch.setenv("DEVICE_PREWARM", "1")
        schema = dedup_schema()
        records = random_records(40, seed=7)

        index = DeviceIndex(schema)
        proc = DeviceProcessor(schema, index)
        log = EventLog()
        proc.add_match_listener(log)
        proc.deduplicate(records)
        cache = index.scorer_cache
        assert cache._warm_thread is not None
        cache._warm_thread.join(timeout=120)
        assert not cache._warm_thread.is_alive()
        # the warm must have actually compiled (a silently-failing prewarm
        # would leave the feature dead while scoring still works); it
        # compiles PRIVATE jit instances (shared-instance tracing races the
        # main thread), so success is observed via the compile counter
        assert cache._warm_compiled > 0

        monkeypatch.setenv("DEVICE_PREWARM", "0")
        index2 = DeviceIndex(schema)
        proc2 = DeviceProcessor(schema, index2)
        log2 = EventLog()
        proc2.add_match_listener(log2)
        proc2.deduplicate(records)
        assert log.match_set() == log2.match_set()


    def test_initial_capacity_presizing(self, monkeypatch):
        """DEVICE_INITIAL_CAPACITY pre-allocates the corpus at the target
        (rounded to the chunk) so near-HBM-scale corpora never pay the
        doubling transient; appends below the pre-size never grow."""
        from sesam_duke_microservice_tpu.engine import device_matcher as dm

        # above _CHUNK regardless of the env so the assertion can only be
        # satisfied by the pre-sizing path, never by the default minimum
        presize = 3 * dm._CHUNK - 1
        monkeypatch.setattr(dm, "_INITIAL_CAPACITY", presize)
        schema = dedup_schema()
        index = DeviceIndex(schema)
        proc = DeviceProcessor(schema, index)
        proc.add_match_listener(EventLog())
        proc.deduplicate(random_records(10, seed=1))
        assert index.corpus.capacity == 3 * dm._CHUNK
        proc.deduplicate(random_records(60, seed=2))
        assert index.corpus.capacity == 3 * dm._CHUNK  # no growth below it


class TestSnapshot:
    def test_snapshot_roundtrip(self, tmp_path):
        schema = dedup_schema()
        records = random_records(30, seed=21)
        log1, index, proc = run_device(schema, [records])
        path = str(tmp_path / "snap.npz")
        index.snapshot_save(path)

        by_id = dict(index.records)
        index2 = DeviceIndex(schema, tunables=MatchTunables())
        assert index2.snapshot_load(path, by_id) is True
        assert index2.corpus.size == index.corpus.size
        assert index2.id_to_row == index.id_to_row
        # matching over the restored corpus equals matching over the original
        proc2 = DeviceProcessor(schema, index2)
        log2 = EventLog()
        proc2.add_match_listener(log2)
        probe = random_records(10, seed=77)
        for i, r in enumerate(probe):
            r.set_values("ID", [f"p{i}"])
        proc2.deduplicate(probe)

        log3 = EventLog()
        proc.listeners[:] = [log3]
        probe2 = random_records(10, seed=77)
        for i, r in enumerate(probe2):
            r.set_values("ID", [f"p{i}"])
        proc.deduplicate(probe2)
        assert log2.match_set() == log3.match_set()

    def test_snapshot_carries_grown_value_slots(self, tmp_path):
        # a snapshot written after value-slot auto-growth must restore into
        # a fresh index (which starts at v=1) by adopting the stored widths
        schema = dedup_schema()
        records = [
            make_record("a", name=["zzz unrelated", "acme inc"], city="oslo",
                        amount="100"),
            make_record("b", name="acme inc", city="oslo", amount="100"),
        ]
        _, index, _ = run_device(schema, [records])
        path = str(tmp_path / "snap.npz")
        index.snapshot_save(path)

        index2 = DeviceIndex(schema, tunables=MatchTunables())
        assert index2.snapshot_load(path, dict(index.records)) is True
        spec = next(s for s in index2.plan.device_props if s.name == "name")
        assert spec.v == 2
        # matching over the restored corpus still sees the 2nd value
        proc2 = DeviceProcessor(schema, index2)
        log2 = EventLog()
        proc2.add_match_listener(log2)
        proc2.deduplicate([make_record("p", name="acme inc", city="oslo",
                                       amount="100")])
        assert ("match", "p", "a") in {e[:3] for e in log2.match_set()}

    def test_snapshot_rejected_on_store_drift(self, tmp_path):
        schema = dedup_schema()
        records = random_records(10, seed=5)
        _, index, _ = run_device(schema, [records])
        path = str(tmp_path / "snap.npz")
        index.snapshot_save(path)

        by_id = dict(index.records)
        by_id.pop(next(iter(by_id)))  # store lost a record -> stale snapshot
        index2 = DeviceIndex(schema, tunables=MatchTunables())
        assert index2.snapshot_load(path, by_id) is False
        assert index2.corpus.size == 0

    def test_snapshot_rejected_on_schema_change(self, tmp_path):
        schema = dedup_schema()
        records = random_records(10, seed=5)
        _, index, _ = run_device(schema, [records])
        path = str(tmp_path / "snap.npz")
        index.snapshot_save(path)

        other = dedup_schema(threshold=0.9)
        other.properties[1].high = 0.5  # changed probability map
        index2 = DeviceIndex(other, tunables=MatchTunables())
        assert index2.snapshot_load(path, dict(index.records)) is False

    def test_snapshot_rejected_on_record_content_change(self, tmp_path):
        schema = dedup_schema()
        records = random_records(10, seed=5)
        _, index, _ = run_device(schema, [records])
        path = str(tmp_path / "snap.npz")
        index.snapshot_save(path)

        # same ids, but one record's VALUE changed in the store after the
        # snapshot was written (update persisted, then crash before re-save)
        by_id = dict(index.records)
        changed = make_record(records[0].record_id, name="totally different",
                              city="oslo", amount="1")
        by_id[records[0].record_id] = changed
        index2 = DeviceIndex(schema, tunables=MatchTunables())
        assert index2.snapshot_load(path, by_id) is False


def test_per_property_char_width_growth(monkeypatch):
    """VERDICT r3 #5: one long-text property must widen only its OWN char
    tensors (riding the wide/scan-DP kernels) while short properties keep
    the narrow Myers path — and links must equal the host engine's for
    differences that only appear deep in the long value."""
    monkeypatch.delenv("DEVICE_MAX_CHARS", raising=False)
    from sesam_duke_microservice_tpu.core import comparators as C
    from sesam_duke_microservice_tpu.core.config import DukeSchema
    from sesam_duke_microservice_tpu.core.records import (
        ID_PROPERTY_NAME,
        Property,
        Record,
    )
    from sesam_duke_microservice_tpu.engine.device_matcher import (
        DeviceIndex,
        DeviceProcessor,
    )
    from sesam_duke_microservice_tpu.engine.processor import Processor
    from sesam_duke_microservice_tpu.index.inverted import InvertedIndex
    from sesam_duke_microservice_tpu.core.config import MatchTunables

    schema = DukeSchema(
        threshold=0.75, maybe_threshold=None,
        properties=[
            Property(ID_PROPERTY_NAME, id_property=True),
            Property("name", C.Levenshtein(), 0.3, 0.9),
            Property("desc", C.Levenshtein(), 0.35, 0.8),
            Property("ssn", C.Exact(), 0.4, 0.85),
        ],
        data_sources=[],
    )

    # long descriptions that agree except deep past the default width —
    # a fixed narrow width would prune on identical prefixes (length kept
    # under DEVICE_DEMOTE_CHARS so this exercises GROWTH; demotion has
    # its own test below)
    base = ("the quick brown fox jumps over the lazy dog again and "
            "again while the band plays on " * 2)           # ~170 chars
    variant = base[:-40] + "completely different ending here lately"
    assert 100 < len(base) <= 256 and 100 < len(variant) <= 256

    def make(rid, name, desc, ssn):
        r = Record()
        r.add_value(ID_PROPERTY_NAME, f"d__{rid}")
        r.add_value("name", name)
        r.add_value("desc", desc)
        r.add_value("ssn", ssn)
        return r

    records = [
        make("1", "kari nordmann", base, "111"),
        make("2", "kari nordmann", base, "111"),          # true dup of 1
        make("3", "ola hansen", variant, "222"),          # deep-tail diff
        make("4", "ola hansen", variant, "222"),          # true dup of 3
        make("5", "per olsen", "a genuinely mid length description "
             "that stays well under the demotion threshold", "333"),
    ]

    class Collector:
        def __init__(self):
            self.pairs = {}

        def batch_ready(self, n):
            pass

        def matches(self, r1, r2, conf):
            self.pairs[tuple(sorted((r1.record_id, r2.record_id)))] = round(
                conf, 9
            )

        def matches_perhaps(self, r1, r2, conf):
            pass

        def no_match_for(self, r):
            pass

        def batch_done(self):
            pass

    index = DeviceIndex(schema, tunables=MatchTunables())
    proc = DeviceProcessor(schema, index)
    dev = Collector()
    proc.add_match_listener(dev)
    proc.deduplicate(records)

    widths = {s.name: s.chars for s in index.plan.device_props}
    # the long property grew; the short ones did not
    assert widths["desc"] >= len(variant)
    assert widths["name"] < 100
    assert widths["desc"] > widths["name"]

    host = Processor(schema, InvertedIndex(schema, MatchTunables()))
    oracle = Collector()
    host.add_match_listener(oracle)
    host.deduplicate(records)

    assert dev.pairs == oracle.pairs
    assert tuple(sorted(("d__1", "d__2"))) in dev.pairs
    assert tuple(sorted(("d__3", "d__4"))) in dev.pairs


def test_long_text_property_demotes_to_host_path(monkeypatch):
    """VERDICT r3 #5 (routing half): values past DEVICE_DEMOTE_CHARS move
    the property to host scoring — the device keeps pruning on the short
    properties with the demoted property's max contribution in the
    optimistic bound — and links still equal the host engine's."""
    monkeypatch.delenv("DEVICE_MAX_CHARS", raising=False)
    from sesam_duke_microservice_tpu.core import comparators as C
    from sesam_duke_microservice_tpu.core.config import (
        DukeSchema,
        MatchTunables,
    )
    from sesam_duke_microservice_tpu.core.records import (
        ID_PROPERTY_NAME,
        Property,
        Record,
    )
    from sesam_duke_microservice_tpu.engine.device_matcher import (
        DeviceIndex,
        DeviceProcessor,
    )
    from sesam_duke_microservice_tpu.engine.processor import Processor
    from sesam_duke_microservice_tpu.index.inverted import InvertedIndex

    schema = DukeSchema(
        threshold=0.75, maybe_threshold=None,
        properties=[
            Property(ID_PROPERTY_NAME, id_property=True),
            Property("name", C.Levenshtein(), 0.3, 0.9),
            Property("desc", C.Levenshtein(), 0.35, 0.8),
            Property("ssn", C.Exact(), 0.4, 0.85),
        ],
        data_sources=[],
    )
    long_a = "an extremely long descriptive paragraph " * 30   # ~1200 chars
    long_b = long_a[:-60] + "with a genuinely different conclusion drawn"

    def make(rid, name, desc, ssn):
        r = Record()
        r.add_value(ID_PROPERTY_NAME, f"d__{rid}")
        r.add_value("name", name)
        r.add_value("desc", desc)
        r.add_value("ssn", ssn)
        return r

    records = [
        make("1", "kari nordmann", long_a, "111"),
        make("2", "kari nordmann", long_a, "111"),
        make("3", "ola hansen", long_b, "222"),
        make("4", "ola hansen", long_b, "222"),
        make("5", "per olsen", "short description", "333"),
    ]

    class Collector:
        def __init__(self):
            self.pairs = {}

        def batch_ready(self, n):
            pass

        def matches(self, r1, r2, conf):
            self.pairs[tuple(sorted((r1.record_id, r2.record_id)))] = round(
                conf, 9
            )

        def matches_perhaps(self, r1, r2, conf):
            pass

        def no_match_for(self, r):
            pass

        def batch_done(self):
            pass

    index = DeviceIndex(schema, tunables=MatchTunables())
    proc = DeviceProcessor(schema, index)
    dev = Collector()
    proc.add_match_listener(dev)
    proc.deduplicate(records)

    device_names = {s.name for s in index.plan.device_props}
    host_names = {p.name for p in index.plan.host_props}
    assert "desc" not in device_names and "desc" in host_names
    assert "name" in device_names and "ssn" in device_names
    # the short properties kept their narrow width
    assert all(s.chars <= 64 for s in index.plan.device_props)

    host = Processor(schema, InvertedIndex(schema, MatchTunables()))
    oracle = Collector()
    host.add_match_listener(oracle)
    host.deduplicate(records)
    assert dev.pairs == oracle.pairs
    assert tuple(sorted(("d__1", "d__2"))) in dev.pairs


def test_sole_device_property_keeps_device_and_rebuilds(monkeypatch):
    """Keep-one demotion path (review finding r4): when the ONLY device
    property sees a >DEVICE_DEMOTE_CHARS value, it must stay on device,
    widen to the cap, and REBUILD the corpus tensors — a widened plan
    over old-width tensors crashed the next append."""
    monkeypatch.delenv("DEVICE_MAX_CHARS", raising=False)
    from sesam_duke_microservice_tpu.core import comparators as C
    from sesam_duke_microservice_tpu.core.config import (
        DukeSchema,
        MatchTunables,
    )
    from sesam_duke_microservice_tpu.core.records import (
        ID_PROPERTY_NAME,
        Property,
        Record,
    )
    from sesam_duke_microservice_tpu.engine.device_matcher import (
        DeviceIndex,
        DeviceProcessor,
    )

    schema = DukeSchema(
        threshold=0.75, maybe_threshold=None,
        properties=[
            Property(ID_PROPERTY_NAME, id_property=True),
            Property("text", C.Levenshtein(), 0.3, 0.9),
        ],
        data_sources=[],
    )

    def make(rid, text):
        r = Record()
        r.add_value(ID_PROPERTY_NAME, f"k__{rid}")
        r.add_value("text", text)
        return r

    index = DeviceIndex(schema, tunables=MatchTunables())
    proc = DeviceProcessor(schema, index)
    # short batch first (narrow tensors), then a long batch that would
    # demote if any other device property existed
    proc.deduplicate([make("1", "short one"), make("2", "short two")])
    long_text = "a very long body of text " * 40   # ~1000 chars
    proc.deduplicate([make("3", long_text), make("4", long_text)])
    spec = index.plan.device_props[0]
    assert spec.name == "text" and spec.chars >= 1024 or spec.chars >= 512
    assert index.plan.host_props == []
    # a further append at the widened shapes must not crash
    proc.deduplicate([make("5", "another short")])
    assert index.corpus.size >= 5


def test_device_arrays_redoes_after_concurrent_mutation():
    """The warm-upload race guard (review finding r4): when a writer
    mutates the host mirror while an upload pass is in flight, the
    generation counter must force a second (incremental) pass so the
    cleared dirty flags cannot hide rows from the device copy."""
    from sesam_duke_microservice_tpu.core.config import MatchTunables

    schema = dedup_schema()
    index = DeviceIndex(schema, tunables=MatchTunables())
    for r in random_records(8, seed=3):
        index.index(r)
    index.commit()
    corpus = index.corpus
    corpus.device_arrays()  # settle

    extra = random_records(4, seed=9)
    for i, r in enumerate(extra):
        r.set_values(ID_PROPERTY_NAME, [f"x{i}"])

    passes = {"n": 0}
    real = type(corpus)._device_arrays_locked

    def racy(self):
        passes["n"] += 1
        out = real(self)
        if passes["n"] == 1:
            # a writer lands mid-upload: append AFTER the pass consumed
            # the dirty flags (the exact interleaving that silently lost
            # rows before the generation counter)
            for r in extra:
                index.index(r)
            index.commit()
        return out

    corpus_cls = type(corpus)
    orig = corpus_cls._device_arrays_locked
    corpus_cls._device_arrays_locked = racy
    try:
        feats, valid, deleted, group = corpus.device_arrays()
    finally:
        corpus_cls._device_arrays_locked = orig
    assert passes["n"] >= 2, "generation change did not force a re-run"
    # the appended rows made it to the device copy
    import numpy as np

    assert int(np.asarray(valid).sum()) == corpus.row_valid.sum()
    assert bool(np.asarray(valid)[index.id_to_row["x0"]])


class TestIncrementalMaskUpload:
    """r5: mask arrays update incrementally (appended-slice + tombstone
    scatter) instead of a wholesale O(capacity) re-upload per commit —
    ~60 MB/batch over the device link at the 10M flagship scale.  The
    device masks must track the host mirror bit-for-bit through any
    interleaving of appends, re-indexes, and deletes."""

    def _masks(self, index):
        import numpy as np

        _, valid, deleted, group = index.corpus.device_arrays()
        return (np.asarray(valid), np.asarray(deleted), np.asarray(group))

    def test_masks_track_host_mirror(self):
        import numpy as np

        schema = dedup_schema()
        index = DeviceIndex(schema)
        batches = [random_records(40, seed=1)]
        for r in batches[0]:
            index.index(r)
        index.commit()
        v0, d0, g0 = self._masks(index)
        np.testing.assert_array_equal(v0, index.corpus.row_valid)

        # re-index half (tombstone + append), delete a few, add new
        b2 = random_records(20, seed=1)  # same ids -> re-index
        for r in b2:
            index.index(r)
        index.commit()
        index.delete(b2[0])
        b3 = random_records(10, seed=5)
        for i, r in enumerate(b3):
            r.set_values(ID_PROPERTY_NAME, [f"n{i}"])
            index.index(r)
        index.commit()

        v, d, g = self._masks(index)
        np.testing.assert_array_equal(v, index.corpus.row_valid)
        np.testing.assert_array_equal(d, index.corpus.row_deleted)
        np.testing.assert_array_equal(g, index.corpus.row_group)
        # and the update really was incremental (no full-refresh flag)
        assert not index.corpus._dirty_masks
        index.close()

    def test_scatter_threshold_falls_back_to_full(self):
        import numpy as np

        schema = dedup_schema()
        index = DeviceIndex(schema)
        records = random_records(30, seed=2)
        for r in records:
            index.index(r)
        index.commit()
        index.corpus.device_arrays()
        # tombstone beyond the scatter threshold: full refresh path
        index.corpus._mask_rows = list(range(20)) * 600  # > 4096
        index.corpus.row_valid[:20] = False
        v, _, _ = self._masks(index)
        np.testing.assert_array_equal(v, index.corpus.row_valid)
        assert index.corpus._mask_rows == []
        index.close()
