"""Benchmark: record pairs scored per second, device vs CPU baseline.

Replicates the reference's stresstest workload shape (seeded fake entities,
sesam_node_deduplication_stresstest_config.conf.json:86-106 — seed 1234,
area in [1,10], ids in [1,1e6]) and measures the BASELINE.json metric:
record-pairs scored per second per chip at dedup semantics.

  * CPU baseline: the host engine's exact pair scoring loop
    (engine.processor.Processor.compare — Duke-InMemoryDatabase-style
    brute force) over a sample of pairs, extrapolated to pairs/sec.
  * Device: DeviceProcessor over the full corpus — every query scored
    against every HBM-resident corpus row by the blockwise XLA program.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import math
import os
import random
import sys
import time

import numpy as np

# bench sizes (env-overridable for quick runs).  The default corpus matches
# the reference stresstest's total size (2 x 10,000 seeded entities,
# sesam_node_deduplication_stresstest_config.conf.json).  The 8192-query
# batch exercises the multi-block pipeline (double-buffered dispatch over
# 4096-query buckets) — the steady-state serving regime the microbatch
# queue produces under load; r2 measured single-block 1024-query batches.
CORPUS = int(os.environ.get("BENCH_CORPUS", "20000"))
# the number of record is a MEDIAN of BENCH_RUNS timed batches (r4 verdict:
# a single-sample bench cannot distinguish a 10% regression from the
# documented host/tunnel variance); per-run rates ride the stderr line
BENCH_RUNS = int(os.environ.get("BENCH_RUNS", "3"))
QUERIES = int(os.environ.get("BENCH_QUERIES", "8192"))
# pre-size the corpus so the warm-up and timed batches (appended then
# tombstoned — tombstones still occupy rows) never cross a capacity
# doubling: growth inside the timed region re-uploads the corpus and
# recompiles the scorer mid-measurement (observed as run 1 fast, runs
# 2-3 slow at BENCH_CORPUS=100000)
os.environ.setdefault(
    "DEVICE_INITIAL_CAPACITY",
    str(max(131072, CORPUS + (2 + BENCH_RUNS + 1) * QUERIES)),
)
# BENCH_BACKEND selects the scoring backend: "device" (single-chip brute
# force, the default/headline), "sharded-brute" (the same exact scoring
# over a jax.sharding.Mesh — on a 1-device mesh this measures the
# shard_map dispatch overhead of the flagship serving configuration), or
# "ann"/"sharded" (embedding-ANN blocking, single-chip / mesh)
BACKEND = os.environ.get("BENCH_BACKEND", "device")
CPU_SAMPLE_PAIRS = int(os.environ.get("BENCH_CPU_PAIRS", "20000"))
# end-to-end ingest bench (records/s through deduplicate, including host
# finalization and link persist) on a FINALIZE-BOUND workload: the corpus
# is duplicate-heavy (groups of BENCH_E2E_GROUP identical records), so
# every query carries ~GROUP surviving pairs into host finalization and
# ~GROUP link upserts into persist — the post-device Amdahl regime this
# round's finalization subsystem exists for.  BENCH_E2E=0 skips it.
E2E = os.environ.get("BENCH_E2E", "1") != "0"
E2E_CORPUS = int(os.environ.get("BENCH_E2E_CORPUS", "8192"))
E2E_QUERIES = int(os.environ.get("BENCH_E2E_QUERIES", "1024"))
E2E_GROUP = int(os.environ.get("BENCH_E2E_GROUP", "64"))
E2E_RUNS = int(os.environ.get("BENCH_E2E_RUNS", "3"))
# decision-observability bench (ISSUE 5): ingest records/s with decision
# sampling on (default rate) vs the subsystem hard-disabled, asserting the
# sampled capture stays under the 5% budget, plus p50/p95 latency of the
# POST /explain replay path.  BENCH_EXPLAIN=0 skips it.
EXPLAIN_BENCH = os.environ.get("BENCH_EXPLAIN", "1") != "0"
EXPLAIN_REPLAYS = int(os.environ.get("BENCH_EXPLAIN_REPLAYS", "50"))
# concurrent-ingest bench (ISSUE 6): aggregate records/s and per-request
# p50/p95 latency with 1/4/8 small-batch clients hammering one workload,
# the continuous microbatching scheduler on vs off (DUKE_SCHEDULER=0's
# lock-winner merge).  Link rows and event multisets must be bit-identical
# between the modes — the scheduler changes when work runs, never what it
# computes.  BENCH_CONC=0 skips it.
CONC = os.environ.get("BENCH_CONC", "1") != "0"
CONC_CORPUS = int(os.environ.get("BENCH_CONC_CORPUS", "4096"))
CONC_BATCH = int(os.environ.get("BENCH_CONC_BATCH", "2"))
CONC_REQUESTS = int(os.environ.get("BENCH_CONC_REQUESTS", "48"))
CONC_CLIENTS = tuple(
    int(c) for c in os.environ.get("BENCH_CONC_CLIENTS", "1,4,8").split(",")
)

# quantized + clustered retrieval bench (ISSUE 9): the embedding-ANN
# backend measured flat-bf16 vs int8 vs int8+IVF on one stresstest corpus
# — records/s, analytic retrieval FLOPs/query, measured link recall vs
# the flat bf16 arm (retrieved pairs rescore exactly, so common links
# carry identical confidences), and embedding HBM bytes/row.
# BENCH_IVF=0 skips it.
IVF_BENCH = os.environ.get("BENCH_IVF", "1") != "0"
IVF_CORPUS = int(os.environ.get("BENCH_IVF_CORPUS", "20000"))
IVF_QUERIES = int(os.environ.get("BENCH_IVF_QUERIES", "2048"))

# durability bench (ISSUE 10): e2e ingest records/s on the finalize-
# bound duplicate-heavy corpus with the link journal off vs each sync
# policy (none / fdatasync / fsync), plus recovery-replay throughput
# over a synthesized journal — so the DUKE_JOURNAL_SYNC default is a
# measured trade (fsync cost vs loss window), not a guess.
# BENCH_DURABILITY=0 skips it.
DURABILITY = os.environ.get("BENCH_DURABILITY", "1") != "0"
DURA_RECOVERY_BATCHES = int(
    os.environ.get("BENCH_DURA_RECOVERY_BATCHES", "10000"))

# open-loop tail-latency / SLO harness (ISSUE 15): Poisson arrivals at a
# sweep of rates against the continuous-microbatching scheduler, reporting
# p50/p99/p999 measured from the SCHEDULED arrival instant (so queueing
# delay counts — the closed-loop `concurrent` bench hides it by
# construction), SLO-violation counts, cold vs AOT-warm time-to-first-200
# in fresh subprocesses, and the recovery read-unavailability window
# serial vs overlapped.  BENCH_TAIL=0 skips it.
TAIL = os.environ.get("BENCH_TAIL", "1") != "0"
TAIL_RATES = tuple(
    float(r) for r in os.environ.get("BENCH_TAIL_RATES", "4,12,24").split(","))
TAIL_SECONDS = float(os.environ.get("BENCH_TAIL_SECONDS", "5"))
TAIL_SLO_MS = float(os.environ.get("BENCH_TAIL_SLO_MS", "1000"))
TAIL_BATCH = int(os.environ.get("BENCH_TAIL_BATCH", "8"))
TAIL_CORPUS = int(os.environ.get("BENCH_TAIL_CORPUS", "4096"))
TAIL_RECOVERY_BATCHES = int(
    os.environ.get("BENCH_TAIL_RECOVERY_BATCHES", "4000"))

# warm-resync ingest bench (this round's encode subsystem): re-POST an
# already-ingested corpus — the reference's full-resync traffic shape —
# and compare records/s cold (empty feature cache) vs warm (digest hits)
# plus the hit/miss split, so BENCH_*.json tracks what the cache buys per
# release.  BENCH_RESYNC=0 skips it.
RESYNC = os.environ.get("BENCH_RESYNC", "1") != "0"
RESYNC_RECORDS = int(os.environ.get("BENCH_RESYNC_RECORDS", "8192"))


def stresstest_records(n, seed=1234, dataset="ds1"):
    """Seeded fake entities mirroring the sesam stresstest value pools."""
    from sesam_duke_microservice_tpu.core.records import (
        DATASET_ID_PROPERTY_NAME,
        ID_PROPERTY_NAME,
        ORIGINAL_ENTITY_ID_PROPERTY_NAME,
        Record,
    )

    rng = random.Random(seed)
    first = ["ole", "kari", "per", "anne", "nils", "ingrid", "lars", "berit",
             "jan", "liv", "arne", "astrid", "knut", "solveig", "odd", "randi"]
    last = ["hansen", "johansen", "olsen", "larsen", "andersen", "pedersen",
            "nilsen", "kristiansen", "jensen", "karlsen", "johnsen", "pettersen"]
    records = []
    for i in range(n):
        r = Record()
        eid = str(rng.randint(1, 1_000_000))
        r.add_value(ID_PROPERTY_NAME, f"{dataset}__{eid}_{i}")
        r.add_value(ORIGINAL_ENTITY_ID_PROPERTY_NAME, f"{eid}_{i}")
        r.add_value(DATASET_ID_PROPERTY_NAME, dataset)
        name = f"{rng.choice(first)} {rng.choice(last)}"
        if rng.random() < 0.15:  # perturbations create near-duplicates
            pos = rng.randrange(len(name))
            name = name[:pos] + rng.choice("abcdefghij") + name[pos + 1:]
        r.add_value("name", name)
        r.add_value("area", str(rng.randint(1, 10)))
        r.add_value("ssn", str(rng.randint(1, 1_000_000)))
        records.append(r)
    return records


def bench_schema():
    from sesam_duke_microservice_tpu.core import comparators as C
    from sesam_duke_microservice_tpu.core.config import DukeSchema
    from sesam_duke_microservice_tpu.core.records import (
        ID_PROPERTY_NAME,
        Property,
    )

    numeric = C.Numeric()
    numeric.min_ratio = 0.7
    return DukeSchema(
        threshold=0.9,
        maybe_threshold=None,
        properties=[
            Property(ID_PROPERTY_NAME, id_property=True),
            Property("name", C.Levenshtein(), 0.3, 0.88),
            Property("area", numeric, 0.45, 0.65),
            Property("ssn", C.Exact(), 0.3, 0.95),
        ],
        data_sources=[],
    )


def cpu_baseline_pairs_per_sec(schema, records) -> float:
    """Exact host pair scoring rate (Duke-style scalar hot loop).

    The baseline stands in for the reference's per-pair scalar engine, so
    the native C++ comparator library is pinned OFF here — it belongs to
    the new framework's side of the comparison, not the baseline's.
    """
    from sesam_duke_microservice_tpu.core import comparators as C
    from sesam_duke_microservice_tpu.engine.processor import Processor

    proc = Processor(schema, database=None)
    rng = random.Random(4321)
    n = len(records)
    pairs = [
        (records[rng.randrange(n)], records[rng.randrange(n)])
        for _ in range(CPU_SAMPLE_PAIRS)
    ]
    saved = C._NATIVE
    C._NATIVE = None
    try:
        t0 = time.perf_counter()
        acc = 0.0
        for r1, r2 in pairs:
            acc += proc.compare(r1, r2)
        dt = time.perf_counter() - t0
    finally:
        C._NATIVE = saved
    assert acc >= 0.0
    return CPU_SAMPLE_PAIRS / dt


def _backend(schema):
    if BACKEND == "sharded-brute":
        from sesam_duke_microservice_tpu.engine.sharded_matcher import (
            ShardedDeviceIndex,
            ShardedDeviceProcessor,
        )

        index = ShardedDeviceIndex(schema)
        return index, ShardedDeviceProcessor(schema, index)
    if BACKEND == "sharded":
        from sesam_duke_microservice_tpu.engine.sharded_matcher import (
            ShardedAnnIndex,
            ShardedAnnProcessor,
        )

        index = ShardedAnnIndex(schema)
        return index, ShardedAnnProcessor(schema, index)
    if BACKEND == "ann":
        from sesam_duke_microservice_tpu.engine.ann_matcher import (
            AnnIndex,
            AnnProcessor,
        )

        index = AnnIndex(schema)
        return index, AnnProcessor(schema, index)
    from sesam_duke_microservice_tpu.engine.device_matcher import (
        DeviceIndex,
        DeviceProcessor,
    )

    index = DeviceIndex(schema)
    return index, DeviceProcessor(schema, index)


def device_pairs_per_sec(schema, corpus_records) -> tuple:
    """Steady-state device scoring: (per-run rates list, per-phase
    seconds dict, per-run trace ids) over BENCH_RUNS timed batches."""
    from sesam_duke_microservice_tpu.telemetry import tracing
    from sesam_duke_microservice_tpu.utils.jit_cache import (
        enable_persistent_cache,
    )

    enable_persistent_cache()

    index, proc = _backend(schema)

    # build the corpus (feature extraction + device transfer, not timed:
    # the metric is scoring throughput; ingest cost is amortized across the
    # corpus lifetime in the incremental service)
    for r in corpus_records:
        index.index(r)
    index.commit()

    # warmup: two batches of the timed runs' exact size — the first pays
    # the full corpus upload + scorer compile, the second the incremental
    # corpus-updater compile at the timed batch's update-slice bucket, so
    # the timed region is compile-free.  Each batch (warm and timed) is
    # deleted again after its run (tombstoned) so every run scores the
    # stated live corpus and round-over-round numbers stay comparable;
    # DEVICE_INITIAL_CAPACITY above keeps the accumulating tombstones
    # from crossing a capacity doubling.
    warm_a = stresstest_records(QUERIES, seed=999, dataset="warm")
    warm_b = stresstest_records(QUERIES, seed=998, dataset="warm2")
    proc.deduplicate(warm_a)
    proc.deduplicate(warm_b)
    for r in warm_a + warm_b:
        index.delete(r)

    rates = []
    trace_ids = []
    retrieval0 = proc.stats.retrieval_seconds
    compare0 = proc.stats.compare_seconds
    phases0 = dict(proc.phases.phase_seconds())
    for run in range(BENCH_RUNS):
        queries = stresstest_records(
            QUERIES, seed=5678 + run, dataset=f"ds2r{run}"
        )
        stats0 = proc.stats.pairs_compared
        t0 = time.perf_counter()
        # each timed run is one force-sampled trace: its engine phase
        # spans land in the in-process flight recorder and the slowest
        # run's id rides the BENCH json, so a regression links straight
        # to a span tree instead of a bare number
        with tracing.start_trace(
            f"bench:run{run}", sampled=True,
            attributes={"queries": QUERIES, "corpus": CORPUS},
        ) as root:
            proc.deduplicate(queries)
        dt = time.perf_counter() - t0
        scored = proc.stats.pairs_compared - stats0
        rates.append(scored / dt)
        trace_ids.append(root.trace_id)
        for r in queries:
            index.delete(r)
    # per-phase split of the timed runs, from the same single-writer
    # telemetry the service scrapes (ProfileStats / PhaseRecorder):
    # device-program resolve (retrieval) vs host finalization (compare)
    # — so round-over-round throughput deltas are attributable
    phases = {
        "retrieval_seconds": round(
            proc.stats.retrieval_seconds - retrieval0, 4),
        "compare_seconds": round(
            proc.stats.compare_seconds - compare0, 4),
        "batch_seconds": {
            k: round(v - phases0.get(k, 0.0), 4)
            for k, v in proc.phases.phase_seconds().items()
        },
    }
    return rates, phases, trace_ids


def duplicate_group_records(n, group, seed, dataset):
    """Duplicate-heavy corpus: ``n`` records over ``n // group`` distinct
    identities (identical name/area/ssn within a group), so each query is
    a fresh copy of an identity and survives against the whole group."""
    from sesam_duke_microservice_tpu.core.records import (
        DATASET_ID_PROPERTY_NAME,
        ID_PROPERTY_NAME,
        ORIGINAL_ENTITY_ID_PROPERTY_NAME,
        Record,
    )

    rng = random.Random(seed)
    identities = max(1, n // group)
    pool = [
        (
            f"person {i} vangsnes {rng.randint(0, 999)}",
            str(rng.randint(1, 10)),
            str(100000 + i),
        )
        for i in range(identities)
    ]
    records = []
    for i in range(n):
        name, area, ssn = pool[i % identities]
        r = Record()
        r.add_value(ID_PROPERTY_NAME, f"{dataset}__{i}")
        r.add_value(ORIGINAL_ENTITY_ID_PROPERTY_NAME, str(i))
        r.add_value(DATASET_ID_PROPERTY_NAME, dataset)
        r.add_value("name", name)
        r.add_value("area", area)
        r.add_value("ssn", ssn)
        records.append(r)
    return records


class _EventTape:
    """Ordered listener event tape for bit-identity assertions."""

    def __init__(self):
        self.events = []

    def batch_ready(self, n):
        pass

    def batch_done(self):
        pass

    def matches(self, r1, r2, confidence):
        self.events.append(("m", r1.record_id, r2.record_id, confidence))

    def matches_perhaps(self, r1, r2, confidence):
        self.events.append(("p", r1.record_id, r2.record_id, confidence))

    def no_match_for(self, record):
        self.events.append(("n", record.record_id))


def _e2e_link_rows(db):
    return sorted(
        (l.id1, l.id2, l.status.value, l.kind.value, l.confidence)
        for l in db.get_all_links()
    )


def _e2e_run(schema, tmpdir, *, serial: bool, finalizer=None,
             mode=None, capture: bool = False) -> dict:
    """One end-to-end ingest measurement: deduplicate (device scoring +
    host finalization) + link persist to a durable sqlite store.

    ``serial=True`` pins the pre-finalization-subsystem configuration —
    one finalize thread, no decisive-band skip, no device finalize,
    per-link synchronous sqlite writes — so the headline can report the
    speedup of the new defaults over the legacy path in one bench
    invocation.  ``finalizer`` overrides the executor outright (the
    ``device_finalize`` on/off arms pin threads=1 and toggle only
    ``DUKE_DEVICE_FINALIZE`` semantics); ``capture`` additionally
    returns the ordered event tape + link rows for bit-identity
    assertions.
    """
    from sesam_duke_microservice_tpu.engine.device_matcher import (
        DeviceIndex,
        DeviceProcessor,
    )
    from sesam_duke_microservice_tpu.engine.finalize import FinalizeExecutor
    from sesam_duke_microservice_tpu.engine.listeners import LinkMatchListener
    from sesam_duke_microservice_tpu.links.sqlite import SqliteLinkDatabase
    from sesam_duke_microservice_tpu.links.write_behind import (
        WriteBehindLinkDatabase,
    )

    mode = mode or ("serial" if serial else "parallel")
    linkdb = SqliteLinkDatabase(os.path.join(tmpdir, f"links-{mode}.sqlite"))
    if serial:
        db, listener = linkdb, LinkMatchListener(linkdb, batch=False)
    else:
        db = WriteBehindLinkDatabase(linkdb)
        listener = LinkMatchListener(db)

    index = DeviceIndex(schema)
    # the parallel arm defaults the pool to the machine's cores so the
    # thread fan-out is actually measured; DUKE_FINALIZE_THREADS still
    # overrides inside FinalizeExecutor
    proc = DeviceProcessor(schema, index, threads=(os.cpu_count() or 2))
    if finalizer is not None:
        proc.finalizer = finalizer
    elif serial:
        proc.finalizer = FinalizeExecutor(1, decisive=False, use_env=False)
    proc.add_match_listener(listener)
    tape = _EventTape()
    if capture:
        proc.add_match_listener(tape)

    corpus = duplicate_group_records(E2E_CORPUS, E2E_GROUP, seed=42,
                                     dataset="base")
    for r in corpus:
        index.index(r)
    index.commit()

    # warmup batch (compiles + full upload), deleted afterwards so every
    # timed run ingests against the same live corpus
    warm = duplicate_group_records(E2E_QUERIES, E2E_GROUP, seed=42,
                                   dataset="warm")
    proc.deduplicate(warm)
    for r in warm:
        index.delete(r)
    tape.events.clear()

    rescored0 = proc.stats.pairs_rescored
    skipped0 = proc.stats.pairs_skipped
    certified0 = proc.stats.pairs_device_certified
    finalize0 = proc.stats.compare_seconds
    t0 = time.perf_counter()
    for run in range(E2E_RUNS):
        batch = duplicate_group_records(
            E2E_QUERIES, E2E_GROUP, seed=42, dataset=f"ing{run}"
        )
        proc.deduplicate(batch)
        for r in batch:
            index.delete(r)
    # the write-behind flush must be durable before the clock stops:
    # records/s includes persist, not just the enqueue
    db.drain()
    dt = time.perf_counter() - t0
    finalize_dt = proc.stats.compare_seconds - finalize0
    out = {
        "records_per_sec": round(E2E_RUNS * E2E_QUERIES / dt, 1),
        "rescored": proc.stats.pairs_rescored - rescored0,
        "skipped": proc.stats.pairs_skipped - skipped0,
        "device_certified": proc.stats.pairs_device_certified - certified0,
        "finalize_seconds": round(finalize_dt, 3),
        # finalize share of e2e wall clock (the ISSUE 12 target figure)
        "finalize_fraction": round(finalize_dt / dt, 4),
        "finalize_threads": proc.finalizer.threads,
    }
    if capture:
        out["events"] = list(tape.events)
        out["links"] = _e2e_link_rows(db)
    db.close()
    return out


def e2e_ingest(schema) -> dict:
    """records/s through ``deduplicate`` + persist, new defaults vs the
    legacy serial path, plus the ISSUE 12 ``device_finalize`` arm:
    DUKE_DEVICE_FINALIZE on vs off at DUKE_FINALIZE_THREADS=1, link rows
    AND ordered event streams asserted bit-identical, and
    ``finalize_fraction`` (finalize share of e2e wall clock) reported
    per arm so the <10% target is a measured number."""
    import tempfile

    from sesam_duke_microservice_tpu.engine.finalize import FinalizeExecutor

    with tempfile.TemporaryDirectory(prefix="duke-e2e-bench") as tmpdir:
        serial = _e2e_run(schema, tmpdir, serial=True)
        parallel = _e2e_run(schema, tmpdir, serial=False)
        dev_on = _e2e_run(
            schema, tmpdir, serial=False, mode="dd-on", capture=True,
            finalizer=FinalizeExecutor(1, device=True, use_env=False),
        )
        dev_off = _e2e_run(
            schema, tmpdir, serial=False, mode="dd-off", capture=True,
            finalizer=FinalizeExecutor(1, device=False, use_env=False),
        )
    if dev_on["events"] != dev_off["events"]:
        raise AssertionError(
            "device-finalize event stream diverged from the host control")
    if dev_on["links"] != dev_off["links"]:
        raise AssertionError(
            "device-finalize link rows diverged from the host control")
    return {
        "metric": "ingest_records_per_sec",
        "value": parallel["records_per_sec"],
        "unit": "records/s",
        "vs_serial_finalize": round(
            parallel["records_per_sec"] / serial["records_per_sec"], 2
        ),
        "serial_records_per_sec": serial["records_per_sec"],
        "finalize_threads": parallel["finalize_threads"],
        "finalize_rescored": parallel["rescored"],
        "finalize_skipped": parallel["skipped"],
        "finalize_fraction": parallel["finalize_fraction"],
        "device_finalize": {
            # both arms pin DUKE_FINALIZE_THREADS=1 (the ISSUE 12 target
            # configuration); bit-identity of events+links was asserted
            "on_records_per_sec": dev_on["records_per_sec"],
            "off_records_per_sec": dev_off["records_per_sec"],
            "finalize_fraction_on": dev_on["finalize_fraction"],
            "finalize_fraction_off": dev_off["finalize_fraction"],
            "finalize_seconds_on": dev_on["finalize_seconds"],
            "finalize_seconds_off": dev_off["finalize_seconds"],
            "device_certified": dev_on["device_certified"],
            "bit_identical": True,
        },
        "corpus": E2E_CORPUS,
        "queries_per_batch": E2E_QUERIES,
        "dup_group": E2E_GROUP,
    }


def _durability_arm(schema, tmpdir, mode: str) -> float:
    """e2e ingest records/s (same finalize-bound corpus shape as the
    ``e2e`` section, write-behind on) with the link journal configured
    per ``mode``: 'off', or sync policy 'none'/'fdatasync'/'fsync'."""
    from sesam_duke_microservice_tpu.engine.device_matcher import (
        DeviceIndex,
        DeviceProcessor,
    )
    from sesam_duke_microservice_tpu.engine.listeners import LinkMatchListener
    from sesam_duke_microservice_tpu.links.journal import LinkJournal
    from sesam_duke_microservice_tpu.links.sqlite import SqliteLinkDatabase
    from sesam_duke_microservice_tpu.links.write_behind import (
        WriteBehindLinkDatabase,
    )

    linkdb = SqliteLinkDatabase(os.path.join(tmpdir, f"links-{mode}.sqlite"))
    journal = (None if mode == "off" else LinkJournal(
        os.path.join(tmpdir, f"links-{mode}.journal"), sync=mode))
    db = WriteBehindLinkDatabase(linkdb, journal=journal)
    listener = LinkMatchListener(db)

    index = DeviceIndex(schema)
    proc = DeviceProcessor(schema, index, threads=(os.cpu_count() or 2))
    proc.add_match_listener(listener)

    corpus = duplicate_group_records(E2E_CORPUS, E2E_GROUP, seed=42,
                                     dataset=f"dura-{mode}")
    for r in corpus:
        index.index(r)
    index.commit()
    warm = duplicate_group_records(E2E_QUERIES, E2E_GROUP, seed=42,
                                   dataset=f"durawarm{mode}")
    proc.deduplicate(warm)
    for r in warm:
        index.delete(r)

    t0 = time.perf_counter()
    for run in range(E2E_RUNS):
        batch = duplicate_group_records(
            E2E_QUERIES, E2E_GROUP, seed=42, dataset=f"dura{mode}{run}"
        )
        proc.deduplicate(batch)
        for r in batch:
            index.delete(r)
    db.drain()
    dt = time.perf_counter() - t0
    db.close()
    return round(E2E_RUNS * E2E_QUERIES / dt, 1)


def durability_bench(schema) -> dict:
    """Journal-cost + recovery-throughput measurements (ISSUE 10).

    The ingest arms share the e2e corpus shape so the per-mode rates are
    directly comparable with the headline ``e2e`` number; the recovery
    arm synthesizes DURA_RECOVERY_BATCHES journaled batches and times a
    cold ``recover()`` into a fresh sqlite store — the restart cost an
    operator pays per 10k stranded (acked-but-unflushed) batches."""
    import tempfile

    from sesam_duke_microservice_tpu.links.journal import LinkJournal
    from sesam_duke_microservice_tpu.links.sqlite import SqliteLinkDatabase
    from sesam_duke_microservice_tpu.links.write_behind import (
        WriteBehindLinkDatabase,
    )

    out = {"ingest_records_per_sec": {}}
    with tempfile.TemporaryDirectory(prefix="duke-dura-bench") as tmpdir:
        for mode in ("off", "none", "fdatasync", "fsync"):
            out["ingest_records_per_sec"][mode] = _durability_arm(
                schema, tmpdir, mode)

        # recovery replay: N small journaled batches, no watermark
        jpath = os.path.join(tmpdir, "recovery.journal")
        journal = LinkJournal(jpath, sync="none")
        for i in range(DURA_RECOVERY_BATCHES):
            journal.append_batch([
                (f"a{i}", f"b{i}", "inferred", "duplicate", 0.9,
                 1_000_000 + i),
            ])
        journal.close()
        inner = SqliteLinkDatabase(os.path.join(tmpdir, "recovery.sqlite"))
        db = WriteBehindLinkDatabase(inner, journal=LinkJournal(jpath))
        t0 = time.perf_counter()
        replayed = db.recover()
        dt = time.perf_counter() - t0
        assert replayed == DURA_RECOVERY_BATCHES
        db.close()
        out["recovery"] = {
            "batches": replayed,
            "seconds": round(dt, 3),
            "batches_per_sec": round(replayed / dt, 1),
            "seconds_per_10k_batches": round(dt * 10000 / replayed, 3),
        }
    base = out["ingest_records_per_sec"]["off"]
    out["journal_overhead"] = {
        mode: round(1 - out["ingest_records_per_sec"][mode] / base, 4)
        for mode in ("none", "fdatasync", "fsync")
    }
    out["default_sync"] = "fdatasync"
    return out


def warm_resync(schema) -> dict:
    """Warm-resync ingest: records/s re-POSTing an already-ingested corpus.

    Sesam's normal sync mode re-POSTs entire datasets of mostly-unchanged
    entities; the corpus is append-only with digest-tracked re-upserts, so
    the pre-PR cost of that traffic was full re-extraction per row.  Two
    timed passes over identical record content (fresh Record objects each
    pass, so digests are genuinely recomputed): cold ingests into an
    empty feature cache, warm re-POSTs the same entities and should
    encode almost entirely from cache hits.  The encode-phase split is
    reported separately because on small corpora device scoring can
    dominate wall time and mask the encode win the cache targets.
    """
    from sesam_duke_microservice_tpu.engine.device_matcher import (
        DeviceIndex,
        DeviceProcessor,
    )
    from sesam_duke_microservice_tpu.ops import feature_cache as FC

    FC.reset()
    cache_on = FC.active() is not None
    index = DeviceIndex(schema)
    proc = DeviceProcessor(schema, index)

    # warmup on a disjoint dataset: compiles + the initial full corpus
    # upload stay out of both timed passes
    warm = stresstest_records(RESYNC_RECORDS, seed=321, dataset="rswarm")
    proc.deduplicate(warm)
    FC.reset()

    def one_pass(run):
        batch = stresstest_records(RESYNC_RECORDS, seed=777, dataset="rs")
        encode0 = proc.phases.phase_seconds().get("encode", 0.0)
        hits0, misses0, _, _ = FC.stats()
        t0 = time.perf_counter()
        proc.deduplicate(batch)
        dt = time.perf_counter() - t0
        hits, misses, _, _ = FC.stats()
        return {
            "records_per_sec": round(RESYNC_RECORDS / dt, 1),
            "encode_seconds": round(
                proc.phases.phase_seconds().get("encode", 0.0) - encode0, 4
            ),
            "cache_hits": hits - hits0,
            "cache_misses": misses - misses0,
        }

    cold = one_pass(0)
    warm_run = one_pass(1)
    return {
        "metric": "resync_records_per_sec",
        "cache_mb": FC.budget_mb() if cache_on else 0,
        "records": RESYNC_RECORDS,
        "cold": cold,
        "warm": warm_run,
        "warm_vs_cold": round(
            warm_run["records_per_sec"] / cold["records_per_sec"], 2
        ),
        "encode_speedup": round(
            cold["encode_seconds"]
            / max(warm_run["encode_seconds"], 1e-9), 2
        ),
    }


def _explain_arm(schema, tmpdir, *, recording: bool) -> dict:
    """One decision-sampling ingest measurement (the _e2e_run shape, on
    the same duplicate-heavy finalize-bound corpus — every query carries
    ~GROUP survivors, so per-decision overhead is maximally visible)."""
    from sesam_duke_microservice_tpu.engine.device_matcher import (
        DeviceIndex,
        DeviceProcessor,
    )
    from sesam_duke_microservice_tpu.engine.listeners import LinkMatchListener
    from sesam_duke_microservice_tpu.links.sqlite import SqliteLinkDatabase
    from sesam_duke_microservice_tpu.links.write_behind import (
        WriteBehindLinkDatabase,
    )
    from sesam_duke_microservice_tpu.telemetry.decisions import (
        DecisionRecorder,
    )

    from sesam_duke_microservice_tpu.ops import feature_cache as FC

    # the two arms ingest identical record content; without a reset the
    # second arm would encode entirely from the first arm's cache hits
    # and the comparison would measure the cache, not the recorder
    FC.reset()
    mode = "rec" if recording else "off"
    db = WriteBehindLinkDatabase(
        SqliteLinkDatabase(os.path.join(tmpdir, f"links-{mode}.sqlite"))
    )
    index = DeviceIndex(schema)
    proc = DeviceProcessor(schema, index, threads=(os.cpu_count() or 2))
    if not recording:
        # hard-disable the whole subsystem (what DUKE_DECISION_RECORD=0
        # gives a deployment): the baseline arm
        proc.decisions = DecisionRecorder(
            schema.threshold, schema.maybe_threshold, enabled=False,
        )
    proc.add_match_listener(LinkMatchListener(db))

    corpus = duplicate_group_records(E2E_CORPUS, E2E_GROUP, seed=42,
                                     dataset="base")
    for r in corpus:
        index.index(r)
    index.commit()
    warm = duplicate_group_records(E2E_QUERIES, E2E_GROUP, seed=42,
                                   dataset="warm")
    proc.deduplicate(warm)
    for r in warm:
        index.delete(r)

    t0 = time.perf_counter()
    for run in range(E2E_RUNS):
        batch = duplicate_group_records(
            E2E_QUERIES, E2E_GROUP, seed=42, dataset=f"ex{mode}{run}"
        )
        proc.deduplicate(batch)
        for r in batch:
            index.delete(r)
    db.drain()
    dt = time.perf_counter() - t0
    out = {
        "records_per_sec": round(E2E_RUNS * E2E_QUERIES / dt, 1),
        "decisions": sum(proc.decisions.outcomes.values()),
        "ring": len(proc.decisions.ring),
    }
    if recording:
        # replay latency on the live index (the POST /explain path minus
        # the HTTP socket): p50/p95 over distinct indexed pairs
        import threading as _threading

        from sesam_duke_microservice_tpu.engine import explain as X

        class _WL:
            lock = _threading.Lock()
            closed = False
            name, kind = "bench", "deduplication"
            datasources = {}

        wl = _WL()
        wl.processor, wl.index, wl.link_database = proc, index, db
        ids = [r.record_id for r in corpus]
        X.explain_request(wl, {"id1": ids[0], "id2": ids[1]})  # jit warm
        lat = []
        for i in range(EXPLAIN_REPLAYS):
            a = ids[(2 * i) % len(ids)]
            b = ids[(2 * i + 1) % len(ids)]
            t1 = time.perf_counter()
            X.explain_request(wl, {"id1": a, "id2": b})
            lat.append(time.perf_counter() - t1)
        lat.sort()
        out["replay_p50_ms"] = round(lat[len(lat) // 2] * 1e3, 2)
        out["replay_p95_ms"] = round(lat[int(len(lat) * 0.95)] * 1e3, 2)
    db.close()
    return out


def explain_bench(schema) -> dict:
    """Decision-sampling overhead + explain replay latency (ISSUE 5
    acceptance: sampled capture costs <5% on the ingest path)."""
    import tempfile

    with tempfile.TemporaryDirectory(prefix="duke-explain-bench") as tmpdir:
        off = _explain_arm(schema, tmpdir, recording=False)
        on = _explain_arm(schema, tmpdir, recording=True)
    overhead_pct = round(
        (off["records_per_sec"] - on["records_per_sec"])
        / off["records_per_sec"] * 100.0, 2,
    )
    return {
        "metric": "decision_sampling_overhead_pct",
        "value": overhead_pct,
        "within_budget": overhead_pct < 5.0,
        "records_per_sec_sampling_on": on["records_per_sec"],
        "records_per_sec_sampling_off": off["records_per_sec"],
        "decisions_recorded": on["decisions"],
        "ring_records": on["ring"],
        "replay_p50_ms": on["replay_p50_ms"],
        "replay_p95_ms": on["replay_p95_ms"],
        "replays": EXPLAIN_REPLAYS,
    }


def _ivf_arm(schema, corpus_records, queries, *, int8: bool, ivf: bool):
    """One retrieval-lever measurement on a fresh AnnIndex: ingest the
    corpus, warm the shapes, time one query batch, and report links +
    retrieval geometry."""
    from sesam_duke_microservice_tpu.engine.ann_matcher import (
        AnnIndex,
        AnnProcessor,
    )
    from sesam_duke_microservice_tpu.ops import encoder as E
    from sesam_duke_microservice_tpu.ops import feature_cache as FC

    os.environ["DUKE_EMB_INT8"] = "1" if int8 else "0"
    os.environ["DUKE_IVF"] = "1" if ivf else "0"
    FC.reset()  # fingerprints differ per storage mode; measure each cold
    index = AnnIndex(schema)
    proc = AnnProcessor(schema, index)

    class _Log:
        def __init__(self):
            self.links = set()

        def batch_ready(self, n):
            pass

        def batch_done(self):
            pass

        def matches(self, r1, r2, confidence):
            a, b = sorted((r1.record_id, r2.record_id))
            self.links.add((a, b, repr(confidence)))

        matches_perhaps = matches

        def no_match_for(self, record):
            pass

    log = _Log()
    proc.add_match_listener(log)
    for r in corpus_records:
        index.index(r)
    index.commit()

    warm = stresstest_records(IVF_QUERIES, seed=991, dataset="ivfwarm")
    proc.deduplicate(warm)
    for r in warm:
        index.delete(r)

    t0 = time.perf_counter()
    proc.deduplicate(queries)
    dt = time.perf_counter() - t0

    corpus = index.corpus
    tree = corpus.feats[E.ANN_PROP]
    emb_bytes_row = sum(a.nbytes for a in tree.values()) / corpus.capacity
    dim = index.dim
    flat_flops = 2.0 * corpus.capacity * dim
    out = {
        "records_per_sec": round(IVF_QUERIES / dt, 1),
        "emb_storage": index.emb_storage,
        "emb_bytes_per_row": round(emb_bytes_row, 1),
        "retrieval_flops_per_query": flat_flops,
    }
    state = index.ivf
    if state is not None and state.ready:
        probe_flops = 2.0 * dim * (
            state.ncells + state.nprobe0 * state.bucket
        )
        out["retrieval_flops_per_query"] = probe_flops
        out["ivf"] = {
            "cells": state.ncells,
            "nprobe": state.nprobe0,
            "bucket": state.bucket,
        }
    return out, log.links


def _ivf_dup_queries(corpus_records, n, seed):
    """Near-duplicate probes: typo'd copies of seeded corpus rows (same
    ssn/area, one name edit) — the record-linkage workload shape the
    recall target is stated for.  The raw stresstest generator draws
    every ssn independently, so at threshold 0.9 its only cross-matches
    are ssn-collision pairs between UNRELATED records (cosine-far by
    construction); measuring recall on that link set grades the probe on
    adversarial noise instead of the duplicate-finding task."""
    from sesam_duke_microservice_tpu.core.records import (
        DATASET_ID_PROPERTY_NAME,
        ID_PROPERTY_NAME,
        ORIGINAL_ENTITY_ID_PROPERTY_NAME,
        Record,
    )

    rng = random.Random(seed)
    out = []
    for i, src in enumerate(rng.sample(corpus_records, n)):
        r = Record()
        r.add_value(ID_PROPERTY_NAME, f"ivfq__{i}")
        r.add_value(ORIGINAL_ENTITY_ID_PROPERTY_NAME, str(i))
        r.add_value(DATASET_ID_PROPERTY_NAME, "ivfq")
        name = src.get_value("name")
        pos = rng.randrange(len(name))
        r.add_value("name", name[:pos] + rng.choice("abcdefghij")
                    + name[pos + 1:])
        r.add_value("area", src.get_value("area"))
        r.add_value("ssn", src.get_value("ssn"))
        out.append(r)
    return out


def ivf_bench(schema) -> dict:
    """Flat-bf16 vs int8 vs int8+IVF on the embedding-ANN backend
    (ISSUE 9 acceptance: >=4x retrieval-FLOP and >=2x embedding-HBM
    reduction at measured recall >= 0.99 vs the flat bf16 scan, with
    retrieved-pair link rows bit-identical)."""
    from sesam_duke_microservice_tpu.engine import device_matcher as DM

    corpus_records = stresstest_records(IVF_CORPUS, seed=1234,
                                        dataset="ivfbase")
    # per-row-unique ssn: the raw generator draws ssn ~ U(1..1e6), so at
    # 20k rows it mints ~20 birthday-collision pairs between UNRELATED
    # records — threshold-crossing links with cosine-far embeddings that
    # no cosine blocker (flat or IVF) is designed to surface.  A real
    # ssn identifies an identity; making it unique per row keeps the
    # measured link set exactly the duplicate-finding task the recall
    # target is stated for (queries inherit their source's ssn below).
    for i, r in enumerate(corpus_records):
        r.set_values("ssn", [str(1_000_000 + i)])
    queries = _ivf_dup_queries(corpus_records, IVF_QUERIES, seed=777)

    # snug capacity for this section: the main device bench pre-sizes
    # DEVICE_INITIAL_CAPACITY for ITS corpus (read at import), which
    # would make the flat arms scan 131k mostly-empty rows and flatter
    # the FLOP ratio; the growth-policy knob is module state, so pin it
    # like the CPU-baseline pins C._NATIVE
    saved = DM._INITIAL_CAPACITY
    DM._INITIAL_CAPACITY = 0
    try:
        flat, flat_links = _ivf_arm(schema, corpus_records, queries,
                                    int8=False, ivf=False)
        int8, int8_links = _ivf_arm(schema, corpus_records, queries,
                                    int8=True, ivf=False)
        both, both_links = _ivf_arm(schema, corpus_records, queries,
                                    int8=True, ivf=True)
    finally:
        DM._INITIAL_CAPACITY = saved
        os.environ.pop("DUKE_EMB_INT8", None)
        os.environ.pop("DUKE_IVF", None)

    def recall(links):
        return round(len(links & flat_links) / max(1, len(flat_links)), 4)

    # links common with the flat arm carry identical confidences by
    # construction (shared exact rescoring); verify instead of assume
    def bit_identical(links):
        flat_by_pair = {(a, b): c for a, b, c in flat_links}
        return all(
            flat_by_pair.get((a, b), c) == c for a, b, c in links
        )

    return {
        "metric": "ivf_retrieval_flop_reduction",
        "value": round(
            flat["retrieval_flops_per_query"]
            / both["retrieval_flops_per_query"], 2
        ),
        "corpus": IVF_CORPUS,
        "queries": IVF_QUERIES,
        "flat_bf16": flat,
        "int8": dict(int8, recall_vs_flat=recall(int8_links),
                     links_bit_identical=bit_identical(int8_links)),
        "int8_ivf": dict(both, recall_vs_flat=recall(both_links),
                         links_bit_identical=bit_identical(both_links)),
        "emb_hbm_reduction": round(
            flat["emb_bytes_per_row"] / both["emb_bytes_per_row"], 2
        ),
        "emb_matrix_reduction": 2.0,  # bf16 -> int8 codes; the scale
                                      # vector is the residual 4 B/row
        # wall-clock on the CPU dev box under-sells both levers: CPU XLA
        # lowers int8 dot_general and the per-query IVF gathers far less
        # efficiently than the bf16 matmul it replaces, while on TPU the
        # int8 MXU path is the FASTER one — the acceptance metrics here
        # are the FLOP/HBM/recall columns, which are platform-invariant
        "cpu_note": "records_per_sec is CPU-lowering-bound for the int8 "
                    "and IVF arms; FLOPs/HBM/recall are the "
                    "platform-invariant columns",
    }


CONC_XML = """
<DukeMicroService>
  <Deduplication name="conc" link-database-type="in-memory">
    <duke>
      <schema>
        <threshold>0.8</threshold>
        <property><name>NAME</name>
          <comparator>levenshtein</comparator><low>0.3</low><high>0.9</high>
        </property>
        <property><name>SSN</name>
          <comparator>exact</comparator><low>0.3</low><high>0.95</high>
        </property>
      </schema>
      <data-source class="io.sesam.dukemicroservice.IncrementalDeduplicationDataSource">
        <param name="dataset-id" value="ds"/>
        <column name="name" property="NAME"/>
        <column name="ssn" property="SSN"/>
      </data-source>
    </duke>
  </Deduplication>
</DukeMicroService>
"""


def _conc_entities(client: int, round_: int) -> list:
    """One small-batch POST body, content-deterministic by (client, round)
    so both arms ingest identical records.  Every 4th round the first two
    records are an exact duplicate pair (a within-request link); all
    other names are pairwise-distant so the link set is order-independent
    across any merge interleave."""
    ents = []
    dup_round = round_ % 4 == 0 and CONC_BATCH >= 2
    for k in range(CONC_BATCH):
        uid = f"c{client}r{round_}k{k}"
        if dup_round and k < 2:
            name = f"duplicated entity xq{client}zz{round_}"
        else:
            name = f"unique {uid} wj{client * 7919 + round_ * 104729 + k}"
        ents.append({"_id": uid, "name": name, "ssn": uid})
    return ents


class _ConcEventLog:
    """Order-insensitive event tape (multiset): under concurrency the
    interleave is nondeterministic, but WHAT the engine decides is not."""

    def __init__(self):
        import threading

        self.events = []
        self._lock = threading.Lock()

    def start_processing(self):
        pass

    def batch_ready(self, size):
        pass

    def batch_done(self):
        pass

    def end_processing(self):
        pass

    def matches(self, r1, r2, confidence):
        with self._lock:
            self.events.append(
                ("match", r1.record_id, r2.record_id, repr(confidence)))

    def matches_perhaps(self, r1, r2, confidence):
        with self._lock:
            self.events.append(
                ("maybe", r1.record_id, r2.record_id, repr(confidence)))

    def no_match_for(self, record):
        with self._lock:
            self.events.append(("none", record.record_id))


def _conc_corpus(n: int) -> list:
    """Background corpus for the concurrent arms (schema property names,
    pairwise-distant values — the queries never match it, so link volume
    stays request-local and order-independent)."""
    from sesam_duke_microservice_tpu.core.records import (
        DATASET_ID_PROPERTY_NAME,
        ID_PROPERTY_NAME,
        ORIGINAL_ENTITY_ID_PROPERTY_NAME,
        Record,
    )

    rng = random.Random(7)
    records = []
    for i in range(n):
        r = Record()
        r.add_value(ID_PROPERTY_NAME, f"ds__base{i}")
        r.add_value(ORIGINAL_ENTITY_ID_PROPERTY_NAME, f"base{i}")
        r.add_value(DATASET_ID_PROPERTY_NAME, "ds")
        r.add_value("NAME", f"corpus row {i} vb{rng.randint(0, 999999)}")
        r.add_value("SSN", f"base{i}")
        records.append(r)
    return records


def _conc_arm(sc, clients: int, *, scheduled: bool) -> tuple:
    """One concurrent-ingest measurement: ``clients`` threads each POST
    ``CONC_REQUESTS`` batches of ``CONC_BATCH`` records.  ``scheduled``
    routes through the IngestScheduler; off is the lock-winner merge in
    ``Workload.submit_batch`` (exactly what DUKE_SCHEDULER=0 serves)."""
    import threading

    from sesam_duke_microservice_tpu.engine.scheduler import IngestScheduler
    from sesam_duke_microservice_tpu.engine.workload import build_workload

    wl = build_workload(sc.deduplications["conc"], sc, backend="device",
                        persistent=False)
    log = _ConcEventLog()
    wl.processor.add_match_listener(log)
    sched = IngestScheduler(lambda kind, name: wl) if scheduled else None
    try:
        # warm the bucket shape + corpus upload outside the timed region
        for r in _conc_corpus(CONC_CORPUS):
            wl.index.index(r)
        wl.index.commit()
        wl.submit_batch("ds", _conc_entities(99, 99))
        latencies = []
        lat_lock = threading.Lock()

        def client(c):
            mine = []
            for round_ in range(CONC_REQUESTS):
                ents = _conc_entities(c, round_)
                t0 = time.perf_counter()
                if sched is not None:
                    sched.submit("deduplication", "conc", "ds", ents)
                else:
                    wl.submit_batch("ds", ents)
                mine.append(time.perf_counter() - t0)
            with lat_lock:
                latencies.extend(mine)

        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        latencies.sort()
        total = clients * CONC_REQUESTS * CONC_BATCH
        out = {
            "records_per_sec": round(total / dt, 1),
            "p50_ms": round(latencies[len(latencies) // 2] * 1e3, 2),
            "p95_ms": round(latencies[int(len(latencies) * 0.95)] * 1e3, 2),
        }
        if sched is not None:
            (q,) = sched.queues()
            out["microbatches"] = q.microbatches
            out["avg_fill_records"] = round(
                q.dispatched_records / max(1, q.microbatches), 2)
        # parity material: warmup request (99) excluded from neither arm —
        # both ingest it, so tapes stay comparable
        links = sorted(
            (l.id1, l.id2, l.status.value, l.kind.value, repr(l.confidence))
            for l in wl.link_database.get_changes_since(0)
        )
        return out, sorted(log.events), links
    finally:
        if sched is not None:
            sched.shutdown()
        wl.close()


def concurrent_bench() -> dict:
    """Scheduler-on vs scheduler-off aggregate ingest under 1/4/8
    small-batch clients (the ISSUE 6 acceptance: >=2x at 8 clients with
    bit-identical link rows)."""
    from sesam_duke_microservice_tpu.core.config import parse_config

    sc = parse_config(CONC_XML)
    out = {
        "metric": "concurrent_ingest_speedup",
        "corpus": CONC_CORPUS,
        "batch_records": CONC_BATCH,
        "requests_per_client": CONC_REQUESTS,
        "clients": {},
    }
    for clients in CONC_CLIENTS:
        off, off_events, off_links = _conc_arm(sc, clients, scheduled=False)
        on, on_events, on_links = _conc_arm(sc, clients, scheduled=True)
        out["clients"][str(clients)] = {
            "off": off,
            "on": on,
            "speedup": round(
                on["records_per_sec"] / off["records_per_sec"], 2),
            "links_bit_identical": on_links == off_links,
            "events_bit_identical": on_events == off_events,
        }
    top = str(max(CONC_CLIENTS))
    out["value"] = out["clients"][top]["speedup"]
    out["vs_unscheduled_at_max_clients"] = out["clients"][top]["speedup"]
    return out


FED_BENCH = os.environ.get("BENCH_FED", "1") != "0"
FED_RECORDS = int(os.environ.get("BENCH_FED_RECORDS", "1536"))
FED_BATCH = int(os.environ.get("BENCH_FED_BATCH", "128"))
FED_GROUPS = int(os.environ.get("BENCH_FED_GROUPS", "3"))
# observability overhead bench (ISSUE 16): federated scatter-ingest with
# every batch under a sampled root trace (the always-on instrumentation
# ceiling: fed.partition/fanout/group/merge spans plus remote span
# capture + graft per group) vs no active trace (span sites cost one
# contextvar read).  The SLO trackers and per-range stats run in BOTH
# arms — they are unconditional.  Budget: <2% ingest slowdown.
# BENCH_OBS=0 skips it.
OBS_BENCH = os.environ.get("BENCH_OBS", "1") != "0"
OBS_RUNS = int(os.environ.get("BENCH_OBS_RUNS", "2"))
# capacity-attribution overhead bench (ISSUE 17): the same federated
# scatter-ingest with the cost ledger + sub-range heat map on (default)
# vs off (costs.configure(False), DUKE_FED_HEAT=0) — the attribution
# hot-path additions are one locked add per BATCH and one unlocked
# histogram increment per record, budgeted at <2% ingest slowdown —
# plus a skewed-keyspace scenario (80% of traffic in 5% of one range)
# asserting the suggested split point lands in the hot band.
# BENCH_CAPACITY=0 skips it.
CAP_BENCH = os.environ.get("BENCH_CAPACITY", "1") != "0"
# mesh differential bench (ISSUE 18): the SAME deterministic batches
# through the single-device brute-force arm and the N-way virtual-mesh
# sharded arm (constraint-driven GSPMD — jit over NamedSharding-placed
# corpus tensors, XLA inserts the merge collectives) inside ONE forced
# N-device child process.  Reports records/s per arm, the analytic
# per-device score-FLOP split, the top-K merge collective's payload in
# bytes/query, and asserts the ordered event tapes bit-identical (exact
# blocking: the merged global top-K IS the single-device top-K).  Also
# snapshots the outcome to MULTICHIP_r06.json at the repo root.
# BENCH_MESH=0 skips it.
MESH_BENCH = os.environ.get("BENCH_MESH", "1") != "0"
MESH_DEVICES = int(os.environ.get("BENCH_MESH_DEVICES", "8"))
MESH_RECORDS = int(os.environ.get("BENCH_MESH_RECORDS", "384"))
# multi-tenant density differential (ISSUE 19): BENCH_MT_TENANTS
# same-process device workloads over BENCH_MT_SCHEMAS distinct schemas,
# three arms — (a) arena OFF / per-workload pinning (the HBM control),
# (b) arena ON with the budget forced to a quarter of the control's
# pinned bytes (spill/fault-in under pressure, tapes must stay
# bit-identical to the control), (c) a 4-schema single-tenant run whose
# jit-compile count and shared-ladder executable census the 100-tenant
# arm must MATCH (N same-schema tenants pay one warm pass).  Plus the
# quota proof: one flooding tenant against a small queue cap absorbs
# every 429 while the polite tenants' p99 submit latency stays inside
# DUKE_SLO_INGEST_MS.  BENCH_MULTITENANT=0 skips it.
MT_BENCH = os.environ.get("BENCH_MULTITENANT", "1") != "0"
MT_TENANTS = int(os.environ.get("BENCH_MT_TENANTS", "100"))
MT_SCHEMAS = max(1, min(4, int(os.environ.get("BENCH_MT_SCHEMAS", "4"))))
MT_BATCHES = int(os.environ.get("BENCH_MT_BATCHES", "2"))
# synthetic-monitoring overhead differential (ISSUE 20): the same
# device-backend scheduler ingest with the canary prober ON (shadow
# workloads built, a full probe cycle forced between every timed batch
# — far denser churn than the 30 s production cadence) vs OFF
# (DUKE_PROBE=0).  Only the user submits are timed, so the arm isolates
# what the prober's PRESENCE costs the ingest path (extra scheduler
# tenant, metrics collector, shared-arena neighbor, cache churn from
# probe cycles).  Budget: <2% ingest slowdown and ZERO probe-attributed
# XLA compiles — the shadow shares its plan fingerprint with the user
# workload, so it must ride the same shared AOT ladder.
# BENCH_PROBES=0 skips it.
PROBE_BENCH = os.environ.get("BENCH_PROBES", "1") != "0"
PROBE_BATCHES = int(os.environ.get("BENCH_PROBE_BATCHES", "6"))
PROBE_ROWS = int(os.environ.get("BENCH_PROBE_ROWS", "64"))
PROBE_RUNS = int(os.environ.get("BENCH_PROBE_RUNS", "2"))

FED_XML = """
<DukeMicroService dataFolder="{folder}">
  <Deduplication name="bench">
    <duke>
      <schema>
        <threshold>0.8</threshold>
        <property><name>NAME</name><comparator>levenshtein</comparator><low>0.1</low><high>0.95</high></property>
        <property><name>EMAIL</name><comparator>exact</comparator><low>0.2</low><high>0.95</high></property>
      </schema>
      <data-source class="io.sesam.dukemicroservice.IncrementalDeduplicationDataSource">
        <param name="dataset-id" value="crm"/>
        <column name="name" property="NAME"/>
        <column name="email" property="EMAIL"/>
      </data-source>
    </duke>
  </Deduplication>
</DukeMicroService>
"""


def federation_bench() -> dict:
    """Federation tier (ISSUE 14): scatter-ingest throughput over N
    groups vs one group, merged-feed drain rate, and a timed live range
    migration with the bit-identity check the chaos differential pins.

    Host-backend groups: the section measures the ROUTER tier (routing,
    scatter fan-out, feed merge, migration machinery), not device
    scoring — the corpus is duplicate-heavy so the link feed is
    non-trivial."""
    import tempfile

    from sesam_duke_microservice_tpu.core.config import parse_config
    from sesam_duke_microservice_tpu.federation import Federation
    from sesam_duke_microservice_tpu.federation.ranges import route_key

    def entities(n):
        return [{"_id": str(i), "name": f"person number {i % 64}",
                 "email": f"p{i % 64}@x.no"} for i in range(n)]

    batches = [entities(FED_RECORDS)[i:i + FED_BATCH]
               for i in range(0, FED_RECORDS, FED_BATCH)]

    def run_arm(n_groups: int):
        tmp = tempfile.mkdtemp(prefix="fed-bench-")
        sc = parse_config(FED_XML.format(folder=tmp),
                          env={"MIN_RELEVANCE": "0.05"})
        fed = Federation(sc, n_groups=n_groups)
        t0 = time.monotonic()
        for batch in batches:
            fed.router.submit("deduplication", "bench", "crm", batch)
        ingest_s = time.monotonic() - t0
        t0 = time.monotonic()
        rows, token = [], ""
        while True:
            page = fed.router.feed_page("deduplication", "bench", token,
                                        5000)
            rows.extend(page["rows"])
            token = page["next_since"]
            if page["drained"]:
                break
        feed_s = time.monotonic() - t0
        return fed, ingest_s, feed_s, rows

    one, one_ingest, one_feed, one_rows = run_arm(1)
    one.close()
    fed, n_ingest, n_feed, n_rows = run_arm(FED_GROUPS)

    def normed(rows):
        return sorted(
            json.dumps({k: v for k, v in r.items() if k != "_updated"},
                       sort_keys=True) for r in rows)

    # timed live migration of one range, with the differential check
    moved = next(r for r in fed.map.ranges() if r.group == 0)
    pre = normed(n_rows)
    t0 = time.monotonic()
    stats = fed.migrate_range(moved.range_id, 1 % FED_GROUPS)
    migrate_s = time.monotonic() - t0
    rows2, token = [], ""
    while True:
        page = fed.router.feed_page("deduplication", "bench", token, 5000)
        rows2.extend(page["rows"])
        token = page["next_since"]
        if page["drained"]:
            break
    fed.close()
    return {
        "metric": "federation_scatter_gather",
        "records": FED_RECORDS,
        "groups": FED_GROUPS,
        "single_group": {
            "ingest_records_per_sec": round(FED_RECORDS / one_ingest, 1),
            "feed_rows_per_sec": round(len(one_rows) / max(one_feed, 1e-9),
                                       1),
        },
        "federated": {
            "ingest_records_per_sec": round(FED_RECORDS / n_ingest, 1),
            "feed_rows_per_sec": round(len(n_rows) / max(n_feed, 1e-9), 1),
        },
        # >1 = the federation ingests faster than one group (groups
        # score their smaller shards concurrently); <1 = router overhead
        # dominates at this corpus size
        "federated_ingest_speedup": round(one_ingest / n_ingest, 2),
        "migration": {
            "seconds": round(migrate_s, 3),
            "moved_records": stats["moved_records"],
            "moved_links": stats["moved_links"],
            "feed_bit_identical_across_migration": normed(rows2) == pre,
        },
    }


def observability_bench() -> dict:
    """Tracing-overhead differential (ISSUE 16): the same federated
    scatter-ingest run twice — every batch under a sampled root span
    (TRACE_SAMPLE_RATE=1.0 equivalent: the full fed.partition/fanout/
    group/merge span tree records, including remote span capture and
    graft per group) vs with no active trace, where every span site is
    a single contextvar read.  The always-on SLO latency trackers,
    per-range outcome stats and queue-depth accounting run identically
    in both arms, so the differential isolates the *span* path — the
    only part sampling can turn off.  Best-of-OBS_RUNS per arm."""
    import tempfile

    from sesam_duke_microservice_tpu.core.config import parse_config
    from sesam_duke_microservice_tpu.federation import Federation
    from sesam_duke_microservice_tpu.telemetry import tracing

    def entities(n):
        return [{"_id": str(i), "name": f"person number {i % 64}",
                 "email": f"p{i % 64}@x.no"} for i in range(n)]

    batches = [entities(FED_RECORDS)[i:i + FED_BATCH]
               for i in range(0, FED_RECORDS, FED_BATCH)]

    def one_run(traced: bool) -> float:
        tmp = tempfile.mkdtemp(prefix="obs-bench-")
        sc = parse_config(FED_XML.format(folder=tmp),
                          env={"MIN_RELEVANCE": "0.05"})
        fed = Federation(sc, n_groups=FED_GROUPS)
        # a private recorder: the bench must not flood the process
        # flight recorder another section may inspect
        rec = tracing.FlightRecorder(8, 64) if traced else None
        t0 = time.monotonic()
        if traced:
            for batch in batches:
                with tracing.start_trace("bench.ingest", sampled=True,
                                         recorder=rec):
                    fed.router.submit("deduplication", "bench", "crm",
                                      batch)
        else:
            for batch in batches:
                fed.router.submit("deduplication", "bench", "crm", batch)
        ingest_s = time.monotonic() - t0
        fed.close()
        return ingest_s

    one_run(traced=False)  # untimed warm-up: imports, comparator caches
    runs = max(1, OBS_RUNS)
    # interleave the arms so drift (allocator growth, page cache) hits
    # both equally — the differential is the whole point
    off_s = on_s = math.inf
    for _ in range(runs):
        off_s = min(off_s, one_run(traced=False))
        on_s = min(on_s, one_run(traced=True))
    off_rate = FED_RECORDS / off_s
    on_rate = FED_RECORDS / on_s
    overhead_pct = round((off_rate - on_rate) / off_rate * 100.0, 2)
    return {
        "metric": "tracing_overhead_pct",
        "value": overhead_pct,
        # the ISSUE 16 acceptance budget: always-on tracing costs the
        # federated ingest path <2% throughput
        "within_budget": overhead_pct < 2.0,
        "records_per_sec_tracing_on": round(on_rate, 1),
        "records_per_sec_tracing_off": round(off_rate, 1),
        "groups": FED_GROUPS,
        "records": FED_RECORDS,
        "runs_per_arm": max(1, OBS_RUNS),
    }


def capacity_bench() -> dict:
    """Attribution-overhead differential + skewed-keyspace split check
    (ISSUE 17).  Arm ON is the default service config (cost ledger
    crediting every batch, heat map bucketing every routed record); arm
    OFF disables both, so the differential isolates exactly what the
    attribution layer adds to the ingest path.  Interleaved best-of, as
    in observability_bench.  The skew scenario rejection-samples record
    ids whose route keys put 80% of traffic in the first 5% of one
    range's keyspan, then checks the suggested split point bisects the
    OBSERVED load (lands inside the hot band) instead of the naive
    midpoint."""
    import tempfile

    from sesam_duke_microservice_tpu.core.config import parse_config
    from sesam_duke_microservice_tpu.federation import Federation
    from sesam_duke_microservice_tpu.federation.ranges import route_key
    from sesam_duke_microservice_tpu.telemetry import costs, heat

    def entities(n):
        return [{"_id": str(i), "name": f"person number {i % 64}",
                 "email": f"p{i % 64}@x.no"} for i in range(n)]

    batches = [entities(FED_RECORDS)[i:i + FED_BATCH]
               for i in range(0, FED_RECORDS, FED_BATCH)]

    def one_run(attributed: bool) -> float:
        tmp = tempfile.mkdtemp(prefix="cap-bench-")
        sc = parse_config(FED_XML.format(folder=tmp),
                          env={"MIN_RELEVANCE": "0.05"})
        costs.configure(attributed)
        old_heat = os.environ.get("DUKE_FED_HEAT")
        if not attributed:
            os.environ["DUKE_FED_HEAT"] = "0"
        try:
            fed = Federation(sc, n_groups=FED_GROUPS)
        finally:
            if not attributed:
                if old_heat is None:
                    os.environ.pop("DUKE_FED_HEAT", None)
                else:
                    os.environ["DUKE_FED_HEAT"] = old_heat
        t0 = time.monotonic()
        for batch in batches:
            fed.router.submit("deduplication", "bench", "crm", batch)
        ingest_s = time.monotonic() - t0
        fed.close()
        costs.configure(True)
        return ingest_s

    one_run(attributed=True)  # untimed warm-up
    runs = max(1, OBS_RUNS)
    off_s = on_s = math.inf
    for _ in range(runs):
        off_s = min(off_s, one_run(attributed=False))
        on_s = min(on_s, one_run(attributed=True))
    off_rate = FED_RECORDS / off_s
    on_rate = FED_RECORDS / on_s
    overhead_pct = round((off_rate - on_rate) / off_rate * 100.0, 2)

    # -- skewed keyspace: 80% of traffic into 5% of one range ---------------
    tmp = tempfile.mkdtemp(prefix="cap-skew-")
    sc = parse_config(FED_XML.format(folder=tmp),
                      env={"MIN_RELEVANCE": "0.05"})
    fed = Federation(sc, n_groups=FED_GROUPS)
    try:
        ds = fed.groups[0].workload(
            "deduplication", "bench").datasources["crm"]
        target = fed.map.owner(route_key(ds.record_id_for_entity(
            {"_id": "probe"})))
        span = target.hi - target.lo
        hot_hi = target.lo + span // 20  # first 5% of the keyspan

        def sample(n, lo, hi):
            out, i = [], 0
            while len(out) < n:
                cand = f"skew{i}"
                i += 1
                key = route_key(ds.record_id_for_entity({"_id": cand}))
                if lo <= key < hi:
                    out.append(cand)
            return out

        hot = sample(400, target.lo, hot_hi)
        cold = sample(100, target.lo, target.hi)
        batch = [{"_id": rid, "name": f"person number {j % 64}",
                  "email": f"p{j % 64}@x.no"}
                 for j, rid in enumerate(hot + cold)]
        fed.router.submit("deduplication", "bench", "crm", batch)
        row = next(r for r in heat.loadmap(fed.router.heat)["ranges"]
                   if r["range"] == target.range_id)
        split = int(row["suggested_split"], 16)
        # a load-bisecting split sits in (or one bucket past) the hot
        # band; the naive midpoint would be ~10x further right
        in_hot_band = target.lo < split <= hot_hi + span // heat.N_BUCKETS
        skew = {
            "range": target.range_id,
            "records": len(batch),
            "hot_band_hi": f"{hot_hi:016x}",
            "suggested_split": row["suggested_split"],
            "split_in_hot_band": in_hot_band,
        }
    finally:
        fed.close()

    return {
        "metric": "attribution_overhead_pct",
        "value": overhead_pct,
        # the ISSUE 17 acceptance budget: cost/heat attribution costs
        # the federated ingest path <2% throughput
        "within_budget": overhead_pct < 2.0,
        "records_per_sec_attribution_on": round(on_rate, 1),
        "records_per_sec_attribution_off": round(off_rate, 1),
        "groups": FED_GROUPS,
        "records": FED_RECORDS,
        "runs_per_arm": runs,
        "skew": skew,
    }


# -- mesh differential: single-device vs N-way virtual mesh (ISSUE 18) -------

_MESH_CHILD = r'''
import json, os, time
from sesam_duke_microservice_tpu.utils.virtual_mesh import force_cpu_platform
force_cpu_platform()
from bench import bench_schema, stresstest_records
from sesam_duke_microservice_tpu.core.config import MatchTunables
from sesam_duke_microservice_tpu.engine.device_matcher import (
    DeviceIndex, DeviceProcessor)
from sesam_duke_microservice_tpu.engine.listeners import MatchListener
from sesam_duke_microservice_tpu.engine.sharded_matcher import (
    ShardedDeviceIndex, ShardedDeviceProcessor)


class Tape(MatchListener):
    def __init__(self):
        self.events = []

    def matches(self, r1, r2, confidence):
        self.events.append(
            ("match", r1.record_id, r2.record_id, round(confidence, 9)))

    def matches_perhaps(self, r1, r2, confidence):
        self.events.append(
            ("maybe", r1.record_id, r2.record_id, round(confidence, 9)))

    def no_match_for(self, record):
        self.events.append(("none", record.record_id))


n = int(os.environ["MESH_RECORDS"])
schema = bench_schema()
# warm batch compiles the arm's programs AND fills the corpus; the timed
# batch then scores against an identical corpus state in both arms
warm_batch = stresstest_records(n, seed=77, dataset="ds1")
timed_batch = stresstest_records(n, seed=78, dataset="ds2")


def run_arm(arm):
    if arm == "mesh":
        index = ShardedDeviceIndex(schema, tunables=MatchTunables())
        proc = ShardedDeviceProcessor(schema, index)
        ndev = index.mesh.size
    else:
        index = DeviceIndex(schema, tunables=MatchTunables())
        proc = DeviceProcessor(schema, index)
        ndev = 1
    tape = Tape()
    proc.add_match_listener(tape)
    proc.deduplicate(warm_batch)
    t0 = time.perf_counter()
    proc.deduplicate(timed_batch)
    dt = time.perf_counter() - t0
    cap = index.corpus.capacity
    top_k = int(os.environ.get("DEVICE_TOP_K", "64"))
    chars = int(os.environ.get("DEVICE_MAX_CHARS", "24"))
    grams = int(os.environ.get("DEVICE_MAX_GRAMS", "24"))
    nprops = len(index.plan.device_props)
    # coarse analytic attribution (same spirit as the ivf section's
    # retrieval model): ~2 flops per char/gram cell per device property
    # per scored corpus row.  The mesh splits the row axis N ways, so
    # per-device work is the single-chip figure / ndev.
    flops_q = 2.0 * cap * (chars + grams) * nprops
    return {
        "devices": ndev,
        "records_per_sec": round(len(timed_batch) / dt, 1),
        "batch_seconds": round(dt, 3),
        "corpus_capacity": cap,
        "score_flops_per_query": flops_q,
        "score_flops_per_query_per_device": flops_q / ndev,
        # the GSPMD top-K merge: each device contributes top_k
        # (logit f32 + index i32) rows per query into the replicated
        # gather XLA inserts for parallel.sharded.merge_topk
        "collective_bytes_per_query": ndev * top_k * 8 if ndev > 1 else 0,
    }, tape.events


single, single_events = run_arm("single")
mesh, mesh_events = run_arm("mesh")
print("MESH " + json.dumps({
    "single_device": single,
    "mesh": mesh,
    "events": len(mesh_events),
    # exact blocking: the merged global top-K IS the single-device
    # top-K, so the whole ordered tape must be bit-identical
    "events_identical": mesh_events == single_events,
}))
'''


def mesh_bench() -> dict:
    """ISSUE 18 acceptance surface: single-device vs N-way virtual-mesh
    differential in a forced-device-count child, bit-identical tapes
    required, snapshot written to MULTICHIP_r06.json."""
    import subprocess

    from sesam_duke_microservice_tpu.utils.virtual_mesh import (
        virtual_mesh_env,
    )

    env = virtual_mesh_env(MESH_DEVICES, "_BENCH_MESH_INNER")
    env.update({
        "PYTHONPATH": os.path.dirname(os.path.abspath(__file__)),
        "MESH_RECORDS": str(MESH_RECORDS),
        # small shapes keep the forced-CPU child's compiles in seconds;
        # the chunk is the mesh granule unit (capacity pads to
        # ndev * chunk), sized so the timed corpus fits one granule
        "DEVICE_CHUNK": "64",
        "DEVICE_QUERY_BUCKETS": "64",
        "DEVICE_TOP_K": "16",
        "DEVICE_MAX_CHARS": "24",
        "DEVICE_MAX_GRAMS": "24",
        "DEVICE_PREWARM": "0",
        "DEVICE_INITIAL_CAPACITY": "0",
        "DUKE_AOT": "0",
    })
    proc = subprocess.run(
        [sys.executable, "-c", _MESH_CHILD], env=env,
        capture_output=True, text=True, timeout=1800,
    )
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("MESH ")]
    if proc.returncode != 0 or not lines:
        raise RuntimeError(
            f"mesh bench child failed: rc={proc.returncode}\n"
            f"{proc.stdout}\n{proc.stderr}")
    out = json.loads(lines[0][len("MESH "):])
    assert out["events_identical"], "mesh arm diverged from single-device"
    out["n_devices"] = MESH_DEVICES
    snapshot = dict(out, rc=proc.returncode, ok=bool(out["events_identical"]),
                    skipped=False)
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "MULTICHIP_r06.json")
    with open(path, "w") as fh:
        json.dump(snapshot, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return out


# -- open-loop tail latency / cold start / recovery window (ISSUE 15) --------

_TAIL_COLD_CHILD = r'''
import json, os, threading, time, urllib.request
t0 = time.perf_counter()
from sesam_duke_microservice_tpu.core.config import parse_config
from sesam_duke_microservice_tpu.service.app import DukeApp, serve
sc = parse_config(os.environ["TAIL_XML"])
app = DukeApp(sc, backend="device", persistent=False)
server = serve(app, port=0, host="127.0.0.1")
threading.Thread(target=server.serve_forever, daemon=True).start()
base = "http://127.0.0.1:%d" % server.server_address[1]
body = json.dumps([
    {"_id": "r%d" % i, "name": "cold start probe %d" % i, "ssn": str(i)}
    for i in range(8)
]).encode()
req = urllib.request.Request(
    base + "/deduplication/conc/ds", data=body,
    headers={"Content-Type": "application/json"}, method="POST")
with urllib.request.urlopen(req, timeout=600) as r:
    assert r.status == 200
elapsed = time.perf_counter() - t0
if os.environ.get("TAIL_JOIN_WARM") == "1":
    # the cold arm waits for the miss-filler so the AOT store is fully
    # populated before the warm arm starts
    for wl in app.deduplications.values():
        cache = getattr(wl.index, "scorer_cache", None)
        t = getattr(cache, "_warm_thread", None)
        if t is not None:
            t.join(timeout=600)
print("TAIL " + json.dumps({"time_to_first_200_s": round(elapsed, 3)}))
server.shutdown()
app.close()
'''

_TAIL_RECOVERY_CHILD = r'''
import json, os, threading, time, urllib.request
t0 = time.perf_counter()
from sesam_duke_microservice_tpu.core.config import parse_config
from sesam_duke_microservice_tpu.service.app import DukeApp, serve
sc = parse_config(os.environ["TAIL_XML"], env={"MIN_RELEVANCE": "0.05"})
# serial mode blocks HERE through the whole replay; overlap returns fast
app = DukeApp(sc, backend="host", persistent=True)
server = serve(app, port=0, host="127.0.0.1")
threading.Thread(target=server.serve_forever, daemon=True).start()
base = "http://127.0.0.1:%d" % server.server_address[1]
read_s = None
while read_s is None:
    try:
        with urllib.request.urlopen(
                base + "/deduplication/people?since=0", timeout=10) as r:
            if r.status == 200:
                read_s = time.perf_counter() - t0
    except Exception:
        time.sleep(0.005)
write_s = None
while write_s is None:
    try:
        with urllib.request.urlopen(base + "/readyz", timeout=10) as r:
            body = json.loads(r.read())
            if body["checks"].get("write_ready"):
                write_s = time.perf_counter() - t0
    except Exception:
        pass
    if write_s is None:
        time.sleep(0.01)
print("TAIL " + json.dumps({
    "read_unavailable_s": round(read_s, 3),
    "write_ready_s": round(write_s, 3),
}))
server.shutdown()
app.close()
'''

TAIL_RECOVERY_XML = """
<DukeMicroService dataFolder="{folder}">
  <Deduplication name="people">
    <duke>
      <schema>
        <threshold>0.8</threshold>
        <property><name>NAME</name><comparator>levenshtein</comparator><low>0.1</low><high>0.95</high></property>
      </schema>
      <data-source class="io.sesam.dukemicroservice.IncrementalDeduplicationDataSource">
        <param name="dataset-id" value="crm"/>
        <column name="name" property="NAME"/>
      </data-source>
    </duke>
  </Deduplication>
</DukeMicroService>
"""


def _tail_entities(i: int) -> list:
    ents = []
    for k in range(TAIL_BATCH):
        uid = f"t{i}k{k}"
        ents.append({"_id": uid,
                     "name": f"open loop {uid} w{i * 7919 + k}",
                     "ssn": str(900000 + i * 31 + k)})
    return ents


def _tail_sweep(sc) -> dict:
    """Poisson open-loop arrivals against the real ingest scheduler.

    Latency is measured from each request's SCHEDULED arrival instant —
    not from when a client thread got around to submitting — so queueing
    delay under a saturated scheduler lands in the percentiles, which is
    exactly what the closed-loop ``concurrent`` bench cannot see."""
    import threading

    from sesam_duke_microservice_tpu.engine.scheduler import (
        IngestScheduler,
        SchedulerReject,
    )
    from sesam_duke_microservice_tpu.engine.workload import build_workload

    wl = build_workload(sc.deduplications["conc"], sc, backend="device",
                        persistent=False)
    sched = IngestScheduler(lambda kind, name: wl)
    out = {}
    try:
        for r in _conc_corpus(TAIL_CORPUS):
            wl.index.index(r)
        wl.index.commit()
        wl.submit_batch("ds", _conc_entities(99, 99))  # warm shapes/upload
        seq = 0
        for rate in TAIL_RATES:
            rng = random.Random(4242)
            arrivals, t = [], 0.0
            while t < TAIL_SECONDS:
                t += rng.expovariate(rate)
                arrivals.append(t)
            lat, rejected, errors = [], 0, 0
            lock = threading.Lock()
            threads = []
            base = time.perf_counter() + 0.05

            def fire(at, ents):
                nonlocal rejected, errors
                t_sched = base + at
                try:
                    sched.submit("deduplication", "conc", "ds", ents)
                    sample = time.perf_counter() - t_sched
                    with lock:
                        lat.append(sample)
                except SchedulerReject:
                    with lock:
                        rejected += 1
                except Exception:
                    with lock:
                        errors += 1

            for at in arrivals:
                seq += 1
                ents = _tail_entities(seq)
                delay = base + at - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
                th = threading.Thread(target=fire, args=(at, ents))
                th.start()
                threads.append(th)
            for th in threads:
                th.join()
            lat.sort()
            n = len(lat)

            def pct(p):
                return (round(lat[min(n - 1, int(n * p))] * 1e3, 2)
                        if n else None)

            slo = sum(1 for s in lat if s * 1e3 > TAIL_SLO_MS) + rejected
            span = arrivals[-1] if arrivals else 1.0
            out[str(rate)] = {
                "target_rps": rate,
                "offered": len(arrivals),
                "completed": n,
                "rejected_429": rejected,
                "errors": errors,
                "p50_ms": pct(0.50),
                "p99_ms": pct(0.99),
                "p999_ms": pct(0.999),
                "slo_ms": TAIL_SLO_MS,
                "slo_violations": slo,
                "achieved_rps": round(n / span, 2),
            }
        return out
    finally:
        sched.shutdown()
        wl.close(save_snapshot=False)


def _tail_child(script: str, extra_env: dict) -> dict:
    import subprocess

    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.abspath(__file__))
    env.update(extra_env)
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, text=True, timeout=900)
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("TAIL ")]
    if proc.returncode != 0 or not lines:
        raise RuntimeError(
            f"tail-latency child failed: rc={proc.returncode}\n"
            f"{proc.stdout}\n{proc.stderr}")
    return json.loads(lines[0][len("TAIL "):])


def _tail_cold_start(tmpdir: str) -> dict:
    """Fresh-process time-to-first-200, empty caches vs populated AOT
    store — the restart contract as a wall-clock number.  Both arms use
    a restricted ladder (one bucket) so the CPU dev box's cold compile
    stays minutes-not-hours; the arms differ ONLY in cache state."""
    aot = os.path.join(tmpdir, "aot")
    xla = os.path.join(tmpdir, "xla")
    child_env = {
        "TAIL_XML": CONC_XML,
        "DUKE_AOT_DIR": aot,
        "JAX_COMPILATION_CACHE_DIR": xla,
        "DUKE_JIT_CACHE_MIN_SECS": "0",
        "DEVICE_PREWARM": "1",
        "DEVICE_CHUNK": "512",
        "DEVICE_QUERY_BUCKETS": "64",
        "DEVICE_TOP_K": "64",
        "DEVICE_INITIAL_CAPACITY": "0",
    }
    cold = _tail_child(_TAIL_COLD_CHILD, dict(child_env, TAIL_JOIN_WARM="1"))
    warm = _tail_child(_TAIL_COLD_CHILD, child_env)
    return {
        "cold_s": cold["time_to_first_200_s"],
        "aot_warm_s": warm["time_to_first_200_s"],
        "speedup": round(cold["time_to_first_200_s"]
                         / max(1e-9, warm["time_to_first_200_s"]), 2),
    }


def _tail_recovery_window(tmpdir: str) -> dict:
    """Read-unavailability and write-ready windows on a restart with a
    journal backlog: DUKE_RECOVERY_OVERLAP=0 (serial control — the app
    cannot serve anything until replay completes) vs =1 (reads serve the
    committed prefix immediately; only writes wait)."""
    import shutil

    from sesam_duke_microservice_tpu.core.config import parse_config
    from sesam_duke_microservice_tpu.links.journal import LinkJournal
    from sesam_duke_microservice_tpu.service.app import DukeApp

    seed = os.path.join(tmpdir, "seed")
    sc = parse_config(TAIL_RECOVERY_XML.format(folder=seed),
                      env={"MIN_RELEVANCE": "0.05"})
    app = DukeApp(sc, backend="host", persistent=True)
    wl = app.deduplications["people"]
    batch = [{"_id": str(i), "name": f"person number {i // 2}"}
             for i in range(32)]
    with wl.lock:
        wl.process_batch("crm", batch)
    links = wl.link_database.get_all_links()
    app.close()
    if not links:
        raise RuntimeError("recovery-window seed produced no links")
    # a backlog of DISTINCT-key link rows (BENCH_TAIL_RECOVERY_BATCHES x
    # 32 rows/batch): every replayed row is a real insert with index
    # maintenance, so the serial-control replay window reflects actual
    # redo work rather than page-cache-hot re-upserts of a few keys
    # (feed_row tolerates the synthetic endpoints: entity fields null)
    lk0 = links[0]
    folder = os.path.join(seed, "deduplication", "people")
    j = LinkJournal(os.path.join(folder, "linkdatabase.journal"),
                    sync="none")
    now = int(time.time() * 1000)
    for b in range(TAIL_RECOVERY_BATCHES):
        rows = [[f"x{b}_{k}", f"y{b}_{k}", lk0.status.value,
                 lk0.kind.value, 0.4242, now + b * 32 + k]
                for k in range(32)]
        j.append_batch(rows)
    j.close()

    arms = {}
    for overlap, name in (("0", "serial"), ("1", "overlap")):
        arm_dir = os.path.join(tmpdir, f"arm{overlap}")
        shutil.copytree(seed, arm_dir)
        arms[name] = _tail_child(_TAIL_RECOVERY_CHILD, {
            "TAIL_XML": TAIL_RECOVERY_XML.format(folder=arm_dir),
            "DUKE_RECOVERY_OVERLAP": overlap,
        })
    arms["recovery_batches"] = TAIL_RECOVERY_BATCHES
    arms["overlap_read_window_smaller"] = (
        arms["overlap"]["read_unavailable_s"]
        < arms["serial"]["read_unavailable_s"])
    return arms


def tail_latency_bench() -> dict:
    """ISSUE 15 acceptance surface: the open-loop sweep, the cold/warm
    restart differential, and the recovery-window differential."""
    import tempfile

    from sesam_duke_microservice_tpu.core.config import parse_config

    sc = parse_config(CONC_XML)
    out = {"rates": _tail_sweep(sc)}
    with tempfile.TemporaryDirectory(prefix="duke-tail-") as tmpdir:
        out["cold_start"] = _tail_cold_start(tmpdir)
        out["recovery_window"] = _tail_recovery_window(tmpdir)
    return out


# -- multi-tenant density (ISSUE 19) ------------------------------------------


_MT_PROPS = [
    [("NAME", "levenshtein"), ("EMAIL", "exact")],
    [("NAME", "levenshtein")],
    [("NAME", "levenshtein"), ("SSN", "exact")],
    [("NAME", "levenshtein"), ("EMAIL", "exact"), ("PHONE", "exact")],
]


def _mt_xml(name: str, props) -> str:
    prop_xml = "".join(
        f"<property><name>{p}</name><comparator>{c}</comparator>"
        f"<low>0.1</low><high>0.95</high></property>"
        for p, c in props)
    cols = "".join(
        f'<column name="{p.lower()}" property="{p}"/>' for p, _ in props)
    return (
        '<DukeMicroService>'
        f'<Deduplication name="{name}" link-database-type="in-memory">'
        '<duke><schema><threshold>0.8</threshold>' + prop_xml +
        '</schema><data-source class="io.sesam.dukemicroservice.'
        'IncrementalDeduplicationDataSource">'
        '<param name="dataset-id" value="crm"/>' + cols +
        '</data-source></duke></Deduplication></DukeMicroService>')


def _mt_entities(i: int, r: int, props) -> list:
    """One tenant's round-``r`` batch: a duplicate pair plus two
    distinct records (every tenant links something every round)."""
    out = []
    for j in range(4):
        rec = {"_id": f"t{i}r{r}x{j}"}
        for p, _ in props:
            if j < 2:
                rec[p.lower()] = f"dup {p.lower()} {i} {r}"
            else:
                rec[p.lower()] = f"uniq {p.lower()} {i} {r} {j}"
        out.append(rec)
    return out


class _MtTape:
    def __init__(self):
        self.events = []

    def start_processing(self):
        pass

    def batch_ready(self, size):
        self.events.append(("batch_ready", size))

    def batch_done(self):
        self.events.append(("batch_done",))

    def end_processing(self):
        pass

    def matches(self, r1, r2, confidence):
        self.events.append(
            ("match", r1.record_id, r2.record_id, repr(confidence)))

    def matches_perhaps(self, r1, r2, confidence):
        self.events.append(
            ("maybe", r1.record_id, r2.record_id, repr(confidence)))

    def no_match_for(self, record):
        self.events.append(("none", record.record_id))


def _mt_arm(n_tenants: int, *, arena: bool, budget=None,
            aot_dir: str) -> dict:
    """Build ``n_tenants`` device workloads round-robin over the schema
    variants, prewarm (joining the warm threads so the compile census is
    complete), ingest MT_BATCHES rounds, and report tapes + compile /
    executable / HBM counters."""
    from sesam_duke_microservice_tpu.core.config import parse_config
    from sesam_duke_microservice_tpu.engine.workload import build_workload
    from sesam_duke_microservice_tpu.ops.arena import ARENA
    from sesam_duke_microservice_tpu.telemetry import JIT_COMPILES
    from sesam_duke_microservice_tpu.utils.jit_cache import SHARED_LADDERS

    keep = {k: os.environ.get(k)
            for k in ("DUKE_ARENA", "DUKE_AOT_DIR",
                      "DEVICE_INITIAL_CAPACITY")}
    os.environ["DUKE_ARENA"] = "1" if arena else "0"
    os.environ["DUKE_AOT_DIR"] = aot_dir
    os.environ["DEVICE_INITIAL_CAPACITY"] = "64"
    ARENA._reset_for_tests()
    SHARED_LADDERS._reset_for_tests()
    old_budget = ARENA._budget_bytes
    if budget is not None:
        ARENA._budget_bytes = lambda: float(budget)
    compiles0 = JIT_COMPILES.single().value
    wls, tapes = [], []
    t0 = time.monotonic()
    try:
        for i in range(n_tenants):
            props = _MT_PROPS[i % MT_SCHEMAS]
            sc = parse_config(_mt_xml(f"tenant{i}", props),
                              env={"MIN_RELEVANCE": "0.05"})
            wl = build_workload(sc.deduplications[f"tenant{i}"], sc,
                                backend="device", persistent=False)
            tape = _MtTape()
            wl.processor.add_match_listener(tape)
            wls.append(wl)
            tapes.append(tape)
            # warm the ladder BEFORE ingest and join the warm thread:
            # the ingest below then dispatches through registered
            # executables, so the compile census counts warm compiles
            # only — deterministic across arms
            cache = wl.index.scorer_cache
            cache.prewarm_async(False)
            t = cache._warm_thread
            if t is not None:
                t.join(timeout=600)
        for r in range(MT_BATCHES):
            for i, wl in enumerate(wls):
                wl.submit_batch(
                    "crm", _mt_entities(i, r, _MT_PROPS[i % MT_SCHEMAS]))
        elapsed = time.monotonic() - t0
        pinned = sum(wl.index.corpus._device_nbytes() for wl in wls)
        device_bytes = ARENA.tier_bytes()["device"] if arena else pinned
        stats = SHARED_LADDERS.stats()
        return {
            "tapes": [tape.events for tape in tapes],
            "compiles": JIT_COMPILES.single().value - compiles0,
            "executables": stats["executables"],
            "ladders": stats["ladders"],
            "pinned_bytes": int(pinned),
            "device_bytes": int(device_bytes),
            "faults": ARENA.faults,
            "spills": ARENA.spills,
            "elapsed_s": round(elapsed, 3),
        }
    finally:
        ARENA._budget_bytes = old_budget
        for wl in wls:
            wl.close()
        for k, v in keep.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _mt_quota() -> dict:
    """One-tenant flood against a small admission queue: the flooder
    absorbs every SchedulerReject (the HTTP 429) while the polite
    tenants' p99 submit latency stays inside DUKE_SLO_INGEST_MS — the
    DRR quantum keeps their rounds coming."""
    import threading as _threading

    from sesam_duke_microservice_tpu.core.config import parse_config
    from sesam_duke_microservice_tpu.engine.scheduler import (
        IngestScheduler,
        SchedulerReject,
    )
    from sesam_duke_microservice_tpu.engine.workload import build_workload

    keep = {k: os.environ.get(k)
            for k in ("DUKE_SCHED_QUEUE_MAX", "DUKE_SCHED_QUANTUM")}
    os.environ["DUKE_SCHED_QUEUE_MAX"] = "4"
    os.environ["DUKE_SCHED_QUANTUM"] = "32"
    slo_ms = float(os.environ.get("DUKE_SLO_INGEST_MS", "1000"))
    names = ["flood", "polite0", "polite1", "polite2"]
    wls = {}
    try:
        for name in names:
            sc = parse_config(_mt_xml(name, _MT_PROPS[0]),
                              env={"MIN_RELEVANCE": "0.05"})
            wls[name] = build_workload(sc.deduplications[name], sc,
                                       backend="host", persistent=False)
        sched = IngestScheduler(lambda kind, name: wls[name])
        stop = _threading.Event()
        flood_rejects = [0]
        flood_submitted = [0]
        polite_rejects = [0]
        lat_lock = _threading.Lock()
        polite_lat = []

        def flooder(f: int):
            i = 0
            while not stop.is_set():
                batch = [{"_id": f"f{f}b{i}x{j}",
                          "name": f"flood {f} {i} {j}",
                          "email": f"f{f}@x"} for j in range(4)]
                try:
                    sched.submit("deduplication", "flood", "crm", batch)
                    flood_submitted[0] += 1
                except SchedulerReject:
                    flood_rejects[0] += 1
                    time.sleep(0.002)
                i += 1

        def polite(name: str):
            for r in range(25):
                batch = [{"_id": f"{name}r{r}a", "name": f"dup {name} {r}",
                          "email": f"{name}@x"},
                         {"_id": f"{name}r{r}b", "name": f"dup {name} {r}",
                          "email": f"{name}@x"}]
                t0 = time.perf_counter()
                try:
                    sched.submit("deduplication", name, "crm", batch)
                except SchedulerReject:
                    polite_rejects[0] += 1
                with lat_lock:
                    polite_lat.append(time.perf_counter() - t0)

        floods = [_threading.Thread(target=flooder, args=(f,))
                  for f in range(10)]
        for t in floods:
            t.start()
        time.sleep(0.25)  # build a flood backlog first
        polites = [_threading.Thread(target=polite, args=(n,))
                   for n in names[1:]]
        for t in polites:
            t.start()
        for t in polites:
            t.join(timeout=300)
        stop.set()
        for t in floods:
            t.join(timeout=60)
        sched.shutdown()
        lat = sorted(polite_lat)
        p99_ms = lat[min(len(lat) - 1, int(len(lat) * 0.99))] * 1000.0
        return {
            "slo_ms": slo_ms,
            "polite_p99_ms": round(p99_ms, 3),
            "p99_within_slo": bool(p99_ms <= slo_ms),
            "polite_rejects": polite_rejects[0],
            "flooder_rejects": flood_rejects[0],
            "flooder_submitted": flood_submitted[0],
            "flood_absorbs_all_429s": bool(
                flood_rejects[0] > 0 and polite_rejects[0] == 0),
        }
    finally:
        for wl in wls.values():
            wl.close()
        for k, v in keep.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def multitenant_bench() -> dict:
    """ISSUE 19 acceptance surface: the 100-tenant density differential
    plus the quota proof."""
    import shutil as _shutil
    import tempfile

    dirs = [tempfile.mkdtemp(prefix=f"duke-mt-{arm}-")
            for arm in ("control", "off", "on")]
    try:
        # (c) 4-schema single-tenant control: the compile/executable
        # census the dense arm must match
        single = _mt_arm(MT_SCHEMAS, arena=False, aot_dir=dirs[0])
        # (a) per-workload pinning control (arena off)
        off = _mt_arm(MT_TENANTS, arena=False, aot_dir=dirs[1])
        # (b) the dense arm: budget = a quarter of the control's pinned
        # bytes, so residency stays >= 4x below per-workload pinning
        budget = max(1, off["pinned_bytes"] // 4)
        on = _mt_arm(MT_TENANTS, arena=True, budget=budget,
                     aot_dir=dirs[2])
        out = {
            "tenants": MT_TENANTS,
            "schemas": MT_SCHEMAS,
            "batches_per_tenant": MT_BATCHES,
            "compiles_single_tenant": single["compiles"],
            "compiles_dense": on["compiles"],
            "compiles_equal": on["compiles"] == single["compiles"],
            "executables_single_tenant": single["executables"],
            "executables_dense": on["executables"],
            "executables_equal":
                on["executables"] == single["executables"],
            "ladders_dense": on["ladders"],
            "pinned_control_bytes": off["pinned_bytes"],
            "arena_device_bytes": on["device_bytes"],
            "hbm_ratio": round(
                off["pinned_bytes"] / max(1, on["device_bytes"]), 2),
            "hbm_at_least_4x_denser":
                on["device_bytes"] * 4 <= off["pinned_bytes"],
            "arena_faults": on["faults"],
            "arena_spills": on["spills"],
            "tapes_bit_identical": on["tapes"] == off["tapes"],
            "elapsed_off_s": off["elapsed_s"],
            "elapsed_on_s": on["elapsed_s"],
            "quota": _mt_quota(),
        }
        return out
    finally:
        for d in dirs:
            _shutil.rmtree(d, ignore_errors=True)


def probe_bench() -> dict:
    """Canary-prober overhead differential (ISSUE 20).

    Interleaved best-of arms like observability_bench: per-batch submit
    times are summed (probe cycles run BETWEEN batches, untimed), so
    the differential measures the prober's passive cost to the ingest
    path, not the probe cycle's own work — production runs cycles every
    DUKE_PROBE_INTERVAL_S seconds, not per batch."""
    import tempfile

    from sesam_duke_microservice_tpu.core.config import parse_config
    from sesam_duke_microservice_tpu.service.app import DukeApp

    def entities(base, n):
        return [{"_id": f"{base}-{i}", "name": f"person number {i % 64}",
                 "email": f"p{i % 64}@x.no"} for i in range(n)]

    batches = [entities(b, PROBE_ROWS) for b in range(PROBE_BATCHES)]
    saved = {k: os.environ.get(k)
             for k in ("DUKE_PROBE", "DUKE_PROBE_INTERVAL_S",
                       "DEVICE_PREWARM")}
    probe_compiles = [0]

    def one_run(probed: bool) -> float:
        # the zero-compile contract needs the warm thread: with prewarm
        # on, the user build populates the shared ladder and the shadow
        # build finds every rung compiled
        os.environ["DEVICE_PREWARM"] = "1"
        os.environ["DUKE_PROBE"] = "1" if probed else "0"
        os.environ["DUKE_PROBE_INTERVAL_S"] = "3600"
        tmp = tempfile.mkdtemp(prefix="probe-bench-")
        sc = parse_config(FED_XML.format(folder=tmp),
                          env={"MIN_RELEVANCE": "0.05"})
        app = DukeApp(sc, backend="device", persistent=False)
        try:
            wl = app.deduplications["bench"]
            t = getattr(wl.index.scorer_cache, "_warm_thread", None)
            if t is not None:
                t.join(timeout=600)
            if probed:
                # build the shadow before the timed window and pin its
                # compile attribution
                app.prober.run_cycle()
                st = app.prober._shadows[("deduplication", "bench")].state
                probe_compiles[0] = max(probe_compiles[0],
                                        st.probe_compiles)
            ingest_s = 0.0
            for batch in batches:
                t0 = time.monotonic()
                app.scheduler.submit("deduplication", "bench", "crm",
                                     batch)
                ingest_s += time.monotonic() - t0
                if probed:
                    app.prober.run_cycle()
            return ingest_s
        finally:
            app.close()

    try:
        one_run(probed=False)  # untimed warm-up: compiles, AOT store
        off_s = on_s = math.inf
        for _ in range(max(1, PROBE_RUNS)):
            off_s = min(off_s, one_run(probed=False))
            on_s = min(on_s, one_run(probed=True))
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    records = PROBE_BATCHES * PROBE_ROWS
    off_rate = records / off_s
    on_rate = records / on_s
    overhead_pct = round((off_rate - on_rate) / off_rate * 100.0, 2)
    return {
        "metric": "probe_overhead_pct",
        "value": overhead_pct,
        # the ISSUE 20 acceptance budget: the prober costs the ingest
        # path <2% throughput and zero XLA compiles
        "within_budget": overhead_pct < 2.0,
        "probe_compiles": probe_compiles[0],
        "records_per_sec_prober_on": round(on_rate, 1),
        "records_per_sec_prober_off": round(off_rate, 1),
        "batches": PROBE_BATCHES,
        "rows_per_batch": PROBE_ROWS,
        "runs_per_arm": max(1, PROBE_RUNS),
    }


def main():
    schema = bench_schema()
    corpus = stresstest_records(CORPUS, seed=1234)

    cpu_rate = cpu_baseline_pairs_per_sec(schema, corpus)
    rates, phases, trace_ids = device_pairs_per_sec(schema, corpus)
    dev_rate = float(np.median(rates))

    # the slowest timed batch's trace id: the flight-recorder entry a
    # regression investigation opens first (GET /debug/traces/<id> in a
    # service run; in-process the same tree sits in tracing.RECORDER)
    slowest = min(range(len(rates)), key=rates.__getitem__)
    result = {
        "metric": "pairs_scored_per_sec",
        "value": round(dev_rate, 1),
        "unit": "pairs/s",
        "vs_baseline": round(dev_rate / cpu_rate, 2),
        "phases": phases,
        "slowest_trace_id": trace_ids[slowest],
    }
    if E2E and BACKEND == "device":
        result["e2e"] = e2e_ingest(schema)
    if RESYNC and BACKEND == "device":
        result["resync"] = warm_resync(schema)
    if EXPLAIN_BENCH and BACKEND == "device":
        result["explain"] = explain_bench(schema)
    if CONC and BACKEND == "device":
        result["concurrent"] = concurrent_bench()
    if IVF_BENCH and BACKEND == "device":
        result["ivf"] = ivf_bench(schema)
    if DURABILITY and BACKEND == "device":
        result["durability"] = durability_bench(schema)
    if FED_BENCH and BACKEND == "device":
        result["federation"] = federation_bench()
    if OBS_BENCH and BACKEND == "device":
        result["observability"] = observability_bench()
    if CAP_BENCH and BACKEND == "device":
        result["capacity"] = capacity_bench()
    if MESH_BENCH and BACKEND == "device":
        result["mesh"] = mesh_bench()
    if MT_BENCH and BACKEND == "device":
        result["multitenant"] = multitenant_bench()
    if PROBE_BENCH and BACKEND == "device":
        result["probes"] = probe_bench()
    if TAIL and BACKEND == "device":
        result["tail_latency"] = tail_latency_bench()
    print(json.dumps(result))
    print(
        f"# cpu_baseline={cpu_rate:.0f} pairs/s, device median-of-{len(rates)}"
        f"={dev_rate:.0f} pairs/s, runs={[round(r/1e6, 1) for r in rates]}M, "
        f"corpus={CORPUS}, queries={QUERIES}",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
