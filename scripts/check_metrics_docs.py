#!/usr/bin/env python3
"""Docs-drift check: every metric family the code registers must be
documented in README.md, and every ``duke_*`` family README documents
must exist in the code (ISSUE 5 satellite).

Pure-stdlib static scan (runs in the CI lint job, no package install):
families are string literals passed to ``counter(``/``gauge(``/
``histogram(`` registry calls or constructed as scrape-time
``FamilySnapshot``s — all spelled ``duke_<subsystem>_<metric>[_total]``
per the telemetry naming scheme, so a regex over the package catches
exactly the registration sites.  README mentions are any ``duke_*``
token; sample-suffix forms (``_bucket``/``_sum``/``_count``) and
label-only fragments normalize back to their family.

Exit 1 with a readable diff when either direction drifts.
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
PACKAGE = ROOT / "sesam_duke_microservice_tpu"
README = ROOT / "README.md"

# registration sites: registry.counter("duke_x", ...) / GLOBAL.gauge( /
# FamilySnapshot("duke_x", ...) — the opening call may break the line
# before the name literal
_REGISTRATION_RE = re.compile(
    r"(?:\.counter|\.gauge|\.histogram|FamilySnapshot)\(\s*\n?\s*"
    r"['\"](duke_[a-z0-9_]+)['\"]",
)
_README_RE = re.compile(r"\bduke_[a-z0-9_]+\b")

# Prometheus sample suffixes that normalize back to the family name
_SAMPLE_SUFFIXES = ("_bucket", "_sum", "_count")


def code_families() -> set:
    out = set()
    for path in sorted(PACKAGE.rglob("*.py")):
        out |= set(_REGISTRATION_RE.findall(path.read_text(encoding="utf-8")))
    return out


def readme_families(code: set) -> set:
    out = set()
    for token in _README_RE.findall(README.read_text(encoding="utf-8")):
        if token in code:
            out.add(token)
            continue
        for suffix in _SAMPLE_SUFFIXES:
            if token.endswith(suffix) and token[: -len(suffix)] in code:
                token = token[: -len(suffix)]
                break
        out.add(token)
    return out


def main() -> int:
    code = code_families()
    if not code:
        print("check_metrics_docs: found no registered families — the "
              "registration regex no longer matches the code; fix me")
        return 1
    readme = readme_families(code)
    undocumented = sorted(code - readme)
    phantom = sorted(readme - code)
    ok = True
    if undocumented:
        ok = False
        print("Metric families registered in code but missing from "
              "README.md:")
        for name in undocumented:
            print(f"  - {name}")
    if phantom:
        ok = False
        print("Metric families documented in README.md but not "
              "registered anywhere:")
        for name in phantom:
            print(f"  - {name}")
    if ok:
        print(f"check_metrics_docs: {len(code)} families, docs in sync")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
