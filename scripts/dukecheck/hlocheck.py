"""Checker 8 — compiled-HLO contraction gate (DK701..DK703).

The source-level EFT discipline (``numerics``, DK602) proves the commit
barriers are *written*; nothing source-level can prove the compiler
still *honors* them.  Both regressions PR 11 measured live entirely
inside XLA: the algebraic simplifier cancelling ``x - (x - a)`` (2.2e-8
vs 3e-16) and backend FMA contraction of ``a*b + c`` (a full f32 ulp on
``log``'s reduction term).  A jaxlib upgrade that starts treating
``reduce-precision(f32 -> f32)`` as the identity — or re-associating
through it — voids certification with every bit-identity test green,
surfacing months later as a 1-ulp verdict flip in production.

This gate lowers and compiles the registered dd programs on the current
(CI) backend and asserts over the **optimized HLO text**:

  * **DK701 — commit survival**: the optimized module must define at
    least as many ``reduce-precision`` instructions as the unoptimized
    lowering.  Fusion legally *duplicates* commits (producers are cloned
    into consumers), so the count may grow; any NET LOSS means a commit
    was eliminated — the precise signature of a simplifier that learned
    to see through the barrier.
  * **DK702 — contraction exposure**: no f32 ``add``/``subtract``
    instruction attributed (via HLO metadata) to ``ops/dd.py`` may
    consume a ``multiply`` as a direct operand.  The EFT discipline puts
    a commit between every product and sum, so a mul feeding an add
    *inside dd-attributed code* is an uncommitted pair the LLVM backend
    is licensed to contract into an fma (contraction is invisible in
    HLO — this adjacency is its necessary precondition, so the gate
    forbids the precondition).
  * **DK703 — gate integrity**: a program that fails to build, lower or
    compile (or produces zero commits where commits are expected) is a
    loud failure, never a silent skip.

Each program is compiled under a **matrix of XLA compiler options**
(fast-math, fast-min-max, max backend optimization) so the next jaxlib
bump that changes a default — or starts honoring one of these flags
differently around ``reduce-precision`` — fails in lint, not in prod.

Findings here are **never baselinable** (enforced by the runner): a
contraction regression is a release blocker by definition — there is no
"known, justified, and grandfathered" compiler miscompilation.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Sequence, Tuple

from .core import Finding

# (name, compiler_options) combos every program must survive.  Values
# must be real Python bools/ints — the PJRT option parser rejects
# strings for typed flags.
FLAG_MATRIX: Tuple[Tuple[str, Dict], ...] = (
    ("default", {}),
    ("fast-math", {"xla_cpu_enable_fast_math": True}),
    ("fast-min-max", {"xla_cpu_enable_fast_min_max": True}),
    ("opt-level-3", {"xla_backend_optimization_level": 3}),
)

REL = "scripts/dukecheck/hlocheck.py"  # finding anchor for gate failures

# one definition line: `%name = f32[...] opcode(...operands...)`
_INST_RE = re.compile(
    r"%([\w.-]+)\s*=\s*(\S+)\s+([\w-]+)\(([^)]*)\)(.*)$"
)
_OPERAND_RE = re.compile(r"%([\w.-]+)")


def parse_instructions(hlo_text: str) -> Dict[str, Tuple[str, str,
                                                         List[str], str]]:
    """``{name: (type, opcode, operand names, trailing metadata)}`` for
    every instruction definition in an HLO text dump (fused computations
    included — their instructions print like any other)."""
    out: Dict[str, Tuple[str, str, List[str], str]] = {}
    for line in hlo_text.splitlines():
        m = _INST_RE.search(line)
        if not m:
            continue
        name, typ, opcode, operands, rest = m.groups()
        out[name] = (typ, opcode, _OPERAND_RE.findall(operands), rest)
    return out


def count_commits(hlo_text: str) -> int:
    """Number of ``reduce-precision`` instruction *definitions* (operand
    references share the name, so substring counting over-counts)."""
    return sum(1 for _, (_, opcode, _, _) in
               parse_instructions(hlo_text).items()
               if opcode == "reduce-precision")


def count_commits_mlir(stablehlo_text: str) -> int:
    """Commit count in the unoptimized (StableHLO MLIR) lowering."""
    return stablehlo_text.count("stablehlo.reduce_precision")


def exposed_contractions(hlo_text: str,
                         source_marker: str = "ops/dd.py") -> List[str]:
    """f32 add/subtract instructions attributed to the dd core that take
    a multiply as a DIRECT operand — the FMA-contraction precondition
    the commit discipline exists to forbid."""
    insts = parse_instructions(hlo_text)
    bad = []
    for name, (typ, opcode, operands, rest) in insts.items():
        if opcode not in ("add", "subtract"):
            continue
        if not typ.startswith("f32"):
            continue
        if source_marker not in rest:
            continue
        for op in operands:
            other = insts.get(op)
            if other is not None and other[1] == "multiply":
                line = ""
                lm = re.search(r"source_line=(\d+)", rest)
                if lm:
                    line = f" (dd.py:{lm.group(1)})"
                bad.append(f"%{name} = {opcode}(.., %{op}=multiply){line}")
                break
    return bad


# -- program registry ---------------------------------------------------------


def _build_dd_core():
    """A composite over every ops.dd primitive (add/sub/mul/div, the
    comparisons' select path, scale_pow2 and the full log chain) — the
    smallest program that exercises each EFT at least once."""
    import jax
    import jax.numpy as jnp

    from sesam_duke_microservice_tpu.ops import dd as D

    def prog(a, b):
        x = D.from_f32(a)
        y = D.from_f32(b)
        s = D.add(D.mul(x, y), D.div(x, y))
        s = D.sub(s, D.maximum(x, D.neg(y)))
        s = D.add(s, D.scale_pow2(x, jnp.full(a.shape, 3, jnp.int32)))
        mag = D.maximum(D.where(D.lt(s, D.const(0.0, like=a)),
                                D.neg(s), s), D.const(1e-6, like=a))
        return D.add(D.log(mag), D.const(1.5, like=a))

    args = (jnp.linspace(0.5, 2.0, 64, dtype=jnp.float32),
            jnp.linspace(1.0, 3.0, 64, dtype=jnp.float32))
    return jax.jit(prog), args


def _build_dd_rescorer():
    """The REAL registered survivor-rescore program for a representative
    plan covering every certified comparator kind (Levenshtein,
    Jaro-Winkler incl. the branch guard, q-gram, token set, exact hash,
    phonetic) over really-extracted feature tensors — the margin-
    critical kernel the finalize verdict split dispatches."""
    import numpy as np

    from sesam_duke_microservice_tpu.core import comparators as C
    from sesam_duke_microservice_tpu.core.config import DukeSchema
    from sesam_duke_microservice_tpu.core.records import (
        ID_PROPERTY_NAME,
        Property,
        Record,
    )
    from sesam_duke_microservice_tpu.ops import features as F
    from sesam_duke_microservice_tpu.ops import scoring as S

    schema = DukeSchema(
        threshold=0.8,
        maybe_threshold=0.6,
        properties=[
            Property(ID_PROPERTY_NAME, id_property=True),
            Property("name", C.Levenshtein(), 0.3, 0.9),
            Property("alias", C.JaroWinkler(), 0.35, 0.85),
            Property("street", C.QGram(), 0.3, 0.8),
            Property("tokens", C.DiceCoefficient(), 0.4, 0.8),
            Property("city", C.Exact(), 0.4, 0.8),
            Property("surname", C.Metaphone(), 0.45, 0.75),
        ],
        data_sources=[],
    )
    plan = F.SchemaFeatures.plan(schema)

    def rec(rid, **props):
        r = Record()
        r.add_value(ID_PROPERTY_NAME, rid)
        for k, v in props.items():
            r.add_value(k, v)
        return r

    rows = [
        rec("a", name="acme corp", alias="acme", street="main street 1",
            tokens="acme corp oslo", city="oslo", surname="smith"),
        rec("b", name="acme corporation", alias="acme co",
            street="main str 1", tokens="acme corporation oslo",
            city="oslo", surname="smyth"),
    ]
    feats = F.extract_batch(plan, rows)
    dd_names = {s.name for s in S.dd_plan_specs(plan)}
    qf = {p: {n: a[0:1] for n, a in t.items()}
          for p, t in feats.items() if p in dd_names}
    cf = {p: {n: a[1:2] for n, a in t.items()}
          for p, t in feats.items() if p in dd_names}
    fn = S.build_dd_rescorer(plan, queries_from_rows=False,
                             pallas_ok=False)
    args = (qf, cf, np.full((1,), -1, np.int32),
            np.zeros((1, 1), np.int32))
    return fn, args


PROGRAMS = (
    ("dd-core", _build_dd_core),
    ("dd-rescorer", _build_dd_rescorer),
)


# -- the gate -----------------------------------------------------------------


def check(modules: Sequence = (), root=None) -> List[Finding]:
    findings: List[Finding] = []
    # the gate compiles for the host backend; pin CPU before jax's
    # backend init so the lint job never tries to grab an accelerator
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        import jax  # noqa: F401
    except Exception as exc:  # pragma: no cover - jax is a hard dep
        return [Finding(
            "DK703", REL, 1,
            f"hlocheck cannot import jax ({exc}) — the contraction gate "
            "must run, not silently skip (install the package in the "
            "lint job)",
            "jax-import",
        )]
    for name, build in PROGRAMS:
        try:
            fn, args = build()
            lowered = fn.lower(*args)
            unopt = count_commits_mlir(lowered.as_text())
        except Exception as exc:
            findings.append(Finding(
                "DK703", REL, 1,
                f"program `{name}` failed to build/lower: {exc!r}",
                f"build:{name}",
            ))
            continue
        if unopt == 0:
            findings.append(Finding(
                "DK703", REL, 1,
                f"program `{name}` lowered with ZERO reduce-precision "
                "commits — the EFT barriers are gone before the "
                "compiler even ran (source regression or lowering "
                "change)",
                f"no-commits:{name}",
            ))
            continue
        for combo, options in FLAG_MATRIX:
            try:
                compiled = lowered.compile(
                    compiler_options=dict(options))
                opt_text = compiled.as_text()
            except Exception as exc:
                findings.append(Finding(
                    "DK703", REL, 1,
                    f"program `{name}` failed to compile under "
                    f"[{combo}]: {exc!r}",
                    f"compile:{name}:{combo}",
                ))
                continue
            opt = count_commits(opt_text)
            if opt < unopt:
                findings.append(Finding(
                    "DK701", REL, 1,
                    f"program `{name}` [{combo}]: optimized HLO defines "
                    f"{opt} reduce-precision commit(s), unoptimized has "
                    f"{unopt} — the compiler ELIMINATED commits "
                    "(fusion only duplicates; a net loss means the "
                    "simplifier sees through the barrier).  This is a "
                    "release blocker, not a baselinable finding.",
                    f"commit-loss:{name}:{combo}",
                ))
            exposed = exposed_contractions(opt_text)
            if exposed:
                findings.append(Finding(
                    "DK702", REL, 1,
                    f"program `{name}` [{combo}]: {len(exposed)} "
                    "dd-attributed f32 add/subtract instruction(s) "
                    "consume a multiply directly — FMA contraction "
                    "exposure (first: " + exposed[0] + ").  Commit the "
                    "product before the sum.",
                    f"fma-exposure:{name}:{combo}",
                ))
    return findings
